"""Relay role algebra + schedule pruning (reference control.cu semantics)."""

from adapcc_tpu.comm.relay import (
    active_recvs,
    compute_role,
    compute_roles,
    prune_broadcast_rounds,
    prune_reduce_rounds,
)
from adapcc_tpu.strategy.ir import Tree


def chain4():
    return Tree(0, {0: [1], 1: [2], 2: [3]})


def binary7():
    return Tree(0, {0: [1, 2], 1: [3, 4], 2: [5, 6]})


def test_all_active_roles():
    t = binary7()
    roles = compute_roles(t, range(7))
    assert roles[0].has_recv and roles[0].has_local and roles[0].has_kernel
    assert not roles[0].has_send  # root never sends
    assert roles[3] == compute_role(t, 3, frozenset(range(7)))
    assert not roles[3].has_recv and roles[3].has_send and not roles[3].has_kernel


def test_pure_forward_relay():
    # chain 0<-1<-2<-3 with rank 1 inactive: it receives from 2's subtree and
    # forwards without reducing (exactly one live input, self inactive)
    t = chain4()
    role = compute_role(t, 1, frozenset({0, 2, 3}))
    assert role.has_recv and not role.has_local
    assert not role.has_kernel  # pure forward
    assert role.has_send


def test_inactive_leaf_subtree_is_dead():
    t = chain4()
    role = compute_role(t, 3, frozenset({0, 1, 2}))
    assert not role.has_send  # nothing live at or below 3
    role2 = compute_role(t, 2, frozenset({0, 1, 2}))
    assert not role2.has_recv  # 3's subtree is dead
    assert role2.has_send and not role2.has_kernel  # sends only its own data


def test_active_recvs_prunes_dead_subtrees():
    t = binary7()
    assert active_recvs(t, 1, frozenset({0, 4})) == [4]
    assert active_recvs(t, 1, frozenset({0})) == []
    assert active_recvs(t, 0, frozenset({3, 6})) == [1, 2]


def test_relay_rank_with_live_subtree_keeps_kernel_choice():
    # rank 1 inactive but both children active → still needs the reduction
    t = binary7()
    role = compute_role(t, 1, frozenset({3, 4}))
    assert role.has_kernel and role.has_send and not role.has_local


def test_prune_reduce_rounds_drops_dead_edges():
    t = binary7()
    rounds = prune_reduce_rounds(t, {0, 1, 2})  # all leaves inactive
    edges = [e for r in rounds for e in r.edges]
    assert (3, 1) not in edges and (6, 2) not in edges
    assert (1, 0) in edges and (2, 0) in edges

    full = prune_reduce_rounds(t, range(7))
    assert [r.edges for r in full] == [r.edges for r in t.reduce_rounds()]


def test_prune_broadcast_keeps_forwarding_path():
    # only leaf 3 active: broadcast must still traverse inactive rank 1
    t = binary7()
    rounds = prune_broadcast_rounds(t, {0, 3})
    edges = [e for r in rounds for e in r.edges]
    assert (0, 1) in edges and (1, 3) in edges
    assert (0, 2) not in edges and (1, 4) not in edges


# --------------------------------------------------------------------------- #
# multi-failure pruning: conservation against a dense reference reduce
# --------------------------------------------------------------------------- #
#
# The relay contract's correctness statement: however many ranks are down,
# the pruned reduce rounds must deliver EXACTLY the sum of the active
# ranks' contributions to the root (relays forward, contribute identity,
# and dead subtrees vanish), and the pruned broadcast rounds must deliver
# the root's value to every live rank.  The executor below replays rounds
# the way the engine does — per round, each edge (s, d) folds acc[s] into
# acc[d] — so conservation here is conservation on the data plane.


def _run_reduce(tree, rounds, active, values):
    acc = {r: (values[r] if r in active else 0) for r in tree.ranks}
    for rnd in rounds:
        recvd = {d: acc[s] for s, d in rnd.edges}
        for d, v in recvd.items():
            acc[d] += v
    return acc


def _run_broadcast(tree, rounds, root_value):
    has = {r: root_value if r == tree.root else None for r in tree.ranks}
    for rnd in rounds:
        recvd = {d: has[s] for s, d in rnd.edges}
        for d, v in recvd.items():
            has[d] = v
    return has


def _assert_reduce_conserves(tree, active):
    values = {r: 10 ** i for i, r in enumerate(sorted(tree.ranks))}
    rounds = prune_reduce_rounds(tree, active)
    acc = _run_reduce(tree, rounds, set(active), values)
    # distinct powers of ten: a wrong contributor set cannot cancel out
    assert acc[tree.root] == sum(values[r] for r in active), (
        f"active={sorted(active)}: root got {acc[tree.root]}"
    )
    return rounds


def chain8():
    return Tree(0, {i: [i + 1] for i in range(7)})


def test_prune_reduce_root_down_conserves():
    # the ROOT is down: it still aggregates (pure collector role) but must
    # not contribute its own value
    for tree in (binary7(), chain4(), chain8()):
        _assert_reduce_conserves(tree, set(tree.ranks) - {tree.root})


def test_prune_reduce_leaf_chain_down_conserves():
    # a whole leaf-side run of the chain is dead: its edges vanish
    # entirely from the pruned rounds (no wasted hops), sum still exact
    t = chain8()
    active = {0, 1, 2, 3, 4}
    rounds = _assert_reduce_conserves(t, active)
    edges = [e for r in rounds for e in r.edges]
    for dead_src in (5, 6, 7):
        assert not any(s == dead_src for s, _ in edges)
    # depth shrank to the live chain
    assert len(rounds) == len(active) - 1


def test_prune_reduce_multi_failure_scattered():
    # root down + a mid-chain relay + a dead leaf pair, together
    t = binary7()
    for active in ({1, 4, 5}, {3, 6}, {2, 3}, {5}):
        _assert_reduce_conserves(t, active)


def test_prune_recover_mid_epoch_sequences():
    # epoch 1: ranks {5, 6} down; epoch 2: 5 recovers; epoch 3: all back.
    # Each epoch's pruning is a pure function of (tree, active): the
    # recovered rank's edge reappears and conservation holds at every step
    t = binary7()
    epochs = [
        set(range(7)) - {5, 6},
        set(range(7)) - {6},
        set(range(7)),
    ]
    for active in epochs:
        _assert_reduce_conserves(t, active)
    e1 = [e for r in prune_reduce_rounds(t, epochs[0]) for e in r.edges]
    e2 = [e for r in prune_reduce_rounds(t, epochs[1]) for e in r.edges]
    assert (5, 2) not in e1 and (5, 2) in e2  # rank 5's edge came back
    e3 = [e for r in prune_reduce_rounds(t, epochs[2]) for e in r.edges]
    assert sorted(e3) == sorted(
        e for r in t.reduce_rounds() for e in r.edges
    )  # full recovery == the unpruned schedule


def test_prune_broadcast_multi_failure_delivers_to_live():
    # broadcast under the same multi-failure actives: every rank on a live
    # path (active, or forwarding toward an active rank) receives the
    # root's value; fully-dead subtrees receive nothing
    t = binary7()
    for active in ({1, 4, 5}, {3, 6}, {0, 3}):
        rounds = prune_broadcast_rounds(t, active)
        has = _run_broadcast(t, rounds, root_value=42)
        for r in active:
            if r == t.root:
                continue
            assert has[r] == 42, f"active rank {r} missed the broadcast"


def test_prune_rounds_stay_partial_permutations():
    # whatever the failure pattern, every pruned round must remain a valid
    # ppermute (CommRound's constructor enforces it — this pins that the
    # pruning never needs to re-pack)
    t = binary7()
    for active in ({1, 4, 5}, {3, 6}, {5}, set(range(7)) - {0}):
        for rnd in prune_reduce_rounds(t, active) + prune_broadcast_rounds(t, active):
            srcs = [s for s, _ in rnd.edges]
            dsts = [d for _, d in rnd.edges]
            assert len(set(srcs)) == len(srcs)
            assert len(set(dsts)) == len(dsts)
