"""Relay role algebra + schedule pruning (reference control.cu semantics)."""

from adapcc_tpu.comm.relay import (
    active_recvs,
    compute_role,
    compute_roles,
    prune_broadcast_rounds,
    prune_reduce_rounds,
)
from adapcc_tpu.strategy.ir import Tree


def chain4():
    return Tree(0, {0: [1], 1: [2], 2: [3]})


def binary7():
    return Tree(0, {0: [1, 2], 1: [3, 4], 2: [5, 6]})


def test_all_active_roles():
    t = binary7()
    roles = compute_roles(t, range(7))
    assert roles[0].has_recv and roles[0].has_local and roles[0].has_kernel
    assert not roles[0].has_send  # root never sends
    assert roles[3] == compute_role(t, 3, frozenset(range(7)))
    assert not roles[3].has_recv and roles[3].has_send and not roles[3].has_kernel


def test_pure_forward_relay():
    # chain 0<-1<-2<-3 with rank 1 inactive: it receives from 2's subtree and
    # forwards without reducing (exactly one live input, self inactive)
    t = chain4()
    role = compute_role(t, 1, frozenset({0, 2, 3}))
    assert role.has_recv and not role.has_local
    assert not role.has_kernel  # pure forward
    assert role.has_send


def test_inactive_leaf_subtree_is_dead():
    t = chain4()
    role = compute_role(t, 3, frozenset({0, 1, 2}))
    assert not role.has_send  # nothing live at or below 3
    role2 = compute_role(t, 2, frozenset({0, 1, 2}))
    assert not role2.has_recv  # 3's subtree is dead
    assert role2.has_send and not role2.has_kernel  # sends only its own data


def test_active_recvs_prunes_dead_subtrees():
    t = binary7()
    assert active_recvs(t, 1, frozenset({0, 4})) == [4]
    assert active_recvs(t, 1, frozenset({0})) == []
    assert active_recvs(t, 0, frozenset({3, 6})) == [1, 2]


def test_relay_rank_with_live_subtree_keeps_kernel_choice():
    # rank 1 inactive but both children active → still needs the reduction
    t = binary7()
    role = compute_role(t, 1, frozenset({3, 4}))
    assert role.has_kernel and role.has_send and not role.has_local


def test_prune_reduce_rounds_drops_dead_edges():
    t = binary7()
    rounds = prune_reduce_rounds(t, {0, 1, 2})  # all leaves inactive
    edges = [e for r in rounds for e in r.edges]
    assert (3, 1) not in edges and (6, 2) not in edges
    assert (1, 0) in edges and (2, 0) in edges

    full = prune_reduce_rounds(t, range(7))
    assert [r.edges for r in full] == [r.edges for r in t.reduce_rounds()]


def test_prune_broadcast_keeps_forwarding_path():
    # only leaf 3 active: broadcast must still traverse inactive rank 1
    t = binary7()
    rounds = prune_broadcast_rounds(t, {0, 3})
    edges = [e for r in rounds for e in r.edges]
    assert (0, 1) in edges and (1, 3) in edges
    assert (0, 2) not in edges and (1, 4) not in edges
