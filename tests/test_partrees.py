"""ParTrees heuristic + synthesizer policy switch + MILP solver."""

import numpy as np
import pytest

from adapcc_tpu.primitives import ALLREDUCE, DEFAULT_CHUNK_BYTES
from adapcc_tpu.strategy.partrees import ParTrees
from adapcc_tpu.strategy.synthesizer import Synthesizer, _infer_local_rank0s
from adapcc_tpu.strategy.xml_io import parse_strategy_xml


def two_hosts():
    ip_table = ["10.0.0.1"] * 4 + ["10.0.0.2"] * 4
    masters = [0, 4]
    world = len(ip_table)
    bw = np.full((world, world), 10.0)
    lat = np.full((world, world), 1.0)
    return ip_table, masters, bw, lat


def test_infer_local_rank0s():
    assert _infer_local_rank0s(["a", "a", "b", "b", "b", "c"]) == [0, 2, 5]


def test_partrees_two_hosts_structure():
    ip_table, masters, bw, lat = two_hosts()
    s = ParTrees().synthesize(ip_table, masters, 2, bw, lat)
    assert s.num_trans == 2
    for t in s.trees:
        assert t.ranks == frozenset(range(8))
        # roots are masters
        assert t.root in masters
        # intra-host chain: each master's first child is its next local rank
        for m in masters:
            kids = t.precedents(m)
            if kids:
                assert kids[0] == m + 1 or kids[0] in masters
        # chain links stay on-host
        for child, parent in t.parent.items():
            if ip_table[child] == ip_table[parent]:
                continue
            # inter-host edges only connect masters
            assert child in masters or parent in masters
    # root diversity across trees
    assert {t.root for t in s.trees} == set(masters)


def test_partrees_bdp_sort_picks_best_root():
    ip_table = ["a", "b", "c"]
    masters = [0, 1, 2]
    bw = np.ones((3, 3))
    lat = np.ones((3, 3))
    bw[1][2] = 100.0  # master 1's outbound link is best → highest bdp → first root
    s = ParTrees().synthesize(ip_table, masters, 1, bw, lat)
    assert s.trees[0].root == 1


def test_partrees_optimize_writes_xml(tmp_path):
    ip_table, masters, bw, lat = two_hosts()
    out = tmp_path / "strategy.xml"
    chunk = ParTrees().optimize(ip_table, masters, ALLREDUCE, 2, 1 << 20, bw, lat, str(out))
    assert chunk == DEFAULT_CHUNK_BYTES
    s = parse_strategy_xml(str(out))
    assert s.world_size == 8 and s.num_trans == 2


@pytest.mark.parametrize("policy,roots", [("ring", {0, 1}), ("binary", {0, 1})])
def test_synthesizer_fixed_policies(policy, roots):
    ip_table = ["a", "b"]
    syn = Synthesizer(None, ip_table, policy=policy)
    s = syn.synthesize(ALLREDUCE, 2, 1 << 20, np.ones((2, 2)), np.ones((2, 2)))
    assert {t.root for t in s.trees} == roots


def test_synthesizer_partrees_policy(tmp_path):
    ip_table, masters, bw, lat = two_hosts()
    out = tmp_path / "s.xml"
    syn = Synthesizer(str(out), ip_table)
    # the persisted chunk is clamped to the transmission size it pipelines
    # (a chunk larger than the payload is just the payload) and round-trips
    # through the XML, so the artifact determines ring execution
    chunk = syn.generate_strategy(ALLREDUCE, 2, 1 << 20, bw, lat)
    assert chunk == min(DEFAULT_CHUNK_BYTES, 1 << 20)
    persisted = parse_strategy_xml(str(out))
    assert persisted.world_size == 8
    assert persisted.chunk_bytes == chunk


def test_milp_solver_prefers_fast_root():
    ip_table = ["a", "b", "c"]
    masters = [0, 1, 2]
    bw = np.ones((3, 3)) * 1.0
    lat = np.ones((3, 3)) * 1.0
    # links out of rank 2 are far faster → rooting the broadcast at 2
    # minimizes makespan (allreduce would also pay the slow return paths,
    # so the preference is only decisive for the one-directional primitive)
    bw[2, :] = 1000.0
    syn = Synthesizer(None, ip_table, policy="milp")
    from adapcc_tpu.primitives import BOARDCAST

    s = syn.synthesize(BOARDCAST, 1, 1 << 26, bw, lat)
    assert s.num_trans == 1
    assert s.trees[0].ranks == frozenset(range(3))
    assert s.trees[0].root == 2


def test_milp_solver_splits_shares_across_trees():
    ip_table, masters, bw, lat = two_hosts()
    syn = Synthesizer(None, ip_table, policy="milp")
    s = syn.synthesize(ALLREDUCE, 2, 1 << 26, bw, lat)
    assert s.num_trans == 2
    assert {t.root for t in s.trees} == {0, 4}


def test_routing_milp_avoids_slow_link():
    """The routing formulation chooses tree edges, not just roots: with one
    pathologically slow link, no chosen inter-host edge crosses it (the
    rotation model cannot express this)."""
    ip_table = ["a", "b", "c", "d"]
    bw = np.full((4, 4), 100.0)
    lat = np.full((4, 4), 1e-4)
    bw[0, 1] = bw[1, 0] = 0.001  # the poisoned link
    syn = Synthesizer(None, ip_table, policy="milp")
    s = syn.synthesize(ALLREDUCE, 1, 1 << 26, bw, lat)
    tree = s.trees[0]
    for child, parent in tree.parent.items():
        assert {child, parent} != {0, 1}, "tree routed through the slow link"
    # still a spanning tree over all masters
    assert tree.ranks == frozenset(range(4))


def test_routing_milp_trees_are_valid_arborescences():
    rng = np.random.default_rng(5)
    ip_table = ["a"] * 2 + ["b"] * 2 + ["c"] * 2 + ["d"] * 2
    world = len(ip_table)
    bw = rng.uniform(1, 50, size=(world, world))
    lat = rng.uniform(1e-5, 1e-3, size=(world, world))
    syn = Synthesizer(None, ip_table, policy="milp")
    s = syn.synthesize(ALLREDUCE, 3, 1 << 24, bw, lat)
    assert s.num_trans == 3
    assert len({t.root for t in s.trees}) == 3  # root diversity
    assert sum(s.tree_shares()) == pytest.approx(1.0)
    for tree in s.trees:
        # Tree's constructor validates single-parent/acyclic; check spanning
        assert tree.ranks == frozenset(range(world))


def test_routing_milp_routes_through_fast_hub():
    """With only node 2's links fast, every tree must run through the hub —
    no tree may use the slow 0↔1 edge, whatever its root."""
    ip_table = ["a", "b", "c"]
    bw = np.full((3, 3), 1.0)
    lat = np.full((3, 3), 1e-3)
    bw[2, :] = bw[:, 2] = 50.0
    syn = Synthesizer(None, ip_table, policy="milp")
    s = syn.synthesize(ALLREDUCE, 2, 1 << 26, bw, lat)
    assert sum(s.tree_shares()) == pytest.approx(1.0)
    for tree in s.trees:
        for child, parent in tree.parent.items():
            assert {child, parent} != {0, 1}, "tree used the slow edge"


def test_routing_milp_falls_back_beyond_size_guard(monkeypatch):
    from adapcc_tpu.strategy import solver as solver_mod

    monkeypatch.setattr(solver_mod, "ROUTING_MILP_MAX_MASTERS", 2)
    ip_table = ["a", "b", "c"]
    bw = np.ones((3, 3)) * 10.0
    lat = np.ones((3, 3)) * 1e-4
    syn = Synthesizer(None, ip_table, policy="milp")
    s = syn.synthesize(ALLREDUCE, 1, 1 << 20, bw, lat)  # 3 masters > guard of 2
    assert s.trees[0].ranks == frozenset(range(3))
    assert s.synthesis == "milp-rotation"


def test_per_primitive_costs_pick_different_trees():
    """Reference solver.py:143-176 models link loads per primitive: REDUCE
    traffic rides child→parent, BOARDCAST parent→child.  On a profile where
    rank 0's *outgoing* links are fast but its *incoming* links are slow, the
    broadcast-optimal tree roots at 0 (sends only) while the reduce-optimal
    tree must not (it would receive over the slow links)."""
    from adapcc_tpu.primitives import BOARDCAST, REDUCE

    ip_table = ["a", "b", "c"]
    bw = np.full((3, 3), 1.0)
    lat = np.full((3, 3), 1e-4)
    bw[0, :] = 1000.0   # 0 sends fast
    bw[:, 0] = 0.01     # 0 receives very slowly
    bw[1, 2] = bw[2, 1] = 100.0
    syn = Synthesizer(None, ip_table, policy="milp")
    b = syn.synthesize(BOARDCAST, 1, 1 << 26, bw, lat)
    r = syn.synthesize(REDUCE, 1, 1 << 26, bw, lat)
    assert b.trees[0].root == 0, "broadcast should root at the fast sender"
    assert r.trees[0].root != 0, "reduce must avoid receiving at rank 0"
    # reduce must not have any edge delivering INTO rank 0 over a slow link
    # except unavoidably the one from its parent-relationship: rank 0 must be
    # a leaf (sends only)
    assert 0 not in r.trees[0].children, "reduce tree makes 0 receive"


def test_alltoall_milp_accounts_for_edge_multiplicity():
    """ALLTOALL link load = number of flows behind the edge (reference else
    branch, solver.py:169-176): the solver must produce a valid spanning
    strategy and record the routing formulation."""
    from adapcc_tpu.primitives import ALLTOALL

    ip_table = ["a", "b", "c", "d"]
    rng = np.random.default_rng(9)
    bw = rng.uniform(5, 50, size=(4, 4))
    lat = np.full((4, 4), 1e-4)
    syn = Synthesizer(None, ip_table, policy="milp")
    s = syn.synthesize(ALLTOALL, 2, 1 << 24, bw, lat)
    assert s.synthesis == "milp-routing"
    for t in s.trees:
        assert t.ranks == frozenset(range(4))
    # alltoall shares are pinned uniform (payloads are per-pair)
    assert all(sh == pytest.approx(0.5) for sh in s.tree_shares())


def test_synthesis_attribute_roundtrips_xml(tmp_path):
    from adapcc_tpu.strategy.xml_io import emit_strategy_xml

    ip_table = ["a", "b", "c"]
    bw = np.ones((3, 3)) * 10.0
    lat = np.ones((3, 3)) * 1e-4
    syn = Synthesizer(None, ip_table, policy="milp")
    s = syn.synthesize(ALLREDUCE, 1, 1 << 20, bw, lat)
    assert s.synthesis == "milp-routing"
    text = emit_strategy_xml(s)
    assert 'synthesis="milp-routing"' in text
    assert parse_strategy_xml(text).synthesis == "milp-routing"
    # heuristic policies record their provenance too
    p = Synthesizer(None, ip_table, policy="par-trees").synthesize(
        ALLREDUCE, 1, 1 << 20, bw, lat
    )
    assert p.synthesis == "partrees"


def test_zero_share_tree_does_not_inflate_makespan():
    """Advisor finding: an unused tree's edge latencies must not bound T.
    With 2 broadcast trees over 2 masters, root diversity forces one tree
    onto the catastrophic 1→0 direction; the used-tree gate lets the solver
    park it at share 0 so T reflects only the fast tree — without the gate T
    is pinned at the slow tree's latency and the share split is arbitrary."""
    from adapcc_tpu.primitives import BOARDCAST

    ip_table = ["a", "b"]
    bw = np.array([[1.0, 1000.0], [0.001, 1.0]])
    lat = np.array([[0.0, 1e-4], [10.0, 0.0]])
    syn = Synthesizer(None, ip_table, policy="milp")
    s = syn.synthesize(BOARDCAST, 2, 1 << 26, bw, lat)
    assert s.num_trans == 2
    shares = {t.root: sh for t, sh in zip(s.trees, s.tree_shares())}
    assert shares[0] == pytest.approx(1.0, abs=1e-6)
    assert shares[1] == pytest.approx(0.0, abs=1e-6)
