"""Record a short collective session and export it as a Perfetto trace.

Drives a virtual-pod (or real-TPU) engine through a handful of traced
dispatches with the tuner in ``record`` mode — so events carry measured
``duration_s`` — then writes ``chrome://tracing`` JSON via
:meth:`adapcc_tpu.utils.observability.CollectiveTrace.dump_chrome_trace`.
Open the output at https://ui.perfetto.dev (``make trace-export``).

Usage::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -m scripts.trace_export [out.json]
"""

from __future__ import annotations

import os
import sys


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    # --no-summary drops the per-impl p50/p99 summary track from the
    # export (the raw-events-only view); default keeps it, so decode-step
    # tail behavior is one Perfetto click, no hand-aggregation
    summary = "--no-summary" not in argv
    argv = [a for a in argv if a != "--no-summary"]
    out = argv[0] if argv else os.path.join(
        "benchmarks", "results", "trace_export.json"
    )
    # record mode: time every dispatch into the trace (and the tuning db,
    # pointed at a scratch file so a demo run never pollutes the real one)
    os.environ.setdefault("ADAPCC_TUNER", "record")
    os.environ.setdefault(
        "ADAPCC_TUNER_DB",
        os.path.join("benchmarks", "results", "trace_export_tuning.jsonl"),
    )

    import jax
    import jax.numpy as jnp
    import numpy as np

    from adapcc_tpu.comm.engine import CollectiveEngine
    from adapcc_tpu.comm.mesh import build_world_mesh
    from adapcc_tpu.compat import ring_kernels_supported
    from adapcc_tpu.strategy.ir import Strategy
    from adapcc_tpu.utils.observability import CollectiveTrace

    world = len(jax.devices())
    mesh = build_world_mesh(world)
    trace = CollectiveTrace()
    engine = CollectiveEngine(mesh, Strategy.ring(world), trace=trace)
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(world, 8192)), jnp.float32
    )
    for _ in range(3):
        jax.block_until_ready(engine.all_reduce(x))
        jax.block_until_ready(engine.all_gather(x))
        if world >= 2:
            # the quantized ppermute ring runs on any backend; the fp32
            # Pallas ring needs a TPU or the Mosaic interpreter
            jax.block_until_ready(engine.ring_allreduce(x, wire_dtype="int8"))
            if ring_kernels_supported():
                jax.block_until_ready(engine.ring_allreduce(x))
    d = os.path.dirname(out)
    if d:
        os.makedirs(d, exist_ok=True)
    engine.trace.dump_chrome_trace(out, impl_summary=summary)
    timed = sum(1 for e in trace.events() if "duration_s" in e.extra)
    print(
        f"[trace-export] {len(trace.events())} events ({timed} timed) -> {out}"
    )
    for impl, stats in trace.impl_summary().items():
        p50 = stats["p50_s"]
        p99 = stats["p99_s"]
        print(
            f"[trace-export]   {impl:<14} n={stats['count']:>4} "
            f"timed={stats['timed']:>4}"
            + (
                f"  p50={p50 * 1e6:>10.1f}us  p99={p99 * 1e6:>10.1f}us"
                if p50 is not None else ""
            )
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
