"""Tunnel watcher: fire the hardware battery at the first live window.

Round 3 lost its single ~2-minute TPU window because the probe loop lived
in /tmp and nothing auto-fired the measurement battery (VERDICT r3,
Missing #1).  This watcher closes that gap:

- every ``--interval`` seconds (default 180) it runs a bounded probe
  subprocess (tiny jit on the default backend; 90 s deadline),
- every attempt is appended to ``benchmarks/results/hw_watch_<tag>.jsonl``
  so the watching itself leaves an artifact,
- on the first successful probe it execs ``python -m benchmarks.hw_session
  <tag>`` (blocking; the battery appends per-phase JSONL as it goes), then
  keeps watching for further windows and re-fires with suffixed tags
  (``<tag>b``, ``<tag>c``) up to ``--max-batteries``.

``JAX_PLATFORMS`` / ``XLA_FLAGS`` are stripped from child environments:
the test-suite conftest pins a virtual CPU pod via those, and a leaked
value would turn a hardware probe into a CPU probe.

Usage::

    python scripts/hw_watch.py r04 --max-hours 11
"""

from __future__ import annotations

import argparse
import json
import os
import string
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from benchmarks.hw_session import PROBE_CODE, hw_env  # noqa: E402


def probe(deadline: int) -> dict:
    t0 = time.time()
    rec: dict = {"ts": round(t0, 1)}
    try:
        p = subprocess.run(
            [sys.executable, "-c", PROBE_CODE],
            capture_output=True, text=True, timeout=deadline,
            cwd=REPO, env=hw_env(),
        )
        rec["secs"] = round(time.time() - t0, 1)
        if p.returncode == 0:
            try:
                rec.update(json.loads((p.stdout or "").strip().splitlines()[-1]))
                # a CPU-fallback probe is NOT a live hardware window
                expect = os.environ.get("HW_EXPECT_PLATFORM", "tpu")
                rec["ok"] = expect == "any" or rec.get("platform") == expect
                if not rec["ok"]:
                    rec["error"] = f"platform {rec.get('platform')!r} != {expect!r}"
            except (json.JSONDecodeError, IndexError):
                rec["ok"] = False
                rec["error"] = "unparseable probe output"
        else:
            rec["ok"] = False
            rec["error"] = (p.stderr or "")[-300:]
    except subprocess.TimeoutExpired:
        rec["ok"] = False
        rec["secs"] = round(time.time() - t0, 1)
        rec["error"] = f"probe timeout after {deadline}s"
    except Exception as e:  # fork/exec failures must not kill an 11 h watch
        rec["ok"] = False
        rec["secs"] = round(time.time() - t0, 1)
        rec["error"] = f"{type(e).__name__}: {e}"
    return rec


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("tag", nargs="?", default="r04")
    ap.add_argument("--interval", type=int, default=180)
    ap.add_argument("--probe-deadline", type=int, default=90)
    ap.add_argument("--max-hours", type=float, default=11.0)
    ap.add_argument("--max-batteries", type=int, default=3)
    args = ap.parse_args()

    # never append to a previous session's (possibly committed) artifacts:
    # if this tag's battery or watch file already exists, auto-suffix a
    # fresh session tag (r04 → r04b → r04c ...)
    results = os.path.join(REPO, "benchmarks", "results")
    os.makedirs(results, exist_ok=True)

    def _taken(tag: str) -> bool:
        return os.path.exists(os.path.join(results, f"hw_{tag}.jsonl")) or \
            os.path.exists(os.path.join(results, f"hw_watch_{tag}.jsonl"))

    if _taken(args.tag):
        base = args.tag
        candidates = [base + ch for ch in string.ascii_lowercase[1:]]
        # unbounded numeric fallback (same scheme as the battery namer):
        # the guard must never silently fall through to the taken tag
        n = 26
        fresh = None
        for cand in candidates:
            if not _taken(cand):
                fresh = cand
                break
        while fresh is None:
            if not _taken(f"{base}x{n}"):
                fresh = f"{base}x{n}"
            n += 1
        print(f"[watch] tag {base!r} has existing artifacts; "
              f"using {fresh!r}", flush=True)
        args.tag = fresh

    out = os.path.join(results, f"hw_watch_{args.tag}.jsonl")
    end = time.time() + args.max_hours * 3600
    succeeded = 0   # batteries whose own probe ran (rc==0) — these spend budget
    attempts = 0    # all batteries fired, incl. ones a flapping window killed

    print(f"[watch] probing every {args.interval}s until "
          f"{args.max_batteries} good batteries or {args.max_hours}h", flush=True)
    while time.time() < end:
        rec = probe(args.probe_deadline)
        with open(out, "a") as f:
            f.write(json.dumps(rec) + "\n")
        if rec.get("ok"):
            # suffix repeat batteries: r04, r04b, ..., r04z, r04x26, r04x27, ...
            if attempts == 0:
                tag = args.tag
            elif attempts < 26:
                tag = args.tag + string.ascii_lowercase[attempts]
            else:
                tag = f"{args.tag}x{attempts}"
            print(f"[watch] LIVE ({rec.get('kind')}) → battery {tag}", flush=True)
            try:
                bat_rc = subprocess.run(
                    [sys.executable, "-m", "benchmarks.hw_session", tag],
                    cwd=REPO, env=hw_env(),
                ).returncode
            except Exception as e:
                bat_rc = -99
                print(f"[watch] battery spawn failed: {e}", flush=True)
            attempts += 1
            if bat_rc == 0:
                succeeded += 1
            with open(out, "a") as f:
                f.write(json.dumps({"ts": round(time.time(), 1),
                                    "battery": tag, "rc": bat_rc}) + "\n")
            # only batteries that got past their own probe spend the budget —
            # a flapping tunnel must not exhaust attempts with zero data
            if succeeded >= args.max_batteries:
                print("[watch] battery budget spent; exiting", flush=True)
                return 0
            # a window just closed or battery finished — back off a little
            time.sleep(max(args.interval, 300) if bat_rc == 0 else args.interval)
        else:
            print(f"[watch] dead ({rec.get('error', '?')[:60]})", flush=True)
            time.sleep(args.interval)
    print(f"[watch] {args.max_hours}h elapsed; "
          f"{succeeded}/{attempts} batteries succeeded", flush=True)
    return 0 if succeeded else 1


if __name__ == "__main__":
    sys.exit(main())
