"""Checkpoint / resume + elastic recovery.

The reference's checkpoint story lives in the elastic imagenet workload
(models/image-classification/main_elastic.py): a mutable ``State`` with
``capture_snapshot``/``apply_snapshot``, atomic save via tmp-file+rename
(main_elastic.py:395-410), and — because vanilla hosts have no shared fs — a
rendezvous-time broadcast of the newest checkpoint from the rank with the
largest epoch (main_elastic.py:306-385).

TPU-native shape: pytrees serialize with flax msgpack (no pickle), the
step-directory manager is orbax (async-capable, the JAX-ecosystem standard),
and the cross-process "broadcast from the freshest rank" rides the
jax.distributed coordinator KV store instead of a temporary gloo process
group.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import queue
import re
import shutil
import subprocess
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
from flax import serialization

#: async crash-consistent checkpointing gate for the workloads
#: (docs/RECOVERY.md §2): ``on`` routes epoch saves through the
#: :class:`AsyncCheckpointManager` background pipeline; malformed → loud
ASYNC_CKPT_ENV = "ADAPCC_ASYNC_CKPT"


def async_checkpointing_enabled(explicit: bool = False) -> bool:
    """The ``ADAPCC_ASYNC_CKPT`` funnel: env > explicit flag > off
    (malformed → loud, the ADAPCC_MERGE_ROUNDS policy)."""
    raw = os.environ.get(ASYNC_CKPT_ENV, "").strip().lower()
    if not raw:
        return bool(explicit)
    if raw in ("on", "1", "true"):
        return True
    if raw in ("off", "0", "false"):
        return False
    raise ValueError(f"{ASYNC_CKPT_ENV}={raw!r}: expected on|off")


def _fsync_path(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    """Durably commit a directory entry change (rename, create): the rename
    itself is atomic but not *durable* until the parent directory's
    metadata hits disk — a crash after rename but before the dir fsync can
    resurface the old name, which is exactly the torn-checkpoint window
    the durability satellite closes."""
    _fsync_path(path)


# --- snapshot container (reference State, main_elastic.py:188-237) ------------

#: ``extra`` keys that describe the *layout* of the stored tensors: when the
#: in-memory state declares one (e.g. Zero1Optimizer.checkpoint_extra's
#: "zero1_layout"), a loaded snapshot must match it exactly — restoring a
#: chunk-permuted master under a flipped layout must fail loudly, not load
LAYOUT_GUARD_KEYS = ("zero1_layout",)


@dataclass
class TrainCheckpointState:
    """Everything a worker needs to resume: mirrors the reference ``State``
    (epoch, best metric, model + optimizer state), as a jax pytree."""

    params: Any
    opt_state: Any = None
    epoch: int = -1
    step: int = 0
    best_metric: float = 0.0
    extra: Dict[str, Any] = field(default_factory=dict)

    def capture_snapshot(self) -> Dict[str, Any]:
        """Serialize-ready dict; ``apply_snapshot`` is its inverse."""
        return {
            "epoch": self.epoch,
            "step": self.step,
            "best_metric": self.best_metric,
            "params": self.params,
            "opt_state": self.opt_state,
            "extra": self.extra,
        }

    def apply_snapshot(self, obj: Dict[str, Any]) -> None:
        """Mutates this state from a snapshot (reference apply_snapshot).

        Layout-guard keys declared by the in-memory ``extra`` are enforced
        against the snapshot before anything mutates: every load funnel
        (load_checkpoint, CheckpointManager.restore, the elastic rendezvous
        broadcast) routes through here, so a resume whose optimizer layout
        (ring/world/align) differs from what was saved raises instead of
        silently loading permuted tensors.
        """
        self._enforce_layout_guard(obj.get("extra"))
        self.epoch = int(obj["epoch"])
        self.step = int(obj["step"])
        self.best_metric = float(obj["best_metric"])
        self.params = obj["params"]
        self.opt_state = obj["opt_state"]
        self.extra = dict(obj.get("extra", {}))

    def _enforce_layout_guard(self, incoming_extra: Any) -> None:
        incoming = dict(incoming_extra or {})
        for key in LAYOUT_GUARD_KEYS:
            expected = (self.extra or {}).get(key)
            if expected is not None and incoming.get(key) != expected:
                raise ValueError(
                    f"checkpoint layout mismatch on extra[{key!r}]: "
                    f"saved={incoming.get(key)!r} vs resuming="
                    f"{expected!r}; restoring would load permuted tensors "
                    "— resume with the matching configuration or re-shard "
                    "offline"
                )
            if (
                expected is None
                and incoming.get(key) is not None
                and self.opt_state is not None
            ):
                # the checkpoint's optimizer state was saved under a sharded
                # layout this resume never declared: restoring it blind is
                # the silent chunk-permutation hazard the tag exists to
                # close.  Params-only loads (opt_state=None templates, e.g.
                # inference) are unaffected — params are not permuted.
                raise ValueError(
                    f"checkpoint carries a layout tag extra[{key!r}] but "
                    "this resume declares none; stamp the resuming state's "
                    "extra (DDPTrainer.checkpoint_extra() / "
                    "Zero1Optimizer.checkpoint_extra()) so the layout can "
                    "be verified, or load with opt_state=None for "
                    "params-only use"
                )

    def to_bytes(self) -> bytes:
        return serialization.to_bytes(self.capture_snapshot())

    def load_bytes(self, blob: bytes) -> None:
        template = self.capture_snapshot()
        # decode once, then guard on the RAW extra before flax template
        # matching (from_bytes is msgpack_restore + from_state_dict).  The
        # raw peek is load-bearing in both guard directions: a declaring
        # state resuming an untagged legacy blob must get the guard's
        # actionable message (not flax's raw key-mismatch), and a tagged
        # blob restored into an undeclared optimizer-carrying state must
        # refuse — from_state_dict silently DROPS unknown extra keys, so
        # apply_snapshot alone would never see the tag
        raw = serialization.msgpack_restore(blob)
        self._enforce_layout_guard(
            raw.get("extra") if isinstance(raw, dict) else None
        )
        self.apply_snapshot(serialization.from_state_dict(template, raw))


# --- single-file atomic checkpoints (main_elastic.py:395-410) -----------------


def save_checkpoint(
    state: TrainCheckpointState, filename: str, is_best: bool = False
) -> None:
    """Atomic **and crash-durable** save: write tmp, flush + fsync the
    bytes, rename-commit, then fsync the parent directory — the rename
    alone orders the name change but does not make it durable, and an
    unfsynced payload can commit a name pointing at unwritten blocks
    (docs/RECOVERY.md §2).  ``is_best`` keeps a ``model_best`` copy beside
    it (both reference behaviors)."""
    checkpoint_dir = os.path.dirname(filename) or "."
    os.makedirs(checkpoint_dir, exist_ok=True)
    # pid-suffixed tmp: concurrent savers on a shared fs each write their own
    # tmp and the (content-identical) renames commit atomically, never torn
    tmp_filename = f"{filename}.tmp.{os.getpid()}"
    with open(tmp_filename, "wb") as f:
        f.write(state.to_bytes())
        f.flush()
        os.fsync(f.fileno())
    os.rename(tmp_filename, filename)
    _fsync_dir(checkpoint_dir)
    if is_best:
        best = os.path.join(checkpoint_dir, "model_best.ckpt")
        best_tmp = f"{best}.tmp.{os.getpid()}"
        shutil.copyfile(filename, best_tmp)
        with open(best_tmp, "rb") as f:
            os.fsync(f.fileno())
        os.rename(best_tmp, best)
        _fsync_dir(checkpoint_dir)


def load_checkpoint(state: TrainCheckpointState, filename: str) -> bool:
    """Load into ``state`` if the file exists; returns whether it did."""
    if not os.path.isfile(filename):
        return False
    with open(filename, "rb") as f:
        state.load_bytes(f.read())
    return True


# --- newest-checkpoint rendezvous broadcast (main_elastic.py:306-385) ---------

#: base64 chars per KV-store blob chunk (~2 MB < the ~4 MB gRPC message cap)
_BLOB_CHUNK_CHARS = 2 * 1024 * 1024


def _rendezvous_fetch(key: str, what: str, budget_s: float) -> str:
    """One rendezvous KV fetch under the PR-10 deadline + bounded-backoff
    funnel (``ADAPCC_RPC_TIMEOUT_S``): a dead peer that never publishes
    its key surfaces as a loud :class:`~adapcc_tpu.coordinator.service.
    CoordinatorUnavailable` naming exactly what was waited for, never an
    indefinite block inside the restore barrier."""
    import random

    from adapcc_tpu.coordinator.service import (
        RPC_BACKOFF_INITIAL_S,
        RPC_BACKOFF_MAX_S,
        RPC_TIMEOUT_ENV,
        CoordinatorUnavailable,
    )
    from adapcc_tpu.launch.dispatcher import fetch_value

    rng = random.Random(0xCCC ^ hash(key) & 0xFFFF)
    deadline = time.monotonic() + budget_s
    backoff = RPC_BACKOFF_INITIAL_S
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise CoordinatorUnavailable(
                f"elastic rendezvous: {what} got no answer within "
                f"{budget_s:.3f}s ({RPC_TIMEOUT_ENV} budget) — a dead peer "
                "must surface loudly, not hang the restore barrier"
            )
        try:
            # per-attempt slice so a transient KV hiccup retries inside the
            # budget instead of burning it all on one blocked get
            slice_ms = max(1, int(min(remaining, 2.0) * 1000))
            return fetch_value(key, slice_ms)
        except Exception as e:  # noqa: BLE001 — the KV timeout type varies
            if "jax.distributed.initialize" in str(e):
                # the transport was never brought up: permanent, not a
                # slow peer — burning the whole budget retrying it would
                # bury the real cause under a misleading timeout
                raise
            sleep = min(
                backoff * (1.0 + rng.random()),
                RPC_BACKOFF_MAX_S,
                max(0.0, deadline - time.monotonic()),
            )
            if sleep > 0:
                time.sleep(sleep)
            backoff = min(backoff * 2, RPC_BACKOFF_MAX_S)


def restore_newest_across_processes(
    state: TrainCheckpointState,
    filename: str,
    timeout_ms: int = 120_000,
    gen: Optional[str] = None,
    load_local: bool = True,
) -> TrainCheckpointState:
    """Elastic-restart restore: load the local checkpoint (if any), then adopt
    the freshest one any process holds.

    Single-process: plain local load.  Multi-process: every process publishes
    its epoch to the coordinator KV store; the holder of the max epoch
    publishes the snapshot blob and everyone else applies it — the KV-store
    analog of the reference's gloo max-epoch broadcast.  Restart generations
    are keyed by ``ADAPCC_RESTART_GEN`` (set by the elastic supervisor; a
    rejoining replacement worker passes the supervisor-journaled admit
    generation via ``gen=`` instead, docs/RECOVERY.md §3) so a relaunched
    world never reads a previous generation's keys.

    Every fetch runs under the ``ADAPCC_RPC_TIMEOUT_S`` deadline with
    bounded jittered backoff (the PR-10 coordinator-RPC funnel): a peer
    that died between publishing and serving its blob surfaces as a loud
    ``CoordinatorUnavailable`` naming the missing key, never an
    indefinite block.  ``timeout_ms`` caps the budget from above for
    callers that want a tighter barrier; the env deadline applies only
    when the operator actually set it.  ``load_local=False`` skips the
    local single-file load for callers that already restored fresher
    state through another funnel (the async step manager's verified
    restore).
    """
    if load_local:
        load_checkpoint(state, filename)
    if jax.process_count() <= 1:
        return state

    from adapcc_tpu.coordinator.service import RPC_TIMEOUT_ENV, rpc_timeout_s
    from adapcc_tpu.launch.dispatcher import publish_value

    if gen is None:
        gen = os.environ.get("ADAPCC_RESTART_GEN", "0")
        prefix = f"adapcc/elastic/g{gen}"
    else:
        # rejoin catch-up: the admit generation is a coordinator counter,
        # deliberately namespaced APART from the supervisor's restart
        # generations — a full-world restart publishes under g<N>, and a
        # later rejoin whose admit counter happens to reach the same N
        # must never read those stale epochs/blobs as its own
        prefix = f"adapcc/elastic/rejoin/g{gen}"
    me = jax.process_index()
    n = jax.process_count()
    # the env deadline wins only when the operator actually set it: the
    # default rpc budget (30 s) must not silently shrink the documented
    # 120 s restore barrier under it (staggered relaunches legitimately
    # take that long to reach the rendezvous)
    if os.environ.get(RPC_TIMEOUT_ENV, "").strip():
        budget_s = min(rpc_timeout_s(), timeout_ms / 1000.0)
    else:
        budget_s = timeout_ms / 1000.0

    publish_value(f"{prefix}/epoch/{me}", str(state.epoch))
    with ThreadPoolExecutor(max_workers=min(32, n)) as pool:
        epochs = list(
            pool.map(
                lambda p: int(
                    _rendezvous_fetch(
                        f"{prefix}/epoch/{p}", f"epoch of peer {p}", budget_s
                    )
                ),
                range(n),
            )
        )
    max_epoch = max(epochs)
    if max_epoch < 0:
        return state  # nobody has a checkpoint: fresh start everywhere
    max_rank = epochs.index(max_epoch)

    # ranks already at max_epoch (shared-fs steady state: all of them) need no
    # blob; the holder publishes only if someone is actually behind.  The blob
    # is chunked: the KV store carries values over gRPC, whose message cap a
    # single whole-checkpoint string would blow past on any real model.
    if me == max_rank and min(epochs) < max_epoch:
        encoded = base64.b64encode(state.to_bytes()).decode()
        chunks = [
            encoded[i : i + _BLOB_CHUNK_CHARS]
            for i in range(0, len(encoded), _BLOB_CHUNK_CHARS)
        ] or [""]
        publish_value(f"{prefix}/blob/count", str(len(chunks)))
        for i, chunk in enumerate(chunks):
            publish_value(f"{prefix}/blob/{i}", chunk)
    elif state.epoch < max_epoch:
        count = int(
            _rendezvous_fetch(
                f"{prefix}/blob/count",
                f"checkpoint blob count from rank {max_rank}",
                budget_s,
            )
        )
        encoded = "".join(
            _rendezvous_fetch(
                f"{prefix}/blob/{i}",
                f"checkpoint blob chunk {i}/{count} from rank {max_rank}",
                budget_s,
            )
            for i in range(count)
        )
        state.load_bytes(base64.b64decode(encoded))
    return state


# --- orbax step-directory manager ---------------------------------------------


class CheckpointManager:
    """Directory-of-steps manager over orbax: ``save(step, state)``,
    ``latest_step()``, ``restore(state, step=None)``, bounded retention.

    This is the shared-fs path the reference's note recommends when "globally
    visible persistent storage" exists (main_elastic.py load_checkpoint
    docstring); on TPU pods that is the norm, so orbax is the primary story
    and the KV broadcast above is the no-shared-fs fallback.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def save(self, step: int, state: TrainCheckpointState) -> None:
        self._mgr.save(step, args=self._ocp.args.StandardSave(state.capture_snapshot()))
        self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, state: TrainCheckpointState, step: Optional[int] = None) -> bool:
        step = step if step is not None else self.latest_step()
        if step is None:
            return False
        restored = self._mgr.restore(
            step, args=self._ocp.args.StandardRestore(state.capture_snapshot())
        )
        state.apply_snapshot(restored)
        return True

    # -- sharded (FSDP/ZeRO) pytrees ------------------------------------------

    def save_sharded(self, step: int, tree: Any) -> None:
        """Save a pytree of (possibly sharded) ``jax.Array``s.

        Each process writes only its addressable shards — FSDP/ZeRO state
        checkpoints at 1/world of the HBM and never materializes the full
        parameter on any host, unlike the msgpack path above (which is for
        small replicated state).
        """
        self._mgr.save(step, args=self._ocp.args.StandardSave(tree))
        self._mgr.wait_until_finished()

    def restore_sharded(self, target: Any, step: Optional[int] = None) -> Any:
        """Restore into ``target``'s layout: a pytree of arrays (their
        shardings are reused) or ``jax.ShapeDtypeStruct``s with shardings.
        Returns the restored tree, sharded as the target prescribes —
        restore-time resharding (e.g. onto a different world size) is
        orbax's job, not a host gather."""
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint step in {self.directory}")
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if isinstance(x, jax.Array)
            else x,
            target,
        )
        return self._mgr.restore(step, args=self._ocp.args.StandardRestore(abstract))

    def close(self) -> None:
        self._mgr.close()


# --- async crash-consistent step-directory manager ----------------------------

MANIFEST_NAME = "MANIFEST.json"
MANIFEST_VERSION = 1

_STEP_DIR_RE = re.compile(r"^step-(\d+)$")
_TMP_DIR_RE = re.compile(r"^\.tmp-step-(\d+)-")


class CheckpointCorrupt(ValueError):
    """A published checkpoint failed integrity verification (checksum
    mismatch, truncated shard, manifest naming a missing file).  Loud by
    design: restoring a torn artifact silently is the failure mode the
    manifest exists to close."""


class AsyncCheckpointManager:
    """Crash-consistent directory-of-steps manager with an async save
    pipeline and content verification (docs/RECOVERY.md §2).

    Layout: one ``step-<n>/`` directory per checkpoint, holding the
    serialized shard blobs plus a ``MANIFEST.json`` recording each shard's
    byte count and sha256.  The publish protocol makes a checkpoint
    all-or-nothing::

        write shards into .tmp-step-<n>-<pid>/   (fsync each file)
        write MANIFEST.json into the tmp dir      (fsync)
        rename .tmp-step-<n>-<pid>/ → step-<n>/   (atomic)
        fsync the parent directory                (durable)

    so the ONE legal kind of crash damage is a leftover ``.tmp-*``
    directory — ignored on scan exactly like the supervisor journal's
    torn tail.  A *published* step that fails verification (bit flip,
    truncation, a shard deleted out from under the manifest) rejects
    loudly at restore with :class:`CheckpointCorrupt`.

    ``save(step, state)`` is synchronous; ``save_async(step, state)``
    snapshots the (immutable) device buffers on the caller's thread and
    runs serialize → checksum → publish on a background thread, so the
    training loop never stalls on checkpoint I/O.  A pipeline error is
    re-raised loudly at the next ``save``/``wait``/``close`` — async must
    not mean silently lossy.

    Retention is **keep-last-good**: ``max_to_keep`` counts only steps
    that pass verification at GC time, so the newest *verified*
    checkpoint is never collected just because a newer corrupt directory
    exists above it (the corrupt one is the casualty, with a stderr
    warning).
    """

    def __init__(self, directory: str, max_to_keep: int = 3) -> None:
        if max_to_keep < 1:
            raise ValueError(f"max_to_keep must be >= 1, got {max_to_keep}")
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = max_to_keep
        self.restores = 0
        self._queue: "queue.Queue" = queue.Queue()
        self._worker: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._spawn_lock = threading.Lock()
        self._idle = threading.Event()
        self._idle.set()

    # -- scan ------------------------------------------------------------------

    def published_steps(self) -> List[int]:
        """Step numbers with a *published* (renamed-in) directory, sorted.
        ``.tmp-*`` leftovers — the mid-save crash window — are ignored by
        construction; a published dir missing its manifest cannot exist
        without tampering and raises loudly on access."""
        steps = []
        for name in os.listdir(self.directory):
            m = _STEP_DIR_RE.match(name)
            if m:
                steps.append(int(m.group(1)))
        return sorted(steps)

    def torn_saves(self) -> List[str]:
        """Leftover ``.tmp-*`` directories (crash-mid-save debris): never
        restorable, safe to ignore, listed so operators can see the crash
        happened."""
        return sorted(
            name
            for name in os.listdir(self.directory)
            if _TMP_DIR_RE.match(name)
        )

    def latest_step(self) -> Optional[int]:
        steps = self.published_steps()
        return steps[-1] if steps else None

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step-{int(step)}")

    # -- integrity -------------------------------------------------------------

    def _manifest(self, step: int) -> Dict[str, Any]:
        path = os.path.join(self._step_dir(step), MANIFEST_NAME)
        if not os.path.exists(path):
            raise CheckpointCorrupt(
                f"published checkpoint step-{step} has no {MANIFEST_NAME}: "
                "the publish protocol writes it before the rename, so this "
                "directory was tampered with — refusing to restore"
            )
        with open(path, encoding="utf-8") as f:
            try:
                manifest = json.load(f)
            except ValueError as e:
                # json.JSONDecodeError — a bit flip or truncation INSIDE
                # the manifest is the same corruption class as one inside
                # a shard: reject as corrupt so latest_good_step/_gc fall
                # back to an older verified step instead of crashing
                raise CheckpointCorrupt(
                    f"step-{step} {MANIFEST_NAME} is not valid JSON "
                    f"({e}) — manifest corrupt, refusing to restore"
                ) from e
        if not isinstance(manifest, dict):
            raise CheckpointCorrupt(
                f"step-{step} {MANIFEST_NAME} holds "
                f"{type(manifest).__name__}, expected an object"
            )
        if manifest.get("version") != MANIFEST_VERSION:
            raise CheckpointCorrupt(
                f"step-{step} manifest version {manifest.get('version')!r} "
                f"!= {MANIFEST_VERSION}"
            )
        shards = manifest.get("shards")
        if not isinstance(shards, dict) or not all(
            isinstance(rec, dict) and "bytes" in rec and "sha256" in rec
            for rec in shards.values()
        ):
            raise CheckpointCorrupt(
                f"step-{step} manifest shard table is malformed — "
                "manifest corrupt, refusing to restore"
            )
        return manifest

    def verify(self, step: int) -> None:
        """Raise :class:`CheckpointCorrupt` unless every shard the
        manifest names exists with the recorded size and sha256."""
        manifest = self._manifest(step)
        d = self._step_dir(step)
        for name, rec in sorted(manifest["shards"].items()):
            path = os.path.join(d, name)
            if not os.path.exists(path):
                raise CheckpointCorrupt(
                    f"step-{step} manifest names shard {name!r} but the "
                    "file is missing — refusing to restore a partial "
                    "checkpoint"
                )
            blob = open(path, "rb").read()
            if len(blob) != int(rec["bytes"]):
                raise CheckpointCorrupt(
                    f"step-{step} shard {name!r} is {len(blob)} bytes, "
                    f"manifest records {rec['bytes']} — truncated or torn, "
                    "refusing to restore"
                )
            digest = hashlib.sha256(blob).hexdigest()
            if digest != rec["sha256"]:
                raise CheckpointCorrupt(
                    f"step-{step} shard {name!r} sha256 {digest[:12]}… != "
                    f"manifest {rec['sha256'][:12]}… — payload corrupt, "
                    "refusing to restore"
                )

    def _verify_quiet(self, step: int) -> bool:
        try:
            self.verify(step)
            return True
        except CheckpointCorrupt:
            return False

    def latest_good_step(self) -> Optional[int]:
        """Newest published step that passes verification — what a
        restart restores from when the newest directory is damaged."""
        for step in reversed(self.published_steps()):
            if self._verify_quiet(step):
                return step
        return None

    # -- save pipeline ---------------------------------------------------------

    def _publish(self, step: int, blobs: Dict[str, bytes]) -> None:
        final = self._step_dir(step)
        if os.path.exists(final):
            if self._verify_quiet(step):
                raise ValueError(
                    f"checkpoint step-{step} already published; steps are "
                    "immutable once committed (save under a new step "
                    "instead)"
                )
            # a resume that restored latest_good_step() re-runs the steps
            # a newer CORRUPT directory covers — replacing the damaged
            # artifact is the recovery, not a mutation of committed state
            print(
                f"[adapcc] checkpoint step-{step} exists but fails "
                "verification; replacing the corrupt artifact",
                file=sys.stderr,
                flush=True,
            )
            shutil.rmtree(final, ignore_errors=True)
        tmp = os.path.join(
            self.directory, f".tmp-step-{int(step)}-{os.getpid()}"
        )
        os.makedirs(tmp, exist_ok=True)
        shards = {}
        for name, blob in sorted(blobs.items()):
            path = os.path.join(tmp, name)
            with open(path, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            shards[name] = {
                "bytes": len(blob),
                "sha256": hashlib.sha256(blob).hexdigest(),
            }
        manifest = {
            "version": MANIFEST_VERSION,
            "step": int(step),
            "shards": shards,
        }
        mpath = os.path.join(tmp, MANIFEST_NAME)
        with open(mpath, "w", encoding="utf-8") as f:
            json.dump(manifest, f, indent=2, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, final)
        _fsync_dir(self.directory)
        self._gc(trusted=int(step))

    def _gc(self, trusted: Optional[int] = None) -> None:
        """Keep-last-good retention (class doc): rank by verification at
        GC time; the newest ``max_to_keep`` *good* steps survive, corrupt
        directories are collected with a loud stderr note.

        Older retained steps are re-hashed on every pass ON PURPOSE — the
        corruption this retention policy defends against (bit rot, a
        sibling process truncating a blob) happens AFTER publish, so a
        cached verified flag would keep a silently-damaged newest step
        and evict the good one under it (the retention regression test
        pins exactly this).  Only ``trusted`` — the step this very call
        just published, whose checksums were computed from the in-memory
        bytes — skips the redundant immediate re-read."""
        published = self.published_steps()
        good = [
            s
            for s in published
            if s == trusted or self._verify_quiet(s)
        ]
        keep = set(good[-self.max_to_keep :])
        for step in published:
            if step in keep:
                continue
            if step not in good:
                print(
                    f"[adapcc] checkpoint step-{step} failed verification; "
                    "collecting the corrupt artifact (the newest GOOD "
                    "checkpoint is retained regardless)",
                    file=sys.stderr,
                    flush=True,
                )
            shutil.rmtree(self._step_dir(step), ignore_errors=True)
        _fsync_dir(self.directory)

    def _raise_pending(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError(
                "async checkpoint pipeline failed on a previous save; the "
                "checkpoint it was writing does NOT exist on disk"
            ) from err

    def save(self, step: int, state: TrainCheckpointState) -> None:
        """Synchronous save: serialize → checksum → publish, durable on
        return."""
        self.wait()
        self._publish(int(step), {"state.msgpack": state.to_bytes()})

    def save_async(self, step: int, state: TrainCheckpointState) -> None:
        """Queue one save on the background pipeline and return
        immediately.

        The snapshot is taken HERE, on the caller's thread: every device
        array is materialized to host memory before this returns, so the
        snapshot stays valid even when the training loop's jitted step
        DONATES the state's buffers an instant later (reference-capture
        alone would hand the background thread arrays the next step
        deletes — the "Array has been deleted" crash).  The D2H copy is
        the snapshot; serialization, checksumming, and the atomic publish
        run off-thread, so the loop never stalls on checkpoint I/O.
        """
        self._raise_pending()
        snapshot = jax.tree_util.tree_map(
            lambda leaf: jax.device_get(leaf)
            if isinstance(leaf, jax.Array)
            else leaf,
            state.capture_snapshot(),
        )
        with self._spawn_lock:
            self._idle.clear()
            self._queue.put((int(step), snapshot))
            if self._worker is None:
                self._worker = threading.Thread(
                    target=self._drain, name="adapcc-async-ckpt", daemon=True
                )
                self._worker.start()

    def _drain(self) -> None:
        while True:
            with self._spawn_lock:
                try:
                    item = self._queue.get_nowait()
                except queue.Empty:
                    # the exit decision and save_async's put+spawn hold the
                    # same lock, so an enqueue can never slip between "saw
                    # empty" and "worker gone"
                    self._worker = None
                    self._idle.set()
                    return
            step, snapshot = item
            try:
                self._publish(
                    step,
                    {"state.msgpack": serialization.to_bytes(snapshot)},
                )
            except BaseException as e:  # noqa: BLE001 — surfaced at next call
                with self._spawn_lock:
                    self._error = e
                    # drop the rest of the queue: later saves would publish
                    # out of order around the failure
                    while True:
                        try:
                            self._queue.get_nowait()
                        except queue.Empty:
                            break
                    self._worker = None
                    self._idle.set()
                    return

    def wait(self, timeout: Optional[float] = None) -> None:
        """Block until every queued async save has published (or failed
        loudly)."""
        if not self._idle.wait(timeout):
            raise TimeoutError(
                f"async checkpoint pipeline still busy after {timeout}s"
            )
        self._raise_pending()

    # -- restore ---------------------------------------------------------------

    def restore(
        self, state: TrainCheckpointState, step: Optional[int] = None
    ) -> bool:
        """Verified restore into ``state``.  ``step=None`` restores the
        newest published step — and fails loudly if that step is corrupt
        (use :meth:`latest_good_step` to fall back deliberately; silent
        fallback would mask the corruption).  Returns False only when no
        checkpoint exists at all."""
        self.wait()
        if step is None:
            step = self.latest_step()
            if step is None:
                return False
        elif not os.path.exists(self._step_dir(step)):
            raise FileNotFoundError(
                f"no published checkpoint step-{step} in {self.directory}"
            )
        self.verify(step)
        blob = open(
            os.path.join(self._step_dir(step), "state.msgpack"), "rb"
        ).read()
        state.load_bytes(blob)
        self.restores += 1
        return True

    def close(self) -> None:
        self.wait()


# --- elastic supervisor (torchrun-elastic analog) ------------------------------


def run_elastic(
    argv: Sequence[str],
    max_restarts: int = 3,
    restart_delay_s: float = 1.0,
    env: Optional[Dict[str, str]] = None,
    _spawn: Optional[Callable] = None,
) -> int:
    """Supervise a worker command, restarting on failure up to ``max_restarts``
    times — the reference's ``torchrun --max_restarts=3`` elastic launch
    (launch_elastic.sh:1-12).  Each generation gets ``ADAPCC_RESTART_GEN`` so
    rendezvous keys never collide across restarts; workers resume from their
    checkpoints via :func:`restore_newest_across_processes`.
    """
    spawn = _spawn or (lambda cmd, env: subprocess.run(cmd, env=env).returncode)
    for gen in range(max_restarts + 1):
        child_env = {**os.environ, **(env or {}), "ADAPCC_RESTART_GEN": str(gen)}
        rc = spawn(list(argv), child_env)
        if rc == 0:
            return 0
        if gen < max_restarts:
            print(f"=> worker failed (rc={rc}); restart {gen + 1}/{max_restarts}")
            time.sleep(restart_delay_s)
    return rc
