"""Checkpoint / resume + elastic recovery.

The reference's checkpoint story lives in the elastic imagenet workload
(models/image-classification/main_elastic.py): a mutable ``State`` with
``capture_snapshot``/``apply_snapshot``, atomic save via tmp-file+rename
(main_elastic.py:395-410), and — because vanilla hosts have no shared fs — a
rendezvous-time broadcast of the newest checkpoint from the rank with the
largest epoch (main_elastic.py:306-385).

TPU-native shape: pytrees serialize with flax msgpack (no pickle), the
step-directory manager is orbax (async-capable, the JAX-ecosystem standard),
and the cross-process "broadcast from the freshest rank" rides the
jax.distributed coordinator KV store instead of a temporary gloo process
group.
"""

from __future__ import annotations

import base64
import os
import shutil
import subprocess
import sys
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Sequence

import jax
from flax import serialization


# --- snapshot container (reference State, main_elastic.py:188-237) ------------

#: ``extra`` keys that describe the *layout* of the stored tensors: when the
#: in-memory state declares one (e.g. Zero1Optimizer.checkpoint_extra's
#: "zero1_layout"), a loaded snapshot must match it exactly — restoring a
#: chunk-permuted master under a flipped layout must fail loudly, not load
LAYOUT_GUARD_KEYS = ("zero1_layout",)


@dataclass
class TrainCheckpointState:
    """Everything a worker needs to resume: mirrors the reference ``State``
    (epoch, best metric, model + optimizer state), as a jax pytree."""

    params: Any
    opt_state: Any = None
    epoch: int = -1
    step: int = 0
    best_metric: float = 0.0
    extra: Dict[str, Any] = field(default_factory=dict)

    def capture_snapshot(self) -> Dict[str, Any]:
        """Serialize-ready dict; ``apply_snapshot`` is its inverse."""
        return {
            "epoch": self.epoch,
            "step": self.step,
            "best_metric": self.best_metric,
            "params": self.params,
            "opt_state": self.opt_state,
            "extra": self.extra,
        }

    def apply_snapshot(self, obj: Dict[str, Any]) -> None:
        """Mutates this state from a snapshot (reference apply_snapshot).

        Layout-guard keys declared by the in-memory ``extra`` are enforced
        against the snapshot before anything mutates: every load funnel
        (load_checkpoint, CheckpointManager.restore, the elastic rendezvous
        broadcast) routes through here, so a resume whose optimizer layout
        (ring/world/align) differs from what was saved raises instead of
        silently loading permuted tensors.
        """
        self._enforce_layout_guard(obj.get("extra"))
        self.epoch = int(obj["epoch"])
        self.step = int(obj["step"])
        self.best_metric = float(obj["best_metric"])
        self.params = obj["params"]
        self.opt_state = obj["opt_state"]
        self.extra = dict(obj.get("extra", {}))

    def _enforce_layout_guard(self, incoming_extra: Any) -> None:
        incoming = dict(incoming_extra or {})
        for key in LAYOUT_GUARD_KEYS:
            expected = (self.extra or {}).get(key)
            if expected is not None and incoming.get(key) != expected:
                raise ValueError(
                    f"checkpoint layout mismatch on extra[{key!r}]: "
                    f"saved={incoming.get(key)!r} vs resuming="
                    f"{expected!r}; restoring would load permuted tensors "
                    "— resume with the matching configuration or re-shard "
                    "offline"
                )
            if (
                expected is None
                and incoming.get(key) is not None
                and self.opt_state is not None
            ):
                # the checkpoint's optimizer state was saved under a sharded
                # layout this resume never declared: restoring it blind is
                # the silent chunk-permutation hazard the tag exists to
                # close.  Params-only loads (opt_state=None templates, e.g.
                # inference) are unaffected — params are not permuted.
                raise ValueError(
                    f"checkpoint carries a layout tag extra[{key!r}] but "
                    "this resume declares none; stamp the resuming state's "
                    "extra (DDPTrainer.checkpoint_extra() / "
                    "Zero1Optimizer.checkpoint_extra()) so the layout can "
                    "be verified, or load with opt_state=None for "
                    "params-only use"
                )

    def to_bytes(self) -> bytes:
        return serialization.to_bytes(self.capture_snapshot())

    def load_bytes(self, blob: bytes) -> None:
        template = self.capture_snapshot()
        # decode once, then guard on the RAW extra before flax template
        # matching (from_bytes is msgpack_restore + from_state_dict).  The
        # raw peek is load-bearing in both guard directions: a declaring
        # state resuming an untagged legacy blob must get the guard's
        # actionable message (not flax's raw key-mismatch), and a tagged
        # blob restored into an undeclared optimizer-carrying state must
        # refuse — from_state_dict silently DROPS unknown extra keys, so
        # apply_snapshot alone would never see the tag
        raw = serialization.msgpack_restore(blob)
        self._enforce_layout_guard(
            raw.get("extra") if isinstance(raw, dict) else None
        )
        self.apply_snapshot(serialization.from_state_dict(template, raw))


# --- single-file atomic checkpoints (main_elastic.py:395-410) -----------------


def save_checkpoint(
    state: TrainCheckpointState, filename: str, is_best: bool = False
) -> None:
    """Atomic save: write tmp, then rename-commit, so an interrupt mid-write
    never corrupts the live checkpoint; ``is_best`` keeps a ``model_best``
    copy beside it (both reference behaviors)."""
    checkpoint_dir = os.path.dirname(filename) or "."
    os.makedirs(checkpoint_dir, exist_ok=True)
    # pid-suffixed tmp: concurrent savers on a shared fs each write their own
    # tmp and the (content-identical) renames commit atomically, never torn
    tmp_filename = f"{filename}.tmp.{os.getpid()}"
    with open(tmp_filename, "wb") as f:
        f.write(state.to_bytes())
    os.rename(tmp_filename, filename)
    if is_best:
        best = os.path.join(checkpoint_dir, "model_best.ckpt")
        best_tmp = f"{best}.tmp.{os.getpid()}"
        shutil.copyfile(filename, best_tmp)
        os.rename(best_tmp, best)


def load_checkpoint(state: TrainCheckpointState, filename: str) -> bool:
    """Load into ``state`` if the file exists; returns whether it did."""
    if not os.path.isfile(filename):
        return False
    with open(filename, "rb") as f:
        state.load_bytes(f.read())
    return True


# --- newest-checkpoint rendezvous broadcast (main_elastic.py:306-385) ---------

#: base64 chars per KV-store blob chunk (~2 MB < the ~4 MB gRPC message cap)
_BLOB_CHUNK_CHARS = 2 * 1024 * 1024


def restore_newest_across_processes(
    state: TrainCheckpointState, filename: str, timeout_ms: int = 120_000
) -> TrainCheckpointState:
    """Elastic-restart restore: load the local checkpoint (if any), then adopt
    the freshest one any process holds.

    Single-process: plain local load.  Multi-process: every process publishes
    its epoch to the coordinator KV store; the holder of the max epoch
    publishes the snapshot blob and everyone else applies it — the KV-store
    analog of the reference's gloo max-epoch broadcast.  Restart generations
    are keyed by ``ADAPCC_RESTART_GEN`` (set by the elastic supervisor) so a
    relaunched world never reads the previous generation's keys.
    """
    load_checkpoint(state, filename)
    if jax.process_count() <= 1:
        return state

    from adapcc_tpu.launch.dispatcher import fetch_value, publish_value

    gen = os.environ.get("ADAPCC_RESTART_GEN", "0")
    me = jax.process_index()
    n = jax.process_count()
    prefix = f"adapcc/elastic/g{gen}"

    publish_value(f"{prefix}/epoch/{me}", str(state.epoch))
    with ThreadPoolExecutor(max_workers=min(32, n)) as pool:
        epochs = list(
            pool.map(
                lambda p: int(fetch_value(f"{prefix}/epoch/{p}", timeout_ms)), range(n)
            )
        )
    max_epoch = max(epochs)
    if max_epoch < 0:
        return state  # nobody has a checkpoint: fresh start everywhere
    max_rank = epochs.index(max_epoch)

    # ranks already at max_epoch (shared-fs steady state: all of them) need no
    # blob; the holder publishes only if someone is actually behind.  The blob
    # is chunked: the KV store carries values over gRPC, whose message cap a
    # single whole-checkpoint string would blow past on any real model.
    if me == max_rank and min(epochs) < max_epoch:
        encoded = base64.b64encode(state.to_bytes()).decode()
        chunks = [
            encoded[i : i + _BLOB_CHUNK_CHARS]
            for i in range(0, len(encoded), _BLOB_CHUNK_CHARS)
        ] or [""]
        publish_value(f"{prefix}/blob/count", str(len(chunks)))
        for i, chunk in enumerate(chunks):
            publish_value(f"{prefix}/blob/{i}", chunk)
    elif state.epoch < max_epoch:
        count = int(fetch_value(f"{prefix}/blob/count", timeout_ms))
        encoded = "".join(
            fetch_value(f"{prefix}/blob/{i}", timeout_ms) for i in range(count)
        )
        state.load_bytes(base64.b64decode(encoded))
    return state


# --- orbax step-directory manager ---------------------------------------------


class CheckpointManager:
    """Directory-of-steps manager over orbax: ``save(step, state)``,
    ``latest_step()``, ``restore(state, step=None)``, bounded retention.

    This is the shared-fs path the reference's note recommends when "globally
    visible persistent storage" exists (main_elastic.py load_checkpoint
    docstring); on TPU pods that is the norm, so orbax is the primary story
    and the KV broadcast above is the no-shared-fs fallback.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def save(self, step: int, state: TrainCheckpointState) -> None:
        self._mgr.save(step, args=self._ocp.args.StandardSave(state.capture_snapshot()))
        self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, state: TrainCheckpointState, step: Optional[int] = None) -> bool:
        step = step if step is not None else self.latest_step()
        if step is None:
            return False
        restored = self._mgr.restore(
            step, args=self._ocp.args.StandardRestore(state.capture_snapshot())
        )
        state.apply_snapshot(restored)
        return True

    # -- sharded (FSDP/ZeRO) pytrees ------------------------------------------

    def save_sharded(self, step: int, tree: Any) -> None:
        """Save a pytree of (possibly sharded) ``jax.Array``s.

        Each process writes only its addressable shards — FSDP/ZeRO state
        checkpoints at 1/world of the HBM and never materializes the full
        parameter on any host, unlike the msgpack path above (which is for
        small replicated state).
        """
        self._mgr.save(step, args=self._ocp.args.StandardSave(tree))
        self._mgr.wait_until_finished()

    def restore_sharded(self, target: Any, step: Optional[int] = None) -> Any:
        """Restore into ``target``'s layout: a pytree of arrays (their
        shardings are reused) or ``jax.ShapeDtypeStruct``s with shardings.
        Returns the restored tree, sharded as the target prescribes —
        restore-time resharding (e.g. onto a different world size) is
        orbax's job, not a host gather."""
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint step in {self.directory}")
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if isinstance(x, jax.Array)
            else x,
            target,
        )
        return self._mgr.restore(step, args=self._ocp.args.StandardRestore(abstract))

    def close(self) -> None:
        self._mgr.close()


# --- elastic supervisor (torchrun-elastic analog) ------------------------------


def run_elastic(
    argv: Sequence[str],
    max_restarts: int = 3,
    restart_delay_s: float = 1.0,
    env: Optional[Dict[str, str]] = None,
    _spawn: Optional[Callable] = None,
) -> int:
    """Supervise a worker command, restarting on failure up to ``max_restarts``
    times — the reference's ``torchrun --max_restarts=3`` elastic launch
    (launch_elastic.sh:1-12).  Each generation gets ``ADAPCC_RESTART_GEN`` so
    rendezvous keys never collide across restarts; workers resume from their
    checkpoints via :func:`restore_newest_across_processes`.
    """
    spawn = _spawn or (lambda cmd, env: subprocess.run(cmd, env=env).returncode)
    for gen in range(max_restarts + 1):
        child_env = {**os.environ, **(env or {}), "ADAPCC_RESTART_GEN": str(gen)}
        rc = spawn(list(argv), child_env)
        if rc == 0:
            return 0
        if gen < max_restarts:
            print(f"=> worker failed (rc={rc}); restart {gen + 1}/{max_restarts}")
            time.sleep(restart_delay_s)
    return rc
