"""Hand-tuned kernels for the hot ops: Pallas flash attention, chunked
(online-softmax) vocab cross-entropy."""

from adapcc_tpu.ops.flash_attention import flash_attention, flash_attention_with_lse
from adapcc_tpu.ops.chunked_ce import (
    chunked_lm_loss,
    chunked_softmax_xent,
    chunked_softmax_xent_shard,
)

__all__ = [
    "flash_attention",
    "flash_attention_with_lse",
    "chunked_lm_loss",
    "chunked_softmax_xent",
    "chunked_softmax_xent_shard",
]
