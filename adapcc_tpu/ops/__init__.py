"""Hand-tuned Pallas TPU kernels for the hot ops."""

from adapcc_tpu.ops.flash_attention import flash_attention, flash_attention_with_lse

__all__ = ["flash_attention", "flash_attention_with_lse"]
