"""Blockwise (flash) causal attention as a Pallas TPU kernel.

The reference's models materialize the full ``[T, T]`` attention matrix in
HBM (models/gpt2 via HuggingFace; our XLA path in models/gpt2.py:99-105 does
the same under fusion).  On TPU the attention matmuls belong on the MXU with
the softmax streamed through VMEM: this kernel computes attention in
``[block_q, block_k]`` tiles with the online-softmax recurrence, never
materializing ``[T, T]``, and recomputes the tiles in the backward pass from
the saved logsumexp — O(T) memory in sequence length.

Forward, per query block i (running max ``m``, normalizer ``l``):

    s_ij   = q_i k_j^T · scale                 (MXU, fp32 accumulate)
    m'     = max(m, rowmax(s_ij))
    p_ij   = exp(s_ij − m')
    l      = l·exp(m − m') + rowsum(p_ij)
    acc    = acc·exp(m − m') + p_ij v_j
    o_i    = acc / l ;  lse_i = m + log l      (saved for backward)

Backward runs two kernels (no atomics needed — each grid program owns its
output block exclusively): a dq pass gridded over query blocks and a dk/dv
pass gridded over key blocks, both rebuilding ``p_ij = exp(s_ij − lse_i)``
from the residuals with ``Δ_i = rowsum(do_i ∘ o_i)``.

Used by the GPT-2 flagship model when ``GPT2Config.attention == "flash"``;
long-context cross-chip attention composes this with the ring/Ulysses
sequence parallelism in :mod:`adapcc_tpu.parallel` (each device runs this
kernel on its local K/V shard).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30

# Mosaic requires the last two dims of every block shape to be divisible by
# the (8, 128) tile or equal to the whole array's dims.  A naive ``[BH, T]``
# logsumexp output with block ``(1, bq)`` violates the sublane rule (the 1),
# so lse/delta cross every pallas_call boundary lane-padded to
# ``[BH, T, _LSE_LANES]`` (block ``(1, bq, 8)``: bq % 8 == 0, 8 == minor dim)
# and are sliced back to ``[BH, T]`` outside the kernels.
_LSE_LANES = 8


def _causal_mask(s, qi, kj, block_q, block_k):
    q_pos = qi * block_q + lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = kj * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(k_pos <= q_pos, s, _NEG_INF)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal, block_q, block_k):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    bq, d = q.shape
    n_k = k_ref.shape[1] // block_k

    m = jnp.full((bq,), _NEG_INF, jnp.float32)
    l = jnp.zeros((bq,), jnp.float32)
    acc = jnp.zeros((bq, d), jnp.float32)
    for j in range(n_k):
        k = k_ref[0, j * block_k : (j + 1) * block_k, :].astype(jnp.float32)
        v = v_ref[0, j * block_k : (j + 1) * block_k, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            s = _causal_mask(s, qi, j, block_q, block_k)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l = l * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m = m_new

    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)
    lse_ref[0] = jnp.broadcast_to(
        (m + jnp.log(l))[:, None], (bq, _LSE_LANES)
    )


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
    *, scale, causal, block_q, block_k,
):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse_col = lse_ref[0][:, 0:1]      # [bq, 1] from the lane-padded layout
    delta_col = delta_ref[0][:, 0:1]
    bq, d = q.shape
    n_k = k_ref.shape[1] // block_k

    dq = jnp.zeros((bq, d), jnp.float32)
    for j in range(n_k):
        k = k_ref[0, j * block_k : (j + 1) * block_k, :].astype(jnp.float32)
        v = v_ref[0, j * block_k : (j + 1) * block_k, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            s = _causal_mask(s, qi, j, block_q, block_k)
        p = jnp.exp(s - lse_col)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_col) * scale
        dq = dq + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, scale, causal, block_q, block_k,
):
    kj = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    bk, d = k.shape
    n_q = q_ref.shape[1] // block_q

    dk = jnp.zeros((bk, d), jnp.float32)
    dv = jnp.zeros((bk, d), jnp.float32)
    for i in range(n_q):
        q = q_ref[0, i * block_q : (i + 1) * block_q, :].astype(jnp.float32)
        do = do_ref[0, i * block_q : (i + 1) * block_q, :].astype(jnp.float32)
        lse_col = lse_ref[0, i * block_q : (i + 1) * block_q, 0:1]
        delta_col = delta_ref[0, i * block_q : (i + 1) * block_q, 0:1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            s = _causal_mask(s, i, kj, block_q, block_k)
        p = jnp.exp(s - lse_col)
        dv = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_col) * scale
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _resolve_interpret(interpret):
    if interpret is None:
        return jax.devices()[0].platform != "tpu"
    return interpret


def _block_sizes(T: int, block_q: int, block_k: int):
    bq, bk = min(block_q, T), min(block_k, T)
    if T % bq or T % bk:
        raise ValueError(f"seq len {T} must divide into blocks ({bq}, {bk})")
    # Mosaic sublane rule: the lane-padded (1, bq, _LSE_LANES) block specs
    # require 8-aligned block sizes (or the degenerate bq == T case).  An
    # unaligned block compiles past tracing and dies deep in Mosaic with a
    # cryptic tiling error on hardware — reject it here with the real reason.
    for name, b in (("block_q", bq), ("block_k", bk)):
        if b % 8 and b != T:
            raise ValueError(
                f"{name}={b} must be a multiple of 8 (Mosaic sublane "
                f"alignment) or equal to the sequence length {T}"
            )
    return bq, bk


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def _flash_bhtd(q, k, v, scale, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    BH, T, D = q.shape
    bq, bk = _block_sizes(T, block_q, block_k)
    grid = (BH, T // bq)
    out, lse3 = pl.pallas_call(
        functools.partial(
            _fwd_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, T, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, _LSE_LANES), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, _LSE_LANES), jnp.float32),
        ],
        interpret=_resolve_interpret(interpret),
    )(q, k, v)
    lse = lse3[:, :, 0]
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, do):
    return _flash_bwd_core(scale, causal, block_q, block_k, interpret, res, do, None)


def _flash_bwd_core(scale, causal, block_q, block_k, interpret, res, do, dlse):
    """Shared backward.  An ``lse`` cotangent adds ``dS_ij += p_ij·dlse_i``,
    which folds into the existing kernels as ``delta → delta − dlse`` (the
    bracket is ``p·(dp − delta)``) — no kernel change needed."""
    q, k, v, out, lse = res
    BH, T, D = q.shape
    bq, bk = _block_sizes(T, block_q, block_k)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)
    interp = _resolve_interpret(interpret)
    # lane-pad the per-row statistics for the kernels' tiled block specs
    lse3 = jnp.broadcast_to(lse[..., None], (BH, T, _LSE_LANES))
    delta3 = jnp.broadcast_to(delta[..., None], (BH, T, _LSE_LANES))

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk
        ),
        grid=(BH, T // bq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, _LSE_LANES), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, _LSE_LANES), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        interpret=interp,
    )(q, k, v, do, lse3, delta3)

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk
        ),
        grid=(BH, T // bk),
        in_specs=[
            pl.BlockSpec((1, T, D), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, T, D), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, T, _LSE_LANES), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, T, _LSE_LANES), lambda b, j: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), k.dtype),
            jax.ShapeDtypeStruct((BH, T, D), v.dtype),
        ],
        interpret=interp,
    )(q, k, v, do, lse3, delta3)
    return dq, dk, dv


_flash_bhtd.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_bhtd_lse(q, k, v, scale, causal, block_q, block_k, interpret):
    """Like :func:`_flash_bhtd` but also returns the per-row logsumexp —
    the merge statistic blockwise consumers (ring attention) need.  Both
    outputs are differentiable: the ``lse`` cotangent lowers to the same
    backward kernels via ``delta − dlse``."""
    out, (_, _, _, _, lse) = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return out, lse


def _flash_fwd_lse(q, k, v, scale, causal, block_q, block_k, interpret):
    out, res = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return (out, res[4]), res


def _flash_bwd_lse(scale, causal, block_q, block_k, interpret, res, cts):
    do, dlse = cts
    return _flash_bwd_core(scale, causal, block_q, block_k, interpret, res, do, dlse)


_flash_bhtd_lse.defvjp(_flash_fwd_lse, _flash_bwd_lse)


def _bthd_call(kernel_entry, q, k, v, causal, scale, block_q, block_k, interpret):
    """Shared model-layout plumbing for the public wrappers: validate,
    default the scale, run ``kernel_entry`` on ``[B·H, T, D]`` tensors, and
    return its raw outputs plus the dims needed to restore the layout."""
    B, T, H, D = q.shape
    if k.shape != q.shape or v.shape != q.shape:
        raise ValueError(f"q/k/v shapes differ: {q.shape} {k.shape} {v.shape}")
    if scale is None:
        scale = float(1.0 / np.sqrt(D))
    to_bhtd = lambda x: x.transpose(0, 2, 1, 3).reshape(B * H, T, D)  # noqa: E731
    raw = kernel_entry(
        to_bhtd(q), to_bhtd(k), to_bhtd(v),
        scale, causal, block_q, block_k, interpret,
    )
    return raw, (B, T, H, D)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Blockwise attention over ``[B, T, H, D]`` tensors (model layout).

    ``interpret=None`` auto-selects the Pallas interpreter off-TPU so the
    same call works on the virtual CPU pod.  ``scale`` defaults to
    ``1/sqrt(D)``.  ``T`` must divide by the block sizes (clamped to ``T``).
    """
    out, (B, T, H, D) = _bthd_call(
        _flash_bhtd, q, k, v, causal, scale, block_q, block_k, interpret
    )
    return out.reshape(B, H, T, D).transpose(0, 2, 1, 3)


def flash_attention_with_lse(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> "tuple[jnp.ndarray, jnp.ndarray]":
    """Blockwise attention returning ``(out [B,T,H,D], lse [B,H,T])``.

    ``lse[b,h,t] = logsumexp_j(scale·q_t·k_j)`` (with the causal mask
    applied) — the statistic a blockwise consumer needs to merge partial
    attention over K/V blocks it sees one at a time (ring attention's
    log-sum-exp combine).  Fully differentiable in both outputs.
    """
    (out, lse), (B, T, H, D) = _bthd_call(
        _flash_bhtd_lse, q, k, v, causal, scale, block_q, block_k, interpret
    )
    return (
        out.reshape(B, H, T, D).transpose(0, 2, 1, 3),
        lse.reshape(B, H, T),
    )
