"""Chunked (online-softmax) cross-entropy over the vocabulary.

The LM head is the memory hog of GPT-2 training: ``logits = x @ wte.T`` is a
``[B, T, V]`` fp32 tensor (512 MB at bench sizes 16×512×16384) that the loss
reads once, the backward re-reads, and XLA materializes in HBM between the
two.  This op never forms it: the forward scans the vocabulary in blocks
maintaining the online-softmax running ``(max, sumexp)`` statistics — the
same trick flash attention plays over keys (ops/flash_attention.py), applied
over the vocab axis — and the backward recomputes each block's logits from
the residuals, so peak live memory is one ``[N, block]`` tile instead of
``[N, V]``.  Each block is still a big MXU matmul, so FLOP efficiency is
unchanged; only HBM traffic drops.

Arbitrary vocab sizes are handled by zero-padding ``w`` to a block multiple
and masking the padded columns to ``-inf`` before the softmax statistics
(their contribution is exactly zero in both passes), so a prime vocab pays
one partial block, not a degenerate block=1 scan.

``chunked_softmax_xent_shard`` is the vocab-parallel (tensor-parallel)
variant for ``shard_map`` bodies: each rank scans only its vocab shard and
the stats merge across the axis with one ``pmax`` + two ``psum``.

Reference counterpart being improved on: the reference's workloads compute
full-vocab HF GPT-2 logits and torch CE over them (models/gpt2/
train_gpt2_ddp.py loss path); there is no memory-efficient variant there.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def _block_logits(x, w_blk, off, V, compute_dtype):
    """One vocab block's logits ``[N, C]`` in fp32, padded columns (local
    index >= V) forced to ``-inf`` so they vanish from softmax statistics."""
    logits = (
        x.astype(compute_dtype) @ w_blk.T.astype(compute_dtype)
    ).astype(jnp.float32)
    C = logits.shape[-1]
    valid = (off + jnp.arange(C)) < V  # [C]
    return jnp.where(valid[None, :], logits, -jnp.inf)


def _pad_blocks(w, block):
    V, D = w.shape
    pad = (-V) % block
    if pad:
        w = jnp.concatenate([w, jnp.zeros((pad, D), w.dtype)])
    return w.reshape((V + pad) // block, block, D), V


def _stats_scan(x, w, y_local, block, compute_dtype):
    """The shared online-softmax core: scan ``w``'s blocks accumulating
    running ``(max, sumexp@max, target-logit)`` over rows of ``x``.

    ``y_local`` is the target id in this weight matrix's local index space;
    ids outside ``[0, V)`` (another shard's target, or the zero-pad tail)
    contribute nothing to the target accumulator.
    """
    N = x.shape[0]
    w_blocks, V = _pad_blocks(w, block)
    offs = jnp.arange(w_blocks.shape[0]) * block
    # a pad-tail id passes the per-block range test but its logit is -inf;
    # the ownership guard keeps it (and other shards' targets) out of t
    y_mine = (y_local >= 0) & (y_local < V)

    def body(carry, inp):
        m, s, t = carry
        w_blk, off = inp
        logits = _block_logits(x, w_blk, off, V, compute_dtype)
        C = logits.shape[-1]
        m_b = jnp.max(logits, axis=-1)
        s_b = jnp.sum(jnp.exp(logits - m_b[:, None]), axis=-1)
        m_new = jnp.maximum(m, m_b)
        s = s * jnp.exp(m - m_new) + s_b * jnp.exp(m_b - m_new)
        yb = y_local - off
        in_blk = (yb >= 0) & (yb < C) & y_mine
        t_b = jnp.take_along_axis(logits, jnp.clip(yb, 0, C - 1)[:, None], axis=-1)[:, 0]
        t = t + jnp.where(in_blk, t_b, 0.0)
        return (m_new, s, t), None

    init = (
        jnp.full((N,), -jnp.inf, jnp.float32),
        jnp.zeros((N,), jnp.float32),
        jnp.zeros((N,), jnp.float32),
    )
    carry, _ = lax.scan(body, init, (w_blocks, offs))
    return carry  # (m, s, t), each [N]


def _bwd_scan(x, w, y_local, lse, scale, block, compute_dtype):
    """The shared backward core: recompute each block's logits against the
    (global) ``lse``, form ``dlogits = (softmax - onehot)·scale``, and
    accumulate ``dx`` (local, un-psum'd) and per-block ``dw``."""
    N, D = x.shape
    w_blocks, V = _pad_blocks(w, block)
    offs = jnp.arange(w_blocks.shape[0]) * block
    y_mine = (y_local >= 0) & (y_local < V)

    def body(dx, inp):
        w_blk, off = inp
        logits = _block_logits(x, w_blk, off, V, compute_dtype)
        p = jnp.exp(logits - lse[:, None])  # softmax columns; 0 at pads
        yb = y_local - off
        onehot = (
            (yb[:, None] == jnp.arange(logits.shape[-1])[None, :])
            & y_mine[:, None]
        ).astype(jnp.float32)
        dl = ((p - onehot) * scale).astype(compute_dtype)
        dx = dx + (dl @ w_blk.astype(compute_dtype)).astype(jnp.float32)
        dw_blk = (dl.T @ x.astype(compute_dtype)).astype(jnp.float32)
        return dx, dw_blk

    dx, dw_blocks = lax.scan(body, jnp.zeros((N, D), jnp.float32), (w_blocks, offs))
    return dx, dw_blocks.reshape(-1, D)[:V]


# -- single-device (or GSPMD-replicated) variants ------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def chunked_softmax_nll(
    x: jnp.ndarray,
    w: jnp.ndarray,
    y: jnp.ndarray,
    block: int = 1024,
    compute_dtype: jnp.dtype = jnp.bfloat16,
) -> jnp.ndarray:
    """Per-row negative log-likelihood ``[N]`` of ``softmax(x @ w.T)`` at
    labels ``y`` — the composable core: masking, weighting, and sharded
    reductions (e.g. the SP boundary mask) happen outside in plain JAX,
    with per-row cotangents flowing back through the block scan.
    """
    nll, _ = _fwd_nll(x, w, y, block, compute_dtype)
    return nll


def _fwd_nll(x, w, y, block, compute_dtype):
    m, s, t = _stats_scan(x, w, y, block, compute_dtype)
    lse = jnp.log(s) + m
    return lse - t, lse


def _nll_vjp_fwd(x, w, y, block, compute_dtype):
    nll, lse = _fwd_nll(x, w, y, block, compute_dtype)
    return nll, (x, w, y, lse)


def _nll_vjp_bwd(block, compute_dtype, res, g):
    x, w, y, lse = res
    # g [N]: per-row cotangent — d nll_n / d logits_nc = softmax_nc - onehot_nc
    dx, dw = _bwd_scan(x, w, y, lse, g[:, None], block, compute_dtype)
    return dx.astype(x.dtype), dw.astype(w.dtype), None


chunked_softmax_nll.defvjp(_nll_vjp_fwd, _nll_vjp_bwd)


def chunked_softmax_xent(
    x: jnp.ndarray,
    w: jnp.ndarray,
    y: jnp.ndarray,
    block: int = 1024,
    compute_dtype: jnp.dtype = jnp.bfloat16,
) -> jnp.ndarray:
    """Mean cross-entropy of ``softmax(x @ w.T)`` against labels ``y``.

    ``x [N, D]`` activations, ``w [V, D]`` (the tied embedding), ``y [N]``
    int labels.  Equivalent to ``-mean(log_softmax(x @ w.T)[n, y[n]])`` with
    the matmul in ``compute_dtype`` and softmax statistics in fp32, never
    materializing more than one ``[N, block]`` logit tile.  Any ``V`` works;
    a non-multiple pays one zero-padded block.
    """
    return jnp.mean(chunked_softmax_nll(x, w, y, block, compute_dtype))


# -- vocab-parallel (tensor-parallel) variant ----------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def chunked_softmax_xent_shard(
    x: jnp.ndarray,
    w_shard: jnp.ndarray,
    y: jnp.ndarray,
    axis_name: str,
    block: int = 1024,
    compute_dtype: jnp.dtype = jnp.bfloat16,
) -> jnp.ndarray:
    """Vocab-parallel chunked cross-entropy, for use inside ``shard_map``.

    The TP composition of :func:`chunked_softmax_xent`: ``w_shard
    [V/world, D]`` is this rank's contiguous vocab rows (the Megatron
    ``wte: P(model, None)`` layout), ``x [N, D]`` and ``y [N]`` (global
    ids) replicated across the axis.  Each rank scans only its shard; the
    online-softmax ``(max, sumexp)`` stats and the target logit then merge
    with one ``pmax`` + two ``psum`` of ``[N]`` vectors — vocabulary,
    logits, and ``dw`` never leave their shard; only ``dx`` needs a psum in
    the backward.  Returns the replicated global-softmax loss.
    """
    loss, _ = _shard_fwd(x, w_shard, y, axis_name, block, compute_dtype)
    return loss


def _shard_fwd(x, w_shard, y, axis_name, block, compute_dtype):
    me = lax.axis_index(axis_name)
    y_local = y - me * w_shard.shape[0]  # this shard's view of the target ids
    m_r, s_r, t_r = _stats_scan(x, w_shard, y_local, block, compute_dtype)
    m = lax.pmax(m_r, axis_name)
    # a rank can't be all-empty (V_local >= 1), so m_r > -inf and the
    # rescale below is well-defined
    s = lax.psum(s_r * jnp.exp(m_r - m), axis_name)
    t = lax.psum(t_r, axis_name)
    lse = jnp.log(s) + m
    return jnp.mean(lse - t), lse


def _shard_vjp_fwd(x, w_shard, y, axis_name, block, compute_dtype):
    loss, lse = _shard_fwd(x, w_shard, y, axis_name, block, compute_dtype)
    return loss, (x, w_shard, y, lse)


def _shard_vjp_bwd(axis_name, block, compute_dtype, res, g):
    x, w_shard, y, lse = res
    me = lax.axis_index(axis_name)
    y_local = y - me * w_shard.shape[0]
    dx, dw = _bwd_scan(x, w_shard, y_local, lse, g / x.shape[0], block, compute_dtype)
    # x was replicated across the axis, so its cotangent sums the per-shard
    # contributions; dw stays local to the shard
    dx = lax.psum(dx, axis_name)
    return dx.astype(x.dtype), dw.astype(w_shard.dtype), None


chunked_softmax_xent_shard.defvjp(_shard_vjp_fwd, _shard_vjp_bwd)


def chunked_lm_loss(
    hidden: jnp.ndarray,
    wte: jnp.ndarray,
    tokens: jnp.ndarray,
    block: int = 1024,
    compute_dtype: jnp.dtype = jnp.bfloat16,
) -> jnp.ndarray:
    """Next-token LM loss from post-LayerNorm hiddens — the drop-in
    memory-efficient replacement for ``lm_loss(model.apply(...), tokens)``:
    identical math (positions ``:-1`` against targets ``1:``, weight-tied
    head in ``compute_dtype``), no ``[B, T, V]`` materialization.
    """
    B, T, D = hidden.shape
    x = hidden[:, :-1].reshape(B * (T - 1), D)
    y = tokens[:, 1:].reshape(B * (T - 1))
    return chunked_softmax_xent(x, wte, y, block, compute_dtype)
