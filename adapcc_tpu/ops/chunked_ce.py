"""Chunked (online-softmax) cross-entropy over the vocabulary.

The LM head is the memory hog of GPT-2 training: ``logits = x @ wte.T`` is a
``[B, T, V]`` fp32 tensor (512 MB at bench sizes 16×512×16384) that the loss
reads once, the backward re-reads, and XLA materializes in HBM between the
two.  This op never forms it: the forward scans the vocabulary in blocks
maintaining the online-softmax running ``(max, sumexp)`` statistics — the
same trick flash attention plays over keys (ops/flash_attention.py), applied
over the vocab axis — and the backward recomputes each block's logits from
the residuals, so peak live memory is one ``[N, block]`` tile instead of
``[N, V]``.  Each block is still a big MXU matmul, so FLOP efficiency is
unchanged; only HBM traffic drops.

Arbitrary vocab sizes are handled by zero-padding ``w`` to a block multiple
and masking the padded columns to ``-inf`` before the softmax statistics
(their contribution is exactly zero in both passes), so a prime vocab pays
one partial block, not a degenerate block=1 scan.

Reference counterpart being improved on: the reference's workloads compute
full-vocab HF GPT-2 logits and torch CE over them (models/gpt2/
train_gpt2_ddp.py loss path); there is no memory-efficient variant there.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def _block_logits(x, w_blk, off, V, compute_dtype):
    """One vocab block's logits ``[N, C]`` in fp32, padded columns (global
    index >= V) forced to ``-inf`` so they vanish from softmax statistics."""
    logits = (
        x.astype(compute_dtype) @ w_blk.T.astype(compute_dtype)
    ).astype(jnp.float32)
    C = logits.shape[-1]
    valid = (off + jnp.arange(C)) < V  # [C]
    return jnp.where(valid[None, :], logits, -jnp.inf)


def _pad_blocks(w, block):
    V, D = w.shape
    pad = (-V) % block
    if pad:
        w = jnp.concatenate([w, jnp.zeros((pad, D), w.dtype)])
    return w.reshape((V + pad) // block, block, D), V


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def chunked_softmax_xent(
    x: jnp.ndarray,
    w: jnp.ndarray,
    y: jnp.ndarray,
    block: int = 1024,
    compute_dtype: jnp.dtype = jnp.bfloat16,
) -> jnp.ndarray:
    """Mean cross-entropy of ``softmax(x @ w.T)`` against labels ``y``.

    ``x [N, D]`` activations, ``w [V, D]`` (the tied embedding), ``y [N]``
    int labels.  Equivalent to ``-mean(log_softmax(x @ w.T)[n, y[n]])`` with
    the matmul in ``compute_dtype`` and softmax statistics in fp32, never
    materializing more than one ``[N, block]`` logit tile.  Any ``V`` works;
    a non-multiple pays one zero-padded block.
    """
    loss, _ = _fwd_scan(x, w, y, block, compute_dtype)
    return loss


def _fwd_scan(x, w, y, block, compute_dtype):
    N = x.shape[0]
    w_blocks, V = _pad_blocks(w, block)
    offs = jnp.arange(w_blocks.shape[0]) * block

    def body(carry, inp):
        m, s, t = carry
        w_blk, off = inp
        logits = _block_logits(x, w_blk, off, V, compute_dtype)  # [N, C]
        C = logits.shape[-1]
        m_b = jnp.max(logits, axis=-1)  # [N]
        s_b = jnp.sum(jnp.exp(logits - m_b[:, None]), axis=-1)
        m_new = jnp.maximum(m, m_b)
        s = s * jnp.exp(m - m_new) + s_b * jnp.exp(m_b - m_new)
        # the target logit, when it falls inside this block
        y_local = y - off
        in_blk = (y_local >= 0) & (y_local < C)
        t_b = jnp.take_along_axis(
            logits, jnp.clip(y_local, 0, C - 1)[:, None], axis=-1
        )[:, 0]
        t = t + jnp.where(in_blk, t_b, 0.0)
        return (m_new, s, t), None

    init = (
        jnp.full((N,), -jnp.inf, jnp.float32),
        jnp.zeros((N,), jnp.float32),
        jnp.zeros((N,), jnp.float32),
    )
    (m, s, t), _ = lax.scan(body, init, (w_blocks, offs))
    lse = jnp.log(s) + m  # [N]
    loss = jnp.mean(lse - t)
    return loss, lse


def _vjp_fwd(x, w, y, block, compute_dtype):
    loss, lse = _fwd_scan(x, w, y, block, compute_dtype)
    return loss, (x, w, y, lse)


def _vjp_bwd(block, compute_dtype, res, g):
    x, w, y, lse = res
    N, D = x.shape
    w_blocks, V = _pad_blocks(w, block)
    offs = jnp.arange(w_blocks.shape[0]) * block
    scale = g / N  # d(mean)/d(per-row)

    def body(dx, inp):
        w_blk, off = inp
        logits = _block_logits(x, w_blk, off, V, compute_dtype)
        p = jnp.exp(logits - lse[:, None])  # block softmax [N, C]; 0 at pads
        y_local = y - off
        onehot = (
            y_local[:, None] == jnp.arange(logits.shape[-1])[None, :]
        ).astype(jnp.float32)
        dl = ((p - onehot) * scale).astype(compute_dtype)
        dx = dx + (dl @ w_blk.astype(compute_dtype)).astype(jnp.float32)
        dw_blk = (dl.T @ x.astype(compute_dtype)).astype(jnp.float32)
        return dx, dw_blk

    dx, dw_blocks = lax.scan(body, jnp.zeros((N, D), jnp.float32), (w_blocks, offs))
    dw = dw_blocks.reshape(-1, D)[:V]
    return dx.astype(x.dtype), dw.astype(w.dtype), None


chunked_softmax_xent.defvjp(_vjp_fwd, _vjp_bwd)


def chunked_lm_loss(
    hidden: jnp.ndarray,
    wte: jnp.ndarray,
    tokens: jnp.ndarray,
    block: int = 1024,
    compute_dtype: jnp.dtype = jnp.bfloat16,
) -> jnp.ndarray:
    """Next-token LM loss from post-LayerNorm hiddens — the drop-in
    memory-efficient replacement for ``lm_loss(model.apply(...), tokens)``:
    identical math (positions ``:-1`` against targets ``1:``, weight-tied
    head in ``compute_dtype``), no ``[B, T, V]`` materialization.
    """
    B, T, D = hidden.shape
    x = hidden[:, :-1].reshape(B * (T - 1), D)
    y = tokens[:, 1:].reshape(B * (T - 1))
    return chunked_softmax_xent(x, wte, y, block, compute_dtype)
