"""Flash-attention tile autotuning.

Round 4 found the best flash block empirically (256 beat 128 by ~11% on the
v5e flagship shape) via a *manual* battery A/B; the default was then pinned
statically (VERDICT r4, "What's weak" #3).  This module makes that sweep a
first-class, cached measurement: for a given (seq, d_head, dtype) it times a
short jitted forward+backward of the real kernel at each candidate tile and
returns the fastest.

Measurement methodology matters on remote-tunnel backends (PERF_NOTES):
a fresh compiled program's first TWO executions pay the executable+buffer
migration transient (~30 s each through the axon tunnel), so each candidate
runs ``warmup >= 2`` untimed executions before the timed ones, and timing is
forced-sync (``jax.device_get`` on a scalar closes the window).

Off-TPU the sweep is skipped entirely — the Pallas interpreter's timings
say nothing about Mosaic and would take minutes — and the static default
resolution is returned.
"""

from __future__ import annotations

import os
import time
from typing import Dict, Optional, Sequence, Tuple

#: measured-best static default (round-4 battery, v5e, T=512)
DEFAULT_BLOCK = 256

#: candidate tile edges swept by the autotuner
CANDIDATES = (128, 256, 512)

_cache: Dict[Tuple, Tuple[int, Dict[int, float]]] = {}


def resolve_block(seq: int, want: int) -> int:
    """Largest 8-aligned tile <= ``want`` that divides ``seq``; falls back
    to the full sequence when no aligned divisor exists."""
    b = min(max(8, want - want % 8), seq)
    while b >= 8 and seq % b:
        b -= 8
    return b if b >= 8 and seq % b == 0 else seq


def autotune_flash_block(
    seq: int,
    d_head: int = 64,
    dtype=None,
    batch: int = 2,
    heads: int = 8,
    candidates: Sequence[int] = CANDIDATES,
    warmup: int = 2,
    iters: int = 3,
    causal: bool = True,
) -> int:
    """Fastest seq-compatible flash tile for this backend, measured.

    Returns the winning block edge; the per-candidate timings are kept in
    :func:`last_timings` for artifact/bench reporting.  Results are cached
    per (platform, seq, d_head, dtype, causal, batch, heads) for the process
    lifetime — the sweep runs once per full problem shape, not once per call.
    """
    import jax
    import jax.numpy as jnp

    dtype = dtype or jnp.bfloat16
    platform = jax.devices()[0].platform
    # batch/heads are part of the key: timings depend on the full problem
    # shape, and a second call at a different batch/head count must re-sweep
    # rather than silently reuse the first shape's winner (ADVICE r5)
    key = (platform, seq, d_head, jnp.dtype(dtype).name, causal, batch, heads)
    if key in _cache:
        return _cache[key][0]

    resolved = []
    for c in candidates:
        r = resolve_block(seq, c)
        if r not in resolved:
            resolved.append(r)
    if platform != "tpu" or len(resolved) == 1:
        # interpreter timings are meaningless for Mosaic tile choice
        best = resolve_block(seq, DEFAULT_BLOCK)
        _cache[key] = (best, {})
        return best

    from adapcc_tpu.ops import flash_attention

    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(rng, (batch, seq, heads, d_head), dtype)
    timings: Dict[int, float] = {}
    for block in resolved:
        def loss(q, k, v, block=block):
            return jnp.sum(
                flash_attention(
                    q, k, v, causal=causal, block_q=block, block_k=block
                ).astype(jnp.float32)
            )

        try:
            fn = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))
            for _ in range(max(warmup, 2)):  # tunnel migration transient
                jax.block_until_ready(fn(x, x, x))
            t0 = time.perf_counter()
            for _ in range(iters):
                val, _ = fn(x, x, x)
                jax.device_get(val)  # forced sync closes the window
            timings[block] = (time.perf_counter() - t0) / iters
        except Exception:  # noqa: BLE001 — e.g. VMEM overflow at 512
            timings[block] = float("inf")
    finite = {b: t for b, t in timings.items() if t != float("inf")}
    best = min(finite, key=finite.get) if finite else resolve_block(seq, DEFAULT_BLOCK)
    _cache[key] = (best, timings)
    return best


def last_timings(
    seq: int,
    d_head: int = 64,
    dtype=None,
    causal: bool = True,
    batch: int = 2,
    heads: int = 8,
) -> Optional[Dict[int, float]]:
    """Per-candidate seconds from the cached sweep for this shape (None if
    the sweep has not run; empty dict if it was skipped off-TPU).  The
    ``batch``/``heads`` defaults mirror :func:`autotune_flash_block` so the
    bare lookup matches the bare sweep."""
    import jax
    import jax.numpy as jnp

    dtype = dtype or jnp.bfloat16
    key = (
        jax.devices()[0].platform, seq, d_head, jnp.dtype(dtype).name, causal,
        batch, heads,
    )
    hit = _cache.get(key)
    return hit[1] if hit else None
