"""Per-rank liveness state machine: healthy → suspected → dead.

The PR-7 elastic loop detects failures from *injected* fault plans folded
into the coordinator's arrival funnel; a production deployment needs the
other funnel — real cross-process silence.  Ranks lease liveness by
heartbeating through the coordinator channel
(:class:`adapcc_tpu.coordinator.service.HeartbeatClient`); this module is
the policy half the supervisor daemon runs over the raw last-beat
timestamps:

- **healthy** — the rank's last beat is within ``timeout_s``;
- **suspected** — silence exceeded ``timeout_s`` but not yet the
  confirmation window (``grace`` further heartbeat periods).  A beat here
  returns the rank to healthy with no decision recorded — the
  false-positive guard for a GC pause / SIGSTOP blip / a briefly
  congested control link;
- **dead** — silence exceeded ``timeout_s + grace × period_s``: the rank
  confirmably stopped leasing, the supervisor journals a demotion and
  actuates the world shrink.

Every transition is a pure function of (last-beat timestamp, now), so the
machine is deterministic under injected clocks — the same property the
fault plans have, extended to wall-clock detection.  The state vocabulary
is exported for the observability gauges (numeric codes, stable).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from adapcc_tpu.elastic.worldview import (
    HEARTBEAT_TIMEOUT_ENV,
    _env_float,
    heartbeat_timeout_s,
)
from adapcc_tpu.primitives import FAULT_TOLERANT_TIME_S

#: expected heartbeat cadence (seconds): ranks beat once per period, the
#: confirmation window is ``grace`` of these past the timeout
HEARTBEAT_PERIOD_ENV = "ADAPCC_HEARTBEAT_PERIOD_S"

#: confirmation count: how many further missed periods past the timeout
#: turn suspicion into a confirmed death (>= 1)
HEARTBEAT_GRACE_ENV = "ADAPCC_HEARTBEAT_GRACE"

DEFAULT_HEARTBEAT_PERIOD_S = 1.0
DEFAULT_HEARTBEAT_GRACE = 2

#: liveness states, with stable numeric codes for the metrics gauges
HEALTHY, SUSPECTED, DEAD = "healthy", "suspected", "dead"
STATE_CODES = {HEALTHY: 0, SUSPECTED: 1, DEAD: 2}

#: recent step-walltime reports retained per rank for the slow-rank rule
MEDIANS_KEPT = 16


def _env_int(name: str, default: int) -> int:
    """Loud parse of an int knob (the ADAPCC_MERGE_ROUNDS policy)."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        value = int(raw)
    except ValueError as e:
        raise ValueError(f"{name}={raw!r}: expected an integer") from e
    if value < 1:
        raise ValueError(f"{name}={raw!r}: must be >= 1")
    return value


def heartbeat_period_s(default: float = DEFAULT_HEARTBEAT_PERIOD_S) -> float:
    return _env_float(HEARTBEAT_PERIOD_ENV, default)


def heartbeat_grace(default: int = DEFAULT_HEARTBEAT_GRACE) -> int:
    return _env_int(HEARTBEAT_GRACE_ENV, default)


@dataclass(frozen=True)
class LivenessConfig:
    """The three knobs of the detection latency/false-positive trade:
    suspicion after ``timeout_s`` of silence, death after ``grace``
    further missed ``period_s`` heartbeats.  ``from_env`` reads the
    ``ADAPCC_HEARTBEAT_*`` rows (docs/SUPERVISOR.md; malformed → loud)."""

    timeout_s: float = FAULT_TOLERANT_TIME_S
    period_s: float = DEFAULT_HEARTBEAT_PERIOD_S
    grace: int = DEFAULT_HEARTBEAT_GRACE

    def __post_init__(self) -> None:
        if self.timeout_s <= 0 or self.period_s <= 0:
            raise ValueError(
                f"timeout_s/period_s must be > 0, got "
                f"{self.timeout_s}/{self.period_s}"
            )
        if self.grace < 1:
            raise ValueError(f"grace must be >= 1, got {self.grace}")

    @property
    def confirm_s(self) -> float:
        """Silence that confirms a death: the suspicion timeout plus the
        grace window."""
        return self.timeout_s + self.grace * self.period_s

    @classmethod
    def from_env(
        cls, timeout_default: float = FAULT_TOLERANT_TIME_S
    ) -> "LivenessConfig":
        return cls(
            timeout_s=heartbeat_timeout_s(timeout_default),
            period_s=heartbeat_period_s(),
            grace=heartbeat_grace(),
        )


@dataclass
class RankHealth:
    """One rank's liveness picture — the gauge row the observability
    satellite exports (age/missed/state per rank)."""

    rank: int
    state: str = HEALTHY
    last_beat: float = 0.0
    beats: int = 0
    #: heartbeat periods elapsed since the last beat (expected: 0 or 1)
    missed: int = 0

    def row(self, now: float) -> dict:
        return {
            "rank": self.rank,
            "state": self.state,
            "age_s": round(max(0.0, now - self.last_beat), 6),
            "missed": self.missed,
            "beats": self.beats,
        }


class LivenessTable:
    """The per-rank state machines, swept together.

    ``beat(rank, now)`` renews the rank's lease (and optionally records a
    reported step walltime for the slow-rank rule); ``sweep(now)`` folds
    elapsed silence into state transitions and returns them.  Both take
    explicit timestamps so tests drive the machine deterministically; the
    daemon passes its monotonic clock.
    """

    def __init__(
        self, world: int, config: Optional[LivenessConfig] = None,
        now: float = 0.0,
    ) -> None:
        if world < 1:
            raise ValueError(f"world must be >= 1, got {world}")
        self.world = world
        self.config = config if config is not None else LivenessConfig()
        # the lease starts at construction: a rank that NEVER beats (died
        # during launch) is detected exactly like one that stopped
        self.ranks: Dict[int, RankHealth] = {
            r: RankHealth(rank=r, last_beat=now) for r in range(world)
        }
        self._medians: Dict[int, List[float]] = {r: [] for r in range(world)}

    def _check_rank(self, rank: int) -> None:
        if rank not in self.ranks:
            raise ValueError(f"rank {rank} outside world [0, {self.world})")

    # -- inputs ----------------------------------------------------------------

    def beat(
        self, rank: int, now: float, median_s: Optional[float] = None
    ) -> Optional[Tuple[int, str, str]]:
        """Renew ``rank``'s lease at ``now``; returns the transition
        ``(rank, old, new)`` when the beat flipped a non-healthy state
        back (suspected → healthy is the false-positive guard firing,
        dead → healthy is a real recovery), else None."""
        self._check_rank(rank)
        h = self.ranks[rank]
        h.last_beat = max(h.last_beat, now)
        h.beats += 1
        h.missed = 0
        if median_s is not None and median_s > 0:
            kept = self._medians[rank]
            kept.append(float(median_s))
            del kept[:-MEDIANS_KEPT]
        if h.state != HEALTHY:
            old, h.state = h.state, HEALTHY
            return (rank, old, HEALTHY)
        return None

    def sweep(self, now: float) -> List[Tuple[int, str, str]]:
        """Fold silence into transitions: for each rank, age = now −
        last_beat; past ``timeout_s`` → suspected, past ``timeout_s +
        grace·period_s`` → dead.  Pure in (timestamps, now): sweeping
        twice at the same instant is a no-op, and sweep cadence never
        changes WHICH transitions happen, only how promptly they are
        observed."""
        cfg = self.config
        out: List[Tuple[int, str, str]] = []
        for rank in range(self.world):
            h = self.ranks[rank]
            age = now - h.last_beat
            h.missed = max(0, int(age // cfg.period_s))
            if age > cfg.confirm_s:
                target = DEAD
            elif age > cfg.timeout_s:
                target = SUSPECTED
            else:
                target = HEALTHY
            # silence only ever escalates; recovery is beat()'s job (a
            # sweep cannot invent a heartbeat)
            if STATE_CODES[target] > STATE_CODES[h.state]:
                out.append((rank, h.state, target))
                h.state = target
        return out

    # -- queries ---------------------------------------------------------------

    def state(self, rank: int) -> str:
        self._check_rank(rank)
        return self.ranks[rank].state

    def dead(self) -> List[int]:
        return [r for r, h in self.ranks.items() if h.state == DEAD]

    def rows(self, now: float) -> List[dict]:
        """The liveness table as data — dumped into the dispatch-trace
        extras on every epoch bump and exported as gauges."""
        return [self.ranks[r].row(now) for r in range(self.world)]

    def medians(self) -> Dict[int, float]:
        """Per-rank median of the recently reported step walltimes — the
        feed for the coordinator's slow-rank demotion rule
        (``ADAPCC_SLOW_RANK_FACTOR``), now carried by a real straggling
        process's own heartbeats instead of synthetic numbers."""
        return {
            r: float(np.median(vals))
            for r, vals in self._medians.items()
            if vals
        }

    def export_gauges(self, metrics, now: float) -> None:
        """Per-rank age / missed-count / state gauges into a
        :class:`~adapcc_tpu.utils.observability.MetricsRegistry`."""
        if metrics is None:
            return
        for row in self.rows(now):
            r = row["rank"]
            metrics.gauge(f"liveness/rank{r}/age_s", row["age_s"])
            metrics.gauge(f"liveness/rank{r}/missed", row["missed"])
            metrics.gauge(
                f"liveness/rank{r}/state", STATE_CODES[row["state"]]
            )


__all__ = [
    "DEAD",
    "DEFAULT_HEARTBEAT_GRACE",
    "DEFAULT_HEARTBEAT_PERIOD_S",
    "HEALTHY",
    "HEARTBEAT_GRACE_ENV",
    "HEARTBEAT_PERIOD_ENV",
    "HEARTBEAT_TIMEOUT_ENV",
    "LivenessConfig",
    "LivenessTable",
    "MEDIANS_KEPT",
    "RankHealth",
    "STATE_CODES",
    "SUSPECTED",
]
