"""Crash-safe write-ahead decision journal for the supervisor daemon.

The supervisor must not become a new single point of hang: every decision
it makes (suspicion, demotion, epoch bump, strategy swap, adaptation
outcome) is appended — serialized, flushed, **fsync'd** — *before* the
actuation runs.  A supervisor restart replays the journal and resumes
with an identical WorldView:

- records whose actuation was confirmed (a later ``applied`` marker
  referencing their ``seq``) fold into state only — they are never
  re-actuated, so a restart performs **zero duplicate epoch bumps**;
- a decision with no ``applied`` marker is exactly the crash window the
  write-ahead order creates (journaled, then died before or during
  actuation): replay surfaces it as *unapplied* and the daemon completes
  it once on resume.

The file is append-only JSONL.  A torn final line (the crash landed
mid-``write``) is detected and ignored on replay — by construction it can
only be the one record whose decision was not yet durable, so dropping it
is the correct recovery.  Anything else malformed raises loudly: a
corrupt journal must never silently replay into a wrong world picture.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set

JOURNAL_VERSION = 1

#: decision kinds with side effects on the data plane: these are written
#: ahead of actuation and need an ``applied`` confirmation marker.  The
#: other kinds (suspicion, demotion, worker admission (``admit``, the
#: rejoin protocol — docs/RECOVERY.md §3), the ``swap`` record the
#: actuation itself emits, adaptation reports) are informational — replay
#: folds them but never re-runs anything for them.
ACTUATING_KINDS = ("epoch", "restore")


@dataclass(frozen=True)
class Decision:
    """One journaled record."""

    seq: int
    kind: str
    payload: Dict[str, Any] = field(default_factory=dict)

    def to_line(self) -> str:
        rec = {"v": JOURNAL_VERSION, "seq": self.seq, "kind": self.kind}
        rec.update(self.payload)
        return json.dumps(rec, sort_keys=True)


@dataclass
class JournalState:
    """What a replay reconstructs."""

    decisions: List[Decision] = field(default_factory=list)
    applied: Set[int] = field(default_factory=set)
    next_seq: int = 0
    #: the last journaled world picture (an ``epoch``/``restore`` record's
    #: alive/relays/epoch payload), None when no membership decision was
    #: ever taken
    last_view: Optional[Dict[str, Any]] = None

    @property
    def unapplied(self) -> List[Decision]:
        """Actuating decisions whose confirmation marker never landed —
        the interrupted work a resuming supervisor completes exactly
        once."""
        return [
            d
            for d in self.decisions
            if d.kind in ACTUATING_KINDS and d.seq not in self.applied
        ]

    def epoch_bumps(self) -> List[Decision]:
        return [d for d in self.decisions if d.kind in ("epoch", "restore")]


class DecisionJournal:
    """Append-only fsync'd JSONL journal (module doc).

    ``append`` is the write-ahead barrier: it returns only after the
    record is durable (``flush`` + ``os.fsync``), so the actuation that
    follows can crash without losing the decision.  ``mark_applied``
    appends the confirmation marker with the same durability.
    """

    def __init__(self, path: str) -> None:
        self.path = path
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._fh = None
        state, good_bytes = self._replay_with_offset()
        # repair the torn tail BEFORE the first append: the torn bytes
        # are by construction the one record that never became durable,
        # but left in place a post-resume append would merge into them —
        # and the merged line would either shadow a durable record on the
        # next replay or make the journal unreadable.  Truncating to the
        # last good record is the durable spelling of "that write never
        # happened".
        if os.path.exists(self.path) and good_bytes < os.path.getsize(
            self.path
        ):
            with open(self.path, "r+b") as f:
                f.truncate(good_bytes)
                f.flush()
                os.fsync(f.fileno())
        self._seq = state.next_seq

    def _handle(self):
        if self._fh is None:
            self._fh = open(self.path, "a", encoding="utf-8")
        return self._fh

    # -- write-ahead append ----------------------------------------------------

    def append(self, kind: str, **payload: Any) -> Decision:
        """Durably journal one decision BEFORE its actuation; returns it
        (the ``seq`` is what :meth:`mark_applied` confirms later)."""
        if kind == "applied":
            raise ValueError(
                "'applied' is the confirmation marker; use mark_applied"
            )
        d = Decision(seq=self._seq, kind=kind, payload=dict(payload))
        self._seq += 1
        fh = self._handle()
        fh.write(d.to_line() + "\n")
        fh.flush()
        os.fsync(fh.fileno())
        return d

    def mark_applied(self, seq: int) -> None:
        """Durably confirm that decision ``seq``'s actuation completed —
        the marker replay uses to guarantee zero double-actuation."""
        fh = self._handle()
        fh.write(
            json.dumps(
                {"v": JOURNAL_VERSION, "seq": self._seq, "kind": "applied",
                 "ref": int(seq)},
                sort_keys=True,
            )
            + "\n"
        )
        self._seq += 1
        fh.flush()
        os.fsync(fh.fileno())

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    # -- replay ----------------------------------------------------------------

    def replay(self) -> JournalState:
        """Fold the journal back into a :class:`JournalState`.

        Tolerates exactly one torn record, and only at the tail (the
        crash-mid-write window); any other malformed or out-of-order line
        raises — silent tolerance there would replay a wrong world."""
        return self._replay_with_offset()[0]

    def _replay_with_offset(self) -> "tuple[JournalState, int]":
        """:meth:`replay`, additionally returning the byte offset of the
        end of the last GOOD record — the truncation point the
        constructor's torn-tail repair uses."""
        state = JournalState()
        if not os.path.exists(self.path):
            return state, 0
        with open(self.path, encoding="utf-8") as fh:
            lines = fh.read().split("\n")
        # drop the trailing empty slice of a newline-terminated file
        if lines and lines[-1] == "":
            lines.pop()
        good_bytes = 0
        for i, line in enumerate(lines):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                if i == len(lines) - 1:
                    break  # torn tail: the one legal kind of damage
                raise ValueError(
                    f"{self.path}:{i + 1}: corrupt journal record "
                    f"(not the torn tail): {line!r}"
                ) from e
            if rec.get("v") != JOURNAL_VERSION:
                raise ValueError(
                    f"{self.path}:{i + 1}: journal version "
                    f"{rec.get('v')!r} != {JOURNAL_VERSION}"
                )
            seq, kind = int(rec["seq"]), str(rec["kind"])
            if seq != state.next_seq:
                raise ValueError(
                    f"{self.path}:{i + 1}: seq {seq} breaks the monotone "
                    f"chain (expected {state.next_seq}) — the journal was "
                    "edited or interleaved"
                )
            state.next_seq = seq + 1
            good_bytes += len(line.encode("utf-8")) + 1  # + the newline
            if kind == "applied":
                state.applied.add(int(rec["ref"]))
                continue
            payload = {
                k: v for k, v in rec.items() if k not in ("v", "seq", "kind")
            }
            d = Decision(seq=seq, kind=kind, payload=payload)
            state.decisions.append(d)
            if kind in ("epoch", "restore"):
                state.last_view = payload
        return state, good_bytes


__all__ = [
    "ACTUATING_KINDS",
    "Decision",
    "DecisionJournal",
    "JOURNAL_VERSION",
    "JournalState",
]
