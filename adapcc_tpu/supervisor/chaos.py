"""Deterministic chaos harness for real multi-process runs.

The elastic fault plans (:mod:`adapcc_tpu.elastic.faults`) inject failures
*logically* — dropped arrivals at the coordinator funnel, per-step relay
masks.  This module spells the same plans as **real process faults** so
the supervisor's heartbeat-loss detection is exercised by genuine
cross-process silence:

- ``down``  → ``SIGKILL`` the rank's process (its heartbeats stop cold);
- ``slow``  → a ``SIGSTOP``/``SIGCONT`` duty cycle that stretches the
  process's wall time by the event's ``slowdown`` factor — the rank keeps
  heartbeating (slower), its self-reported step walltimes inflate, and
  the supervisor's slow-rank rule (``ADAPCC_SLOW_RANK_FACTOR``) demotes a
  *really straggling process*, not a synthetic median;
- ``recover`` → ``SIGCONT`` (a killed rank cannot be un-killed; its
  recovery event maps to the deployment's restart story, not a signal).

The schedule is a pure function of ``(plan, step_period_s)`` — same plan,
same byte-identical action list — so two chaos drills under one seed see
identical fault timelines, the property every deterministic-injection
test in this repo rides on.

The third seam is the heartbeat transport itself: :class:`BeatChaos`
drops or delays individual beats deterministically (hash-seeded per
``(seed, rank, seq)``), which tests detection without touching any
process — a lossy control network, in one object.
"""

from __future__ import annotations

import hashlib
import os
import signal
import threading
import time
from dataclasses import dataclass
from typing import List, Mapping, Optional, Tuple

from adapcc_tpu.elastic.faults import FaultPlan

#: duty-cycle granularity for the SIGSTOP straggler: one stop+cont pair
#: per window, stopped for ``1 - 1/slowdown`` of it
DEFAULT_DUTY_WINDOW_S = 0.2

_SIGNALS = {
    "kill": signal.SIGKILL,
    "stop": signal.SIGSTOP,
    "cont": signal.SIGCONT,
}


@dataclass(frozen=True)
class ChaosAction:
    """One scheduled signal: deliver ``kind`` to ``rank`` at ``at_s``
    seconds after the injector starts."""

    at_s: float
    kind: str
    rank: int

    def __post_init__(self) -> None:
        if self.kind not in _SIGNALS:
            raise ValueError(
                f"unknown chaos action {self.kind!r}; expected one of "
                f"{sorted(_SIGNALS)}"
            )
        if self.at_s < 0:
            raise ValueError(f"at_s must be >= 0, got {self.at_s}")


def wall_schedule(
    plan: FaultPlan,
    step_period_s: float,
    duty_window_s: float = DEFAULT_DUTY_WINDOW_S,
) -> List[ChaosAction]:
    """Compile a step-indexed :class:`FaultPlan` into a wall-clock signal
    schedule (module doc).  Pure and deterministic: sorted by
    ``(at_s, rank, kind)``, byte-identical across calls.

    ``slow`` events become a stop/cont duty cycle from the event's step
    until the rank's ``recover`` step (or one step past the plan's last
    event): stopped ``1 − 1/slowdown`` of every ``duty_window_s``, so the
    process's wall time stretches by ≈``slowdown``.
    """
    if step_period_s <= 0:
        raise ValueError(f"step_period_s must be > 0, got {step_period_s}")
    if duty_window_s <= 0:
        raise ValueError(f"duty_window_s must be > 0, got {duty_window_s}")
    actions: List[ChaosAction] = []
    horizon_s = (plan.last_step() + 1) * step_period_s
    for i, e in enumerate(plan.events):
        t0 = e.step * step_period_s
        if e.kind == "down":
            actions.append(ChaosAction(t0, "kill", e.rank))
        elif e.kind == "recover":
            # harmless for a killed rank (no pid to signal by then); ends
            # a straggler's duty cycle for sure even if windows drifted
            actions.append(ChaosAction(t0, "cont", e.rank))
        else:  # slow
            until = next(
                (
                    later.step * step_period_s
                    for later in plan.events[i + 1:]
                    if later.rank == e.rank and later.kind != "slow"
                ),
                horizon_s,
            )
            stopped = duty_window_s * (1.0 - 1.0 / e.slowdown)
            t = t0
            while t < until:
                if stopped > 0:
                    actions.append(ChaosAction(round(t, 9), "stop", e.rank))
                    actions.append(
                        ChaosAction(round(t + stopped, 9), "cont", e.rank)
                    )
                t += duty_window_s
    return sorted(actions, key=lambda a: (a.at_s, a.rank, a.kind))


class ChaosInjector:
    """Deliver a :func:`wall_schedule` to real processes.

    ``run(pids)`` sleeps to each action's offset and sends the signal; a
    rank whose process already exited is skipped (killing a corpse is not
    an error — the schedule outliving a process is the normal end state
    of a ``down`` event).  ``start``/``join`` run it on a thread so the
    drill's training loop keeps the main thread.
    """

    def __init__(
        self,
        plan: FaultPlan,
        step_period_s: float,
        duty_window_s: float = DEFAULT_DUTY_WINDOW_S,
    ) -> None:
        self.plan = plan
        self.step_period_s = float(step_period_s)
        self.schedule: Tuple[ChaosAction, ...] = tuple(
            wall_schedule(plan, step_period_s, duty_window_s)
        )
        self.delivered: List[ChaosAction] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def _signal(self, pid: int, action: ChaosAction) -> bool:
        try:
            os.kill(pid, _SIGNALS[action.kind])
        except (ProcessLookupError, PermissionError):
            return False
        self.delivered.append(action)
        return True

    def run(self, pids: Mapping[int, int]) -> List[ChaosAction]:
        missing = [r for r in {a.rank for a in self.schedule} if r not in pids]
        if missing:
            raise ValueError(
                f"chaos schedule names ranks {sorted(missing)} with no pid"
            )
        t0 = time.monotonic()
        for action in self.schedule:
            delay = t0 + action.at_s - time.monotonic()
            if delay > 0 and self._stop.wait(delay):
                break
            self._signal(pids[action.rank], action)
        return list(self.delivered)

    def start(self, pids: Mapping[int, int]) -> "ChaosInjector":
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError("chaos injector already running")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self.run, args=(dict(pids),), name="adapcc-chaos",
            daemon=True,
        )
        self._thread.start()
        return self

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    def stop(self) -> None:
        self._stop.set()
        self.join(timeout=5)


class BeatChaos:
    """Deterministic heartbeat drop/delay at the transport seam.

    ``gate(rank, seq)`` → ``(send, delay_s)``: whether beat ``seq`` from
    ``rank`` goes out at all, and how long to hold it first.  Decisions
    are hash-seeded per ``(seed, rank, seq)`` — no RNG state, so two
    clients (or one client re-created after a crash) gate identically.
    """

    def __init__(
        self,
        drop_rate: float = 0.0,
        delay_s: float = 0.0,
        delay_rate: float = 0.0,
        seed: int = 0,
    ) -> None:
        if not 0.0 <= drop_rate <= 1.0 or not 0.0 <= delay_rate <= 1.0:
            raise ValueError("drop_rate/delay_rate must be in [0, 1]")
        if delay_s < 0:
            raise ValueError(f"delay_s must be >= 0, got {delay_s}")
        self.drop_rate = float(drop_rate)
        self.delay_s = float(delay_s)
        self.delay_rate = float(delay_rate)
        self.seed = int(seed)

    def _unit(self, rank: int, seq: int, salt: str) -> float:
        h = hashlib.sha256(
            f"{self.seed}:{rank}:{seq}:{salt}".encode()
        ).digest()
        return int.from_bytes(h[:8], "big") / float(1 << 64)

    def gate(self, rank: int, seq: int) -> Tuple[bool, float]:
        if self._unit(rank, seq, "drop") < self.drop_rate:
            return False, 0.0
        delay = (
            self.delay_s
            if self._unit(rank, seq, "delay") < self.delay_rate
            else 0.0
        )
        return True, delay


__all__ = [
    "BeatChaos",
    "ChaosAction",
    "ChaosInjector",
    "DEFAULT_DUTY_WINDOW_S",
    "wall_schedule",
]
