"""The autonomous supervisor: out-of-band detect → decide → swap.

PR 7 (elastic failover) and PR 9 (online adaptation) both close their
loops *from inside the training loop* — the trainer polls the worldview,
activates standby plans, and runs the adaptation pass between its own
steps.  This daemon moves that authority out of band, the shape
production collective stacks use (The Big Send-off, PAPERS.md): a
:class:`Supervisor` owns the loop, training processes only observe epoch
bumps (and retry ``EpochMismatch`` exactly as they already do).

Two detection funnels feed the same
:meth:`~adapcc_tpu.coordinator.logic.CoordinatorLogic.worldview`:

- **real cross-process silence** — ranks lease liveness through the
  coordinator's heartbeat RPC; the supervisor sweeps the per-rank
  :class:`~adapcc_tpu.supervisor.liveness.LivenessTable` (healthy →
  suspected → dead with a grace window) and journals confirmed deaths;
- **injected fault plans** — ``ADAPCC_FAULT_PLAN`` events folded at the
  supervisor's own cadence (the CPU-testable twin), including ``slow``
  events, which the chaos harness also spells as a real SIGSTOP
  duty-cycle (:mod:`adapcc_tpu.supervisor.chaos`).

Every decision is journaled to a fsync'd write-ahead log *before*
actuation (:mod:`adapcc_tpu.supervisor.journal`), so a supervisor restart
replays to an identical WorldView with zero duplicate epoch bumps — the
supervisor itself is not a new single point of hang.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from adapcc_tpu.elastic.worldview import WorldView, slow_ranks_from_medians
from adapcc_tpu.supervisor.journal import DecisionJournal
from adapcc_tpu.supervisor.liveness import (
    DEAD,
    HEALTHY,
    SUSPECTED,
    LivenessConfig,
    LivenessTable,
)

#: workload gate: ``on`` arms ``train_ddp --supervisor`` from the
#: environment (the battery spelling); anything else but ``off``/unset is
#: a loud error
SUPERVISOR_ENV = "ADAPCC_SUPERVISOR"

#: consecutive poll failures before the daemon thread gives up loudly (a
#: supervisor spinning on a poisoned poll is as useless as a hung one)
MAX_CONSECUTIVE_ERRORS = 5


def supervisor_enabled(explicit: bool = False) -> bool:
    """The ``ADAPCC_SUPERVISOR`` funnel: env > explicit flag > off;
    malformed → loud (the ADAPCC_MERGE_ROUNDS policy)."""
    raw = os.environ.get(SUPERVISOR_ENV, "").strip().lower()
    if not raw:
        return bool(explicit)
    if raw in ("on", "1", "true"):
        return True
    if raw in ("off", "0", "false"):
        return False
    raise ValueError(f"{SUPERVISOR_ENV}={raw!r}: expected on|off")


class Supervisor:
    """One world's autonomous failure-handling authority (module doc).

    Wiring::

        logic = CoordinatorLogic(world)            # heartbeat funnel
        cache = StandbyPlanCache(engine); cache.build(); cache.warm(...)
        sup = Supervisor(logic, engine, cache=cache, trainer=trainer,
                         journal_path="topology/supervisor.journal")
        sup.start(period_s=0.25)                   # the daemon thread
        ...
        mask = sup.current_mask()                  # what trainers consume
        sup.stop()

    ``poll()`` is one deterministic pass (tests drive it with injected
    clocks); ``start`` runs it on a timer.  All decisions are
    write-ahead journaled; ``Supervisor(..., resume=True)`` (the default)
    replays an existing journal before doing anything else.
    """

    def __init__(
        self,
        logic,
        engine=None,
        cache=None,
        trainer=None,
        journal_path: Optional[str] = None,
        config: Optional[LivenessConfig] = None,
        metrics=None,
        adapt=None,
        adapt_every: int = 0,
        fault_plan=None,
        step_source: Optional[Callable[[], int]] = None,
        on_world_change: Optional[Callable[[WorldView, WorldView], Any]] = None,
        clock: Callable[[], float] = time.monotonic,
        resume: bool = True,
    ) -> None:
        if cache is not None and engine is None:
            engine = cache.engine
        if fault_plan is not None:
            if step_source is None:
                raise ValueError(
                    "a fault plan needs step_source (the plan's events are "
                    "keyed by training step; the supervisor cannot fold "
                    "them without knowing where the run is)"
                )
            if fault_plan.world != logic.world_size:
                raise ValueError(
                    f"fault plan world {fault_plan.world} != coordinator "
                    f"world {logic.world_size}"
                )
        if adapt_every < 0:
            raise ValueError(f"adapt_every must be >= 0, got {adapt_every}")
        self.logic = logic
        self.engine = engine
        self.cache = cache
        self.trainer = trainer
        self.metrics = metrics
        self.adapt = adapt
        self.adapt_every = int(adapt_every)
        self.fault_plan = fault_plan
        self.step_source = step_source
        self.on_world_change = on_world_change
        self.clock = clock
        self.config = (
            config if config is not None else LivenessConfig.from_env()
        )
        now = clock()
        self.table = LivenessTable(logic.world_size, self.config, now=now)
        self.journal = (
            DecisionJournal(journal_path) if journal_path else None
        )
        #: the view whose actuation last completed — what trainers see
        self._applied_view: WorldView = logic.worldview()
        #: epoch token for engine dispatches planned against this view
        self.engine_epoch: int = engine.epoch if engine is not None else 0
        #: ranks the fault plan currently marks down / slow (feed B state)
        self._plan_dead: frozenset = frozenset()
        self._plan_slow: frozenset = frozenset()
        self._beats_seen: Dict[int, int] = {}
        self.decisions = 0
        self.polls = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._lock = threading.RLock()
        if resume and self.journal is not None:
            self._resume()

    # -- journal helpers -------------------------------------------------------

    def _journal(self, kind: str, **payload):
        self.decisions += 1
        if self.metrics is not None:
            self.metrics.incr("supervisor/decisions")
            self.metrics.incr(f"supervisor/decisions/{kind}")
        if self.journal is not None:
            return self.journal.append(kind, **payload)
        return None

    def _view_payload(self, wv: WorldView) -> dict:
        return {
            "alive": sorted(wv.alive),
            "relays": sorted(wv.relays),
            "wv_epoch": wv.epoch,
        }

    # -- resume ----------------------------------------------------------------

    def _resume(self) -> None:
        """Replay the journal: restore the applied view and complete any
        decision that was journaled but whose actuation never confirmed.
        Confirmed decisions are NEVER re-actuated — the zero-duplicate-
        epoch-bump property the restart drill pins."""
        state = self.journal.replay()
        if state.last_view is not None:
            replayed = WorldView(
                world_size=self.logic.world_size,
                alive=frozenset(state.last_view["alive"]),
                relays=frozenset(state.last_view["relays"]),
                epoch=int(state.last_view["wv_epoch"]),
            )
            live = self.logic.worldview()
            # never regress a live coordinator that moved past the journal
            # while the supervisor was down; a fresh (or lagging) logic is
            # brought up to the journaled picture
            if replayed.epoch >= live.epoch:
                self._applied_view = self.logic.restore_worldview(
                    replayed.alive, replayed.relays, replayed.epoch
                )
            else:
                self._applied_view = live
        for d in state.decisions:
            if d.kind == "swap" and "engine_epoch" in d.payload:
                self.engine_epoch = max(
                    self.engine_epoch, int(d.payload["engine_epoch"])
                )
            if d.kind in ("admit", "recover") and "gen" in d.payload:
                # re-seed the admit counter: a fresh logic starts at 0,
                # and without this a post-restart rejoin would reuse a
                # journaled generation's rendezvous namespace (and read
                # the earlier rejoin's stale keys as its own).  Both the
                # heartbeat admit and the fault-plan recover bump it;
                # pre-PR-13 recover records carry no gen and are skipped
                if hasattr(self.logic, "seed_restart_generation"):
                    self.logic.seed_restart_generation(int(d.payload["gen"]))
        # the fresh liveness table must agree with the replayed view: a
        # journald death stays DEAD (no duplicate suspicion walk, no
        # duplicate dead decision), and beats that PREDATE the restart are
        # history, not new evidence of life — only a post-restart beat
        # (fresh count) may flip a dead rank back to healthy
        for rank in sorted(self._applied_view.dead):
            if rank in self.table.ranks:
                self.table.ranks[rank].state = DEAD
        if hasattr(self.logic, "heartbeat_snapshot"):
            self._beats_seen = {
                r: rec["beats"]
                for r, rec in self.logic.heartbeat_snapshot().items()
            }
        for d in state.unapplied:
            # the crash window: journaled, died before the actuation
            # confirmed — complete it exactly once
            self._actuate(self._applied_view, seq=d.seq)

    def reconcile(self) -> None:
        """Re-actuate the (replayed) applied view against a freshly built
        engine — the cold-restart bootstrap for a supervisor process that
        came back with a new engine/cache (the in-process restart path
        never needs this: the engine kept its swapped strategy)."""
        if not self._applied_view.degraded:
            return
        d = self._journal("restore", **self._view_payload(self._applied_view))
        self._actuate(self._applied_view, seq=d.seq if d else None)

    # -- actuation -------------------------------------------------------------

    def _actuate(self, wv: WorldView, seq: Optional[int] = None) -> None:
        """Drive the data plane onto ``wv``: standby-cache swap (dead
        ranks) or base-plan restore (recovery / relay-only change), the
        trainer's program adoption, and the world-change callback.  The
        journal confirmation marker lands only after everything ran."""
        if self.cache is not None:
            if wv.dead:
                plan, self.engine_epoch = self.cache.activate(wv.alive)
                strategy = plan.strategy
                swap_payload = {
                    "label": plan.label,
                    "fingerprint": strategy.fingerprint(),
                    "warmed": plan.warmed,
                    "engine_epoch": self.engine_epoch,
                }
            else:
                # recovery or relay-only demotion: the base plan's compiled
                # programs never left the cache — relay masks are runtime
                # state, so no re-emitted strategy is needed
                self.engine_epoch = self.cache.restore_full()
                strategy = self.cache.base_strategy
                swap_payload = {
                    "label": "base",
                    "fingerprint": strategy.fingerprint(),
                    "warmed": True,
                    "engine_epoch": self.engine_epoch,
                }
            self._journal("swap", **swap_payload)
            if self.trainer is not None:
                self.trainer.adopt_strategy(strategy)
        elif self.engine is not None:
            self.engine_epoch = self.engine.advance_epoch()
        if self.on_world_change is not None:
            self.on_world_change(self._applied_view, wv)
        if (
            self.engine is not None
            and getattr(self.engine, "trace", None) is not None
        ):
            # satellite: the liveness table rides the dispatch trace on
            # every epoch bump, so a trace dump answers "what did the
            # supervisor believe when it swapped"
            self.engine.trace.record(
                "supervisor",
                "epoch_bump",
                0,
                epoch=self.engine_epoch,
                seq=seq,
                liveness=self.table.rows(self.clock()),
                **self._view_payload(wv),
            )
        self._applied_view = wv
        if self.metrics is not None:
            self.metrics.gauge("supervisor/wv_epoch", wv.epoch)
            self.metrics.gauge("supervisor/engine_epoch", self.engine_epoch)
        if seq is not None and self.journal is not None:
            self.journal.mark_applied(seq)

    # -- the loop --------------------------------------------------------------

    def _feed_heartbeats(self, now: float) -> List[tuple]:
        """Feed A: consume new beats from the coordinator's heartbeat
        funnel into the liveness table, then sweep silence into
        transitions.

        The sweep is gated on a lease actually existing: until the FIRST
        beat ever arrives, no rank has taken a liveness lease and silence
        is not evidence — a deployment that never wires heartbeats (the
        fault-plan-only workload spelling) must not watch its whole world
        age past the confirm window and declare everyone dead.  Once any
        rank leases, a rank that never did is detected exactly like one
        that stopped (the died-during-launch case)."""
        transitions: List[tuple] = []
        snapshot = (
            self.logic.heartbeat_snapshot()
            if hasattr(self.logic, "heartbeat_snapshot")
            else {}
        )
        for rank, rec in snapshot.items():
            if rec["beats"] > self._beats_seen.get(rank, 0):
                self._beats_seen[rank] = rec["beats"]
                t = self.table.beat(rank, rec["ts"], rec.get("median_s"))
                if t is not None:
                    transitions.append(t)
        if self._beats_seen:
            transitions.extend(self.table.sweep(now))
        return transitions

    def _feed_fault_plan(self, note) -> None:
        """Feed B: fold the injected plan's state at the current training
        step into the same decision stream real silence feeds."""
        state = self.fault_plan.state_at(int(self.step_source()))
        down, slow = state.down, frozenset(state.slow_map)
        for rank in sorted(down - self._plan_dead):
            note("dead", rank=rank, origin="plan")
            self.logic.mark_down([rank])
        recovered = self._plan_dead - down
        if recovered:
            # this path bumps the admit counter too (the ranks were DEAD),
            # so the journaled record must carry the generation — replay
            # re-seeds from it exactly like a heartbeat-path admit, or a
            # restarted supervisor would reissue this generation's
            # rendezvous namespace to the next rejoin
            gen = self.logic.mark_recovered(recovered)
            note("recover", ranks=sorted(recovered), origin="plan", gen=gen)
        self._plan_dead, self._plan_slow = down, slow

    def poll(self, now: Optional[float] = None) -> List[dict]:
        """One pass of the loop; returns the decisions taken (journaled
        order).  Deterministic given the heartbeat timestamps, the clock,
        and the step source."""
        with self._lock:
            return self._poll_locked(
                self.clock() if now is None else float(now)
            )

    def _poll_locked(self, now: float) -> List[dict]:
        self.polls += 1
        taken: List[dict] = []

        def note(kind: str, **payload):
            self._journal(kind, **payload)
            taken.append({"kind": kind, **payload})

        # -- detect ------------------------------------------------------------
        for rank, old, new in self._feed_heartbeats(now):
            if new == SUSPECTED:
                note("suspect", rank=rank, age_s=round(
                    now - self.table.ranks[rank].last_beat, 6))
            elif new == DEAD:
                note("dead", rank=rank, origin="heartbeat")
                self.logic.mark_down([rank])
            elif old == DEAD and new == HEALTHY:
                # a replacement (or restarted) worker leased in for a
                # DEAD rank — the rejoin protocol's admit decision
                # (docs/RECOVERY.md §3): the journaled generation is the
                # rendezvous namespace the newcomer's catch-up restore
                # (restore_newest_across_processes(gen=)) keys by, and the
                # membership change actuates below as the grow-back epoch
                # (StandbyPlanCache.restore_full → warm base plan)
                gen = self.logic.mark_recovered([rank])
                note("admit", rank=rank, origin="heartbeat", gen=gen)
            elif old == SUSPECTED and new == HEALTHY:
                # the false-positive guard fired: a paused-then-resumed
                # rank inside the grace window was never demoted
                note("clear", rank=rank)
        if self.fault_plan is not None:
            self._feed_fault_plan(note)
        # -- demote (slow-rank rule over reported step medians) ---------------
        medians = self.table.medians()
        measured_slow = (
            slow_ranks_from_medians(medians, factor=self.logic.slow_factor)
            if len(medians) > 2
            else frozenset()
        )
        target_relays = (measured_slow | self._plan_slow) - self.logic.worldview().dead
        current_relays = self.logic.worldview().relays
        if target_relays != current_relays:
            demoted = sorted(target_relays - current_relays)
            promoted = sorted(current_relays - target_relays)
            if demoted:
                note("demote", ranks=demoted, medians={
                    str(r): round(medians[r], 6) for r in demoted
                    if r in medians
                })
            if promoted:
                note("promote", ranks=promoted)
            self.logic.set_relays(target_relays)
        # -- decide + swap -----------------------------------------------------
        wv = self.logic.worldview()
        if (wv.alive, wv.relays) != (
            self._applied_view.alive,
            self._applied_view.relays,
        ):
            d = self._journal("epoch", **self._view_payload(wv))
            taken.append({"kind": "epoch", **self._view_payload(wv)})
            self._actuate(wv, seq=d.seq if d is not None else None)
        # -- adapt (the PR-9 loop, now supervisor-owned) -----------------------
        if (
            self.adapt is not None
            and self.adapt_every
            and self.polls % self.adapt_every == 0
        ):
            rep = self.adapt.maybe_adapt()
            # steady states are not decisions: while a re-route is live
            # every pass reads "congestion-active"/"congestion-sustained",
            # and journaling each would fsync an append per poll for the
            # whole window without recording anything actionable — only
            # the transitions (reroute, cleared, swap, …) ride the WAL
            if rep.outcome not in (
                "off", "no-drift", "congestion-active",
                "congestion-sustained",
            ):
                # the triage verdict rides the journal: a later audit must
                # be able to tell a transient congestion re-route (model
                # untouched, restore pending) from a re-calibrated
                # degradation swap (docs/FABRIC.md §3)
                note(
                    "adapt",
                    outcome=rep.outcome,
                    triage=rep.triage,
                    winner=rep.winner_fingerprint,
                    engine_epoch=rep.epoch,
                )
                if rep.swapped and rep.epoch is not None:
                    self.engine_epoch = rep.epoch
        # -- observe -----------------------------------------------------------
        self.table.export_gauges(self.metrics, now)
        return taken

    # -- queries ---------------------------------------------------------------

    @property
    def applied_view(self) -> WorldView:
        return self._applied_view

    def worldview(self) -> WorldView:
        return self.logic.worldview()

    def current_mask(self) -> np.ndarray:
        """The ``[world]`` bool contribution mask of the last *actuated*
        view — what a training step consumes.  Trainers never see a
        decision before its swap completed (the actuation order is the
        WAL order)."""
        with self._lock:
            return self._applied_view.mask()

    # -- daemon thread ---------------------------------------------------------

    def start(self, period_s: float = 0.25) -> "Supervisor":
        if period_s <= 0:
            raise ValueError(f"period_s must be > 0, got {period_s}")
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError("supervisor already running")
        self._stop.clear()

        def loop() -> None:
            errors = 0
            while not self._stop.wait(period_s):
                try:
                    self.poll()
                    errors = 0
                except Exception:  # noqa: BLE001 — the daemon must not die silently
                    errors += 1
                    print(
                        f"[adapcc] supervisor poll failed "
                        f"({errors}/{MAX_CONSECUTIVE_ERRORS}):\n"
                        f"{traceback.format_exc()}",
                        file=sys.stderr,
                        flush=True,
                    )
                    if self.metrics is not None:
                        self.metrics.incr("supervisor/errors")
                    if errors >= MAX_CONSECUTIVE_ERRORS:
                        print(
                            "[adapcc] supervisor giving up after "
                            f"{errors} consecutive failures",
                            file=sys.stderr,
                            flush=True,
                        )
                        return

        self._thread = threading.Thread(
            target=loop, name="adapcc-supervisor", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        if self.journal is not None:
            self.journal.close()


__all__ = [
    "MAX_CONSECUTIVE_ERRORS",
    "SUPERVISOR_ENV",
    "Supervisor",
    "supervisor_enabled",
]
