"""Autonomous supervisor daemon (docs/SUPERVISOR.md).

Out-of-band failure handling for the elastic + adaptation loops: ranks
lease liveness through the coordinator's heartbeat RPC, a per-rank
healthy → suspected → dead state machine confirms real cross-process
silence, every decision is write-ahead journaled (fsync'd) before
actuation, and a deterministic chaos harness (SIGKILL / SIGSTOP
duty-cycle / heartbeat drop-delay) drives the whole loop against real
processes.
"""

from adapcc_tpu.supervisor.chaos import (
    BeatChaos,
    ChaosAction,
    ChaosInjector,
    wall_schedule,
)
from adapcc_tpu.supervisor.daemon import (
    SUPERVISOR_ENV,
    Supervisor,
    supervisor_enabled,
)
from adapcc_tpu.supervisor.journal import Decision, DecisionJournal
from adapcc_tpu.supervisor.liveness import (
    DEAD,
    HEALTHY,
    HEARTBEAT_GRACE_ENV,
    HEARTBEAT_PERIOD_ENV,
    SUSPECTED,
    LivenessConfig,
    LivenessTable,
)

__all__ = [
    "BeatChaos",
    "ChaosAction",
    "ChaosInjector",
    "DEAD",
    "Decision",
    "DecisionJournal",
    "HEALTHY",
    "HEARTBEAT_GRACE_ENV",
    "HEARTBEAT_PERIOD_ENV",
    "LivenessConfig",
    "LivenessTable",
    "SUPERVISOR_ENV",
    "SUSPECTED",
    "Supervisor",
    "supervisor_enabled",
    "wall_schedule",
]
