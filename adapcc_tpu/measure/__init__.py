"""Measurement harnesses (the reference's units-test/ instrumentation suite).

Three tools the reference keeps as standalone scripts become a library here:

- :mod:`adapcc_tpu.measure.wait_time` — per-step worker-skew (straggler)
  measurement with emulated heterogeneity (units-test/get_wait_time.py).
- :mod:`adapcc_tpu.measure.throughput` — coordinator-timestamped training
  throughput (units-test/throughput.py).
- :mod:`adapcc_tpu.measure.gns` — gradient-noise-scale estimation
  (units-test/get_gns.py).
"""

from adapcc_tpu.measure.gns import GNSEstimator, gns_from_norms
from adapcc_tpu.measure.throughput import ThroughputMeter
from adapcc_tpu.measure.wait_time import WaitTimeProbe, emulate_heterogeneous_steps

__all__ = [
    "GNSEstimator",
    "gns_from_norms",
    "ThroughputMeter",
    "WaitTimeProbe",
    "emulate_heterogeneous_steps",
]
