"""Gradient-noise-scale (GNS) estimation (units-test/get_gns.py analog).

The reference computes the GNS of a DDP run from two gradient-norm
estimates per step — the per-worker gradient (small batch ``b``) and the
allreduced gradient (large batch ``B = b × world``) — using the unbiased
estimators of the large-batch-training noise model (get_gns.py:26-108):

    |G|²  ≈ (B·|G_B|² − b·|G_b|²) / (B − b)
    S     ≈ (|G_b|² − |G_B|²) / (1/b − 1/B)
    B_noise = S / |G|²

Both are noisy per step, so the estimator EMA-smooths S and |G|²
*separately* before taking the ratio (the reference's running averages).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp


def tree_sq_norm(tree: Any) -> jnp.ndarray:
    """Σ‖leaf‖² over a pytree (one scalar)."""
    leaves = jax.tree_util.tree_leaves(tree)
    return sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)


def ddp_grad_sq_norms(
    local_grads: Any, mean_grads: Any, axis_name: str
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(E|G_b|², |G_B|²) from inside shard_map: the small-batch norm is the
    cross-rank average of each worker's local grad norm; the big-batch norm
    is the norm of the already-averaged gradient."""
    small = jax.lax.pmean(tree_sq_norm(local_grads), axis_name)
    big = tree_sq_norm(mean_grads)
    return small, big


def gns_from_norms(
    small_sq: float, big_sq: float, b_small: int, b_big: int
) -> Tuple[float, float]:
    """Unbiased (|G|², S) from one pair of norm estimates."""
    if b_big <= b_small:
        raise ValueError(f"need b_big > b_small, got {b_big} <= {b_small}")
    g2 = (b_big * big_sq - b_small * small_sq) / (b_big - b_small)
    s = (small_sq - big_sq) / (1.0 / b_small - 1.0 / b_big)
    return g2, s


class GNSEstimator:
    """EMA-smoothed gradient noise scale over a training run.

    ``update`` per step with the two squared norms (host floats or scalars
    from :func:`ddp_grad_sq_norms`); read ``gns`` any time.
    """

    def __init__(self, b_small: int, b_big: int, ema: float = 0.9) -> None:
        self.b_small = b_small
        self.b_big = b_big
        self.ema = ema
        self._g2: Optional[float] = None
        self._s: Optional[float] = None

    def update(self, small_sq: float, big_sq: float) -> Optional[float]:
        g2, s = gns_from_norms(float(small_sq), float(big_sq), self.b_small, self.b_big)
        if self._g2 is None:
            self._g2, self._s = g2, s
        else:
            self._g2 = self.ema * self._g2 + (1 - self.ema) * g2
            self._s = self.ema * self._s + (1 - self.ema) * s
        return self.gns

    @property
    def gns(self) -> Optional[float]:
        """Current B_noise estimate (None before any update or while the
        smoothed |G|² is ≤ 0, which happens early in noisy runs)."""
        if self._g2 is None or self._g2 <= 0:
            return None
        return self._s / self._g2
