"""Per-step worker-skew (straggler) measurement.

The reference instruments its DDP hook with gRPC timestamps and reports, per
step, how long the fastest worker waits for the slowest (max−min arrival),
optionally scaling one rank's compute by ``heter_alpha`` to emulate
heterogeneity (units-test/get_wait_time.py:29-62,96-140; results
wait_time_{homo,heter}_bc128.csv).

Here the probe wraps :class:`~adapcc_tpu.coordinator.logic.CoordinatorLogic`:
every ``hook_arrive`` stamps a host clock per (step, rank), and skew is
computed from those stamps — the same measurement point as the reference
(the moment a worker's backward pass finishes and it reports ready).
"""

from __future__ import annotations

import csv
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional, Sequence

from adapcc_tpu.coordinator.logic import CoordinatorLogic


class WaitTimeProbe:
    """Records hook-arrival timestamps and derives per-step skew.

    Use as a shim in front of the coordinator: call :meth:`hook_arrive`
    wherever the training loop would call the coordinator's, or call
    :meth:`stamp` directly from a custom hook.
    """

    def __init__(self, logic: Optional[CoordinatorLogic] = None) -> None:
        self.logic = logic
        self._lock = threading.Lock()
        self._stamps: Dict[int, Dict[int, float]] = defaultdict(dict)
        # negotiate() round-trip per (step, rank): the coordinator-overhead
        # component the reference logs to proto/latency_0.0.txt, distinct
        # from worker skew
        self._rpc: Dict[int, Dict[int, float]] = defaultdict(dict)

    def stamp(self, step: int, rank: int, t: Optional[float] = None) -> None:
        with self._lock:
            self._stamps[step][rank] = time.monotonic() if t is None else t

    def hook_arrive(self, step: int, rank: int) -> List[int]:
        """Stamp, then forward to the wrapped coordinator (if any), timing
        the negotiation round-trip."""
        self.stamp(step, rank)
        if self.logic is not None:
            t0 = time.perf_counter()
            active = self.logic.hook_arrive(step, rank)
            with self._lock:
                self._rpc[step][rank] = time.perf_counter() - t0
            return active
        return []

    def wait_time(self, step: int) -> float:
        """max−min arrival across ranks for ``step`` (0.0 if <2 arrivals)."""
        with self._lock:
            stamps = list(self._stamps.get(step, {}).values())
        if len(stamps) < 2:
            return 0.0
        return max(stamps) - min(stamps)

    def rpc_overhead(self, step: int) -> float:
        """Worst per-rank negotiate() round-trip for ``step``.

        Note the leader's rent-or-buy wait is *inside* its round-trip, so
        this upper-bounds pure RPC cost the same way the reference's hook
        timestamps do (commu.py:387-394 time the send_ready_request call).
        """
        with self._lock:
            vals = list(self._rpc.get(step, {}).values())
        return max(vals) if vals else 0.0

    def steps(self) -> List[int]:
        with self._lock:
            return sorted(set(self._stamps) | set(self._rpc))

    def write_csv(self, path: str) -> None:
        """``step,wait_time_s,rpc_overhead_s`` rows — the reference's wait
        CSV shape plus the coordinator-overhead column."""
        with open(path, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["step", "wait_time_s", "rpc_overhead_s"])
            for step in self.steps():
                w.writerow(
                    [
                        step,
                        f"{self.wait_time(step):.6f}",
                        f"{self.rpc_overhead(step):.6f}",
                    ]
                )


def emulate_heterogeneous_steps(
    probe: WaitTimeProbe,
    world_size: int,
    num_steps: int,
    base_compute_s: float = 0.005,
    heter_alpha: float = 1.0,
    slow_ranks: Sequence[int] = (0,),
    step_timeout_s: float = 60.0,
) -> List[float]:
    """Drive ``world_size`` emulated workers through ``num_steps`` hook
    rounds; ``slow_ranks`` compute for ``base_compute_s × heter_alpha``
    (everyone else ``base_compute_s``) — the reference's ``heter_alpha``
    emulation (get_wait_time.py:60,103).  Returns the per-step wait times.

    Workers barrier between steps, like a real DDP loop barriers on the
    gradient allreduce: without it the straggler's lag would accumulate and
    later steps would report the *cumulative* skew, not the per-step skew.
    """
    barrier = threading.Barrier(world_size)
    errors: List[BaseException] = []
    broken: List[int] = []  # ranks that saw the barrier break

    def worker(rank: int) -> None:
        try:
            for step in range(num_steps):
                delay = base_compute_s * (heter_alpha if rank in slow_ranks else 1.0)
                time.sleep(delay)
                probe.hook_arrive(step, rank)
                barrier.wait(timeout=step_timeout_s)
        except threading.BrokenBarrierError:
            # either a peer aborted (its error is in `errors`) or this
            # rank's wait timed out — the caller distinguishes below
            broken.append(rank)
        except BaseException as exc:  # noqa: BLE001 — re-raised in the caller
            errors.append(exc)
            barrier.abort()  # release peers so the caller's join() returns

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(world_size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
    if broken:  # barrier broke with no peer error captured: a wait timed out
        raise TimeoutError(
            f"emulation barrier broke on ranks {sorted(broken)} with no peer "
            f"error — a barrier.wait exceeded step_timeout_s={step_timeout_s}"
        )
    return [probe.wait_time(s) for s in range(num_steps)]
