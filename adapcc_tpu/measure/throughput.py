"""Training-throughput measurement (units-test/throughput.py analog).

The reference's harness times DDP steps with coordinator timestamps and
prints samples/s.  Here the meter wraps any step callable: it blocks on the
returned arrays (so async dispatch doesn't hide device time), keeps per-step
wall times, and reports mean/median throughput excluding warmup (the
reference's first-op CUDA-cache caveat, README.md:106-107 — on TPU the
analog is XLA compile time on step 0).
"""

from __future__ import annotations

import csv
import statistics
import time
from typing import Any, Callable, Dict, List, Optional

import jax


class ThroughputMeter:
    def __init__(self, samples_per_step: int, warmup_steps: int = 1) -> None:
        self.samples_per_step = samples_per_step
        self.warmup_steps = warmup_steps
        self.step_times: List[float] = []

    def timed_step(self, fn: Callable[[], Any]) -> Any:
        """Run one step, blocking until device work completes."""
        t0 = time.perf_counter()
        out = fn()
        jax.block_until_ready(out)
        self.step_times.append(time.perf_counter() - t0)
        return out

    def _measured(self) -> List[float]:
        return self.step_times[self.warmup_steps :]

    def summary(self) -> Dict[str, float]:
        times = self._measured()
        if not times:
            return {"steps": 0.0, "samples_per_s": 0.0, "mean_step_s": 0.0, "median_step_s": 0.0}
        mean = sum(times) / len(times)
        return {
            "steps": float(len(times)),
            "samples_per_s": self.samples_per_step / mean,
            "mean_step_s": mean,
            "median_step_s": statistics.median(times),
        }

    def write_csv(self, path: str) -> None:
        with open(path, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["step", "step_time_s", "samples_per_s"])
            for i, t in enumerate(self.step_times):
                w.writerow([i, f"{t:.6f}", f"{self.samples_per_step / t:.3f}"])

    def run(
        self,
        step_fn: Callable[[int], Any],
        num_steps: int,
        probe: Optional[Any] = None,
        rank: int = 0,
    ) -> Dict[str, float]:
        """Time ``num_steps`` calls of ``step_fn(i)``; optionally stamp a
        :class:`~adapcc_tpu.measure.wait_time.WaitTimeProbe` per step (the
        reference couples both measurements in one harness)."""
        for i in range(num_steps):
            self.timed_step(lambda: step_fn(i))
            if probe is not None:
                probe.stamp(i, rank)
        return self.summary()
