"""Ring attention: exact causal attention over a sequence-sharded axis.

Long-context support the reference does not have (SURVEY §5.7 — its nearest
analog is the chunked pipelining of collectives, allreduce.cu:536-653).  Here
the same communication family is applied to attention itself: each rank holds
a contiguous sequence shard of Q/K/V; K/V blocks rotate around the mesh axis
via ``lax.ppermute`` while a flash-style online softmax accumulates exact
attention — compute on the current block overlaps the ICI transfer of the
next, so the ring is bandwidth-, not latency-bound.

All accumulation in float32; block math in the input dtype (bfloat16 on the
MXU).  No data-dependent control flow — one ``lax.scan`` of ``world`` steps.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

_NEG_INF = -1e30  # finite "masked" score: keeps exp() well-defined


def _ring_perm(world):
    """Receive-from-right rotation: after j shifts a rank holds the K/V
    block originally owned by rank (me + j) % world.  Shared by the dense
    and flash ring paths — one definition of the rotation direction."""
    return [(i, (i - 1) % world) for i in range(world)]


def ring_attention_shard(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    causal: bool = True,
    scale: Optional[float] = None,
    block_impl: str = "dense",
    block_q: int = 128,
    block_k: int = 128,
) -> jnp.ndarray:
    """Per-shard ring attention, for use inside ``shard_map``.

    ``q/k/v``: ``[B, T_local, H, D]`` — this rank's contiguous sequence shard
    (rank r holds global positions ``[r*T_local, (r+1)*T_local)``).
    Returns ``[B, T_local, H, D]`` in ``q.dtype``.

    ``block_impl="flash"`` computes each ring step's block attention with
    the Pallas flash kernel (ops/flash_attention.py) instead of the dense
    ``[T_local, T_local]`` einsum: scores stream through VMEM in MXU tiles,
    so per-device memory stays O(T_local) at long context.  Partial results
    merge by the log-sum-exp combine over the kernel's ``lse`` output.
    """
    if block_impl == "flash":
        return _ring_flash_shard(q, k, v, axis_name, causal, scale, block_q, block_k)
    if block_impl != "dense":
        raise ValueError(f"unknown block_impl {block_impl!r} (dense|flash)")
    B, Tl, H, D = q.shape
    world = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    if scale is None:
        scale = 1.0 / (D**0.5)

    qf = q.astype(jnp.float32) * scale
    q_pos = me * Tl + jnp.arange(Tl)  # global query positions

    perm = _ring_perm(world)

    def step(carry, j):
        o, m, l, k_blk, v_blk = carry
        src = (me + j) % world
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", qf, k_blk.astype(jnp.float32)
        )  # [B,H,Tl,Tl]
        if causal:
            k_pos = src * Tl + jnp.arange(Tl)
            mask = k_pos[None, None, None, :] <= q_pos[None, None, :, None]
            s = jnp.where(mask, s, _NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1))  # [B,H,Tl]
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])  # [B,H,Tl,Tl]
        l_new = l * alpha + jnp.sum(p, axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32)
        )

        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return (o_new, m_new, l_new, k_nxt, v_nxt), None

    o0 = jnp.zeros((B, H, Tl, D), jnp.float32)
    m0 = jnp.full((B, H, Tl), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tl), jnp.float32)
    (o, _, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v), jnp.arange(world))

    # fully-masked rows (can't happen for causal self-attention, where every
    # query sees itself) would have l == 0; guard the divide anyway
    out = o / jnp.maximum(l, 1e-20)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def _ring_flash_shard(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    causal: bool,
    scale: Optional[float],
    block_q: int,
    block_k: int,
) -> jnp.ndarray:
    """Flash-ring: each ring step runs the blockwise Pallas kernel on the
    K/V block currently held, then merges via log-sum-exp using the
    kernel's ``lse`` output.  Per causal step the block is one of three
    static programs (``lax.switch`` on the rotating source rank): fully
    visible (past block), diagonal (own block, causal mask), or skipped
    (future block contributes ``lse = −inf``).

    The whole scan runs in the kernel's ``[B·H, T_local, D]`` layout —
    transposed once on entry and once on exit, never per step (the public
    wrapper's per-call layout round-trip would be inverted immediately by
    the merge)."""
    # the kernel-layout entry point, deliberately: one transpose per ring,
    # not one per step
    from adapcc_tpu.ops.flash_attention import _flash_bhtd_lse

    B, Tl, H, D = q.shape
    world = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    if scale is None:
        scale = float(1.0 / (D**0.5))
    perm = _ring_perm(world)
    to_bhtd = lambda x: x.transpose(0, 2, 1, 3).reshape(B * H, Tl, D)  # noqa: E731
    qf, kf, vf = to_bhtd(q), to_bhtd(k), to_bhtd(v)

    def full_block(qf, kb, vb):
        return _flash_bhtd_lse(qf, kb, vb, scale, False, block_q, block_k, None)

    def diag_block(qf, kb, vb):
        return _flash_bhtd_lse(qf, kb, vb, scale, True, block_q, block_k, None)

    def skip_block(qf, kb, vb):
        return jnp.zeros_like(qf), jnp.full((B * H, Tl), _NEG_INF, jnp.float32)

    def step(carry, j):
        o_acc, m, l, k_blk, v_blk = carry
        src = (me + j) % world
        if causal:
            idx = jnp.where(src == me, 1, jnp.where(src < me, 0, 2))
            o_blk, lse_blk = lax.switch(
                idx, (full_block, diag_block, skip_block), qf, k_blk, v_blk
            )
        else:
            o_blk, lse_blk = full_block(qf, k_blk, v_blk)

        # log-sum-exp merge: o_blk is normalized within its block, so its
        # weight in the running estimate is exp(lse_blk − m_new)
        m_new = jnp.maximum(m, lse_blk)
        alpha = jnp.exp(m - m_new)
        w = jnp.exp(lse_blk - m_new)
        o_acc = o_acc * alpha[..., None] + o_blk.astype(jnp.float32) * w[..., None]
        l_new = l * alpha + w

        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return (o_acc, m_new, l_new, k_nxt, v_nxt), None

    o0 = jnp.zeros((B * H, Tl, D), jnp.float32)
    m0 = jnp.full((B * H, Tl), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B * H, Tl), jnp.float32)
    (o, _, l, _, _), _ = lax.scan(step, (o0, m0, l0, kf, vf), jnp.arange(world))
    out = o / jnp.maximum(l, 1e-20)[..., None]
    return out.reshape(B, H, Tl, D).transpose(0, 2, 1, 3).astype(q.dtype)


def ring_attention(
    mesh: Mesh,
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = "ranks",
    causal: bool = True,
    scale: Optional[float] = None,
    block_impl: str = "dense",
    block_q: int = 128,
    block_k: int = 128,
) -> jnp.ndarray:
    """Global-view convenience wrapper: ``q/k/v [B, T, H, D]`` with ``T``
    divisible by the mesh axis size; shards the sequence dim, runs the ring,
    returns the full ``[B, T, H, D]`` result.  ``block_impl="flash"`` runs
    each step's block attention on the Pallas flash kernel."""
    spec = P(None, axis_name, None, None)
    fn = shard_map(
        partial(
            ring_attention_shard, axis_name=axis_name, causal=causal, scale=scale,
            block_impl=block_impl, block_q=block_q, block_k=block_k,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def reference_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, causal: bool = True
) -> jnp.ndarray:
    """Plain full attention — the correctness oracle for the ring."""
    B, T, H, D = q.shape
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
    s = s / (D**0.5)
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
