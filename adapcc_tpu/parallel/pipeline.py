"""Deprecated location: the forward pipeline block moved to
``adapcc_tpu.pipe.forward`` when the pipeline-parallel training plane
landed (docs/PIPELINE.md).  This shim keeps old imports working and
warns ONCE per process — parity between the two spellings is pinned in
``tests/test_pipe.py``."""

from __future__ import annotations

import warnings
from typing import Any, Callable

import jax.numpy as jnp
from jax.sharding import Mesh

from adapcc_tpu.pipe.forward import pipeline_apply as _pipeline_apply

_MOVED_WARNED = False


def pipeline_apply(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stacked_params: Any,
    batch: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "stages",
    num_microbatches: int = 4,
) -> jnp.ndarray:
    """Deprecated alias of :func:`adapcc_tpu.pipe.forward.pipeline_apply`.
    Warns once — a long loop must not drown in a warning per call — then
    delegates unchanged."""
    global _MOVED_WARNED
    if not _MOVED_WARNED:
        _MOVED_WARNED = True
        warnings.warn(
            "adapcc_tpu.parallel.pipeline moved to adapcc_tpu.pipe.forward; "
            "import pipeline_apply from there",
            DeprecationWarning,
            stacklevel=2,
        )
    return _pipeline_apply(
        stage_fn,
        stacked_params,
        batch,
        mesh,
        axis_name=axis_name,
        num_microbatches=num_microbatches,
    )
