"""Sequence-parallel GPT-2 training: the whole step in one shard_map.

Long-context training the reference cannot do (SURVEY §5.7 — no sequence
parallelism anywhere in it).  The global batch ``[B, T]`` is sharded over a
mesh axis along ``T``; every layer of the model is position-wise except
attention, which crosses shards via the ring or Ulysses SP programs
(``GPT2Config.sp_axis`` / ``sp_impl``, models/gpt2.py), optionally on the
Pallas flash block kernel (``attention="flash"``).  The loss handles the
shard-boundary target with one ``[B]``-sized ppermute (``lm_loss_sp``) and
the parameter gradients are psum-reduced, so one jitted program trains on a
sequence ``world×`` longer than a single device could hold.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from adapcc_tpu.models.gpt2 import GPT2, lm_loss_sp


def gpt2_sp_loss_and_grad(
    model: GPT2, mesh: Mesh, axis_name: str = "ranks",
    data_axis: Optional[str] = None, loss: str = "dense",
    loss_block: int = 1024,
) -> Callable[[Any, jnp.ndarray], Tuple[jnp.ndarray, Any]]:
    """Jitted ``(params, tokens [B, T]) → (loss, grads)`` with the sequence
    sharded over ``axis_name``; params replicated, grads psum-replicated.

    ``model.cfg.sp_axis`` must equal ``axis_name`` (the attention layers run
    the cross-shard SP program on that axis) and ``T`` must divide by the
    axis size.

    With ``data_axis`` (a 2D ``(data, sp)`` mesh — the production
    long-context layout) the batch dim is additionally sharded over the
    data axis: each data row runs an independent SP ring on its batch
    shard, losses average over rows, and gradients sync across BOTH axes
    — DP × SP in one jitted program.
    """
    cfg = model.cfg
    if cfg.sp_axis != axis_name:
        raise ValueError(
            f"model.cfg.sp_axis {cfg.sp_axis!r} must equal the mesh axis "
            f"{axis_name!r} the step is sharded over"
        )
    if data_axis is not None and data_axis not in mesh.axis_names:
        raise ValueError(f"data_axis {data_axis!r} not in mesh axes {mesh.axis_names}")
    if loss not in ("dense", "chunked"):
        raise ValueError(f"loss must be 'dense' or 'chunked', got {loss!r}")
    use_chunked = loss == "chunked"

    def shard_step(params, tokens):
        if use_chunked:
            # long-context × long-vocab: no [B, T_local, V] logits either
            from adapcc_tpu.models.gpt2 import lm_loss_sp_chunked

            def loss_fn(p):
                hidden = model.apply(p, tokens, return_hidden=True)
                return lm_loss_sp_chunked(
                    hidden, p["params"]["wte"]["embedding"], tokens, axis_name,
                    block=min(loss_block, cfg.vocab_size),
                    compute_dtype=cfg.dtype,
                )
        else:

            def loss_fn(p):
                logits = model.apply(p, tokens)
                return lm_loss_sp(logits, tokens, axis_name)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # lm_loss_sp psums in the FORWARD pass, and psum transposes to psum
        # under shard_map — so each shard's backward already carries a
        # world× factor on its local contribution.  pmean (psum/world)
        # cancels it exactly; verified against the unsharded gradient in
        # tests/test_gpt2_sp.py.
        # with a data axis the per-data-shard grads also average — one
        # pmean over both axes (sum/(sp·dp)) instead of two all-reduce rounds
        axes = (axis_name,) if data_axis is None else (axis_name, data_axis)
        grads = jax.tree_util.tree_map(lambda g: lax.pmean(g, axes), grads)
        if data_axis is not None:
            loss = lax.pmean(loss, data_axis)
        return loss, grads

    batch_spec = (
        P(None, axis_name) if data_axis is None else P(data_axis, axis_name)
    )
    fn = jax.shard_map(
        shard_step,
        mesh=mesh,
        in_specs=(P(), batch_spec),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(fn)


def gpt2_sp_train_step(
    model: GPT2, tx, mesh: Mesh, axis_name: str = "ranks",
    data_axis: Optional[str] = None, loss: str = "dense",
    loss_block: int = 1024,
) -> Callable:
    """Jitted ``(params, opt_state, tokens) → (params, opt_state, loss)``
    full SP (or DP×SP, with ``data_axis``) training step."""
    import optax

    loss_and_grad = gpt2_sp_loss_and_grad(
        model, mesh, axis_name, data_axis, loss, loss_block
    )

    @jax.jit
    def step(params, opt_state, tokens):
        loss, grads = loss_and_grad(params, tokens)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return step
