"""Parallelism strategies beyond DP: sequence (ring attention), tensor,
pipeline, and expert parallelism.

The reference is a communication library whose only first-class strategy is
DP (SURVEY §2.3) — TP/PP/SP are absent and ALLTOALL is an unimplemented stub
(commu.py:31-33, trans.h:27-36).  On TPU these axes are first-class: every
strategy here is expressed as shardings + collectives over a
``jax.sharding.Mesh`` axis so XLA schedules the ICI traffic.
"""

from adapcc_tpu.parallel.ulysses import ulysses_attention, ulysses_attention_shard
from adapcc_tpu.parallel.gpt2_sp import gpt2_sp_loss_and_grad, gpt2_sp_train_step
from adapcc_tpu.parallel.ring_attention import (
    ring_attention,
    ring_attention_shard,
)
from adapcc_tpu.parallel.tensor import (
    column_parallel_dense,
    gpt2_tp_rules,
    row_parallel_dense,
    tree_shardings,
)
from adapcc_tpu.pipe.forward import pipeline_apply
from adapcc_tpu.parallel.expert import expert_parallel_moe
from adapcc_tpu.parallel.fsdp import (
    Zero1Optimizer,
    fsdp_shardings,
    fsdp_tp_shardings,
    fsdp_tp_train_step,
    fsdp_train_step,
    shard_fsdp,
    zero1_train_step,
)

__all__ = [
    "gpt2_sp_loss_and_grad",
    "gpt2_sp_train_step",
    "ring_attention",
    "ring_attention_shard",
    "ulysses_attention",
    "ulysses_attention_shard",
    "column_parallel_dense",
    "row_parallel_dense",
    "gpt2_tp_rules",
    "tree_shardings",
    "pipeline_apply",
    "expert_parallel_moe",
    "Zero1Optimizer",
    "fsdp_shardings",
    "fsdp_tp_shardings",
    "fsdp_tp_train_step",
    "fsdp_train_step",
    "shard_fsdp",
    "zero1_train_step",
]
