"""Tensor parallelism: Megatron-style sharded matmuls, the TPU way.

Two complementary forms:

1. **GSPMD shardings** (:func:`gpt2_tp_rules` + :func:`tree_shardings`):
   annotate parameter pytrees with ``NamedSharding`` by path pattern and let
   XLA insert the all-gathers/reduce-scatters over ICI — the idiomatic pjit
   path.  qkv/fc kernels shard their output dim (column parallel), residual
   projections shard their input dim (row parallel), so a block needs exactly
   one collective pair per sublayer.

2. **Explicit shard_map primitives** (:func:`column_parallel_dense` /
   :func:`row_parallel_dense`): for code already inside a ``shard_map`` body
   (e.g. combined with ring attention), the classic column→row pairing where
   the column output stays sharded and the row matmul finishes with one
   ``psum``.
"""

from __future__ import annotations

import re
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def column_parallel_dense(
    x: jnp.ndarray, w_shard: jnp.ndarray, b_shard: Optional[jnp.ndarray] = None
) -> jnp.ndarray:
    """``x [..., Din] @ w_shard [Din, Dout/world]`` → sharded ``[..., Dout/world]``.

    Input is replicated across the TP axis; output columns stay sharded —
    feed straight into :func:`row_parallel_dense` with no collective.
    """
    y = x @ w_shard
    if b_shard is not None:
        y = y + b_shard
    return y


def row_parallel_dense(
    x_shard: jnp.ndarray,
    w_shard: jnp.ndarray,
    axis_name: str,
    b: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """``x_shard [..., Din/world] @ w_shard [Din/world, Dout]`` → ``psum`` →
    replicated ``[..., Dout]``.  The single collective of the column→row pair.
    Bias (if any) must be the full row and is added once, after the psum.
    """
    y = lax.psum(x_shard @ w_shard, axis_name)
    if b is not None:
        y = y + b
    return y


#: (path-regex, PartitionSpec) rules for the flax GPT-2 in models/gpt2.py.
def gpt2_tp_rules(tp_axis: str = "model") -> List[Tuple[str, P]]:
    """Megatron sharding for GPT-2 params: attention qkv + MLP fc are column
    parallel (kernel ``[Din, Dout]`` → shard ``Dout``), both residual ``proj``
    kernels are row parallel (shard ``Din``), embeddings shard the vocab /
    feature dim, everything else (LayerNorm, biases of row layers) replicated.
    """
    return [
        (r".*attn/qkv/kernel", P(None, tp_axis)),
        (r".*attn/qkv/bias", P(tp_axis)),
        (r".*attn/proj/kernel", P(tp_axis, None)),
        (r".*/fc/kernel", P(None, tp_axis)),
        (r".*/fc/bias", P(tp_axis)),
        (r".*h\d+/proj/kernel", P(tp_axis, None)),
        (r".*wte/embedding", P(tp_axis, None)),
    ]


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def tree_shardings(
    tree: Any, mesh: Mesh, rules: Sequence[Tuple[str, P]]
) -> Any:
    """NamedSharding pytree for ``tree``: first rule whose regex fully matches
    the leaf's ``a/b/c`` path wins; unmatched leaves are replicated.  A rule
    only applies if the spec divides the leaf's shape evenly — otherwise the
    leaf falls back to replicated (same lenient behavior XLA would need
    padding for)."""
    def assign(path, leaf):
        name = _path_str(path)
        for pat, spec in rules:
            if re.fullmatch(pat, name):
                ok = True
                for dim, axes in enumerate(spec):
                    if axes is None:
                        continue
                    axis_names = axes if isinstance(axes, tuple) else (axes,)
                    size = 1
                    for a in axis_names:
                        size *= mesh.shape[a]
                    if dim >= leaf.ndim or leaf.shape[dim] % size != 0:
                        ok = False
                        break
                if ok:
                    return NamedSharding(mesh, spec)
                break
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(assign, tree)


def shard_tree(tree: Any, mesh: Mesh, rules: Sequence[Tuple[str, P]]) -> Any:
    """Place ``tree``'s leaves on ``mesh`` per ``rules`` (device_put)."""
    shardings = tree_shardings(tree, mesh, rules)
    return jax.tree_util.tree_map(jax.device_put, tree, shardings)
