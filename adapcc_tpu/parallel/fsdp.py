"""Fully-sharded data parallelism (FSDP / ZeRO), the TPU way.

The reference's only first-class strategy is replicated DP (SURVEY §2.3):
every rank holds full params + full optimizer state and allreduces full
gradients (train_ddp.py:35-41).  At modern model sizes that wastes
``(world-1)/world`` of HBM on redundant state.  This module adds the two
standard remedies as first-class strategies, both expressed as shardings on
a ``jax.sharding.Mesh`` axis so XLA schedules the ICI traffic:

1. **FSDP / ZeRO-3 via GSPMD** (:func:`fsdp_shardings`,
   :func:`fsdp_train_step`): every parameter leaf is sharded over the data
   axis along its largest divisible dimension; optimizer state inherits the
   same sharding.  XLA inserts the all-gather before each use and the
   reduce-scatter after each gradient — the scaling-book "weight sharding"
   recipe, zero hand-written collectives.

2. **ZeRO-1** (:class:`Zero1Optimizer`): params stay replicated (so the
   forward is untouched and composes with the adaptive gradient hook), but
   the *optimizer state* lives sharded: gradients are reduce-scattered onto
   a flat ``[N/world]`` shard, the optax update runs on that shard only,
   and the updated parameter slice is all-gathered back.  Optimizer memory
   drops by ``1/world`` and the gradient sync becomes the optimal
   reduce-scatter + all-gather pair (bandwidth-equal to one allreduce).

Both paths are pure functions over (params, opt_state, batch) and compose
with ``jax.jit`` donation for in-place updates.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from adapcc_tpu.comm.mesh import RANKS_AXIS


# -- FSDP (ZeRO-3) via GSPMD shardings ----------------------------------------


def _leaf_spec(
    shape: Tuple[int, ...], world: int, min_elems: int, axis_name: str
) -> P:
    """PartitionSpec sharding the largest dim divisible by ``world``.

    Small leaves (biases, layernorm scales) stay replicated — sharding them
    buys nothing and forces XLA to all-gather scalars.
    """
    if not shape or int(np.prod(shape)) < min_elems:
        return P()
    # largest divisible dim wins; ties go to the later (usually output) dim
    best, best_size = None, 0
    for i, d in enumerate(shape):
        if d % world == 0 and d >= best_size:
            best, best_size = i, d
    if best is None:
        return P()
    spec: list = [None] * len(shape)
    spec[best] = axis_name
    return P(*spec)


def fsdp_shardings(
    params: Any,
    mesh: Mesh,
    axis_name: str = RANKS_AXIS,
    min_shard_elems: int = 2**14,
) -> Any:
    """Pytree of ``NamedSharding`` sharding each leaf over the data axis.

    The same tree annotates optimizer state: optax states mirror the param
    tree structure, so mapping the leaf rule over ``tx.init(params)`` gives
    each moment buffer the sharding of its parameter.
    """
    world = mesh.shape[axis_name]

    def one(leaf):
        return NamedSharding(
            mesh, _leaf_spec(jnp.shape(leaf), world, min_shard_elems, axis_name)
        )

    return jax.tree_util.tree_map(one, params)


def shard_fsdp(
    params: Any,
    mesh: Mesh,
    axis_name: str = RANKS_AXIS,
    min_shard_elems: int = 2**14,
) -> Any:
    """Device-put ``params`` into their FSDP shardings (1/world HBM each)."""
    return jax.device_put(
        params, fsdp_shardings(params, mesh, axis_name, min_shard_elems)
    )


def fsdp_train_step(
    loss_fn: Callable[[Any, Any], jnp.ndarray],
    tx: optax.GradientTransformation,
    mesh: Mesh,
    axis_name: str = RANKS_AXIS,
    donate: bool = True,
    min_shard_elems: int = 2**14,
) -> Callable:
    """Compile a full FSDP train step: params + optimizer state sharded over
    the data axis, batch sharded over the same axis, XLA-inserted
    all-gather/reduce-scatter over ICI.

    Returns ``step(params, opt_state, batch) -> (params, opt_state, loss)``
    where the sharded layouts are preserved across calls (out_shardings =
    in_shardings, so the update is a stable fixed point under donation).
    """

    return _sharded_train_step(
        loss_fn, tx, mesh,
        lambda tree: fsdp_shardings(tree, mesh, axis_name, min_shard_elems),
        batch_spec=P(axis_name),
        donate=donate,
    )


def _sharded_train_step(
    loss_fn: Callable,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    shardings_of: Callable[[Any], Any],
    batch_spec: P,
    donate: bool,
) -> Callable:
    """Shared engine for every GSPMD sharded-state step variant: state lives
    in the layout ``shardings_of`` assigns, out_shardings = in_shardings so
    the update is a stable fixed point under donation."""

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    def compile_for(params: Any, opt_state: Any) -> Callable:
        p_sh = shardings_of(params)
        # optax state mirrors the param tree per-transform, so the same rule
        # tree-maps over it: moment buffers inherit their parameter's layout,
        # scalars (count) fall to replicated
        o_sh = shardings_of(opt_state)
        b_sh = NamedSharding(mesh, batch_spec)
        return jax.jit(
            step,
            in_shardings=(p_sh, o_sh, b_sh),
            out_shardings=(p_sh, o_sh, NamedSharding(mesh, P())),
            donate_argnums=(0, 1) if donate else (),
        )

    cache: dict = {}

    def stepper(params, opt_state, batch):
        # keyed by tree structure + leaf shapes: a new model layout gets a
        # new program instead of silently reusing stale shardings
        key = _tree_key(params)
        if key not in cache:
            cache[key] = compile_for(params, opt_state)
        return cache[key](params, opt_state, batch)

    return stepper


def _tree_key(tree: Any) -> Tuple:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return (treedef, tuple((jnp.shape(l), jnp.result_type(l)) for l in leaves))


# -- FSDP × TP: 2D sharding over a (data, model) mesh -------------------------


def fsdp_tp_shardings(
    params: Any,
    mesh: Mesh,
    tp_rules: Any,
    data_axis: str = "data",
    min_shard_elems: int = 2**14,
) -> Any:
    """2D layout: Megatron TP rules claim their dims over the model axis,
    then FSDP shards the largest *free* divisible dim over the data axis —
    the scaling-book "FSDP + tensor parallelism" composition.  A leaf whose
    only divisible dim is TP-claimed stays 1D-sharded; small leaves get no
    additional data-axis sharding (TP-ruled small leaves keep their TP
    spec, unruled ones stay replicated).
    """
    from adapcc_tpu.parallel.tensor import tree_shardings

    tp = tree_shardings(params, mesh, tp_rules)
    data_size = mesh.shape[data_axis]

    def combine(leaf, tp_sh):
        shape = jnp.shape(leaf)
        spec = list(tp_sh.spec) + [None] * (len(shape) - len(tp_sh.spec))
        if shape and int(np.prod(shape)) >= min_shard_elems:
            best, best_size = None, 0
            for i, d in enumerate(shape):
                if spec[i] is not None:
                    continue
                if d % data_size == 0 and d >= best_size:
                    best, best_size = i, d
            if best is not None:
                spec[best] = data_axis
        return NamedSharding(mesh, P(*spec))

    return jax.tree_util.tree_map(combine, params, tp)


def fsdp_tp_train_step(
    loss_fn: Callable[[Any, Any], jnp.ndarray],
    tx: optax.GradientTransformation,
    mesh: Mesh,
    tp_rules: Any,
    data_axis: str = "data",
    donate: bool = True,
    min_shard_elems: int = 2**14,
) -> Callable:
    """FSDP over ``data_axis`` × tensor parallel per ``tp_rules``: params and
    optimizer state live 2D-sharded, batch shards over the data axis, and XLA
    inserts the per-axis collectives (all-gather on use over data, psum of
    row-parallel partials over model) — one jitted program on one mesh.
    """
    return _sharded_train_step(
        loss_fn, tx, mesh,
        lambda tree: fsdp_tp_shardings(
            tree, mesh, tp_rules, data_axis, min_shard_elems
        ),
        batch_spec=P(data_axis),
        donate=donate,
    )


# -- ZeRO-1: sharded optimizer state over the flat gradient vector ------------


class _FlatMeta(NamedTuple):
    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    sizes: Tuple[int, ...]
    dtypes: Tuple[Any, ...]
    total: int
    padded: int


def _flatten_meta(params: Any, world: int, align: int = 1) -> _FlatMeta:
    """``align`` rounds the per-rank shard length up to a multiple (the
    Pallas ring kernels move whole VMEM tiles, so the ring path needs
    tile-aligned shards; the XLA path keeps align=1)."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    shapes = tuple(tuple(l.shape) for l in leaves)
    sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
    dtypes = tuple(l.dtype for l in leaves)
    total = int(sum(sizes))
    shard = -(-total // world)
    shard = -(-shard // align) * align
    return _FlatMeta(treedef, shapes, sizes, dtypes, total, world * shard)


def _flatten(tree: Any, meta: _FlatMeta, dtype=jnp.float32) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(tree)
    flat = jnp.concatenate([jnp.ravel(l).astype(dtype) for l in leaves])
    return jnp.pad(flat, (0, meta.padded - meta.total))


def _unflatten(flat: jnp.ndarray, meta: _FlatMeta) -> Any:
    parts = []
    off = 0
    for shape, size, dt in zip(meta.shapes, meta.sizes, meta.dtypes):
        parts.append(flat[off : off + size].reshape(shape).astype(dt))
        off += size
    return jax.tree_util.tree_unflatten(meta.treedef, parts)


def zero1_apply_shard(
    tx: optax.GradientTransformation,
    master: jnp.ndarray,
    opt_state: Any,
    g_shard: jnp.ndarray,
    meta: _FlatMeta,
    axis_name: str,
    ring: bool = False,
    ring_interpret: bool = False,
    ring_chunk_bytes: Optional[int] = None,
    overlap_chunks: int = 1,
):
    """The in-shard ZeRO-1 update cycle, shared by every composition site
    (Zero1Optimizer.apply, zero1_train_step, DDPTrainer(zero1=True)):
    optax update on this rank's flat ``[N/world]`` slice, then one
    ``all_gather`` rebuilds the replicated params.  Runs inside shard_map;
    ``master``/``opt_state`` enter WITHOUT their leading shard dim.

    ``ring=True`` rides the Pallas ICI ring all-gather instead of XLA's
    (the hand-tuned data plane): rank ``r`` then owns chunk ``(r+1) % world``
    (the ring's natural ownership), and the gathered rank-ordered rows are
    rolled back into chunk order before unflattening.  ``ring_chunk_bytes``
    is the staging granularity handed down from the strategy plane (None =
    default; payloads above it stream through HBM staging).

    ``overlap_chunks > 1`` (XLA path only — the Pallas ring streams its own
    chunks) splits the param all-gather into that many independent
    collectives over contiguous shard slices, so XLA's async collectives
    overlap later slices' gathers with the unflatten/cast compute — and, in
    a scanned multi-step program, with the next step's forward — of earlier
    slices (docs/OVERLAP.md §3).  The gathered bytes and their layout are
    identical: chunk ``j`` of every rank lands in the same flat positions,
    so results are bitwise-equal to the single-collective gather.
    """
    updates, opt_state = tx.update(g_shard, opt_state, master)
    master = optax.apply_updates(master, updates)
    if ring:
        from adapcc_tpu.comm.pallas_ring import ring_all_gather_shard

        world = meta.padded // master.size
        gathered = ring_all_gather_shard(
            master, world, axis_name, interpret=ring_interpret,
            chunk_bytes=ring_chunk_bytes,
        )
        # gathered[i] = rank i's payload = chunk (i+1) % world
        flat_p = jnp.roll(gathered, 1, axis=0).reshape(-1)
    elif overlap_chunks > 1:
        from adapcc_tpu.ddp.overlap import even_chunk_bounds

        gathered = [
            lax.all_gather(master[off : off + n], axis_name)  # [world, n]
            for off, n in even_chunk_bounds(master.size, overlap_chunks)
        ]
        flat_p = jnp.concatenate(gathered, axis=1).reshape(-1)
    else:
        flat_p = lax.all_gather(master, axis_name).reshape(-1)
    return master, opt_state, _unflatten(flat_p, meta)


def local_grad_shard(
    flat_g: jnp.ndarray, meta: _FlatMeta, world: int, axis_name: str,
    offset: int = 0,
) -> jnp.ndarray:
    """This rank's slice of an already-replicated flat gradient — a free
    local read, no collective.  ``offset=1`` selects the ring path's chunk
    ownership (rank ``r`` owns chunk ``(r+1) % world``)."""
    shard_len = meta.padded // world
    idx = lax.axis_index(axis_name)
    if offset:
        idx = (idx + offset) % world
    return lax.dynamic_index_in_dim(
        flat_g.reshape(world, shard_len), idx, keepdims=False
    )


class Zero1Optimizer:
    """Optimizer-state-sharded DDP (ZeRO stage 1) over one mesh axis.

    Params stay replicated; the optimizer state is a flat ``[N/world]``
    fp32 shard per rank.  Each step, inside one ``shard_map`` program:

    1. ``psum_scatter`` the flat gradient → this rank's ``[N/world]`` slice
       (bandwidth-optimal: the reduce-scatter half of a ring allreduce);
    2. optax update on the slice against this rank's opt-state shard —
       1/world of the adam moment memory and FLOPs per rank;
    3. ``all_gather`` the updated parameter slice → replicated new params
       (the other half of the ring).

    The fp32 flat master copy also gives mixed-precision training a proper
    master-weight update for bf16 params for free.

    ``ring=True`` swaps both collectives onto the Pallas ICI ring kernels
    (:mod:`adapcc_tpu.comm.pallas_ring`) — the hand-tuned data plane, the
    TPU analog of the reference's CUDA chunk pipeline (trans.cu:58-100).
    The ring's natural chunk ownership (rank ``r`` finishes reduce-scatter
    holding chunk ``(r+1) % world``) is adopted as the shard layout, so no
    extra rotation hop is paid at step time; shards are VMEM-tile aligned.
    Checkpoints of ring and non-ring masters are NOT interchangeable (the
    row→chunk mapping differs).
    """

    def __init__(
        self,
        tx: optax.GradientTransformation,
        mesh: Mesh,
        axis_name: str = RANKS_AXIS,
        ring: bool = False,
        ring_interpret: Optional[bool] = None,
        ring_chunk_bytes: Optional[int] = None,
        wire_dtype: Optional[str] = None,
        tuner: Optional[Any] = None,
        overlap: str = "off",
        overlap_chunk_bytes: Optional[int] = None,
    ) -> None:
        self.tx = tx
        self.mesh = mesh
        self.axis_name = axis_name
        self.world = mesh.shape[axis_name]
        self.ring = ring
        # overlapped collectives (docs/OVERLAP.md §3): "bucket" splits the
        # gradient reduce-scatter and the param all-gather into independent
        # per-chunk collectives at ``overlap_chunk_bytes`` granularity
        # (default: the reference's 4 MB chunk, env-overridable through the
        # ring chunk resolver) so XLA interleaves them with surrounding
        # compute.  Identical bytes, identical layout — checkpoints are
        # unaffected.  The value arrives caller-resolved: DDPTrainer and
        # train_ddp apply the ADAPCC_OVERLAP precedence *before* passing it
        # down, because the env may legally pin "microbatch" for the
        # trainer's scan while this optimizer's collectives stay "off"
        if overlap == "microbatch":
            raise ValueError(
                "Zero1Optimizer has no microbatch axis to pipeline over — "
                "microbatch overlap lives in DDPTrainer's accumulation "
                "scan (overlap='microbatch' there composes with zero1=True)"
            )
        if overlap not in ("off", "bucket"):
            raise ValueError(
                f"overlap={overlap!r}: expected 'off' or 'bucket'"
            )
        self.overlap = overlap
        if self.overlap == "bucket" and ring:
            raise ValueError(
                "overlap='bucket' with ring=True would chunk the Pallas "
                "ring's payload twice: the ring kernel already streams "
                "chunk_bytes-sized tiles (ring_chunk_bytes steers it); "
                "use one chunking plane or the other"
            )
        self.overlap_chunk_bytes = overlap_chunk_bytes
        # measurement-driven chunk choice (adapcc_tpu/tuner): when the ring
        # staging granularity is left open and ADAPCC_TUNER=choose, init()
        # asks the tuner's policy for it (sized to the actual flat master)
        # instead of falling to the default.  Explicit ring_chunk_bytes and
        # the ADAPCC_RING_CHUNK_BYTES env keep their precedence — the tuner
        # only fills the knob nobody pinned.
        self.tuner = tuner
        #: the TunedPlan behind an adopted chunk (None = not tuner-chosen)
        self.tuned_plan = None
        if ring_interpret is None:
            ring_interpret = jax.devices()[0].platform != "tpu"
        self.ring_interpret = ring_interpret
        # gradient-sync wire codec (quant registry; None/"off" = payload
        # dtype, ADAPCC_WIRE_DTYPE overrides — the ring_chunk_bytes
        # precedence).  zero1_train_step applies the codec's wire value to
        # each rank's gradient contribution before the reduce-scatter;
        # resolved eagerly so a typo'd codec dies at construction
        from adapcc_tpu.quant import resolve_wire_dtype

        self.wire_dtype = resolve_wire_dtype(wire_dtype)
        #: staging granularity for the ring collectives (strategy plane's
        #: synthesized chunk_bytes; None = default, env-overridable for
        #: sweeps).  Payloads above it ride the HBM-streaming kernel, so
        #: gradient size is bounded by HBM, not VMEM — chunk *layout* is
        #: unaffected (the executed tile divides the shard), so this knob
        #: never invalidates a checkpoint.
        self.ring_chunk_bytes = ring_chunk_bytes
        self._meta: Optional[_FlatMeta] = None
        self._compiled: Optional[Callable] = None

    def _align(self) -> int:
        if not self.ring:
            return 1
        from adapcc_tpu.comm.pallas_ring import _tile_elems

        return _tile_elems(jnp.float32)

    def overlap_chunks(self, shard_len: Optional[int] = None) -> int:
        """How many independent collectives the overlapped RS/AG pair
        splits into: 1 when overlap is off, else the fp32 shard's byte
        count over ``overlap_chunk_bytes`` (env-overridable through the
        ring chunk resolver — one precedence ladder for every chunk knob).
        ``shard_len`` defaults to the initialized flat master's."""
        if self.overlap != "bucket":
            return 1
        if shard_len is None:
            if self._meta is None:
                raise RuntimeError("call init(params) first")
            shard_len = self._meta.padded // self.world
        from adapcc_tpu.ddp.overlap import overlap_chunk_count

        return overlap_chunk_count(int(shard_len) * 4, self.overlap_chunk_bytes)

    def tuning_key(self):
        """The tuning-database cell this optimizer's ring collectives
        execute, or None off the ring path / before ``init``.  Callers
        timing zero1 steps record into THIS key — the tuner-chosen cell
        when the tuner picked the chunk, else the executed configuration
        via the kernel's own planner — so the measurements land where the
        next ``init()``'s ``choose("zero1_ring", ...)`` will look (the
        loop closes across runs through the persisted database)."""
        if self.tuner is None or self._meta is None or not self.ring:
            return None
        if self.tuned_plan is not None:
            return self.tuned_plan.key
        from adapcc_tpu.comm.pallas_ring import plan_ring_schedule
        from adapcc_tpu.tuner.policy import NO_CHUNK

        plan = plan_ring_schedule(
            self._meta.padded, jnp.float32, self.world, self.ring_chunk_bytes
        )
        return self.tuner.key_for(
            "zero1_ring", self._meta.padded * 4, plan.path,
            # same key vocabulary as the candidate grid: vmem is one cell
            NO_CHUNK if plan.path == "vmem" else plan.chunk_bytes, "off",
        )

    def init(self, params: Any) -> Tuple[jnp.ndarray, Any]:
        """Returns ``(flat_master [world, N/world] fp32, opt_state shard)``.

        Both carry a leading ``[world]`` dim sharded over the mesh axis, so
        each device holds exactly its slice.  In ring mode row ``r`` holds
        chunk ``(r+1) % world`` (the ring's ownership); the XLA path keeps
        the identity layout.
        """
        meta = self._meta = _flatten_meta(params, self.world, self._align())
        self._compiled = None  # re-init with a new tree invalidates the program
        if (
            self.ring
            and self.ring_chunk_bytes is None
            and self.tuner is not None
            and self.tuner.choosing
        ):
            # the ring collectives move the whole padded flat master; size
            # the cell to that payload.  "zero1_ring" cells carry only the
            # chunk axis (no codec — the wire dtype is a separate knob)
            self.tuned_plan = self.tuner.choose("zero1_ring", meta.padded * 4)
            self.ring_chunk_bytes = self.tuned_plan.chunk_bytes
        flat = _flatten(params, meta)
        shard_len = meta.padded // self.world
        master = flat.reshape(self.world, shard_len)
        if self.ring:
            # row r ← chunk (r+1) % world
            master = jnp.roll(master, -1, axis=0)
        opt_state = jax.vmap(self.tx.init)(master)
        sharding = NamedSharding(self.mesh, P(self.axis_name))
        return (
            jax.device_put(master, sharding),
            jax.device_put(opt_state, sharding),
        )

    def _build(self) -> Callable:
        meta = self._meta
        world, axis, tx = self.world, self.axis_name, self.tx
        shard_len = meta.padded // world

        ring, ring_interpret = self.ring, self.ring_interpret
        ring_chunk_bytes = self.ring_chunk_bytes
        overlap_chunks = self.overlap_chunks(shard_len)

        def per_shard(master, opt_state, grads_tree):
            # strip the [1] shard dim shard_map leaves on the leading axis
            master = master[0]
            opt_state = jax.tree_util.tree_map(lambda x: x[0], opt_state)
            # grads enter replicated (in_spec P()): every rank already holds
            # the full synced gradient, so its shard is a free local slice —
            # no collective needed on this path (ring ownership = offset 1)
            g_shard = local_grad_shard(
                _flatten(grads_tree, meta), meta, world, axis,
                offset=1 if ring else 0,
            )
            master, opt_state, new_params = zero1_apply_shard(
                tx, master, opt_state, g_shard, meta, axis,
                ring=ring, ring_interpret=ring_interpret,
                ring_chunk_bytes=ring_chunk_bytes,
                overlap_chunks=overlap_chunks,
            )
            return (
                master[None],
                jax.tree_util.tree_map(lambda x: x[None], opt_state),
                new_params,
            )

        fn = jax.shard_map(
            per_shard,
            mesh=self.mesh,
            in_specs=(P(axis), P(axis), P()),
            out_specs=(P(axis), P(axis), P()),
            check_vma=False,
        )
        return jax.jit(fn, donate_argnums=(0, 1))

    # -- checkpoint layout tagging ---------------------------------------------

    #: key under which the layout tag rides in ``TrainCheckpointState.extra``
    LAYOUT_KEY = "zero1_layout"

    def layout_metadata(self) -> Dict[str, Any]:
        """The master/opt-state layout this optimizer produces: ring mode
        permutes chunk ownership (row ``r`` holds chunk ``(r+1) % world``)
        and tile-aligns shards, so ring and non-ring checkpoints are NOT
        interchangeable — the tag makes a flipped ``--zero1-ring`` resume
        fail loudly instead of silently loading permuted master weights."""
        return {"ring": self.ring, "align": self._align(), "world": self.world}

    def checkpoint_extra(self, extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """``TrainCheckpointState.extra`` payload with the layout recorded."""
        out = dict(extra or {})
        out[self.LAYOUT_KEY] = self.layout_metadata()
        return out

    def validate_checkpoint_extra(self, extra: Optional[Dict[str, Any]]) -> None:
        """Raise unless the checkpoint's recorded layout matches this
        optimizer's.  A checkpoint with no tag is also rejected: an untagged
        ZeRO-1 master is exactly the silent-corruption hazard the tag
        exists to close."""
        recorded = (extra or {}).get(self.LAYOUT_KEY)
        if recorded is None:
            raise ValueError(
                "checkpoint has no zero1 layout tag (extra["
                f"{self.LAYOUT_KEY!r}]); refusing to restore a ZeRO-1 master "
                "of unknown chunk layout — re-save with "
                "Zero1Optimizer.checkpoint_extra()"
            )
        expected = self.layout_metadata()
        mismatches = {
            k: (recorded.get(k), v)
            for k, v in expected.items()
            if recorded.get(k) != v
        }
        if mismatches:
            detail = ", ".join(
                f"{k}: checkpoint={a!r} vs optimizer={b!r}"
                for k, (a, b) in sorted(mismatches.items())
            )
            raise ValueError(
                f"ZeRO-1 checkpoint layout mismatch ({detail}); restoring "
                "would load chunk-permuted master weights — resume with the "
                "matching ring/world configuration or re-shard offline"
            )

    def restore(self, ckpt: Any) -> Tuple[jnp.ndarray, Any]:
        """Validated restore from a :class:`TrainCheckpointState`-shaped
        object whose ``opt_state`` is the ``(master, opt shard)`` pair and
        whose ``extra`` carries the layout tag; returns the pair placed on
        this optimizer's sharding."""
        self.validate_checkpoint_extra(getattr(ckpt, "extra", None))
        master, opt_state = ckpt.opt_state
        sharding = NamedSharding(self.mesh, P(self.axis_name))
        return (
            jax.device_put(jnp.asarray(master), sharding),
            jax.device_put(opt_state, sharding),
        )

    def apply(
        self, master: jnp.ndarray, opt_state: Any, grads: Any
    ) -> Tuple[jnp.ndarray, Any, Any]:
        """One sharded update from a *replicated* (already-synced) gradient
        pytree — the layout the DDP hook hands back.  Returns ``(master,
        opt_state, new_params)`` with ``new_params`` replicated in the
        original dtypes.  For per-rank unsynced gradients use
        :func:`zero1_train_step`, whose program computes them in-shard."""
        if self._meta is None:
            raise RuntimeError("call init(params) first")
        if self._compiled is None:
            self._compiled = self._build()
        return self._compiled(master, opt_state, grads)


def zero1_train_step(
    loss_fn: Callable[[Any, Any], jnp.ndarray],
    opt: Zero1Optimizer,
    mesh: Mesh,
) -> Callable:
    """Full ZeRO-1 DDP step: per-rank grads from the sharded batch, then the
    reduce-scatter / sharded-update / all-gather cycle — one jitted program.

    ``step(params, master, opt_state, batch) -> (params, master, opt_state,
    losses)``; ``batch`` leading dim is global and sharded over ``opt``'s
    mesh axis.  ``losses`` is the gathered ``[world]`` per-rank loss vector
    (``losses.mean()`` is the global batch loss when ``loss_fn`` is a mean);
    gradient semantics are the mean over ranks, matching DDP averaging.
    """
    meta_holder: dict = {}
    axis_name = opt.axis_name

    def build(params):
        meta = _flatten_meta(params, opt.world, opt._align())
        world = opt.world
        shard_len = meta.padded // world
        tx = opt.tx
        ring, ring_interpret = opt.ring, opt.ring_interpret
        ring_chunk_bytes = opt.ring_chunk_bytes
        overlap_chunks = opt.overlap_chunks(shard_len)

        if opt.wire_dtype != "off":
            from adapcc_tpu.quant import get_codec

            codec_apply = get_codec(opt.wire_dtype).apply
        else:
            codec_apply = None

        def per_shard(params, master, opt_state, batch):
            master = master[0]
            opt_state = jax.tree_util.tree_map(lambda x: x[0], opt_state)
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            # unsynced per-rank grads: the reduce-scatter both averages and
            # slices (the bandwidth-optimal half of a ring allreduce)
            flat_g = _flatten(grads, meta) / world
            if codec_apply is not None:
                # wire codec on the contribution (value semantics): the
                # scattered sum is the sum of quantized per-rank gradients,
                # matching the quantized ring's accumulation contract
                flat_g = codec_apply(flat_g)
            if ring:
                from adapcc_tpu.comm.pallas_ring import ring_reduce_scatter_shard

                # the Pallas ring leaves rank r with reduced chunk
                # (r+1) % world — exactly this mode's master/opt layout
                g_shard = ring_reduce_scatter_shard(
                    flat_g, world, axis_name, interpret=ring_interpret,
                    chunk_bytes=ring_chunk_bytes,
                )
            elif overlap_chunks > 1:
                # per-bucket rolling reduce-scatter (docs/OVERLAP.md §3):
                # each contiguous shard slice scatters as an independent
                # collective XLA can interleave with the flatten/codec
                # compute and with the other slices.  Block r of chunk
                # [:, off:off+n].reshape(-1) is row r's slice, so the
                # concatenated shards keep the identity layout — bitwise
                # equal to the single psum_scatter
                from adapcc_tpu.ddp.overlap import even_chunk_bounds

                g2d = flat_g.reshape(world, shard_len)
                g_shard = jnp.concatenate([
                    lax.psum_scatter(
                        g2d[:, off : off + n].reshape(-1), axis_name,
                        scatter_dimension=0, tiled=True,
                    )
                    for off, n in even_chunk_bounds(shard_len, overlap_chunks)
                ])
            else:
                g_shard = lax.psum_scatter(
                    flat_g.reshape(world, shard_len), axis_name,
                    scatter_dimension=0, tiled=False,
                )
            master, opt_state, new_params = zero1_apply_shard(
                tx, master, opt_state, g_shard, meta, axis_name,
                ring=ring, ring_interpret=ring_interpret,
                ring_chunk_bytes=ring_chunk_bytes,
                overlap_chunks=overlap_chunks,
            )
            return (
                new_params,
                master[None],
                jax.tree_util.tree_map(lambda x: x[None], opt_state),
                loss[None],
            )

        fn = jax.shard_map(
            per_shard,
            mesh=mesh,
            in_specs=(P(), P(axis_name), P(axis_name), P(axis_name)),
            out_specs=(P(), P(axis_name), P(axis_name), P(axis_name)),
            check_vma=False,
        )
        return jax.jit(fn, donate_argnums=(1, 2))

    def stepper(params, master, opt_state, batch):
        for leaf in jax.tree_util.tree_leaves(batch):
            shape = getattr(leaf, "shape", None)
            dim = shape[0] if shape else None
            if dim is not None and dim % opt.world:
                raise ValueError(
                    f"zero1_train_step: batch leading dim {dim} does not "
                    f"divide world={opt.world}; pad or resize the global "
                    "batch (an indivisible batch would otherwise fail with "
                    "an opaque shard_map/GSPMD error)"
                )
        key = _tree_key(params)
        if key not in meta_holder:
            meta_holder[key] = build(params)
        return meta_holder[key](params, master, opt_state, batch)

    return stepper
