"""Expert parallelism: MoE dispatch/combine over an ``experts`` mesh axis.

The reference's ALLTOALL primitive is an unimplemented stub — its MoE
workload delegates the shuffle to fastmoe/NCCL (SURVEY §2.3,
models/moe/train_moe.py:20-41).  Here the all-to-all is native:
each rank owns ``E / world`` experts and a token shard; routing happens
locally, per-expert buffers are exchanged with ``lax.all_to_all`` over ICI,
experts run on their home rank, and a second all-to-all brings results back
for the weighted combine.  Capacity is static per (rank, expert) so every
shape is fixed under jit.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from adapcc_tpu.models.moe import MoEConfig


def moe_capacity(cfg: MoEConfig, n_loc: int) -> int:
    """Static per-(rank, expert) token capacity for a local shard of
    ``n_loc`` tokens — the ONE definition of the exchange geometry, shared
    by the EP shard program and the train_moe tuner probe so the probed
    all-to-all payload can never drift from the executed one."""
    return max(
        1,
        int(-(-cfg.capacity_factor * cfg.top_k * n_loc // cfg.num_experts)),
    )


def _moe_shard(
    router_kernel: jnp.ndarray,
    router_bias: jnp.ndarray,
    w1: jnp.ndarray,
    w2: jnp.ndarray,
    x: jnp.ndarray,
    *,
    cfg: MoEConfig,
    axis_name,
    capacity: int,
    a2a=None,
):
    """Per-shard EP MoE.  ``x [n_loc, D]`` token shard; ``w1/w2`` carry this
    rank's expert slice ``[E_loc, ...]``; router params are replicated.
    ``axis_name`` may be a tuple of mesh axes (two-level worlds); ``a2a``
    overrides the token shuffle (e.g. the hierarchical DCN×ICI exchange).
    Returns ``(y [n_loc, D], aux_loss)``."""
    if a2a is None:
        a2a = partial(
            lax.all_to_all, axis_name=axis_name, split_axis=0, concat_axis=0,
            tiled=False,
        )
    world = lax.psum(1, axis_name)
    n_loc, D = x.shape
    E = cfg.num_experts
    e_loc = w1.shape[0]

    # --- local routing (fp32 softmax) ------------------------------------
    logits = x.astype(jnp.float32) @ router_kernel + router_bias
    probs = jax.nn.softmax(logits, axis=-1)  # [n_loc, E]

    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(jnp.argmax(probs, -1), E), axis=0)
    aux_loss = E * jnp.sum(lax.pmean(me, axis_name) * lax.pmean(ce, axis_name))
    if cfg.router_z_coef:
        # ST-MoE router z-loss, globally token-averaged (matches MoEMLP)
        z = jax.nn.logsumexp(logits, axis=-1)
        aux_loss = aux_loss + cfg.router_z_coef * lax.pmean(
            jnp.mean(z**2), axis_name
        )

    # top-k dispatch with per-rank positional capacity
    combine = jnp.zeros((n_loc, E, capacity), jnp.float32)
    remaining = probs
    used = jnp.zeros((E,), jnp.int32)
    for _ in range(cfg.top_k):
        choice = jnp.argmax(remaining, axis=-1)
        prob = jnp.take_along_axis(remaining, choice[:, None], 1)[:, 0]
        onehot = jax.nn.one_hot(choice, E, dtype=jnp.int32)
        pos_in_expert = (jnp.cumsum(onehot, axis=0) - onehot) + used[None, :]
        pos = jnp.sum(onehot * pos_in_expert, axis=-1)
        keep = pos < capacity
        combine = combine + (
            (prob * keep)[:, None, None]
            * jax.nn.one_hot(choice, E)[:, :, None]
            * jax.nn.one_hot(pos, capacity)[:, None, :]
        )
        used = used + jnp.sum(onehot * keep[:, None], axis=0)
        remaining = remaining * (1.0 - jax.nn.one_hot(choice, E))
    dispatch = (combine > 0).astype(cfg.dtype)  # [n_loc, E, C]

    # --- dispatch all-to-all --------------------------------------------
    # my tokens' contributions to all E experts, grouped by owner rank
    expert_in = jnp.einsum("nec,nd->ecd", dispatch, x.astype(cfg.dtype))
    expert_in = expert_in.reshape(world, e_loc, capacity, D)
    # exchange: afterwards axis 0 indexes the *source* rank and the local
    # expert slice is mine
    recv = a2a(expert_in)

    # --- my experts run on everyone's tokens ----------------------------
    flat = recv.transpose(1, 0, 2, 3).reshape(e_loc, world * capacity, D)
    h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", flat, w1.astype(cfg.dtype)))
    out = jnp.einsum("ech,ehd->ecd", h, w2.astype(cfg.dtype))
    out = out.reshape(e_loc, world, capacity, D).transpose(1, 0, 2, 3)

    # --- return all-to-all + weighted combine ---------------------------
    back = a2a(out)
    expert_out = back.reshape(E, capacity, D)
    y = jnp.einsum("nec,ecd->nd", combine.astype(cfg.dtype), expert_out)
    return y.astype(x.dtype), aux_loss


def expert_parallel_moe(
    params: Any,
    x: jnp.ndarray,
    cfg: MoEConfig,
    mesh: Mesh,
    axis_name: str = "experts",
    capacity: int | None = None,
    engine: Any = None,
):
    """Apply an EP-sharded MoE MLP.

    ``params``: a :class:`~adapcc_tpu.models.moe.MoEMLP` param tree (router
    Dense + stacked ``w1/w2``); experts shard over ``mesh[axis_name]``, tokens
    shard over the same axis (DP-style), router is replicated.  ``x [N, D]``
    with ``N`` divisible by the axis size.  Returns ``(y [N, D], aux_loss)``.

    On a two-level ``("dcn", "ici")`` mesh the expert/token world is the
    flattened ``dcn × ici`` grid and the dispatch/return shuffles run as the
    hierarchical two-hop exchange (`all_to_all_two_level_shard`): intra-slice
    regrouping on ICI, then strictly lane-aligned DCN traffic — instead of a
    DCN-oblivious flat collective.

    ``engine`` (a :class:`~adapcc_tpu.comm.engine.CollectiveEngine` built on
    the SAME mesh) routes the dispatch/combine all-to-alls through the
    engine's :meth:`~adapcc_tpu.comm.engine.CollectiveEngine.expert_a2a`
    instead of a raw ``lax.all_to_all`` — bit-identical exchange (pinned by
    a parity test), but the traffic is now *traced* in the engine's
    dispatch trace and *tuned* under the ``all_to_all`` primitive like
    every other collective (docs/LATENCY.md §5; the tuner database is fed
    by engine-level probe dispatches at this payload geometry, see
    workloads/train_moe.py).
    """
    from adapcc_tpu.comm.two_level import (
        all_to_all_two_level_shard,
        is_two_level,
    )

    a2a = None
    if is_two_level(mesh):
        if axis_name != "experts":
            raise ValueError(
                "on a (dcn, ici) mesh expert_parallel_moe shards experts over "
                f"the full flattened grid; a specific axis_name ({axis_name!r}) "
                "would be silently ignored — build a flat sub-mesh for "
                "single-axis EP instead"
            )
        num_slices, ici_size = (int(s) for s in mesh.devices.shape)
        axis_name = tuple(mesh.axis_names)
        world = num_slices * ici_size
        a2a = partial(
            all_to_all_two_level_shard,
            num_slices=num_slices,
            ici_size=ici_size,
        )
    else:
        world = mesh.shape[axis_name]
    if engine is not None:
        if engine.world_size != world:
            raise ValueError(
                f"engine world {engine.world_size} != expert-parallel world "
                f"{world}; build the engine on the MoE mesh"
            )
        if bool(getattr(engine, "two_level", False)) != is_two_level(mesh):
            raise ValueError(
                "engine and mesh disagree about the (dcn, ici) hierarchy; "
                "build the engine on the MoE mesh"
            )
        a2a = engine.expert_a2a(
            axis_name=None if is_two_level(mesh) else axis_name
        )
    p = params["params"]
    if cfg.num_experts % world:
        raise ValueError(f"{cfg.num_experts} experts not divisible by world {world}")
    if capacity is None:
        capacity = moe_capacity(cfg, x.shape[0] // world)

    fn = shard_map(
        partial(_moe_shard, cfg=cfg, axis_name=axis_name, capacity=capacity, a2a=a2a),
        mesh=mesh,
        in_specs=(P(), P(), P(axis_name), P(axis_name), P(axis_name)),
        out_specs=(P(axis_name), P()),
        check_vma=False,
    )
    y, aux = fn(
        p["router"]["kernel"].astype(jnp.float32),
        p["router"]["bias"].astype(jnp.float32),
        p["w1"],
        p["w2"],
        x,
    )
    return y, aux
