"""Ulysses (DeepSpeed-style) sequence parallelism: all-to-all head exchange.

The second of the two standard long-context schemes (alongside
:mod:`adapcc_tpu.parallel.ring_attention`): instead of rotating K/V blocks
around a ring, each rank trades its sequence shard for a head shard with one
``all_to_all``, computes *full-sequence* attention on its subset of heads,
and trades back.  Two all-to-alls of activation size per layer vs the ring's
``world`` K/V hops — cheaper when heads ≥ world and the interconnect favors
few large transfers; the ring wins when per-device memory cannot hold the
full sequence for even one head.

Layout per shard (inside ``shard_map``):

    in:   [B, T/world, H, D]      sequence-sharded
    →     [B, T, H/world, D]      head-sharded (all_to_all)
    attn: full causal attention over T on H/world heads
    →     [B, T/world, H, D]      back to sequence-sharded (all_to_all)

No reference analog (SURVEY §5.7 — the reference has no sequence
parallelism); this is a new TPU-first capability.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from adapcc_tpu.parallel.ring_attention import _NEG_INF


def ulysses_attention_shard(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    causal: bool = True,
    scale: Optional[float] = None,
    block_impl: str = "dense",
    block_q: int = 128,
    block_k: int = 128,
) -> jnp.ndarray:
    """Per-shard Ulysses attention, for use inside ``shard_map``.

    ``q/k/v``: ``[B, T_local, H, D]`` with ``H`` divisible by the axis size;
    rank r holds global positions ``[r*T_local, (r+1)*T_local)``.
    Returns ``[B, T_local, H, D]`` in ``q.dtype``.

    ``block_impl="flash"`` runs the per-head full-sequence attention on the
    Pallas flash kernel — after the all-to-all each rank holds the WHOLE
    sequence for its head group, so the single-device kernel applies
    directly (no merge statistics needed, unlike the ring).
    """
    if block_impl not in ("dense", "flash"):
        raise ValueError(f"unknown block_impl {block_impl!r} (dense|flash)")
    B, Tl, H, D = q.shape
    world = lax.psum(1, axis_name)
    if H % world != 0:
        raise ValueError(f"heads ({H}) must divide by the axis size ({world})")
    if scale is None:
        scale = 1.0 / (D**0.5)

    def seq_to_heads(x):
        # [B, Tl, H, D] → [B, world*Tl, H/world, D]: split heads into world
        # groups, exchange so each rank holds every sequence block of its
        # head group, then stitch blocks back in global sequence order
        x = x.reshape(B, Tl, world, H // world, D)  # [B,Tl,w,h,D]
        x = jnp.moveaxis(x, 2, 0)  # [w,B,Tl,h,D]
        x = lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0)
        # row j is now the j-th rank's sequence block of MY head group
        x = jnp.moveaxis(x, 1, 0)  # [B,w,Tl,h,D]
        return x.reshape(B, world * Tl, H // world, D)

    def heads_to_seq(x):
        # inverse: [B, T, H/world, D] → [B, Tl, H, D]
        x = x.swapaxes(0, 1).reshape(world, Tl, B, H // world, D)
        x = lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0)
        # row g is my sequence block of head group g
        x = jnp.moveaxis(x, 0, 2)  # [Tl,B,w,h,D] ← [w,Tl,B,h,D]
        return jnp.moveaxis(x, 0, 1).reshape(B, Tl, H, D)

    qh = seq_to_heads(q)
    kh = seq_to_heads(k)
    vh = seq_to_heads(v)

    if block_impl == "flash":
        from adapcc_tpu.ops import flash_attention

        out = flash_attention(
            qh, kh, vh, causal=causal, scale=scale,
            block_q=block_q, block_k=block_k,
        )
        return heads_to_seq(out).astype(q.dtype)

    s = jnp.einsum(
        "bqhd,bkhd->bhqk", qh.astype(jnp.float32) * scale, kh.astype(jnp.float32)
    )
    if causal:
        T = world * Tl
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, vh.astype(jnp.float32))
    return heads_to_seq(out).astype(q.dtype)


def ulysses_attention(
    mesh: Mesh,
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = "ranks",
    causal: bool = True,
    scale: Optional[float] = None,
    block_impl: str = "dense",
) -> jnp.ndarray:
    """Global-view wrapper: ``q/k/v [B, T, H, D]`` with ``T`` and ``H``
    divisible by the mesh axis size.  ``block_impl="flash"`` runs the
    per-head attention on the Pallas flash kernel."""
    spec = P(None, axis_name, None, None)
    fn = shard_map(
        partial(ulysses_attention_shard, axis_name=axis_name, causal=causal, scale=scale,
                block_impl=block_impl),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
