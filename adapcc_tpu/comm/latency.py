"""Latency-optimal small-message collectives: recursive doubling + binomial trees.

Every data plane before this one optimizes the bandwidth-bound regime: the
Pallas ring (PR 2/6) and the wire codecs (PR 3) all pay the ring's
``2·(p−1)·α`` fixed-latency bill, which is the right trade when ``β·n``
dominates.  Small payloads — MoE router tensors, inference logits, norm
scalars, the sub-crossover tail of a bucketed gradient — invert that:
``(p−1)·α`` IS the cost, and a logarithmic schedule pays ``log2(p)·α``
instead (GC3 / "The Big Send-off", PAPERS.md).

This module is the small-message data plane:

- :func:`rd_allreduce_shard` — recursive-**halving** reduce-scatter followed
  by recursive-**doubling** all-gather (the MPICH/Rabenseifner shape):
  ``2·log2(p)`` ppermute rounds total, message sizes halving/doubling so the
  wire volume stays ``2·(p−1)/p·n`` — bandwidth-optimal AND latency-optimal
  on a fully-connected fabric.  On a physical ring/torus the round-``k``
  exchange rides ``min(2^k, p−2^k)`` ICI hops, which is exactly why the ring
  still wins large payloads; :func:`adapcc_tpu.sim.cost_model.
  recursive_doubling_allreduce_time` prices that embedding and
  ``allreduce_crossover_bytes`` finds the break-even.
- :func:`binomial_broadcast_shard` / :func:`binomial_reduce_shard` — one
  single-shot binomial tree phase (``ceil(log2 p)`` rounds, full payload per
  hop): the latency-optimal rooted collectives.
- :func:`tree_allreduce_shard` — reduce-to-root + broadcast, the
  ``algo="tree"`` allreduce arm.

Power-of-two contract: recursive doubling pairs ranks by XOR, so the data
plane **rejects loudly** on non-power-of-two worlds (the cost model prices
the textbook fold-in instead, so the selector still reasons about such
worlds — it just never routes them here).  Binomial trees run on any world.

Relay semantics match the engine's schedule plane: ``active_mask`` gates the
*contribution* (inactive ranks inject the reduction identity) while every
rank stays on the exchange path and receives results — the reference's
``hasLocal`` role algebra (control.cu), spelled as masked XOR exchanges.

Selection is a sized decision end to end: ``ADAPCC_COLL_ALGO`` >
explicit ``algo=`` argument > a measured tuner cell > the sim crossover
(under ``auto``), with the executed algorithm recorded in the dispatch
trace next to ``wire_dtype`` (docs/LATENCY.md).
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import jax.numpy as jnp
from jax import lax

from adapcc_tpu.comm.mesh import RANKS_AXIS
from adapcc_tpu.primitives import ReduceOp

#: algorithm selector vocabulary: ``auto`` = size-adaptive (tuner, then the
#: sim crossover), the rest pin one data plane.  ``ir`` pins the compiled
#: ScheduleProgram executor (``adapcc_tpu/compiler``, docs/COMPILER.md) —
#: allreduce only today; RS/AG dispatches under a global ``ir`` pin keep
#: their legacy planes, exactly like a ``tree`` pin does
COLL_ALGOS = ("auto", "ring", "rd", "tree", "ir")

#: env override for the collective algorithm (docs/LATENCY.md §3); the top
#: of the precedence ladder env > arg > tuner > sim-crossover
COLL_ALGO_ENV = "ADAPCC_COLL_ALGO"


def resolve_coll_algo(algo: Optional[str] = None) -> Optional[str]:
    """The collective algorithm in force: ``ADAPCC_COLL_ALGO`` env > the
    explicit argument > ``None`` (caller decides its legacy default —
    the engine keeps ``ring`` so an unset environment never changes a
    working dispatch).  A malformed value raises — a typo'd
    ``ADAPCC_COLL_ALGO=rdx`` silently running the ring would invalidate
    the A/B it was meant to drive (the ADAPCC_MERGE_ROUNDS policy)."""
    env = os.environ.get(COLL_ALGO_ENV)
    value = env if env is not None and env.strip() else algo
    if value is None:
        return None
    v = str(value).strip().lower()
    if v not in COLL_ALGOS:
        raise ValueError(
            f"{COLL_ALGO_ENV}/algo={value!r}: expected one of "
            f"{'|'.join(COLL_ALGOS)}"
        )
    return v


#: primitives with a latency-plane variant and the algorithms each speaks:
#: rd = recursive halving/doubling (allreduce composes both halves;
#: reduce_scatter is the halving half, all_gather the doubling half), the
#: binomial tree composes rooted phases and exists for allreduce only
_LATENCY_PRIMITIVE_ALGOS = {
    "allreduce": ("rd", "tree"),
    "reduce_scatter": ("rd",),
    "all_gather": ("rd",),
}


def latency_algo_unsupported_reason(
    world: int, algo: str, two_level: bool = False,
    primitive: str = "allreduce",
) -> Optional[str]:
    """Why the latency plane cannot run ``algo`` for ``primitive`` on this
    world — None when it can.  The ONE support funnel shared by the engine
    dispatches (allreduce AND the RS/AG variants, docs/LATENCY.md §5), the
    auto-selector, and the tuner's candidate grid, so a cell can never
    claim a program the data plane would refuse."""
    if algo not in ("rd", "tree"):
        raise ValueError(
            f"algo={algo!r} is not a latency-plane algorithm ('rd'|'tree')"
        )
    allowed = _LATENCY_PRIMITIVE_ALGOS.get(primitive)
    if allowed is None:
        return (
            f"primitive {primitive!r} has no latency-plane variant "
            f"(only {sorted(_LATENCY_PRIMITIVE_ALGOS)})"
        )
    if algo not in allowed:
        return (
            f"{primitive} has no {algo!r} variant: binomial trees are "
            "rooted phases only allreduce composes — reduce_scatter/"
            "all_gather speak the recursive halving/doubling ('rd') half"
        )
    if two_level:
        return (
            "two-level (dcn, ici) worlds route through the hierarchical "
            "schedule; the latency plane needs a flat ranks mesh"
        )
    if algo == "rd" and world & (world - 1):
        return (
            f"recursive doubling pairs ranks by XOR and needs a power-of-two "
            f"world, got {world}; the cost model prices the fold-in, the "
            "data plane rejects it"
        )
    return None


# --------------------------------------------------------------------------- #
# shard-level programs (call inside shard_map)
# --------------------------------------------------------------------------- #


def _combine(a: jnp.ndarray, b: jnp.ndarray, op: ReduceOp) -> jnp.ndarray:
    if op is ReduceOp.MAX:
        return jnp.maximum(a, b)
    return a + b


def _xor_perm(world: int, d: int) -> List[Tuple[int, int]]:
    """The round's full exchange permutation: every rank swaps with its
    XOR-partner at distance ``d`` (a bijection, so ppermute delivers to
    everyone — no zero-fill corner for MAX)."""
    return [(i, i ^ d) for i in range(world)]


def _halving_rounds(cur, me, world: int, axis_name: str, op: ReduceOp):
    """The recursive-HALVING reduce-scatter rounds (distances p/2 … 1):
    each round keeps the half the rank's final segment lives in, sends the
    other, folds in what arrives.  After ``log2(p)`` rounds rank ``r``
    holds the fully reduced segment ``r``.  Shared by the allreduce and
    the standalone reduce-scatter — one definition of the halving walk."""
    d = world // 2
    while d >= 1:
        half = cur.shape[0] // 2
        bit = (me // d) % 2
        send = lax.dynamic_slice(cur, ((1 - bit) * half,), (half,))
        keep = lax.dynamic_slice(cur, (bit * half,), (half,))
        recvd = lax.ppermute(send, axis_name, _xor_perm(world, d))
        cur = _combine(keep, recvd, op)
        d //= 2
    return cur


def _doubling_rounds(cur, me, world: int, axis_name: str):
    """The recursive-DOUBLING all-gather rounds (distances 1 … p/2): each
    round swaps the gathered block with the XOR-partner and concatenates
    (the bit-0 rank owns the lower half), doubling the gathered extent.
    Shared by the allreduce and the standalone all-gather."""
    d = 1
    while d < world:
        recvd = lax.ppermute(cur, axis_name, _xor_perm(world, d))
        low = (me // d) % 2 == 0
        first = jnp.where(low, cur, recvd)
        second = jnp.where(low, recvd, cur)
        cur = jnp.concatenate([first, second])
        d *= 2
    return cur


def rd_allreduce_shard(
    x: jnp.ndarray,
    active_mask: Optional[jnp.ndarray],
    world: int,
    axis_name: str = RANKS_AXIS,
    op: ReduceOp = ReduceOp.SUM,
) -> jnp.ndarray:
    """Recursive-halving reduce-scatter + recursive-doubling all-gather
    allreduce over ``axis_name``; call inside shard_map.

    ``2·log2(world)`` ppermute rounds.  Round ``k`` of the halving phase
    pairs ranks across distance ``world/2^(k+1)`` and exchanges half the
    working segment (each rank keeps the half its final segment lives in
    and folds the received half into it); after ``log2(world)`` rounds rank
    ``r`` holds the fully reduced segment ``r``.  The doubling phase mirrors
    it back up: each round swaps the current block with the XOR-partner and
    concatenates, doubling the gathered extent.  Wire volume is the ring's
    ``2·(p−1)/p·n``; fixed cost is ``2·log2(p)·α`` instead of ``2·(p−1)·α``.

    Power-of-two worlds only (loud reject — see module docstring).
    ``active_mask`` follows the relay contract: inactive ranks contribute
    the reduction identity but stay on the exchange path and receive the
    result; ``ReduceOp.AVG`` normalizes by the active count.
    """
    reason = latency_algo_unsupported_reason(world, "rd")
    if reason is not None:
        raise ValueError(f"rd_allreduce_shard: {reason}")
    from adapcc_tpu.comm.engine import (
        _avg_normalize,
        _identity_for,
        _mask_contribution,
    )

    flat = x.reshape(-1)
    if flat.size == 0 or world == 1:
        if op is ReduceOp.AVG:
            return x  # one contributor: the average is the value
        return x
    if active_mask is not None:
        flat = _mask_contribution(flat, active_mask, axis_name, op)
    n = flat.size
    seg = -(-n // world)
    pad = world * seg - n
    if pad:
        ident = _identity_for(op, flat.dtype)
        flat = jnp.concatenate([flat, jnp.full((pad,), ident, flat.dtype)])
    me = lax.axis_index(axis_name)
    # recursive-halving reduce-scatter, then the recursive-doubling
    # all-gather mirroring the same (distance, size) pairs back up — the
    # standalone RS/AG entry points share both walks
    cur = _halving_rounds(flat, me, world, axis_name, op)
    cur = _doubling_rounds(cur, me, world, axis_name)

    result = cur[:n].reshape(x.shape)
    if active_mask is not None:
        return _avg_normalize(result, active_mask, op)
    if op is ReduceOp.AVG:
        return result / world
    return result


def rd_reduce_scatter_shard(
    x: jnp.ndarray,
    active_mask: Optional[jnp.ndarray],
    world: int,
    axis_name: str = RANKS_AXIS,
    op: ReduceOp = ReduceOp.SUM,
) -> jnp.ndarray:
    """Recursive-HALVING reduce-scatter over ``axis_name``; call inside
    shard_map (the RS half of :func:`rd_allreduce_shard`, standing alone
    so re-ranking can select it — docs/LATENCY.md §5).

    Input: this rank's full ``n``-element contribution (``n`` must divide
    the world — the engine's reduce_scatter row contract).  Output: the
    ``n/world``-element segment ``r`` fully reduced on rank ``r`` —
    ``log2(p)`` ppermute rounds at the ring reduce-scatter's ``(p−1)/p·n``
    wire volume (vs the ring's ``p−1`` rounds).  Power-of-two worlds only
    (loud reject via the shared support funnel).  ``active_mask`` follows
    the relay contract: inactive ranks contribute the reduction identity
    but stay on the exchange path and receive their segment;
    ``ReduceOp.AVG`` normalizes by the active count.
    """
    reason = latency_algo_unsupported_reason(
        world, "rd", primitive="reduce_scatter"
    )
    if reason is not None:
        raise ValueError(f"rd_reduce_scatter_shard: {reason}")
    from adapcc_tpu.comm.engine import _avg_normalize, _mask_contribution

    flat = x.reshape(-1)
    if flat.size % world:
        raise ValueError(
            f"rd reduce-scatter payload ({flat.size} elems) must divide "
            f"the world ({world})"
        )
    if world == 1:
        return flat
    if active_mask is not None:
        flat = _mask_contribution(flat, active_mask, axis_name, op)
    me = lax.axis_index(axis_name)
    out = _halving_rounds(flat, me, world, axis_name, op)
    if active_mask is not None:
        return _avg_normalize(out, active_mask, op)
    if op is ReduceOp.AVG:
        return out / world
    return out


def rd_all_gather_shard(
    x: jnp.ndarray,
    world: int,
    axis_name: str = RANKS_AXIS,
) -> jnp.ndarray:
    """Recursive-DOUBLING all-gather over ``axis_name``; call inside
    shard_map (the AG half of :func:`rd_allreduce_shard`, standing alone
    so re-ranking can select it — docs/LATENCY.md §5).

    Input: this rank's payload (any shape).  Output: ``[world, *payload]``
    — everyone's payloads in rank order — in ``log2(p)`` ppermute rounds
    at the ring all-gather's ``(p−1)/p·n`` wire volume (vs the ring's
    ``p−1`` rounds).  Power-of-two worlds only (loud reject via the shared
    support funnel).  Relay semantics live with the caller: the engine
    zeroes inactive contributions before the exchange, exactly like its
    XLA all-gather plane.
    """
    reason = latency_algo_unsupported_reason(
        world, "rd", primitive="all_gather"
    )
    if reason is not None:
        raise ValueError(f"rd_all_gather_shard: {reason}")
    if world == 1:
        return x[None]
    me = lax.axis_index(axis_name)
    cur = _doubling_rounds(x.reshape(-1), me, world, axis_name)
    return cur.reshape((world,) + x.shape)


def _binomial_rounds(world: int) -> List[int]:
    """Ascending round distances 1, 2, 4, ... < world (any world size)."""
    out: List[int] = []
    d = 1
    while d < world:
        out.append(d)
        d *= 2
    return out


def _tree_round_tables(
    world: int, d: int, root: int, up: bool
):
    """One binomial-tree round's ppermute edges + destination mask, in
    virtual-rank space rotated so ``root`` is vrank 0.

    ``up=True`` (reduce): vranks ``v + d`` with ``v % 2d == 0`` send their
    partial DOWN to ``v``.  ``up=False`` (broadcast): vranks ``v`` that
    already hold the value send UP to ``v + d``.
    """
    import numpy as np

    perm: List[Tuple[int, int]] = []
    dst_mask = np.zeros((world,), dtype=bool)
    for v in range(0, world, 2 * d):
        other = v + d
        if other >= world:
            continue
        src_v, dst_v = (other, v) if up else (v, other)
        src = (src_v + root) % world
        dst = (dst_v + root) % world
        perm.append((src, dst))
        dst_mask[dst] = True
    return perm, dst_mask


def binomial_broadcast_shard(
    x: jnp.ndarray,
    root: int,
    world: int,
    axis_name: str = RANKS_AXIS,
) -> jnp.ndarray:
    """Single-shot binomial-tree broadcast from ``root``: ``ceil(log2 p)``
    ppermute rounds, the set of value-holders doubling each round (vs the
    chain tree's ``p−1`` rounds).  Any world size; call inside shard_map.
    Every rank ends holding the root's value (relays included — broadcast
    values are unaffected by relay roles, docs/ELASTIC.md)."""
    if not 0 <= root < world:
        raise ValueError(f"root {root} outside world [0, {world})")
    if world == 1:
        return x
    out = x
    me = lax.axis_index(axis_name)
    # descending distances: the first hop crosses half the (virtual) world
    for d in reversed(_binomial_rounds(world)):
        perm, dst_mask = _tree_round_tables(world, d, root, up=False)
        recvd = lax.ppermute(out, axis_name, perm)
        is_dst = jnp.asarray(dst_mask)[me]
        out = jnp.where(is_dst, recvd, out)
    return out


def binomial_reduce_shard(
    x: jnp.ndarray,
    active_mask: Optional[jnp.ndarray],
    root: int,
    world: int,
    axis_name: str = RANKS_AXIS,
    op: ReduceOp = ReduceOp.SUM,
) -> jnp.ndarray:
    """Single-shot binomial-tree reduce to ``root``: ``ceil(log2 p)``
    ppermute rounds with halving sender sets.  ``root`` holds the full
    reduction; other ranks hold partials for their subtree (the same
    contract as the engine's schedule-path reduce).  ``active_mask``
    follows the relay contract (identity contribution, stays on the path).
    Any world size; call inside shard_map."""
    if not 0 <= root < world:
        raise ValueError(f"root {root} outside world [0, {world})")
    from adapcc_tpu.comm.engine import _avg_normalize, _mask_contribution

    acc = x
    if active_mask is not None:
        acc = _mask_contribution(acc, active_mask, axis_name, op)
    if world == 1:
        if active_mask is not None:
            return _avg_normalize(acc, active_mask, op)
        return acc  # one contributor: AVG over 1 is the value itself
    me = lax.axis_index(axis_name)
    for d in _binomial_rounds(world):
        perm, dst_mask = _tree_round_tables(world, d, root, up=True)
        recvd = lax.ppermute(acc, axis_name, perm)
        is_dst = jnp.asarray(dst_mask)[me]
        acc = jnp.where(is_dst, _combine(acc, recvd, op), acc)
    if active_mask is not None:
        return _avg_normalize(acc, active_mask, op)
    if op is ReduceOp.AVG:
        return acc / world
    return acc


def tree_allreduce_shard(
    x: jnp.ndarray,
    active_mask: Optional[jnp.ndarray],
    world: int,
    axis_name: str = RANKS_AXIS,
    op: ReduceOp = ReduceOp.SUM,
    root: int = 0,
) -> jnp.ndarray:
    """Binomial-tree allreduce: reduce to ``root`` + broadcast back —
    ``2·ceil(log2 p)`` rounds, full payload per hop.  The ``algo="tree"``
    arm of the selector: latency-optimal like recursive doubling but with
    ``O(n)`` per-hop payloads, so it prices above ``rd`` for allreduce
    (its own regime is the rooted broadcast/reduce primitives); it exists
    on the allreduce axis so the tuner can *measure* that, not assume it.
    Any world size; call inside shard_map."""
    from adapcc_tpu.comm.engine import _avg_normalize

    if world == 1:
        return x
    # the reduce phase must NOT normalize (the broadcast would re-ship an
    # already-averaged value — fine — but the identity-contribution math
    # for AVG needs the active count applied exactly once, at the end)
    reduced = binomial_reduce_shard(
        x, active_mask, root, world, axis_name,
        op=ReduceOp.SUM if op is ReduceOp.AVG else op,
    )
    out = binomial_broadcast_shard(reduced, root, world, axis_name)
    if op is ReduceOp.AVG:
        if active_mask is not None:
            return _avg_normalize(out, active_mask, ReduceOp.AVG)
        return out / world
    return out
