"""Pallas ring collectives over ICI: the hand-tuned data plane.

The reference's performance path is hand-written CUDA: persistent per-tree
threads pushing 4 MB chunks through pre-shared IPC staging buffers with
event/flag handshakes (csrc/allreduce.cu:568-654, trans.cu:58-100).  The TPU
analog is a Pallas kernel that drives the ICI fabric directly with
``make_async_remote_copy`` RDMA — this module provides ring
reduce-scatter / all-gather / allreduce kernels with:

- **chunked pipelining**: the buffer is split into ``world`` chunks walking
  the ring, the Pallas version of the reference's chunk pipeline;
- **double-buffered staging** (2 comm slots), the analog of the reference's
  per-sibling staging slots;
- **credit-based flow control**: a receiver returns a capacity credit to its
  upstream neighbor after consuming a slot, so a fast sender can never
  clobber an unconsumed slot even on long rings — replacing the reference's
  shm bool + IPC-event handshake (trans.cu:73-98) with semaphores;
- **neighbor barrier** on entry so no device writes into a peer that has not
  allocated its buffers yet.

Everything is testable off-hardware: ``interpret=True`` runs the kernels
under the Pallas TPU interpreter on a virtual CPU mesh **with race detection
enabled** — a sanitizer the reference never had (SURVEY §5.2).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from adapcc_tpu.comm.mesh import RANKS_AXIS

#: VMEM tiles are (sublanes, 128) with sublanes scaling inversely with item
#: width: fp32 → (8, 128), bf16 → (16, 128), int8/fp8 → (32, 128).  Chunks
#: are padded to whole tiles of the payload dtype (``_tile_elems``).
_LANES = 128


def _tile_elems(dtype) -> int:
    itemsize = jnp.dtype(dtype).itemsize
    sublanes = {4: 8, 2: 16, 1: 32}.get(itemsize, 8)
    return _LANES * sublanes


def _interpret_params(interpret):
    if interpret is True:
        return pltpu.InterpretParams(detect_races=True)
    return interpret  # False or a caller-provided InterpretParams


# --------------------------------------------------------------------------- #
# kernel body
# --------------------------------------------------------------------------- #

def _ring_kernel(
    x_ref,
    out_ref,
    work,
    comm,
    send_sem,
    recv_sem,
    cap_sem,
    *,
    world: int,
    axis_name: str,
    do_reduce_scatter: bool,
    do_all_gather: bool,
):
    """Unidirectional ring walk: reduce-scatter phase then all-gather phase.

    ``x_ref``/``work`` are ``[world, S, 128]`` (chunk-major); ``comm`` is the
    ``[2, S, 128]`` double-buffered staging area written by the left
    neighbor's RDMA.
    """
    my_id = lax.axis_index(axis_name)
    right = (my_id + 1) % world
    left = (my_id + world - 1) % world

    # entry barrier with both neighbors (they write into our comm buffer)
    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(barrier, inc=1, device_id=left)
    pltpu.semaphore_signal(barrier, inc=1, device_id=right)
    pltpu.semaphore_wait(barrier, 2)

    work[...] = x_ref[...]

    n_rs = world - 1 if do_reduce_scatter else 0
    n_ag = world - 1 if do_all_gather else 0
    total_steps = n_rs + n_ag

    for step in range(total_steps):
        slot = step % 2
        in_rs = step < n_rs
        if in_rs:
            send_idx = (my_id + world - step) % world
            recv_idx = (my_id + world - step - 1) % world
        else:
            ag = step - n_rs
            # after RS each rank owns the fully reduced chunk (my_id + 1);
            # without RS (pure all-gather) it owns chunk my_id
            own = 1 if do_reduce_scatter else 0
            send_idx = (my_id + world + own - ag) % world
            recv_idx = (my_id + world + own - ag - 1) % world

        # flow control: slot `slot` in the right neighbor was last written at
        # step-2; wait for the credit it returns after consuming that write
        if step >= 2:
            pltpu.semaphore_wait(cap_sem, 1)

        rdma = pltpu.make_async_remote_copy(
            src_ref=work.at[send_idx],
            dst_ref=comm.at[slot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[slot],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()  # outbound sent AND left neighbor's chunk landed

        if in_rs:
            work[recv_idx] = work[recv_idx] + comm[slot]
        else:
            work[recv_idx] = comm[slot]

        # return a capacity credit upstream: slot is free for reuse
        pltpu.semaphore_signal(cap_sem, inc=1, device_id=left)

    # drain outstanding credits so no signal outlives the kernel
    tail = min(2, total_steps)
    for _ in range(tail):
        pltpu.semaphore_wait(cap_sem, 1)
    out_ref[...] = work[...]


# --------------------------------------------------------------------------- #
# shard-level wrappers (call inside shard_map)
# --------------------------------------------------------------------------- #

def _pad_chunks(flat: jnp.ndarray, world: int):
    """Pad to world × (whole dtype-native tiles) and reshape chunk-major."""
    tile = _tile_elems(flat.dtype)
    chunk = -(-flat.size // world)          # ceil
    chunk = -(-chunk // tile) * tile        # round up to full tiles
    padded = jnp.zeros((world * chunk,), flat.dtype).at[: flat.size].set(flat)
    return padded.reshape(world, chunk // _LANES, _LANES), chunk


def _run_ring_chunks(chunks: jnp.ndarray, *, world, axis_name, rs, ag, interpret):
    """Run the ring kernel on a pre-chunked ``[world, S, 128]`` array."""
    from adapcc_tpu.compat import ring_kernels_supported

    if not ring_kernels_supported():
        # the one funnel every ring entry point (and so --zero1-ring,
        # engine.ring_*, the benchmarks) routes through: fail with guidance
        # here rather than a cryptic Mosaic/legacy-pallas error deeper in
        raise RuntimeError(
            "Pallas ICI ring kernels need a real TPU or the Mosaic TPU "
            "interpret mode (jax >= 0.5); this build has neither — use the "
            "XLA collective path instead (e.g. drop --zero1-ring)"
        )
    kernel = functools.partial(
        _ring_kernel,
        world=world,
        axis_name=axis_name,
        do_reduce_scatter=rs,
        do_all_gather=ag,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(chunks.shape, chunks.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM(chunks.shape, chunks.dtype),                # work
            pltpu.VMEM((2,) + chunks.shape[1:], chunks.dtype),     # comm slots
            pltpu.SemaphoreType.DMA((2,)),                         # send
            pltpu.SemaphoreType.DMA((2,)),                         # recv
            pltpu.SemaphoreType.REGULAR,                           # capacity
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=0
        ),
        interpret=_interpret_params(interpret),
    )(chunks)


def _run_ring(x: jnp.ndarray, *, world, axis_name, rs, ag, interpret):
    chunks, chunk = _pad_chunks(x.reshape(-1), world)
    out = _run_ring_chunks(
        chunks, world=world, axis_name=axis_name, rs=rs, ag=ag, interpret=interpret
    )
    return out, chunk


def ring_allreduce_shard(
    x: jnp.ndarray,
    world: int,
    axis_name: str = RANKS_AXIS,
    interpret: bool = False,
) -> jnp.ndarray:
    """Sum-allreduce via ring reduce-scatter + ring all-gather.

    Bandwidth-optimal (2·(world−1)/world of the buffer per link), the same
    schedule family the reference benchmarks against NCCL rings
    (nccl-perf/tree/all_reduce.cu).
    """
    if world == 1:
        return x
    out, _ = _run_ring(x, world=world, axis_name=axis_name, rs=True, ag=True, interpret=interpret)
    return out.reshape(-1)[: x.size].reshape(x.shape)


def ring_reduce_scatter_shard(
    x: jnp.ndarray,
    world: int,
    axis_name: str = RANKS_AXIS,
    interpret: bool = False,
) -> jnp.ndarray:
    """Ring reduce-scatter: returns this rank's reduced chunk (padded shape
    ``[chunk]``); rank r owns chunk ``(r + 1) % world`` of the flattened,
    tile-padded input."""
    if world == 1:
        return x.reshape(-1)
    out, chunk = _run_ring(x, world=world, axis_name=axis_name, rs=True, ag=False, interpret=interpret)
    my_id = lax.axis_index(axis_name)
    own = (my_id + 1) % world
    return out.reshape(world, chunk)[own]


def ring_all_gather_shard(
    x: jnp.ndarray,
    world: int,
    axis_name: str = RANKS_AXIS,
    interpret: bool = False,
) -> jnp.ndarray:
    """Ring all-gather of per-rank chunks: input is this rank's ``[chunk]``
    payload (tile-aligned), output is ``[world, chunk]`` in rank order."""
    if world == 1:
        return x.reshape(1, -1)
    tile = _tile_elems(x.dtype)
    if x.size % tile:
        raise ValueError(f"all-gather payload must be tile-aligned ({tile} elems), got {x.size}")
    my_id = lax.axis_index(axis_name)
    chunks = jnp.zeros((world, x.size), x.dtype)
    # place the local payload in the row this rank owns; the ring walk
    # replaces every other row with the neighbors' payloads
    chunks = lax.dynamic_update_index_in_dim(chunks, x.reshape(-1), my_id, 0)
    chunks = chunks.reshape(world, x.size // _LANES, _LANES)
    out = _run_ring_chunks(
        chunks, world=world, axis_name=axis_name, rs=False, ag=True, interpret=interpret
    )
    return out.reshape(world, -1)
