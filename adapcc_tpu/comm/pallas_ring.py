"""Pallas ring collectives over ICI: the hand-tuned data plane.

The reference's performance path is hand-written CUDA: persistent per-tree
threads pushing 4 MB chunks through pre-shared IPC staging buffers with
event/flag handshakes (csrc/allreduce.cu:568-654, trans.cu:58-100).  The TPU
analog is a Pallas kernel that drives the ICI fabric directly with
``make_async_remote_copy`` RDMA — this module provides ring
reduce-scatter / all-gather / allreduce kernels with:

- **chunked pipelining**: the buffer is split into ``world`` chunks walking
  the ring, the Pallas version of the reference's chunk pipeline;
- **double-buffered staging** (2 comm slots), the analog of the reference's
  per-sibling staging slots;
- **credit-based flow control**: a receiver returns a capacity credit to its
  upstream neighbor after consuming a slot, so a fast sender can never
  clobber an unconsumed slot even on long rings — replacing the reference's
  shm bool + IPC-event handshake (trans.cu:73-98) with semaphores;
- **neighbor barrier** on entry so no device writes into a peer that has not
  allocated its buffers yet.

Two execution paths share those mechanics, selected per payload by
:func:`plan_ring_schedule`:

- **vmem** — the whole payload is VMEM-resident (input + work + comm slots),
  the right program when everything fits in one ``chunk_bytes`` staging
  budget;
- **hbm-stream** — the payload lives in HBM (``pltpu.ANY``) and a grid over
  (ring step × tile) streams ``chunk_bytes``-sized tiles through fixed VMEM
  staging: local DMA in → remote RDMA → accumulate → local DMA out, with the
  credit protocol carried across grid steps.  This is the TPU analog of the
  reference's fixed ``MAX_BUF_SIZE`` staging design (include/init.h:14-25):
  collective payload size is bounded by HBM, not by on-device scratch.

The tile granularity is the strategy plane's synthesized ``chunk_bytes``
(``Strategy.chunk_bytes`` → ``engine.ring_*`` → here), overridable for
sweeps with ``ADAPCC_RING_CHUNK_BYTES``.  The executed tile is a
near-budget whole-VMEM-tile size covering the per-rank chunk with minimal
zero padding (< one tile per chunk, sliced back out by the wrappers), so
the external chunk layout (and with it the ZeRO-1 shard layout) is
byte-identical across every chunk size — which also makes results
bit-identical: each element sees the same adds in the same ring order
regardless of tiling.

Everything is testable off-hardware: ``interpret=True`` runs the kernels
under the Pallas TPU interpreter on a virtual CPU mesh **with race detection
enabled** — a sanitizer the reference never had (SURVEY §5.2).
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from adapcc_tpu.comm.mesh import RANKS_AXIS
from adapcc_tpu.primitives import DEFAULT_CHUNK_BYTES

#: VMEM tiles are (sublanes, 128) with sublanes scaling inversely with item
#: width: fp32 → (8, 128), bf16 → (16, 128), int8/fp8 → (32, 128).  Chunks
#: are padded to whole tiles of the payload dtype (``_tile_elems``).
_LANES = 128

#: env override for the ring staging granularity (chunk-size sweeps); wins
#: over both the caller's value and the strategy's synthesized chunk_bytes
RING_CHUNK_ENV = "ADAPCC_RING_CHUNK_BYTES"

#: env gate for the fused wire-codec kernels (A/B vs the unfused quantized
#: ppermute ring): ``auto`` (default) fuses whenever the plan supports it,
#: ``off`` forces the quant-ring reroute, ``on`` demands the fused path and
#: fails loudly where it cannot run.  Malformed → loud error (the
#: ADAPCC_MERGE_ROUNDS policy: a typo must not silently invalidate an A/B).
FUSED_WIRE_ENV = "ADAPCC_FUSED_WIRE"

FUSED_WIRE_MODES = ("auto", "on", "off")

#: wire dtypes the fused kernels speak, with their wire-array itemsize.
#: "off" is not fused (the plain kernels ship the payload dtype); other
#: registry codecs reroute to the unfused quantized ppermute ring.
_FUSED_WIRE_ITEMSIZE = {"bf16": 2, "int8": 1}


def _tile_elems(dtype) -> int:
    itemsize = jnp.dtype(dtype).itemsize
    sublanes = {4: 8, 2: 16, 1: 32}.get(itemsize, 8)
    return _LANES * sublanes


def resolve_fused_wire() -> str:
    """The fused-wire gate in force (``auto`` | ``on`` | ``off``)."""
    env = os.environ.get(FUSED_WIRE_ENV)
    if env is None or not env.strip():
        return "auto"
    mode = env.strip().lower()
    if mode not in FUSED_WIRE_MODES:
        raise ValueError(
            f"{FUSED_WIRE_ENV}={env!r}: expected one of "
            f"{'|'.join(FUSED_WIRE_MODES)}"
        )
    return mode


def fused_wire_unsupported_reason(
    dtype, wire_dtype: str, block_size: Optional[int] = None
) -> Optional[str]:
    """Why the fused codec kernels cannot run this configuration, or None
    when they can.  The one support funnel the engine, the wrappers, and
    the tuner's candidate grid all consult — so a candidate cell can never
    claim a fused path the data plane would not run.

    The codec math is defined on fp32 payloads (quant/codec.py), and the
    in-kernel block view needs whole 128-lane rows per block nested inside
    every staging tile: ``block_size`` must be a multiple of 128 whose row
    count divides the fp32 sublane tile (8 rows) — {128, 256, 512, 1024}.
    """
    if wire_dtype == "off":
        return "wire_dtype=off has no codec to fuse (the plain kernels ship fp32)"
    if wire_dtype not in _FUSED_WIRE_ITEMSIZE:
        return (
            f"wire_dtype={wire_dtype!r} has no fused kernel "
            f"(fused codecs: {'|'.join(sorted(_FUSED_WIRE_ITEMSIZE))})"
        )
    if jnp.dtype(dtype) != jnp.float32:
        return (
            f"fused wire codecs are defined on float32 payloads, got "
            f"{jnp.dtype(dtype).name} (quant/codec.py block semantics)"
        )
    if wire_dtype == "int8":
        if block_size is None:
            block_size = _default_block_size()
        rows = block_size // _LANES
        if block_size % _LANES or rows < 1 or 8 % rows:
            return (
                f"int8 block_size={block_size} cannot tile VMEM staging: "
                f"need a multiple of {_LANES} whose {_LANES}-lane row count "
                "divides the fp32 sublane tile (8) — one of 128|256|512|1024"
            )
    return None


def _default_block_size() -> int:
    from adapcc_tpu.quant.codec import DEFAULT_BLOCK_SIZE

    return DEFAULT_BLOCK_SIZE


def fused_ring_dispatch_reason(
    dtype, wire_dtype: str, block_size: Optional[int] = None
) -> Optional[str]:
    """Why a dispatch cannot take the fused wire path HERE (env gate,
    kernel support, codec geometry) — None when it can.  Under
    ``ADAPCC_FUSED_WIRE=on`` any reason becomes a loud error instead of a
    reroute: the operator demanded the fused kernel, a silent fallback
    would invalidate the A/B."""
    mode = resolve_fused_wire()
    if mode == "off":
        reason: Optional[str] = f"{FUSED_WIRE_ENV}=off pins the unfused path"
    else:
        from adapcc_tpu.compat import ring_kernels_supported

        if not ring_kernels_supported():
            reason = (
                "ring kernels need a real TPU or the Mosaic TPU interpret "
                "mode (jax >= 0.5); this build has neither"
            )
        else:
            reason = fused_wire_unsupported_reason(dtype, wire_dtype, block_size)
    if reason is not None and mode == "on":
        raise ValueError(
            f"{FUSED_WIRE_ENV}=on but the fused wire path cannot run: {reason}"
        )
    return reason


_REROUTE_NOTED: set = set()


def note_quant_reroute(wire_dtype: str, reason: str) -> None:
    """One-time (per process, per reason) stderr note that a codec dispatch
    abandoned the staged Pallas kernel for the XLA ppermute quant ring —
    operators reading throughput must know which data plane produced it."""
    key = (wire_dtype, reason)
    if key in _REROUTE_NOTED:
        return
    _REROUTE_NOTED.add(key)
    import sys

    print(
        f"adapcc: wire_dtype={wire_dtype} ring collective rerouted off the "
        f"staged Pallas kernel onto the unfused ppermute quant ring "
        f"(impl=quant_ring): {reason}",
        file=sys.stderr,
    )


def _scale_rows(n_blocks: int) -> int:
    """Rows of the fp32 scale side-channel tile holding ``n_blocks`` per-
    block scales: whole 128-lane rows, padded to the fp32 sublane tile so
    the slot is itself a legal VMEM tile."""
    rows = -(-n_blocks // _LANES)
    return -(-rows // 8) * 8


def _interpret_params(interpret):
    if interpret is True:
        return pltpu.InterpretParams(detect_races=True)
    return interpret  # False or a caller-provided InterpretParams


def resolve_chunk_bytes(chunk_bytes: Optional[int] = None) -> int:
    """The staging granularity actually in force: the ``ADAPCC_RING_CHUNK_
    BYTES`` sweep override wins, then the caller's (synthesized) value, then
    the default.  A malformed override raises — a typo silently falling back
    to the default would invalidate a chunk-size sweep (same policy as
    ADAPCC_MERGE_ROUNDS)."""
    env = os.environ.get(RING_CHUNK_ENV)
    if env is not None and env.strip():
        try:
            value = int(env)
        except ValueError:
            raise ValueError(
                f"{RING_CHUNK_ENV}={env!r}: expected a positive byte count"
            ) from None
        if value <= 0:
            raise ValueError(
                f"{RING_CHUNK_ENV}={env!r}: expected a positive byte count"
            )
        return value
    if chunk_bytes is not None:
        if chunk_bytes <= 0:
            raise ValueError(f"chunk_bytes must be positive, got {chunk_bytes}")
        return int(chunk_bytes)
    return DEFAULT_CHUNK_BYTES


@dataclass(frozen=True)
class RingSchedule:
    """The executed ring schedule — the observable contract for traces,
    benchmarks, and tests: which path ran, at what staging granularity,
    under which wire codec."""

    path: str              #: "vmem" | "hbm-stream"
    world: int
    steps: int             #: ring steps (RS + AG walks)
    chunk_bytes: int       #: requested staging budget (resolved)
    stage_bytes: int       #: executed tile bytes (near-budget, minimal padding)
    n_tiles: int           #: tiles per ring step on the hbm-stream path
    payload_bytes: int     #: caller bytes before padding
    padded_bytes: int      #: world × tile-padded chunk bytes
    dtype: str = "float32"
    #: wire codec fused into the kernels ("off" = the plain fp32 kernels)
    wire_dtype: str = "off"
    #: int8 quantization block (elements per fp32 scale); 0 when no blocks
    block_size: int = 0
    #: bytes of one staged *wire* tile (what each RDMA actually ships);
    #: equals ``stage_bytes`` on the unfused path
    wire_stage_bytes: int = 0
    #: bytes of one fp32 scale side-channel tile (int8 plans only)
    scale_slot_bytes: int = 0

    @property
    def scale_bytes(self) -> int:
        """Total scale side-channel VMEM the kernel allocates: one send
        slot + two comm slots, plus (vmem path) the per-chunk scale store
        the all-gather forwards bits from.  Zero off the int8 path — this
        is exactly what ``vmem_bound_bytes`` grows by on int8 plans."""
        if self.scale_slot_bytes == 0:
            return 0
        slots = 3 + (self.world if self.path == "vmem" else 0)
        return slots * self.scale_slot_bytes

    @property
    def vmem_bound_bytes(self) -> int:
        """Peak VMEM the data buffers need.  Unfused: the whole payload
        three times over (pallas input + output + work scratch) plus 2 comm
        slots on the vmem path, 4 staging tiles (1 send + 1 accumulate +
        2 comm) on the stream path.  Fused plans stage the *wire* arrays
        (1 send + 2 comm slots at wire density) next to the fp32 staging,
        plus the scale side channel (:attr:`scale_bytes`)."""
        chunk = self.padded_bytes // self.world
        if self.wire_dtype == "off":
            if self.path == "vmem":
                return 3 * self.padded_bytes + 2 * chunk
            return 4 * self.stage_bytes
        if self.path == "vmem":
            return 3 * self.padded_bytes + 3 * self.wire_stage_bytes + self.scale_bytes
        return 2 * self.stage_bytes + 3 * self.wire_stage_bytes + self.scale_bytes

    def to_row(self) -> dict:
        return {
            "ring_path": self.path,
            "chunk_bytes": self.chunk_bytes,
            "stage_bytes": self.stage_bytes,
            "n_tiles": self.n_tiles,
            "steps": self.steps,
            "world": self.world,
            "payload_bytes": self.payload_bytes,
            "padded_bytes": self.padded_bytes,
            "wire_dtype": self.wire_dtype,
            "wire_stage_bytes": self.wire_stage_bytes,
            "scale_slot_bytes": self.scale_slot_bytes,
        }


def _stage_rows_for(chunk_rows: int, sublanes: int, budget_bytes: int, row_bytes: int) -> int:
    """Near-budget whole-tile staging size with minimal padding: the chunk
    is covered by ``n = ceil(k / target)`` tiles of ``s = ceil(k / n)``
    native tiles each — the smallest tile achieving the minimal tile count,
    so zero-padding waste is bounded by ``n − 1`` native tiles per chunk
    (< one staging tile) instead of collapsing to single-tile staging when
    the chunk's tile count has no divisor near the budget (e.g. a prime
    count).  When the budget divides the chunk exactly, this is the budget
    itself and padding is zero.  The wrappers slice the padding back out,
    so the external chunk layout (and the ZeRO-1 shard layout built on it)
    is identical on both paths, for every chunk size."""
    k = chunk_rows // sublanes  # chunk is tile-aligned by construction
    target = max(1, budget_bytes // (row_bytes * sublanes))
    n = -(-k // target)
    return -(-k // n) * sublanes


def _wire_geometry(stage_rows: int, wire_dtype: str, block_size: int):
    """(wire_stage_bytes, scale_slot_bytes) for one ``[stage_rows, 128]``
    fp32 staging tile under a fused codec."""
    wire_stage = stage_rows * _LANES * _FUSED_WIRE_ITEMSIZE[wire_dtype]
    if wire_dtype != "int8":
        return wire_stage, 0
    n_blocks = stage_rows * _LANES // block_size
    return wire_stage, _scale_rows(n_blocks) * _LANES * 4


def plan_ring_schedule(
    nelems: int,
    dtype,
    world: int,
    chunk_bytes: Optional[int] = None,
    rs: bool = True,
    ag: bool = True,
    wire_dtype: str = "off",
    block_size: Optional[int] = None,
) -> RingSchedule:
    """Pure planning: path selection + executed tile size for a ring
    collective over ``nelems`` elements of ``dtype`` (total payload across
    the ``world`` ring chunks).

    Selection rule: the **vmem** path runs when the whole padded payload
    fits inside one ``chunk_bytes`` staging budget ("payloads under one
    chunk" — its VMEM need is then bounded by ~3× the budget); anything
    larger takes the **hbm-stream** path, whose VMEM need is a fixed set of
    staging tiles regardless of payload size.

    ``wire_dtype`` ≠ "off" plans the fused codec kernels: the staging
    budget then also covers the fp32 scale vectors an int8 tile carries
    (the scale side channel), and the plan records the wire/scale slot
    geometry (:attr:`RingSchedule.wire_stage_bytes` /
    :attr:`RingSchedule.scale_slot_bytes`) so ``vmem_bound_bytes`` accounts
    every buffer the fused kernel actually allocates.  The external chunk
    layout is the payload dtype's on every path and codec — wire density
    never changes element→chunk assignment, so ZeRO-1 shard layouts are
    codec-independent.
    """
    dtype = jnp.dtype(dtype)
    if wire_dtype != "off":
        if block_size is None:
            block_size = _default_block_size()
        reason = fused_wire_unsupported_reason(dtype, wire_dtype, block_size)
        if reason is not None:
            raise ValueError(f"cannot plan a fused wire ring: {reason}")
    itemsize = dtype.itemsize
    tile = _tile_elems(dtype)
    sublanes = tile // _LANES
    chunk = -(-max(1, int(nelems)) // max(1, world))  # ceil elems per rank
    chunk = -(-chunk // tile) * tile                  # whole dtype tiles
    padded_bytes = world * chunk * itemsize
    budget = resolve_chunk_bytes(chunk_bytes)
    steps = (world - 1 if rs else 0) + (world - 1 if ag else 0)
    fused = wire_dtype != "off"
    blk = int(block_size) if fused and wire_dtype == "int8" else 0
    if world == 1 or padded_bytes <= budget:
        chunk_rows = chunk // _LANES
        wire_stage, scale_slot = (
            _wire_geometry(chunk_rows, wire_dtype, blk) if fused else (0, 0)
        )
        return RingSchedule(
            path="vmem", world=world, steps=steps, chunk_bytes=budget,
            stage_bytes=chunk * itemsize, n_tiles=1,
            payload_bytes=int(nelems) * itemsize, padded_bytes=padded_bytes,
            dtype=dtype.name, wire_dtype=wire_dtype, block_size=blk,
            wire_stage_bytes=wire_stage, scale_slot_bytes=scale_slot,
        )
    chunk_rows = chunk // _LANES
    # the staging budget covers what one tile actually keeps in VMEM: the
    # payload row plus, on int8 plans, its amortized fp32 scale bytes (one
    # scale per block_size elements; ceil so block 1024's fraction of a
    # byte per row still counts) — the wire_dtype-aware tile budget
    row_bytes = _LANES * itemsize
    if blk:
        row_bytes += -(-(_LANES * 4) // blk)
    stage_rows = _stage_rows_for(chunk_rows, sublanes, budget, row_bytes)
    n_tiles = -(-chunk_rows // stage_rows)
    wire_stage, scale_slot = (
        _wire_geometry(stage_rows, wire_dtype, blk) if fused else (0, 0)
    )
    return RingSchedule(
        path="hbm-stream", world=world, steps=steps, chunk_bytes=budget,
        stage_bytes=stage_rows * _LANES * itemsize,
        n_tiles=n_tiles,
        payload_bytes=int(nelems) * itemsize,
        # the kernel's working footprint: each chunk zero-padded to whole
        # staging tiles (the wrappers slice the padding back out)
        padded_bytes=world * n_tiles * stage_rows * _LANES * itemsize,
        dtype=dtype.name, wire_dtype=wire_dtype, block_size=blk,
        wire_stage_bytes=wire_stage, scale_slot_bytes=scale_slot,
    )


# --------------------------------------------------------------------------- #
# kernel bodies
# --------------------------------------------------------------------------- #

def _ring_kernel(
    x_ref,
    out_ref,
    work,
    comm,
    send_sem,
    recv_sem,
    cap_sem,
    *,
    world: int,
    axis_name: str,
    do_reduce_scatter: bool,
    do_all_gather: bool,
):
    """VMEM-resident unidirectional ring walk: reduce-scatter phase then
    all-gather phase.

    ``x_ref``/``work`` are ``[world, S, 128]`` (chunk-major); ``comm`` is the
    ``[2, S, 128]`` double-buffered staging area written by the left
    neighbor's RDMA.
    """
    my_id = lax.axis_index(axis_name)
    right = (my_id + 1) % world
    left = (my_id + world - 1) % world

    # entry barrier with both neighbors (they write into our comm buffer)
    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(barrier, inc=1, device_id=left)
    pltpu.semaphore_signal(barrier, inc=1, device_id=right)
    pltpu.semaphore_wait(barrier, 2)

    work[...] = x_ref[...]

    n_rs = world - 1 if do_reduce_scatter else 0
    n_ag = world - 1 if do_all_gather else 0
    total_steps = n_rs + n_ag

    for step in range(total_steps):
        slot = step % 2
        in_rs = step < n_rs
        if in_rs:
            send_idx = (my_id + world - step) % world
            recv_idx = (my_id + world - step - 1) % world
        else:
            ag = step - n_rs
            # after RS each rank owns the fully reduced chunk (my_id + 1);
            # without RS (pure all-gather) it owns chunk my_id
            own = 1 if do_reduce_scatter else 0
            send_idx = (my_id + world + own - ag) % world
            recv_idx = (my_id + world + own - ag - 1) % world

        # flow control: slot `slot` in the right neighbor was last written at
        # step-2; wait for the credit it returns after consuming that write
        if step >= 2:
            pltpu.semaphore_wait(cap_sem, 1)

        rdma = pltpu.make_async_remote_copy(
            src_ref=work.at[send_idx],
            dst_ref=comm.at[slot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[slot],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()  # outbound sent AND left neighbor's chunk landed

        if in_rs:
            work[recv_idx] = work[recv_idx] + comm[slot]
        else:
            work[recv_idx] = comm[slot]

        # return a capacity credit upstream: slot is free for reuse
        pltpu.semaphore_signal(cap_sem, inc=1, device_id=left)

    # drain outstanding credits so no signal outlives the kernel
    tail = min(2, total_steps)
    for _ in range(tail):
        pltpu.semaphore_wait(cap_sem, 1)
    out_ref[...] = work[...]


def _stream_ring_kernel(
    x_ref,
    out_ref,
    send_stage,
    acc,
    comm,
    local_sem,
    send_sem,
    recv_sem,
    cap_sem,
    *,
    world: int,
    axis_name: str,
    do_reduce_scatter: bool,
    do_all_gather: bool,
    n_tiles: int,
    stage_rows: int,
    total_iters: int,
):
    """HBM-streaming ring walk: grid = (ring step, tile within the chunk).

    ``x_ref``/``out_ref`` are HBM-resident ``[world, R, 128]``; ``out_ref``
    doubles as the work buffer (seeded from ``x_ref`` at the first grid
    iteration).  Each grid iteration moves one ``[stage_rows, 128]`` tile:
    local DMA stages the outbound tile into VMEM, one RDMA ships it to the
    right neighbor's double-buffered ``comm`` slot, and the landed inbound
    tile is folded back into HBM (accumulate during reduce-scatter, adopt
    during all-gather).  The credit protocol is the VMEM kernel's, carried
    across grid steps over the flattened (step × tile) counter: slot ``i %
    2`` is reused only after the downstream neighbor's credit from
    iteration ``i − 2`` arrives, so a fast sender can never clobber an
    unconsumed staging slot — the reference's fixed-staging flow control
    (trans.cu:73-98) at grid scope.
    """
    step = pl.program_id(0)
    tile = pl.program_id(1)
    it = step * n_tiles + tile
    my_id = lax.axis_index(axis_name)
    right = (my_id + 1) % world
    left = (my_id + world - 1) % world

    n_rs = world - 1 if do_reduce_scatter else 0

    @pl.when(it == 0)
    def _enter():
        # entry barrier with both neighbors, then seed the HBM work buffer
        # (out_ref) from the input — the one whole-payload DMA of the path
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(barrier, inc=1, device_id=left)
        pltpu.semaphore_signal(barrier, inc=1, device_id=right)
        pltpu.semaphore_wait(barrier, 2)
        seed = pltpu.make_async_copy(x_ref, out_ref, local_sem)
        seed.start()
        seed.wait()

    # chunk walk indices (the VMEM kernel's formulas on a traced step; the
    # +2·world keeps every branch of the where non-negative under floor-mod)
    in_rs = step < n_rs
    own = 1 if do_reduce_scatter else 0
    ag = step - n_rs
    send_idx = jnp.where(
        in_rs,
        (my_id + 2 * world - step) % world,
        (my_id + 2 * world + own - ag) % world,
    )
    recv_idx = jnp.where(
        in_rs,
        (my_id + 2 * world - step - 1) % world,
        (my_id + 2 * world + own - ag - 1) % world,
    )
    slot = it % 2
    row0 = tile * stage_rows
    rows = pl.ds(row0, stage_rows)

    # stage the outbound tile: HBM work → fixed VMEM staging.  One buffer
    # suffices: the RDMA below completes (send side included) inside this
    # iteration, so the staging is always free for the next tile — the
    # double buffering that matters for flow control is the *comm* slots,
    # which the left neighbor writes asynchronously
    stage_in = pltpu.make_async_copy(
        out_ref.at[send_idx, rows], send_stage, local_sem
    )
    stage_in.start()
    stage_in.wait()

    @pl.when(it >= 2)
    def _credit_wait():
        pltpu.semaphore_wait(cap_sem, 1)

    rdma = pltpu.make_async_remote_copy(
        src_ref=send_stage,
        dst_ref=comm.at[slot],
        send_sem=send_sem.at[slot],
        recv_sem=recv_sem.at[slot],
        device_id=right,
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )
    rdma.start()
    rdma.wait()  # outbound sent AND left neighbor's tile landed

    @pl.when(in_rs)
    def _reduce():
        # accumulate: HBM tile → VMEM, add the landed tile, DMA back
        acc_in = pltpu.make_async_copy(out_ref.at[recv_idx, rows], acc, local_sem)
        acc_in.start()
        acc_in.wait()
        acc[...] = acc[...] + comm[slot]
        acc_out = pltpu.make_async_copy(acc, out_ref.at[recv_idx, rows], local_sem)
        acc_out.start()
        acc_out.wait()

    @pl.when(jnp.logical_not(in_rs))
    def _adopt():
        adopt = pltpu.make_async_copy(comm.at[slot], out_ref.at[recv_idx, rows], local_sem)
        adopt.start()
        adopt.wait()

    # return a capacity credit upstream: slot is free for reuse
    pltpu.semaphore_signal(cap_sem, inc=1, device_id=left)

    @pl.when(it == total_iters - 1)
    def _drain():
        for _ in range(min(2, total_iters)):
            pltpu.semaphore_wait(cap_sem, 1)


# --------------------------------------------------------------------------- #
# fused wire-codec kernels: quantize/dequantize inside the VMEM staging
# --------------------------------------------------------------------------- #
#
# The EQuARX move (PAPERS.md) on the staged pipeline: each staging tile is
# encoded to the wire dtype *before* its RDMA and decoded+accumulated in
# fp32 on receive, so codec compute hides behind the RDMA of the
# neighboring tile and the fabric carries ~4x fewer bytes on the same
# credit-based flow control.  Bit-contract with the unfused quantized
# ppermute ring (quant/ring.py):
#
# - the block math is quant/codec.py's, verbatim: per-block absmax/127
#   fp32 scales, deterministic round, clip to [-127, 127].  Blocks nest in
#   staging tiles (fused_wire_unsupported_reason enforces the geometry),
#   so tile-wise encoding produces the same bits as chunk-wise encoding;
# - reduce-scatter dequant-accumulates-requants per hop in fp32 — only the
#   wire is narrow, the running sum never is;
# - all-gather encodes each reduced chunk ONCE (at its owner) and forwards
#   the encoded bits verbatim.  The int8 *codes* are exactly recoverable
#   by re-quantizing the decoded fp32 values against the original scale
#   (|q| <= 127 makes round(q*s/s) == q in fp32); the *scale* happens to
#   re-derive stably too (fl(fl(127*s)/127) == s for 127-quotient scales)
#   but only as a numerical accident of the quotient form — for raw values
#   the same expression drifts an ulp ~1% of the time.  So the scales ride
#   a side-channel store ([world, s_rows, 128] fp32; VMEM scratch on the
#   vmem path, an HBM side output on the stream path) and are forwarded
#   bit-verbatim: rank-to-rank bit identity rests on construction, not on
#   the accident holding for every backend.  Every rank, owner included,
#   adopts the decoded wire value, so results are bit-identical rank to
#   rank (and match the unfused ring up to the FP contraction of the
#   per-hop accumulate — XLA may fuse the dequantize multiply into an FMA
#   with the add differently across programs, a <= 2-ulp effect; the wire
#   bits and add order are op-identical).


def _wire_scales_of(s_tile: jnp.ndarray, n_blocks: int) -> jnp.ndarray:
    """[s_rows, 128] scale tile → the [n_blocks] fp32 scale vector."""
    return s_tile.reshape(-1)[:n_blocks]


def _fused_block_scales(vals: jnp.ndarray, rows_per_block: int) -> jnp.ndarray:
    """Per-block fp32 scales of one ``[R, 128]`` tile — the exact absmax/127
    derivation of ``quant/codec.quantize_int8``."""
    n_blocks = vals.shape[0] // rows_per_block
    blocks = vals.reshape(n_blocks, rows_per_block, _LANES)
    absmax = jnp.max(jnp.abs(blocks), axis=(1, 2))
    return jnp.where(absmax > 0, absmax / 127.0, 1.0)


def _fused_encode(vals: jnp.ndarray, wire_dtype: str, rows_per_block: int):
    """Encode one ``[R, 128]`` fp32 tile: returns ``(wire, scales | None)``
    with the exact ops of ``quant/codec.quantize_int8`` (deterministic
    rounding) so fused and unfused wire bits can never drift."""
    if wire_dtype == "bf16":
        return vals.astype(jnp.bfloat16), None
    scales = _fused_block_scales(vals, rows_per_block)
    n_blocks = vals.shape[0] // rows_per_block
    blocks = vals.reshape(n_blocks, rows_per_block, _LANES)
    q = jnp.clip(jnp.round(blocks / scales[:, None, None]), -127.0, 127.0)
    return q.astype(jnp.int8).reshape(vals.shape), scales


def _fused_requantize(
    vals: jnp.ndarray, scales: jnp.ndarray, rows_per_block: int
) -> jnp.ndarray:
    """Re-derive the int8 codes of already-decoded values against their
    original (forwarded) scales — exact: ``round((q·s)/s) == q`` for
    ``|q| <= 127`` in fp32, so the all-gather forwards bits verbatim
    without carrying the code arrays through HBM."""
    n_blocks = vals.shape[0] // rows_per_block
    blocks = vals.reshape(n_blocks, rows_per_block, _LANES)
    q = jnp.clip(jnp.round(blocks / scales[:, None, None]), -127.0, 127.0)
    return q.astype(jnp.int8).reshape(vals.shape)


def _fused_decode(
    wire: jnp.ndarray,
    scales: Optional[jnp.ndarray],
    wire_dtype: str,
    rows_per_block: int,
) -> jnp.ndarray:
    """Decode one wire tile back to fp32 (``quant/codec.dequantize_int8``
    ops, tile-shaped)."""
    if wire_dtype == "bf16":
        return wire.astype(jnp.float32)
    n_blocks = wire.shape[0] // rows_per_block
    blocks = wire.reshape(n_blocks, rows_per_block, _LANES).astype(jnp.float32)
    return (blocks * scales[:, None, None]).reshape(wire.shape)


def _scales_to_tile(scales: jnp.ndarray, s_rows: int) -> jnp.ndarray:
    """[n_blocks] scale vector → the [s_rows, 128] side-channel tile
    (padding scales are 1.0, the all-zero-block convention)."""
    pad = s_rows * _LANES - scales.shape[0]
    return jnp.concatenate(
        [scales, jnp.ones((pad,), jnp.float32)]
    ).reshape(s_rows, _LANES)


def _fused_ring_kernel(
    x_ref,
    out_ref,
    work,
    wire_send,
    scale_send,
    comm_w,
    comm_s,
    scale_store,
    send_w_sem,
    recv_w_sem,
    send_s_sem,
    recv_s_sem,
    cap_sem,
    *,
    world: int,
    axis_name: str,
    do_reduce_scatter: bool,
    do_all_gather: bool,
    wire_dtype: str,
    rows_per_block: int,
    s_rows: int,
):
    """VMEM-resident fused ring walk: the ``_ring_kernel`` schedule with
    the wire codec applied per chunk.  ``wire_send``/``comm_w`` carry the
    encoded chunk (int8 codes or bf16), ``scale_send``/``comm_s`` the fp32
    block scales (int8 only), ``scale_store`` the per-chunk scales the
    all-gather forwards verbatim.  One capacity credit covers both slot
    arrays — the flow control is the unfused kernel's, unchanged."""
    my_id = lax.axis_index(axis_name)
    right = (my_id + 1) % world
    left = (my_id + world - 1) % world
    int8 = wire_dtype == "int8"
    n_blocks = work.shape[1] * _LANES // (rows_per_block * _LANES) if int8 else 0

    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(barrier, inc=1, device_id=left)
    pltpu.semaphore_signal(barrier, inc=1, device_id=right)
    pltpu.semaphore_wait(barrier, 2)

    work[...] = x_ref[...]

    n_rs = world - 1 if do_reduce_scatter else 0
    n_ag = world - 1 if do_all_gather else 0
    total_steps = n_rs + n_ag

    for step in range(total_steps):
        slot = step % 2
        in_rs = step < n_rs
        if in_rs:
            send_idx = (my_id + world - step) % world
            recv_idx = (my_id + world - step - 1) % world
        else:
            ag = step - n_rs
            own = 1 if do_reduce_scatter else 0
            send_idx = (my_id + world + own - ag) % world
            recv_idx = (my_id + world + own - ag - 1) % world

        vals = work[send_idx]
        if in_rs or step == n_rs:
            # RS hops re-encode the moving partial; the first AG hop is
            # the once-per-reduced-chunk encode that defines the bits
            wire, scales = _fused_encode(vals, wire_dtype, rows_per_block)
        else:
            # later AG hops forward verbatim: stored scales, exact codes
            scales = (
                _wire_scales_of(scale_store[send_idx], n_blocks)
                if int8 else None
            )
            wire = (
                _fused_requantize(vals, scales, rows_per_block)
                if int8 else vals.astype(jnp.bfloat16)
            )
        wire_send[...] = wire
        if int8:
            scale_send[...] = _scales_to_tile(scales, s_rows)
        if not in_rs and step == n_rs:
            # the owner adopts its own DECODED chunk: every rank must see
            # the same post-codec value, owner included (quant/ring.py)
            work[send_idx] = _fused_decode(
                wire, scales, wire_dtype, rows_per_block
            )

        if step >= 2:
            pltpu.semaphore_wait(cap_sem, 1)

        rdma_w = pltpu.make_async_remote_copy(
            src_ref=wire_send,
            dst_ref=comm_w.at[slot],
            send_sem=send_w_sem.at[slot],
            recv_sem=recv_w_sem.at[slot],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma_w.start()
        if int8:
            rdma_s = pltpu.make_async_remote_copy(
                src_ref=scale_send,
                dst_ref=comm_s.at[slot],
                send_sem=send_s_sem.at[slot],
                recv_sem=recv_s_sem.at[slot],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma_s.start()
            rdma_s.wait()
        rdma_w.wait()  # outbound sent AND left neighbor's arrays landed

        landed_scales = (
            _wire_scales_of(comm_s[slot], n_blocks) if int8 else None
        )
        landed = _fused_decode(
            comm_w[slot], landed_scales, wire_dtype, rows_per_block
        )
        if in_rs:
            work[recv_idx] = work[recv_idx] + landed
        else:
            work[recv_idx] = landed
            if int8:
                # bank the forwarded-bit scales for the next AG hop
                scale_store[recv_idx] = comm_s[slot]

        pltpu.semaphore_signal(cap_sem, inc=1, device_id=left)

    tail = min(2, total_steps)
    for _ in range(tail):
        pltpu.semaphore_wait(cap_sem, 1)
    out_ref[...] = work[...]


def _fused_stream_ring_kernel(
    x_ref,
    out_ref,
    scales_hbm,
    send_stage,
    acc,
    wire_send,
    scale_send,
    comm_w,
    comm_s,
    local_sem,
    send_w_sem,
    recv_w_sem,
    send_s_sem,
    recv_s_sem,
    cap_sem,
    *,
    world: int,
    axis_name: str,
    do_reduce_scatter: bool,
    do_all_gather: bool,
    n_tiles: int,
    stage_rows: int,
    total_iters: int,
    wire_dtype: str,
    rows_per_block: int,
    s_rows: int,
):
    """HBM-streaming fused ring walk: ``_stream_ring_kernel``'s grid and
    credit protocol with the codec in the staging tiles.  Each iteration
    stages one fp32 tile, encodes it in VMEM (fresh on RS hops and the
    first AG hop; re-derived against forwarded scales afterwards), ships
    the wire arrays (codes + scale side channel), and folds the landed
    tile back into HBM in fp32.  ``scales_hbm`` is the per-chunk scale
    store ([world, n_tiles·s_rows, 128] fp32, an ANY-space side output)
    the all-gather forwards bits from."""
    step = pl.program_id(0)
    tile = pl.program_id(1)
    it = step * n_tiles + tile
    my_id = lax.axis_index(axis_name)
    right = (my_id + 1) % world
    left = (my_id + world - 1) % world
    int8 = wire_dtype == "int8"
    n_blocks = stage_rows * _LANES // (rows_per_block * _LANES) if int8 else 0

    n_rs = world - 1 if do_reduce_scatter else 0

    @pl.when(it == 0)
    def _enter():
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(barrier, inc=1, device_id=left)
        pltpu.semaphore_signal(barrier, inc=1, device_id=right)
        pltpu.semaphore_wait(barrier, 2)
        seed = pltpu.make_async_copy(x_ref, out_ref, local_sem)
        seed.start()
        seed.wait()

    in_rs = step < n_rs
    own = 1 if do_reduce_scatter else 0
    ag = step - n_rs
    send_idx = jnp.where(
        in_rs,
        (my_id + 2 * world - step) % world,
        (my_id + 2 * world + own - ag) % world,
    )
    recv_idx = jnp.where(
        in_rs,
        (my_id + 2 * world - step - 1) % world,
        (my_id + 2 * world + own - ag - 1) % world,
    )
    slot = it % 2
    rows = pl.ds(tile * stage_rows, stage_rows)
    srows = pl.ds(tile * s_rows, s_rows)
    # fresh encode on RS hops and the first AG hop (the once-per-reduced-
    # chunk encode); later AG hops re-derive codes against forwarded scales
    fresh = jnp.logical_or(in_rs, ag == 0)

    stage_in = pltpu.make_async_copy(
        out_ref.at[send_idx, rows], send_stage, local_sem
    )
    stage_in.start()
    stage_in.wait()

    if int8:

        @pl.when(jnp.logical_not(fresh))
        def _load_forwarded_scales():
            fwd = pltpu.make_async_copy(
                scales_hbm.at[send_idx, srows], scale_send, local_sem
            )
            fwd.start()
            fwd.wait()

    vals = send_stage[...]
    if int8:

        @pl.when(fresh)
        def _derive_fresh_scales():
            # only fresh hops pay the absmax pass; forwarded hops already
            # DMA'd the original scale bits into scale_send above
            scale_send[...] = _scales_to_tile(
                _fused_block_scales(vals, rows_per_block), s_rows
            )

        scales = _wire_scales_of(scale_send[...], n_blocks)
        # one requantize serves both cases: with fresh scales it IS the
        # encode (same round/clip ops), with forwarded scales it is exact
        wire_send[...] = _fused_requantize(vals, scales, rows_per_block)
    else:
        scales = None
        wire_send[...] = vals.astype(jnp.bfloat16)

    @pl.when(jnp.logical_and(jnp.logical_not(in_rs), ag == 0))
    def _adopt_own():
        # the owner adopts its own decoded tile: every rank must end with
        # the same post-codec bits, owner included
        acc[...] = _fused_decode(
            wire_send[...],
            _wire_scales_of(scale_send[...], n_blocks) if int8 else None,
            wire_dtype, rows_per_block,
        )
        own_out = pltpu.make_async_copy(
            acc, out_ref.at[send_idx, rows], local_sem
        )
        own_out.start()
        own_out.wait()

    @pl.when(it >= 2)
    def _credit_wait():
        pltpu.semaphore_wait(cap_sem, 1)

    rdma_w = pltpu.make_async_remote_copy(
        src_ref=wire_send,
        dst_ref=comm_w.at[slot],
        send_sem=send_w_sem.at[slot],
        recv_sem=recv_w_sem.at[slot],
        device_id=right,
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )
    rdma_w.start()
    if int8:
        rdma_s = pltpu.make_async_remote_copy(
            src_ref=scale_send,
            dst_ref=comm_s.at[slot],
            send_sem=send_s_sem.at[slot],
            recv_sem=recv_s_sem.at[slot],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma_s.start()
        rdma_s.wait()
    rdma_w.wait()  # outbound sent AND left neighbor's arrays landed

    landed_scales = (
        _wire_scales_of(comm_s[slot], n_blocks) if int8 else None
    )

    @pl.when(in_rs)
    def _reduce():
        acc_in = pltpu.make_async_copy(
            out_ref.at[recv_idx, rows], acc, local_sem
        )
        acc_in.start()
        acc_in.wait()
        acc[...] = acc[...] + _fused_decode(
            comm_w[slot], landed_scales, wire_dtype, rows_per_block
        )
        acc_out = pltpu.make_async_copy(
            acc, out_ref.at[recv_idx, rows], local_sem
        )
        acc_out.start()
        acc_out.wait()

    @pl.when(jnp.logical_not(in_rs))
    def _adopt():
        acc[...] = _fused_decode(
            comm_w[slot], landed_scales, wire_dtype, rows_per_block
        )
        adopt = pltpu.make_async_copy(
            acc, out_ref.at[recv_idx, rows], local_sem
        )
        adopt.start()
        adopt.wait()
        if int8:
            # bank the forwarded-bit scales for the next AG hop
            bank = pltpu.make_async_copy(
                comm_s.at[slot], scales_hbm.at[recv_idx, srows], local_sem
            )
            bank.start()
            bank.wait()

    pltpu.semaphore_signal(cap_sem, inc=1, device_id=left)

    @pl.when(it == total_iters - 1)
    def _drain():
        for _ in range(min(2, total_iters)):
            pltpu.semaphore_wait(cap_sem, 1)


# --------------------------------------------------------------------------- #
# shard-level wrappers (call inside shard_map)
# --------------------------------------------------------------------------- #

def _pad_chunks(flat: jnp.ndarray, world: int):
    """Pad to world × (whole dtype-native tiles) and reshape chunk-major."""
    tile = _tile_elems(flat.dtype)
    chunk = -(-flat.size // world)          # ceil
    chunk = -(-chunk // tile) * tile        # round up to full tiles
    padded = jnp.zeros((world * chunk,), flat.dtype).at[: flat.size].set(flat)
    return padded.reshape(world, chunk // _LANES, _LANES), chunk


def _check_ring_supported() -> None:
    from adapcc_tpu.compat import ring_kernels_supported

    if not ring_kernels_supported():
        # the one funnel every ring entry point (and so --zero1-ring,
        # engine.ring_*, the benchmarks) routes through: fail with guidance
        # here rather than a cryptic Mosaic/legacy-pallas error deeper in
        raise RuntimeError(
            "Pallas ICI ring kernels need a real TPU or the Mosaic TPU "
            "interpret mode (jax >= 0.5); this build has neither — use the "
            "XLA collective path instead (e.g. drop --zero1-ring)"
        )


def _check_fused_wire(dtype, wire_dtype: str, block_size: Optional[int]) -> None:
    """Loud reject where fused codec semantics don't apply — running fp32
    silently under a requested codec would invalidate every wire A/B."""
    reason = fused_wire_unsupported_reason(dtype, wire_dtype, block_size)
    if reason is not None:
        raise ValueError(
            f"wire_dtype={wire_dtype!r} cannot run on the fused Pallas ring: "
            f"{reason}"
        )


def _run_fused_ring_chunks(
    chunks: jnp.ndarray,
    plan: RingSchedule,
    *,
    world,
    axis_name,
    rs,
    ag,
    interpret,
    block_size: int,
):
    """Dispatch a fused-codec plan on a pre-chunked ``[world, S, 128]``
    fp32 array (both paths).  The wrappers slice stream-path padding back
    out, exactly like the unfused dispatch."""
    wire_dtype = plan.wire_dtype
    int8 = wire_dtype == "int8"
    wire_jnp = jnp.int8 if int8 else jnp.bfloat16
    rows_per_block = (block_size // _LANES) if int8 else 1
    chunk_rows = chunks.shape[1]
    if plan.path == "vmem":
        s_rows = _scale_rows(chunk_rows // rows_per_block) if int8 else 0
        body = functools.partial(
            _fused_ring_kernel,
            world=world,
            axis_name=axis_name,
            do_reduce_scatter=rs,
            do_all_gather=ag,
            wire_dtype=wire_dtype,
            rows_per_block=rows_per_block,
            s_rows=s_rows,
        )
        wire_shape = (chunk_rows, _LANES)
        scale_shape = (s_rows, _LANES)
        scratch = [
            pltpu.VMEM(chunks.shape, chunks.dtype),              # work
            pltpu.VMEM(wire_shape, wire_jnp),                    # wire send
        ]
        if int8:
            scratch.append(pltpu.VMEM(scale_shape, jnp.float32))  # scale send
        scratch.append(pltpu.VMEM((2,) + wire_shape, wire_jnp))   # comm codes
        if int8:
            scratch.extend([
                pltpu.VMEM((2,) + scale_shape, jnp.float32),      # comm scales
                pltpu.VMEM((world,) + scale_shape, jnp.float32),  # scale store
            ])
        scratch.extend([
            pltpu.SemaphoreType.DMA((2,)),                        # send codes
            pltpu.SemaphoreType.DMA((2,)),                        # recv codes
        ])
        if int8:
            scratch.extend([
                pltpu.SemaphoreType.DMA((2,)),                    # send scales
                pltpu.SemaphoreType.DMA((2,)),                    # recv scales
            ])
        scratch.append(pltpu.SemaphoreType.REGULAR)               # capacity

        if int8:
            kernel = body
        else:
            # bf16 needs no scale side channel: bind the unused refs to
            # None so the plan's VMEM accounting matches the allocations
            def kernel(x_ref, out_ref, work, wire_send, comm_w,
                       send_w, recv_w, cap_sem):
                return body(
                    x_ref, out_ref, work, wire_send, None, comm_w, None,
                    None, send_w, recv_w, None, None, cap_sem,
                )

        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(chunks.shape, chunks.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=scratch,
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True, collective_id=0
            ),
            interpret=_interpret_params(interpret),
        )(chunks)

    stage_rows = plan.stage_bytes // (_LANES * jnp.dtype(chunks.dtype).itemsize)
    s_rows = _scale_rows(stage_rows // rows_per_block) if int8 else 0
    total_iters = plan.steps * plan.n_tiles
    padded_rows = plan.n_tiles * stage_rows
    if padded_rows != chunk_rows:
        chunks = jnp.pad(chunks, ((0, 0), (0, padded_rows - chunk_rows), (0, 0)))
    body = functools.partial(
        _fused_stream_ring_kernel,
        world=world,
        axis_name=axis_name,
        do_reduce_scatter=rs,
        do_all_gather=ag,
        n_tiles=plan.n_tiles,
        stage_rows=stage_rows,
        total_iters=total_iters,
        wire_dtype=wire_dtype,
        rows_per_block=rows_per_block,
        s_rows=s_rows,
    )
    tile_shape = (stage_rows, _LANES)
    scale_shape = (s_rows, _LANES)
    payload_shape = jax.ShapeDtypeStruct(chunks.shape, chunks.dtype)
    scratch = [
        pltpu.VMEM(tile_shape, chunks.dtype),              # fp32 send staging
        pltpu.VMEM(tile_shape, chunks.dtype),              # fp32 accumulate
        pltpu.VMEM(tile_shape, wire_jnp),                  # wire send
    ]
    if int8:
        scratch.append(pltpu.VMEM(scale_shape, jnp.float32))  # scale send
    scratch.append(pltpu.VMEM((2,) + tile_shape, wire_jnp))   # comm codes
    if int8:
        scratch.append(
            pltpu.VMEM((2,) + scale_shape, jnp.float32)       # comm scales
        )
    scratch.extend([
        pltpu.SemaphoreType.DMA(()),                          # local DMAs
        pltpu.SemaphoreType.DMA((2,)),                        # send codes
        pltpu.SemaphoreType.DMA((2,)),                        # recv codes
    ])
    if int8:
        scratch.extend([
            pltpu.SemaphoreType.DMA((2,)),                    # send scales
            pltpu.SemaphoreType.DMA((2,)),                    # recv scales
        ])
    scratch.append(pltpu.SemaphoreType.REGULAR)               # capacity
    if int8:
        kernel = body
        out_shape = (
            payload_shape,
            # per-chunk scale store: the AG's forwarded-bit side channel
            jax.ShapeDtypeStruct(
                (world, plan.n_tiles * s_rows, _LANES), jnp.float32
            ),
        )
        out_specs = (
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        )
    else:
        # bf16 needs no scale side channel or store: bind the unused refs
        # to None so the plan's VMEM accounting matches the allocations
        def kernel(x_ref, out_ref, send_stage, acc, wire_send, comm_w,
                   local_sem, send_w, recv_w, cap_sem):
            return body(
                x_ref, out_ref, None, send_stage, acc, wire_send, None,
                comm_w, None, local_sem, send_w, recv_w, None, None,
                cap_sem,
            )

        out_shape = payload_shape
        out_specs = pl.BlockSpec(memory_space=pltpu.ANY)
    result = pl.pallas_call(
        kernel,
        grid=(plan.steps, plan.n_tiles),
        out_shape=out_shape,
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=out_specs,
        scratch_shapes=scratch,
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True,
            collective_id=0,
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=_interpret_params(interpret),
    )(chunks)
    out = result[0] if int8 else result
    return out[:, :chunk_rows] if padded_rows != chunk_rows else out


def _run_ring_chunks(
    chunks: jnp.ndarray,
    *,
    world,
    axis_name,
    rs,
    ag,
    interpret,
    chunk_bytes: Optional[int] = None,
    wire_dtype: str = "off",
    block_size: Optional[int] = None,
):
    """Run the ring on a pre-chunked ``[world, S, 128]`` array, dispatching
    to the VMEM-resident or HBM-streaming kernel per the planned schedule
    (the fused codec variants when ``wire_dtype`` names one)."""
    if wire_dtype != "off":
        # codec-semantics reject comes FIRST: it holds on every build,
        # and a kernel-support RuntimeError must not mask it
        _check_fused_wire(chunks.dtype, wire_dtype, block_size)
        if block_size is None:
            block_size = _default_block_size()
    _check_ring_supported()
    plan = plan_ring_schedule(
        chunks.size, chunks.dtype, world, chunk_bytes, rs=rs, ag=ag,
        wire_dtype=wire_dtype, block_size=block_size,
    )
    if wire_dtype != "off":
        return _run_fused_ring_chunks(
            chunks, plan, world=world, axis_name=axis_name, rs=rs, ag=ag,
            interpret=interpret, block_size=int(block_size),
        )
    if plan.path == "vmem":
        kernel = functools.partial(
            _ring_kernel,
            world=world,
            axis_name=axis_name,
            do_reduce_scatter=rs,
            do_all_gather=ag,
        )
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(chunks.shape, chunks.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[
                pltpu.VMEM(chunks.shape, chunks.dtype),                # work
                pltpu.VMEM((2,) + chunks.shape[1:], chunks.dtype),     # comm slots
                pltpu.SemaphoreType.DMA((2,)),                         # send
                pltpu.SemaphoreType.DMA((2,)),                         # recv
                pltpu.SemaphoreType.REGULAR,                           # capacity
            ],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True, collective_id=0
            ),
            interpret=_interpret_params(interpret),
        )(chunks)

    stage_rows = plan.stage_bytes // (_LANES * jnp.dtype(chunks.dtype).itemsize)
    total_iters = plan.steps * plan.n_tiles
    # zero-pad each chunk to whole staging tiles (bounded by < one tile per
    # chunk, see _stage_rows_for) and slice the padding back out below, so
    # callers see the legacy tile-aligned layout on both paths
    chunk_rows = chunks.shape[1]
    padded_rows = plan.n_tiles * stage_rows
    if padded_rows != chunk_rows:
        chunks = jnp.pad(chunks, ((0, 0), (0, padded_rows - chunk_rows), (0, 0)))
    kernel = functools.partial(
        _stream_ring_kernel,
        world=world,
        axis_name=axis_name,
        do_reduce_scatter=rs,
        do_all_gather=ag,
        n_tiles=plan.n_tiles,
        stage_rows=stage_rows,
        total_iters=total_iters,
    )
    tile_shape = (stage_rows, _LANES)
    out = pl.pallas_call(
        kernel,
        grid=(plan.steps, plan.n_tiles),
        out_shape=jax.ShapeDtypeStruct(chunks.shape, chunks.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[
            pltpu.VMEM(tile_shape, chunks.dtype),          # send staging
            pltpu.VMEM(tile_shape, chunks.dtype),          # accumulate staging
            pltpu.VMEM((2,) + tile_shape, chunks.dtype),   # comm slots
            pltpu.SemaphoreType.DMA(()),                   # local DMAs
            pltpu.SemaphoreType.DMA((2,)),                 # send
            pltpu.SemaphoreType.DMA((2,)),                 # recv
            pltpu.SemaphoreType.REGULAR,                   # capacity
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True,
            collective_id=0,
            # the ring walk is stateful: both grid dims must run in order
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=_interpret_params(interpret),
    )(chunks)
    return out[:, :chunk_rows] if padded_rows != chunk_rows else out


def _run_ring(
    x: jnp.ndarray, *, world, axis_name, rs, ag, interpret, chunk_bytes=None,
    wire_dtype="off", block_size=None,
):
    chunks, chunk = _pad_chunks(x.reshape(-1), world)
    out = _run_ring_chunks(
        chunks, world=world, axis_name=axis_name, rs=rs, ag=ag,
        interpret=interpret, chunk_bytes=chunk_bytes,
        wire_dtype=wire_dtype, block_size=block_size,
    )
    return out, chunk


def ring_allreduce_shard(
    x: jnp.ndarray,
    world: int,
    axis_name: str = RANKS_AXIS,
    interpret: bool = False,
    chunk_bytes: Optional[int] = None,
    wire_dtype: str = "off",
    block_size: Optional[int] = None,
) -> jnp.ndarray:
    """Sum-allreduce via ring reduce-scatter + ring all-gather.

    Bandwidth-optimal (2·(world−1)/world of the buffer per link), the same
    schedule family the reference benchmarks against NCCL rings
    (nccl-perf/tree/all_reduce.cu).  ``chunk_bytes`` is the staging
    granularity (synthesized by the strategy plane; env-overridable): payloads
    above it stream through HBM, below it stay VMEM-resident.

    ``wire_dtype`` names a fused wire codec (``bf16`` | ``int8``): staging
    tiles are encoded before their RDMA and decoded+accumulated in fp32 on
    receive, the all-gather forwards each reduced chunk's encoded bits
    verbatim — results are bit-identical rank to rank and match the unfused
    ``quant/ring.py`` path wherever the chunk layouts coincide.  Rejects
    loudly where codec semantics don't apply (non-fp32 payloads, block
    sizes that can't tile VMEM) — never silently runs fp32.
    """
    if world == 1:
        return x
    out, _ = _run_ring(
        x, world=world, axis_name=axis_name, rs=True, ag=True,
        interpret=interpret, chunk_bytes=chunk_bytes,
        wire_dtype=wire_dtype, block_size=block_size,
    )
    return out.reshape(-1)[: x.size].reshape(x.shape)


def ring_reduce_scatter_shard(
    x: jnp.ndarray,
    world: int,
    axis_name: str = RANKS_AXIS,
    interpret: bool = False,
    chunk_bytes: Optional[int] = None,
    wire_dtype: str = "off",
    block_size: Optional[int] = None,
) -> jnp.ndarray:
    """Ring reduce-scatter: returns this rank's reduced chunk (padded shape
    ``[chunk]``); rank r owns chunk ``(r + 1) % world`` of the flattened,
    tile-padded input.

    Under a fused ``wire_dtype`` every hop ships encoded tiles and
    dequant-accumulates in fp32; the owned chunk comes back as the fp32
    running sum (no final encode — a standalone RS has no forwarding phase
    to pin bits for).  Loud reject where the codec can't apply."""
    if world == 1:
        return x.reshape(-1)
    out, chunk = _run_ring(
        x, world=world, axis_name=axis_name, rs=True, ag=False,
        interpret=interpret, chunk_bytes=chunk_bytes,
        wire_dtype=wire_dtype, block_size=block_size,
    )
    my_id = lax.axis_index(axis_name)
    own = (my_id + 1) % world
    return out.reshape(world, chunk)[own]


def ring_all_gather_shard(
    x: jnp.ndarray,
    world: int,
    axis_name: str = RANKS_AXIS,
    interpret: bool = False,
    chunk_bytes: Optional[int] = None,
    wire_dtype: str = "off",
    block_size: Optional[int] = None,
) -> jnp.ndarray:
    """Ring all-gather of per-rank chunks: input is this rank's ``[chunk]``
    payload (tile-aligned), output is ``[world, chunk]`` in rank order.

    Under a fused ``wire_dtype`` each rank encodes its chunk ONCE and the
    ring forwards the encoded bits verbatim (scales ride the side
    channel), so every rank — owner included — holds the identical
    post-codec values.  Loud reject where the codec can't apply."""
    if world == 1:
        return x.reshape(1, -1)
    tile = _tile_elems(x.dtype)
    if x.size % tile:
        raise ValueError(f"all-gather payload must be tile-aligned ({tile} elems), got {x.size}")
    if wire_dtype != "off":
        # validate before any traced axis op so the reject fires eagerly
        _check_fused_wire(x.dtype, wire_dtype, block_size)
    my_id = lax.axis_index(axis_name)
    chunks = jnp.zeros((world, x.size), x.dtype)
    # place the local payload in the row this rank owns; the ring walk
    # replaces every other row with the neighbors' payloads
    chunks = lax.dynamic_update_index_in_dim(chunks, x.reshape(-1), my_id, 0)
    chunks = chunks.reshape(world, x.size // _LANES, _LANES)
    out = _run_ring_chunks(
        chunks, world=world, axis_name=axis_name, rs=False, ag=True,
        interpret=interpret, chunk_bytes=chunk_bytes,
        wire_dtype=wire_dtype, block_size=block_size,
    )
    return out.reshape(world, -1)
