"""Pallas ring collectives over ICI: the hand-tuned data plane.

The reference's performance path is hand-written CUDA: persistent per-tree
threads pushing 4 MB chunks through pre-shared IPC staging buffers with
event/flag handshakes (csrc/allreduce.cu:568-654, trans.cu:58-100).  The TPU
analog is a Pallas kernel that drives the ICI fabric directly with
``make_async_remote_copy`` RDMA — this module provides ring
reduce-scatter / all-gather / allreduce kernels with:

- **chunked pipelining**: the buffer is split into ``world`` chunks walking
  the ring, the Pallas version of the reference's chunk pipeline;
- **double-buffered staging** (2 comm slots), the analog of the reference's
  per-sibling staging slots;
- **credit-based flow control**: a receiver returns a capacity credit to its
  upstream neighbor after consuming a slot, so a fast sender can never
  clobber an unconsumed slot even on long rings — replacing the reference's
  shm bool + IPC-event handshake (trans.cu:73-98) with semaphores;
- **neighbor barrier** on entry so no device writes into a peer that has not
  allocated its buffers yet.

Two execution paths share those mechanics, selected per payload by
:func:`plan_ring_schedule`:

- **vmem** — the whole payload is VMEM-resident (input + work + comm slots),
  the right program when everything fits in one ``chunk_bytes`` staging
  budget;
- **hbm-stream** — the payload lives in HBM (``pltpu.ANY``) and a grid over
  (ring step × tile) streams ``chunk_bytes``-sized tiles through fixed VMEM
  staging: local DMA in → remote RDMA → accumulate → local DMA out, with the
  credit protocol carried across grid steps.  This is the TPU analog of the
  reference's fixed ``MAX_BUF_SIZE`` staging design (include/init.h:14-25):
  collective payload size is bounded by HBM, not by on-device scratch.

The tile granularity is the strategy plane's synthesized ``chunk_bytes``
(``Strategy.chunk_bytes`` → ``engine.ring_*`` → here), overridable for
sweeps with ``ADAPCC_RING_CHUNK_BYTES``.  The executed tile is a
near-budget whole-VMEM-tile size covering the per-rank chunk with minimal
zero padding (< one tile per chunk, sliced back out by the wrappers), so
the external chunk layout (and with it the ZeRO-1 shard layout) is
byte-identical across every chunk size — which also makes results
bit-identical: each element sees the same adds in the same ring order
regardless of tiling.

Everything is testable off-hardware: ``interpret=True`` runs the kernels
under the Pallas TPU interpreter on a virtual CPU mesh **with race detection
enabled** — a sanitizer the reference never had (SURVEY §5.2).
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from adapcc_tpu.comm.mesh import RANKS_AXIS
from adapcc_tpu.primitives import DEFAULT_CHUNK_BYTES

#: VMEM tiles are (sublanes, 128) with sublanes scaling inversely with item
#: width: fp32 → (8, 128), bf16 → (16, 128), int8/fp8 → (32, 128).  Chunks
#: are padded to whole tiles of the payload dtype (``_tile_elems``).
_LANES = 128

#: env override for the ring staging granularity (chunk-size sweeps); wins
#: over both the caller's value and the strategy's synthesized chunk_bytes
RING_CHUNK_ENV = "ADAPCC_RING_CHUNK_BYTES"


def _tile_elems(dtype) -> int:
    itemsize = jnp.dtype(dtype).itemsize
    sublanes = {4: 8, 2: 16, 1: 32}.get(itemsize, 8)
    return _LANES * sublanes


def _interpret_params(interpret):
    if interpret is True:
        return pltpu.InterpretParams(detect_races=True)
    return interpret  # False or a caller-provided InterpretParams


def resolve_chunk_bytes(chunk_bytes: Optional[int] = None) -> int:
    """The staging granularity actually in force: the ``ADAPCC_RING_CHUNK_
    BYTES`` sweep override wins, then the caller's (synthesized) value, then
    the default.  A malformed override raises — a typo silently falling back
    to the default would invalidate a chunk-size sweep (same policy as
    ADAPCC_MERGE_ROUNDS)."""
    env = os.environ.get(RING_CHUNK_ENV)
    if env is not None and env.strip():
        try:
            value = int(env)
        except ValueError:
            raise ValueError(
                f"{RING_CHUNK_ENV}={env!r}: expected a positive byte count"
            ) from None
        if value <= 0:
            raise ValueError(
                f"{RING_CHUNK_ENV}={env!r}: expected a positive byte count"
            )
        return value
    if chunk_bytes is not None:
        if chunk_bytes <= 0:
            raise ValueError(f"chunk_bytes must be positive, got {chunk_bytes}")
        return int(chunk_bytes)
    return DEFAULT_CHUNK_BYTES


@dataclass(frozen=True)
class RingSchedule:
    """The executed ring schedule — the observable contract for traces,
    benchmarks, and tests: which path ran, at what staging granularity."""

    path: str              #: "vmem" | "hbm-stream"
    world: int
    steps: int             #: ring steps (RS + AG walks)
    chunk_bytes: int       #: requested staging budget (resolved)
    stage_bytes: int       #: executed tile bytes (near-budget, minimal padding)
    n_tiles: int           #: tiles per ring step on the hbm-stream path
    payload_bytes: int     #: caller bytes before padding
    padded_bytes: int      #: world × tile-padded chunk bytes
    dtype: str = "float32"

    @property
    def vmem_bound_bytes(self) -> int:
        """Peak VMEM the data buffers need: the whole payload three times
        over (pallas input + output + work scratch) plus 2 comm slots on
        the vmem path, 4 staging tiles (1 send + 1 accumulate + 2 comm) on
        the stream path."""
        chunk = self.padded_bytes // self.world
        if self.path == "vmem":
            return 3 * self.padded_bytes + 2 * chunk
        return 4 * self.stage_bytes

    def to_row(self) -> dict:
        return {
            "ring_path": self.path,
            "chunk_bytes": self.chunk_bytes,
            "stage_bytes": self.stage_bytes,
            "n_tiles": self.n_tiles,
            "steps": self.steps,
            "world": self.world,
            "payload_bytes": self.payload_bytes,
            "padded_bytes": self.padded_bytes,
        }


def _stage_rows_for(chunk_rows: int, sublanes: int, budget_bytes: int, row_bytes: int) -> int:
    """Near-budget whole-tile staging size with minimal padding: the chunk
    is covered by ``n = ceil(k / target)`` tiles of ``s = ceil(k / n)``
    native tiles each — the smallest tile achieving the minimal tile count,
    so zero-padding waste is bounded by ``n − 1`` native tiles per chunk
    (< one staging tile) instead of collapsing to single-tile staging when
    the chunk's tile count has no divisor near the budget (e.g. a prime
    count).  When the budget divides the chunk exactly, this is the budget
    itself and padding is zero.  The wrappers slice the padding back out,
    so the external chunk layout (and the ZeRO-1 shard layout built on it)
    is identical on both paths, for every chunk size."""
    k = chunk_rows // sublanes  # chunk is tile-aligned by construction
    target = max(1, budget_bytes // (row_bytes * sublanes))
    n = -(-k // target)
    return -(-k // n) * sublanes


def plan_ring_schedule(
    nelems: int,
    dtype,
    world: int,
    chunk_bytes: Optional[int] = None,
    rs: bool = True,
    ag: bool = True,
) -> RingSchedule:
    """Pure planning: path selection + executed tile size for a ring
    collective over ``nelems`` elements of ``dtype`` (total payload across
    the ``world`` ring chunks).

    Selection rule: the **vmem** path runs when the whole padded payload
    fits inside one ``chunk_bytes`` staging budget ("payloads under one
    chunk" — its VMEM need is then bounded by ~3× the budget); anything
    larger takes the **hbm-stream** path, whose VMEM need is 4 staging
    tiles regardless of payload size.
    """
    dtype = jnp.dtype(dtype)
    itemsize = dtype.itemsize
    tile = _tile_elems(dtype)
    sublanes = tile // _LANES
    chunk = -(-max(1, int(nelems)) // max(1, world))  # ceil elems per rank
    chunk = -(-chunk // tile) * tile                  # whole dtype tiles
    padded_bytes = world * chunk * itemsize
    budget = resolve_chunk_bytes(chunk_bytes)
    steps = (world - 1 if rs else 0) + (world - 1 if ag else 0)
    if world == 1 or padded_bytes <= budget:
        return RingSchedule(
            path="vmem", world=world, steps=steps, chunk_bytes=budget,
            stage_bytes=chunk * itemsize, n_tiles=1,
            payload_bytes=int(nelems) * itemsize, padded_bytes=padded_bytes,
            dtype=dtype.name,
        )
    chunk_rows = chunk // _LANES
    stage_rows = _stage_rows_for(chunk_rows, sublanes, budget, _LANES * itemsize)
    n_tiles = -(-chunk_rows // stage_rows)
    return RingSchedule(
        path="hbm-stream", world=world, steps=steps, chunk_bytes=budget,
        stage_bytes=stage_rows * _LANES * itemsize,
        n_tiles=n_tiles,
        payload_bytes=int(nelems) * itemsize,
        # the kernel's working footprint: each chunk zero-padded to whole
        # staging tiles (the wrappers slice the padding back out)
        padded_bytes=world * n_tiles * stage_rows * _LANES * itemsize,
        dtype=dtype.name,
    )


# --------------------------------------------------------------------------- #
# kernel bodies
# --------------------------------------------------------------------------- #

def _ring_kernel(
    x_ref,
    out_ref,
    work,
    comm,
    send_sem,
    recv_sem,
    cap_sem,
    *,
    world: int,
    axis_name: str,
    do_reduce_scatter: bool,
    do_all_gather: bool,
):
    """VMEM-resident unidirectional ring walk: reduce-scatter phase then
    all-gather phase.

    ``x_ref``/``work`` are ``[world, S, 128]`` (chunk-major); ``comm`` is the
    ``[2, S, 128]`` double-buffered staging area written by the left
    neighbor's RDMA.
    """
    my_id = lax.axis_index(axis_name)
    right = (my_id + 1) % world
    left = (my_id + world - 1) % world

    # entry barrier with both neighbors (they write into our comm buffer)
    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(barrier, inc=1, device_id=left)
    pltpu.semaphore_signal(barrier, inc=1, device_id=right)
    pltpu.semaphore_wait(barrier, 2)

    work[...] = x_ref[...]

    n_rs = world - 1 if do_reduce_scatter else 0
    n_ag = world - 1 if do_all_gather else 0
    total_steps = n_rs + n_ag

    for step in range(total_steps):
        slot = step % 2
        in_rs = step < n_rs
        if in_rs:
            send_idx = (my_id + world - step) % world
            recv_idx = (my_id + world - step - 1) % world
        else:
            ag = step - n_rs
            # after RS each rank owns the fully reduced chunk (my_id + 1);
            # without RS (pure all-gather) it owns chunk my_id
            own = 1 if do_reduce_scatter else 0
            send_idx = (my_id + world + own - ag) % world
            recv_idx = (my_id + world + own - ag - 1) % world

        # flow control: slot `slot` in the right neighbor was last written at
        # step-2; wait for the credit it returns after consuming that write
        if step >= 2:
            pltpu.semaphore_wait(cap_sem, 1)

        rdma = pltpu.make_async_remote_copy(
            src_ref=work.at[send_idx],
            dst_ref=comm.at[slot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[slot],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()  # outbound sent AND left neighbor's chunk landed

        if in_rs:
            work[recv_idx] = work[recv_idx] + comm[slot]
        else:
            work[recv_idx] = comm[slot]

        # return a capacity credit upstream: slot is free for reuse
        pltpu.semaphore_signal(cap_sem, inc=1, device_id=left)

    # drain outstanding credits so no signal outlives the kernel
    tail = min(2, total_steps)
    for _ in range(tail):
        pltpu.semaphore_wait(cap_sem, 1)
    out_ref[...] = work[...]


def _stream_ring_kernel(
    x_ref,
    out_ref,
    send_stage,
    acc,
    comm,
    local_sem,
    send_sem,
    recv_sem,
    cap_sem,
    *,
    world: int,
    axis_name: str,
    do_reduce_scatter: bool,
    do_all_gather: bool,
    n_tiles: int,
    stage_rows: int,
    total_iters: int,
):
    """HBM-streaming ring walk: grid = (ring step, tile within the chunk).

    ``x_ref``/``out_ref`` are HBM-resident ``[world, R, 128]``; ``out_ref``
    doubles as the work buffer (seeded from ``x_ref`` at the first grid
    iteration).  Each grid iteration moves one ``[stage_rows, 128]`` tile:
    local DMA stages the outbound tile into VMEM, one RDMA ships it to the
    right neighbor's double-buffered ``comm`` slot, and the landed inbound
    tile is folded back into HBM (accumulate during reduce-scatter, adopt
    during all-gather).  The credit protocol is the VMEM kernel's, carried
    across grid steps over the flattened (step × tile) counter: slot ``i %
    2`` is reused only after the downstream neighbor's credit from
    iteration ``i − 2`` arrives, so a fast sender can never clobber an
    unconsumed staging slot — the reference's fixed-staging flow control
    (trans.cu:73-98) at grid scope.
    """
    step = pl.program_id(0)
    tile = pl.program_id(1)
    it = step * n_tiles + tile
    my_id = lax.axis_index(axis_name)
    right = (my_id + 1) % world
    left = (my_id + world - 1) % world

    n_rs = world - 1 if do_reduce_scatter else 0

    @pl.when(it == 0)
    def _enter():
        # entry barrier with both neighbors, then seed the HBM work buffer
        # (out_ref) from the input — the one whole-payload DMA of the path
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(barrier, inc=1, device_id=left)
        pltpu.semaphore_signal(barrier, inc=1, device_id=right)
        pltpu.semaphore_wait(barrier, 2)
        seed = pltpu.make_async_copy(x_ref, out_ref, local_sem)
        seed.start()
        seed.wait()

    # chunk walk indices (the VMEM kernel's formulas on a traced step; the
    # +2·world keeps every branch of the where non-negative under floor-mod)
    in_rs = step < n_rs
    own = 1 if do_reduce_scatter else 0
    ag = step - n_rs
    send_idx = jnp.where(
        in_rs,
        (my_id + 2 * world - step) % world,
        (my_id + 2 * world + own - ag) % world,
    )
    recv_idx = jnp.where(
        in_rs,
        (my_id + 2 * world - step - 1) % world,
        (my_id + 2 * world + own - ag - 1) % world,
    )
    slot = it % 2
    row0 = tile * stage_rows
    rows = pl.ds(row0, stage_rows)

    # stage the outbound tile: HBM work → fixed VMEM staging.  One buffer
    # suffices: the RDMA below completes (send side included) inside this
    # iteration, so the staging is always free for the next tile — the
    # double buffering that matters for flow control is the *comm* slots,
    # which the left neighbor writes asynchronously
    stage_in = pltpu.make_async_copy(
        out_ref.at[send_idx, rows], send_stage, local_sem
    )
    stage_in.start()
    stage_in.wait()

    @pl.when(it >= 2)
    def _credit_wait():
        pltpu.semaphore_wait(cap_sem, 1)

    rdma = pltpu.make_async_remote_copy(
        src_ref=send_stage,
        dst_ref=comm.at[slot],
        send_sem=send_sem.at[slot],
        recv_sem=recv_sem.at[slot],
        device_id=right,
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )
    rdma.start()
    rdma.wait()  # outbound sent AND left neighbor's tile landed

    @pl.when(in_rs)
    def _reduce():
        # accumulate: HBM tile → VMEM, add the landed tile, DMA back
        acc_in = pltpu.make_async_copy(out_ref.at[recv_idx, rows], acc, local_sem)
        acc_in.start()
        acc_in.wait()
        acc[...] = acc[...] + comm[slot]
        acc_out = pltpu.make_async_copy(acc, out_ref.at[recv_idx, rows], local_sem)
        acc_out.start()
        acc_out.wait()

    @pl.when(jnp.logical_not(in_rs))
    def _adopt():
        adopt = pltpu.make_async_copy(comm.at[slot], out_ref.at[recv_idx, rows], local_sem)
        adopt.start()
        adopt.wait()

    # return a capacity credit upstream: slot is free for reuse
    pltpu.semaphore_signal(cap_sem, inc=1, device_id=left)

    @pl.when(it == total_iters - 1)
    def _drain():
        for _ in range(min(2, total_iters)):
            pltpu.semaphore_wait(cap_sem, 1)


# --------------------------------------------------------------------------- #
# shard-level wrappers (call inside shard_map)
# --------------------------------------------------------------------------- #

def _pad_chunks(flat: jnp.ndarray, world: int):
    """Pad to world × (whole dtype-native tiles) and reshape chunk-major."""
    tile = _tile_elems(flat.dtype)
    chunk = -(-flat.size // world)          # ceil
    chunk = -(-chunk // tile) * tile        # round up to full tiles
    padded = jnp.zeros((world * chunk,), flat.dtype).at[: flat.size].set(flat)
    return padded.reshape(world, chunk // _LANES, _LANES), chunk


def _check_ring_supported() -> None:
    from adapcc_tpu.compat import ring_kernels_supported

    if not ring_kernels_supported():
        # the one funnel every ring entry point (and so --zero1-ring,
        # engine.ring_*, the benchmarks) routes through: fail with guidance
        # here rather than a cryptic Mosaic/legacy-pallas error deeper in
        raise RuntimeError(
            "Pallas ICI ring kernels need a real TPU or the Mosaic TPU "
            "interpret mode (jax >= 0.5); this build has neither — use the "
            "XLA collective path instead (e.g. drop --zero1-ring)"
        )


def _run_ring_chunks(
    chunks: jnp.ndarray,
    *,
    world,
    axis_name,
    rs,
    ag,
    interpret,
    chunk_bytes: Optional[int] = None,
):
    """Run the ring on a pre-chunked ``[world, S, 128]`` array, dispatching
    to the VMEM-resident or HBM-streaming kernel per the planned schedule."""
    _check_ring_supported()
    plan = plan_ring_schedule(
        chunks.size, chunks.dtype, world, chunk_bytes, rs=rs, ag=ag
    )
    if plan.path == "vmem":
        kernel = functools.partial(
            _ring_kernel,
            world=world,
            axis_name=axis_name,
            do_reduce_scatter=rs,
            do_all_gather=ag,
        )
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(chunks.shape, chunks.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[
                pltpu.VMEM(chunks.shape, chunks.dtype),                # work
                pltpu.VMEM((2,) + chunks.shape[1:], chunks.dtype),     # comm slots
                pltpu.SemaphoreType.DMA((2,)),                         # send
                pltpu.SemaphoreType.DMA((2,)),                         # recv
                pltpu.SemaphoreType.REGULAR,                           # capacity
            ],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True, collective_id=0
            ),
            interpret=_interpret_params(interpret),
        )(chunks)

    stage_rows = plan.stage_bytes // (_LANES * jnp.dtype(chunks.dtype).itemsize)
    total_iters = plan.steps * plan.n_tiles
    # zero-pad each chunk to whole staging tiles (bounded by < one tile per
    # chunk, see _stage_rows_for) and slice the padding back out below, so
    # callers see the legacy tile-aligned layout on both paths
    chunk_rows = chunks.shape[1]
    padded_rows = plan.n_tiles * stage_rows
    if padded_rows != chunk_rows:
        chunks = jnp.pad(chunks, ((0, 0), (0, padded_rows - chunk_rows), (0, 0)))
    kernel = functools.partial(
        _stream_ring_kernel,
        world=world,
        axis_name=axis_name,
        do_reduce_scatter=rs,
        do_all_gather=ag,
        n_tiles=plan.n_tiles,
        stage_rows=stage_rows,
        total_iters=total_iters,
    )
    tile_shape = (stage_rows, _LANES)
    out = pl.pallas_call(
        kernel,
        grid=(plan.steps, plan.n_tiles),
        out_shape=jax.ShapeDtypeStruct(chunks.shape, chunks.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[
            pltpu.VMEM(tile_shape, chunks.dtype),          # send staging
            pltpu.VMEM(tile_shape, chunks.dtype),          # accumulate staging
            pltpu.VMEM((2,) + tile_shape, chunks.dtype),   # comm slots
            pltpu.SemaphoreType.DMA(()),                   # local DMAs
            pltpu.SemaphoreType.DMA((2,)),                 # send
            pltpu.SemaphoreType.DMA((2,)),                 # recv
            pltpu.SemaphoreType.REGULAR,                   # capacity
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True,
            collective_id=0,
            # the ring walk is stateful: both grid dims must run in order
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=_interpret_params(interpret),
    )(chunks)
    return out[:, :chunk_rows] if padded_rows != chunk_rows else out


def _run_ring(
    x: jnp.ndarray, *, world, axis_name, rs, ag, interpret, chunk_bytes=None
):
    chunks, chunk = _pad_chunks(x.reshape(-1), world)
    out = _run_ring_chunks(
        chunks, world=world, axis_name=axis_name, rs=rs, ag=ag,
        interpret=interpret, chunk_bytes=chunk_bytes,
    )
    return out, chunk


def ring_allreduce_shard(
    x: jnp.ndarray,
    world: int,
    axis_name: str = RANKS_AXIS,
    interpret: bool = False,
    chunk_bytes: Optional[int] = None,
) -> jnp.ndarray:
    """Sum-allreduce via ring reduce-scatter + ring all-gather.

    Bandwidth-optimal (2·(world−1)/world of the buffer per link), the same
    schedule family the reference benchmarks against NCCL rings
    (nccl-perf/tree/all_reduce.cu).  ``chunk_bytes`` is the staging
    granularity (synthesized by the strategy plane; env-overridable): payloads
    above it stream through HBM, below it stay VMEM-resident.
    """
    if world == 1:
        return x
    out, _ = _run_ring(
        x, world=world, axis_name=axis_name, rs=True, ag=True,
        interpret=interpret, chunk_bytes=chunk_bytes,
    )
    return out.reshape(-1)[: x.size].reshape(x.shape)


def ring_reduce_scatter_shard(
    x: jnp.ndarray,
    world: int,
    axis_name: str = RANKS_AXIS,
    interpret: bool = False,
    chunk_bytes: Optional[int] = None,
) -> jnp.ndarray:
    """Ring reduce-scatter: returns this rank's reduced chunk (padded shape
    ``[chunk]``); rank r owns chunk ``(r + 1) % world`` of the flattened,
    tile-padded input."""
    if world == 1:
        return x.reshape(-1)
    out, chunk = _run_ring(
        x, world=world, axis_name=axis_name, rs=True, ag=False,
        interpret=interpret, chunk_bytes=chunk_bytes,
    )
    my_id = lax.axis_index(axis_name)
    own = (my_id + 1) % world
    return out.reshape(world, chunk)[own]


def ring_all_gather_shard(
    x: jnp.ndarray,
    world: int,
    axis_name: str = RANKS_AXIS,
    interpret: bool = False,
    chunk_bytes: Optional[int] = None,
) -> jnp.ndarray:
    """Ring all-gather of per-rank chunks: input is this rank's ``[chunk]``
    payload (tile-aligned), output is ``[world, chunk]`` in rank order."""
    if world == 1:
        return x.reshape(1, -1)
    tile = _tile_elems(x.dtype)
    if x.size % tile:
        raise ValueError(f"all-gather payload must be tile-aligned ({tile} elems), got {x.size}")
    my_id = lax.axis_index(axis_name)
    chunks = jnp.zeros((world, x.size), x.dtype)
    # place the local payload in the row this rank owns; the ring walk
    # replaces every other row with the neighbors' payloads
    chunks = lax.dynamic_update_index_in_dim(chunks, x.reshape(-1), my_id, 0)
    chunks = chunks.reshape(world, x.size // _LANES, _LANES)
    out = _run_ring_chunks(
        chunks, world=world, axis_name=axis_name, rs=False, ag=True,
        interpret=interpret, chunk_bytes=chunk_bytes,
    )
    return out.reshape(world, -1)
