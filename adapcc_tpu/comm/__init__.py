"""Communication plane: mesh helpers, relay algebra, and collective engine."""
