"""Relay-control role algebra: subset collectives with forwarding stragglers.

The reference's novelty (README.md:8-12) is that any subset of ranks can
perform a collective while inactive ranks ("relays") stay on the data path as
pure forwarders.  Its native controller computes four booleans per rank per
tree — ``<hasRecv, hasLocal, hasKernel, hasSend>`` (csrc/control.cu:27-87,
csrc/include/control.h:21-26) — that gate each stage of the chunk pipeline.

Here the same algebra is a pure function of (tree, active set).  It serves
two purposes:

1. **Schedule pruning** — edges whose source subtree holds no active rank
   carry nothing and are dropped before compilation (the analog of
   ``getActiveRecvs``, control.cu:89-101).
2. **Runtime masking** — when the active set is dynamic (changes step to
   step without recompiling), inactive ranks contribute the reduction
   identity instead, and the roles here are the proof obligations that
   masking preserves the reference semantics.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, FrozenSet, Iterable, List, Tuple

from adapcc_tpu.strategy.ir import CommRound, Strategy, Tree


@dataclass(frozen=True)
class RelayRole:
    """Per-rank pipeline gates for one tree under one active set."""

    has_recv: bool    # some precedent subtree holds an active rank
    has_local: bool   # this rank's own contribution participates
    has_kernel: bool  # a reduction is actually needed (vs pure forwarding)
    has_send: bool    # must push (partial) results toward the root


def subtree_active(tree: Tree, rank: int, active: FrozenSet[int]) -> bool:
    return bool(tree.subtree(rank) & active)


def live_ranks(tree: Tree, active: FrozenSet[int]) -> FrozenSet[int]:
    """Ranks whose subtree holds an active rank, via one bottom-up pass
    (avoids the per-edge O(n) subtree walk when pruning pod-scale trees)."""
    live = set()
    for r in tree._postorder(tree.root):
        if r in active or any(c in live for c in tree.children.get(r, ())):
            live.add(r)
    return frozenset(live)


def active_recvs(tree: Tree, rank: int, active: FrozenSet[int]) -> List[int]:
    """Children whose subtrees still carry live data (control.cu:89-101)."""
    return [c for c in tree.precedents(rank) if subtree_active(tree, c, active)]


def compute_role(tree: Tree, rank: int, active: FrozenSet[int]) -> RelayRole:
    recvs = active_recvs(tree, rank, active)
    has_local = rank in active
    has_recv = bool(recvs)

    # a reduction kernel is needed only when ≥2 live inputs meet at this rank
    live_inputs = len(recvs) + (1 if has_local else 0)
    has_kernel = has_recv and live_inputs >= 2

    # nothing below (or at) this rank is live → nothing to send; roots never send
    has_send = rank != tree.root and subtree_active(tree, rank, active)

    return RelayRole(has_recv, has_local, has_kernel, has_send)


def compute_roles(tree: Tree, active: Iterable[int]) -> Dict[int, RelayRole]:
    act = frozenset(active)
    return {r: compute_role(tree, r, act) for r in sorted(tree.ranks)}


# --------------------------------------------------------------------------- #
# schedule pruning
# --------------------------------------------------------------------------- #

def prune_reduce_rounds(tree: Tree, active: Iterable[int]) -> List[CommRound]:
    """Reduce rounds with dead edges removed.

    An up-edge ``(c → p)`` carries data iff ``subtree(c)`` holds an active
    rank.  Relay ranks with live subtrees keep forwarding (their own
    contribution is masked out by the engine), which is exactly the
    reference's pure-forward role (hasKernel=false, hasSend=true).
    """
    live = live_ranks(tree, frozenset(active))
    rounds = []
    for rnd in tree.reduce_rounds():
        kept = tuple((s, d) for s, d in rnd.edges if s in live)
        if kept:
            rounds.append(CommRound(kept))
    return rounds


def prune_broadcast_rounds(tree: Tree, active: Iterable[int]) -> List[CommRound]:
    """Broadcast rounds delivering the result everywhere it is needed.

    The reference broadcasts results to every rank on the tree (relays
    forward downstream, boardcast.cu:255-305); a down-edge is dead only when
    the entire destination subtree neither wants the result nor forwards it
    to anyone who does — i.e. when the subtree is empty of active ranks AND
    has no active descendants.  Since "wants the result" = active, that is
    the same subtree-active test, applied to the destination.
    """
    live = live_ranks(tree, frozenset(active))
    rounds = []
    for rnd in tree.broadcast_rounds():
        kept = tuple((s, d) for s, d in rnd.edges if d in live)
        if kept:
            rounds.append(CommRound(kept))
    return rounds
