"""Two-level (DCN × ICI) collective execution.

The reference expresses hierarchy *inside* one flat rank world: ParTrees
attaches intra-host chains under per-host masters and the CUDA contexts walk
the whole tree over whatever transport each edge happens to cross
(gurobi/trees.py chain policy; csrc/allreduce.cu edge classification by ip,
allreduce.cu:473-522).  On TPU the hierarchy is a *mesh axis*: a multi-slice
world is a ``("dcn", "ici")`` mesh, intra-slice traffic rides the ICI torus
and inter-slice traffic rides DCN.  A synthesized strategy executes as

1. **slice-local reduce** over the ``ici`` axis — the strategy's intra-host
   chains collapse into the XLA collective, which is already the optimal ICI
   program (the chain shape is the reference's PCIe pattern, not a TPU one);
2. **master-tree rounds** over the ``dcn`` axis — the strategy's inter-host
   edges, collapsed to slice indices by :func:`slice_tree`, run as masked
   ``ppermute`` reduce/broadcast rounds exactly like the flat engine, but on
   the DCN axis only;
3. the broadcast down the master tree lands on every ``ici`` lane at once,
   so the result is already replicated intra-slice.

This keeps the synthesizer's decision surface (which inter-host links carry
data, rooted where, with what shares) while guaranteeing — by construction,
not by device ordering — that intra-host edges never touch DCN.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from adapcc_tpu.comm.engine import (
    _avg_normalize,
    _build_merged_plan,
    _identity_for,
    _MergedPlan,
    _run_broadcast_rounds,
    _run_merged_groups,
    _run_reduce_rounds,
    _run_segments,
    _segment_sizes,
    _stack_segments,
    _unstack_segments,
)
from adapcc_tpu.primitives import ReduceOp
from adapcc_tpu.strategy.ir import Strategy, Tree

#: canonical axis names for a two-level world mesh
DCN_AXIS = "dcn"
ICI_AXIS = "ici"


def build_two_level_mesh(
    num_slices: int,
    ici_size: Optional[int] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """A ``(num_slices, ici_size)`` mesh with axes ``("dcn", "ici")``.

    Flat rank ``r`` (the strategy/ip-table world rank) sits at mesh position
    ``(r // ici_size, r % ici_size)`` — the same slice grouping the detector
    writes into the logical graph (hosts = slices).

    Ragged layouts reject loudly (a world that does not divide into equal
    slices has no two-level sketch — docs/HIERARCHY.md §1), as does
    ``ici_size=1`` (a slice of one rank has no ICI level).  The single-pod
    degenerate case (``num_slices=1``) falls back to the flat plane: it
    returns the ordinary 1-D ranks mesh, because one pod IS a flat world
    and every two-level code path would only add a trivial DCN axis.
    """
    if num_slices < 1:
        raise ValueError(f"num_slices must be >= 1, got {num_slices}")
    if ici_size is not None and ici_size < 2:
        raise ValueError(
            f"ici_size must be >= 2, got {ici_size}: a slice of one rank "
            "has no ICI level — use the flat ranks mesh"
        )
    devs = list(devices) if devices is not None else list(jax.devices())
    if ici_size is None:
        if len(devs) % num_slices:
            raise ValueError(
                f"{len(devs)} devices do not split into {num_slices} slices"
            )
        ici_size = len(devs) // num_slices
        if ici_size < 2:
            raise ValueError(
                f"{len(devs)} devices over {num_slices} slices leave "
                f"ici_size={ici_size}: a slice of one rank has no ICI "
                "level — use the flat ranks mesh"
            )
    need = num_slices * ici_size
    if len(devs) < need:
        raise ValueError(f"need {need} devices, have {len(devs)}")
    if num_slices == 1:
        # degenerate single-pod world: the flat plane, by construction
        from adapcc_tpu.comm.mesh import build_world_mesh

        return build_world_mesh(ici_size, devices=devs)
    grid = np.array(devs[:need]).reshape(num_slices, ici_size)
    return Mesh(grid, (DCN_AXIS, ICI_AXIS))


def is_two_level(mesh: Mesh) -> bool:
    return tuple(mesh.axis_names) == (DCN_AXIS, ICI_AXIS)


def slice_tree(tree: Tree, rank_slice: Sequence[int], num_slices: int) -> Tree:
    """Collapse a world tree to its inter-slice master tree.

    ``rank_slice[r]`` is the slice of world rank ``r``.  Every tree edge
    whose endpoints share a slice is an intra-slice edge (executed by the ICI
    collective); the remaining edges must form a spanning tree over slice
    indices — one inbound DCN edge per non-root slice, the condition the
    ParTrees chain construction guarantees (masters parent other masters,
    chains stay under their own master).
    """
    children: Dict[int, List[int]] = {}
    inbound: Dict[int, int] = {}
    for c, p in tree.parent.items():
        sp, sc = rank_slice[p], rank_slice[c]
        if sp == sc:
            continue
        if sc in inbound:
            raise ValueError(
                f"slice {sc} has two inbound inter-slice edges (from {inbound[sc]} "
                f"and {sp}); strategy is not slice-hierarchical"
            )
        inbound[sc] = sp
        children.setdefault(sp, []).append(sc)
    root_slice = rank_slice[tree.root]
    st = Tree(root_slice, children)
    missing = set(range(num_slices)) - st.ranks
    if missing:
        raise ValueError(f"slices {sorted(missing)} unreachable in the master tree")
    return st


def mesh_rank_slice(num_slices: int, ici_size: int) -> List[int]:
    if num_slices < 1 or ici_size < 1:
        raise ValueError(
            f"need num_slices/ici_size >= 1, got {num_slices}x{ici_size}"
        )
    return [r // ici_size for r in range(num_slices * ici_size)]


_TL_MERGED_PLANS: Dict = {}


def _two_level_merged_plan(
    strategy: Strategy, num_slices: int, ici_size: int
) -> Optional["_MergedPlan"]:
    """Merged DCN-round plan over the strategy's slice trees (the two-level
    analog of ``engine._merged_plan``), or None when merging buys nothing.

    On top of the DCN-round merge, the merged executor fuses ALL trees'
    slice-local reductions into ONE ici-axis collective over the stacked
    segments — the sequential path pays one per tree.
    """
    def rounds_of():
        rank_slice = mesh_rank_slice(num_slices, ici_size)
        slice_trees = [
            slice_tree(t, rank_slice, num_slices) for t in strategy.trees
        ]
        return (
            [st.reduce_rounds() for st in slice_trees],
            [st.broadcast_rounds() for st in slice_trees],
        )

    return _build_merged_plan(
        strategy, num_slices, rounds_of, _TL_MERGED_PLANS,
        key_extra=(num_slices, ici_size),
    )


def _run_two_level_merged(
    x: jnp.ndarray,
    strategy: Strategy,
    plan: "_MergedPlan",
    num_slices: int,
    ici_size: int,
    dcn_axis: str,
    ici_axis: str,
    op: ReduceOp,
    phases: str,  # "reduce" | "broadcast" | "both"
    contrib_of=None,
    root_select: bool = False,
) -> jnp.ndarray:
    """One merged two-level execution shared by allreduce / reduce /
    broadcast: mask+stack the per-tree segments, ONE ici-axis collective
    for level 1 (all trees at once), merged DCN groups for level 2.

    ``root_select`` is the broadcast flavor of level 1: instead of reducing
    contributions, row ``t`` takes tree ``t``'s root-rank value (everyone
    else feeds zero into the slice psum).
    """
    flat = x.reshape(-1)
    if contrib_of is not None:
        flat = contrib_of(flat)
    sizes = _segment_sizes(flat.size, strategy.tree_shares())
    stacked = _stack_segments(flat, sizes, _identity_for(op, flat.dtype))

    if root_select:
        rank_slice = mesh_rank_slice(num_slices, ici_size)
        root_slices = jnp.asarray(
            np.array([rank_slice[t.root] for t in strategy.trees])
        )
        root_lanes = jnp.asarray(
            np.array([t.root % ici_size for t in strategy.trees])
        )
        sel = (
            (lax.axis_index(dcn_axis) == root_slices)
            & (lax.axis_index(ici_axis) == root_lanes)
        )[:, None]
        acc = lax.psum(
            jnp.where(sel, stacked, jnp.zeros_like(stacked)), ici_axis
        ).astype(stacked.dtype)
    elif op is ReduceOp.MAX:
        acc = lax.pmax(stacked, ici_axis)
    else:
        acc = lax.psum(stacked, ici_axis)

    if phases in ("reduce", "both"):
        combine = "max" if op is ReduceOp.MAX else "add"
        acc = _run_merged_groups(acc, plan.reduce_groups, dcn_axis, combine)
    if phases in ("broadcast", "both"):
        acc = _run_merged_groups(acc, plan.broadcast_groups, dcn_axis, "adopt")
    return _unstack_segments(acc, sizes).reshape(x.shape)


def allreduce_two_level_shard(
    x: jnp.ndarray,
    active_mask: jnp.ndarray,
    strategy: Strategy,
    num_slices: int,
    ici_size: int,
    dcn_axis: str = DCN_AXIS,
    ici_axis: str = ICI_AXIS,
    op: ReduceOp = ReduceOp.SUM,
) -> jnp.ndarray:
    """Strategy allreduce on a ``(dcn, ici)`` mesh; call inside shard_map.

    ``x`` is this rank's contribution, ``active_mask`` a ``[world]`` bool
    array over flat ranks (``slice * ici_size + lane``).  Tensor segments
    split across trees by share like the flat engine; each tree contributes
    its master tree (via :func:`slice_tree`) for the DCN rounds.
    """
    rank_slice = mesh_rank_slice(num_slices, ici_size)
    flat_rank = lax.axis_index(dcn_axis) * ici_size + lax.axis_index(ici_axis)
    my_active = active_mask[flat_rank]

    def contrib_of(v):
        return jnp.where(my_active, v, _identity_for(op, v.dtype))

    plan = _two_level_merged_plan(strategy, num_slices, ici_size)
    if plan is not None:
        result = _run_two_level_merged(
            x, strategy, plan, num_slices, ici_size, dcn_axis, ici_axis,
            op, "both", contrib_of=contrib_of,
        )
        return _avg_normalize(result, active_mask, op)

    def per_segment(seg: jnp.ndarray, tree: Tree) -> jnp.ndarray:
        contrib = contrib_of(seg)
        # level 1: slice-local reduction rides the ICI axis
        if op is ReduceOp.MAX:
            acc = lax.pmax(contrib, ici_axis)
        else:
            acc = lax.psum(contrib, ici_axis)
        # level 2: master tree over slice indices rides the DCN axis
        st = slice_tree(tree, rank_slice, num_slices)
        acc = _run_reduce_rounds(acc, st.reduce_rounds(), dcn_axis, num_slices, op)
        acc = _run_broadcast_rounds(acc, st.broadcast_rounds(), dcn_axis, num_slices)
        return acc

    result = _run_segments(x, strategy, per_segment)
    return _avg_normalize(result, active_mask, op)


def all_to_all_two_level_shard(
    x: jnp.ndarray,
    num_slices: int,
    ici_size: int,
    dcn_axis: str = DCN_AXIS,
    ici_axis: str = ICI_AXIS,
) -> jnp.ndarray:
    """Hierarchical all-to-all on a ``(dcn, ici)`` mesh; call inside shard_map.

    ``x [world, *payload]``: block ``x[d·I + i]`` is this rank's payload for
    destination flat rank ``(d, i)``.  Returns ``y [world, *payload]`` with
    row ``s`` = the block sent by source flat rank ``s`` to this rank — the
    same contract as a flat ``lax.all_to_all``, executed as the classic
    two-hop algorithm:

    1. **intra-slice** (ICI): exchange destination-*lane* blocks within the
       slice, so lane ``i`` ends up holding everything its slice wants to
       send to remote lane-``i`` ranks;
    2. **inter-slice** (DCN): exchange destination-*slice* blocks strictly
       lane-to-same-lane across slices.

    Every byte crosses DCN exactly once and always lane-aligned — the DCN
    fabric never carries intra-slice reshuffling, unlike the flat collective,
    which is free to route any (src, dst) pair across slices.  The reference
    left ALLTOALL an unimplemented enum stub (adapcc.py:59-61); this is the
    hierarchy-aware completion.
    """
    S, I = num_slices, ici_size
    if x.shape[0] != S * I:
        raise ValueError(
            f"all_to_all payload leading dim {x.shape[0]} != world {S * I}"
        )
    payload = x.shape[1:]
    xr = x.reshape((S, I) + payload)
    # phase 1: lane j receives, from each lane i' of its own slice, the
    # [S_dest] blocks that (slice, i') addressed to remote lane j
    y1 = lax.all_to_all(xr, ici_axis, split_axis=1, concat_axis=1, tiled=True)
    # y1[d', i_src] = block from (my_slice, i_src) to (d', my_lane)
    # phase 2: slice d' receives, lane-aligned, the blocks addressed to it
    y2 = lax.all_to_all(y1, dcn_axis, split_axis=0, concat_axis=0, tiled=True)
    # y2[d_src, i_src] = block from (d_src, i_src) to me
    return y2.reshape((S * I,) + payload)


def all_gather_two_level_shard(
    x: jnp.ndarray,
    num_slices: int,
    ici_size: int,
    dcn_axis: str = DCN_AXIS,
    ici_axis: str = ICI_AXIS,
) -> jnp.ndarray:
    """DCN-light hierarchical all-gather; call inside shard_map.

    ``x`` is this rank's payload; returns ``[world, *payload]`` in flat rank
    order (``slice * ici_size + lane``).  Gathers over the DCN axis *first*
    — each payload crosses DCN exactly once — then replicates slice stacks
    over ICI; a flat ``lax.all_gather`` on the combined axes would instead
    let GSPMD route intra-slice reshuffling across DCN.  The final transpose
    from (lane, slice) to (slice, lane) order is a local relabel.
    """
    g_dcn = lax.all_gather(x, dcn_axis, axis=0)       # [S, *p]  per (·, lane)
    g = lax.all_gather(g_dcn, ici_axis, axis=0)       # [I, S, *p]
    return jnp.swapaxes(g, 0, 1).reshape((num_slices * ici_size,) + x.shape)


def reduce_scatter_two_level_shard(
    x: jnp.ndarray,
    num_slices: int,
    ici_size: int,
    dcn_axis: str = DCN_AXIS,
    ici_axis: str = ICI_AXIS,
) -> jnp.ndarray:
    """ICI-first hierarchical reduce-scatter (sum); call inside shard_map.

    ``x`` is this rank's flat ``[n]`` contribution (``n % world == 0``);
    returns this rank's fully reduced ``[n / world]`` chunk, in flat rank
    order: rank ``(s, i)`` receives world-chunk ``s·I + i``, matching the
    flat engine's :meth:`reduce_scatter` row semantics.

    The ICI scatter runs first so DCN carries only ``1/ici_size`` of the
    buffer; a local chunk pre-permutation (a reshape/transpose, no
    collective) makes the two-hop scatter land the flat chunk order.
    """
    S, I = num_slices, ici_size
    world = S * I
    if x.size % world:
        raise ValueError(
            f"reduce_scatter payload ({x.size} elems) must divide the world "
            f"({world})"
        )
    c = x.size // world
    # chunk (i·S + s) of the permuted buffer ← flat chunk (s·I + i): after
    # the ici-then-dcn scatter, rank (s, i) holds permuted chunk (i·S + s),
    # i.e. exactly flat chunk (s·I + i)
    xp = x.reshape(S, I, c).swapaxes(0, 1).reshape(-1)
    part = lax.psum_scatter(xp, ici_axis, scatter_dimension=0, tiled=True)
    return lax.psum_scatter(part, dcn_axis, scatter_dimension=0, tiled=True)


def allreduce_two_level_composed_shard(
    x: jnp.ndarray,
    active_mask: jnp.ndarray,
    plan,
    num_slices: int,
    ici_size: int,
    dcn_axis: str = DCN_AXIS,
    ici_axis: str = ICI_AXIS,
    op: ReduceOp = ReduceOp.SUM,
) -> jnp.ndarray:
    """The synthesized bandwidth-optimal two-level allreduce — the
    execution of a :class:`~adapcc_tpu.strategy.hierarchy.TwoLevelPlan`
    with ``pod_algo="rs-ag"`` (docs/HIERARCHY.md §3); call inside
    shard_map on a ``(dcn, ici)`` mesh:

    1. **RS-within-pod** — ``psum_scatter`` over the ICI axis: lane ``i``
       is left holding the fully pod-reduced chunk ``i`` (1/ici of the
       payload);
    2. **AR-across-leaders** — every lane allreduces ITS chunk over the
       DCN axis, so DCN carries ``1/ici_size`` of the buffer (the wire-time
       win over the replicate-first fixed schedule, which ships the whole
       payload).  The schedule is the plan's solved leader level: binomial
       ``tree`` rounds (the leader strategy's trees lowered to ppermutes
       over the DCN axis), or the segmented leader ring (``rs-ag``) as
       XLA ``psum_scatter`` + ``all_gather`` over the DCN axis;
    3. **AG-within-pod** — ``all_gather`` over the ICI axis restores the
       full payload on every lane.

    Relay contract unchanged: inactive ranks contribute zeros but stay on
    the data path and receive the result; ``AVG`` divides by the active
    count.  ``MAX`` is rejected (``psum_scatter`` has no max variant —
    the engine routes MAX through the projected schedule path instead).
    The payload is zero-padded to a multiple of the world internally and
    sliced back, so any size works.
    """
    if op is ReduceOp.MAX:
        raise ValueError(
            "the composed two-level path supports SUM/AVG only "
            "(psum_scatter has no max variant); MAX rides the projected "
            "schedule path"
        )
    leader_strategy = plan.leader_strategy
    world = num_slices * ici_size
    flat_rank = lax.axis_index(dcn_axis) * ici_size + lax.axis_index(ici_axis)
    my_active = active_mask[flat_rank]

    flat = x.reshape(-1)
    contrib = jnp.where(my_active, flat, jnp.zeros_like(flat))
    pad = (-flat.size) % world
    if pad:
        contrib = jnp.concatenate(
            [contrib, jnp.zeros((pad,), dtype=flat.dtype)]
        )
    # phase 1: reduce-scatter within the pod — lane i owns chunk i
    chunk = lax.psum_scatter(
        contrib, ici_axis, scatter_dimension=0, tiled=True
    )
    # phase 2: leader-level allreduce of the chunk over the DCN axis
    if plan.leader_algo == "rs-ag":
        part = lax.psum_scatter(
            chunk, dcn_axis, scatter_dimension=0, tiled=True
        )
        chunk = lax.all_gather(part, dcn_axis, axis=0, tiled=True)
    else:  # "tree": the solved leader trees lowered to DCN ppermute rounds
        def per_segment(seg: jnp.ndarray, tree: Tree) -> jnp.ndarray:
            acc = _run_reduce_rounds(
                seg, tree.reduce_rounds(), dcn_axis, num_slices, op
            )
            return _run_broadcast_rounds(
                acc, tree.broadcast_rounds(), dcn_axis, num_slices
            )

        chunk = _run_segments(chunk, leader_strategy, per_segment)
    # phase 3: all-gather within the pod restores the full payload
    full = lax.all_gather(chunk, ici_axis, axis=0, tiled=True)
    if pad:
        full = full[: flat.size]
    return _avg_normalize(full.reshape(x.shape), active_mask, op)


def reduce_two_level_shard(
    x: jnp.ndarray,
    active_mask: jnp.ndarray,
    strategy: Strategy,
    num_slices: int,
    ici_size: int,
    dcn_axis: str = DCN_AXIS,
    ici_axis: str = ICI_AXIS,
    op: ReduceOp = ReduceOp.SUM,
) -> jnp.ndarray:
    """Two-level reduce: the total lands on every lane of each tree's *root
    slice* (the slice-granular analog of the flat engine's root-holds-result
    semantics, reference reduce.cu:258-269); other slices hold partials."""
    rank_slice = mesh_rank_slice(num_slices, ici_size)
    flat_rank = lax.axis_index(dcn_axis) * ici_size + lax.axis_index(ici_axis)
    my_active = active_mask[flat_rank]

    def contrib_of(v):
        return jnp.where(my_active, v, _identity_for(op, v.dtype))

    plan = _two_level_merged_plan(strategy, num_slices, ici_size)
    if plan is not None:
        result = _run_two_level_merged(
            x, strategy, plan, num_slices, ici_size, dcn_axis, ici_axis,
            op, "reduce", contrib_of=contrib_of,
        )
        return _avg_normalize(result, active_mask, op)

    def per_segment(seg: jnp.ndarray, tree: Tree) -> jnp.ndarray:
        contrib = contrib_of(seg)
        acc = lax.pmax(contrib, ici_axis) if op is ReduceOp.MAX else lax.psum(contrib, ici_axis)
        st = slice_tree(tree, rank_slice, num_slices)
        return _run_reduce_rounds(acc, st.reduce_rounds(), dcn_axis, num_slices, op)

    result = _run_segments(x, strategy, per_segment)
    return _avg_normalize(result, active_mask, op)


def broadcast_two_level_shard(
    x: jnp.ndarray,
    strategy: Strategy,
    num_slices: int,
    ici_size: int,
    dcn_axis: str = DCN_AXIS,
    ici_axis: str = ICI_AXIS,
) -> jnp.ndarray:
    """Two-level broadcast: each tree's root *rank* value replicates across
    its slice's ICI lanes (masked psum — one nonzero contributor), then
    streams down the master tree over DCN."""
    rank_slice = mesh_rank_slice(num_slices, ici_size)
    my_dcn = lax.axis_index(dcn_axis)
    my_lane = lax.axis_index(ici_axis)

    plan = _two_level_merged_plan(strategy, num_slices, ici_size)
    if plan is not None:
        return _run_two_level_merged(
            x, strategy, plan, num_slices, ici_size, dcn_axis, ici_axis,
            ReduceOp.SUM, "broadcast", root_select=True,
        )

    def per_segment(seg: jnp.ndarray, tree: Tree) -> jnp.ndarray:
        root_slice = rank_slice[tree.root]
        root_lane = tree.root % ici_size
        # replicate the root rank's segment across its slice (everyone else
        # contributes zero; slices other than the root's hold garbage until
        # the DCN broadcast overwrites them)
        is_root_rank = (my_dcn == root_slice) & (my_lane == root_lane)
        acc = lax.psum(jnp.where(is_root_rank, seg, jnp.zeros_like(seg)), ici_axis)
        st = slice_tree(tree, rank_slice, num_slices)
        return _run_broadcast_rounds(acc, st.broadcast_rounds(), dcn_axis, num_slices)

    return _run_segments(x, strategy, per_segment)
