"""Mesh construction and device-topology helpers.

The reference binds each process to one GPU (`cudaSetDevice(localRank)`,
csrc/run.cu:49) and builds a rank world over MPI.  On TPU the runtime already
owns every local chip, so a "world" is a `jax.sharding.Mesh` axis: one mesh
axis position per reference rank.  Multi-host worlds come from
`jax.distributed` + the same mesh spanning all processes.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

#: canonical mesh axis name for the collective world (reference "world rank")
RANKS_AXIS = "ranks"


def build_world_mesh(world_size: Optional[int] = None, devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh of ``world_size`` devices — the collective world."""
    devs = list(devices) if devices is not None else list(jax.devices())
    if world_size is not None:
        if len(devs) < world_size:
            raise ValueError(f"need {world_size} devices, have {len(devs)}")
        devs = devs[:world_size]
    return Mesh(np.array(devs), (RANKS_AXIS,))


def device_ip(device) -> str:
    """Stable host identifier for a device, used where the reference uses the
    node ip (tree edge classification, strategy XML).  TPU devices expose the
    owning process; devices in one process share ICI locality."""
    return f"process-{getattr(device, 'process_index', 0)}"


def mesh_ip_table(mesh: Mesh) -> List[str]:
    """Rank→"ip" list for a world mesh (analog of topology/ip_table.txt)."""
    return [device_ip(d) for d in mesh.devices.flat]
