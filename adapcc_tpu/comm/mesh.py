"""Mesh construction and device-topology helpers.

The reference binds each process to one GPU (`cudaSetDevice(localRank)`,
csrc/run.cu:49) and builds a rank world over MPI.  On TPU the runtime already
owns every local chip, so a "world" is a `jax.sharding.Mesh` axis: one mesh
axis position per reference rank.  Multi-host worlds come from
`jax.distributed` + the same mesh spanning all processes.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

#: canonical mesh axis name for the collective world (reference "world rank")
RANKS_AXIS = "ranks"


def build_world_mesh(world_size: Optional[int] = None, devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh of ``world_size`` devices — the collective world."""
    devs = list(devices) if devices is not None else list(jax.devices())
    if world_size is not None:
        if len(devs) < world_size:
            raise ValueError(f"need {world_size} devices, have {len(devs)}")
        devs = devs[:world_size]
    return Mesh(np.array(devs), (RANKS_AXIS,))


def device_ip(device) -> str:
    """Stable host identifier for a device, used where the reference uses the
    node ip (tree edge classification, strategy XML).  TPU devices expose the
    owning process; devices in one process share ICI locality."""
    return f"process-{getattr(device, 'process_index', 0)}"


def mesh_ip_table(mesh: Mesh) -> List[str]:
    """Rank→"ip" list for a world mesh (analog of topology/ip_table.txt).

    On a two-level ``(dcn, ici)`` mesh the slice is the host analog — the
    synthesizer's host grouping (masters + intra-host chains) must follow
    slice boundaries, not process boundaries, so ranks are labeled by their
    slice row.  A single-process virtual pod would otherwise collapse to one
    "host" and the synthesized hierarchy would not match the DCN×ICI
    execution split (comm/two_level.py).
    """
    from adapcc_tpu.comm.two_level import is_two_level

    if is_two_level(mesh):
        # label purely by slice row: a slice spanning several processes is
        # still ONE host analog (embedding the process ip here would split
        # it, hand the synthesizer two masters per slice, and trip
        # slice_tree's single-inbound-edge check)
        _, ici = mesh.devices.shape
        return [f"slice-{r // ici}" for r in range(mesh.devices.size)]
    return [device_ip(d) for d in mesh.devices.flat]
