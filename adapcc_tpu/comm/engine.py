"""Schedule-driven collective engine on a `jax.sharding.Mesh`.

The TPU-native replacement for the reference's native transmission contexts
(csrc/allreduce.cu / reduce.cu / boardcast.cu): where the reference spawns two
persistent pthreads per tree that move 4 MB chunks through IPC staging buffers
(allreduce.cu:430-659), here every strategy tree lowers to a static sequence
of masked ``jax.lax.ppermute`` rounds inside one jitted ``shard_map`` program.
XLA owns chunking, overlap, and ICI routing; the strategy owns the *shape* of
the communication (which links carry data, in what order, rooted where).

Relay semantics (reference control.cu): the active set arrives as a runtime
``[world]`` mask, so step-to-step relay decisions never trigger recompilation.
Inactive ranks contribute the reduction identity but remain on the data path
as forwarders — the masked-collective formulation of the reference's
``<hasRecv, hasLocal, hasKernel, hasSend>`` role algebra.

Full-world allreduce additionally has an XLA fast path (``lax.psum``), which
is the optimal program on an ICI torus; the schedule path exists for subset /
relay semantics and for topology-shaped strategies.  ``ALLGATHER`` /
``ALLTOALL`` / ``REDUCESCATTER`` — enum stubs the reference never implemented
(commu.py:65-69 maps only three primitives) — are provided natively via XLA
collectives.
"""

from __future__ import annotations

import functools
import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from adapcc_tpu.primitives import ReduceOp
from adapcc_tpu.strategy.ir import CommRound, Strategy, Tree
from adapcc_tpu.comm.mesh import RANKS_AXIS


#: default KV-stream granularity: one DCN chunk per ~4 MiB of wire payload
#: (the reference's transmission contexts moved 4 MB IPC chunks; the trace
#: records the chunk count so a future live window can sweep it)
KV_TRANSFER_CHUNK_BYTES = 4 << 20


class EpochMismatch(RuntimeError):
    """A collective was issued against a world epoch that is no longer
    current (the coordinator advanced the WorldView — a rank died, was
    demoted, or recovered — and the engine swapped plans).

    Retryable by construction: the caller refreshes its epoch token (the
    exception carries ``current``) and re-issues; the
    :class:`~adapcc_tpu.communicator.Communicator` layer does exactly that
    with bounded retry + backoff.  This is the hang-free contract — a
    stale issuer gets a loud, catchable signal instead of running a
    schedule the world has moved past.
    """

    def __init__(self, issued: int, current: int) -> None:
        super().__init__(
            f"collective issued against dead epoch {issued} (current epoch "
            f"is {current}); refresh the epoch token and retry"
        )
        self.issued = issued
        self.current = current


def _identity_for(op: ReduceOp, dtype) -> jnp.ndarray:
    if op is ReduceOp.MAX:
        return jnp.asarray(-jnp.inf if jnp.issubdtype(dtype, jnp.floating) else jnp.iinfo(dtype).min, dtype)
    return jnp.asarray(0, dtype)


def _dst_mask(round_: CommRound, world: int) -> np.ndarray:
    m = np.zeros((world,), dtype=bool)
    for _, d in round_.edges:
        m[d] = True
    return m


def _segment_sizes(n: int, shares: Sequence[float]) -> List[int]:
    """Static split of ``n`` elements across trees, proportional to shares.

    Mirrors the reference's 1/numTrans sharding (allreduce.cu:310,536), except
    shares may be non-uniform when the MILP solver optimized them.
    """
    sizes = [int(n * s) for s in shares]
    rem = n - sum(sizes)
    i = 0
    while rem > 0:
        sizes[i % len(sizes)] += 1
        rem -= 1
        i += 1
    return sizes


# --------------------------------------------------------------------------- #
# per-shard (inside shard_map) schedule execution
# --------------------------------------------------------------------------- #

def _run_reduce_rounds(
    acc: jnp.ndarray,
    rounds: Sequence[CommRound],
    axis_name: str,
    world: int,
    op: ReduceOp,
) -> jnp.ndarray:
    """Push partial reductions up the tree, one ppermute per round.

    ppermute delivers zeros to ranks that are not a destination, so for SUM
    the combine is a plain add; MAX needs an explicit destination mask.
    """
    for rnd in rounds:
        recvd = lax.ppermute(acc, axis_name, list(rnd.edges))
        if op is ReduceOp.MAX:
            is_dst = jnp.asarray(_dst_mask(rnd, world))[lax.axis_index(axis_name)]
            acc = jnp.where(is_dst, jnp.maximum(acc, recvd), acc)
        else:
            acc = acc + recvd
    return acc


def _run_broadcast_rounds(
    acc: jnp.ndarray,
    rounds: Sequence[CommRound],
    axis_name: str,
    world: int,
) -> jnp.ndarray:
    """Stream the rooted value down the tree; destinations adopt what lands."""
    for rnd in rounds:
        recvd = lax.ppermute(acc, axis_name, list(rnd.edges))
        is_dst = jnp.asarray(_dst_mask(rnd, world))[lax.axis_index(axis_name)]
        acc = jnp.where(is_dst, recvd, acc)
    return acc


def _mask_contribution(
    seg: jnp.ndarray, active_mask: jnp.ndarray, axis_name: str, op: ReduceOp
) -> jnp.ndarray:
    """Relay masking: inactive ranks contribute the reduction identity while
    staying on the forwarding path (reference hasLocal gate, control.cu)."""
    my_active = active_mask[lax.axis_index(axis_name)]
    return jnp.where(my_active, seg, _identity_for(op, seg.dtype))


# --------------------------------------------------------------------------- #
# merged multi-tree execution: one ppermute per round ACROSS trees
# --------------------------------------------------------------------------- #
#
# The reference gets tree-level concurrency from one pthread pair per tree
# (allreduce.cu:735-742): all trees' round-k transfers ride different links
# at the same wall-clock time.  The naive XLA lowering loses that — each
# tree's round chain runs sequentially inside the traced program.  Rotated
# trees (ring / binary / ParTrees) have isomorphic round structures, so the
# merged executor stacks the per-tree segments into one [T, seg] buffer and
# combines every tree's round-k edges into as few ppermutes as the
# partial-permutation contract allows (greedy coloring: within one ppermute
# each rank sends at most once and receives at most once).  Each rank
# *selects* which tree's row it sends from a static per-round table, so the
# per-link bytes are identical to the sequential path — only the dispatch
# count drops, by ~num_trans.  A ring strategy with T=world merged this way
# IS the bandwidth-optimal segmented ring allreduce (reduce-scatter shape up,
# all-gather shape down).


class _MergedPlan:
    """Static per-round send/receive tables for merged multi-tree execution.

    Each group is ``(perm, src_row, dst_row, is_dst)``: the ppermute edge
    list plus, per rank, which stacked row it sends / receives into.
    """

    def __init__(self, reduce_groups, broadcast_groups):
        self.reduce_groups = reduce_groups
        self.broadcast_groups = broadcast_groups


def _color_rounds(per_tree_rounds: Sequence[Sequence[CommRound]], world: int):
    """Align trees' rounds by index and split each union into valid partial
    permutations; returns the group table list."""
    groups = []
    depth = max((len(r) for r in per_tree_rounds), default=0)
    for k in range(depth):
        edges: List[Tuple[int, int, int]] = []  # (src, dst, tree)
        for ti, rounds in enumerate(per_tree_rounds):
            if k < len(rounds):
                edges.extend((s, d, ti) for s, d in rounds[k].edges)
        colors: List[List[Tuple[int, int, int]]] = []
        for e in edges:
            for c in colors:
                if all(e[0] != s and e[1] != d for s, d, _ in c):
                    c.append(e)
                    break
            else:
                colors.append([e])
        for c in colors:
            perm = tuple((s, d) for s, d, _ in c)
            src_row = np.zeros((world,), np.int32)
            dst_row = np.zeros((world,), np.int32)
            is_dst = np.zeros((world,), bool)
            for s, d, t in c:
                src_row[s] = t
                dst_row[d] = t
                is_dst[d] = True
            groups.append((perm, src_row, dst_row, is_dst))
    return groups


_MERGED_PLANS: Dict[Tuple, Optional[_MergedPlan]] = {}

#: one deprecation warning per process for the reference's "boardcast"
#: spelling (satellite of the latency PR; see CollectiveEngine.boardcast)
_BOARDCAST_WARNED = False


def _merged_env_disabled() -> bool:
    """``ADAPCC_MERGE_ROUNDS=0`` disables round merging everywhere — the A/B
    knob for measuring the merged executor against sequential per-tree
    chains on hardware (flat and two-level paths share it).  Unknown values
    raise: a typo silently enabling the default would invalidate the A/B
    (same policy as bench.py's BENCH_REMAT validation)."""
    import os

    val = os.environ.get("ADAPCC_MERGE_ROUNDS", "1").strip().lower()
    if val in ("0", "off", "false", "no"):
        return True
    if val in ("", "1", "on", "true", "yes"):
        return False
    raise ValueError(
        f"ADAPCC_MERGE_ROUNDS={val!r}: expected 1/on/true or 0/off/false"
    )


def _merged_plan(strategy: Strategy) -> Optional[_MergedPlan]:
    """Build (and cache) the merged plan, or None when merging buys nothing:
    a single tree (groups == rounds) or heavily skewed MILP shares (stacking
    pads every segment to the largest, wasting bandwidth)."""
    return _build_merged_plan(
        strategy,
        strategy.world_size,
        lambda: (
            [t.reduce_rounds() for t in strategy.trees],
            [t.broadcast_rounds() for t in strategy.trees],
        ),
        _MERGED_PLANS,
    )


def _build_merged_plan(
    strategy: Strategy,
    world: int,
    rounds_of: Callable[[], Tuple[list, list]],
    cache: Dict,
    key_extra: Tuple = (),
) -> Optional[_MergedPlan]:
    """Shared gate + coloring + cache for merged plans (flat and two-level
    differ only in the rounds source and the permutation world).

    Returns None when merging buys nothing: env kill-switch, a single tree
    (groups == rounds), heavily skewed MILP shares (stacking pads every
    segment to the largest, wasting bandwidth), or a coloring that fails to
    reduce the round count.
    """
    if _merged_env_disabled():
        return None
    shares = strategy.tree_shares()
    key = (
        strategy.fingerprint(), *key_extra,
        tuple(round(s, 6) for s in shares),
    )
    if key in cache:
        return cache[key]
    plan: Optional[_MergedPlan] = None
    if len(strategy.trees) > 1 and max(shares) <= 2.0 * min(shares):
        reduce_rounds, bcast_rounds = rounds_of()
        rg = _color_rounds(reduce_rounds, world)
        bg = _color_rounds(bcast_rounds, world)
        n_sequential = sum(len(r) for r in reduce_rounds) + sum(
            len(r) for r in bcast_rounds
        )
        if len(rg) + len(bg) < n_sequential:
            plan = _MergedPlan(rg, bg)
    cache[key] = plan
    return plan


def _stack_segments(
    flat: jnp.ndarray, sizes: Sequence[int], pad_value
) -> jnp.ndarray:
    """[n] → [T, max(sizes)] with each tree's segment padded to the max."""
    pad = max(sizes)
    rows = []
    off = 0
    for size in sizes:
        seg = flat[off : off + size]
        if size < pad:
            seg = jnp.concatenate([seg, jnp.full((pad - size,), pad_value, flat.dtype)])
        rows.append(seg)
        off += size
    return jnp.stack(rows)


def _unstack_segments(stacked: jnp.ndarray, sizes: Sequence[int]) -> jnp.ndarray:
    return jnp.concatenate([stacked[t, :size] for t, size in enumerate(sizes)])


def _run_merged_groups(
    stacked: jnp.ndarray,
    groups,
    axis_name: str,
    combine: str,
) -> jnp.ndarray:
    """Run one phase's merged rounds: each group is one ppermute where rank r
    sends its ``src_row[r]``-th stacked row and folds the received segment
    into its ``dst_row[r]``-th row (``combine``: add | max | adopt)."""
    me = lax.axis_index(axis_name)
    for perm, src_row, dst_row, is_dst in groups:
        send = lax.dynamic_index_in_dim(
            stacked, jnp.asarray(src_row)[me], 0, keepdims=False
        )
        recvd = lax.ppermute(send, axis_name, perm)
        row = jnp.asarray(dst_row)[me]
        sel = jnp.asarray(is_dst)[me]
        cur = lax.dynamic_index_in_dim(stacked, row, 0, keepdims=False)
        if combine == "add":
            new = jnp.where(sel, cur + recvd, cur)
        elif combine == "max":
            new = jnp.where(sel, jnp.maximum(cur, recvd), cur)
        else:  # adopt (broadcast)
            new = jnp.where(sel, recvd, cur)
        stacked = lax.dynamic_update_index_in_dim(stacked, new, row, 0)
    return stacked


def _run_merged(
    x: jnp.ndarray,
    strategy: Strategy,
    plan: _MergedPlan,
    axis_name: str,
    op: ReduceOp,
    phases: str,  # "reduce" | "broadcast" | "both"
    active_mask: Optional[jnp.ndarray],
) -> jnp.ndarray:
    flat = x.reshape(-1)
    if flat.size == 0:
        return x
    if active_mask is not None:
        flat = _mask_contribution(flat, active_mask, axis_name, op)
    sizes = _segment_sizes(flat.size, strategy.tree_shares())
    pad_value = _identity_for(op, flat.dtype)
    stacked = _stack_segments(flat, sizes, pad_value)
    if phases in ("reduce", "both"):
        combine = "max" if op is ReduceOp.MAX else "add"
        stacked = _run_merged_groups(stacked, plan.reduce_groups, axis_name, combine)
    if phases in ("broadcast", "both"):
        stacked = _run_merged_groups(stacked, plan.broadcast_groups, axis_name, "adopt")
    return _unstack_segments(stacked, sizes).reshape(x.shape)


def _run_segments(
    x: jnp.ndarray,
    strategy: Strategy,
    per_segment: Callable[[jnp.ndarray, Tree], jnp.ndarray],
) -> jnp.ndarray:
    """Shared scaffolding: flatten, split across trees by share, run each
    tree's segment program, reassemble in the original shape."""
    flat = x.reshape(-1)
    if flat.size == 0:
        return x
    sizes = _segment_sizes(flat.size, strategy.tree_shares())
    outs: List[jnp.ndarray] = []
    off = 0
    for tree, size in zip(strategy.trees, sizes):
        if size == 0:
            continue
        outs.append(per_segment(flat[off : off + size], tree))
        off += size
    result = jnp.concatenate(outs) if len(outs) > 1 else outs[0]
    return result.reshape(x.shape)


def _avg_normalize(result: jnp.ndarray, active_mask: jnp.ndarray, op: ReduceOp) -> jnp.ndarray:
    if op is not ReduceOp.AVG:
        return result
    n_active = jnp.maximum(jnp.sum(active_mask.astype(result.dtype)), 1)
    return result / n_active


def masked_psum_shard(
    x: jnp.ndarray,
    active_mask: jnp.ndarray,
    axis_name: str = RANKS_AXIS,
    op: ReduceOp = ReduceOp.SUM,
) -> jnp.ndarray:
    """Subset allreduce as one XLA collective: ``psum(where(active, x, id))``.

    On a flat ICI mesh this is the optimal program for the reference's
    subset-collective semantics — the masked contribution *is* the relay
    algebra (inactive ranks forward zeros through the torus), and XLA's
    all-reduce already uses the bandwidth-optimal schedule.  The tree-schedule
    path (:func:`allreduce_shard`) earns its keep on hierarchical/irregular
    topologies where the synthesized strategy beats the flat collective.
    """
    contrib = _mask_contribution(x, active_mask, axis_name, op)
    n_active = jnp.maximum(jnp.sum(active_mask.astype(x.dtype)), 1)
    return _fused_reduce(contrib, axis_name, op, n_active)


def allreduce_shard(
    x: jnp.ndarray,
    active_mask: jnp.ndarray,
    strategy: Strategy,
    axis_name: str = RANKS_AXIS,
    op: ReduceOp = ReduceOp.SUM,
) -> jnp.ndarray:
    """Strategy-shaped allreduce over ``axis_name``; call inside shard_map.

    ``x`` is this rank's contribution (any shape); ``active_mask`` is a
    ``[world]`` bool/int array.  Result lands on every rank, active or not
    (relays receive too, matching the reference broadcast phase).
    """
    world = strategy.world_size
    plan = _merged_plan(strategy)
    if plan is not None:
        result = _run_merged(x, strategy, plan, axis_name, op, "both", active_mask)
        return _avg_normalize(result, active_mask, op)

    def per_segment(seg, tree):
        acc = _mask_contribution(seg, active_mask, axis_name, op)
        acc = _run_reduce_rounds(acc, tree.reduce_rounds(), axis_name, world, op)
        return _run_broadcast_rounds(acc, tree.broadcast_rounds(), axis_name, world)

    return _avg_normalize(_run_segments(x, strategy, per_segment), active_mask, op)


def _chunk_bounds(nelems: int, chunk_elems: int) -> List[Tuple[int, int]]:
    """Static ``(offset, length)`` split of a flat payload at the chunk
    granularity; the tail chunk keeps the remainder."""
    return [
        (off, min(chunk_elems, nelems - off))
        for off in range(0, nelems, chunk_elems)
    ]


def _tree_allreduce_chunk(
    seg: jnp.ndarray,
    tree: Tree,
    active_mask: jnp.ndarray,
    axis_name: str,
    world: int,
    op: ReduceOp,
) -> jnp.ndarray:
    """One chunk's allreduce through ONE tree's round schedule — the unit
    the chunked dispatch (and its dispatch-count tests) fan out over."""
    acc = _mask_contribution(seg, active_mask, axis_name, op)
    acc = _run_reduce_rounds(acc, tree.reduce_rounds(), axis_name, world, op)
    return _run_broadcast_rounds(acc, tree.broadcast_rounds(), axis_name, world)


def chunked_allreduce_shard(
    x: jnp.ndarray,
    active_mask: jnp.ndarray,
    strategy: Strategy,
    axis_name: str = RANKS_AXIS,
    op: ReduceOp = ReduceOp.SUM,
    chunk_bytes: Optional[int] = None,
) -> jnp.ndarray:
    """Bucket-rolling strategy allreduce: the payload splits into
    independent per-chunk collectives of at most ``chunk_bytes`` each
    (``ADAPCC_RING_CHUNK_BYTES`` overrides, the one chunk-knob precedence
    ladder), so XLA's async collectives can interleave chunk transfers
    with whatever compute still runs — the engine half of the per-bucket
    rolling sync (docs/OVERLAP.md §2, the reference's 4 MB chunk pipeline,
    commu.py:401-403).

    Bitwise contract: the payload is first split across trees by share at
    the SAME boundaries as the unchunked dispatch (``_segment_sizes`` over
    the whole payload), and only then chunked within each tree's segment —
    so every element rides the same tree and the same per-round add order
    as :func:`allreduce_shard`, and the result is bitwise-identical on
    single- and multi-tree strategies alike.  Chunking the flat payload
    directly would shift the element→tree assignment and change last-bit
    reduction order on multi-tree strategies."""
    from adapcc_tpu.comm.pallas_ring import resolve_chunk_bytes

    flat = x.reshape(-1)
    if flat.size == 0:
        return x
    chunk_elems = max(1, resolve_chunk_bytes(chunk_bytes) // flat.dtype.itemsize)
    if flat.size <= chunk_elems:
        return allreduce_shard(x, active_mask, strategy, axis_name=axis_name, op=op)
    world = strategy.world_size
    sizes = _segment_sizes(flat.size, strategy.tree_shares())
    outs: List[jnp.ndarray] = []
    off = 0
    for tree, size in zip(strategy.trees, sizes):
        if size == 0:
            continue
        seg = flat[off : off + size]
        off += size
        outs.extend(
            _tree_allreduce_chunk(
                seg[o : o + n], tree, active_mask, axis_name, world, op
            )
            for o, n in _chunk_bounds(size, chunk_elems)
        )
    result = jnp.concatenate(outs) if len(outs) > 1 else outs[0]
    return _avg_normalize(result, active_mask, op).reshape(x.shape)


def chunked_psum_shard(
    x: jnp.ndarray,
    active_mask: Optional[jnp.ndarray],
    axis_name: str = RANKS_AXIS,
    op: ReduceOp = ReduceOp.SUM,
    chunk_bytes: Optional[int] = None,
    world: Optional[int] = None,
) -> jnp.ndarray:
    """Bucket-rolling XLA-collective allreduce: the psum-plane twin of
    :func:`chunked_allreduce_shard`.  ``active_mask=None`` is the
    statically-full-world case (``world`` supplies the AVG denominator);
    a mask routes each chunk through :func:`masked_psum_shard` with the
    usual relay semantics."""
    from adapcc_tpu.comm.pallas_ring import resolve_chunk_bytes

    flat = x.reshape(-1)
    if flat.size == 0:
        return x
    if active_mask is None and world is None:
        raise ValueError("chunked_psum_shard needs world when active_mask is None")
    chunk_elems = max(1, resolve_chunk_bytes(chunk_bytes) // flat.dtype.itemsize)

    def one(seg: jnp.ndarray) -> jnp.ndarray:
        if active_mask is None:
            return _fused_reduce(seg, axis_name, op, world)
        return masked_psum_shard(seg, active_mask, axis_name, op)

    if flat.size <= chunk_elems:
        return one(flat).reshape(x.shape)
    outs = [
        one(flat[off : off + n])
        for off, n in _chunk_bounds(flat.size, chunk_elems)
    ]
    return jnp.concatenate(outs).reshape(x.shape)


def reduce_shard(
    x: jnp.ndarray,
    active_mask: jnp.ndarray,
    strategy: Strategy,
    axis_name: str = RANKS_AXIS,
    op: ReduceOp = ReduceOp.SUM,
) -> jnp.ndarray:
    """Reduce-to-root: each tree's segment is valid on that tree's root only
    (reference reduceContext keeps the result at the root, reduce.cu:258-269);
    other ranks hold partial sums for their segment."""
    world = strategy.world_size
    plan = _merged_plan(strategy)
    if plan is not None:
        result = _run_merged(x, strategy, plan, axis_name, op, "reduce", active_mask)
        return _avg_normalize(result, active_mask, op)

    def per_segment(seg, tree):
        acc = _mask_contribution(seg, active_mask, axis_name, op)
        return _run_reduce_rounds(acc, tree.reduce_rounds(), axis_name, world, op)

    return _avg_normalize(_run_segments(x, strategy, per_segment), active_mask, op)


def _fused_reduce(x: jnp.ndarray, axis_name: str, op: ReduceOp, denom) -> jnp.ndarray:
    """One XLA collective for the op: pmax for MAX, psum for SUM, psum/denom
    for AVG.  ``denom`` is the caller's averaging base — the full world on
    fast paths, the active count on masked paths."""
    if op is ReduceOp.MAX:
        return lax.pmax(x, axis_name)
    s = lax.psum(x, axis_name)
    if op is ReduceOp.AVG:
        s = s / denom
    return s


def reduce_fastpath_shard(
    x: jnp.ndarray,
    strategy: Strategy,
    axis_name: str = RANKS_AXIS,
    op: ReduceOp = ReduceOp.SUM,
) -> jnp.ndarray:
    """Full-world reduce as one fused XLA collective per tree segment: psum
    (or pmax), result kept on that segment's root — same contract as the
    schedule path (root holds the total, others keep their local partial)
    without the per-round ppermute overhead on a healthy pod."""
    me = lax.axis_index(axis_name)

    def per_segment(seg, tree):
        total = _fused_reduce(seg, axis_name, op, strategy.world_size)
        return jnp.where(me == tree.root, total, seg)

    return _run_segments(x, strategy, per_segment)


def broadcast_fastpath_shard(
    x: jnp.ndarray,
    strategy: Strategy,
    axis_name: str = RANKS_AXIS,
) -> jnp.ndarray:
    """Full-world broadcast as one masked psum per tree segment: only the
    root contributes, so the sum IS the root's value on every rank."""
    me = lax.axis_index(axis_name)

    def per_segment(seg, tree):
        contrib = jnp.where(me == tree.root, seg, jnp.zeros_like(seg))
        # psum promotes bool to int32; the schedule path preserves dtype
        return lax.psum(contrib, axis_name).astype(seg.dtype)

    return _run_segments(x, strategy, per_segment)


def broadcast_shard(
    x: jnp.ndarray,
    strategy: Strategy,
    axis_name: str = RANKS_AXIS,
) -> jnp.ndarray:
    """Broadcast from each tree's root: the root's segment replaces everyone
    else's (reference boardcastContext reads the user tensor at the root,
    boardcast.cu:279-282)."""
    world = strategy.world_size
    plan = _merged_plan(strategy)
    if plan is not None:
        return _run_merged(
            x, strategy, plan, axis_name, ReduceOp.SUM, "broadcast", None
        )

    def per_segment(seg, tree):
        return _run_broadcast_rounds(seg, tree.broadcast_rounds(), axis_name, world)

    return _run_segments(x, strategy, per_segment)


# --------------------------------------------------------------------------- #
# host-level engine: compiled-program cache + stacked-array entry points
# --------------------------------------------------------------------------- #

class CollectiveEngine:
    """Compiled, cached collective programs over one world mesh.

    The analog of the reference's persistent transmission context
    (SURVEY.md §3.2): creating one is cheap; the first call per
    (primitive, shape, dtype, op) compiles and caches, later calls replay the
    executable.  ``clear()`` drops the cache — the analog of
    ``exitThreads`` tearing contexts down before re-synthesis
    (reconstruct_topology, adapcc.py:63-67).

    Entry points take **stacked** arrays of shape ``[world, ...]`` where row
    ``r`` is rank ``r``'s contribution, and return the same shape (row ``r``
    = rank ``r``'s result).  This is the single-controller view; training
    loops instead call the ``*_shard`` functions inside their own shard_map.
    """

    def __init__(
        self,
        mesh: Mesh,
        strategy: Strategy,
        axis_name: str = RANKS_AXIS,
        use_xla_fastpath: bool = True,
        trace: Optional[Any] = None,
        tuner: Optional[Any] = None,
    ) -> None:
        if mesh.devices.size != strategy.world_size:
            raise ValueError(
                f"mesh has {mesh.devices.size} devices but strategy world is "
                f"{strategy.world_size}"
            )
        # fail fast on a typo'd A/B knob: dying here costs nothing, dying at
        # the first traced collective costs the whole backend/model setup
        _merged_env_disabled()
        from adapcc_tpu.tuner import CollectiveTuner, tuner_mode

        # same fail-fast policy for ADAPCC_TUNER; additionally, a non-off
        # mode with no caller-provided tuner auto-builds one for this mesh,
        # so `ADAPCC_TUNER=record benchmarks.collectives ...` measures into
        # the database with zero wiring at the call site
        if tuner is None and tuner_mode() != "off":
            tuner = CollectiveTuner.for_mesh(mesh)
        #: optional CollectiveTuner: consulted by ring_allreduce when
        #: ADAPCC_TUNER=choose, fed dispatch walltimes when record|choose
        self.tuner = tuner
        self.mesh = mesh
        self.strategy = strategy
        # two-level world: a ("dcn", "ici") mesh executes strategies
        # hierarchically — intra-slice traffic on the ICI axis, master trees
        # on the DCN axis (comm/two_level.py); flat meshes keep the single
        # ``ranks`` axis.  XLA-native primitives reduce over all mesh axes.
        from adapcc_tpu.comm.two_level import is_two_level

        self.two_level = is_two_level(mesh)
        if self.two_level:
            self.num_slices, self.ici_size = (int(s) for s in mesh.devices.shape)
            self.axis_name = tuple(mesh.axis_names)
        else:
            self.axis_name = axis_name
        self.use_xla_fastpath = use_xla_fastpath
        #: optional CollectiveTrace recording every dispatch (track.txt analog)
        self.trace = trace
        self._cache: Dict[Tuple, Callable] = {}
        #: world epoch (adapcc_tpu.elastic): bumped by :meth:`advance_epoch`
        #: on every membership change; collectives issued with a stale
        #: ``epoch=`` token raise :class:`EpochMismatch` instead of running
        self.epoch = 0
        # fail fast on a typo'd ADAPCC_COLL_ALGO, same policy as the merge
        # and tuner knobs above
        from adapcc_tpu.comm.latency import resolve_coll_algo

        resolve_coll_algo(None)
        #: lazily computed sim crossover (ring vs recursive doubling) the
        #: `auto` algorithm selector consults; None = not yet computed
        self._algo_crossover: Optional[float] = None
        #: the ScheduleProgram executed by ``algo="ir"`` dispatches; None =
        #: derive from the strategy on first use (docs/COMPILER.md).  An
        #: explicit :meth:`set_schedule_program` pin survives strategy
        #: hot-swaps; a derived program is re-derived after one.
        self._ir_program: Optional[Any] = None
        self._ir_program_explicit = False
        #: program fingerprints already certified by compiler.verify — a
        #: program is verified once, not per compiled shape
        self._ir_verified: set = set()
        #: (base fingerprint, resolved passes) -> optimized program memo
        #: (compiler/optimize.py); keyed by fingerprint so a strategy
        #: hot-swap or re-pin misses naturally instead of needing a flush
        self._ir_optimized: Dict[Tuple, Any] = {}
        #: whether the last strategy-derived IR program came from the
        #: Strategy.schedule_program memo (dispatch-trace extra); None
        #: until something derives
        self._ir_derived_cache_hit: Optional[bool] = None

    # -- elastic plan failover -------------------------------------------------

    def advance_epoch(self, strategy: Optional[Strategy] = None) -> int:
        """World change: bump the epoch and optionally hot-swap the
        executing strategy.

        Compiled programs stay cached under their strategy fingerprint
        (``_schedule_variant``), so swapping to a pre-warmed standby plan
        (:class:`adapcc_tpu.elastic.standby.StandbyPlanCache`) is a
        dispatch-time cache-key switch — no cold recompile stall on the
        failover step.  Unlike :meth:`clear`, nothing is dropped: the old
        epoch's programs remain warm for the recovery swap back.
        """
        if strategy is not None:
            if strategy.world_size != self.world_size:
                raise ValueError(
                    f"standby strategy world {strategy.world_size} != engine "
                    f"world {self.world_size}; elastic swaps keep the mesh "
                    "and mask dead ranks (relay semantics), they do not "
                    "shrink the device set"
                )
            self.strategy = strategy
            # a strategy-derived IR program belongs to the old strategy;
            # re-derive lazily (an explicit set_schedule_program pin stays)
            if not self._ir_program_explicit:
                self._ir_program = None
        self.epoch += 1
        return self.epoch

    def _check_epoch(self, epoch: Optional[int]) -> None:
        if epoch is not None and epoch != self.epoch:
            raise EpochMismatch(epoch, self.epoch)

    def _record(
        self, primitive: str, impl: str, stacked: jnp.ndarray, **extra: Any
    ) -> None:
        if self.trace is not None:
            self.trace.record(
                primitive, impl, int(stacked.nbytes), epoch=self.epoch, **extra
            )

    @property
    def world_size(self) -> int:
        return self.strategy.world_size

    def clear(self) -> None:
        self._cache.clear()
        if self.tuner is not None:
            # dropped programs recompile on next dispatch; the timer must
            # re-discard those first calls or a compile walltime lands in
            # the database as a steady-state sample
            self.tuner.timer.reset()

    def _active_to_mask(self, active_gpus: Optional[Sequence[int]]) -> jnp.ndarray:
        if active_gpus is None:
            return jnp.ones((self.world_size,), dtype=jnp.bool_)
        ranks = list(active_gpus)
        bad = [r for r in ranks if not 0 <= r < self.world_size]
        if bad:
            raise ValueError(f"active ranks {bad} outside world [0, {self.world_size})")
        m = np.zeros((self.world_size,), dtype=bool)
        m[ranks] = True
        return jnp.asarray(m)

    def _check_world_dim(self, stacked: jnp.ndarray, what: str) -> None:
        if stacked.shape[0] != self.world_size:
            raise ValueError(
                f"{what} expects a stacked [world, ...] array with leading dim "
                f"{self.world_size}, got shape {stacked.shape}"
            )

    def _schedule_variant(self) -> Tuple[str, bool]:
        """Cache-key component for schedule-path programs: the strategy
        fingerprint plus whether the trace will take the merged-round path —
        flipping ADAPCC_MERGE_ROUNDS mid-process must miss the cache, not
        replay a program traced under the other setting."""
        if self.two_level:
            from adapcc_tpu.comm.two_level import _two_level_merged_plan

            merged = (
                _two_level_merged_plan(
                    self.strategy, self.num_slices, self.ici_size
                )
                is not None
            )
        else:
            merged = _merged_plan(self.strategy) is not None
        return (self.strategy.fingerprint(), merged)

    def _shard_mapped(self, key: Tuple, per_shard: Callable, n_args: int) -> Callable:
        fn = self._cache.get(key)
        if fn is None:
            specs = (P(self.axis_name),) + (P(),) * (n_args - 1)
            fn = jax.jit(
                jax.shard_map(
                    per_shard,
                    mesh=self.mesh,
                    in_specs=specs,
                    out_specs=P(self.axis_name),
                    # collective results flow through ppermute/RDMA, whose
                    # replication jax cannot infer
                    check_vma=False,
                )
            )
            self._cache[key] = fn
        return fn

    # -- latency plane (adapcc_tpu/comm/latency): size-adaptive algorithm ------

    #: the tuner-grid narrowing a pinned algorithm implies: a dispatch that
    #: can only execute one plane must not offer the others' cells (they
    #: would starve the explorer — the wire-pin collapse, algorithm flavor)
    _ALGO_NARROW = {"ring": ("ring",), "rd": ("rd",), "tree": ("tree",)}

    def _allreduce_crossover_bytes(self) -> float:
        """Sim crossover (ring vs recursive doubling) for this world — the
        analytic half of the ``auto`` selector.  With a tuner attached,
        the TUNER's policy owns the number (it may carry an injected
        custom cost model, and its candidate-grid gate must agree with
        the auto decision on every payload); standalone engines compute
        it from the calibrated α-β model, cached per engine."""
        if self.tuner is not None:
            return self.tuner.policy.algo_crossover_bytes()
        if self._algo_crossover is None:
            from adapcc_tpu.sim.calibrate import load_or_default
            from adapcc_tpu.sim.cost_model import (
                allreduce_crossover_bytes,
                bottleneck_ring_coeffs,
            )

            model = load_or_default(world=self.world_size)
            self._algo_crossover = allreduce_crossover_bytes(
                self.world_size,
                bottleneck_ring_coeffs(model, self.world_size),
            )
        return self._algo_crossover

    def _auto_algo(
        self, per_rank_bytes: int, wire_dtype: Optional[str] = None
    ) -> Optional[str]:
        """The ``auto`` selector's analytic decision: recursive doubling
        for sub-crossover payloads where the latency plane can run, None
        (= stay on the ring plane) otherwise.  Trees never win allreduce
        on the model (full payload every hop), so they are executed only
        by pin or by a measured tuner cell.

        ``auto`` is NOT an explicit rd pin: a pinned wire codec (env or
        the caller's ``wire_dtype`` argument) keeps auto on the
        codec-capable ring planes instead of tripping the loud
        algo-vs-codec conflict guard — that guard exists for two
        *explicit* pins in contradiction."""
        from adapcc_tpu.comm.latency import latency_algo_unsupported_reason

        if self.two_level or self.world_size < 2:
            return None
        if self._wire_pinned_non_off(wire_dtype):
            return None
        if latency_algo_unsupported_reason(
            self.world_size, "rd", self.two_level
        ) is not None:
            return None
        if per_rank_bytes < self._allreduce_crossover_bytes():
            return "rd"
        return None

    def _wire_pinned_non_off(self, wire_dtype: Optional[str]) -> bool:
        """Whether an EXPLICIT wire-codec pin (env or argument — never the
        strategy's synthesized default) resolves to a real codec."""
        import os

        from adapcc_tpu.quant import resolve_wire_dtype
        from adapcc_tpu.quant.codec import WIRE_DTYPE_ENV

        env = os.environ.get(WIRE_DTYPE_ENV)
        if wire_dtype is None and (env is None or not env.strip()):
            return False
        return resolve_wire_dtype(wire_dtype) != "off"

    def _check_algo_wire_conflict(
        self, algo: str, wire_dtype: Optional[str]
    ) -> None:
        """Two explicit pins in conflict reject loudly: the latency plane
        has no wire-codec variants, so a pinned non-"off" codec cannot
        ride a pinned rd/tree dispatch (silently running fp32 under a
        codec label is the lie the fused-wire work eliminated).  Only
        explicit pins conflict — the strategy's synthesized default, the
        auto selector, and the tuner all stand down instead."""
        if self._wire_pinned_non_off(wire_dtype):
            raise ValueError(
                f"collective algo {algo!r} has no wire-codec plane but a "
                "wire_dtype is pinned (env or argument); pin one knob or "
                "the other — codecs ride the ring planes only"
            )

    def _latency_allreduce(
        self,
        stacked: jnp.ndarray,
        algo: str,
        mask: Optional[jnp.ndarray] = None,
        op: ReduceOp = ReduceOp.SUM,
    ) -> Tuple[jnp.ndarray, Tuple, bool]:
        """Dispatch one latency-plane allreduce (``rd`` | ``tree``);
        returns ``(result, cache_key, cache_hit)``.  Rejects loudly where
        the plane cannot run — reachable only via an explicit pin (the
        auto selector and the tuner grid both consult the same support
        funnel first)."""
        from adapcc_tpu.comm import latency as lat

        reason = lat.latency_algo_unsupported_reason(
            self.world_size, algo, self.two_level
        )
        if reason is not None:
            raise ValueError(f"allreduce algo={algo!r} cannot run here: {reason}")
        world = self.world_size
        axis = self.axis_name
        fn = (
            lat.rd_allreduce_shard if algo == "rd" else lat.tree_allreduce_shard
        )
        if mask is None:
            mask = jnp.ones((world,), dtype=jnp.bool_)

        def per_shard(x, m):  # x: [1, *payload]
            return fn(x[0], m, world, axis, op=op)[None]

        key = (f"{algo}_allreduce", stacked.shape, stacked.dtype.name, op)
        cache_hit = key in self._cache
        return self._shard_mapped(key, per_shard, 2)(stacked, mask), key, cache_hit

    # -- IR plane (adapcc_tpu/compiler): the compiled ScheduleProgram executor -

    def _certify_program(self, program) -> None:
        """Verify a ScheduleProgram once per fingerprint (the verifier is
        pure; re-running it per compiled shape would be dispatch noise)."""
        from adapcc_tpu.compiler.verify import verify_program

        fp = program.fingerprint()
        if fp not in self._ir_verified:
            verify_program(program)
            self._ir_verified.add(fp)

    def set_schedule_program(self, program) -> None:
        """Pin the :class:`~adapcc_tpu.compiler.ir.ScheduleProgram` that
        ``algo="ir"`` dispatches execute — the entry point for synthesized
        schedules with no Strategy spelling (docs/COMPILER.md).  The
        program is verified here, before anything compiles; a bad program
        dies at the pin, not at the first traced collective."""
        if program.world != self.world_size:
            raise ValueError(
                f"schedule program {program.name!r} is for world "
                f"{program.world}, engine world is {self.world_size}"
            )
        self._certify_program(program)
        self._ir_program = program
        self._ir_program_explicit = True

    def schedule_program(self):
        """The pre-optimization ScheduleProgram ``algo="ir"`` dispatches
        resolve: the pinned one, else a verified program derived from the
        engine's strategy (memoized in ``Strategy.schedule_program`` —
        whether that memo hit is surfaced in the dispatch-trace extras).
        On a two-level ``(dcn, ici)`` mesh the derived program is the
        composed two-level schedule, the hierarchy the mesh can actually
        execute.  ``sim/replay.simulate_program`` takes this same object —
        pricing and execution share the schedule by construction."""
        if self._ir_program is None:
            if self.two_level:
                from adapcc_tpu.compiler.builders import (
                    two_level_allreduce_program,
                )

                program = two_level_allreduce_program(
                    self.num_slices,
                    self.ici_size,
                    wire_dtype=self.strategy.wire_dtype,
                )
                self._ir_derived_cache_hit = False
            else:
                program = self.strategy.schedule_program()
                self._ir_derived_cache_hit = bool(
                    self.strategy.__dict__.get("_last_program_cache_hit")
                )
            self._certify_program(program)
            self._ir_program = program
        return self._ir_program

    def optimized_schedule_program(self):
        """The post-optimization program ``algo="ir"`` actually lowers:
        :meth:`schedule_program` through the ``compiler/optimize.py`` pass
        pipeline in force (``ADAPCC_IR_OPT``), memoized per (base
        fingerprint, resolved passes).  Every pass verifies pass-in and
        pass-out inside ``optimize_program``, so the result joins the
        certified set; an already-optimal program comes back as the SAME
        object (the passes are identity on it)."""
        from adapcc_tpu.compiler.optimize import (
            optimize_program,
            resolve_ir_opt,
        )

        base = self.schedule_program()
        passes = resolve_ir_opt()
        key = (base.fingerprint(), passes)
        program = self._ir_optimized.get(key)
        if program is None:
            program = optimize_program(base, passes=passes)
            self._ir_verified.add(program.fingerprint())
            self._ir_optimized[key] = program
        return program

    def _ir_allreduce(
        self,
        stacked: jnp.ndarray,
        op: ReduceOp,
        per_rank_bytes: int,
        active_gpus: Optional[Sequence[int]],
    ) -> jnp.ndarray:
        """Dispatch one allreduce through the compiled ScheduleProgram
        executor (``compiler/lower.py``): resolve the program, run the
        optimizer pipeline in force, lower the POST-optimization object —
        flat mesh or native two-level — with the executed program's
        fingerprint, pass list and dispatch count in the trace, and
        record-mode timings under the tuner's ``IR_PATH`` /
        ``IR_OPT_PATH`` cells."""
        from adapcc_tpu.compiler import lower as ir_lower
        from adapcc_tpu.tuner.policy import IR_OPT_PATH, IR_PATH, NO_CHUNK

        if active_gpus is not None:
            raise ValueError(
                "algo='ir' executes the program's own relay masks; "
                "active_gpus subsets are not expressible on this path — "
                "build a program with relays= and set_schedule_program it"
            )
        base = self.schedule_program()
        program = self.optimized_schedule_program()
        # two explicit pins in conflict reject loudly (the rd/tree wire
        # policy): on the IR path the wire codec is a PROGRAM property,
        # so an env/argument pin that disagrees with the program's
        # first-class annotation cannot be honored silently
        if self._wire_pinned_non_off(None):
            from adapcc_tpu.quant import resolve_wire_dtype

            pinned = resolve_wire_dtype(None)
            if pinned != program.wire_dtype:
                raise ValueError(
                    f"algo='ir' program {program.name!r} carries "
                    f"wire_dtype={program.wire_dtype!r} but {pinned!r} is "
                    "pinned; IR wire codecs are program properties — "
                    "rebuild the program with that codec or drop the pin"
                )
        tuner = self.tuner
        key = (
            "ir_allreduce", program.fingerprint(), stacked.shape,
            stacked.dtype.name, op,
        )
        if self.two_level:
            # native hierarchy execution: every color ships over exactly
            # the (dcn | ici) axis its classification names — rejects
            # loudly (naming the round) for programs that do not
            # decompose, BEFORE anything compiles
            dcn_axis, ici_axis = self.axis_name
            ir_lower.two_level_color_axes(
                program, self.num_slices, self.ici_size
            )
            per_shard = ir_lower.allreduce_per_shard_two_level(
                program, self.num_slices, self.ici_size,
                dcn_axis, ici_axis, op,
            )
        else:
            per_shard = ir_lower.allreduce_per_shard(
                program, self.axis_name, op
            )
        cache_hit = key in self._cache
        timing = tuner is not None and tuner.recording
        t0 = time.perf_counter()
        out = self._shard_mapped(key, per_shard, 1)(stacked)
        extras: Dict[str, Any] = {
            "algo": "ir",
            "program": program.name,
            "program_fingerprint": program.fingerprint(),
            "wire_dtype": program.wire_dtype,
            "passes": list(program.applied_passes),
            "dispatches": ir_lower.dispatch_count(program),
        }
        if program is not base:
            extras["base_fingerprint"] = base.fingerprint()
        if not self._ir_program_explicit and (
            self._ir_derived_cache_hit is not None
        ):
            extras["program_cache_hit"] = self._ir_derived_cache_hit
        if self.two_level:
            extras["hier"] = f"{self.num_slices}x{self.ici_size}"
        if timing:
            jax.block_until_ready(out)
            duration = time.perf_counter() - t0
            extras["duration_s"] = duration
            # optimized and naive lowerings are different executables:
            # they live in different tuner cells so measured medians can
            # arbitrate the opt axis (the ADAPCC_IR_OPT A/B)
            path = IR_PATH if program is base else IR_OPT_PATH
            tuner.observe_dispatch(
                tuner.key_for(
                    "allreduce", per_rank_bytes, path, NO_CHUNK,
                    program.wire_dtype,
                ),
                key,
                duration,
            )
        self._record(
            "allreduce", "ir", stacked, cache_hit=cache_hit, **extras
        )
        return out

    def all_reduce(
        self,
        stacked: jnp.ndarray,
        *,
        active_gpus: Optional[Sequence[int]] = None,
        op: ReduceOp = ReduceOp.SUM,
        epoch: Optional[int] = None,
        algo: Optional[str] = None,
    ) -> jnp.ndarray:
        """Allreduce with subset semantics and a size-adaptive algorithm
        selector (docs/LATENCY.md): ``algo`` is one of
        ``auto|ring|rd|tree|ir`` under the precedence **env > explicit arg >
        tuner > sim-crossover** — ``ADAPCC_COLL_ALGO`` wins, then the
        argument, then (for ``auto``/unset with a choosing tuner) a
        measured algorithm cell, then the calibrated crossover decides
        ``auto``.  Unset everywhere keeps the legacy ring/XLA plane.  The
        executed algorithm is recorded in the dispatch trace next to the
        impl, like ``wire_dtype``."""
        # keyword-only for the same reason as reduce_scatter: a positional
        # all_reduce(t, ReduceOp.AVG) must fail at the call site, not bind
        # the enum to active_gpus
        self._check_epoch(epoch)
        self._check_world_dim(stacked, "all_reduce")
        from adapcc_tpu.comm.latency import resolve_coll_algo
        from adapcc_tpu.tuner.policy import ALGO_OF_PATH, NO_CHUNK

        algo_req = resolve_coll_algo(algo)
        per_rank_bytes = (
            int(np.prod(stacked.shape[1:])) * stacked.dtype.itemsize
        )
        if algo_req == "ir":
            return self._ir_allreduce(stacked, op, per_rank_bytes, active_gpus)
        mask = self._active_to_mask(active_gpus)
        tuner = self.tuner
        tplan = None
        executed_algo: Optional[str] = None
        if algo_req in ("rd", "tree"):
            executed_algo = algo_req  # pinned: loud reject if unsupported
        elif (
            algo_req in (None, "auto")
            and not self.two_level
            and tuner is not None
            and tuner.choosing
            # an env-pinned codec collapses the policy's grid to that
            # codec's cells, none of which this plane's {xla, rd, tree}
            # arbitration can offer (all fp32) — stand down like
            # _auto_algo does, instead of dying on an empty grid
            and not self._wire_pinned_non_off(None)
        ):
            # the measured slot of the ladder: rank THE CELLS THIS PLANE
            # CAN RUN — the XLA-plane baseline cell against the rd/tree
            # cells — READ-ONLY (rank_only: no exploration, no incumbent
            # write).  An exploring choose() over the full Pallas grid
            # would pin the explorer on chunk/codec cells whose trial
            # budget can never drain from this entry point, and without
            # the xla cell a measured rd sample would beat every
            # unmeasurable alternative forever.  Only an rd/tree winner
            # reroutes; the xla winner keeps the fastpath below.
            tplan = tuner.rank_only(
                "allreduce", per_rank_bytes, stacked.dtype.name,
                algos=("xla", "rd", "tree"),
            )
            executed_algo = ALGO_OF_PATH.get(tplan.key.path)
        elif algo_req == "auto":
            executed_algo = self._auto_algo(per_rank_bytes)
        if executed_algo is not None:
            self._check_algo_wire_conflict(executed_algo, None)
            timing = tuner is not None and tuner.recording
            t0 = time.perf_counter()
            out, key, cache_hit = self._latency_allreduce(
                stacked, executed_algo, mask, op
            )
            extras: Dict[str, Any] = {"algo": executed_algo}
            if timing:
                jax.block_until_ready(out)
                duration = time.perf_counter() - t0
                extras["duration_s"] = duration
                tuner.observe_dispatch(
                    tuner.key_for(
                        "allreduce", per_rank_bytes, executed_algo,
                        NO_CHUNK, "off",
                    ),
                    key,
                    duration,
                )
            if tplan is not None:
                extras["tuner"] = tplan.trace_extra(
                    applied=tplan.key.path == executed_algo
                )
            self._record(
                "allreduce", executed_algo, stacked,
                cache_hit=cache_hit, **extras,
            )
            return out
        plan2l = None
        if (
            self.two_level
            and op is not ReduceOp.MAX
            # an explicit "ring" pin (env or argument) names the LEGACY
            # ring/psum plane — the composed plan must stand down like
            # every other unpinned selector, or the pin's A/B (e.g. the
            # small_msg_crossover battery arms) silently times the wrong
            # program under the pinned label
            and algo_req in (None, "auto")
        ):
            from adapcc_tpu.strategy.hierarchy import plan_of

            candidate = plan_of(self.strategy)
            # only the RS/AG pod algorithm has a composed data plane; a
            # "replicate" plan IS the projected schedule path below, and
            # MAX has no psum_scatter spelling — both ride the fixed path
            if candidate is not None and candidate.pod_algo == "rs-ag":
                plan2l = candidate
        if plan2l is not None:
            from adapcc_tpu.comm.two_level import (
                allreduce_two_level_composed_shard,
            )

            per_shard = functools.partial(
                allreduce_two_level_composed_shard,
                plan=plan2l,
                num_slices=self.num_slices,
                ici_size=self.ici_size,
                op=op,
            )
            key = (
                "allreduce2l-composed", self.strategy.fingerprint(),
                plan2l.leader_algo, stacked.shape, stacked.dtype.name, op,
            )
            cache_hit = key in self._cache
            timing = tuner is not None and tuner.recording
            t0 = time.perf_counter()
            out = self._shard_mapped(key, per_shard, 2)(stacked, mask)
            extras = {
                "algo": "two-level",
                # the EXECUTED plan is an artifact, not a guess: which
                # levels ran which schedule, on what sketch
                "hier": {
                    "pods": plan2l.sketch.num_pods,
                    "pod_size": plan2l.sketch.pod_size,
                    "pod_algo": plan2l.pod_algo,
                    "leader_algo": plan2l.leader_algo,
                    "resolved_level": plan2l.resolved_level,
                },
            }
            if timing:
                from adapcc_tpu.tuner.policy import TWO_LEVEL_PATH

                jax.block_until_ready(out)
                duration = time.perf_counter() - t0
                extras["duration_s"] = duration
                tuner.observe_dispatch(
                    tuner.key_for(
                        "allreduce", per_rank_bytes, TWO_LEVEL_PATH,
                        NO_CHUNK, "off",
                    ),
                    key,
                    duration,
                )
            self._record(
                "allreduce", "two_level[composed]", stacked,
                cache_hit=cache_hit, **extras,
            )
            return out
        if self.use_xla_fastpath and active_gpus is None:
            per_shard = functools.partial(self._psum_shard, op=op)
            key = ("psum", stacked.shape, stacked.dtype.name, op)
        elif self.two_level:
            from adapcc_tpu.comm.two_level import allreduce_two_level_shard

            per_shard = functools.partial(
                allreduce_two_level_shard,
                strategy=self.strategy,
                num_slices=self.num_slices,
                ici_size=self.ici_size,
                op=op,
            )
            key = ("allreduce2l", self._schedule_variant(), stacked.shape, stacked.dtype.name, op)
        else:
            per_shard = functools.partial(
                allreduce_shard,
                strategy=self.strategy,
                axis_name=self.axis_name,
                op=op,
            )
            key = ("allreduce", self._schedule_variant(), stacked.shape, stacked.dtype.name, op)
        from adapcc_tpu.tuner.policy import XLA_PATH

        is_psum = key[0] == "psum"
        cache_hit = key in self._cache
        # the psum fastpath is the xla cell's measurable arm: record-mode
        # timings close the loop the rank_only arbitration reads
        timing = tuner is not None and tuner.recording and is_psum
        t0 = time.perf_counter()
        out = self._shard_mapped(key, per_shard, 2)(stacked, mask)
        ring_extras: Dict[str, Any] = {"algo": "ring"}
        if timing:
            jax.block_until_ready(out)
            duration = time.perf_counter() - t0
            ring_extras["duration_s"] = duration
            tuner.observe_dispatch(
                tuner.key_for(
                    "allreduce", per_rank_bytes, XLA_PATH, NO_CHUNK, "off"
                ),
                key,
                duration,
            )
        if tplan is not None:
            # applied only when the chosen cell's plane actually ran: the
            # xla cell over the psum fastpath.  A masked/two-level
            # schedule dispatch is NOT that plane, and a chunk/codec cell
            # can never run here — the trace must say so (PR 6's
            # executed-impl honesty).
            ring_extras["tuner"] = tplan.trace_extra(
                applied=tplan.key.path == XLA_PATH and is_psum
            )
        self._record(
            "allreduce", "xla" if is_psum else "schedule", stacked,
            cache_hit=cache_hit, **ring_extras,
        )
        return out

    def _psum_shard(self, x: jnp.ndarray, mask: jnp.ndarray, op: ReduceOp) -> jnp.ndarray:
        return _fused_reduce(x, self.axis_name, op, self.world_size)

    def reduce(
        self,
        stacked: jnp.ndarray,
        *,
        active_gpus: Optional[Sequence[int]] = None,
        op: ReduceOp = ReduceOp.SUM,
        epoch: Optional[int] = None,
    ) -> jnp.ndarray:
        self._check_epoch(epoch)
        self._check_world_dim(stacked, "reduce")
        if self.use_xla_fastpath and active_gpus is None and not self.two_level:
            per_shard = functools.partial(
                reduce_fastpath_shard,
                strategy=self.strategy, axis_name=self.axis_name, op=op,
            )
            key = ("reduce_fast", self.strategy.fingerprint(), stacked.shape, stacked.dtype.name, op)
            self._record("reduce", "xla", stacked, cache_hit=key in self._cache)
            return self._shard_mapped(key, per_shard, 1)(stacked)
        if self.two_level:
            from adapcc_tpu.comm.two_level import reduce_two_level_shard

            per_shard = functools.partial(
                reduce_two_level_shard,
                strategy=self.strategy,
                num_slices=self.num_slices,
                ici_size=self.ici_size,
                op=op,
            )
            key = ("reduce2l", self._schedule_variant(), stacked.shape, stacked.dtype.name, op)
        else:
            per_shard = functools.partial(
                reduce_shard, strategy=self.strategy, axis_name=self.axis_name, op=op
            )
            key = ("reduce", self._schedule_variant(), stacked.shape, stacked.dtype.name, op)
        self._record("reduce", "schedule", stacked, cache_hit=key in self._cache)
        return self._shard_mapped(key, per_shard, 2)(stacked, self._active_to_mask(active_gpus))

    def broadcast(
        self,
        stacked: jnp.ndarray,
        active_gpus: Optional[Sequence[int]] = None,
        *,
        epoch: Optional[int] = None,
    ) -> jnp.ndarray:
        """Broadcast from each tree's root (the reference's ``boardcast``
        context; the typo'd spelling survives as a deprecated alias —
        :meth:`boardcast`).

        ``active_gpus`` mirrors the reference C ABI (run.cu:150 takes the
        active set for every collective).  Broadcast *values* are
        unaffected by relay roles — inactive ranks still forward and
        receive — but the tree roots SOURCE the value, so the active set
        is enforced against them: a stale set naming a dead root rejects
        loudly here instead of silently broadcasting that root's garbage
        (the elastic failover path swaps to a standby plan rooted on an
        alive rank first).  The mask then rides the schedule program as a
        real operand — the same plumbing as :meth:`reduce` — so a masked
        dispatch can never replay the unmasked full-world fastpath."""
        self._check_epoch(epoch)
        self._check_world_dim(stacked, "broadcast")
        mask = self._active_to_mask(active_gpus)
        if active_gpus is not None:
            act = {int(r) for r in active_gpus}
            dead_roots = sorted(
                {t.root for t in self.strategy.trees} - act
            )
            if dead_roots:
                # conservative by design: the engine cannot distinguish a
                # DEAD root (broadcasting its stale row is the silent
                # corruption this guard closes) from a merely demoted-slow
                # one (alive; broadcast values are mask-independent, so
                # including it in the set is always sound).  Callers with
                # the distinction pass alive∪relays for broadcast; the
                # elastic failover path swaps to a re-rooted standby plan.
                raise ValueError(
                    f"broadcast roots {dead_roots} are not in the active set "
                    f"{sorted(act)}: a dead root cannot source the broadcast "
                    "— swap to a degraded plan rooted on alive ranks "
                    "(adapcc_tpu.elastic.standby), or, if the root is only "
                    "demoted-slow, include it in active_gpus (broadcast "
                    "values are unaffected by relay roles)"
                )
        if self.use_xla_fastpath and active_gpus is None and not self.two_level:
            per_shard = functools.partial(
                broadcast_fastpath_shard,
                strategy=self.strategy, axis_name=self.axis_name,
            )
            key = ("broadcast_fast", self.strategy.fingerprint(), stacked.shape, stacked.dtype.name)
            self._record("broadcast", "xla", stacked, cache_hit=key in self._cache)
            return self._shard_mapped(key, per_shard, 1)(stacked)
        masked = active_gpus is not None
        if self.two_level:
            from adapcc_tpu.comm.two_level import broadcast_two_level_shard

            inner = functools.partial(
                broadcast_two_level_shard,
                strategy=self.strategy,
                num_slices=self.num_slices,
                ici_size=self.ici_size,
            )
            key = ("broadcast2l", self._schedule_variant(), stacked.shape, stacked.dtype.name, masked)
        else:
            inner = functools.partial(
                broadcast_shard, strategy=self.strategy, axis_name=self.axis_name
            )
            key = ("broadcast", self._schedule_variant(), stacked.shape, stacked.dtype.name, masked)

        if masked:
            # the mask is a real operand of the compiled program (reduce's
            # plumbing): broadcast values are mask-independent by the relay
            # contract (forwarders still deliver), but the masked dispatch
            # compiles its own keyed program, so a later degraded plan can
            # consume the mask without a silent full-world replay
            def per_shard(x, m):
                return inner(x)
        else:
            per_shard = inner
        self._record("broadcast", "schedule", stacked, cache_hit=key in self._cache)
        if masked:
            return self._shard_mapped(key, per_shard, 2)(stacked, mask)
        return self._shard_mapped(key, per_shard, 1)(stacked)

    def boardcast(
        self,
        stacked: jnp.ndarray,
        active_gpus: Optional[Sequence[int]] = None,
        *,
        epoch: Optional[int] = None,
    ) -> jnp.ndarray:
        """Deprecated: the reference's typo'd spelling of
        :meth:`broadcast` (adapcc.py:55-57, boardcast.cu), kept as an
        alias so reference-shaped callers keep working.  Warns ONCE per
        process — a long training loop must not drown in a warning per
        step — then delegates unchanged."""
        global _BOARDCAST_WARNED
        if not _BOARDCAST_WARNED:
            _BOARDCAST_WARNED = True
            warnings.warn(
                "CollectiveEngine.boardcast (the reference's spelling) is "
                "deprecated; call CollectiveEngine.broadcast instead",
                DeprecationWarning,
                stacklevel=2,
            )
        return self.broadcast(stacked, active_gpus, epoch=epoch)

    # -- primitives the reference only declared (trans.h:27-36 enum stubs) ----
    # implemented here at full adaptive depth: active-subset masking with the
    # same relay contract as all_reduce (inactive ranks contribute identity
    # but stay on the forwarding path and receive results), plus hierarchical
    # DCN×ICI shaping on two-level worlds

    def _my_flat_rank(self):
        """Flat rank inside shard_map, on flat or two-level meshes."""
        if self.two_level:
            dcn_axis, ici_axis = self.axis_name
            return lax.axis_index(dcn_axis) * self.ici_size + lax.axis_index(ici_axis)
        return lax.axis_index(self.axis_name)

    def _latency_variant(
        self, primitive: str, algo: Optional[str]
    ) -> Optional[str]:
        """Resolve the latency-plane algorithm for an RS/AG dispatch
        (docs/LATENCY.md §5): ``ADAPCC_COLL_ALGO`` env > the explicit
        argument, validated against the SAME support funnel the allreduce
        selector and the tuner grid consult — a pinned variant the plane
        cannot run rejects loudly, never a silent fallback to the default
        plane under the pinned label.  ``auto``/``ring``/unset keep the
        legacy XLA/two-level plane (the allreduce crossover is an
        allreduce-shaped decision; these primitives adopt a variant only
        by pin or by the re-ranking loop)."""
        from adapcc_tpu.comm.latency import (
            latency_algo_unsupported_reason,
            resolve_coll_algo,
        )

        algo_req = resolve_coll_algo(algo)
        if algo_req not in ("rd", "tree"):
            return None
        reason = latency_algo_unsupported_reason(
            self.world_size, algo_req, self.two_level, primitive=primitive
        )
        if reason is not None:
            raise ValueError(
                f"{primitive} algo={algo_req!r} cannot run here: {reason}"
            )
        return algo_req

    def all_gather(
        self,
        stacked: jnp.ndarray,
        active_gpus: Optional[Sequence[int]] = None,
        *,
        epoch: Optional[int] = None,
        algo: Optional[str] = None,
    ) -> jnp.ndarray:
        """All-gather with subset semantics (reference stub: trans.h ALLGATHER).

        Input ``[world, *payload]`` (row r = rank r's shard) → output
        ``[world, world, *payload]`` (row r = the full gathered stack as seen
        by rank r).  With ``active_gpus``, inactive ranks contribute zeros
        (the gather identity) but still receive the gathered stack — the
        relay contract of :meth:`all_reduce`.  Two-level worlds gather
        hierarchically (DCN first, so each payload crosses DCN once).

        ``algo="rd"`` (or an ``ADAPCC_COLL_ALGO`` pin) runs the
        recursive-doubling all-gather instead — ``log2(p)`` rounds for
        latency-bound payloads (docs/LATENCY.md §5) — behind the shared
        support funnel (power-of-two flat worlds only, loud reject
        otherwise); the executed algorithm rides the trace like
        ``wire_dtype``.
        """
        self._check_epoch(epoch)
        self._check_world_dim(stacked, "all_gather")
        mask = self._active_to_mask(active_gpus)
        masked = active_gpus is not None
        if self._latency_variant("all_gather", algo) == "rd":
            from adapcc_tpu.comm import latency as lat

            world = self.world_size
            axis = self.axis_name

            def per_shard(x, m):  # x: [1, *payload]
                v = x[0]
                if masked:
                    v = jnp.where(m[self._my_flat_rank()], v, jnp.zeros_like(v))
                return lat.rd_all_gather_shard(v, world, axis)[None]

            key = ("allgather_rd", stacked.shape, stacked.dtype.name, masked)
            self._record(
                "all_gather", "rd", stacked,
                cache_hit=key in self._cache, algo="rd",
            )
            return self._shard_mapped(key, per_shard, 2)(stacked, mask)

        if self.two_level:
            from adapcc_tpu.comm.two_level import all_gather_two_level_shard

            def per_shard(x, m):  # x: [1, *payload]
                v = x[0]
                if masked:
                    v = jnp.where(m[self._my_flat_rank()], v, jnp.zeros_like(v))
                return all_gather_two_level_shard(
                    v, self.num_slices, self.ici_size
                )[None]

            key = ("allgather2l", stacked.shape, stacked.dtype.name, masked)
            self._record(
                "all_gather", "two_level", stacked,
                cache_hit=key in self._cache, algo="ring",
            )
            return self._shard_mapped(key, per_shard, 2)(stacked, mask)

        def per_shard(x, m):  # x: [1, *payload]
            v = x[0]
            if masked:
                v = jnp.where(m[self._my_flat_rank()], v, jnp.zeros_like(v))
            return lax.all_gather(v, self.axis_name, axis=0)[None]

        key = ("allgather", stacked.shape, stacked.dtype.name, masked)
        self._record(
            "all_gather", "xla", stacked,
            cache_hit=key in self._cache, algo="ring",
        )
        return self._shard_mapped(key, per_shard, 2)(stacked, mask)

    def all_to_all(
        self,
        stacked: jnp.ndarray,
        active_gpus: Optional[Sequence[int]] = None,
        *,
        epoch: Optional[int] = None,
    ) -> jnp.ndarray:
        """All-to-all over ICI with subset semantics.

        ``stacked[src, dst]`` blocks are exchanged so each rank ``r`` ends up
        with ``stacked[:, r]`` — the expert-parallel shuffle the reference
        delegates to fastmoe/NCCL (models/moe/train_moe.py, AdapCC.alltoall
        stub adapcc.py:59-61).  Expects ``stacked.shape[1] == world``.  With
        ``active_gpus``, blocks *originating* from inactive ranks are zeroed
        (they contribute identity); every rank, active or not, still receives
        its incoming blocks — inactive ranks stay on the fabric as relays.
        """
        self._check_epoch(epoch)
        self._check_world_dim(stacked, "all_to_all")
        if stacked.shape[1] != self.world_size:
            raise ValueError(
                f"all_to_all needs a [world, world, ...] stacked array, got {stacked.shape}"
            )
        from adapcc_tpu.tuner.policy import A2A_XLA_PATH, NO_CHUNK

        mask = self._active_to_mask(active_gpus)
        masked = active_gpus is not None

        if self.two_level:
            from adapcc_tpu.comm.two_level import all_to_all_two_level_shard

            def per_shard(x, m):  # x: [1, world, *payload]
                v = x[0]
                if masked:
                    v = jnp.where(m[self._my_flat_rank()], v, jnp.zeros_like(v))
                return all_to_all_two_level_shard(
                    v, self.num_slices, self.ici_size
                )[None]

            key = ("alltoall2l", stacked.shape, stacked.dtype.name, masked)
            impl = path = "two_level"
        else:
            def per_shard(x, m):  # x: [1, world, *payload]
                v = x[0]
                if masked:
                    v = jnp.where(m[self._my_flat_rank()], v, jnp.zeros_like(v))
                return lax.all_to_all(v, self.axis_name, split_axis=0, concat_axis=0)[None]

            key = ("alltoall", stacked.shape, stacked.dtype.name, masked)
            impl, path = "xla", A2A_XLA_PATH
        # all_to_all is tuned like every other collective (the primitive
        # the reference left a stub and PR 4 left untimed): with a tuner
        # attached, record|choose time every dispatch into the database
        # under the `all_to_all` primitive — the MoE dispatch/combine
        # traffic (parallel/expert.py via workloads/train_moe.py) lands
        # here at its real payload geometry
        cache_hit = key in self._cache
        tuner = self.tuner
        timing = tuner is not None and tuner.recording
        t0 = time.perf_counter()
        out = self._shard_mapped(key, per_shard, 2)(stacked, mask)
        extras: Dict[str, Any] = {}
        if timing:
            jax.block_until_ready(out)
            duration = time.perf_counter() - t0
            extras["duration_s"] = duration
            # one rank's send volume: its full [world, *payload] row
            per_rank_bytes = (
                int(np.prod(stacked.shape[1:])) * stacked.dtype.itemsize
            )
            tuner.observe_dispatch(
                tuner.key_for(
                    "all_to_all", per_rank_bytes, path, NO_CHUNK, "off"
                ),
                key,
                duration,
            )
        self._record("all_to_all", impl, stacked, cache_hit=cache_hit, **extras)
        return out

    def expert_a2a(self, axis_name: Optional[str] = None) -> Callable:
        """Shard-level MoE token-exchange function for
        :func:`adapcc_tpu.parallel.expert.expert_parallel_moe` — the
        engine-routed spelling of its ``a2a`` override, so expert traffic
        rides the engine's configuration (two-level hierarchy included)
        and is *traced* like every other collective.

        Returns ``a2a(v)`` to be called inside the caller's own shard_map:
        on a flat mesh it is the XLA ``lax.all_to_all`` over ``axis_name``
        (default: the engine's axis), on a two-level ``(dcn, ici)`` mesh
        the hierarchical two-hop exchange.  Each traced application
        records one ``all_to_all`` event (impl suffixed ``[moe]``) into
        the engine's dispatch trace — once per compiled program, the
        traceable boundary when the exchange lives inside a jitted step.
        The tuner database is fed by :meth:`all_to_all` probe dispatches
        at the same payload geometry (workloads/train_moe.py), since an
        in-jit exchange cannot be walltimed individually.
        """
        if self.two_level:
            from adapcc_tpu.comm.two_level import all_to_all_two_level_shard

            inner = functools.partial(
                all_to_all_two_level_shard,
                num_slices=self.num_slices,
                ici_size=self.ici_size,
            )
            impl = "two_level[moe]"
        else:
            name = axis_name if axis_name is not None else self.axis_name
            if name not in self.mesh.axis_names:
                raise ValueError(
                    f"expert_a2a axis {name!r} is not a mesh axis "
                    f"{tuple(self.mesh.axis_names)}"
                )
            inner = functools.partial(
                lax.all_to_all, axis_name=name,
                split_axis=0, concat_axis=0, tiled=False,
            )
            impl = "xla[moe]"

        def a2a(v):
            if self.trace is not None:
                nbytes = int(np.prod(v.shape)) * jnp.dtype(v.dtype).itemsize
                self.trace.record("all_to_all", impl, nbytes, moe=True)
            return inner(v)

        return a2a

    def kv_transfer(
        self,
        pages: Any,
        *,
        src_pod: int,
        dst_pod: int,
        wire_dtype: str = "off",
        block_size: Optional[int] = None,
        chunk_bytes: int = KV_TRANSFER_CHUNK_BYTES,
        dst_sharding: Optional[Any] = None,
        epoch: Optional[int] = None,
    ) -> Any:
        """Point-to-point KV-cache handoff between serving pods — a chunked
        DCN stream as a first-class engine primitive (docs/SERVING.md §7).

        ``pages`` is a pytree of stacked ``[world, ...]`` arrays (one slot's
        per-layer K/V pages in the :class:`~adapcc_tpu.serve.kv_cache
        .SlotKVCache` layout); the return value is the same pytree as it
        arrives on the destination pod.  ``wire_dtype="off"`` (the default)
        is the bit-exact fp32 path — the values are untouched, which is what
        the disaggregated-vs-colocated parity drill pins.  A non-"off" codec
        from the :mod:`adapcc_tpu.quant` registry puts the block-wise
        quantized wire under the stream: the returned pages carry the
        decode(encode(x)) wire values, and admission under a lossy wire is
        gated by the token-level-KL acceptance bound upstream
        (:mod:`adapcc_tpu.serve.disagg` — the engine moves bytes, the router
        owns the acceptance bar).

        Every transfer records ONE dispatch-trace event (``primitive=
        "kv_transfer"``, impl ``dcn_stream[+codec]``) with the executed
        payload bytes, wire dtype, wire bytes, chunk count at
        ``chunk_bytes`` granularity, wall duration, and the (src_pod,
        dst_pod) route — the same honesty contract as every collective.
        ``dst_sharding`` re-places the arrived pages (the destination
        pool's cache sharding); chunking is transport accounting — the
        codec is applied whole-payload so block geometry never depends on
        the stream granularity.
        """
        self._check_epoch(epoch)
        if chunk_bytes < 1:
            raise ValueError(f"chunk_bytes must be >= 1, got {chunk_bytes}")
        from adapcc_tpu.quant import get_codec
        from adapcc_tpu.sim.cost_model import wire_bytes_per_element

        codec = get_codec(wire_dtype)  # loud on an unknown codec name
        from adapcc_tpu.quant.codec import DEFAULT_BLOCK_SIZE

        block = int(block_size) if block_size is not None else DEFAULT_BLOCK_SIZE
        leaves, treedef = jax.tree_util.tree_flatten(pages)
        if not leaves:
            raise ValueError("kv_transfer needs at least one page array")
        for leaf in leaves:
            self._check_world_dim(leaf, "kv_transfer")
        t0 = time.perf_counter()
        nbytes = 0
        wire_bytes = 0.0
        moved = []
        for leaf in leaves:
            nbytes += int(leaf.nbytes)
            if codec.name == "off":
                out = leaf  # identity: the bit-exact default path
                wire_bytes += float(leaf.nbytes)
            else:
                out = codec.apply(leaf, block).astype(leaf.dtype)
                wire_bytes += float(leaf.size) * wire_bytes_per_element(
                    codec.name, block
                )
            if dst_sharding is not None:
                out = jax.device_put(out, dst_sharding)
            moved.append(out)
        jax.block_until_ready(moved)
        duration = time.perf_counter() - t0
        chunks = max(1, -(-int(wire_bytes) // int(chunk_bytes)))
        if self.trace is not None:
            suffix = "" if codec.name == "off" else f"+{codec.name}"
            extras: Dict[str, Any] = {
                "epoch": self.epoch,
                "wire_dtype": codec.name,
                "wire_bytes": int(wire_bytes),
                "chunks": chunks,
                "chunk_bytes": int(chunk_bytes),
                "duration_s": duration,
                "src_pod": int(src_pod),
                "dst_pod": int(dst_pod),
            }
            if codec.name != "off":
                extras["block_size"] = block
            self.trace.record("kv_transfer", f"dcn_stream{suffix}", nbytes, **extras)
        return jax.tree_util.tree_unflatten(treedef, moved)

    def pipe_send(
        self,
        stacked: jnp.ndarray,
        *,
        src: int,
        dst: int,
        kind: str = "activation",
        mb: Optional[int] = None,
        tick: Optional[int] = None,
        epoch: Optional[int] = None,
    ) -> jnp.ndarray:
        """Point-to-point pipeline stage hop over the ICI fabric: move rank
        ``src``'s row of a stacked ``[world, ...]`` buffer to rank ``dst``,
        leaving every other row untouched (docs/PIPELINE.md).

        The single-controller analog of a send/recv pair — one compiled
        ``shard_map`` ppermute per (route, shape, dtype), cached like every
        other engine program.  Each hop records ONE dispatch-trace event
        (``primitive="pipe_send"``, impl ``ici_hop``) with the executed
        payload bytes (one row, not the stacked buffer) and the
        (src, dst) route, plus the schedule coordinates (``kind``
        ``activation``/``grad``/``tied_embed``, microbatch, tick) when the
        executor provides them — the stage-hop analog of the
        :meth:`kv_transfer` honesty contract.
        """
        self._check_epoch(epoch)
        self._check_world_dim(stacked, "pipe_send")
        w = self.world_size
        for label, r in (("src", src), ("dst", dst)):
            if not 0 <= r < w:
                raise ValueError(
                    f"pipe_send {label}={r} outside world [0, {w})"
                )
        if src == dst:
            raise ValueError(f"pipe_send src == dst == {src}: nothing to move")
        if kind not in ("activation", "grad", "tied_embed"):
            raise ValueError(
                f"pipe_send kind={kind!r}: expected 'activation', 'grad' or "
                "'tied_embed'"
            )
        axis = self.axis_name

        def per_shard(x: jnp.ndarray) -> jnp.ndarray:
            me = lax.axis_index(axis)
            moved = lax.ppermute(x, axis, perm=[(src, dst)])
            return jnp.where(me == dst, moved, x)

        fn = self._shard_mapped(
            ("pipe_send", src, dst, stacked.shape, stacked.dtype.name),
            per_shard,
            1,
        )
        out = fn(stacked)
        if self.trace is not None:
            extras: Dict[str, Any] = {
                "epoch": self.epoch,
                "src": int(src),
                "dst": int(dst),
                "kind": kind,
            }
            if mb is not None:
                extras["mb"] = int(mb)
            if tick is not None:
                extras["tick"] = int(tick)
            self.trace.record(
                "pipe_send", "ici_hop", int(stacked.nbytes) // w, **extras
            )
        return out

    def pipe_recv(
        self,
        stacked: jnp.ndarray,
        *,
        src: int,
        dst: int,
        **kwargs: Any,
    ) -> jnp.ndarray:
        """Destination-side spelling of the stage hop.  In the
        single-controller engine one dispatch is both halves of a
        send/recv pair, so this forwards to :meth:`pipe_send` — calling
        either records exactly one trace event for the hop."""
        return self.pipe_send(stacked, src=src, dst=dst, **kwargs)

    def _ring_plan(
        self,
        stacked: jnp.ndarray,
        chunk_bytes: Optional[int],
        rs: bool,
        ag: bool,
        wire_dtype: str = "off",
        block_size: Optional[int] = None,
    ):
        """The executed ring schedule for a stacked call: the synthesized
        ``Strategy.chunk_bytes`` is the default granularity, an explicit
        argument overrides it, and the ``ADAPCC_RING_CHUNK_BYTES`` sweep env
        (resolved inside the planner) overrides both.  The plan decides the
        VMEM vs HBM-streaming path (and the fused wire geometry when a
        codec is on) and is recorded into the dispatch trace — the chunk
        size and wire dtype a ring collective ran at are an artifact, not
        a guess."""
        from adapcc_tpu.comm.pallas_ring import plan_ring_schedule

        per_rank = int(np.prod(stacked.shape[1:]))
        # allreduce / reduce-scatter shards carry the full payload per rank;
        # a pure all-gather's shard is one chunk of a world × chunk payload
        nelems = per_rank if rs else per_rank * self.world_size
        return plan_ring_schedule(
            nelems,
            stacked.dtype,
            self.world_size,
            chunk_bytes if chunk_bytes is not None else self.strategy.chunk_bytes,
            rs=rs,
            ag=ag,
            wire_dtype=wire_dtype,
            block_size=block_size,
        )

    @staticmethod
    def _ring_extras(plan) -> Dict[str, Any]:
        """Trace payload for a Pallas-ring dispatch — ONE definition shared
        by allreduce/RS/AG so the three primitives' artifacts cannot
        drift.  ``wire_dtype`` is the EXECUTED codec (from the plan), never
        a hard-coded constant; ``wire_bytes`` is what the per-rank payload
        actually costs on the fabric under it."""
        extras = {
            "chunk_bytes": plan.chunk_bytes,
            "stage_bytes": plan.stage_bytes,
            "n_tiles": plan.n_tiles,
            "wire_dtype": plan.wire_dtype,
        }
        if plan.wire_dtype == "off":
            extras["wire_bytes"] = plan.payload_bytes
        else:
            from adapcc_tpu.sim.cost_model import wire_bytes_per_element

            extras["wire_bytes"] = int(
                (plan.payload_bytes / 4.0)
                * wire_bytes_per_element(plan.wire_dtype, plan.block_size or 1)
            )
            extras["block_size"] = plan.block_size
            extras["scale_slot_bytes"] = plan.scale_slot_bytes
            extras["fused"] = True
        return extras

    def _record_ring(self, primitive: str, plan, stacked: jnp.ndarray) -> None:
        if self.trace is not None:
            suffix = "" if plan.wire_dtype == "off" else f"+{plan.wire_dtype}"
            self.trace.record(
                primitive,
                f"pallas_ring[{plan.path}{suffix}]",
                int(stacked.nbytes),
                **self._ring_extras(plan),
            )

    def _resolved_wire_dtype(self, wire_dtype: Optional[str]) -> str:
        """The wire codec a ring dispatch runs: ADAPCC_WIRE_DTYPE override >
        explicit argument > the strategy's synthesized ``wire_dtype`` — the
        same precedence ladder as the ring chunk size."""
        from adapcc_tpu.quant import resolve_wire_dtype

        return resolve_wire_dtype(
            wire_dtype if wire_dtype is not None else self.strategy.wire_dtype
        )

    def _wire_ring_allreduce(
        self, stacked: jnp.ndarray, wire_dtype: str, block_size: int
    ) -> Tuple[jnp.ndarray, Tuple, Dict[str, Any]]:
        """Ring allreduce over codec-compressed chunks (the EQuARX shape):
        reduce-scatter dequant-accumulate-requants at every hop, all-gather
        ships each reduced chunk's encoded blocks once.  ppermute-based —
        any backend, no Pallas requirement.  Returns ``(result, cache_key,
        trace_extras)`` so :meth:`ring_allreduce` can fold tuner timing and
        provenance into one trace record."""
        from adapcc_tpu.quant import get_codec, wire_ring_allreduce_shard
        from adapcc_tpu.sim.cost_model import wire_bytes_per_element

        codec = get_codec(wire_dtype)  # fail before tracing, not inside
        world = self.world_size

        def per_shard(x):  # x: [1, *payload]
            return wire_ring_allreduce_shard(
                x[0], world, self.axis_name,
                wire_dtype=codec.name, block_size=block_size,
            )[None]

        key = (
            "quant_ring_allreduce", stacked.shape, stacked.dtype.name,
            codec.name, block_size,
        )
        per_rank = int(np.prod(stacked.shape[1:]))
        extras = {
            "wire_dtype": codec.name,
            "block_size": block_size,
            "wire_bytes": int(
                per_rank * wire_bytes_per_element(codec.name, block_size)
            ),
        }
        return self._shard_mapped(key, per_shard, 1)(stacked), key, extras

    def ring_allreduce(
        self,
        stacked: jnp.ndarray,
        interpret: Optional[bool] = None,
        chunk_bytes: Optional[int] = None,
        wire_dtype: Optional[str] = None,
        quant_block_size: Optional[int] = None,
        algo: Optional[str] = None,
    ) -> jnp.ndarray:
        """Pallas ICI ring allreduce (hand-tuned data plane; see
        :mod:`adapcc_tpu.comm.pallas_ring`).  ``interpret=None`` auto-selects
        the interpreter off-TPU so the same call works on the virtual pod.
        ``chunk_bytes=None`` uses the strategy's synthesized granularity.

        ``wire_dtype=None`` adopts the strategy's synthesized codec
        (``ADAPCC_WIRE_DTYPE`` overrides both): a non-"off" codec reroutes
        to the quantized ppermute ring (:meth:`_wire_ring_allreduce`) —
        compressed chunks on the wire, fp32 accumulation at every hop.

        With a tuner attached (:mod:`adapcc_tpu.tuner`), ``ADAPCC_TUNER=
        choose`` lets the measured policy fill the knobs the caller left
        open — precedence **env > explicit arg > tuner > strategy** — and
        ``record``/``choose`` time every dispatch (``block_until_ready``
        walltime, compile warmup discarded) into the tuning database.  The
        dispatch trace carries the decision (``tuner={chosen, source,
        applied}``) next to the executed values, so precedence is visible
        in the artifact."""
        from adapcc_tpu.comm.pallas_ring import ring_allreduce_shard

        if self.two_level:
            raise ValueError(
                "ring_allreduce needs a flat ranks mesh (a single ICI ring); "
                "two-level worlds use the strategy allreduce"
            )
        self._check_world_dim(stacked, "ring_allreduce")
        # the single source of the key vocabulary: candidates(), live
        # recording, and trace replay must all spell one cell identically
        from adapcc_tpu.comm.latency import resolve_coll_algo
        from adapcc_tpu.tuner.policy import ALGO_OF_PATH, ALGO_PATHS, NO_CHUNK, QUANT_PATH

        # algorithm selector (docs/LATENCY.md): env > arg > tuner cell >
        # sim-crossover (under "auto"); unset everywhere keeps the ring —
        # the legacy contract of this entry point
        algo_req = resolve_coll_algo(algo)
        wire_arg = wire_dtype  # the caller's pin, before tuner adoption
        if algo_req == "ir":
            # the IR pin owns every allreduce entry point: rerouting here
            # (not silently running the ring under the pinned label) is
            # the same honesty rule as the rd/tree pins.  Ring-plane
            # knobs have no IR meaning — the program carries its own
            # chunking and codec — so explicit ones conflict loudly.
            if wire_arg is not None or chunk_bytes is not None:
                raise ValueError(
                    "algo='ir' executes a ScheduleProgram whose chunking "
                    "and wire codec are program properties; drop the "
                    "chunk_bytes/wire_dtype arguments or the ir pin"
                )
            per_rank_bytes = (
                int(np.prod(stacked.shape[1:])) * stacked.dtype.itemsize
            )
            return self._ir_allreduce(stacked, ReduceOp.SUM, per_rank_bytes, None)
        if algo_req in ("rd", "tree"):
            # double-pin conflict BEFORE the tuner consult: under both
            # pins the candidate grid is legitimately empty (neither the
            # ring planes nor the algo cells may be offered), and choose()
            # would die with a misleading "no candidate cells" — the
            # purpose-built diagnostic must fire first
            self._check_algo_wire_conflict(algo_req, wire_arg)
        per_rank_bytes = int(np.prod(stacked.shape[1:])) * stacked.dtype.itemsize
        tuner = self.tuner
        tplan = None
        tuner_chose_quant = False
        tuner_chose_algo: Optional[str] = None
        algos_narrow = self._ALGO_NARROW.get(algo_req)
        if algos_narrow is None and self._wire_pinned_non_off(wire_arg):
            # a caller-pinned codec rides the ring planes only: narrow the
            # algorithm axis so the explorer never offers a cell the
            # conflict guard would refuse on execution (the wire-pin
            # collapse, engine side; the env pin is collapsed inside
            # candidates() already — this covers the explicit argument)
            algos_narrow = ("ring",)
        if tuner is not None and tuner.choosing:
            tplan = tuner.choose(
                "allreduce", per_rank_bytes, stacked.dtype.name,
                algos=algos_narrow,
            )
            if algo_req in (None, "auto") and tplan.key.path in ALGO_PATHS:
                tuner_chose_algo = ALGO_OF_PATH[tplan.key.path]
            # the tuner only fills knobs the caller left open; the env
            # overrides (resolved inside resolve_chunk_bytes /
            # resolve_wire_dtype) still win over everything
            if wire_dtype is None and tplan.key.path not in ALGO_PATHS:
                wire_dtype = tplan.wire_dtype
                # a codec cell names its PATH too: the unfused quant-ring
                # cell must actually run unfused, or the fused-vs-unfused
                # A/B can never measure its second arm
                tuner_chose_quant = (
                    tplan.wire_dtype != "off" and tplan.key.path == QUANT_PATH
                )
            if chunk_bytes is None and tplan.chunk_bytes is not None:
                chunk_bytes = tplan.chunk_bytes
        executed_algo: Optional[str] = None
        if algo_req in ("rd", "tree"):
            executed_algo = algo_req  # pinned: loud reject if unsupported
        elif tuner_chose_algo is not None:
            executed_algo = tuner_chose_algo
        elif algo_req == "auto" and tplan is None:
            # the sim crossover is the LAST rung of the ladder: a choosing
            # tuner's committed cell — ring-plane included — outranks it
            # (tplan carries the decision above; overriding a committed
            # ring cell here would discard its adopted chunk/codec knobs
            # and starve the cells the tuner is trying to measure)
            executed_algo = self._auto_algo(per_rank_bytes, wire_arg)
        timing = tuner is not None and tuner.recording
        t0 = time.perf_counter()
        if executed_algo is not None:
            self._check_algo_wire_conflict(executed_algo, wire_arg)
            out, cache_key, _ = self._latency_allreduce(stacked, executed_algo)
            impl = executed_algo
            executed_path, executed_chunk = executed_algo, NO_CHUNK
            extras = {"algo": executed_algo}
            wd = "off"
        elif (wd := self._resolved_wire_dtype(wire_dtype)) != "off":
            from adapcc_tpu.comm.pallas_ring import (
                fused_ring_dispatch_reason,
                note_quant_reroute,
                resolve_fused_wire,
            )
            from adapcc_tpu.quant import DEFAULT_BLOCK_SIZE

            block = quant_block_size or DEFAULT_BLOCK_SIZE
            reroute = fused_ring_dispatch_reason(stacked.dtype, wd, block)
            # ADAPCC_FUSED_WIRE=on outranks the tuner's path cell: "on"
            # means NOTHING runs unfused here, tuner exploration included
            chosen_reroute = (
                reroute is None
                and tuner_chose_quant
                and resolve_fused_wire() != "on"
            )
            if chosen_reroute:
                reroute = "tuner chose the unfused quant-ring cell"
            if reroute is None:
                # the fused path: codec inside the staged Pallas kernels —
                # compressed tiles on the wire, fp32 accumulation in VMEM
                if interpret is None:
                    interpret = jax.devices()[0].platform != "tpu"
                world = self.world_size
                plan = self._ring_plan(
                    stacked, chunk_bytes, rs=True, ag=True,
                    wire_dtype=wd, block_size=block,
                )

                def per_shard(x):  # x: [1, *payload]
                    return ring_allreduce_shard(
                        x[0], world, self.axis_name, interpret=interpret,
                        chunk_bytes=plan.chunk_bytes,
                        wire_dtype=wd, block_size=block,
                    )[None]

                cache_key = (
                    "ring_allreduce", stacked.shape, stacked.dtype.name,
                    bool(interpret), plan.path, plan.stage_bytes, wd, block,
                )
                out = self._shard_mapped(cache_key, per_shard, 1)(stacked)
                impl = f"pallas_ring[{plan.path}+{wd}]"
                executed_path, executed_chunk = plan.path, plan.chunk_bytes
                extras = self._ring_extras(plan)
            else:
                # the staged kernel was abandoned for this dispatch — say so
                # once, loudly, and record the executed impl honestly (a
                # tuner-chosen unfused cell is a deliberate A/B arm, not an
                # abandonment — no note for it)
                if not chosen_reroute:
                    note_quant_reroute(wd, reroute)
                out, cache_key, extras = self._wire_ring_allreduce(
                    stacked, wd, block
                )
                extras["reroute_reason"] = reroute
                impl = f"quant_ring[{wd}]"
                executed_path, executed_chunk = QUANT_PATH, NO_CHUNK
        else:
            if interpret is None:
                interpret = jax.devices()[0].platform != "tpu"
            world = self.world_size
            plan = self._ring_plan(stacked, chunk_bytes, rs=True, ag=True)

            def per_shard(x):  # x: [1, *payload]
                return ring_allreduce_shard(
                    x[0], world, self.axis_name, interpret=interpret,
                    chunk_bytes=plan.chunk_bytes,
                )[None]

            cache_key = (
                "ring_allreduce", stacked.shape, stacked.dtype.name,
                bool(interpret), plan.path, plan.stage_bytes,
            )
            out = self._shard_mapped(cache_key, per_shard, 1)(stacked)
            impl = f"pallas_ring[{plan.path}]"
            executed_path, executed_chunk = plan.path, plan.chunk_bytes
            extras = self._ring_extras(plan)
        # the executed ALGORITHM rides the trace like wire_dtype: every
        # ring-family branch above is "ring", the latency branch stamped
        # its own name
        extras.setdefault("algo", "ring")
        if timing:
            # measurement semantics: the sample is the full dispatch-to-
            # completion walltime.  The block serializes the host loop by
            # design — that is what "record" mode buys its database with
            jax.block_until_ready(out)
            duration = time.perf_counter() - t0
            extras["duration_s"] = duration
            tuner.observe_dispatch(
                tuner.key_for(
                    "allreduce", per_rank_bytes, executed_path,
                    # a vmem dispatch is ONE cell regardless of budget (the
                    # knob is inert there); keying by the resolved budget
                    # would split its samples away from the candidate grid
                    NO_CHUNK if executed_path == "vmem" else executed_chunk,
                    wd,
                ),
                cache_key,
                duration,
            )
        if tplan is not None:
            applied = (
                wd == tplan.wire_dtype
                and executed_path == tplan.key.path
                and (
                    tplan.chunk_bytes is None
                    or executed_chunk == tplan.chunk_bytes
                )
            )
            extras["tuner"] = tplan.trace_extra(applied=applied)
        if self.trace is not None:
            self.trace.record("allreduce", impl, int(stacked.nbytes), **extras)
        return out

    def _ring_wire_args(
        self, stacked: jnp.ndarray, wire_dtype: Optional[str],
        quant_block_size: Optional[int], primitive: str,
    ) -> Tuple[str, Optional[int]]:
        """Resolve the wire codec for a ring RS/AG dispatch and validate it
        against the fused kernels — the ONLY data plane those primitives
        have for a codec, so an unsupported combination rejects loudly
        instead of silently running fp32 under a codec label."""
        wd = self._resolved_wire_dtype(wire_dtype)
        if wd == "off":
            return wd, None
        from adapcc_tpu.comm.pallas_ring import fused_ring_dispatch_reason
        from adapcc_tpu.quant import DEFAULT_BLOCK_SIZE

        block = quant_block_size or DEFAULT_BLOCK_SIZE
        reason = fused_ring_dispatch_reason(stacked.dtype, wd, block)
        if reason is not None:
            raise ValueError(
                f"{primitive} has no unfused wire data plane "
                f"(quant/ring.py is allreduce-only): wire_dtype={wd!r} "
                f"cannot run here — {reason}.  Pin wire_dtype='off' (or "
                "ADAPCC_WIRE_DTYPE=off) to run the fp32 kernels."
            )
        return wd, block

    def ring_reduce_scatter(
        self,
        stacked: jnp.ndarray,
        interpret: Optional[bool] = None,
        chunk_bytes: Optional[int] = None,
        wire_dtype: Optional[str] = None,
        quant_block_size: Optional[int] = None,
    ) -> jnp.ndarray:
        """Pallas ICI ring reduce-scatter (the RS half of the hand-tuned ring,
        :func:`adapcc_tpu.comm.pallas_ring.ring_reduce_scatter_shard`).

        Input ``[world, n]`` → output ``[world, chunk]`` with row ``r`` = the
        fully reduced chunk ``r`` of the flattened, tile-padded input
        (``chunk = tile_round(ceil(n / world))``).  The kernel leaves chunk
        ``(r+1) % world`` on rank ``r``; one static roll restores chunk order
        in the stacked single-controller view so this matches
        :meth:`reduce_scatter`'s row semantics on tile-aligned payloads.

        ``wire_dtype`` (default: the strategy's synthesized codec, under
        the usual env > arg > strategy precedence) runs the fused codec
        kernels: hops ship encoded tiles, accumulation stays fp32.  There
        is no unfused RS codec plane — where the fused path can't run, the
        dispatch rejects loudly rather than silently running fp32.
        """
        from adapcc_tpu.comm.pallas_ring import ring_reduce_scatter_shard

        if self.two_level:
            raise ValueError(
                "ring_reduce_scatter needs a flat ranks mesh (a single ICI "
                "ring); two-level worlds use the strategy primitives"
            )
        self._check_world_dim(stacked, "ring_reduce_scatter")
        wd, block = self._ring_wire_args(
            stacked, wire_dtype, quant_block_size, "ring_reduce_scatter"
        )
        if interpret is None:
            interpret = jax.devices()[0].platform != "tpu"
        world = self.world_size
        plan = self._ring_plan(
            stacked, chunk_bytes, rs=True, ag=False,
            wire_dtype=wd, block_size=block,
        )

        def per_shard(x):  # x: [1, *payload]
            out = ring_reduce_scatter_shard(
                x[0], world, self.axis_name, interpret=interpret,
                chunk_bytes=plan.chunk_bytes,
                wire_dtype=wd, block_size=block,
            )
            # relabel to chunk order INSIDE the compiled program: the kernel
            # leaves rank r holding chunk (r+1) % world; one [chunk]-sized
            # ppermute hop lands chunk r on rank r (an eager host-side roll
            # would dispatch a second, uncached cross-device permute per call)
            out = lax.ppermute(
                out, self.axis_name, [(i, (i + 1) % world) for i in range(world)]
            )
            return out[None]

        key = (
            "ring_rs", stacked.shape, stacked.dtype.name, bool(interpret),
            plan.path, plan.stage_bytes, wd, block,
        )
        self._record_ring("reduce_scatter", plan, stacked)
        return self._shard_mapped(key, per_shard, 1)(stacked)

    def ring_all_gather(
        self,
        stacked: jnp.ndarray,
        interpret: Optional[bool] = None,
        chunk_bytes: Optional[int] = None,
        wire_dtype: Optional[str] = None,
        quant_block_size: Optional[int] = None,
    ) -> jnp.ndarray:
        """Pallas ICI ring all-gather (the AG half of the hand-tuned ring).

        Input ``[world, chunk]`` (row ``r`` = rank ``r``'s tile-aligned
        payload) → output ``[world, world, chunk]`` — row ``r`` is the full
        gathered stack as seen by rank ``r``, matching :meth:`all_gather`.

        ``wire_dtype`` runs the fused codec kernels: each rank's chunk is
        encoded ONCE and the encoded bits are forwarded verbatim, so every
        rank holds identical post-codec values.  No unfused AG codec plane
        exists — unsupported combinations reject loudly.
        """
        from adapcc_tpu.comm.pallas_ring import ring_all_gather_shard

        if self.two_level:
            raise ValueError(
                "ring_all_gather needs a flat ranks mesh (a single ICI "
                "ring); two-level worlds use the strategy primitives"
            )
        self._check_world_dim(stacked, "ring_all_gather")
        wd, block = self._ring_wire_args(
            stacked, wire_dtype, quant_block_size, "ring_all_gather"
        )
        if interpret is None:
            interpret = jax.devices()[0].platform != "tpu"
        world = self.world_size
        plan = self._ring_plan(
            stacked, chunk_bytes, rs=False, ag=True,
            wire_dtype=wd, block_size=block,
        )

        def per_shard(x):  # x: [1, chunk]
            return ring_all_gather_shard(
                x[0], world, self.axis_name, interpret=interpret,
                chunk_bytes=plan.chunk_bytes,
                wire_dtype=wd, block_size=block,
            )[None]

        key = (
            "ring_ag", stacked.shape, stacked.dtype.name, bool(interpret),
            plan.path, plan.stage_bytes, wd, block,
        )
        self._record_ring("all_gather", plan, stacked)
        return self._shard_mapped(key, per_shard, 1)(stacked)

    def reduce_scatter(
        self,
        stacked: jnp.ndarray,
        *,
        active_gpus: Optional[Sequence[int]] = None,
        op: ReduceOp = ReduceOp.SUM,
        epoch: Optional[int] = None,
        algo: Optional[str] = None,
    ) -> jnp.ndarray:
        """Reduce-scatter with subset semantics (reference stub: REDUCESCATTER).

        ``active_gpus``/``op`` are keyword-only: a positional
        ``reduce_scatter(t, ReduceOp.AVG)`` predates the active_gpus
        parameter and must fail loudly rather than bind the enum to the
        mask (ADVICE r5).

        Row ``r`` of the result is the reduction of everyone's ``r``-th
        world-slice: input ``[world, n]`` → output ``[world, n // world]``.
        With ``active_gpus``, inactive ranks contribute the reduction
        identity but still receive their chunk (the relay contract);
        ``ReduceOp.AVG`` averages over the *active* count.  Two-level worlds
        scatter hierarchically (ICI first, so DCN carries only ``1/ici`` of
        the buffer).

        ``algo="rd"`` (or an ``ADAPCC_COLL_ALGO`` pin) runs the
        recursive-halving reduce-scatter instead — ``log2(p)`` rounds for
        latency-bound payloads (docs/LATENCY.md §5) — behind the shared
        support funnel (power-of-two flat worlds only, loud reject
        otherwise); the executed algorithm rides the trace like
        ``wire_dtype``.
        """
        self._check_epoch(epoch)
        self._check_world_dim(stacked, "reduce_scatter")
        if op is ReduceOp.MAX:
            raise ValueError(
                "reduce_scatter supports SUM/AVG (psum_scatter has no max "
                "variant); use reduce + a local slice for MAX"
            )
        n = int(np.prod(stacked.shape[1:]))
        if n % self.world_size:
            raise ValueError(
                f"reduce_scatter payload ({n} elems) must divide the world "
                f"({self.world_size})"
            )
        mask = self._active_to_mask(active_gpus)
        masked = active_gpus is not None

        if self._latency_variant("reduce_scatter", algo) == "rd":
            from adapcc_tpu.comm import latency as lat

            world = self.world_size
            axis = self.axis_name

            def per_shard(x, m):  # x: [1, n]
                out = lat.rd_reduce_scatter_shard(
                    x.reshape(-1), m if masked else None, world, axis, op=op
                )
                return out[None, :]

            key = (
                "reducescatter_rd", stacked.shape, stacked.dtype.name, op,
                masked,
            )
            self._record(
                "reduce_scatter", "rd", stacked,
                cache_hit=key in self._cache, algo="rd",
            )
            return self._shard_mapped(key, per_shard, 2)(stacked, mask)

        def _contrib(v, m):
            if masked:
                v = jnp.where(m[self._my_flat_rank()], v, jnp.zeros_like(v))
            return v

        def _norm(out, m):
            if op is ReduceOp.AVG:
                denom = (
                    jnp.maximum(jnp.sum(m.astype(out.dtype)), 1)
                    if masked else self.world_size
                )
                out = out / denom
            return out

        if self.two_level:
            from adapcc_tpu.comm.two_level import reduce_scatter_two_level_shard

            def per_shard(x, m):  # x: [1, n]
                v = _contrib(x.reshape(-1), m)
                out = reduce_scatter_two_level_shard(
                    v, self.num_slices, self.ici_size
                )
                return _norm(out, m)[None, :]

            key = ("reducescatter2l", stacked.shape, stacked.dtype.name, op, masked)
            self._record(
                "reduce_scatter", "two_level", stacked,
                cache_hit=key in self._cache, algo="ring",
            )
            return self._shard_mapped(key, per_shard, 2)(stacked, mask)

        def per_shard(x, m):  # x: [1, n]
            v = _contrib(x.reshape(-1), m)
            out = lax.psum_scatter(v, self.axis_name, scatter_dimension=0, tiled=True)
            return _norm(out, m)[None, :]

        key = ("reducescatter", stacked.shape, stacked.dtype.name, op, masked)
        self._record(
            "reduce_scatter", "xla", stacked,
            cache_hit=key in self._cache, algo="ring",
        )
        return self._shard_mapped(key, per_shard, 2)(stacked, mask)
