"""Shared utilities: observability (metrics, traces, profiling helpers)."""

from adapcc_tpu.utils.observability import (
    AverageMeter,
    CollectiveTrace,
    MetricsRegistry,
    ProgressMeter,
    parse_track_log,
    parse_training_log,
    profiler_trace,
)

__all__ = [
    "AverageMeter",
    "CollectiveTrace",
    "MetricsRegistry",
    "ProgressMeter",
    "parse_track_log",
    "parse_training_log",
    "profiler_trace",
]
