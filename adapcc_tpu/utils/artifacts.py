"""One env-var → JSON-artifact funnel for every injectable schedule.

Two injection artifacts share the same lifecycle — ``ADAPCC_FAULT_PLAN``
(:mod:`adapcc_tpu.elastic.faults`) and ``ADAPCC_CONGESTION_PROFILE``
(:mod:`adapcc_tpu.sim.congestion`) — and the same failure policy:

- env unset → ``None`` (no injection; the healthy default),
- env set but the file is missing → :class:`FileNotFoundError`,
- env set but the file is not that artifact's JSON → :class:`ValueError`
  naming the env var and the parse failure,
- env set but the artifact was authored for another world →
  :class:`ValueError` with the artifact's hint of what silently injecting
  it would corrupt.

A set-but-broken value must never silently run un-injected (the
ADAPCC_MERGE_ROUNDS policy): the whole point of an injection artifact is
the drill it drives, and a typo'd path that "ran fine" is the drill not
happening.  This module is the ONE spelling of that funnel so the two
artifacts (and any future one) can never drift apart.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Mapping, Optional, TypeVar

T = TypeVar("T")


def load_env_json_artifact(
    env_var: str,
    from_dict: Callable[[Mapping], T],
    kind: str,
    world: Optional[int] = None,
    env: Optional[Mapping[str, str]] = None,
    mismatch_hint: str = "injecting it as-is would corrupt the drill",
) -> Optional[T]:
    """The shared env→artifact funnel (module doc).

    ``from_dict`` parses the decoded JSON object into the artifact type;
    the returned object must expose a ``world`` attribute, validated
    against the runtime ``world`` when one is given.  ``kind`` names the
    artifact in every diagnostic ("fault-plan", "congestion-profile", …).
    Semantic validation errors raised by ``from_dict`` itself (an unknown
    event kind, a factor < 1) propagate unchanged — they already carry
    the loud, specific message.
    """
    env = env if env is not None else os.environ
    path = env.get(env_var, "").strip()
    if not path:
        return None
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"{env_var}={path!r}: no such {kind} artifact"
        )
    expected = (
        f" (expected world={world})" if world is not None else ""
    )
    try:
        with open(path) as f:
            obj = json.load(f)
        artifact = from_dict(obj)
    except json.JSONDecodeError as e:
        raise ValueError(
            f"{env_var}={path!r} is not a {kind} JSON artifact: invalid "
            f"JSON — {e}{expected}"
        ) from e
    except KeyError as e:
        # name the offending field, not just the exception repr: the
        # author of a hand-edited artifact needs to know WHICH key the
        # schema wants (the generic message was the bug this fixes)
        raise ValueError(
            f"{env_var}={path!r} is not a {kind} JSON artifact: missing "
            f"required field {e.args[0]!r}{expected}"
        ) from e
    except TypeError as e:
        raise ValueError(
            f"{env_var}={path!r} is not a {kind} JSON artifact: "
            f"malformed field — {e}{expected}"
        ) from e
    if world is not None and artifact.world != world:
        raise ValueError(
            f"{env_var}={path!r} was authored for world={artifact.world} "
            f"but this run has world={world}; re-author the {kind} — "
            f"{mismatch_hint}"
        )
    return artifact
