"""Observability: structured metrics, collective traces, profiler hooks.

The reference's observability is printf-based throughout (SURVEY.md §5.5):
relay decisions and per-element progress printed from the native layer
(control.cu:79-81, allreduce.cu:541-542), chunk-arrival debug dumps in
log/track.txt, AverageMeter/ProgressMeter training meters
(accuracy_benchmark.py:470-539), and ad-hoc log-scraping post-processors
(process_log.py, process_gns.py).  This module provides the structured
versions: the same meters, a metrics registry with JSON export, a collective
trace that records engine dispatches (the track.txt analog), a
``jax.profiler`` context for Perfetto traces, and parsers for both trace and
training logs.
"""

from __future__ import annotations

import contextlib
import json
import random
import re
import threading
import time
from collections import defaultdict, deque
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple


# --- training meters (accuracy_benchmark.py:470-539) --------------------------


class AverageMeter:
    """Tracks current value, running average, sum, count."""

    def __init__(self, name: str, fmt: str = ":f") -> None:
        self.name = name
        self.fmt = fmt
        self.reset()

    def reset(self) -> None:
        self.val = 0.0
        self.avg = 0.0
        self.sum = 0.0
        self.count = 0

    def update(self, val: float, n: int = 1) -> None:
        self.val = float(val)
        self.sum += float(val) * n
        self.count += n
        self.avg = self.sum / max(self.count, 1)

    def __str__(self) -> str:
        fmtstr = "{name} {val" + self.fmt + "} ({avg" + self.fmt + "})"
        return fmtstr.format(name=self.name, val=self.val, avg=self.avg)


class ProgressMeter:
    """``[ 10/500] loss 0.61 (0.73)  acc 81.2 (76.9)``-style progress lines."""

    def __init__(self, num_batches: int, meters: Sequence[AverageMeter], prefix: str = "") -> None:
        num_digits = len(str(num_batches // 1))
        self._batch_fmt = "[" + "{:" + str(num_digits) + "d}" + "/" + str(num_batches) + "]"
        self.meters = list(meters)
        self.prefix = prefix

    def display(self, batch: int) -> str:
        entries = [self.prefix + self._batch_fmt.format(batch)]
        entries += [str(m) for m in self.meters]
        line = "\t".join(entries)
        print(line)
        return line


# --- metrics registry ---------------------------------------------------------


def nearest_rank_percentile(sorted_samples: Sequence[float], q: float) -> float:
    """THE nearest-rank percentile convention, repo-wide: every consumer
    (the metrics reservoir, the dispatch-trace summary, the tuner
    database, the serving ledger, the queueing model) quotes percentiles
    through this one spelling, so a p99 from any artifact is comparable
    with a p99 from any other.  ``sorted_samples`` must be sorted
    ascending and non-empty."""
    rank = max(0, int(-(-q * len(sorted_samples) // 1)) - 1)
    return sorted_samples[min(rank, len(sorted_samples) - 1)]


class MetricsRegistry:
    """Named counters/gauges/timers with JSON export; thread-safe.

    Timings keep running count/total/max exactly, plus a **bounded
    reservoir** of samples (Vitter's algorithm R, deterministic seed) so
    :meth:`snapshot` can report p50/p99 with O(1) memory per timing — a
    long-running trainer recording per-step codec timings must not grow a
    list without bound, and tail latency (the p99 a straggler policy keys
    on) is invisible to count/mean/max alone.
    """

    #: samples retained per timing for the percentile estimate; above this
    #: count, reservoir sampling keeps a uniform subset
    RESERVOIR_SIZE = 512

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = defaultdict(float)
        self._gauges: Dict[str, float] = {}
        self._timings: Dict[str, Dict[str, Any]] = {}
        # deterministic reservoir replacement: two identical runs snapshot
        # identical percentiles (the sim-bench byte-stability policy)
        self._rng = random.Random(0x5EED)

    def incr(self, name: str, by: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += by

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    @contextlib.contextmanager
    def timer(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    def observe(self, name: str, seconds: float) -> None:
        """Record an externally measured duration into the ``name`` timing."""
        s = float(seconds)
        with self._lock:
            t = self._timings.get(name)
            if t is None:
                t = self._timings[name] = {
                    "count": 0, "total": 0.0, "max": s, "reservoir": [],
                }
            t["count"] += 1
            t["total"] += s
            t["max"] = max(t["max"], s)
            res = t["reservoir"]
            if len(res) < self.RESERVOIR_SIZE:
                res.append(s)
            else:
                j = self._rng.randrange(t["count"])
                if j < self.RESERVOIR_SIZE:
                    res[j] = s

    @staticmethod
    def _percentile(sorted_samples: List[float], q: float) -> float:
        """Nearest-rank percentile over the (sorted) reservoir."""
        return nearest_rank_percentile(sorted_samples, q)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            timings = {}
            for k, t in self._timings.items():
                if not t["count"]:
                    continue
                res = sorted(t["reservoir"])
                timings[k] = {
                    "count": t["count"],
                    "total_s": t["total"],
                    "mean_s": t["total"] / t["count"],
                    "max_s": t["max"],
                    "p50_s": self._percentile(res, 0.50),
                    "p99_s": self._percentile(res, 0.99),
                }
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "timings": timings,
            }

    def to_json(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)


# --- collective dispatch trace (log/track.txt analog) -------------------------


@dataclass
class TraceEvent:
    ts: float
    primitive: str
    impl: str
    nbytes: int
    step: Optional[int] = None
    extra: Dict[str, Any] = field(default_factory=dict)


class CollectiveTrace:
    """Records engine dispatches — which collective ran, with what payload,
    under which implementation.  The reference dumps per-chunk arrival lines
    into log/track.txt from inside the CUDA contexts; under XLA the chunk
    loop lives inside one compiled program, so the traceable boundary is the
    dispatch (one event per collective call), with Perfetto
    (:func:`profiler_trace`) covering intra-program detail.

    Capacity is a bounded **ring**: at capacity the *oldest* event is
    evicted for each new one, so a long run's trace ends with the steady
    state it was running in, not the startup noise it left hours ago.
    ``dropped`` counts evictions.
    """

    def __init__(self, capacity: int = 100_000) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._lock = threading.Lock()
        self._events: "deque[TraceEvent]" = deque(maxlen=capacity)
        self._dropped = 0

    def record(
        self,
        primitive: str,
        impl: str,
        nbytes: int,
        step: Optional[int] = None,
        **extra: Any,
    ) -> None:
        ev = TraceEvent(time.time(), primitive, impl, nbytes, step, extra)
        with self._lock:
            if len(self._events) >= self.capacity:
                self._dropped += 1  # the deque evicts its oldest on append
            self._events.append(ev)

    def events(self) -> List[TraceEvent]:
        with self._lock:
            return list(self._events)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def dump(self, path: str) -> None:
        """``track.txt``-style lines: ``ts primitive impl nbytes step {extra}``."""
        with open(path, "w") as f:
            for e in self.events():
                f.write(
                    f"{e.ts:.6f} {e.primitive} {e.impl} {e.nbytes} "
                    f"{-1 if e.step is None else e.step} {json.dumps(e.extra)}\n"
                )

    def impl_summary(self) -> Dict[str, Dict[str, Any]]:
        """Per-impl dispatch statistics over the buffered events: count,
        how many carried a measured ``duration_s``, and nearest-rank
        p50/p99 over those durations (None with nothing timed).  The
        aggregation a tail claim needs from a trace — e.g. decode-step
        allreduces under ``rd`` vs ``ring`` — without hand-scraping the
        event list."""
        grouped: Dict[str, List[float]] = {}
        counts: Dict[str, int] = {}
        for e in self.events():
            counts[e.impl] = counts.get(e.impl, 0) + 1
            if "duration_s" in e.extra:
                grouped.setdefault(e.impl, []).append(
                    float(e.extra["duration_s"])
                )
        out: Dict[str, Dict[str, Any]] = {}
        for impl, count in sorted(counts.items()):
            timed = sorted(grouped.get(impl, []))

            def pct(q: float) -> Optional[float]:
                if not timed:
                    return None
                return nearest_rank_percentile(timed, q)

            out[impl] = {
                "count": count,
                "timed": len(timed),
                "p50_s": pct(0.50),
                "p99_s": pct(0.99),
            }
        return out

    def dump_chrome_trace(self, path: str, impl_summary: bool = True) -> str:
        """``chrome://tracing`` / Perfetto JSON: one complete ("X") event
        per dispatch.  Events that carry a measured ``duration_s`` (the
        tuner's record mode) render with real extent; untimed dispatches
        render as instants.  Args carry the plan provenance — impl, bytes,
        wire dtype, and the tuner decision — so a timeline click answers
        "what ran here and who chose it".

        With ``impl_summary`` (default on), one extra slice per impl lands
        on a dedicated ``summary`` track (tid 1), spanning that impl's
        first→last dispatch, with :meth:`impl_summary`'s count/p50/p99 in
        its args — so per-impl tail behavior (the decode-step p99 a
        serving claim keys on) is one timeline click, no hand-aggregation.
        """
        trace_events = []
        for e in self.events():
            dur_us = float(e.extra.get("duration_s", 0.0)) * 1e6
            # timed dispatches are recorded AFTER completion, so e.ts is the
            # slice END; the slice must start duration earlier or every
            # event renders shifted right by its own extent
            args: Dict[str, Any] = {"impl": e.impl, "nbytes": e.nbytes}
            if e.step is not None:
                args["step"] = e.step
            for k in ("chunk_bytes", "stage_bytes", "wire_dtype", "wire_bytes"):
                if k in e.extra:
                    args[k] = e.extra[k]
            tuner = e.extra.get("tuner")
            if isinstance(tuner, dict):
                args["tuner_source"] = tuner.get("source")
                args["tuner_applied"] = tuner.get("applied")
                args["tuner_chosen"] = tuner.get("chosen")
            trace_events.append(
                {
                    "name": e.primitive,
                    "cat": "collective",
                    "ph": "X",
                    "ts": e.ts * 1e6 - dur_us,  # microseconds, start-of-slice
                    "dur": dur_us,
                    "pid": 0,
                    "tid": 0,
                    "args": args,
                }
            )
        if impl_summary:
            spans: Dict[str, List[float]] = {}
            for e in self.events():
                dur_us = float(e.extra.get("duration_s", 0.0)) * 1e6
                start = e.ts * 1e6 - dur_us
                span = spans.setdefault(e.impl, [start, e.ts * 1e6])
                span[0] = min(span[0], start)
                span[1] = max(span[1], e.ts * 1e6)
            for impl, stats in self.impl_summary().items():
                lo, hi = spans[impl]
                args = {
                    "count": stats["count"],
                    "timed": stats["timed"],
                }
                if stats["p50_s"] is not None:
                    args["p50_us"] = stats["p50_s"] * 1e6
                    args["p99_us"] = stats["p99_s"] * 1e6
                trace_events.append(
                    {
                        "name": f"summary:{impl}",
                        "cat": "summary",
                        "ph": "X",
                        "ts": lo,
                        "dur": max(hi - lo, 1.0),
                        "pid": 0,
                        "tid": 1,
                        "args": args,
                    }
                )
            trace_events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": 0,
                    "tid": 1,
                    "args": {"name": "per-impl summary (p50/p99)"},
                }
            )
        with open(path, "w") as f:
            json.dump(
                {"traceEvents": trace_events, "displayTimeUnit": "ms"},
                f,
                sort_keys=True,
            )
        return path


def parse_track_log(path: str) -> List[TraceEvent]:
    """Read a :meth:`CollectiveTrace.dump` file back into events."""
    out: List[TraceEvent] = []
    with open(path) as f:
        for line in f:
            parts = line.split(" ", 5)
            if len(parts) != 6:
                continue
            step = int(parts[4])
            out.append(
                TraceEvent(
                    ts=float(parts[0]),
                    primitive=parts[1],
                    impl=parts[2],
                    nbytes=int(parts[3]),
                    step=None if step < 0 else step,
                    extra=json.loads(parts[5]),
                )
            )
    return out


# --- jax profiler (Perfetto) --------------------------------------------------


@contextlib.contextmanager
def profiler_trace(log_dir: str) -> Iterator[None]:
    """Capture a ``jax.profiler`` trace (XLA ops, transfers, host activity)
    into ``log_dir`` — the TPU answer to the reference's nsys reports
    (nccl-perf/tree/report_allreduce.txt, SURVEY.md §5.1)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


# --- training-log post-processors (process_log.py/process_gns.py) -------------

_FLOAT = r"([-+]?\d+(?:\.\d+)?(?:[eE][-+]?\d+)?)"


def parse_training_log(
    path: str, key: str = "loss", pattern: Optional[str] = None
) -> List[Tuple[int, float]]:
    """Scrape ``(step, value)`` pairs out of a free-form training log.

    Default pattern matches ``... step <N> ... <key> <float>`` or
    ``<key>: <float>`` lines (the shapes the reference's process_log.py and
    process_gns.py scrape); pass ``pattern`` with two groups (step, value)
    for custom formats.
    """
    if pattern is None:
        pattern = rf"step\s*[:=]?\s*(\d+).*?{re.escape(key)}\s*[:=]?\s*{_FLOAT}"
    rx = re.compile(pattern)
    out: List[Tuple[int, float]] = []
    with open(path) as f:
        for line in f:
            m = rx.search(line)
            if m:
                out.append((int(m.group(1)), float(m.group(2))))
    return out
