"""Tensor-parallel GPT-2 decode forward with bit-exact batching.

The serving plane's correctness bar (ISSUE 14) is brutal: a batched,
head-sharded decode step must emit **the same bits** as the same request
run alone through :func:`adapcc_tpu.models.gpt2_generate.generate`.  Two
construction rules buy that:

1. **Slot independence.**  Every op outside attention's head split is
   row-wise in the slot axis (embeds, LayerNorms, Dense matmuls contract
   over features only, softmax is per-row), and the flax modules applied
   here are the *same module classes with the same params* the training
   model uses — not a reimplementation — so slot ``s`` of a batched step
   computes exactly what a ``B=1`` step computes.

2. **A re-association-free collective.**  The Megatron row-parallel psum
   would split the ``d_model`` contraction across ranks and re-associate
   the sum — goodbye bit parity.  Instead attention is **head-sharded**:
   rank ``r`` owns heads ``[r·Hl, (r+1)·Hl)`` and its slice of the KV
   cache, computes its heads' attention outputs (einsums are elementwise
   in the head axis, so each slice is bitwise the reference's), and
   scatters them into a zero-padded ``[world, S, 1, d_model]`` partial.
   The per-token collective is then ONE
   :meth:`~adapcc_tpu.comm.engine.CollectiveEngine.all_reduce` per layer
   whose sum touches each element exactly once (``x + 0 = x``) — the
   combine is a concatenation wearing an allreduce's clothes, so the
   size-adaptive algorithm selection (ring vs recursive doubling vs
   tree) and the dispatch tracing of the engine apply to decode-step
   traffic, and the math stays exact.  (The quantized wire is
   deliberately NOT part of this combine: fp32 exactness is what buys
   the bit parity — a lossy decode plane needs its own acceptance bar,
   ROADMAP item 3.)

The payload per dispatch is ``slots · d_model`` elements — hundreds of
bytes to a few KB, far below the ~100 KB crossover — so under
``algo="auto"`` a power-of-two world rides the recursive-doubling plane
(docs/LATENCY.md), which is the whole reason the serving plane exists as
a workload for the adaptive-CC stack.
"""

from __future__ import annotations

import functools
from typing import Any, List, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from adapcc_tpu.models.gpt2 import GPT2Config
from adapcc_tpu.models.gpt2_generate import sample_token


class TPDecodeModel:
    """Head-sharded one-token-per-step decode programs for one config.

    All entry points are jitted once per shape (slots is fixed by the
    batcher), layer params are *arguments* so one compiled program serves
    every layer, and nothing here retraces across the server's lifetime —
    slot reuse is free.
    """

    def __init__(
        self,
        cfg: GPT2Config,
        world: int,
        temperature: float = 1.0,
        top_k: int = 0,
        top_p: float = 0.0,
    ) -> None:
        if cfg.n_head % world:
            raise ValueError(
                f"n_head={cfg.n_head} must divide over the TP world {world}"
            )
        if cfg.d_model % cfg.n_head:
            raise ValueError(
                f"d_model={cfg.d_model} must divide over n_head={cfg.n_head}"
            )
        self.cfg = cfg
        self.world = int(world)
        self.heads_local = cfg.n_head // world
        self.head_dim = cfg.d_model // cfg.n_head
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.embed = jax.jit(self._embed)
        self.attn_partial = jax.jit(self._attn_partial)
        self.post_attn = jax.jit(self._post_attn)
        self.logits = jax.jit(self._logits)
        self.sample = jax.jit(self._sample)

    # -- per-step programs -----------------------------------------------------

    def _embed(
        self, params: Any, tok: jnp.ndarray, pos: jnp.ndarray
    ) -> jnp.ndarray:
        """``tok [S, 1] int32``, ``pos [S] int32`` → ``x [S, 1, C]``.

        Same modules + params as ``GPT2.__call__``: token and (per-slot)
        position embeddings added elementwise, dropout is identity at
        serving time (deterministic)."""
        cfg = self.cfg
        wte = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype)
        wpe = nn.Embed(cfg.max_seq, cfg.d_model, dtype=cfg.dtype)
        return (
            wte.apply({"params": params["wte"]}, tok)
            + wpe.apply({"params": params["wpe"]}, pos[:, None])
        )

    def _attn_partial(
        self,
        layer_params: Any,
        x: jnp.ndarray,
        k_pages: jnp.ndarray,
        v_pages: jnp.ndarray,
        pos: jnp.ndarray,
    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """One layer's pre-collective half: ln1 + qkv (replicated), the
        per-slot cache write at each slot's own position, and the
        head-sharded attention — returning the zero-padded stacked
        partial ``[world, S, 1, C]`` ready for ``engine.all_reduce``.

        Mirrors ``CausalSelfAttention.__call__``'s decode branch op for
        op (same einsum strings, the same fp32 cast + ``-1e30`` mask +
        softmax dtype round-trip), with the scalar ``cache_index``
        generalized to a per-slot position.
        """
        cfg = self.cfg
        world, Hl, hd = self.world, self.heads_local, self.head_dim
        S = x.shape[0]
        h = nn.LayerNorm(dtype=jnp.float32).apply(
            {"params": layer_params["ln1"]}, x
        )
        qkv = nn.Dense(3 * cfg.d_model, dtype=cfg.dtype).apply(
            {"params": layer_params["attn"]["qkv"]}, h
        )
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def shard_heads(t: jnp.ndarray) -> jnp.ndarray:
            # [S, 1, C] → [world, S, 1, Hl, hd]: rank w's contiguous heads
            return jnp.moveaxis(t.reshape(S, 1, world, Hl, hd), 2, 0)

        q_s = shard_heads(q)
        k_s = shard_heads(k).astype(cfg.dtype)
        v_s = shard_heads(v).astype(cfg.dtype)

        def write_slot(pages, new, p):
            # pages [max_seq, Hl, hd] ← new [1, Hl, hd] at row p
            return jax.lax.dynamic_update_slice(pages, new, (p, 0, 0))

        write = jax.vmap(  # over world (pos shared)
            jax.vmap(write_slot, in_axes=(0, 0, 0)), in_axes=(0, 0, None)
        )
        k_pages = write(k_pages, k_s, pos)
        v_pages = write(v_pages, v_s, pos)

        scale = 1.0 / np.sqrt(hd)
        att = (
            jnp.einsum("wsqhd,wskhd->wshqk", q_s, k_pages).astype(jnp.float32)
            * scale
        )
        valid = jnp.arange(cfg.max_seq) <= pos[:, None]  # [S, max_seq]
        att = jnp.where(valid[None, :, None, None, :], att, -1e30)
        att = jax.nn.softmax(att, axis=-1).astype(cfg.dtype)
        out = jnp.einsum("wshqk,wskhd->wsqhd", att, v_pages)
        out = out.reshape(world, S, 1, Hl * hd)
        # rank w's heads land at their concat offset; every other element
        # is an exact zero, so the allreduce's sum is a concatenation
        partial = jnp.zeros((world, S, 1, cfg.d_model), cfg.dtype)
        for w in range(world):
            partial = partial.at[
                w, :, :, w * Hl * hd : (w + 1) * Hl * hd
            ].set(out[w])
        return partial, k_pages, v_pages

    def _post_attn(
        self, layer_params: Any, x: jnp.ndarray, attn_full: jnp.ndarray
    ) -> jnp.ndarray:
        """One layer's post-collective half (replicated): the residual
        projection of the gathered head concat, then the MLP — the same
        module stack as ``Block.__call__`` after attention."""
        cfg = self.cfg
        proj = nn.Dense(cfg.d_model, dtype=cfg.dtype).apply(
            {"params": layer_params["attn"]["proj"]}, attn_full
        )
        x = x + proj
        h = nn.LayerNorm(dtype=jnp.float32).apply(
            {"params": layer_params["ln2"]}, x
        )
        h = nn.Dense(4 * cfg.d_model, dtype=cfg.dtype).apply(
            {"params": layer_params["fc"]}, h
        )
        h = nn.gelu(h)
        h = nn.Dense(cfg.d_model, dtype=cfg.dtype).apply(
            {"params": layer_params["proj"]}, h
        )
        return x + h

    def _logits(self, params: Any, x: jnp.ndarray) -> jnp.ndarray:
        """Final LayerNorm + the weight-tied LM head (``GPT2.__call__``'s
        closing lines, same cast order)."""
        cfg = self.cfg
        x = nn.LayerNorm(dtype=jnp.float32).apply(
            {"params": params["ln_f"]}, x
        )
        wte = params["wte"]["embedding"]
        logits = x.astype(cfg.dtype) @ wte.T.astype(cfg.dtype)
        return logits.astype(jnp.float32)

    def _sample(
        self, rng: jnp.ndarray, logits: jnp.ndarray
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Per-slot RNG split + sample: ``rng [S, 2]``, ``logits
        [S, 1, V]`` → ``(rng', sampled [S])``.

        Each slot advances **its own** key exactly the way the generate
        scan advances its single key (`split` then sample with the
        subkey), and samples over its own ``[1, V]`` row — under ``vmap``
        both the threefry bits and the filtered categorical are
        elementwise in the slot axis, so slot ``s`` draws the same token
        the one-at-a-time reference draws at the same position.
        """
        sample = functools.partial(
            sample_token,
            temperature=self.temperature,
            top_k=self.top_k,
            top_p=self.top_p,
        )

        def one(key: jnp.ndarray, lg: jnp.ndarray):
            key, sub = jax.random.split(key)
            return key, sample(sub, lg)[0]

        return jax.vmap(one)(rng, logits)

    # -- one full decode step --------------------------------------------------

    def decode_step(
        self,
        params: Any,
        engine,
        cache_layers: List[Tuple[jnp.ndarray, jnp.ndarray]],
        tok: jnp.ndarray,
        pos: jnp.ndarray,
        rng: jnp.ndarray,
        algo: Optional[str] = "auto",
    ) -> Tuple[jnp.ndarray, jnp.ndarray, List[Tuple[jnp.ndarray, jnp.ndarray]]]:
        """One token for every slot: embed → per layer (attention partial
        → ``engine.all_reduce`` → MLP) → logits → per-slot sample.

        Returns ``(rng', sampled [S], new_cache_layers)``.  The per-layer
        allreduce is the ONLY cross-rank exchange; its executed algorithm
        (and wire dtype, and tuner provenance) lands in the engine's
        dispatch trace like any training collective.
        """
        x = self.embed(params, tok, pos)
        new_layers: List[Tuple[jnp.ndarray, jnp.ndarray]] = []
        for layer in range(self.cfg.n_layer):
            lp = params[f"h{layer}"]
            k_pages, v_pages = cache_layers[layer]
            partial, k_pages, v_pages = self.attn_partial(
                lp, x, k_pages, v_pages, pos
            )
            new_layers.append((k_pages, v_pages))
            full = engine.all_reduce(partial, algo=algo)
            x = self.post_attn(lp, x, full[0])
        logits = self.logits(params, x)
        rng, sampled = self.sample(rng, logits)
        return rng, sampled, new_layers

    @property
    def collective_bytes(self) -> int:
        """Per-rank payload of one decode-step allreduce, for one slot —
        multiply by the batcher's slot count for the dispatch size the
        tuner/selector sees."""
        return self.cfg.d_model * jnp.dtype(self.cfg.dtype).itemsize
