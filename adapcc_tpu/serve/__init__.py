"""Latency-SLO inference serving plane (docs/SERVING.md).

Every subsystem so far optimizes training throughput; the north-star
traffic ("millions of users", ROADMAP) is *decode*: one token per step,
per-token collectives far below the ~100 KB ring ↔ recursive-doubling
crossover — exactly the regime the small-message plane
(:mod:`adapcc_tpu.comm.latency`) was built for.  This package serves a
tensor-parallel GPT-2 end to end through the adaptive-CC stack:

- :mod:`adapcc_tpu.serve.trace` — deterministic synthetic request
  traffic: seeded Poisson arrivals via ``jax.random``, replayable as a
  JSON artifact through the one env→artifact funnel
  (``ADAPCC_SERVE_TRACE``), so every latency claim is reproducible;
- :mod:`adapcc_tpu.serve.kv_cache` — a slot-paged fixed-shape KV cache
  laid out on the TP mesh (heads axis): admission claims a slot,
  evict-on-EOS frees it for the next request **without retracing** (all
  shapes static);
- :mod:`adapcc_tpu.serve.model` — the head-sharded decode forward whose
  per-token combine is ONE :meth:`CollectiveEngine.all_reduce` per layer,
  so size-adaptive algorithm selection and dispatch tracing apply to
  decode-step collectives — and whose token streams are **bit-identical**
  to :func:`adapcc_tpu.models.gpt2_generate.generate` (each rank
  contributes its head block into a zero-padded partial; the sum
  re-associates nothing — fp32 exactness is what buys the parity, which
  is why the quantized wire is NOT yet fused into the decode combine:
  a lossy plane needs its own acceptance bar, ROADMAP item 3);
- :mod:`adapcc_tpu.serve.scheduler` — the continuous batcher: per-request
  admission into fixed decode slots, prefill/decode interleave (a newly
  admitted request force-feeds prompt tokens while its neighbors decode),
  per-request RNG streams, p50/p99 sojourn through the
  :class:`~adapcc_tpu.utils.observability.MetricsRegistry` reservoir.

Offline pricing lives in :mod:`adapcc_tpu.sim.cost_model` (the queueing
extension: arrival rate × slots × per-token step time → the
latency/throughput frontier ``make serve-bench`` emits), and the
tail-aware tuner objective (``ADAPCC_TUNER_OBJECTIVE=p99``) lives in
:mod:`adapcc_tpu.tuner.policy`.
"""

from __future__ import annotations

import os
from typing import Optional

#: fixed decode-slot count of the continuous batcher (env > arg > default)
SERVE_SLOTS_ENV = "ADAPCC_SERVE_SLOTS"

DEFAULT_SERVE_SLOTS = 4

#: per-request sojourn SLO in milliseconds (env > arg > None = no SLO)
SERVE_SLO_ENV = "ADAPCC_SERVE_SLO_MS"


def resolve_serve_slots(explicit: Optional[int] = None) -> int:
    """Decode-slot count in force: ``ADAPCC_SERVE_SLOTS`` env > the
    caller's explicit value > :data:`DEFAULT_SERVE_SLOTS`.  Malformed or
    non-positive values raise — a typo'd slot count silently serving a
    different batch geometry would invalidate the latency numbers the
    run was meant to produce (the ADAPCC_MERGE_ROUNDS policy)."""
    env = os.environ.get(SERVE_SLOTS_ENV)
    value: object = env if env is not None and env.strip() else explicit
    if value is None:
        return DEFAULT_SERVE_SLOTS
    try:
        slots = int(str(value).strip())
    except ValueError as e:
        raise ValueError(
            f"{SERVE_SLOTS_ENV}={value!r}: expected a positive integer"
        ) from e
    if slots < 1:
        raise ValueError(
            f"{SERVE_SLOTS_ENV}={value!r}: slot count must be >= 1"
        )
    return slots


def resolve_serve_slo_ms(explicit: Optional[float] = None) -> Optional[float]:
    """Sojourn SLO in force: ``ADAPCC_SERVE_SLO_MS`` env > the caller's
    explicit value > None (no SLO tracked).  Malformed / non-positive
    values raise loudly (same policy as :func:`resolve_serve_slots`)."""
    env = os.environ.get(SERVE_SLO_ENV)
    value: object = env if env is not None and env.strip() else explicit
    if value is None:
        return None
    try:
        slo = float(str(value).strip())
    except ValueError as e:
        raise ValueError(
            f"{SERVE_SLO_ENV}={value!r}: expected a positive number of "
            "milliseconds"
        ) from e
    if slo <= 0:
        raise ValueError(
            f"{SERVE_SLO_ENV}={value!r}: the SLO must be > 0 ms"
        )
    return slo


from adapcc_tpu.serve.kv_cache import SlotKVCache  # noqa: E402
from adapcc_tpu.serve.model import TPDecodeModel  # noqa: E402
from adapcc_tpu.serve.scheduler import (  # noqa: E402
    GPT2Server,
    Request,
    RequestResult,
)
from adapcc_tpu.serve.trace import (  # noqa: E402
    SERVE_TRACE_ENV,
    ArrivalTrace,
    RequestSpec,
    load_serve_trace,
    synthesize_arrival_trace,
)
from adapcc_tpu.serve.disagg import (  # noqa: E402
    DISAGG_ENV,
    KV_KL_BOUND_ENV,
    KV_WIRE_DTYPE_ENV,
    ClusterRouter,
    measure_token_kl,
    resolve_disagg,
    resolve_kv_kl_bound,
    resolve_kv_wire_dtype,
)

__all__ = [
    "ArrivalTrace",
    "ClusterRouter",
    "DEFAULT_SERVE_SLOTS",
    "DISAGG_ENV",
    "GPT2Server",
    "KV_KL_BOUND_ENV",
    "KV_WIRE_DTYPE_ENV",
    "Request",
    "RequestResult",
    "RequestSpec",
    "SERVE_SLO_ENV",
    "SERVE_SLOTS_ENV",
    "SERVE_TRACE_ENV",
    "SlotKVCache",
    "TPDecodeModel",
    "load_serve_trace",
    "measure_token_kl",
    "resolve_disagg",
    "resolve_kv_kl_bound",
    "resolve_kv_wire_dtype",
    "resolve_serve_slo_ms",
    "resolve_serve_slots",
    "synthesize_arrival_trace",
]
