"""The disaggregated serving cluster: two pods, one router, traced KV streams.

Topology: a **prefill pod** and a **decode pod** of equal TP world (the
two-pod ``HierarchySketch`` layout — equal worlds keep the head-sharded
page layout ``[world, slots, max_seq, Hl, hd]`` migration-compatible
without a reshard).  Each pod owns its own mesh, its own
:class:`~adapcc_tpu.comm.engine.CollectiveEngine` (both recording into
ONE shared dispatch trace) and its own
:class:`~adapcc_tpu.serve.kv_cache.SlotKVCache`; one
:class:`~adapcc_tpu.serve.model.TPDecodeModel` serves both pods' compiled
step programs.

Request lifecycle (the bit-parity contract):

1. **admit → prefill**: FIFO admission into a free prefill slot, RNG
   reset to ``PRNGKey(seed)`` — exactly the colocated batcher's
   admission.  The lane force-feeds its prompt one token per step; the
   step that feeds position ``prompt_len − 1`` samples the **first
   generated token** (TTFT lands here, in the prefill pod).
2. **migrate**: the finished prefill's pages — only the filled prefix
   ``[:prompt_len]`` — ride :meth:`CollectiveEngine.kv_transfer` into a
   zeroed decode slot (one traced DCN stream per migration), together
   with the lane's RNG key.  No free decode slot → the lane **waits
   resident** in its prefill slot: frozen out of prefill compute, RNG
   untouched, never dropped.
3. **decode**: the decode pod streams the remaining tokens with the
   colocated step semantics (EOS latch included).

Why the streams are bit-identical to the colocated ``GPT2Server``: a
lane's tokens depend only on its prompt, its RNG **split count**, and
the (exact, re-association-free) layer math over its own pages — never
on the global clock or on its neighbors.  The router advances a lane's
RNG exactly once per step the lane actually computes (frozen lanes have
their keys restored after the fixed-shape pool step), migrates the key
with the pages, and the fp32 (``"off"``) wire moves pages bit-exactly —
so the k-th computed step of a request sees the same key and the same
pages wherever it runs.  The int8 wire deliberately breaks page
exactness; that is why it is gated behind the token-level KL probe
(:func:`measure_token_kl`) at construction time.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from adapcc_tpu.comm.engine import KV_TRANSFER_CHUNK_BYTES, CollectiveEngine
from adapcc_tpu.models.gpt2 import GPT2Config
from adapcc_tpu.serve import resolve_serve_slo_ms, resolve_serve_slots
from adapcc_tpu.serve.disagg import (
    KV_KL_BOUND_ENV,
    resolve_kv_kl_bound,
    resolve_kv_wire_dtype,
)
from adapcc_tpu.serve.kv_cache import SlotKVCache
from adapcc_tpu.serve.model import TPDecodeModel
from adapcc_tpu.serve.scheduler import Request, RequestResult
from adapcc_tpu.serve.trace import ArrivalTrace
from adapcc_tpu.strategy.ir import Strategy
from adapcc_tpu.utils.observability import (
    MetricsRegistry,
    nearest_rank_percentile,
)

#: pod ids stamped on every kv_transfer trace event (HierarchySketch order)
PREFILL_POD = 0
DECODE_POD = 1


@dataclass
class _ClusterLane:
    """One occupied slot's host state, in whichever pod currently owns it."""

    req: Request
    admitted_step: int
    tokens: np.ndarray = field(default=None)  # type: ignore[assignment]
    #: scan position: index of the token the NEXT step feeds
    pos: int = 0
    first_token_step: int = -1
    #: router step at which the lane entered the decode pod (−1 = not yet)
    migrated_step: int = -1
    wall_t0: float = 0.0

    @property
    def prompt_len(self) -> int:
        return len(self.req.prompt)


class _Pool:
    """One pod: a mesh, an engine, a slot cache, lanes, and RNG rows."""

    def __init__(
        self,
        name: str,
        pod_id: int,
        cfg: GPT2Config,
        mesh,
        slots: int,
        trace=None,
        engine: Optional[CollectiveEngine] = None,
    ) -> None:
        self.name = name
        self.pod_id = pod_id
        self.cfg = cfg
        self.mesh = mesh
        self.world = int(mesh.devices.size)
        self.slots = int(slots)
        if engine is None:
            engine = CollectiveEngine(
                mesh, Strategy.ring(self.world), trace=trace
            )
        self.engine = engine
        #: per-pod registry so the two pods' kv_cache.* ledgers stay split
        self.cache_metrics = MetricsRegistry()
        self.lanes: Dict[int, _ClusterLane] = {}
        self.reset()

    def reset(self) -> None:
        """(Re)build the pod's serving state from scratch — fresh pages,
        every slot free, RNG zeroed.  This is also the pod-death path:
        the cache-metrics registry survives, so eviction/reuse counters
        accumulate across a rebuild."""
        self.cache = SlotKVCache(
            self.cfg, self.world, self.slots, mesh=self.mesh,
            metrics=self.cache_metrics,
        )
        self.lanes = {}
        self.free: List[int] = list(range(self.slots))
        # committed to THIS pod's devices: the two pods' meshes are
        # disjoint device sets, and a stray default-device RNG array
        # would collide with the pod's committed pages inside the jitted
        # decode step
        self.rng = jax.device_put(
            jnp.zeros((self.slots, 2), jnp.uint32),
            NamedSharding(self.mesh, PartitionSpec()),
        )


def measure_token_kl(
    cfg: GPT2Config,
    params: Any,
    world: int,
    wire_dtype: str,
    prompt: Optional[List[int]] = None,
    block_size: Optional[int] = None,
) -> float:
    """Token-level KL (nats) a lossy KV wire would inflict on the first
    decode-pod step: prefill a deterministic probe prompt (exact fp32
    math, engine-free — the stacked partial's sum replaces the
    allreduce, which is the same concatenation), then compute the
    next-token distribution twice — over the exact pages and over
    ``codec.apply``'d pages (exactly what ``kv_transfer`` would move) —
    and return ``KL(p_exact ‖ p_codec)``.

    ``"off"`` returns exactly 0.0 (identity wire).  This is the
    acceptance probe the :class:`ClusterRouter` runs at construction:
    one measurement per (config, params, wire) — the EQuARX-style bar
    the colocated decode combine never needed because fp32 bought bit
    parity outright.
    """
    from adapcc_tpu.quant import get_codec
    from adapcc_tpu.quant.codec import DEFAULT_BLOCK_SIZE

    codec = get_codec(wire_dtype)
    if codec.name == "off":
        return 0.0
    block = int(block_size) if block_size is not None else DEFAULT_BLOCK_SIZE
    tp = TPDecodeModel(cfg, world)
    if prompt is None:
        plen = max(1, min(8, cfg.max_seq - 2))
        prompt = [1 + (i % (cfg.vocab_size - 1)) for i in range(plen)]
    plen = len(prompt)
    if plen + 1 >= cfg.max_seq:
        raise ValueError(
            f"KL probe prompt of {plen} tokens leaves no room for a "
            f"generated token under max_seq={cfg.max_seq}"
        )
    shape = (world, 1, cfg.max_seq, tp.heads_local, tp.head_dim)
    layers: List[Tuple[jnp.ndarray, jnp.ndarray]] = [
        (jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))
        for _ in range(cfg.n_layer)
    ]

    def step(cache_layers, tok: int, pos_i: int):
        x = tp.embed(
            params, jnp.asarray([[tok]], jnp.int32),
            jnp.asarray([pos_i], jnp.int32),
        )
        new_layers = []
        for layer in range(cfg.n_layer):
            lp = params[f"h{layer}"]
            k_pages, v_pages = cache_layers[layer]
            partial, k_pages, v_pages = tp.attn_partial(
                lp, x, k_pages, v_pages, jnp.asarray([pos_i], jnp.int32)
            )
            new_layers.append((k_pages, v_pages))
            # the allreduce's sum, without an engine: exact concatenation
            x = tp.post_attn(lp, x, partial.sum(axis=0))
        return new_layers, tp.logits(params, x)

    logits = None
    for i, tok in enumerate(prompt):
        layers, logits = step(layers, int(tok), i)
    first_token = int(jnp.argmax(logits[0, 0]))

    def distorted(cache_layers):
        out = []
        for k_pages, v_pages in cache_layers:
            kq = k_pages.at[:, :, :plen].set(
                codec.apply(k_pages[:, :, :plen], block).astype(k_pages.dtype)
            )
            vq = v_pages.at[:, :, :plen].set(
                codec.apply(v_pages[:, :, :plen], block).astype(v_pages.dtype)
            )
            out.append((kq, vq))
        return out

    _, exact = step(layers, first_token, plen)
    _, lossy = step(distorted(layers), first_token, plen)
    lp_exact = jax.nn.log_softmax(exact[0, 0].astype(jnp.float32))
    lp_lossy = jax.nn.log_softmax(lossy[0, 0].astype(jnp.float32))
    kl = jnp.sum(jnp.exp(lp_exact) * (lp_exact - lp_lossy))
    return max(float(kl), 0.0)


class ClusterRouter:
    """Routes requests through the two-pod disaggregated cluster.

    The public surface mirrors :class:`~adapcc_tpu.serve.scheduler.
    GPT2Server` (``submit`` / ``submit_trace`` / ``step`` / ``run`` /
    ``results`` / ``summary``) so the two serving planes are drop-in
    alternatives for the same arrival trace; ``summary`` additionally
    splits latency per pool and carries the KV-stream ledger.
    """

    def __init__(
        self,
        cfg: GPT2Config,
        params: Any,
        prefill_mesh,
        decode_mesh,
        prefill_slots: Optional[int] = None,
        decode_slots: Optional[int] = None,
        temperature: float = 1.0,
        top_k: int = 0,
        top_p: float = 0.0,
        eos_id: Optional[int] = None,
        algo: Optional[str] = "auto",
        trace=None,
        metrics: Optional[MetricsRegistry] = None,
        slo_ms: Optional[float] = None,
        kv_wire_dtype: Optional[str] = None,
        kv_kl_bound: Optional[float] = None,
        kv_block_size: Optional[int] = None,
        kv_chunk_bytes: int = KV_TRANSFER_CHUNK_BYTES,
    ) -> None:
        pw = int(prefill_mesh.devices.size)
        dw = int(decode_mesh.devices.size)
        if pw != dw:
            raise ValueError(
                f"prefill pod world={pw} != decode pod world={dw}: equal "
                "TP worlds are what keep the head-sharded KV page layout "
                "migration-compatible without a reshard"
            )
        self.cfg = cfg
        self.params = params
        self.pool_world = pw
        #: total chips across both pods — the budget the colocated
        #: baseline gets in an equal-chip-count comparison
        self.world = 2 * pw
        self.eos_id = eos_id
        self.algo = algo
        self.slo_ms = resolve_serve_slo_ms(slo_ms)
        self.trace = trace
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.kv_wire_dtype = resolve_kv_wire_dtype(kv_wire_dtype)
        self.kv_block_size = kv_block_size
        self.kv_chunk_bytes = int(kv_chunk_bytes)
        self.kv_kl: Optional[float] = None
        if self.kv_wire_dtype != "off":
            bound = resolve_kv_kl_bound(kv_kl_bound)
            self.kv_kl = measure_token_kl(
                cfg, params, pw, self.kv_wire_dtype,
                block_size=kv_block_size,
            )
            if self.kv_kl > bound:
                raise ValueError(
                    f"KV wire dtype {self.kv_wire_dtype!r} rejected: "
                    f"measured token-level KL {self.kv_kl:.3e} nats exceeds "
                    f"the acceptance bound {bound:.3e} ({KV_KL_BOUND_ENV}); "
                    "serve the bit-exact fp32 wire ('off') or raise the "
                    "bound deliberately"
                )
            self.kv_kl_bound = bound
        self.tp = TPDecodeModel(
            cfg, pw, temperature=temperature, top_k=top_k, top_p=top_p
        )
        self.prefill = _Pool(
            "prefill", PREFILL_POD, cfg, prefill_mesh,
            resolve_serve_slots(prefill_slots), trace=trace,
        )
        self.decode = _Pool(
            "decode", DECODE_POD, cfg, decode_mesh,
            resolve_serve_slots(decode_slots), trace=trace,
        )
        self.clock = 0
        self._pending: Deque[Request] = deque()
        #: prefill slots whose lane finished prefill and awaits a decode
        #: slot (FIFO by readiness; frozen out of prefill compute)
        self._ready: Deque[int] = deque()
        self._results: Dict[int, RequestResult] = {}
        self._arrival_wall: Dict[int, float] = {}
        #: req_id → router step the request entered the decode pod
        self._migrated: Dict[int, int] = {}
        self._kv_transfers = 0
        self._kv_payload_bytes = 0
        self._kv_wire_bytes = 0

    # -- admission -------------------------------------------------------------

    def submit(self, req: Request) -> None:
        """Same loud validations as the colocated server's ``submit`` —
        the two planes must reject exactly the same traffic."""
        if req.total > self.cfg.max_seq:
            raise ValueError(
                f"request {req.req_id}: {req.total} tokens > "
                f"max_seq={self.cfg.max_seq} cache slots"
            )
        if req.max_new_tokens < 1:
            raise ValueError(
                f"request {req.req_id}: max_new_tokens must be >= 1"
            )
        if not req.prompt:
            raise ValueError(f"request {req.req_id}: empty prompt")
        bad = [t for t in req.prompt if not 0 <= t < self.cfg.vocab_size]
        if bad:
            raise ValueError(
                f"request {req.req_id}: prompt token(s) {bad[:3]} outside "
                f"vocab_size={self.cfg.vocab_size}"
            )
        self._pending.append(req)

    def submit_trace(self, trace: ArrivalTrace) -> None:
        if trace.world != self.world:
            raise ValueError(
                f"arrival trace was authored for world={trace.world} but "
                f"this cluster runs world={self.world} "
                f"(2 pods x {self.pool_world})"
            )
        for spec in trace.requests:
            self.submit(Request.from_spec(spec))

    def _admit(self) -> None:
        pool = self.prefill
        while pool.free and self._pending and (
            self._pending[0].arrival_step <= self.clock
        ):
            req = self._pending.popleft()
            slot = pool.free.pop(0)
            lane = _ClusterLane(req=req, admitted_step=self.clock)
            lane.tokens = np.zeros((req.total,), np.int32)
            lane.tokens[: len(req.prompt)] = np.asarray(req.prompt, np.int32)
            lane.wall_t0 = time.perf_counter()
            pool.lanes[slot] = lane
            pool.cache.clear_slot(slot)
            pool.rng = pool.rng.at[slot].set(jax.random.PRNGKey(req.seed))
            self.metrics.incr("serve.admitted")

    # -- the cluster step ------------------------------------------------------

    def step(self) -> int:
        """One router tick: admit into prefill, advance both pods by one
        token (the cluster's two compiled steps run per tick — the wall
        cost of a tick is their max, which is what the sim twin prices),
        then migrate every finished prefill a decode slot can take.
        Returns the number of lanes that computed."""
        now = time.perf_counter()
        for req in self._pending:
            if req.arrival_step > self.clock:
                break  # arrival-sorted FIFO (the discipline _admit assumes)
            self._arrival_wall.setdefault(req.req_id, now)
        self._admit()
        frozen = set(self._ready)
        n = self._step_pool(self.prefill, frozen)
        n += self._step_pool(self.decode, set())
        self._migrate_ready()
        self.clock += 1
        return n

    def _step_pool(self, pool: _Pool, frozen: set) -> int:
        """Advance one pod's occupied, non-frozen lanes by one token.

        Frozen lanes (finished prefills awaiting a decode slot) stay
        resident but out of the computation: their RNG rows are restored
        after the fixed-shape step (the vmapped sampler splits every
        row), and their position is pointed at the first *unmigrated*
        row so the step's unconditional cache write for their slot can
        only touch a row the migration never copies.
        """
        active = sorted(s for s in pool.lanes if s not in frozen)
        if not active:
            return 0
        t0 = time.perf_counter()
        tok = np.zeros((pool.slots, 1), np.int32)
        pos = np.zeros((pool.slots,), np.int32)
        for s in active:
            lane = pool.lanes[s]
            tok[s, 0] = lane.tokens[lane.pos]
            pos[s] = lane.pos
        saved_rng = {}
        for s in frozen:
            if s in pool.lanes:
                pos[s] = pool.lanes[s].pos  # a row beyond the migrated prefix
                saved_rng[s] = pool.rng[s]
        pool.rng, sampled, new_layers = self.tp.decode_step(
            self.params,
            pool.engine,
            pool.cache.layers,
            jnp.asarray(tok),
            jnp.asarray(pos),
            pool.rng,
            algo=self.algo,
        )
        for layer, (k_pages, v_pages) in enumerate(new_layers):
            pool.cache.update(layer, k_pages, v_pages)
        for s, key in saved_rng.items():
            pool.rng = pool.rng.at[s].set(key)
        sampled_host = np.asarray(sampled)
        self.metrics.observe(f"serve.{pool.name}.step_s",
                             time.perf_counter() - t0)
        self.metrics.gauge(f"serve.{pool.name}.slots_busy", len(active))
        self.metrics.gauge("serve.queue_depth", len(self._pending))
        for s in active:
            self._advance(pool, s, int(sampled_host[s]))
        return len(active)

    def _advance(self, pool: _Pool, slot: int, sampled: int) -> None:
        """The colocated ``_advance_lane`` body, with one extra outcome:
        a prefill lane that just wrote its first generated token (and
        neither completed nor latched EOS) becomes *ready* and queues
        for migration instead of decoding in place."""
        lane = pool.lanes[slot]
        req = lane.req
        t = lane.pos
        prompt_len = lane.prompt_len
        if t + 1 >= prompt_len:
            lane.tokens[t + 1] = sampled
            if t + 1 == prompt_len:
                lane.first_token_step = self.clock + 1
        lane.pos = t + 1
        wrote_eos = (
            self.eos_id is not None
            and t + 1 >= prompt_len
            and int(lane.tokens[t + 1]) == self.eos_id
        )
        if wrote_eos and lane.pos < req.total - 1:
            lane.tokens[lane.pos + 1:] = self.eos_id
            self.metrics.incr("serve.evicted_eos")
            self._complete(pool, slot, eos_evicted=True)
            return
        if lane.pos == req.total - 1:
            # max_new_tokens == 1 completes inside the prefill pod: there
            # is nothing left to decode, so no migration is owed
            self._complete(pool, slot, eos_evicted=False)
            return
        if pool is self.prefill and lane.pos >= prompt_len:
            self._ready.append(slot)

    def _migrate_ready(self) -> None:
        """Move finished prefills into free decode slots, FIFO: pages
        (filled prefix only) through the traced ``kv_transfer`` stream,
        RNG key by copy.  Runs at end of step — a migrated lane decodes
        its next token on the next tick.  Lanes the decode pod cannot
        take yet stay queued; nothing is ever dropped."""
        while self._ready and self.decode.free:
            slot = self._ready.popleft()
            lane = self.prefill.lanes.pop(slot)
            p = lane.pos  # == prompt_len: rows [0, p) are the filled prefix
            pages = [
                (k[:, slot, :p], v[:, slot, :p])
                for k, v in self.prefill.cache.layers
            ]
            moved = self.prefill.engine.kv_transfer(
                pages,
                src_pod=PREFILL_POD,
                dst_pod=DECODE_POD,
                wire_dtype=self.kv_wire_dtype,
                block_size=self.kv_block_size,
                chunk_bytes=self.kv_chunk_bytes,
                dst_sharding=self.decode.cache.sharding,
            )
            dslot = self.decode.free.pop(0)
            self.decode.cache.clear_slot(dslot)
            self.decode.cache.layers = [
                (k.at[:, dslot, :p].set(mk), v.at[:, dslot, :p].set(mv))
                for (k, v), (mk, mv) in zip(self.decode.cache.layers, moved)
            ]
            # the RNG key migrates with the pages; hop through the host
            # so the prefill-committed key cannot drag the decode pod's
            # RNG array onto the wrong devices
            self.decode.rng = self.decode.rng.at[dslot].set(
                np.asarray(jax.device_get(self.prefill.rng[slot]))
            )
            self.prefill.cache.release_slot(slot, used_tokens=p, evicted=False)
            self.prefill.free.append(slot)
            self.prefill.free.sort()
            lane.migrated_step = self.clock + 1
            self._migrated[lane.req.req_id] = lane.migrated_step
            self.decode.lanes[dslot] = lane
            payload = sum(int(k.nbytes) + int(v.nbytes) for k, v in pages)
            self._kv_transfers += 1
            self._kv_payload_bytes += payload
            self._kv_wire_bytes += self._wire_bytes(pages)
            self.metrics.incr("serve.migrated")

    def _wire_bytes(self, pages) -> int:
        if self.kv_wire_dtype == "off":
            return sum(int(k.nbytes) + int(v.nbytes) for k, v in pages)
        from adapcc_tpu.quant.codec import DEFAULT_BLOCK_SIZE
        from adapcc_tpu.sim.cost_model import wire_bytes_per_element

        block = (
            int(self.kv_block_size)
            if self.kv_block_size is not None else DEFAULT_BLOCK_SIZE
        )
        per_elem = wire_bytes_per_element(self.kv_wire_dtype, block)
        return int(sum(
            (int(k.size) + int(v.size)) * per_elem for k, v in pages
        ))

    def _complete(self, pool: _Pool, slot: int, eos_evicted: bool) -> None:
        lane = pool.lanes.pop(slot)
        pool.free.append(slot)
        pool.free.sort()
        req = lane.req
        pool.cache.release_slot(
            slot, used_tokens=lane.pos + 1, evicted=eos_evicted
        )
        if slot in self._ready and pool is self.prefill:
            self._ready.remove(slot)  # defensive; a ready lane never computes
        wall = time.perf_counter() - self._arrival_wall.pop(
            req.req_id, lane.wall_t0
        )
        result = RequestResult(
            req_id=req.req_id,
            tokens=[int(x) for x in lane.tokens],
            prompt_len=len(req.prompt),
            arrival_step=req.arrival_step,
            admitted_step=lane.admitted_step,
            first_token_step=lane.first_token_step,
            completed_step=self.clock + 1,
            eos_evicted=eos_evicted,
            wall_s=wall,
        )
        self._results[req.req_id] = result
        self.metrics.incr("serve.completed")
        self.metrics.observe("serve.sojourn_steps", result.sojourn_steps)
        if result.first_token_step >= 0:
            self.metrics.observe("serve.ttft_steps", result.ttft_steps)
        self.metrics.observe("serve.sojourn_s", wall)

    # -- failure injection -----------------------------------------------------

    def kill_decode_pool(self) -> List[int]:
        """Decode-pod death, mid-stream: every in-flight decode lane's
        request re-enters the *front* of the prefill queue with its
        original arrival step (FIFO order among the victims preserved),
        and the pod is rebuilt from scratch.  Nothing is dropped; the
        re-prefill recomputes the same RNG stream from ``PRNGKey(seed)``,
        so the victims' token streams are unchanged — the pinned casualty
        is exactly those requests' TTFT (first_token_step is re-earned
        after the death)."""
        victims = [
            self.decode.lanes[s].req.req_id
            for s in sorted(self.decode.lanes)
        ]
        for s in sorted(self.decode.lanes, reverse=True):
            lane = self.decode.lanes[s]
            self._pending.appendleft(lane.req)
            self._migrated.pop(lane.req.req_id, None)
        self.decode.reset()
        self.metrics.incr("serve.decode_pod_deaths")
        self.metrics.incr("serve.re_prefilled", len(victims))
        return victims

    # -- fabric integration ----------------------------------------------------

    def kv_stream_fabric_job(self, fabric, name: str = "kv_stream",
                             priority: Optional[str] = "high"):
        """Register the router's cumulative KV-stream traffic with a
        :class:`~adapcc_tpu.adapt.fabric.SharedFabric`, so congestion
        triage prices serving migrations against training DCN traffic.
        Serving is latency-critical, hence priority ``"high"`` by
        default.  Uses wire bytes (what the DCN actually carries), with
        a 1-byte floor so a cold router still registers."""
        return fabric.add_job(
            name,
            priority=priority,
            nbytes=max(1, int(self._kv_wire_bytes)),
            degree=1,
        )

    # -- the drive loop --------------------------------------------------------

    def run(self, max_steps: Optional[int] = None) -> List[RequestResult]:
        """Step until every submitted request completes (or ``max_steps``
        elapses — loudly, same policy as the colocated server)."""
        budget = max_steps if max_steps is not None else 1_000_000
        steps = 0
        while self._pending or self.prefill.lanes or self.decode.lanes:
            if steps >= budget:
                raise RuntimeError(
                    f"serve run exceeded max_steps={budget} with "
                    f"{len(self._pending)} queued / "
                    f"{len(self.prefill.lanes)} prefill / "
                    f"{len(self.decode.lanes)} decode in-flight requests"
                )
            self.step()
            steps += 1
        return self.results()

    def results(self) -> List[RequestResult]:
        return [self._results[k] for k in sorted(self._results)]

    def summary(self) -> dict:
        """The disaggregated serving ledger: the colocated summary's
        step-clock percentiles, split per pool (TTFT is prefill-pod
        latency by construction; decode residency runs migration →
        completion), plus the KV-stream ledger and per-pod cache stats."""
        res = self.results()
        out: dict = {
            "requests": len(res),
            "world": self.world,
            "steps": self.clock,
            "disagg": True,
            "pools": {
                "prefill": {
                    "world": self.prefill.world,
                    "slots": self.prefill.slots,
                },
                "decode": {
                    "world": self.decode.world,
                    "slots": self.decode.slots,
                },
            },
            "kv_cache": self.decode.cache.layout(),
            "kv_cache_stats": {
                "prefill": self.prefill.cache.stats(),
                "decode": self.decode.cache.stats(),
            },
            "kv_stream": {
                "wire_dtype": self.kv_wire_dtype,
                "transfers": self._kv_transfers,
                "payload_bytes": self._kv_payload_bytes,
                "wire_bytes": self._kv_wire_bytes,
                "chunk_bytes": self.kv_chunk_bytes,
            },
        }
        if self.kv_kl is not None:
            out["kv_stream"]["token_kl"] = self.kv_kl
            out["kv_stream"]["kl_bound"] = self.kv_kl_bound
        if res:
            def pct(xs, q):
                return int(nearest_rank_percentile(xs, q))

            sojourns = sorted(r.sojourn_steps for r in res)
            ttfts = sorted(
                r.ttft_steps for r in res if r.first_token_step >= 0
            )
            out["p50_sojourn_steps"] = pct(sojourns, 0.50)
            out["p99_sojourn_steps"] = pct(sojourns, 0.99)
            if ttfts:
                # arrival → first token: queue wait + prefill-pod service
                out["p50_ttft_steps"] = pct(ttfts, 0.50)
                out["p99_ttft_steps"] = pct(ttfts, 0.99)
                out["pools"]["prefill"]["p50_sojourn_steps"] = pct(ttfts, 0.50)
                out["pools"]["prefill"]["p99_sojourn_steps"] = pct(ttfts, 0.99)
            decode_res = sorted(
                r.completed_step - self._migrated[r.req_id]
                for r in res if r.req_id in self._migrated
            )
            if decode_res:
                # migration → completion: decode-pod residency
                out["pools"]["decode"]["p50_sojourn_steps"] = pct(
                    decode_res, 0.50
                )
                out["pools"]["decode"]["p99_sojourn_steps"] = pct(
                    decode_res, 0.99
                )
        snap = self.metrics.snapshot()
        for pool in ("prefill", "decode"):
            step_t = snap["timings"].get(f"serve.{pool}.step_s")
            if step_t:
                out["pools"][pool]["p50_step_ms"] = step_t["p50_s"] * 1e3
                out["pools"][pool]["p99_step_ms"] = step_t["p99_s"] * 1e3
        if self.slo_ms is not None and res:
            within = sum(1 for r in res if r.wall_s * 1e3 <= self.slo_ms)
            out["slo_ms"] = self.slo_ms
            out["slo_attainment"] = within / len(res)
        return out
