"""Disaggregated prefill/decode serving over DCN (docs/SERVING.md §7).

The colocated batcher (:mod:`adapcc_tpu.serve.scheduler`) interleaves
prefill and decode in one pool, so a long prompt stalls every decode
lane behind it for its whole prefill.  This package splits the cluster
into **two pods** (the PR 11 ``HierarchySketch`` layout): a *prefill
pool* that turns prompts into KV pages and first tokens, and a *decode
pool* that streams the remaining tokens — with the finished prefill's
pages migrated between them by
:meth:`~adapcc_tpu.comm.engine.CollectiveEngine.kv_transfer`, a chunked
point-to-point DCN stream that is dispatch-traced (executed bytes, wire
dtype, chunk count, duration) like every other collective.

- :class:`ClusterRouter` (:mod:`adapcc_tpu.serve.disagg.cluster`) —
  admission → prefill → migrate → decode, with TTFT/sojourn accounting
  split per pool and the same ``ADAPCC_SERVE_SLO_MS`` attainment clock
  the colocated server keeps;
- the fp32 (``"off"``) KV wire is the default and **bit-exact**: the
  disaggregated token streams are pinned identical to the colocated
  ``GPT2Server`` and to the one-at-a-time ``generate`` loop;
- the int8 wire (``ADAPCC_KV_WIRE_DTYPE=int8``, the EQuARX direction)
  is gated behind a measured **token-level KL acceptance bound**
  (``ADAPCC_KV_KL_BOUND``): at router construction a probe prefill
  compares the next-token distribution over exact vs codec'd pages and
  admits the lossy wire only under the bound — above it, construction
  fails loudly rather than silently serving distorted streams.

Offline, :func:`adapcc_tpu.sim.cost_model.simulate_disagg_queue` prices
the same tandem queue (prefill service → DCN transfer on calibrated α-β
→ decode service), and ``make disagg-bench`` emits the
colocated-vs-disaggregated frontier.
"""

from __future__ import annotations

import os
from typing import Optional

#: opt into the disaggregated serving path (truthy/falsy; env > arg > off)
DISAGG_ENV = "ADAPCC_DISAGG"

#: wire dtype of the KV-page migration stream (a `quant/` codec name)
KV_WIRE_DTYPE_ENV = "ADAPCC_KV_WIRE_DTYPE"

#: token-level KL acceptance bound for a lossy KV wire (nats)
KV_KL_BOUND_ENV = "ADAPCC_KV_KL_BOUND"

#: default acceptance bar: a lossy KV wire may distort the next-token
#: distribution by at most this much (nats) before it is rejected
DEFAULT_KV_KL_BOUND = 0.02

_TRUTHY = frozenset({"1", "true", "on", "yes"})
_FALSY = frozenset({"0", "false", "off", "no"})


def resolve_disagg(explicit: Optional[bool] = None) -> bool:
    """Whether the disaggregated serving path is in force:
    ``ADAPCC_DISAGG`` env > the caller's explicit value > off.  Anything
    other than 1/true/on/yes vs 0/false/off/no raises — a typo'd toggle
    silently serving the wrong topology would invalidate the latency
    numbers the run was meant to produce (the loud env-knob policy)."""
    env = os.environ.get(DISAGG_ENV)
    if env is not None and env.strip():
        token = env.strip().lower()
        if token in _TRUTHY:
            return True
        if token in _FALSY:
            return False
        raise ValueError(
            f"{DISAGG_ENV}={env!r}: expected one of "
            f"{sorted(_TRUTHY)} / {sorted(_FALSY)}"
        )
    return bool(explicit) if explicit is not None else False


def resolve_kv_wire_dtype(explicit: Optional[str] = None) -> str:
    """KV-migration wire dtype in force: ``ADAPCC_KV_WIRE_DTYPE`` env >
    the caller's explicit value > ``"off"`` (fp32, bit-exact).  The name
    is validated against the codec registry immediately, so an unknown
    codec fails at resolution time, not mid-migration."""
    from adapcc_tpu.quant import get_codec

    env = os.environ.get(KV_WIRE_DTYPE_ENV)
    value = env.strip() if env is not None and env.strip() else explicit
    name = value if value is not None else "off"
    get_codec(name)  # loud on an unknown codec name
    return name


def resolve_kv_kl_bound(explicit: Optional[float] = None) -> float:
    """Token-level KL acceptance bound (nats) in force:
    ``ADAPCC_KV_KL_BOUND`` env > the caller's explicit value >
    :data:`DEFAULT_KV_KL_BOUND`.  Malformed / non-positive values raise
    (a zero bound would reject even the bit-exact wire on float fuzz)."""
    env = os.environ.get(KV_KL_BOUND_ENV)
    value: object = env if env is not None and env.strip() else explicit
    if value is None:
        return DEFAULT_KV_KL_BOUND
    try:
        bound = float(str(value).strip())
    except ValueError as e:
        raise ValueError(
            f"{KV_KL_BOUND_ENV}={value!r}: expected a positive KL bound "
            "in nats"
        ) from e
    if bound <= 0:
        raise ValueError(
            f"{KV_KL_BOUND_ENV}={value!r}: the KL bound must be > 0"
        )
    return bound


from adapcc_tpu.serve.disagg.cluster import (  # noqa: E402
    ClusterRouter,
    measure_token_kl,
)

__all__ = [
    "ClusterRouter",
    "DEFAULT_KV_KL_BOUND",
    "DISAGG_ENV",
    "KV_KL_BOUND_ENV",
    "KV_WIRE_DTYPE_ENV",
    "measure_token_kl",
    "resolve_disagg",
    "resolve_kv_kl_bound",
    "resolve_kv_wire_dtype",
]
