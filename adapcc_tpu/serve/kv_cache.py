"""Slot-paged KV cache, sharded over the TP mesh by attention heads.

The serving-time memory bottleneck is the KV cache, not the weights: one
decode slot holds ``2 · n_layer · max_seq · d_model`` cache entries, and a
fixed-slot continuous batcher keeps ``slots`` of them alive at once.  This
module lays that state out as fixed-shape arrays

    ``[world, slots, max_seq, n_head/world, head_dim]``  (per layer, K and V)

with the leading axis sharded over the TP mesh — each rank materializes
only its own heads' pages, which is exactly the Megatron head split the
decode forward (:mod:`adapcc_tpu.serve.model`) computes attention over.

Slot lifecycle is the whole point:

- **admission** claims a free slot and zeroes its pages (one sliced
  ``.set(0)`` per layer — a freed slot's stale keys are masked out of
  attention anyway, but zeroed pages keep the cache state bit-identical
  to a fresh ``generate`` cache, which the parity drill pins);
- **evict-on-EOS** frees the slot immediately — the remaining tokens of a
  finished stream are all EOS by the generate loop's own latch, so no
  model step is owed — and the next admission **reuses the slot without
  retracing**: every shape is static, so the compiled step programs are
  cache hits for the entire life of the server.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from adapcc_tpu.models.gpt2 import GPT2Config


class SlotKVCache:
    """Per-layer (K, V) slot pages on the TP mesh.

    The arrays are owned functionally: the decode step consumes and
    returns them (`layers` is replaced wholesale each step), so the cache
    object is a layout + lifecycle manager, not a mutable device buffer.
    """

    def __init__(
        self,
        cfg: GPT2Config,
        world: int,
        slots: int,
        mesh=None,
        axis_name: str = "ranks",
        metrics=None,
    ) -> None:
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if world < 1:
            raise ValueError(f"world must be >= 1, got {world}")
        if cfg.n_head % world:
            raise ValueError(
                f"n_head={cfg.n_head} must divide over the TP world "
                f"{world} (head-sharded cache pages)"
            )
        self.cfg = cfg
        self.world = int(world)
        self.slots = int(slots)
        self.heads_local = cfg.n_head // world
        self.head_dim = cfg.d_model // cfg.n_head
        shape = (
            self.world, self.slots, cfg.max_seq, self.heads_local,
            self.head_dim,
        )
        self._sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            self._sharding = NamedSharding(mesh, P(axis_name))
        #: per layer: (k_pages, v_pages), each [world, slots, max_seq, Hl, hd]
        self.layers: List[Tuple[jnp.ndarray, jnp.ndarray]] = [
            (self._place(jnp.zeros(shape, cfg.dtype)),
             self._place(jnp.zeros(shape, cfg.dtype)))
            for _ in range(cfg.n_layer)
        ]
        #: optional MetricsRegistry: the slot-lifecycle ledger — occupancy /
        #: eviction / reuse gauges plus the page-bytes reservoir the serving
        #: summary surfaces (docs/SERVING.md §7)
        self.metrics = metrics
        self._occupied: set = set()
        self._ever_used: set = set()

    @property
    def sharding(self):
        """The pages' placement (None off-mesh) — the destination a
        cross-pod ``engine.kv_transfer`` re-places migrated pages under."""
        return self._sharding

    def _note_occupancy(self) -> None:
        if self.metrics is not None:
            self.metrics.gauge("kv_cache.occupied_slots", len(self._occupied))
            self.metrics.gauge(
                "kv_cache.occupancy", len(self._occupied) / self.slots
            )

    def _place(self, arr: jnp.ndarray) -> jnp.ndarray:
        if self._sharding is not None:
            return jax.device_put(arr, self._sharding)
        return arr

    # -- lifecycle -------------------------------------------------------------

    def clear_slot(self, slot: int) -> None:
        """Zero one slot's pages across all layers (admission hygiene:
        the fresh-cache state ``generate`` starts from)."""
        if not 0 <= slot < self.slots:
            raise ValueError(f"slot {slot} outside [0, {self.slots})")
        self.layers = [
            (k.at[:, slot].set(0), v.at[:, slot].set(0))
            for k, v in self.layers
        ]
        if self.metrics is not None:
            self.metrics.incr("kv_cache.admissions")
            if slot in self._ever_used:
                # the retrace-free reuse the fixed-shape layout exists for
                self.metrics.incr("kv_cache.slot_reuse")
        self._ever_used.add(slot)
        self._occupied.add(slot)
        self._note_occupancy()

    def release_slot(
        self, slot: int, used_tokens: Optional[int] = None,
        evicted: bool = False,
    ) -> None:
        """Free one slot's pages at completion: the eviction counter and
        the page-bytes histogram sample (``used_tokens`` × the per-token KV
        footprint — the bytes the request actually wrote, not the fixed
        ``max_seq`` reservation)."""
        if not 0 <= slot < self.slots:
            raise ValueError(f"slot {slot} outside [0, {self.slots})")
        self._occupied.discard(slot)
        self._note_occupancy()
        if self.metrics is None:
            return
        self.metrics.incr("kv_cache.released")
        if evicted:
            self.metrics.incr("kv_cache.evictions")
        if used_tokens is not None:
            self.metrics.observe(
                "kv_cache.page_bytes", used_tokens * self.bytes_per_token
            )

    @property
    def bytes_per_token(self) -> int:
        """KV footprint of ONE cached token across all layers and ranks
        (K and V) — the unit the page-bytes histogram and the KV-transfer
        pricing both count in."""
        itemsize = jnp.dtype(self.cfg.dtype).itemsize
        return 2 * self.cfg.n_layer * self.cfg.d_model * itemsize

    def stats(self) -> dict:
        """The slot-lifecycle ledger (counters/gauges plus the page-bytes
        reservoir percentiles) read back from the registry — the summary
        row ``serve_gpt2`` surfaces; zeros when no registry is attached."""
        out = {
            "occupied_slots": len(self._occupied),
            "occupancy": len(self._occupied) / self.slots,
            "admissions": 0, "slot_reuse": 0, "evictions": 0, "released": 0,
        }
        if self.metrics is None:
            return out
        snap = self.metrics.snapshot()
        for key in ("admissions", "slot_reuse", "evictions", "released"):
            out[key] = int(snap["counters"].get(f"kv_cache.{key}", 0))
        pages = snap["timings"].get("kv_cache.page_bytes")
        if pages:
            out["page_bytes"] = {
                "count": pages["count"],
                "p50": pages["p50_s"],
                "p99": pages["p99_s"],
                "max": pages["max_s"],
            }
        return out

    def update(
        self, layer: int, k_pages: jnp.ndarray, v_pages: jnp.ndarray
    ) -> None:
        """Adopt one layer's post-step pages (the decode step's output)."""
        self.layers[layer] = (k_pages, v_pages)

    # -- layout ----------------------------------------------------------------

    @property
    def nbytes_per_rank(self) -> int:
        """One rank's cache footprint — the number that scales as
        ``1/world`` and makes head sharding worth it."""
        k, _ = self.layers[0]
        per_layer = 2 * k.nbytes // self.world
        return per_layer * self.cfg.n_layer

    def layout(self) -> dict:
        """Artifact row describing the paging geometry."""
        return {
            "layers": self.cfg.n_layer,
            "world": self.world,
            "slots": self.slots,
            "max_seq": self.cfg.max_seq,
            "heads_local": self.heads_local,
            "head_dim": self.head_dim,
            "dtype": jnp.dtype(self.cfg.dtype).name,
            "nbytes_per_rank": self.nbytes_per_rank,
        }

    def __repr__(self) -> str:
        return (
            f"SlotKVCache(layers={self.cfg.n_layer}, world={self.world}, "
            f"slots={self.slots}, max_seq={self.cfg.max_seq}, "
            f"heads_local={self.heads_local}, head_dim={self.head_dim})"
        )
