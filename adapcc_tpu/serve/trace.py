"""Deterministic synthetic request traffic for the serving plane.

The Big Send-off's lesson (PAPERS.md) is that collectives must be priced
against tail latency *under real traffic*, not medians under a benchmark
loop — so every serving claim in this repo is driven by an explicit
arrival trace: seeded Poisson inter-arrival gaps (``jax.random``, so two
runs of the same seed produce the same trace on any backend), per-request
prompts/lengths/RNG seeds, all replayable from a JSON artifact through
the one env→artifact funnel (:mod:`adapcc_tpu.utils.artifacts`,
``ADAPCC_SERVE_TRACE``) exactly like fault plans and congestion profiles.

Arrival times are measured in **decode steps** (the scheduler's virtual
clock), not wall seconds: the continuous batcher admits at step
boundaries, so step-granular arrivals are what it can actually observe,
and they keep the trace — and every latency percentile derived from it —
byte-reproducible.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import List, Mapping, Optional, Sequence, Tuple

from adapcc_tpu.utils.artifacts import load_env_json_artifact

#: env var naming a JSON arrival-trace artifact to replay
SERVE_TRACE_ENV = "ADAPCC_SERVE_TRACE"


@dataclass(frozen=True)
class RequestSpec:
    """One request of an arrival trace."""

    req_id: int
    #: decode step (virtual clock) at which the request becomes admissible
    arrival_step: int
    prompt: Tuple[int, ...]
    max_new_tokens: int
    #: per-request RNG seed: the serving plane samples this request with
    #: ``jax.random.PRNGKey(seed)``, the same key a one-at-a-time
    #: ``gpt2_generate.generate`` reference run would use — the handle the
    #: bit-identity drill holds on to
    seed: int

    def __post_init__(self) -> None:
        if self.arrival_step < 0:
            raise ValueError(
                f"request {self.req_id}: arrival_step must be >= 0, got "
                f"{self.arrival_step}"
            )
        if not self.prompt:
            raise ValueError(f"request {self.req_id}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"request {self.req_id}: max_new_tokens must be >= 1, got "
                f"{self.max_new_tokens} (a request that decodes nothing is "
                "not serving traffic)"
            )

    @property
    def total_tokens(self) -> int:
        return len(self.prompt) + self.max_new_tokens

    @property
    def service_steps(self) -> int:
        """Engine steps the request occupies a decode slot: the scan
        length of the equivalent ``generate`` call (``total − 1``)."""
        return self.total_tokens - 1

    def to_dict(self) -> dict:
        return {
            "req_id": self.req_id,
            "arrival_step": self.arrival_step,
            "prompt": list(self.prompt),
            "max_new_tokens": self.max_new_tokens,
            "seed": self.seed,
        }

    @classmethod
    def from_dict(cls, obj: Mapping) -> "RequestSpec":
        return cls(
            req_id=int(obj["req_id"]),
            arrival_step=int(obj["arrival_step"]),
            prompt=tuple(int(t) for t in obj["prompt"]),
            max_new_tokens=int(obj["max_new_tokens"]),
            seed=int(obj["seed"]),
        )


@dataclass
class ArrivalTrace:
    """A replayable arrival schedule (the serving analog of a FaultPlan).

    ``world`` is the TP world the trace was authored for — validated by
    the env funnel so a trace authored for one mesh can never silently
    drive another (prompt vocab / head split assumptions ride on it).
    """

    world: int
    seed: int
    requests: List[RequestSpec] = field(default_factory=list)
    label: str = ""

    def __post_init__(self) -> None:
        if self.world < 1:
            raise ValueError(f"world must be >= 1, got {self.world}")
        steps = [r.arrival_step for r in self.requests]
        if steps != sorted(steps):
            raise ValueError(
                "arrival trace requests must be sorted by arrival_step "
                "(the batcher admits FIFO)"
            )

    def to_dict(self) -> dict:
        return {
            "world": self.world,
            "seed": self.seed,
            "label": self.label,
            "requests": [r.to_dict() for r in self.requests],
        }

    @classmethod
    def from_dict(cls, obj: Mapping) -> "ArrivalTrace":
        return cls(
            world=int(obj["world"]),
            seed=int(obj["seed"]),
            label=str(obj.get("label", "")),
            requests=[RequestSpec.from_dict(r) for r in obj["requests"]],
        )

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, sort_keys=True, indent=1)
        return path

    def __len__(self) -> int:
        return len(self.requests)


def synthesize_arrival_trace(
    world: int,
    num_requests: int,
    rate: float,
    seed: int = 0,
    prompt_len: Tuple[int, int] = (4, 12),
    max_new_tokens: Tuple[int, int] = (8, 16),
    vocab_size: int = 256,
    eos_id: Optional[int] = None,
    label: str = "synthetic-poisson",
) -> ArrivalTrace:
    """Seeded Poisson traffic: exponential inter-arrival gaps at ``rate``
    requests per decode step (``jax.random``, deterministic per seed),
    uniform prompt lengths / generation budgets in the given inclusive
    ranges, uniform prompt tokens below ``vocab_size``.

    ``eos_id`` (when given) is excluded from prompt bodies so an injected
    separator can't end a request at its first sampled comparison —
    traces that *want* EOS-in-prompt coverage author it by hand.
    """
    import jax
    import numpy as np

    if num_requests < 1:
        raise ValueError(f"num_requests must be >= 1, got {num_requests}")
    if rate <= 0:
        raise ValueError(f"rate must be > 0 requests/step, got {rate}")
    if prompt_len[0] < 1 or prompt_len[0] > prompt_len[1]:
        raise ValueError(f"bad prompt_len range {prompt_len}")
    if max_new_tokens[0] < 1 or max_new_tokens[0] > max_new_tokens[1]:
        raise ValueError(f"bad max_new_tokens range {max_new_tokens}")
    if vocab_size < 2:
        raise ValueError(f"vocab_size must be >= 2, got {vocab_size}")
    key = jax.random.PRNGKey(seed)
    k_gap, k_plen, k_new, k_tok, k_seed = jax.random.split(key, 5)
    gaps = np.asarray(
        jax.random.exponential(k_gap, (num_requests,)) / rate
    )
    arrivals = np.floor(np.cumsum(gaps)).astype(int)
    plens = np.asarray(
        jax.random.randint(
            k_plen, (num_requests,), prompt_len[0], prompt_len[1] + 1
        )
    )
    news = np.asarray(
        jax.random.randint(
            k_new, (num_requests,), max_new_tokens[0], max_new_tokens[1] + 1
        )
    )
    toks = np.asarray(
        jax.random.randint(
            k_tok, (num_requests, int(prompt_len[1])), 0, vocab_size
        )
    )
    if eos_id is not None:
        # deterministic re-map of any sampled eos to its neighbor token
        toks = np.where(
            toks == int(eos_id), (toks + 1) % vocab_size, toks
        )
    seeds = np.asarray(
        jax.random.randint(k_seed, (num_requests,), 0, 1 << 30)
    )
    requests = [
        RequestSpec(
            req_id=i,
            arrival_step=int(arrivals[i]),
            prompt=tuple(int(t) for t in toks[i, : int(plens[i])]),
            max_new_tokens=int(news[i]),
            seed=int(seeds[i]),
        )
        for i in range(num_requests)
    ]
    return ArrivalTrace(world=world, seed=seed, requests=requests, label=label)


def load_serve_trace(
    world: Optional[int] = None, env: Optional[Mapping[str, str]] = None
) -> Optional[ArrivalTrace]:
    """The ``ADAPCC_SERVE_TRACE`` env funnel: None when unset, the parsed
    artifact otherwise — missing file / non-trace JSON / world mismatch
    all raise loudly (:func:`adapcc_tpu.utils.artifacts
    .load_env_json_artifact`'s shared policy)."""
    return load_env_json_artifact(
        SERVE_TRACE_ENV,
        ArrivalTrace.from_dict,
        "serve arrival-trace",
        world=world,
        env=env,
        mismatch_hint=(
            "its prompts and head split were authored for that mesh — "
            "replaying it as-is would serve different traffic than the "
            "trace claims"
        ),
    )


def arrival_steps(trace: ArrivalTrace) -> Sequence[int]:
    """The trace's arrival clock, for the queueing model."""
    return [r.arrival_step for r in trace.requests]
