"""The continuous batcher: per-request admission into fixed decode slots.

One :class:`GPT2Server` owns ``slots`` decode lanes over a TP mesh.  Each
engine step advances **every occupied lane by one token**: lanes still
inside their prompt force-feed the next prompt token (prefill), lanes
past it sample (decode) — so prefill and decode interleave in one fixed-
shape compiled step and admission never waits for a batch boundary
(continuous batching, Orca-style, at token granularity).

The step semantics are a transliteration of the ``generate`` scan body
(:mod:`adapcc_tpu.models.gpt2_generate`) with the scan index generalized
to a per-slot position and the EOS latch moved to the host:

- every occupied lane splits its own RNG every step (prefill steps too —
  that is what keeps lane streams bit-identical to a one-at-a-time
  ``generate`` run with the same per-request key);
- a sampled EOS at a generated position latches the stream: every later
  position is EOS by construction, so the lane is **evicted immediately**
  and its remaining tokens filled host-side — zero model steps owed, and
  the freed slot admits the next queued request without retracing;
- completion (position ``total − 1`` written) frees the slot at end of
  step; admission happens at start of step — a freed slot serves new
  traffic on the next step, exactly the discipline the queueing model in
  :mod:`adapcc_tpu.sim.cost_model` prices offline.

Latency accounting runs on two clocks: the deterministic **step clock**
(sojourn/TTFT in decode steps — byte-reproducible, what tests pin) and
the wall clock (per-step and per-request seconds through the
:class:`~adapcc_tpu.utils.observability.MetricsRegistry` reservoir, what
the SLO attainment and the p99 tuner objective consume).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from adapcc_tpu.models.gpt2 import GPT2Config
from adapcc_tpu.serve import resolve_serve_slo_ms, resolve_serve_slots
from adapcc_tpu.serve.kv_cache import SlotKVCache
from adapcc_tpu.serve.model import TPDecodeModel
from adapcc_tpu.serve.trace import ArrivalTrace, RequestSpec
from adapcc_tpu.utils.observability import (
    MetricsRegistry,
    nearest_rank_percentile,
)


@dataclass
class Request:
    """A live request (the scheduler-side spelling of a RequestSpec)."""

    req_id: int
    prompt: List[int]
    max_new_tokens: int
    seed: int
    arrival_step: int = 0

    @classmethod
    def from_spec(cls, spec: RequestSpec) -> "Request":
        return cls(
            req_id=spec.req_id,
            prompt=list(spec.prompt),
            max_new_tokens=spec.max_new_tokens,
            seed=spec.seed,
            arrival_step=spec.arrival_step,
        )

    @property
    def total(self) -> int:
        return len(self.prompt) + self.max_new_tokens


@dataclass
class RequestResult:
    """One served request: the token stream plus its latency ledger."""

    req_id: int
    tokens: List[int]
    prompt_len: int
    arrival_step: int
    admitted_step: int
    #: step at which the first *generated* token was written
    first_token_step: int = -1
    completed_step: int = -1
    #: True when the stream ended on a latched EOS before max_new_tokens
    eos_evicted: bool = False
    #: wall seconds from ARRIVAL to completion (the SLO clock — queue
    #: wait included, matching the step-clock sojourn convention and the
    #: sim twin's attainment)
    wall_s: float = 0.0

    @property
    def sojourn_steps(self) -> int:
        """Arrival → completion in decode steps (queue wait included)."""
        return self.completed_step - self.arrival_step

    @property
    def ttft_steps(self) -> int:
        """Arrival → first generated token, in decode steps."""
        return self.first_token_step - self.arrival_step

    @property
    def generated(self) -> List[int]:
        return self.tokens[self.prompt_len:]


@dataclass
class _Lane:
    """One occupied decode slot's host state."""

    req: Request
    admitted_step: int
    #: tokens written so far (prompt pre-filled); grows to req.total
    tokens: np.ndarray = field(default=None)  # type: ignore[assignment]
    #: scan position: index of the token the NEXT step feeds
    pos: int = 0
    first_token_step: int = -1
    wall_t0: float = 0.0


class GPT2Server:
    """Continuous-batching GPT-2 server on one TP mesh.

    ``algo`` is handed to every decode-step ``engine.all_reduce`` —
    ``"auto"`` (default) lets the calibrated crossover / tuner pick the
    small-message plane; ``ADAPCC_COLL_ALGO`` still outranks it (the
    engine's standing precedence).  Sampling parameters are server-wide
    and static, mirroring ``generate``'s static arguments.
    """

    def __init__(
        self,
        cfg: GPT2Config,
        params: Any,
        mesh,
        slots: Optional[int] = None,
        temperature: float = 1.0,
        top_k: int = 0,
        top_p: float = 0.0,
        eos_id: Optional[int] = None,
        algo: Optional[str] = "auto",
        engine=None,
        trace=None,
        metrics: Optional[MetricsRegistry] = None,
        slo_ms: Optional[float] = None,
    ) -> None:
        self.cfg = cfg
        self.params = params
        self.mesh = mesh
        self.world = int(mesh.devices.size)
        self.slots = resolve_serve_slots(slots)
        self.eos_id = eos_id
        self.algo = algo
        self.slo_ms = resolve_serve_slo_ms(slo_ms)
        if engine is None:
            from adapcc_tpu.comm.engine import CollectiveEngine
            from adapcc_tpu.strategy.ir import Strategy

            engine = CollectiveEngine(
                mesh, Strategy.ring(self.world), trace=trace
            )
        self.engine = engine
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.tp = TPDecodeModel(
            cfg, self.world, temperature=temperature, top_k=top_k, top_p=top_p
        )
        self.cache = SlotKVCache(
            cfg, self.world, self.slots, mesh=mesh, metrics=self.metrics
        )
        self.clock = 0
        self._pending: Deque[Request] = deque()
        self._lanes: Dict[int, _Lane] = {}
        self._free: List[int] = list(range(self.slots))
        self._results: Dict[int, RequestResult] = {}
        #: req_id → wall time its arrival step was first reached: the SLO
        #: clock starts at ARRIVAL, not admission, or queue wait would be
        #: invisible to attainment exactly in the overload regime the SLO
        #: exists for (the sim twin's sojourn convention)
        self._arrival_wall: Dict[int, float] = {}
        #: per-slot RNG keys, advanced only for occupied lanes
        self._rng = jnp.zeros((self.slots, 2), jnp.uint32)

    # -- admission -------------------------------------------------------------

    def submit(self, req: Request) -> None:
        if req.total > self.cfg.max_seq:
            raise ValueError(
                f"request {req.req_id}: {req.total} tokens > "
                f"max_seq={self.cfg.max_seq} cache slots"
            )
        if req.max_new_tokens < 1:
            raise ValueError(
                f"request {req.req_id}: max_new_tokens must be >= 1"
            )
        if not req.prompt:
            raise ValueError(f"request {req.req_id}: empty prompt")
        bad = [t for t in req.prompt if not 0 <= t < self.cfg.vocab_size]
        if bad:
            # nn.Embed's gather would silently clamp an out-of-range id
            # under jit — the server would serve different traffic than
            # the trace claims (the set-but-broken → loud artifact policy)
            raise ValueError(
                f"request {req.req_id}: prompt token(s) {bad[:3]} outside "
                f"vocab_size={self.cfg.vocab_size}"
            )
        self._pending.append(req)

    def submit_trace(self, trace: ArrivalTrace) -> None:
        if trace.world != self.world:
            raise ValueError(
                f"arrival trace was authored for world={trace.world} but "
                f"this server runs world={self.world}"
            )
        for spec in trace.requests:
            self.submit(Request.from_spec(spec))

    def _admit(self) -> None:
        while self._free and self._pending and (
            self._pending[0].arrival_step <= self.clock
        ):
            req = self._pending.popleft()
            slot = self._free.pop(0)
            lane = _Lane(req=req, admitted_step=self.clock)
            lane.tokens = np.zeros((req.total,), np.int32)
            lane.tokens[: len(req.prompt)] = np.asarray(req.prompt, np.int32)
            lane.wall_t0 = time.perf_counter()
            self._lanes[slot] = lane
            self.cache.clear_slot(slot)
            self._rng = self._rng.at[slot].set(
                jax.random.PRNGKey(req.seed)
            )
            self.metrics.incr("serve.admitted")

    # -- the decode step -------------------------------------------------------

    def step(self) -> int:
        """Admit, then advance every occupied lane by one token.  Returns
        the number of lanes that made progress (0 = idle tick: queue
        empty or all arrivals in the future)."""
        now = time.perf_counter()
        for req in self._pending:
            # the SLO clock starts when the arrival step is reached, even
            # if no slot is free yet — queue wait is sojourn, not overhead
            if req.arrival_step > self.clock:
                break  # arrival-sorted FIFO (the discipline _admit assumes)
            self._arrival_wall.setdefault(req.req_id, now)
        self._admit()
        active = sorted(self._lanes)
        if not active:
            self.clock += 1
            return 0
        t0 = time.perf_counter()
        tok = np.zeros((self.slots, 1), np.int32)
        pos = np.zeros((self.slots,), np.int32)
        for s in active:
            lane = self._lanes[s]
            tok[s, 0] = lane.tokens[lane.pos]
            pos[s] = lane.pos
        self._rng, sampled, new_layers = self.tp.decode_step(
            self.params,
            self.engine,
            self.cache.layers,
            jnp.asarray(tok),
            jnp.asarray(pos),
            self._rng,
            algo=self.algo,
        )
        for layer, (k_pages, v_pages) in enumerate(new_layers):
            self.cache.update(layer, k_pages, v_pages)
        sampled_host = np.asarray(sampled)
        self.metrics.observe("serve.step_s", time.perf_counter() - t0)
        self.metrics.gauge("serve.slots_busy", len(active))
        self.metrics.gauge("serve.queue_depth", len(self._pending))
        for s in active:
            self._advance_lane(s, int(sampled_host[s]))
        self.clock += 1
        return len(active)

    def _advance_lane(self, slot: int, sampled: int) -> None:
        """The generate scan body's host half for one lane: forced prompt
        vs sampled write, EOS eviction.  The scan's carried ``done`` latch
        has no host-side twin on purpose: it exists only because a scan
        cannot stop early — here the step that WRITES an EOS at a
        generated position evicts (or completes) the lane below, so no
        lane ever survives to feed an EOS back in."""
        lane = self._lanes[slot]
        req = lane.req
        t = lane.pos
        prompt_len = len(req.prompt)
        if t + 1 >= prompt_len:
            lane.tokens[t + 1] = sampled
            if t + 1 == prompt_len:
                # the step that wrote the token ends at clock+1 — the same
                # convention completed_step uses, so TTFT and sojourn
                # percentiles count engine steps identically
                lane.first_token_step = self.clock + 1
        # else: position t+1 is a forced prompt token, already in place
        lane.pos = t + 1
        wrote_eos = (
            self.eos_id is not None
            and t + 1 >= prompt_len
            and int(lane.tokens[t + 1]) == self.eos_id
        )
        if wrote_eos and lane.pos < req.total - 1:
            # the latch makes every later position EOS: fill host-side and
            # evict — the freed slot serves the queue next step, and no
            # compiled program is owed for the tail
            lane.tokens[lane.pos + 1:] = self.eos_id
            self.metrics.incr("serve.evicted_eos")
            self._complete(slot, eos_evicted=True)
            return
        if lane.pos == req.total - 1:
            self._complete(slot, eos_evicted=False)

    def _complete(self, slot: int, eos_evicted: bool) -> None:
        lane = self._lanes.pop(slot)
        self._free.append(slot)
        self._free.sort()
        req = lane.req
        self.cache.release_slot(
            slot, used_tokens=lane.pos + 1, evicted=eos_evicted
        )
        wall = time.perf_counter() - self._arrival_wall.pop(
            req.req_id, lane.wall_t0
        )
        result = RequestResult(
            req_id=req.req_id,
            tokens=[int(x) for x in lane.tokens],
            prompt_len=len(req.prompt),
            arrival_step=req.arrival_step,
            admitted_step=lane.admitted_step,
            first_token_step=lane.first_token_step,
            completed_step=self.clock + 1,
            eos_evicted=eos_evicted,
            wall_s=wall,
        )
        self._results[req.req_id] = result
        self.metrics.incr("serve.completed")
        self.metrics.observe("serve.sojourn_steps", result.sojourn_steps)
        if result.first_token_step >= 0:
            self.metrics.observe("serve.ttft_steps", result.ttft_steps)
        self.metrics.observe("serve.sojourn_s", wall)

    # -- the drive loop --------------------------------------------------------

    def run(self, max_steps: Optional[int] = None) -> List[RequestResult]:
        """Step until every submitted request completes (or ``max_steps``
        elapses — loudly: an under-budgeted drive must not return a
        partial ledger as if it were the full one)."""
        budget = max_steps if max_steps is not None else 1_000_000
        steps = 0
        while self._pending or self._lanes:
            if steps >= budget:
                raise RuntimeError(
                    f"serve run exceeded max_steps={budget} with "
                    f"{len(self._pending)} queued / {len(self._lanes)} "
                    "in-flight requests"
                )
            self.step()
            steps += 1
        return self.results()

    def results(self) -> List[RequestResult]:
        return [self._results[k] for k in sorted(self._results)]

    def summary(self) -> dict:
        """The serving ledger: deterministic step-clock percentiles plus
        the wall-clock SLO attainment."""
        res = self.results()
        snap = self.metrics.snapshot()
        out: dict = {
            "requests": len(res),
            "slots": self.slots,
            "world": self.world,
            "steps": self.clock,
            "kv_cache": self.cache.layout(),
            "kv_cache_stats": self.cache.stats(),
        }
        if res:
            sojourns = sorted(r.sojourn_steps for r in res)
            ttfts = sorted(r.ttft_steps for r in res if r.first_token_step >= 0)

            def pct(xs, q):
                return int(nearest_rank_percentile(xs, q))

            out["p50_sojourn_steps"] = pct(sojourns, 0.50)
            out["p99_sojourn_steps"] = pct(sojourns, 0.99)
            if ttfts:
                out["p50_ttft_steps"] = pct(ttfts, 0.50)
                out["p99_ttft_steps"] = pct(ttfts, 0.99)
        step_t = snap["timings"].get("serve.step_s")
        if step_t:
            out["p50_step_ms"] = step_t["p50_s"] * 1e3
            out["p99_step_ms"] = step_t["p99_s"] * 1e3
        if self.slo_ms is not None and res:
            within = sum(1 for r in res if r.wall_s * 1e3 <= self.slo_ms)
            out["slo_ms"] = self.slo_ms
            out["slo_attainment"] = within / len(res)
        return out
