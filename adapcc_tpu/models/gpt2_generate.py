"""GPT-2 autoregressive generation (the reference's models/gpt2/interact.py).

The reference samples from its trained PersonaChat GPT-2 with a host-side
top-k/top-p loop (interact.py sample_sequence).  TPU-first shape: the whole
prefill+decode loop is ONE ``lax.scan`` inside one jitted program — fixed-
shape KV cache per layer (no growing arrays), one token per scan step, prompt
tokens force-fed for the first ``prompt_len`` steps and sampled thereafter.
No data-dependent Python control flow; EOS handling is a carried mask.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from adapcc_tpu.models.gpt2 import GPT2, GPT2Config


def filter_top_k(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    """Mask everything below the k-th largest logit to -inf."""
    kth = jnp.sort(logits, axis=-1)[..., -k][..., None]
    return jnp.where(logits < kth, -jnp.inf, logits)


def filter_top_p(logits: jnp.ndarray, p: float) -> jnp.ndarray:
    """Nucleus filtering: keep the smallest prefix of the sorted distribution
    with cumulative probability ≥ p (the first token always survives)."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # a sorted position is cut when the mass *before* it already reaches p
    cut = cum - probs >= p
    threshold = jnp.min(jnp.where(cut, jnp.inf, sorted_logits), axis=-1, keepdims=True)
    return jnp.where(logits < threshold, -jnp.inf, logits)


def sample_token(
    rng: jax.Array,
    logits: jnp.ndarray,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 0.0,
) -> jnp.ndarray:
    """One token per batch row from filtered logits; greedy iff T == 0."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k:
        logits = filter_top_k(logits, top_k)
    if top_p:
        logits = filter_top_p(logits, top_p)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


@partial(
    jax.jit,
    static_argnames=(
        "model", "prompt_len", "max_new_tokens", "temperature", "top_k", "top_p",
        "eos_id",
    ),
)
def generate(
    model: GPT2,
    params: Any,
    prompt: jnp.ndarray,
    prompt_len: int,
    max_new_tokens: int,
    rng: Optional[jax.Array] = None,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 0.0,
    eos_id: Optional[int] = None,
) -> jnp.ndarray:
    """Generate ``max_new_tokens`` past a ``[B, prompt_len]`` prompt.

    Returns ``[B, prompt_len + max_new_tokens]`` int32 (prompt included).
    After EOS a row emits ``eos_id`` forever.  The cache holds
    ``model.cfg.max_seq`` slots; total length must fit in it.
    """
    cfg = model.cfg
    B = prompt.shape[0]
    total = prompt_len + max_new_tokens
    if total > cfg.max_seq:
        raise ValueError(f"{total} tokens > max_seq={cfg.max_seq} cache slots")
    if rng is None:
        rng = jax.random.PRNGKey(0)

    cache = model.init(
        jax.random.PRNGKey(0), jnp.zeros((B, 1), jnp.int32), decode=True,
        pos=jnp.zeros((), jnp.int32),
    )["cache"]

    tokens0 = jnp.zeros((B, total), jnp.int32)
    tokens0 = jax.lax.dynamic_update_slice(tokens0, prompt.astype(jnp.int32), (0, 0))

    def step(carry, t):
        tokens, cache, rng, done = carry
        tok_in = jax.lax.dynamic_slice(tokens, (0, t), (B, 1))
        logits, mutated = model.apply(
            {"params": params, "cache": cache},
            tok_in,
            decode=True,
            pos=t,
            mutable=["cache"],
        )
        rng, sub = jax.random.split(rng)
        nxt = sample_token(sub, logits[:, 0], temperature, top_k, top_p)
        if eos_id is not None:
            # only sampled tokens can latch EOS: positions t < prompt_len are
            # forced prompt tokens (which may legitimately contain eos as a
            # separator, e.g. PersonaChat dialogue turns)
            done = done | ((tok_in[:, 0] == eos_id) & (t >= prompt_len))
            nxt = jnp.where(done, eos_id, nxt)
        # prompt positions are forced, generated positions sampled
        forced = t + 1 < prompt_len
        prompt_next = tokens[:, jnp.minimum(t + 1, total - 1)]
        written = jnp.where(forced, prompt_next, nxt)
        tokens = jax.lax.dynamic_update_slice(tokens, written[:, None], (0, t + 1))
        return (tokens, mutated["cache"], rng, done), None

    done0 = jnp.zeros((B,), bool)
    (tokens, _, _, _), _ = jax.lax.scan(
        step, (tokens0, cache, rng, done0), jnp.arange(total - 1)
    )
    return tokens


# --------------------------------------------------------------------------- #
# interact CLI (models/gpt2/interact.py analog)
# --------------------------------------------------------------------------- #


class ByteTokenizer:
    """Offline fallback tokenizer: raw UTF-8 bytes + BOS/EOS (vocab 258).

    The reference's interact.py needs the downloaded GPT-2 BPE vocab; in a
    zero-egress environment a byte-level mapping keeps the loop usable.
    """

    vocab_size = 258
    bos_id = 256
    eos_id = 257

    def encode(self, text: str) -> list:
        return [self.bos_id] + list(text.encode("utf-8"))

    def decode(self, ids) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")


def load_tokenizer():
    """HuggingFace GPT-2 BPE when its files are available locally, else the
    byte fallback."""
    try:
        from transformers import GPT2TokenizerFast

        tok = GPT2TokenizerFast.from_pretrained("gpt2", local_files_only=True)
        tok.eos_id = tok.eos_token_id
        return tok
    except Exception:
        return ByteTokenizer()


def interact(argv: Optional[list] = None) -> None:
    """REPL (or one-shot with ``--prompt``): prompt in, continuation out.

    ``--ckpt`` loads trained params (TrainCheckpointState files written by
    workloads/train_gpt2.py ``--checkpoint-file``); the model-shape flags
    mirror train_gpt2's so the same command line that trained a model can
    sample from it.
    """
    import argparse

    from adapcc_tpu.launch.launcher import apply_platform_env

    apply_platform_env()  # honor JAX_PLATFORMS despite site customizations

    ap = argparse.ArgumentParser(description="GPT-2 interactive sampling")
    ap.add_argument("--ckpt", "--checkpoint", dest="ckpt", default=None)
    ap.add_argument("--prompt", default=None,
                    help="one-shot mode: generate from this prompt and exit")
    ap.add_argument("--max-new-tokens", type=int, default=32)
    ap.add_argument("--temperature", type=float, default=0.9)
    ap.add_argument("--top-k", type=int, default=40)
    ap.add_argument("--top-p", type=float, default=0.9)
    ap.add_argument("--seed", type=int, default=0)
    # model shape: same flags and defaults as workloads/train_gpt2.py (except
    # --vocab, which follows the tokenizer), so a default-trained checkpoint
    # round-trips with a default generate command line
    ap.add_argument("--vocab", type=int, default=None,
                    help="default: tokenizer vocab size")
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=2)
    ap.add_argument("--dmodel", type=int, default=128)
    args = ap.parse_args(argv)

    if args.max_new_tokens >= args.seq:
        raise SystemExit(
            f"--max-new-tokens {args.max_new_tokens} must be < --seq "
            f"{args.seq}: the KV cache holds prompt + generation together"
        )
    import os

    mismatch = (
        f"checkpoint {args.ckpt!r} not found or incompatible with the "
        f"model shape (--vocab/--seq/--layers/--heads/--dmodel must "
        f"match training)"
    )
    if args.ckpt and not os.path.exists(args.ckpt):
        # fail before building/compiling the model; same message as the
        # post-load mismatch path so callers can match on one string
        raise SystemExit(mismatch)

    tok = load_tokenizer()
    vocab = args.vocab or max(getattr(tok, "vocab_size", 258), 258)
    cfg = GPT2Config(vocab_size=vocab, max_seq=args.seq,
                     n_layer=args.layers, n_head=args.heads, d_model=args.dmodel)
    model = GPT2(cfg)
    params = model.init(
        jax.random.PRNGKey(args.seed), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    if args.ckpt:
        from adapcc_tpu.checkpoint import TrainCheckpointState, load_checkpoint

        state = TrainCheckpointState(params={"params": params})
        try:
            ok = load_checkpoint(state, args.ckpt)
        except Exception as e:  # flax from_bytes raises on shape mismatch
            raise SystemExit(f"{mismatch}\n  cause: {e}") from e
        if not ok:
            raise SystemExit(mismatch)
        params = state.params["params"]
        print(f"loaded checkpoint (epoch {state.epoch})")

    rng = jax.random.PRNGKey(args.seed)

    def respond(text: str, rng: jax.Array) -> str:
        ids = tok.encode(text)[-(cfg.max_seq - args.max_new_tokens):]
        prompt = jnp.asarray(np.array(ids)[None], jnp.int32)
        out = generate(
            model, params, prompt, prompt_len=len(ids),
            max_new_tokens=args.max_new_tokens, rng=rng,
            temperature=args.temperature, top_k=args.top_k, top_p=args.top_p,
            eos_id=getattr(tok, "eos_id", None),
        )
        return tok.decode(np.asarray(out[0])[len(ids):].tolist())

    if args.prompt is not None:
        print(respond(args.prompt, rng))
        return

    while True:
        try:
            text = input(">>> ")
        except (EOFError, KeyboardInterrupt):
            break
        if not text.strip():
            continue
        rng, sub = jax.random.split(rng)
        print(respond(text, sub))


if __name__ == "__main__":
    interact()
