"""ResNet: the reference's headline image-classification architecture.

The reference's elastic-imagenet workload instantiates torchvision ResNets
by name — ``--arch resnet18`` is the default and the accuracy/GNS studies
run on it (models/image-classification/main_elastic.py:73-77, 243-244); the
committed DDP bucket-shape table that drives chunk sizing is ResNet18's
(log/model_bucket_info.txt:1-13).  This is the TPU-first re-design, not a
torchvision translation:

- NHWC layout and a compute ``dtype`` knob (bf16 keeps the convs on the
  MXU at full rate; params/norm statistics stay fp32).
- ``norm="group"`` (default) is stateless GroupNorm — the standard choice
  for large-batch data-parallel training on TPU pods (no running statistics
  to carry, no cross-replica dependence), so the model drops straight into
  the ``loss_fn(params, batch)`` DDP interface.
- ``norm="batch"`` is full BatchNorm with an optional ``axis_name``: under
  ``shard_map`` the batch statistics are averaged across the mesh axis
  (**SyncBN**) so every rank's running stats stay bit-identical — stronger
  than the reference's per-GPU unsynced BN.  Stateful; thread the
  ``batch_stats`` collection through :class:`~adapcc_tpu.ddp.DDPTrainer`'s
  ``stateful_loss`` mode.
- Bottleneck stride placement follows the v1.5 convention (stride on the
  3x3, matching what torchvision ships — so parity comparisons compare
  like with like).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


def _norm(norm: str, dtype, axis_name: Optional[str], train: bool) -> ModuleDef:
    if norm == "group":
        # groups must divide channels; stage widths are powers of two, so
        # min(32, C) always divides (tiny test widths included)
        return partial(
            _AutoGroupNorm, dtype=dtype, param_dtype=jnp.float32
        )
    if norm == "batch":
        return partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=dtype,
            param_dtype=jnp.float32,
            axis_name=axis_name,
        )
    raise ValueError(f"norm must be 'group' or 'batch', got {norm!r}")


class _AutoGroupNorm(nn.Module):
    """GroupNorm whose group count adapts to the channel count."""

    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        channels = x.shape[-1]
        # largest group count <= 32 that divides the channel count: flax
        # GroupNorm requires divisibility, and non-power-of-two widths
        # (e.g. C=48) would otherwise die inside flax with a generic error
        groups = next(g for g in range(min(32, channels), 0, -1) if channels % g == 0)
        return nn.GroupNorm(
            num_groups=groups,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
        )(x)


class BasicBlock(nn.Module):
    """Two 3x3 convs + identity/projection shortcut (ResNet-18/34)."""

    features: int
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        conv = partial(
            nn.Conv, use_bias=False, dtype=self.dtype, param_dtype=jnp.float32
        )
        residual = x
        y = conv(self.features, (3, 3), self.strides, padding="SAME")(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = conv(self.features, (3, 3), padding="SAME")(y)
        y = self.norm()(y)
        if residual.shape != y.shape:
            residual = conv(
                self.features, (1, 1), self.strides, name="shortcut_conv"
            )(residual)
            residual = self.norm(name="shortcut_norm")(residual)
        return nn.relu(y + residual)


class Bottleneck(nn.Module):
    """1x1 → 3x3 → 1x1(x4) bottleneck (ResNet-50+), stride on the 3x3
    (the v1.5 placement torchvision uses)."""

    features: int
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        conv = partial(
            nn.Conv, use_bias=False, dtype=self.dtype, param_dtype=jnp.float32
        )
        residual = x
        y = conv(self.features, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = conv(self.features, (3, 3), self.strides, padding="SAME")(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = conv(self.features * 4, (1, 1))(y)
        y = self.norm()(y)
        if residual.shape != y.shape:
            residual = conv(
                self.features * 4, (1, 1), self.strides, name="shortcut_conv"
            )(residual)
            residual = self.norm(name="shortcut_norm")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """Configurable ResNet over NHWC images.

    ``small_inputs=True`` swaps the 7x7/2+maxpool imagenet stem for a 3x3/1
    stem (the CIFAR-style variant the test pods use — 32x32 inputs keep
    spatial extent instead of collapsing to 1x1 by stage 3).
    """

    stage_sizes: Sequence[int]
    block_cls: Callable[..., nn.Module] = BasicBlock
    num_classes: int = 1000
    width: int = 64
    norm: str = "group"
    axis_name: Optional[str] = None
    dtype: jnp.dtype = jnp.bfloat16
    small_inputs: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        """``x [B, H, W, C]`` → logits ``[B, num_classes]``."""
        norm = _norm(self.norm, self.dtype, self.axis_name, train)
        conv = partial(
            nn.Conv, use_bias=False, dtype=self.dtype, param_dtype=jnp.float32
        )
        x = x.astype(self.dtype)
        if self.small_inputs:
            x = conv(self.width, (3, 3), padding="SAME", name="stem_conv")(x)
        else:
            x = conv(self.width, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                     name="stem_conv")(x)
        x = norm(name="stem_norm")(x)
        x = nn.relu(x)
        if not self.small_inputs:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for stage, n_blocks in enumerate(self.stage_sizes):
            for block in range(n_blocks):
                strides = (2, 2) if stage > 0 and block == 0 else (1, 1)
                x = self.block_cls(
                    features=self.width * 2 ** stage,
                    norm=norm,
                    strides=strides,
                    dtype=self.dtype,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        # fp32 head: the classifier matmul + softmax stay in full precision
        return nn.Dense(
            self.num_classes, dtype=jnp.float32, param_dtype=jnp.float32
        )(x.astype(jnp.float32))


def ResNet18(**kw) -> ResNet:
    return ResNet(stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock, **kw)


def ResNet34(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), block_cls=BasicBlock, **kw)


def ResNet50(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), block_cls=Bottleneck, **kw)
