"""ViT: vision-transformer DDP workload (reference models/vit/train_vit.py
uses vit-pytorch with synthetic data).  Patch embed → encoder blocks → CLS
head; bf16 matmuls, static shapes."""

from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    d_model: int = 384
    n_layer: int = 12
    n_head: int = 6
    mlp_ratio: int = 4
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.bfloat16

    @staticmethod
    def tiny() -> "ViTConfig":
        return ViTConfig(image_size=32, patch_size=8, num_classes=10, d_model=64, n_layer=2, n_head=2)


class EncoderBlock(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        cfg = self.cfg
        h = nn.LayerNorm(dtype=jnp.float32)(x)
        h = nn.MultiHeadDotProductAttention(
            num_heads=cfg.n_head, dtype=cfg.dtype, deterministic=deterministic, name="attn"
        )(h, h)
        x = x + h
        h = nn.LayerNorm(dtype=jnp.float32)(x)
        h = nn.Dense(cfg.mlp_ratio * cfg.d_model, dtype=cfg.dtype)(h)
        h = nn.gelu(h)
        h = nn.Dense(cfg.d_model, dtype=cfg.dtype)(h)
        return x + nn.Dropout(cfg.dropout)(h, deterministic=deterministic)


class ViT(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, images: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        """``images [B, H, W, C]`` → logits ``[B, num_classes]``."""
        cfg = self.cfg
        B = images.shape[0]
        x = nn.Conv(
            cfg.d_model,
            (cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size),
            dtype=cfg.dtype,
            name="patch_embed",
        )(images)
        x = x.reshape(B, -1, cfg.d_model)

        cls = self.param("cls", nn.initializers.normal(0.02), (1, 1, cfg.d_model))
        x = jnp.concatenate([jnp.broadcast_to(cls, (B, 1, cfg.d_model)).astype(cfg.dtype), x], axis=1)
        pos = self.param("pos", nn.initializers.normal(0.02), (1, x.shape[1], cfg.d_model))
        x = x + pos.astype(cfg.dtype)

        for i in range(cfg.n_layer):
            x = EncoderBlock(cfg, name=f"block_{i}")(x, deterministic)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        return nn.Dense(cfg.num_classes, dtype=jnp.float32, name="head")(x[:, 0])
