"""GPT-2: the flagship training workload.

The reference trains HuggingFace GPT-2 (PersonaChat) under torch DDP with its
adaptive allreduce (models/gpt2/train_gpt2_ddp.py); this is a from-scratch
flax implementation of the same architecture family, shaped for TPU:

- all matmuls in ``bfloat16`` with ``float32`` accumulation/params — the MXU
  sweet spot;
- static shapes everywhere (fixed ``max_seq``), causal mask via additive
  bias, no dynamic control flow under jit;
- optional ``nn.remat`` over blocks to trade FLOPs for HBM;
- weight-tied LM head (embedding transpose), GPT-2 initialization scheme
  (scaled residual projections).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    max_seq: int = 1024
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.bfloat16
    remat: bool = False
    #: rematerialization granularity when ``remat`` is on: "full" recomputes
    #: everything in the block (max HBM savings, ~1/3 extra FLOPs); "dots"
    #: saves matmul outputs and recomputes only the cheap elementwise ops
    #: (jax.checkpoint_policies.checkpoint_dots) — the usual TPU sweet spot,
    #: since MXU FLOPs are the scarce resource and elementwise recompute is
    #: nearly free against HBM-bound steps
    remat_policy: str = "full"
    #: "xla" materializes [T, T] scores and lets XLA fuse; "flash" runs the
    #: blockwise Pallas kernel (ops/flash_attention.py) — O(T) memory, MXU
    #: tiles, no attention-matrix HBM traffic.  Training path only (decode
    #: uses the KV cache) and requires dropout == 0.
    attention: str = "xla"
    #: flash kernel tile edge (block_q == block_k); the VMEM-vs-parallelism
    #: trade to sweep on hardware (bench.py BENCH_FLASH_BLOCK)
    flash_block: int = 128
    #: sequence parallelism: when set (a mesh axis name), the model expects
    #: to run INSIDE shard_map with tokens sequence-sharded over that axis —
    #: attention crosses shards via the ring / Ulysses programs
    #: (parallel/gpt2_sp.py wraps the whole train step), positions are
    #: globally offset by the shard index, and ``attention == "flash"``
    #: selects the Pallas block kernel inside the SP program.  Training
    #: only (decode keeps a single-device KV cache); requires dropout == 0.
    sp_axis: Optional[str] = None
    #: which SP scheme carries attention across shards: "ring" rotates K/V
    #: blocks (O(T_local) memory), "ulysses" trades sequence for heads with
    #: one all-to-all each way (needs n_head % world == 0)
    sp_impl: str = "ring"

    @staticmethod
    def small() -> "GPT2Config":
        return GPT2Config()

    @staticmethod
    def tiny() -> "GPT2Config":
        """Test-sized config: compiles in seconds, fits anywhere."""
        return GPT2Config(vocab_size=512, max_seq=64, n_layer=2, n_head=2, d_model=64)


class CausalSelfAttention(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(
        self, x: jnp.ndarray, deterministic: bool = True, decode: bool = False
    ) -> jnp.ndarray:
        cfg = self.cfg
        B, T, C = x.shape
        head_dim = cfg.d_model // cfg.n_head

        qkv = nn.Dense(3 * cfg.d_model, dtype=cfg.dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, cfg.n_head, head_dim)
        k = k.reshape(B, T, cfg.n_head, head_dim)
        v = v.reshape(B, T, cfg.n_head, head_dim)

        scale = 1.0 / np.sqrt(head_dim)
        if cfg.sp_axis is not None and not decode:
            # sequence-parallel attention: this module runs inside shard_map
            # with [B, T_local, ...] shards; K/V cross shards via the ring or
            # Ulysses program (attention dropout unsupported there)
            if cfg.dropout != 0.0:
                raise ValueError("sequence parallelism requires dropout == 0")
            block_impl = "flash" if cfg.attention == "flash" else "dense"
            if cfg.sp_impl == "ring":
                from adapcc_tpu.parallel.ring_attention import ring_attention_shard

                out = ring_attention_shard(
                    q, k, v, axis_name=cfg.sp_axis, causal=True, scale=scale,
                    block_impl=block_impl,
                    block_q=cfg.flash_block, block_k=cfg.flash_block,
                )
            elif cfg.sp_impl == "ulysses":
                from adapcc_tpu.parallel.ulysses import ulysses_attention_shard

                out = ulysses_attention_shard(
                    q, k, v, axis_name=cfg.sp_axis, causal=True, scale=scale,
                    block_impl=block_impl,
                    block_q=cfg.flash_block, block_k=cfg.flash_block,
                )
            else:
                raise ValueError(f"unknown sp_impl {cfg.sp_impl!r} (ring|ulysses)")
            return self._project(out.reshape(B, T, cfg.d_model), deterministic)
        if decode:
            # single-token autoregressive step against a fixed-shape KV cache
            # (static [max_seq] slots — no dynamic shapes under jit)
            if T != 1:
                raise ValueError(f"decode mode feeds one token at a time, got T={T}")
            is_init = self.has_variable("cache", "cached_key")
            cached_k = self.variable(
                "cache", "cached_key",
                jnp.zeros, (B, cfg.max_seq, cfg.n_head, head_dim), cfg.dtype,
            )
            cached_v = self.variable(
                "cache", "cached_value",
                jnp.zeros, (B, cfg.max_seq, cfg.n_head, head_dim), cfg.dtype,
            )
            cache_idx = self.variable(
                "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
            )
            if is_init:
                idx = cache_idx.value
                cached_k.value = jax.lax.dynamic_update_slice(
                    cached_k.value, k.astype(cfg.dtype), (0, idx, 0, 0)
                )
                cached_v.value = jax.lax.dynamic_update_slice(
                    cached_v.value, v.astype(cfg.dtype), (0, idx, 0, 0)
                )
                cache_idx.value = idx + 1
                k, v = cached_k.value, cached_v.value
                att = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
                valid = jnp.arange(cfg.max_seq) <= idx
                att = jnp.where(valid[None, None, None], att, -1e30)
            else:
                att = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
        elif cfg.attention == "flash" and cfg.dropout == 0.0:
            from adapcc_tpu.ops import flash_attention

            out = flash_attention(
                q.astype(cfg.dtype), k.astype(cfg.dtype), v.astype(cfg.dtype),
                causal=True, scale=scale,
                block_q=cfg.flash_block, block_k=cfg.flash_block,
            )
            return self._project(out.reshape(B, T, cfg.d_model), deterministic)
        else:
            att = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
            causal = jnp.tril(jnp.ones((T, T), dtype=bool))
            att = jnp.where(causal[None, None], att, -1e30)
        att = jax.nn.softmax(att, axis=-1).astype(cfg.dtype)
        att = nn.Dropout(cfg.dropout)(att, deterministic=deterministic)

        out = jnp.einsum("bhqk,bkhd->bqhd", att, v).reshape(B, T, cfg.d_model)
        return self._project(out, deterministic)

    def _project(self, out: jnp.ndarray, deterministic: bool) -> jnp.ndarray:
        cfg = self.cfg
        # scaled init on the residual projection (GPT-2 scheme)
        proj = nn.Dense(
            cfg.d_model,
            dtype=cfg.dtype,
            kernel_init=nn.initializers.normal(0.02 / np.sqrt(2 * cfg.n_layer)),
            name="proj",
        )(out)
        return nn.Dropout(cfg.dropout)(proj, deterministic=deterministic)


class Block(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(
        self, x: jnp.ndarray, deterministic: bool = True, decode: bool = False
    ) -> jnp.ndarray:
        cfg = self.cfg
        x = x + CausalSelfAttention(cfg, name="attn")(
            nn.LayerNorm(dtype=jnp.float32, name="ln1")(x), deterministic, decode
        )
        h = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x)
        h = nn.Dense(4 * cfg.d_model, dtype=cfg.dtype, name="fc")(h)
        h = nn.gelu(h)
        h = nn.Dense(
            cfg.d_model,
            dtype=cfg.dtype,
            kernel_init=nn.initializers.normal(0.02 / np.sqrt(2 * cfg.n_layer)),
            name="proj",
        )(h)
        return x + nn.Dropout(cfg.dropout)(h, deterministic=deterministic)


class GPT2(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(
        self,
        tokens: jnp.ndarray,
        deterministic: bool = True,
        decode: bool = False,
        pos: Optional[jnp.ndarray] = None,
        return_hidden: bool = False,
    ) -> jnp.ndarray:
        """``tokens [B, T] int32`` → logits ``[B, T, vocab] float32``.

        ``decode=True`` runs one-token autoregressive steps against a mutable
        ``'cache'`` collection; ``pos`` (int32 scalar) is the absolute
        position of the fed token (required in decode mode).
        ``return_hidden=True`` skips the LM head and returns the post-ln_f
        ``[B, T, d_model]`` hiddens — for the chunked vocab loss
        (ops/chunked_ce.py), which fuses the head matmul into the loss and
        never materializes ``[B, T, vocab]``.
        """
        cfg = self.cfg
        B, T = tokens.shape

        wte = nn.Embed(
            cfg.vocab_size,
            cfg.d_model,
            embedding_init=nn.initializers.normal(0.02),
            dtype=cfg.dtype,
            name="wte",
        )
        wpe = nn.Embed(
            cfg.max_seq,
            cfg.d_model,
            embedding_init=nn.initializers.normal(0.01),
            dtype=cfg.dtype,
            name="wpe",
        )
        if decode and pos is None:
            raise ValueError("decode=True needs pos (the fed token's absolute position)")
        if pos is not None:
            positions = jnp.asarray(pos).reshape((1,))
        elif cfg.sp_axis is not None:
            # sequence-sharded: this shard covers global positions
            # [me*T_local, (me+1)*T_local)
            positions = jax.lax.axis_index(cfg.sp_axis) * T + jnp.arange(T)
        else:
            positions = jnp.arange(T)
        x = wte(tokens) + wpe(positions)[None]
        x = nn.Dropout(cfg.dropout)(x, deterministic=deterministic)

        block = Block
        if cfg.remat:
            policies = {
                "full": None,  # recompute everything
                "dots": jax.checkpoint_policies.checkpoint_dots,
                "dots_no_batch": (
                    jax.checkpoint_policies.dots_with_no_batch_dims_saveable
                ),
            }
            if cfg.remat_policy not in policies:
                raise ValueError(
                    f"remat_policy {cfg.remat_policy!r} not in {sorted(policies)}"
                )
            block = nn.remat(
                Block, static_argnums=(2, 3), policy=policies[cfg.remat_policy]
            )
        for i in range(cfg.n_layer):
            x = block(cfg, name=f"h{i}")(x, deterministic, decode)

        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        if return_hidden:
            return x
        # weight-tied LM head
        logits = x.astype(cfg.dtype) @ wte.embedding.T.astype(cfg.dtype)
        return logits.astype(jnp.float32)


def lm_loss(logits: jnp.ndarray, tokens: jnp.ndarray) -> jnp.ndarray:
    """Next-token cross-entropy over a ``[B, T]`` batch."""
    logits = logits[:, :-1]
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def lm_loss_chunked(
    model: "GPT2", params, tokens: jnp.ndarray, block: int = 1024
) -> jnp.ndarray:
    """:func:`lm_loss` without the ``[B, T, vocab]`` logits tensor: the model
    returns post-ln_f hiddens and the weight-tied head matmul fuses into the
    chunked online-softmax loss (ops/chunked_ce.py).  Same math — head in
    ``cfg.dtype``, fp32 softmax — at 1/(vocab/block) of the logits HBM.
    Gradients flow to ``wte`` through both its embedding use and the head.
    """
    from adapcc_tpu.ops.chunked_ce import chunked_lm_loss

    hidden = model.apply(params, tokens, return_hidden=True)
    wte = params["params"]["wte"]["embedding"]
    return chunked_lm_loss(hidden, wte, tokens, block, model.cfg.dtype)


def _sp_targets_and_mask(tokens: jnp.ndarray, axis_name: str):
    """Shared SP boundary handling: each local position's target is the next
    token — the shard's last position's target lives on the *next* rank and
    arrives by one tiny ``[B]`` ppermute (rank r receives rank r+1's first
    token, the ring modules' shared convention); the last rank's final
    position has no target and is masked out."""
    from jax import lax

    from adapcc_tpu.parallel.ring_attention import _ring_perm

    B, Tl = tokens.shape
    world = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    next_first = lax.ppermute(tokens[:, 0], axis_name, _ring_perm(world))  # [B]
    targets = jnp.concatenate([tokens[:, 1:], next_first[:, None]], axis=1)
    valid = jnp.ones((B, Tl), jnp.float32)
    valid = valid.at[:, -1].set(jnp.where(me == world - 1, 0.0, 1.0))
    return targets, valid


def _sp_masked_mean(nll: jnp.ndarray, valid: jnp.ndarray, axis_name: str):
    """psum-weighted global mean over valid positions — replicated, and
    numerically identical to the unsharded mean."""
    from jax import lax

    total = lax.psum(jnp.sum(nll * valid.astype(nll.dtype)), axis_name)
    count = lax.psum(jnp.sum(valid), axis_name)
    return total / count


def lm_loss_sp_chunked(
    hidden: jnp.ndarray,
    wte: jnp.ndarray,
    tokens: jnp.ndarray,
    axis_name: str,
    block: int = 1024,
    compute_dtype=None,
) -> jnp.ndarray:
    """:func:`lm_loss_sp` without the ``[B, T_local, vocab]`` logits tensor:
    the long-context × long-vocab composition.  Same boundary handling and
    psum-weighted global mean (the shared helpers); the per-position NLL
    comes from the chunked online-softmax scan (ops/chunked_ce.py).
    """
    from adapcc_tpu.ops.chunked_ce import chunked_softmax_nll

    B, Tl, D = hidden.shape
    targets, valid = _sp_targets_and_mask(tokens, axis_name)
    nll = chunked_softmax_nll(
        hidden.reshape(B * Tl, D), wte, targets.reshape(B * Tl),
        block, compute_dtype or hidden.dtype,
    ).reshape(B, Tl)
    return _sp_masked_mean(nll, valid, axis_name)


def lm_loss_sp(logits: jnp.ndarray, tokens: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """:func:`lm_loss` under sequence sharding, for use inside ``shard_map``.

    ``logits/tokens`` are this shard's ``[B, T_local, V]`` / ``[B, T_local]``
    slices of the global sequence.  Each local position's target is the next
    token — for the shard's last position that token lives on the *next*
    rank, so it arrives by one tiny ``ppermute`` ([B] int32).  The last
    rank's final position has no target and is masked out; the result is the
    psum-weighted global mean, numerically identical to ``lm_loss`` on the
    unsharded batch (and replicated across ranks).
    """
    targets, valid = _sp_targets_and_mask(tokens, axis_name)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return _sp_masked_mean(nll, valid, axis_name)
