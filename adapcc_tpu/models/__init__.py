"""Model zoo: the workload families the reference trains with its collectives
(SURVEY §2.4: VGG16 DDP, ViT, GPT-2, MoE, elastic ResNet image
classification) re-implemented as flax modules shaped for TPU execution —
bf16 matmuls on the MXU, static shapes, remat-friendly blocks."""

from adapcc_tpu.models.mlp import MLP
from adapcc_tpu.models.gpt2 import GPT2, GPT2Config
from adapcc_tpu.models.resnet import ResNet, ResNet18, ResNet34, ResNet50

__all__ = ["MLP", "GPT2", "GPT2Config", "ResNet", "ResNet18", "ResNet34", "ResNet50"]
