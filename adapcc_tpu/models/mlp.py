"""Small MLP: the unit-test workload (analog of the reference's smoke
benchmarks that train tiny models just to exercise the collectives)."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    features: Sequence[int] = (32, 32, 10)

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        for i, f in enumerate(self.features):
            x = nn.Dense(f, name=f"dense_{i}")(x)
            if i < len(self.features) - 1:
                x = nn.relu(x)
        return x
