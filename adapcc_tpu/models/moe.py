"""Mixture-of-Experts with expert parallelism.

The reference benchmarks a fastmoe ``FMoETransformerMLP`` whose all-to-all
dispatch is done by fastmoe/NCCL — *not* by AdapCC, whose ALLTOALL primitive
was an unimplemented stub (SURVEY §2.3; models/moe/train_moe.py:20-41).
Here EP is native: capacity-based top-k routing with one-hot dispatch/combine
einsums over a stacked expert axis.  Sharding that axis over an ``experts``
mesh axis makes XLA lower the dispatch einsums to ICI all-to-alls — the
TPU-idiomatic form of the fastmoe shuffle; the explicit
``CollectiveEngine.all_to_all`` covers the manual path.
"""

from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    d_model: int = 256
    d_hidden: int = 1024
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: jnp.dtype = jnp.bfloat16
    #: ST-MoE router z-loss: penalizes large router logits (mean logsumexp²
    #: over tokens), keeping the fp32 softmax well-scaled.  The term rides
    #: inside the returned aux scalar, so its EFFECTIVE weight on the
    #: objective is this coefficient × the consumer's aux-loss weight —
    #: with train_moe's default ``--aux-weight 0.01``, a 0.1 here lands on
    #: ST-MoE's recommended effective 1e-3.  Defaults to 0 (disabled) so the
    #: aux objective is opt-in; workloads that want it set it explicitly
    #: (train_moe passes 0.1).
    router_z_coef: float = 0.0

    @staticmethod
    def tiny() -> "MoEConfig":
        return MoEConfig(num_experts=4, d_model=32, d_hidden=64, top_k=2)


class MoEMLP(nn.Module):
    """Top-k routed expert MLP (switch-style dispatch, static capacity)."""

    cfg: MoEConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray):
        """``x [B, T, D]`` → ``(y [B, T, D], aux_loss scalar)``."""
        cfg = self.cfg
        B, T, D = x.shape
        n_tokens = B * T
        capacity = int(np.ceil(cfg.capacity_factor * cfg.top_k * n_tokens / cfg.num_experts))
        tokens = x.reshape(n_tokens, D)

        # routing (fp32 for a stable softmax)
        gate_logits = nn.Dense(cfg.num_experts, dtype=jnp.float32, name="router")(
            tokens.astype(jnp.float32)
        )
        gate_probs = jax.nn.softmax(gate_logits, axis=-1)

        # load-balancing auxiliary loss (switch-transformer form)
        me = jnp.mean(gate_probs, axis=0)
        ce = jnp.mean(
            jax.nn.one_hot(jnp.argmax(gate_probs, axis=-1), cfg.num_experts), axis=0
        )
        aux_loss = cfg.num_experts * jnp.sum(me * ce)
        if cfg.router_z_coef:
            z = jax.nn.logsumexp(gate_logits, axis=-1)  # [tokens]
            aux_loss = aux_loss + cfg.router_z_coef * jnp.mean(z**2)

        # top-k dispatch with per-expert positional capacity
        combine = jnp.zeros((n_tokens, cfg.num_experts, capacity), dtype=jnp.float32)
        remaining = gate_probs
        used = jnp.zeros((cfg.num_experts,), dtype=jnp.int32)
        for _ in range(cfg.top_k):
            choice = jnp.argmax(remaining, axis=-1)                    # [tokens]
            prob = jnp.take_along_axis(remaining, choice[:, None], 1)[:, 0]
            onehot = jax.nn.one_hot(choice, cfg.num_experts, dtype=jnp.int32)
            # position of each token within its chosen expert's buffer
            pos_in_expert = (jnp.cumsum(onehot, axis=0) - onehot) + used[None, :]
            pos = jnp.sum(onehot * pos_in_expert, axis=-1)             # [tokens]
            keep = pos < capacity
            combine = combine + (
                (prob * keep)[:, None, None]
                * jax.nn.one_hot(choice, cfg.num_experts)[:, :, None]
                * jax.nn.one_hot(pos, capacity)[:, None, :]
            )
            used = used + jnp.sum(onehot * keep[:, None], axis=0)
            remaining = remaining * (1.0 - jax.nn.one_hot(choice, cfg.num_experts))

        dispatch = (combine > 0).astype(cfg.dtype)                     # [tokens, E, C]

        # expert computation over the stacked expert axis; sharding this axis
        # over an "experts" mesh axis yields all-to-all dispatch under pjit
        w1 = self.param(
            "w1", nn.initializers.normal(0.02), (cfg.num_experts, D, cfg.d_hidden)
        )
        w2 = self.param(
            "w2", nn.initializers.normal(0.02), (cfg.num_experts, cfg.d_hidden, D)
        )
        expert_in = jnp.einsum("nec,nd->ecd", dispatch, tokens.astype(cfg.dtype))
        h = nn.gelu(jnp.einsum("ecd,edh->ech", expert_in, w1.astype(cfg.dtype)))
        expert_out = jnp.einsum("ech,ehd->ecd", h, w2.astype(cfg.dtype))
        y = jnp.einsum("nec,ecd->nd", combine.astype(cfg.dtype), expert_out)

        return y.reshape(B, T, D).astype(x.dtype), aux_loss
