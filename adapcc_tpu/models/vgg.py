"""VGG: the reference's default DDP benchmark model (train_ddp.py:33 VGG16).

Conv-heavy with a huge classifier head — the gradient-bucket shapes that
drove the reference's chunk-size heuristic (log/model_bucket_info.txt lists
VGG16's 102.8M-float bucket).  NHWC layout, bf16-friendly.
"""

from __future__ import annotations

from typing import Tuple, Union

import flax.linen as nn
import jax.numpy as jnp

# layer specs: int = conv channels, "M" = maxpool (VGG16 = D configuration)
VGG16_CFG: Tuple[Union[int, str], ...] = (
    64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
    512, 512, 512, "M", 512, 512, 512, "M",
)
VGG11_CFG: Tuple[Union[int, str], ...] = (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M")


class VGG(nn.Module):
    cfg: Tuple[Union[int, str], ...] = VGG16_CFG
    num_classes: int = 10
    classifier_width: int = 4096
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        """``x [B, H, W, C]`` → logits ``[B, num_classes]``."""
        for i, spec in enumerate(self.cfg):
            if spec == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(int(spec), (3, 3), padding="SAME", dtype=self.dtype, name=f"conv_{i}")(x)
                x = nn.relu(x)
        x = x.reshape(x.shape[0], -1)
        x = nn.Dense(self.classifier_width, dtype=self.dtype, name="fc1")(x)
        x = nn.relu(x)
        x = nn.Dense(self.classifier_width, dtype=self.dtype, name="fc2")(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


def VGG16(**kw) -> VGG:
    return VGG(cfg=VGG16_CFG, **kw)


def VGG11(**kw) -> VGG:
    return VGG(cfg=VGG11_CFG, **kw)
