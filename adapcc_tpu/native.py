"""ctypes binding for the native schedule engine (``libadapcc_rt.so``).

Mirrors how the reference loads its native layer — ``CDLL('./communicator.so')``
(reference adapcc.py:17-20) — but the native code here is the *host-side*
schedule machinery (XML parse, round lowering, relay pruning, role algebra);
the device data plane stays XLA/Pallas.  Every entry point has an identical
pure-Python implementation, and :func:`available` gates usage so missing or
unbuilt native code degrades to Python silently.

Build: ``make native`` at the repo root.
"""

from __future__ import annotations

import ctypes
import os
from typing import List, Optional, Sequence

from adapcc_tpu.comm.relay import RelayRole
from adapcc_tpu.strategy.ir import CommRound

_LIB_NAMES = ("libadapcc_rt.so",)
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    _load_attempted = True
    candidates = [os.path.join(_repo_root(), n) for n in _LIB_NAMES]
    env = os.environ.get("ADAPCC_RT_PATH")
    if env:
        candidates.insert(0, env)
    for path in candidates:
        if not os.path.exists(path):
            continue
        try:
            lib = ctypes.CDLL(path)
            _bind_symbols(lib)
        except (OSError, AttributeError):
            # unloadable, or a stale build missing newer entry points —
            # skip it so available() degrades to the Python implementations
            continue
        _lib = lib
        break
    return _lib


def _bind_symbols(lib: ctypes.CDLL) -> None:
    lib.adapcc_parse_strategy.restype = ctypes.c_void_p
    lib.adapcc_parse_strategy.argtypes = [ctypes.c_char_p]
    lib.adapcc_free_strategy.argtypes = [ctypes.c_void_p]
    lib.adapcc_error.restype = ctypes.c_char_p
    lib.adapcc_error.argtypes = [ctypes.c_void_p]
    for fn in ("adapcc_world_size", "adapcc_num_trees"):
        getattr(lib, fn).restype = ctypes.c_int
        getattr(lib, fn).argtypes = [ctypes.c_void_p]
    lib.adapcc_tree_root.restype = ctypes.c_int
    lib.adapcc_tree_root.argtypes = [ctypes.c_void_p, ctypes.c_int]
    i32p = ctypes.POINTER(ctypes.c_int32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    for fn in ("adapcc_reduce_rounds", "adapcc_broadcast_rounds"):
        getattr(lib, fn).restype = ctypes.c_int
        getattr(lib, fn).argtypes = [ctypes.c_void_p, ctypes.c_int, i32p, i32p, ctypes.c_int, ctypes.c_int]
    for fn in ("adapcc_prune_reduce_rounds", "adapcc_prune_broadcast_rounds"):
        getattr(lib, fn).restype = ctypes.c_int
        getattr(lib, fn).argtypes = [ctypes.c_void_p, ctypes.c_int, u8p, i32p, i32p, ctypes.c_int, ctypes.c_int]
    lib.adapcc_relay_role.restype = ctypes.c_int
    lib.adapcc_relay_role.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int, u8p]
    lib.adapcc_synthesize_partrees.restype = ctypes.c_void_p
    lib.adapcc_synthesize_partrees.argtypes = [
        ctypes.c_char_p, i32p, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
        ctypes.c_int,
    ]
    lib.adapcc_tree_ip.restype = ctypes.c_char_p
    lib.adapcc_tree_ip.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]


def available() -> bool:
    return _load() is not None


class NativeStrategy:
    """A strategy parsed and lowered by the native engine."""

    def __init__(self, xml_text: Optional[str], _handle=None):
        lib = _load()
        if lib is None:
            raise RuntimeError("libadapcc_rt.so not built; run `make native`")
        self._lib = lib
        self._h = _handle if _handle is not None else lib.adapcc_parse_strategy(xml_text.encode())
        err = lib.adapcc_error(self._h)
        if err:
            msg = err.decode()
            lib.adapcc_free_strategy(self._h)
            self._h = None
            raise ValueError(f"native strategy failed: {msg}")

    @classmethod
    def synthesize_partrees(
        cls,
        ip_table: Sequence[str],
        local_rank0_list: Sequence[int],
        parallel_degree: int,
        bandwidth_graph: Sequence[Sequence[float]],
        latency_graph: Sequence[Sequence[float]],
    ) -> "NativeStrategy":
        """Native ParTrees synthesis (parity with
        :class:`adapcc_tpu.strategy.partrees.ParTrees.synthesize`)."""
        lib = _load()
        if lib is None:
            raise RuntimeError("libadapcc_rt.so not built; run `make native`")
        import numpy as np

        world = len(ip_table)
        if world == 0:
            raise ValueError("ip table is empty")
        masters = (ctypes.c_int32 * len(local_rank0_list))(*local_rank0_list)
        # marshal matrices through numpy buffers: per-element Python indexing
        # would cost O(world²) interpreter time per synthesis call
        dp = ctypes.POINTER(ctypes.c_double)
        flat_bw = np.ascontiguousarray(bandwidth_graph, dtype=np.float64)
        flat_lat = np.ascontiguousarray(latency_graph, dtype=np.float64)
        # shape check before raw pointers cross the boundary: a wrong-sized
        # matrix would be an out-of-bounds native read, not a clean error
        for name, m in (("bandwidth_graph", flat_bw), ("latency_graph", flat_lat)):
            if m.shape != (world, world):
                raise ValueError(f"{name} must be {world}x{world}, got {m.shape}")
        handle = lib.adapcc_synthesize_partrees(
            "\n".join(ip_table).encode(), masters, len(local_rank0_list),
            parallel_degree, flat_bw.ctypes.data_as(dp), flat_lat.ctypes.data_as(dp),
            world,
        )
        return cls(None, _handle=handle)

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.adapcc_free_strategy(self._h)
            self._h = None

    # -- queries ---------------------------------------------------------------

    @property
    def world_size(self) -> int:
        return self._lib.adapcc_world_size(self._h)

    @property
    def num_trees(self) -> int:
        return self._lib.adapcc_num_trees(self._h)

    def tree_root(self, t: int) -> int:
        return self._lib.adapcc_tree_root(self._h, t)

    def _rounds(self, fn, t: int, active: Optional[Sequence[int]] = None) -> List[CommRound]:
        max_edges = max(4 * self.world_size, 64)
        max_rounds = max_edges
        edges = (ctypes.c_int32 * (2 * max_edges))()
        offsets = (ctypes.c_int32 * (max_rounds + 1))()
        if active is not None:
            act = set(active)
            mask = (ctypes.c_uint8 * self.world_size)(
                *[1 if r in act else 0 for r in range(self.world_size)]
            )
            n = fn(self._h, t, mask, edges, offsets, max_edges, max_rounds)
        else:
            n = fn(self._h, t, edges, offsets, max_edges, max_rounds)
        if n < 0:
            raise RuntimeError("native round lowering failed (buffer or tree index)")
        out = []
        for i in range(n):
            es = tuple(
                (edges[2 * e], edges[2 * e + 1]) for e in range(offsets[i], offsets[i + 1])
            )
            out.append(CommRound(es))
        return out

    def reduce_rounds(self, t: int) -> List[CommRound]:
        return self._rounds(self._lib.adapcc_reduce_rounds, t)

    def broadcast_rounds(self, t: int) -> List[CommRound]:
        return self._rounds(self._lib.adapcc_broadcast_rounds, t)

    def prune_reduce_rounds(self, t: int, active: Sequence[int]) -> List[CommRound]:
        return self._rounds(self._lib.adapcc_prune_reduce_rounds, t, active)

    def prune_broadcast_rounds(self, t: int, active: Sequence[int]) -> List[CommRound]:
        return self._rounds(self._lib.adapcc_prune_broadcast_rounds, t, active)

    def to_strategy(self, chunk_bytes: Optional[int] = None):
        """Rebuild a Python :class:`~adapcc_tpu.strategy.ir.Strategy` from the
        native handle (parent edges recovered from the broadcast lowering), so
        natively synthesized strategies plug into the collective engine."""
        from adapcc_tpu.primitives import DEFAULT_CHUNK_BYTES
        from adapcc_tpu.strategy.ir import Strategy, Tree

        trees = []
        for t in range(self.num_trees):
            children: dict = {}
            ranks = {self.tree_root(t)}
            for rnd in self.broadcast_rounds(t):
                for parent, child in rnd.edges:
                    children.setdefault(parent, []).append(child)
                    ranks.update((parent, child))
            ips = {}
            for r in ranks:
                ip = self._lib.adapcc_tree_ip(self._h, t, r)
                if ip is not None:
                    ips[r] = ip.decode()
            trees.append(Tree(self.tree_root(t), children, ips))
        return Strategy(
            trees, self.world_size,
            DEFAULT_CHUNK_BYTES if chunk_bytes is None else chunk_bytes,
        )

    def relay_role(self, t: int, rank: int, active: Sequence[int]) -> RelayRole:
        act = set(active)
        mask = (ctypes.c_uint8 * self.world_size)(
            *[1 if r in act else 0 for r in range(self.world_size)]
        )
        bits = self._lib.adapcc_relay_role(self._h, t, rank, mask)
        if bits < 0:
            raise RuntimeError("native relay_role failed")
        return RelayRole(
            has_recv=bool(bits & 1),
            has_local=bool(bits & 2),
            has_kernel=bool(bits & 4),
            has_send=bool(bits & 8),
        )
