"""Overlapped gradient sync: pipeline per-bucket collectives behind compute.

AdapCC's core win is chunked, pipelined collectives that keep the wire busy
while other work proceeds (SURVEY §3.3: the DDP hook hands buckets to an
async relay as backward produces them); the baseline port computes the full
gradient, then syncs it — all communication time is exposed.  This module is
the static overlap schedule that software-pipelines gradient synchronization
*inside* the compiled step, in two shape-static, scan-friendly mechanisms:

1. **Microbatch-pipelined sync** (``"microbatch"``): in the trainer's
   accumulation ``lax.scan``, the carry holds the *previous* microbatch's
   gradient delta; the loop body dispatches that delta's allreduce and then
   runs the next microbatch's forward/backward — two independent subgraphs
   in one scan iteration, which XLA's async collectives and latency-hiding
   scheduler interleave.  Only the last delta's sync (the drain) has no
   compute left to hide behind.  Wire volume grows to ``accum`` full-size
   syncs (each delta is gradient-sized), so this mode trades bytes for
   overlap — the measured tuner, not the α-β model, decides whether that
   trade wins on a given fabric (:mod:`adapcc_tpu.tuner`).

2. **Per-bucket rolling sync** (``"bucket"``): the existing
   :class:`~adapcc_tpu.ddp.bucketing.BucketPlan` drives the new chunked
   engine entry points (:func:`adapcc_tpu.comm.engine.
   chunked_allreduce_shard` / :func:`~adapcc_tpu.comm.engine.
   chunked_psum_shard`): every bucket dispatches as an independent
   collective split at its per-bucket ``chunk_bytes`` (the reference's
   4 MB-chunk heuristic, commu.py:401-403 — previously computed and
   dropped), so XLA's async collectives interleave bucket chunks with the
   remaining compute (the optimizer tail, the scatter-back casts, the next
   scanned step).  Numerics are bitwise-identical to the unchunked sync:
   every element rides the same per-element reduction order, just in a
   smaller dispatch.

``ADAPCC_OVERLAP`` overrides the constructor-selected mode for sweeps —
the same env-beats-caller precedence as ``ADAPCC_RING_CHUNK_BYTES`` and
``ADAPCC_WIRE_DTYPE``; a malformed value raises instead of silently
falling back.  Pricing lives in :func:`adapcc_tpu.sim.cost_model.
overlapped_step_time`; the tuner's ``ddp_step`` cells carry the overlap
axis (docs/OVERLAP.md).
"""

from __future__ import annotations

import os
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from adapcc_tpu.comm.mesh import RANKS_AXIS
from adapcc_tpu.primitives import ReduceOp

#: env override for the overlap mode (off | microbatch | bucket)
OVERLAP_ENV = "ADAPCC_OVERLAP"

#: the schedulable overlap modes, risk order ("off" first so candidate
#: tie-breaks keep the non-overlapped plane)
OVERLAP_MODES = ("off", "bucket", "microbatch")


def resolve_overlap_mode(overlap: Optional[str] = None) -> str:
    """The overlap schedule actually in force: the ``ADAPCC_OVERLAP`` sweep
    override wins, then the caller's mode, then ``"off"``.  A malformed
    value raises — a typo silently falling back to the default would
    invalidate an overlap A/B (same policy as ADAPCC_RING_CHUNK_BYTES)."""
    env = os.environ.get(OVERLAP_ENV)
    if env is not None and env.strip():
        value = env.strip().lower()
        if value not in OVERLAP_MODES:
            raise ValueError(
                f"{OVERLAP_ENV}={env!r}: expected one of {OVERLAP_MODES}"
            )
        return value
    if overlap is None:
        return "off"
    if overlap not in OVERLAP_MODES:
        raise ValueError(
            f"overlap={overlap!r}: expected one of {OVERLAP_MODES}"
        )
    return overlap


# --------------------------------------------------------------------------- #
# mechanism 2: per-bucket rolling sync (device half; call inside shard_map)
# --------------------------------------------------------------------------- #


def rolling_bucket_sync(
    buckets: Sequence[jnp.ndarray],
    chunk_bytes: Sequence[int],
    active_mask: Optional[jnp.ndarray],
    *,
    mode: str,
    strategy: Any,
    axis_name: str = RANKS_AXIS,
    op: ReduceOp = ReduceOp.SUM,
) -> List[jnp.ndarray]:
    """Dispatch each bucket as an independent chunked collective honoring
    its per-bucket ``chunk_bytes`` (env-overridable inside the engine's
    chunked entry points).  ``mode`` picks the data plane the hook resolved:
    ``"psum"`` = masked XLA collectives, ``"schedule"`` = strategy-tree
    allreduce.  Values are bitwise-identical to the unchunked dispatch —
    only the collective granularity changes."""
    from adapcc_tpu.comm.engine import (
        chunked_allreduce_shard,
        chunked_psum_shard,
    )

    if len(buckets) != len(chunk_bytes):
        raise ValueError(
            f"{len(buckets)} buckets but {len(chunk_bytes)} chunk sizes — "
            "the bucket plan and its chunk table must describe one layout"
        )
    out: List[jnp.ndarray] = []
    for bucket, cb in zip(buckets, chunk_bytes):
        if mode == "psum":
            out.append(
                chunked_psum_shard(
                    bucket, active_mask, axis_name=axis_name, op=op,
                    chunk_bytes=cb, world=strategy.world_size,
                )
            )
        else:
            mask = (
                active_mask
                if active_mask is not None
                else jnp.ones((strategy.world_size,), dtype=jnp.bool_)
            )
            out.append(
                chunked_allreduce_shard(
                    bucket, mask, strategy, axis_name=axis_name, op=op,
                    chunk_bytes=cb,
                )
            )
    return out


# --------------------------------------------------------------------------- #
# mechanism 1: microbatch-pipelined sync (device half; call inside shard_map)
# --------------------------------------------------------------------------- #


def microbatch_pipelined_sync(
    vg: Callable,
    params: Any,
    model_state: Any,
    micro: Any,
    sync_fn: Callable[[Any], Any],
    accum: int,
) -> Tuple[jnp.ndarray, Any, Any]:
    """The pipelined accumulation scan (mechanism 1 of docs/OVERLAP.md).

    ``vg(params, model_state, mb) -> ((loss, new_model_state), grads)`` is
    one microbatch's forward/backward; ``micro`` is the
    ``[accum, B/accum, ...]`` microbatch stack; ``sync_fn`` is the hook's
    allreduce (mask already bound).  The scan carry holds the previous
    microbatch's raw delta: each iteration dispatches ``sync_fn(prev)``
    and *then* computes the current microbatch — independent subgraphs XLA
    overlaps — accumulating synced deltas in fp32.  After the scan one
    drain sync covers the final delta (the only exposed transfer).

    Returns ``(mean_loss_f32, synced_mean_grads_in_param_dtype,
    new_model_state)``.  Numerics: the synced mean equals the baseline's
    sync-of-accumulated-mean by linearity of the collective; only the
    fp32 accumulation *order* differs (sum of synced deltas vs sync of
    summed deltas), so parity holds to accumulation-order tolerance, not
    bitwise — the documented contract the parity test asserts.
    """
    if accum < 2:
        raise ValueError(
            f"microbatch pipelining needs accum >= 2, got {accum}: with a "
            "single microbatch there is no later compute to hide the sync "
            "behind (use overlap='bucket' or 'off')"
        )
    tm = jax.tree_util.tree_map
    mb0 = tm(lambda x: x[0], micro)
    rest = tm(lambda x: x[1:], micro)
    (loss0, ms), g0 = vg(params, model_state, mb0)
    zeros = tm(lambda p: jnp.zeros(p.shape, jnp.float32), params)

    def body(carry, mb):
        acc_l, acc_s, prev, ms = carry
        # the previous delta's collective and this microbatch's compute are
        # data-independent: XLA's async collectives run them concurrently
        synced_prev = sync_fn(prev)
        (loss, ms), g = vg(params, ms, mb)
        acc_s = tm(lambda a, s: a + s.astype(jnp.float32), acc_s, synced_prev)
        return (acc_l + loss.astype(jnp.float32), acc_s, g, ms), None

    # the carry seeds with ``ms`` — microbatch 0's *updated* model state —
    # so stateful losses see every microbatch sequentially (torch
    # grad-accum semantics, same contract as the sequential path)
    (loss_sum, acc_s, last, new_ms), _ = lax.scan(
        body, (loss0.astype(jnp.float32), zeros, g0, ms), rest
    )
    drained = sync_fn(last)  # the pipeline drain: the one exposed sync
    synced = tm(
        lambda a, d, p: ((a + d.astype(jnp.float32)) / accum).astype(p.dtype),
        acc_s, drained, params,
    )
    return loss_sum / accum, synced, new_ms


# --------------------------------------------------------------------------- #
# flat-vector chunk table (ZeRO-1 chunked reduce-scatter / all-gather)
# --------------------------------------------------------------------------- #


def even_chunk_bounds(total: int, n_chunks: int) -> List[Tuple[int, int]]:
    """``(offset, length)`` table splitting ``total`` elements into
    ``n_chunks`` near-equal contiguous chunks (remainder spread over the
    leading chunks) — the static split the ZeRO-1 chunked collectives and
    their parity tests share."""
    if total < 0:
        raise ValueError(f"total must be >= 0, got {total}")
    n = max(1, min(int(n_chunks), max(1, total)))
    base, rem = divmod(total, n)
    bounds: List[Tuple[int, int]] = []
    off = 0
    for i in range(n):
        length = base + (1 if i < rem else 0)
        bounds.append((off, length))
        off += length
    return bounds


def overlap_chunk_count(nbytes: int, chunk_bytes: Optional[int]) -> int:
    """How many independent collectives a ``nbytes`` payload splits into at
    ``chunk_bytes`` granularity (env-overridable via the ring chunk
    resolver — one precedence ladder for every chunk knob)."""
    from adapcc_tpu.comm.pallas_ring import resolve_chunk_bytes

    cb = resolve_chunk_bytes(chunk_bytes)
    return max(1, -(-int(nbytes) // cb))
