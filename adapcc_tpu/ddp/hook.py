"""Gradient-sync hook: the TPU analog of ``cuda_allreduce_hook``.

The reference registers a torch-DDP comm hook that, per gradient bucket,
negotiates the step's active set with the coordinator, sizes chunks, and
either runs the adaptive allreduce (active rank), skips it (BSP straggler),
or hands the bucket to an async relay replay (commu.py:385-435, SURVEY §3.3).

Under XLA the data plane must be one compiled program, so the hook splits
into the two halves the reference interleaves:

- **host half** (:meth:`GradSyncHook.negotiate`): once per step, before the
  jitted train step — talk to the coordinator (hook_fetch + update_relay)
  and produce the ``[world]`` active mask.  Runs in microseconds, off the
  device critical path (the reference pays the same ~1 ms gRPC cost,
  proto/latency_0.0.txt).

- **device half** (:meth:`GradSyncHook.sync`): inside the jitted step —
  bucket the gradient pytree, run the strategy allreduce per bucket with the
  active mask, scatter back.  AVG semantics over the active count, matching
  DDP gradient averaging.
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax.numpy as jnp
import numpy as np

from adapcc_tpu.comm.engine import allreduce_shard, masked_psum_shard
from adapcc_tpu.comm.mesh import RANKS_AXIS
from adapcc_tpu.ddp.bucketing import (
    BucketPlan,
    build_bucket_plan,
    flatten_to_buckets,
    unflatten_from_buckets,
)
from adapcc_tpu.primitives import ReduceOp
from adapcc_tpu.strategy.ir import Strategy


class GradSyncHook:
    def __init__(
        self,
        strategy: Strategy,
        axis_name: str = RANKS_AXIS,
        op: ReduceOp = ReduceOp.AVG,
        bucket_cap_mb: float = 100.0,
        use_xla_fastpath: bool = True,
        communicator: Optional[Any] = None,
        mode: str = "auto",
        compress: str = "off",
        error_feedback: bool = False,
        quant_block_size: int = 256,
        overlap: str = "off",
        trace: Optional[Any] = None,
        metrics: Optional[Any] = None,
    ) -> None:
        """``mode``: ``"psum"`` = per-leaf masked psum (one XLA collective per
        leaf — no bucketing copies, optimal on a flat ICI mesh and still
        honoring subset semantics); ``"schedule"`` = bucketed strategy-tree
        allreduce (the adaptive path for hierarchical topologies);
        ``"auto"`` = psum when fastpath is allowed and the strategy spans a
        single host group, schedule otherwise.

        ``compress`` names a wire codec from the quant registry
        (:mod:`adapcc_tpu.quant` — ``"off" | "bf16" | "int8"`` plus anything
        registered later), or ``"strategy"`` to adopt the synthesized
        ``Strategy.wire_dtype``; ``ADAPCC_WIRE_DTYPE`` overrides either.
        ``"bf16"`` casts gradients to bfloat16 for the wire (halving ICI/DCN
        bytes, the torch-DDP ``bf16_compress_hook`` analog) and back
        afterwards — accumulation then happens in bf16, adding ~bf16-eps
        relative error to the synced mean.  ``"int8"`` gives every
        contribution its block-wise quantized wire *value* (per-block fp32
        scales over ``quant_block_size`` elements, deterministic rounding)
        before the fp32 collective — the XLA-plane realization of the
        quantized allreduce (the ring engine moves actual int8 bytes; see
        docs/QUANT.md).  ``"off"`` keeps the gradient dtype end to end.

        ``error_feedback``: carry each rank's quantization error in a
        residual buffer folded into the next step's gradient (the
        :func:`adapcc_tpu.quant.error_feedback_step` loop) — drive it via
        :meth:`sync_error_feedback`; the trainer threads the buffer.

        ``overlap`` selects the sync schedule (docs/OVERLAP.md; resolved at
        construction, ``ADAPCC_OVERLAP`` overriding): ``"bucket"`` forces
        the bucketed path on either data plane and dispatches every bucket
        as independent chunked collectives honoring the plan's per-bucket
        ``chunk_bytes``; ``"microbatch"`` is a trainer-level schedule and
        leaves the hook's per-sync program unchanged.

        ``trace``/``metrics`` are optional observability sinks (a
        :class:`~adapcc_tpu.utils.observability.CollectiveTrace` /
        :class:`~adapcc_tpu.utils.observability.MetricsRegistry`): the
        first traced sync records the bucket plan — count, byte histogram,
        oversized leaves, resolved chunk sizes, and the model-predicted
        ``exposed_comm_s`` floor — into both.  When absent, an attached
        communicator's engine trace / metrics registry are used.
        """
        from adapcc_tpu.ddp.overlap import resolve_overlap_mode
        from adapcc_tpu.quant import get_codec

        if compress != "strategy":
            get_codec(compress)  # loud, lists the registered codecs
        if quant_block_size < 1:
            raise ValueError(
                f"quant_block_size must be >= 1, got {quant_block_size}"
            )
        self.error_feedback = error_feedback
        self.quant_block_size = quant_block_size
        self.strategy = strategy
        self.axis_name = axis_name
        self.op = op
        self.bucket_cap_mb = bucket_cap_mb
        self.use_xla_fastpath = use_xla_fastpath
        self.communicator = communicator
        self.mode = mode
        self.compress = compress
        self.overlap = resolve_overlap_mode(overlap)
        self._trace = trace
        self._metrics = metrics
        self._plan: Optional[BucketPlan] = None
        self.recorded_buckets: List[tuple] = []  # (size, chunk_bytes) per bucket

    def _resolved_mode(self) -> str:
        if self.mode != "auto":
            return self.mode
        if not self.use_xla_fastpath:
            return "schedule"
        ips = set()
        for t in self.strategy.trees:
            ips |= set(t.ips.values())
        single_host = len(ips) <= 1
        return "psum" if single_host else "schedule"

    # -- host half -------------------------------------------------------------

    def negotiate(self, step: int) -> jnp.ndarray:
        """Coordinator round-trip → active mask for this step.

        Mirrors the reference's per-step sequence: ``update_relay(step)``
        (controller heartbeat) + first-bucket ``hook_fetch`` (rent-or-buy
        freeze).  Without a communicator/coordinator, everyone is active.
        """
        world = self.strategy.world_size
        if self.communicator is None or self.communicator._hooker is None:
            return jnp.ones((world,), dtype=jnp.bool_)
        self.communicator.update_relay(step)
        active_processes = self.communicator.hook_ready(step)
        # the coordinator speaks process ranks; the mask indexes chip ranks
        active_chips = self.communicator.chips_of_processes(active_processes)
        mask = np.zeros((world,), dtype=bool)
        mask[[r for r in active_chips if 0 <= r < world]] = True
        return jnp.asarray(mask)

    # -- device half -----------------------------------------------------------

    def effective_compress(self) -> str:
        """The wire codec this hook runs: ``ADAPCC_WIRE_DTYPE`` override >
        (``compress="strategy"`` → the strategy's synthesized wire_dtype) >
        the constructor's ``compress`` — the engine ring's precedence
        ladder, so hook and engine can never disagree about the codec a
        strategy asked for."""
        from adapcc_tpu.quant import resolve_wire_dtype

        value = (
            self.strategy.wire_dtype
            if self.compress == "strategy"
            else self.compress
        )
        return resolve_wire_dtype(value)

    def _codec_apply(self, g: jnp.ndarray) -> jnp.ndarray:
        from adapcc_tpu.quant import get_codec

        return get_codec(self.effective_compress()).apply(
            g, self.quant_block_size
        )

    def sync(self, grads: Any, active_mask: Optional[jnp.ndarray]) -> Any:
        """Allreduce a gradient pytree; call inside shard_map.

        ``active_mask=None`` means *statically* full-world (no coordinator
        attached): masking and the active-count divide fold away at trace
        time, leaving exactly the plain-DDP program.
        """
        import jax as _jax

        codec = self.effective_compress()
        if codec == "bf16":
            orig_dtypes = _jax.tree_util.tree_map(lambda g: g.dtype, grads)
            wire = _jax.tree_util.tree_map(
                lambda g: g.astype(jnp.bfloat16), grads
            )
            synced = self._sync_impl(wire, active_mask)
            return _jax.tree_util.tree_map(
                lambda s, dt: s.astype(dt), synced, orig_dtypes
            )
        if codec != "off":
            # quantized wire values, fp32 accumulation: each contribution is
            # replaced by its decode(encode(·)) before the collective — the
            # value contract the quantized ring engine also honors
            grads = _jax.tree_util.tree_map(self._codec_apply, grads)
        return self._sync_impl(grads, active_mask)

    def sync_error_feedback(
        self, grads: Any, residual: Any, active_mask: Optional[jnp.ndarray]
    ) -> tuple:
        """Error-feedback sync; call inside shard_map.  Returns ``(synced,
        new_residual)``: the wire carries ``codec(grads + residual)`` and
        the per-rank quantization error is banked for the next step, so no
        gradient mass is ever dropped (codec ``"off"`` keeps the residual
        identically zero and reduces to :meth:`sync`).

        Dtype contract: the residual accumulates in fp32 (a narrow bank
        would lose the very mass it defers), but the wire and the synced
        result keep each gradient leaf's own dtype — the fp32 compensation
        must not silently widen a bf16 program's collective operands, and
        the residual absorbs the cast-back error along with the codec's.
        """
        import jax as _jax

        tm = _jax.tree_util.tree_map
        orig_dtypes = tm(lambda g: g.dtype, grads)
        compensated = tm(
            lambda g, r: g.astype(jnp.float32) + r, grads, residual
        )
        wire = tm(
            lambda c, dt: self._codec_apply(c).astype(dt),
            compensated, orig_dtypes,
        )
        new_residual = tm(
            lambda c, w: c - w.astype(jnp.float32), compensated, wire
        )
        synced = self._sync_impl(wire, active_mask)
        return tm(lambda s, dt: s.astype(dt), synced, orig_dtypes), new_residual

    def resolved_chunk_bytes(self) -> List[int]:
        """The per-bucket chunk sizes the dispatch actually honors:
        ``ADAPCC_RING_CHUNK_BYTES`` override > the plan's per-bucket
        heuristic — the chunk-knob precedence every other chunk consumer
        follows.  Requires a recorded plan (first traced sync)."""
        from adapcc_tpu.comm.pallas_ring import resolve_chunk_bytes

        if self._plan is None:
            raise ValueError(
                "no recorded bucket plan yet: resolved_chunk_bytes() reads "
                "the table the first traced sync records"
            )
        return [resolve_chunk_bytes(c) for c in self._plan.chunk_bytes]

    def _record_plan(self, plan: BucketPlan, data_plane: str) -> None:
        """Bucket-plan observability (host side, once per trace): counts and
        the byte histogram into the metrics registry, the full table — with
        the resolved chunk sizes and the cost model's predicted
        ``exposed_comm_s`` floor for the active overlap schedule — into the
        dispatch trace."""
        metrics = self._metrics
        if metrics is None and self.communicator is not None:
            metrics = getattr(self.communicator, "metrics", None)
        trace = self._trace
        if trace is None and self.communicator is not None:
            trace = getattr(
                getattr(self.communicator, "engine", None), "trace", None
            )
        if metrics is None and trace is None:
            return
        if metrics is not None:
            metrics.gauge("bucket_plan.num_buckets", plan.num_buckets)
            metrics.gauge("bucket_plan.total_bytes", plan.total_bytes)
            if plan.oversized_leaves:
                metrics.incr(
                    "bucket_plan.oversized_leaves", plan.oversized_leaves
                )
            for b in plan.bucket_bytes:
                # the byte histogram rides the timing reservoir: p50/p99
                # of bucket sizes in the snapshot, O(1) memory
                metrics.observe("bucket_plan.bucket_bytes", float(b))
        if trace is not None:
            from adapcc_tpu.sim.calibrate import load_or_default
            from adapcc_tpu.sim.cost_model import (
                bottleneck_ring_coeffs,
                exposed_comm_floor_s,
            )

            world = self.strategy.world_size
            coeffs = bottleneck_ring_coeffs(load_or_default(world=world), world)
            wd = self.effective_compress()
            trace.record(
                "grad_sync",
                f"{data_plane}[{self.overlap}]",
                plan.total_bytes,
                buckets=plan.num_buckets,
                bucket_bytes=list(plan.bucket_bytes),
                plan_chunk_bytes=list(plan.chunk_bytes),
                chunk_bytes=self.resolved_chunk_bytes(),
                oversized_leaves=plan.oversized_leaves,
                overlap=self.overlap,
                wire_dtype=wd,
                exposed_comm_s=exposed_comm_floor_s(
                    world, plan.total_bytes, coeffs,
                    overlap=self.overlap,
                    bucket_bytes=plan.bucket_bytes,
                    wire_dtype=wd,
                ),
            )

    def _bucket_plan(self, grads: Any, data_plane: str) -> BucketPlan:
        if self._plan is None:
            # first trace records the bucket table (the analog of the
            # reference's step-0/1 record phase, commu.py:409-418)
            self._plan = build_bucket_plan(grads, self.bucket_cap_mb)
            self.recorded_buckets = [
                (s, c) for s, c in zip(self._plan.bucket_sizes, self._plan.chunk_bytes)
            ]
            self._record_plan(self._plan, data_plane)
        return self._plan

    def _sync_impl(self, grads: Any, active_mask: Optional[jnp.ndarray]) -> Any:
        import jax as _jax
        from jax import lax as _lax

        data_plane = self._resolved_mode()
        if self.overlap == "bucket":
            # per-bucket rolling sync: the bucket plan drives independent
            # chunked collectives on whichever data plane resolved —
            # bitwise-identical values, finer dispatch granularity so
            # XLA's async collectives interleave buckets with remaining
            # compute (docs/OVERLAP.md §2)
            from adapcc_tpu.ddp.overlap import rolling_bucket_sync

            mask = active_mask
            if data_plane != "psum" and mask is None:
                mask = jnp.ones((self.strategy.world_size,), dtype=jnp.bool_)
            plan = self._bucket_plan(grads, data_plane)
            buckets = flatten_to_buckets(plan, grads)
            synced = rolling_bucket_sync(
                buckets, plan.chunk_bytes, mask,
                mode=data_plane, strategy=self.strategy,
                axis_name=self.axis_name, op=self.op,
            )
            return unflatten_from_buckets(plan, synced)
        if data_plane == "psum":
            if active_mask is None:
                world = self.strategy.world_size

                def full(g):
                    s = _lax.psum(g, self.axis_name)
                    return s / world if self.op is ReduceOp.AVG else s

                return _jax.tree_util.tree_map(full, grads)
            return _jax.tree_util.tree_map(
                lambda g: masked_psum_shard(g, active_mask, self.axis_name, self.op),
                grads,
            )
        if active_mask is None:
            active_mask = jnp.ones((self.strategy.world_size,), dtype=jnp.bool_)
        plan = self._bucket_plan(grads, data_plane)
        buckets = flatten_to_buckets(plan, grads)
        synced = [
            allreduce_shard(
                b, active_mask, self.strategy, axis_name=self.axis_name, op=self.op
            )
            for b in buckets
        ]
        return unflatten_from_buckets(plan, synced)

    def sync_deferred(
        self, grads: Any, deferred: Any, active_mask: jnp.ndarray
    ) -> tuple:
        """Async (non-BSP) relay sync; call inside shard_map.

        The reference's non-BSP mode replays a straggler's recorded buckets
        through relay ranks so its gradients still land
        (commu.py:160-170,427-431 + run.cu updateActive).  Under one SPMD
        program the replay becomes a carried per-rank buffer: a rank masked
        out of this step banks ``grads + deferred`` locally and contributes
        the accumulated sum at its next active step, when the masked
        allreduce folds it into the average.  Returns
        ``(synced, new_deferred)``; active ranks leave with a cleared buffer.
        """
        import jax as _jax
        from jax import lax as _lax

        contrib = _jax.tree_util.tree_map(lambda g, d: g + d, grads, deferred)
        synced = self.sync(contrib, active_mask)
        my_active = active_mask[_lax.axis_index(self.axis_name)]
        new_deferred = _jax.tree_util.tree_map(
            lambda c: jnp.where(my_active, jnp.zeros_like(c), c), contrib
        )
        return synced, new_deferred

    def reset_plan(self) -> None:
        """Drop the recorded bucket table (model structure changed)."""
        self._plan = None
        self.recorded_buckets = []
