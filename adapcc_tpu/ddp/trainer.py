"""DDP trainer: jitted data-parallel train step with adaptive gradient sync.

The TPU-shaped equivalent of the reference's training template
(train_ddp.py:30-58): model replicated, batch sharded over the world mesh
axis, gradients synced by the :class:`GradSyncHook` (strategy allreduce with
relay masking), optimizer step applied identically everywhere.  The whole
step — forward, backward, sync, update — is one ``shard_map`` program under
``jit``; the per-step coordinator negotiation stays on the host and feeds in
only a ``[world]`` active mask, so relay decisions never recompile.

``reconstruct_topology`` parity: calling :meth:`rebuild` with a new strategy
recompiles the step against the re-synthesized schedule (the analog of
tearing down and re-creating transmission contexts, adapcc.py:63-67).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax.sharding import Mesh, PartitionSpec as P

from adapcc_tpu.comm.mesh import RANKS_AXIS
from adapcc_tpu.ddp.hook import GradSyncHook
from adapcc_tpu.strategy.ir import Strategy


@struct.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jnp.ndarray
    # non-gradient model collections (e.g. BatchNorm ``batch_stats``),
    # updated by the loss when the trainer runs in ``stateful_loss`` mode;
    # the default empty tuple adds no pytree leaves, so stateless trainers
    # and old checkpoints are unaffected
    model_state: Any = ()

    @classmethod
    def create(
        cls,
        params: Any,
        tx: optax.GradientTransformation,
        model_state: Any = (),
    ) -> "TrainState":
        return cls(
            params=params,
            opt_state=tx.init(params),
            step=jnp.zeros((), jnp.int32),
            model_state=model_state,
        )


class DDPTrainer:
    """Builds and caches the compiled data-parallel train step.

    ``loss_fn(params, batch) -> scalar`` is evaluated per rank on that rank's
    batch shard; everything else is the trainer's business.  With
    ``stateful_loss=True`` the contract becomes ``loss_fn(params,
    model_state, batch) -> (scalar, new_model_state)`` — non-gradient model
    collections (BatchNorm running stats) ride in ``TrainState.model_state``.
    """

    def __init__(
        self,
        loss_fn: Callable[[Any, Any], jnp.ndarray],
        tx: optax.GradientTransformation,
        mesh: Mesh,
        strategy: Strategy,
        axis_name: str = RANKS_AXIS,
        bucket_cap_mb: float = 100.0,
        use_xla_fastpath: bool = True,
        communicator: Optional[Any] = None,
        # off by default: donation deletes the caller's input state buffers,
        # which surprises library users; training loops that own their state
        # should turn it on for in-place updates
        donate_state: bool = False,
        sync_mode: str = "auto",
        measure_gns: bool = False,
        # BSP mode (reference is_bsp, commu.py:107): a straggler's gradients
        # are dropped from its missed step.  bsp=False is the async relay
        # mode — stragglers bank their gradients in a per-rank deferred
        # buffer that folds into their next active step's allreduce
        # (commu.py:160-170, 427-431).
        bsp: bool = True,
        # force the compiled step to take a runtime active mask even without
        # a communicator (workloads injecting their own skew signal; tests)
        dynamic_mask: Optional[bool] = None,
        # gradient accumulation: split each rank's batch shard into this many
        # microbatches, scanned inside the compiled step with fp32 gradient
        # accumulation — same math as the full batch (for mean losses), peak
        # activation memory divided by accum_steps
        accum_steps: int = 1,
        # ZeRO-1 optimizer sharding (parallel/fsdp.py) composed with the
        # adaptive sync: the hook's strategy/relay allreduce produces the
        # synced gradient, then each rank updates only its flat [N/world]
        # optimizer shard and all-gathers the new params — relay tolerance
        # and 1/world optimizer memory in ONE compiled program.  States come
        # from :meth:`init_state` (not TrainState.create).
        zero1: bool = False,
        # zero1's param all-gather rides the Pallas ICI ring kernel instead
        # of XLA's (the hand-tuned data plane); shards become VMEM-tile
        # aligned in the ring's chunk ownership — see Zero1Optimizer(ring=)
        zero1_ring: bool = False,
        # ring staging granularity (strategy plane's synthesized
        # chunk_bytes; None = default).  Payloads above it stream through
        # fixed HBM→VMEM staging instead of living VMEM-resident
        zero1_ring_chunk_bytes: Optional[int] = None,
        # redundant ZeRO-1 shard placement (elastic/redundancy.py,
        # docs/RECOVERY.md): replicate each rank's optimizer shard to this
        # many ring-neighbor holders after every step, piggybacked on the
        # post-step all-gather window, so a dead rank's shard is repaired
        # from its in-fabric replica instead of a checkpoint reload.
        # None = the ADAPCC_SHARD_REPLICAS env funnel (default 0 = off);
        # requires zero1=True (there is no single-owner state otherwise)
        shard_replicas: Optional[int] = None,
        # gradient-sync wire codec (quant registry: "off" | "bf16" | "int8",
        # or "strategy" to adopt the synthesized Strategy.wire_dtype).
        # "bf16" halves wire bytes (torch bf16_compress_hook analog, ~bf16-
        # eps error on the synced mean); "int8" quantizes block-wise with
        # per-block fp32 scales (docs/QUANT.md)
        grad_compress: str = "off",
        # carry each rank's quantization error into the next step's gradient
        # (adapcc_tpu.quant error-feedback loop): closes the deterministic-
        # rounding accuracy gap of int8.  The residual rides the compiled
        # step as a per-rank [world, ...] buffer, exactly like the async
        # relay bank; requires BSP mode (the deferred bank and the residual
        # would otherwise double-carry the same missed-gradient mass)
        error_feedback: bool = False,
        # stateful losses carry non-gradient model collections (BatchNorm
        # running stats): ``loss_fn(params, model_state, batch) -> (loss,
        # new_model_state)``, with the state riding in
        # ``TrainState.model_state``.  The state is compiled replicated, so
        # on a multi-rank mesh the loss must produce cross-rank identical
        # state — BatchNorm with ``axis_name`` set (SyncBN) does; unsynced
        # per-rank statistics would silently diverge from the spec.
        # Relay/masked steps: the active mask gates GRADIENT sync only; the
        # SyncBN pmean still averages every rank's batch, by design —
        # a straggler's forward ran on real data, so its activation
        # statistics are sound even when its late gradients are dropped,
        # and full-axis stats stay bit-identical across ranks (a masked
        # pmean would fork per-rank state and violate the replication spec).
        stateful_loss: bool = False,
        # measurement-driven tuning (adapcc_tpu/tuner): record each step's
        # dispatch walltime into the tuning database under the executed
        # (wire codec, ring chunk) cell, and every ``tune_every`` steps let
        # the policy re-choose the gradient-sync codec — the trainer adopts
        # a winning challenger by recompiling with the new codec (hysteresis
        # in the policy keeps that rare).  ADAPCC_TUNER=off still disables
        # everything globally; an attached communicator's tuner is reused so
        # engine dispatches and step timings share one database.
        tune: bool = False,
        tuner: Optional[Any] = None,
        tune_every: int = 16,
        # overlapped gradient sync (adapcc_tpu/ddp/overlap, docs/OVERLAP.md;
        # ADAPCC_OVERLAP overrides, resolved at construction):
        #   "off"        — compute the full gradient, then sync (baseline);
        #   "bucket"     — per-bucket rolling sync: every bucket dispatches
        #                  as independent chunked collectives honoring the
        #                  plan's per-bucket chunk_bytes, so XLA's async
        #                  collectives interleave them with remaining
        #                  compute.  Bitwise-identical gradients;
        #   "microbatch" — pipeline each microbatch delta's allreduce
        #                  behind the next microbatch's forward/backward in
        #                  the accumulation scan (requires accum_steps >= 2,
        #                  BSP, no error_feedback/measure_gns); parity to
        #                  accumulation-order tolerance, accum x wire bytes.
        overlap: str = "off",
    ) -> None:
        self.loss_fn = loss_fn
        self.stateful_loss = stateful_loss
        # one internal signature for both modes: (params, ms, batch) -> (loss, ms)
        if stateful_loss:
            self._loss3 = loss_fn
        else:
            self._loss3 = lambda p, ms, b: (loss_fn(p, b), ms)
        self.tx = tx
        self.mesh = mesh
        self.axis_name = axis_name
        self.donate_state = donate_state
        if accum_steps < 1:
            raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
        self.accum_steps = accum_steps
        self.zero1 = zero1
        if zero1_ring and not zero1:
            raise ValueError("zero1_ring=True requires zero1=True")
        self.zero1_ring = zero1_ring
        self.zero1_ring_chunk_bytes = zero1_ring_chunk_bytes
        from adapcc_tpu.elastic.redundancy import shard_replicas as _replicas

        # env > explicit arg > off (the chunk-bytes precedence ladder);
        # resolved eagerly so a malformed env var dies at construction
        self.shard_replicas = _replicas(
            default=0 if shard_replicas is None else int(shard_replicas)
        )
        if self.shard_replicas and not zero1:
            raise ValueError(
                "shard_replicas > 0 requires zero1=True: replicated DDP "
                "state has no single-owner optimizer shard to replicate "
                "(every rank already holds everything)"
            )
        #: the in-fabric replica set (built at init_state when armed)
        self.replica_store: Optional[Any] = None
        if error_feedback and not bsp:
            raise ValueError(
                "error_feedback=True requires BSP mode: the async relay "
                "bank already defers gradient mass, and layering the "
                "quantization residual on top would double-carry it"
            )
        self.error_feedback = error_feedback
        from adapcc_tpu.ddp.overlap import resolve_overlap_mode

        self.overlap = resolve_overlap_mode(overlap)
        if self.overlap == "microbatch":
            # guard rails for the pipelined scan — each incompatibility
            # would silently change semantics, so all reject at
            # construction (the bsp/error-feedback precedent above):
            if accum_steps < 2:
                raise ValueError(
                    "overlap='microbatch' needs accum_steps >= 2: with one "
                    "microbatch there is no later compute to hide the sync "
                    "behind (use overlap='bucket')"
                )
            if not bsp:
                raise ValueError(
                    "overlap='microbatch' requires BSP mode: the async "
                    "relay's deferred bank folds into ONE sync per step, "
                    "which the per-microbatch pipeline would re-sync "
                    "accum times"
                )
            if error_feedback:
                raise ValueError(
                    "overlap='microbatch' with error_feedback=True would "
                    "apply the codec (and bank its residual) per "
                    "microbatch delta — a different quantization loop than "
                    "the one the residual compensates; use "
                    "overlap='bucket' (residual threads unchanged) or "
                    "drop error_feedback"
                )
            if measure_gns:
                raise ValueError(
                    "overlap='microbatch' never materializes the unsynced "
                    "accumulated gradient the GNS estimator contrasts; "
                    "use overlap='bucket' or drop measure_gns"
                )
        self.hook = GradSyncHook(
            strategy,
            axis_name=axis_name,
            bucket_cap_mb=bucket_cap_mb,
            use_xla_fastpath=use_xla_fastpath,
            communicator=communicator,
            mode=sync_mode,
            compress=grad_compress,
            error_feedback=error_feedback,
            overlap=self.overlap,
        )
        if error_feedback and self.hook.effective_compress() == "off":
            # the residual of a no-op codec is provably zero, but the bank
            # would still thread (and donate) a world-sized fp32 copy of
            # every param through each compiled step
            raise ValueError(
                "error_feedback=True with an 'off' wire codec banks an "
                "identically-zero residual at world x params x 4 bytes per "
                "step; pass grad_compress='int8' (or 'strategy' / set "
                "ADAPCC_WIRE_DTYPE) or drop error_feedback"
            )
        self.bsp = bsp
        self._dynamic_mask = (
            dynamic_mask
            if dynamic_mask is not None
            else (communicator is not None or not bsp)
        )
        if not bsp and not self._dynamic_mask:
            raise ValueError("async relay (bsp=False) needs a runtime active mask")
        if communicator is not None and not self._dynamic_mask:
            raise ValueError(
                "a coordinator-attached trainer must compile a dynamic-mask "
                "step: dynamic_mask=False would silently discard the "
                "negotiated active set"
            )
        self._deferred: Optional[Any] = None
        self._residual: Optional[Any] = None  # error-feedback bank
        self._bank_dirty = False  # some rank holds banked (deferred) grads
        self._coord_calibrated = False
        self._compiled: Optional[Callable] = None
        self._scan_cache: dict = {}  # ("scan", n_steps) → compiled program
        # elastic plan failover (adapcc_tpu.elastic, docs/ELASTIC.md):
        # compiled step programs keyed by the strategy fingerprint they were
        # traced under.  prewarm() AOT-compiles a standby strategy's step;
        # adopt_strategy() then swaps to it as a dispatch-time cache-key
        # switch — the training-loop twin of the engine's standby plan cache
        self._program_cache: dict = {}  # fingerprint → compiled step
        self._host_step = 0
        # supervised mode (docs/SUPERVISOR.md): when an out-of-band
        # Supervisor is attached, step() pulls its last ACTUATED
        # contribution mask instead of negotiating — membership authority
        # leaves the training loop entirely
        self._supervisor = None
        # optional gradient-noise-scale measurement (units-test/get_gns.py):
        # the per-rank vs allreduced gradient norms fall out of the sync step
        # for free; the estimator is created at the first step, when the
        # per-rank batch size is known
        if measure_gns and mesh.devices.size < 2:
            raise ValueError(
                "measure_gns needs a multi-device mesh: the estimator contrasts "
                "per-rank (small-batch) vs allreduced (big-batch) gradients"
            )
        self.measure_gns = measure_gns
        self._gns: Optional[Any] = None
        self._gns_pending: list = []
        self._zero1_opt: Optional[Any] = None
        # -- autotuning state --------------------------------------------------
        if tune_every < 1:
            raise ValueError(f"tune_every must be >= 1, got {tune_every}")
        self.tune_every = tune_every
        if tune and tuner is None:
            tuner = getattr(communicator, "tuner", None)
        if tune and tuner is None:
            from adapcc_tpu.tuner import CollectiveTuner

            tuner = CollectiveTuner.for_mesh(mesh)
        if tune and tuner.explicit_mode is None:
            # tune=True is an explicit opt-in: with ADAPCC_TUNER unset the
            # tuner must actually choose — for the per-step codec AND the
            # Zero1Optimizer chunk gate (which reads tuner.choosing).  A
            # caller-pinned mode (e.g. an explicit record-only tuner) is
            # respected; the env still overrides either way.
            tuner = tuner.with_mode("choose")
        self.tune = tune
        self.tuner = tuner if tune else None
        # the overlap schedules THIS trainer can legally compile — the
        # tuner's ddp_step grid is narrowed to these so the explorer never
        # pins on a cell the trainer cannot run (the error-feedback/'off'
        # codec precedent)
        modes = ["off", "bucket"]
        if (
            accum_steps >= 2
            and bsp
            and not error_feedback
            and not measure_gns
        ):
            modes.append("microbatch")
        self._overlap_modes = tuple(modes)
        self._grad_bytes: Optional[float] = None
        # warmup-discard token: bumped on every recompile so the first step
        # of each compiled program (which pays tracing + XLA compile) never
        # lands in the database as a steady-state sample
        self._build_gen = 0

    def _tuning(self) -> bool:
        """Is per-step tuning live right now?  ``tune=True`` opts the
        trainer in (its tuner view defaults to choose, see ``__init__``);
        ``ADAPCC_TUNER=off`` still kills it globally (same contract as the
        engine)."""
        return self.tune and self.tuner is not None and self.tuner.recording

    # -- step program ----------------------------------------------------------

    def _zero1_overlap(self) -> str:
        """The Zero1Optimizer schedule the trainer's overlap mode implies:
        any overlapped trainer schedule also chunks the zero1 RS/AG pair
        (the Pallas ring streams its own chunks, so the ring path keeps
        one chunking plane).  One definition for construction AND tuner
        adoption — the two must never disagree."""
        return (
            "bucket"
            if self.overlap != "off" and not self.zero1_ring
            else "off"
        )

    def init_state(self, params: Any, model_state: Any = ()) -> TrainState:
        """Build the trainer's state: replicated optax state normally, the
        ZeRO-1 flat master + sharded optimizer state when ``zero1=True``."""
        if not self.zero1:
            return TrainState.create(params, self.tx, model_state=model_state)
        from adapcc_tpu.parallel.fsdp import Zero1Optimizer

        opt = self._zero1_opt = Zero1Optimizer(
            self.tx, self.mesh, self.axis_name, ring=self.zero1_ring,
            ring_chunk_bytes=self.zero1_ring_chunk_bytes,
            tuner=self.tuner,
            overlap=self._zero1_overlap(),
        )
        master, opt_state = opt.init(params)
        if self.shard_replicas:
            from adapcc_tpu.elastic.redundancy import ShardReplicaStore

            self.replica_store = ShardReplicaStore(
                self.mesh.shape[self.axis_name],
                ips=self.hook.strategy.trees[0].ips,
                replicas=self.shard_replicas,
            )
        if self.zero1_ring_chunk_bytes is None:
            # adopt the optimizer's (possibly tuner-chosen) staging
            # granularity so the step program and the optimizer execute the
            # same ring plan
            self.zero1_ring_chunk_bytes = opt.ring_chunk_bytes
        return TrainState(
            params=params,
            opt_state=(master, opt_state),
            step=jnp.zeros((), jnp.int32),
            model_state=model_state,
        )

    def checkpoint_extra(self, extra: Optional[dict] = None) -> dict:
        """``TrainCheckpointState.extra`` payload for this trainer's state.

        In ZeRO-1 mode it stamps the optimizer's layout tag (ring/world/
        align), which ``checkpoint.py``'s layout guard enforces on every
        load — a resume with ``--zero1-ring`` flipped fails loudly instead
        of silently loading a chunk-permuted master."""
        if not self.zero1:
            return dict(extra or {})
        if self._zero1_opt is None:
            raise ValueError(
                "call init_state(params) before checkpoint_extra(): the "
                "layout tag records the constructed optimizer's geometry"
            )
        return self._zero1_opt.checkpoint_extra(extra)

    def _check_state(self, state: TrainState) -> None:
        """Catch the common zero1 misuse (TrainState.create's replicated
        optax state) before it dies as a cryptic shard_map spec error."""
        if not self.zero1:
            return
        world = self.mesh.shape[self.axis_name]
        opt = state.opt_state
        ok = (
            isinstance(opt, tuple)
            and len(opt) == 2
            and getattr(opt[0], "ndim", 0) == 2
            and opt[0].shape[0] == world
        )
        if not ok:
            raise ValueError(
                "zero1=True needs the sharded (master [world, N/world], opt "
                "shard) state from trainer.init_state(params) — got a "
                "replicated optax state (TrainState.create?)"
            )

    def _state_spec(self):
        """shard_map pytree-prefix spec for TrainState: everything
        replicated, except the ZeRO-1 ``(master, opt shard)`` pair whose
        leading ``[world]`` dim shards over the axis."""
        opt_spec = P(self.axis_name) if self.zero1 else P()
        return TrainState(
            params=P(), opt_state=opt_spec, step=P(), model_state=P()
        )

    def _apply_synced(
        self, state: TrainState, synced: Any, model_state: Any = None
    ) -> TrainState:
        """Optimizer tail shared by every step variant: one change to the
        update rule applies to step() and scan_steps() alike.

        Runs inside the shard_map body.  ZeRO-1: the synced gradient is
        replicated (the hook allreduced it), so this rank's flat slice is a
        free local read; the optax update touches only the [N/world] shard
        and one all-gather rebuilds the replicated params.
        """
        if model_state is None:
            model_state = state.model_state
        if not self.zero1:
            updates, opt_state = self.tx.update(synced, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            return TrainState(
                params=params,
                opt_state=opt_state,
                step=state.step + 1,
                model_state=model_state,
            )

        from adapcc_tpu.parallel.fsdp import (
            _flatten,
            _flatten_meta,
            local_grad_shard,
            zero1_apply_shard,
        )

        world = self.mesh.shape[self.axis_name]
        if self.zero1_ring:
            from adapcc_tpu.comm.pallas_ring import _tile_elems

            align = _tile_elems(jnp.float32)
            ring_interpret = jax.devices()[0].platform != "tpu"
        else:
            align, ring_interpret = 1, False
        meta = _flatten_meta(state.params, world, align)
        master, opt_state = state.opt_state  # [1, L] / [1, ...] per shard
        master = master[0]
        opt_state = jax.tree_util.tree_map(lambda x: x[0], opt_state)
        # the hook already allreduced: every rank holds the same synced
        # grads, so its slice is a free local read (ring ownership = offset 1)
        g_shard = local_grad_shard(
            _flatten(synced, meta), meta, world, self.axis_name,
            offset=1 if self.zero1_ring else 0,
        )
        overlap_chunks = (
            self._zero1_opt.overlap_chunks(meta.padded // world)
            if self._zero1_opt is not None
            else 1
        )
        master, opt_state, params = zero1_apply_shard(
            self.tx, master, opt_state, g_shard, meta, self.axis_name,
            ring=self.zero1_ring, ring_interpret=ring_interpret,
            ring_chunk_bytes=self.zero1_ring_chunk_bytes,
            overlap_chunks=overlap_chunks,
        )
        return TrainState(
            params=params,
            opt_state=(
                master[None],
                jax.tree_util.tree_map(lambda x: x[None], opt_state),
            ),
            step=state.step + 1,
            model_state=model_state,
        )

    def _value_and_grad(self, params: Any, model_state: Any, batch: Any):
        """Per-rank (loss, grads, new_model_state), microbatch-accumulated
        when accum_steps>1.

        Accumulation runs as a ``lax.scan`` over ``[accum, B/accum, ...]``
        microbatches with fp32 gradient carry; the mean over equal-size
        microbatches equals the full-batch value for mean losses, so every
        sync/update path downstream is unchanged.  Model state threads
        through the microbatches sequentially (torch grad-accum semantics:
        BatchNorm statistics see every microbatch).
        """
        accum = self.accum_steps
        vg = jax.value_and_grad(self._loss3, has_aux=True)
        if accum == 1:
            (loss, new_ms), grads = vg(params, model_state, batch)
            return loss, grads, new_ms

        micro = self._to_microbatches(batch)
        g0 = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params
        )

        def body(carry, mb):
            acc_l, acc_g, ms = carry
            (loss, ms), g = vg(params, ms, mb)
            acc_g = jax.tree_util.tree_map(
                lambda a, x: a + x.astype(jnp.float32), acc_g, g
            )
            return (acc_l + loss.astype(jnp.float32), acc_g, ms), None

        (loss_sum, g_sum, new_ms), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), g0, model_state), micro
        )
        grads = jax.tree_util.tree_map(
            lambda g, p: (g / accum).astype(p.dtype), g_sum, params
        )
        return loss_sum / accum, grads, new_ms

    def _to_microbatches(self, batch: Any) -> Any:
        """``[B, ...]`` leaves → ``[accum, B/accum, ...]`` microbatch stacks
        (shared by the sequential and pipelined accumulation paths)."""
        accum = self.accum_steps

        def to_micro(x):
            b = x.shape[0]
            if b % accum:
                raise ValueError(
                    f"per-rank batch {b} not divisible by accum_steps {accum}"
                )
            return x.reshape((accum, b // accum) + x.shape[1:])

        return jax.tree_util.tree_map(to_micro, batch)

    def _loss_and_synced(
        self, params: Any, model_state: Any, batch: Any, mask
    ):
        """Per-rank ``(loss, synced_grads, new_model_state)`` for the plain
        (non-banked) sync paths: sequential accumulate-then-sync by
        default, the microbatch-pipelined scan under
        ``overlap='microbatch'`` (docs/OVERLAP.md §1)."""
        if self.overlap != "microbatch":
            loss, grads, new_ms = self._value_and_grad(
                params, model_state, batch
            )
            return loss, self.hook.sync(grads, mask), new_ms
        from adapcc_tpu.ddp.overlap import microbatch_pipelined_sync

        vg = jax.value_and_grad(self._loss3, has_aux=True)
        return microbatch_pipelined_sync(
            vg, params, model_state, self._to_microbatches(batch),
            lambda g: self.hook.sync(g, mask), self.accum_steps,
        )

    def _static_full_step(self, state: TrainState, batch: Any):
        """The static full-world step (no mask, no relay banking): the body
        scan_steps scans and _build's static path reduces to."""
        loss, synced, new_ms = self._loss_and_synced(
            state.params, state.model_state, batch, None
        )
        return self._apply_synced(state, synced, new_ms), loss

    def _build(self) -> Callable:
        # without a coordinator (or an explicit dynamic_mask request) the
        # active set is statically full-world, so the compiled program takes
        # no mask input and the masking folds away
        dynamic_mask = self._dynamic_mask
        deferred_relay = not self.bsp
        error_feedback = self.error_feedback

        pipelined = self.overlap == "microbatch"

        def per_shard(state: TrainState, batch: Any, *extra: Any):
            mask = extra[0] if dynamic_mask else None
            outs = []
            if not pipelined:
                loss, grads, new_ms = self._value_and_grad(
                    state.params, state.model_state, batch
                )
            if pipelined:
                # microbatch-pipelined sync (docs/OVERLAP.md §1): each
                # delta's allreduce dispatches behind the next microbatch's
                # compute inside the accumulation scan.  The banked paths
                # (deferred relay, error feedback) and measure_gns are
                # construction-rejected with this schedule.
                loss, synced, new_ms = self._loss_and_synced(
                    state.params, state.model_state, batch, mask
                )
            elif deferred_relay:
                # deferred rides in/out with a sharded [world] leading dim;
                # strip the per-shard [1] so it matches the grads tree
                deferred = jax.tree_util.tree_map(lambda d: d[0], extra[-1])
                synced, new_deferred = self.hook.sync_deferred(grads, deferred, mask)
                outs.append(jax.tree_util.tree_map(lambda d: d[None], new_deferred))
            elif error_feedback:
                # the residual bank rides like the deferred bank: per-rank,
                # sharded [world] leading dim, replaced wholesale every step
                residual = jax.tree_util.tree_map(lambda r: r[0], extra[-1])
                synced, new_residual = self.hook.sync_error_feedback(
                    grads, residual, mask
                )
                outs.append(
                    jax.tree_util.tree_map(lambda r: r[None], new_residual)
                )
            else:
                synced = self.hook.sync(grads, mask)
            new_state = self._apply_synced(state, synced, new_ms)
            if self.measure_gns:
                from adapcc_tpu.measure.gns import ddp_grad_sq_norms

                small, big = ddp_grad_sq_norms(grads, synced, self.axis_name)
                outs.insert(0, jnp.stack([small, big]))
            # [1] per rank → stacked [world] losses
            return (new_state, loss[None], *outs)

        banked = deferred_relay or error_feedback
        in_specs = (
            (self._state_spec(), P(self.axis_name))
            + ((P(),) if dynamic_mask else ())
            + ((P(self.axis_name),) if banked else ())
        )
        out_specs = (
            (self._state_spec(), P(self.axis_name))
            + ((P(),) if self.measure_gns else ())
            + ((P(self.axis_name),) if banked else ())
        )
        fn = jax.shard_map(
            per_shard,
            mesh=self.mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            # gradients pass through ppermute chains; jax cannot prove the
            # result replicated, but the allreduce guarantees it
            check_vma=False,
        )
        donate = (0,) if self.donate_state else ()
        if banked:
            # the deferred/residual bank is replaced wholesale every step;
            # donating it avoids holding two world-sized copies per dispatch
            donate = donate + (len(in_specs) - 1,)
        return jax.jit(fn, donate_argnums=donate)

    def step(
        self,
        state: TrainState,
        batch: Any,
        step_idx: Optional[int] = None,
        active_mask: Optional[jnp.ndarray] = None,
    ) -> Tuple[TrainState, jnp.ndarray]:
        """One training step.  ``batch`` leading dim is the global batch,
        sharded over the mesh axis.  Returns (new_state, per-rank losses).

        ``active_mask`` overrides the coordinator's negotiation (workloads
        injecting their own skew signal; requires a dynamic-mask trainer).
        """
        self._check_state(state)
        # local binding: an out-of-band supervisor's adopt_strategy may
        # null self._compiled between this resolution and the dispatch
        # below; the step then finishes on the outgoing program (exactly
        # like a collective already in flight when an epoch bumps) and the
        # NEXT step picks up the swapped one
        fn = self._compiled
        if fn is None:
            key = self._program_key()
            fn = self._program_cache.get(key)
            if fn is None:
                fn = self._build()
                self._build_gen += 1  # an actual (re)trace, not a cache hit
                self._program_cache[key] = fn
            self._compiled = fn
        if not self._coord_calibrated:
            # rent-or-buy calibration: this trainer's actual gradient volume
            # + the bootstrap's profiled link bandwidth replace the
            # coordinator's hardcoded cost constants.  Latches on SUCCESS —
            # a False (worker process, coordinator not yet enabled, no
            # profile) retries next step; the no-server case is a cheap
            # attribute check inside calibrate_coordinator
            comm = self.hook.communicator
            if comm is None or not hasattr(comm, "calibrate_coordinator"):
                self._coord_calibrated = True
            else:
                grad_bytes = sum(
                    leaf.nbytes for leaf in jax.tree_util.tree_leaves(state.params)
                )
                self._coord_calibrated = comm.calibrate_coordinator(
                    float(grad_bytes)
                )
        # host-side counter: reading state.step would force a device sync on
        # every dispatch, serializing the loop
        idx = self._host_step if step_idx is None else step_idx
        self._host_step = idx + 1
        if active_mask is not None and not self._dynamic_mask:
            raise ValueError(
                "this trainer compiled a static full-world step; pass "
                "dynamic_mask=True to drive explicit active masks"
            )
        if active_mask is None and self._supervisor is not None:
            # supervised mode (docs/SUPERVISOR.md): the out-of-band daemon
            # owns detect → decide → swap; the step only OBSERVES its last
            # actuated view — the trainer never makes a membership call
            active_mask = jnp.asarray(self._supervisor.current_mask())
        if active_mask is None and self.hook.communicator is not None:
            active_mask = self.hook.negotiate(idx)
        args = [state, batch]
        if self._dynamic_mask:
            if active_mask is None:
                active_mask = jnp.ones((self.mesh.devices.size,), dtype=jnp.bool_)
            args.append(active_mask)
        if not self.bsp:
            if self._deferred is None:
                world = self.mesh.devices.size
                self._deferred = jax.tree_util.tree_map(
                    lambda p: jnp.zeros((world,) + p.shape, p.dtype), state.params
                )
            args.append(self._deferred)
        elif self.error_feedback:
            if self._residual is None:
                world = self.mesh.devices.size
                # fp32 regardless of param dtype: a residual accumulated in
                # a narrow dtype would itself lose the mass it exists to keep
                self._residual = jax.tree_util.tree_map(
                    lambda p: jnp.zeros((world,) + p.shape, jnp.float32),
                    state.params,
                )
            args.append(self._residual)
        tuning = self._tuning()
        if tuning:
            import time as _time

            t0 = _time.perf_counter()
            out = fn(*args)
            jax.block_until_ready(out)
            self._tune_observe(state, _time.perf_counter() - t0)
        else:
            out = fn(*args)
        if not self.bsp:
            *out, self._deferred = out
        elif self.error_feedback:
            *out, self._residual = out
        if self.replica_store is not None:
            # the piggyback window (docs/RECOVERY.md §1): the shard rows
            # this step's optimizer update just wrote are exactly what the
            # post-step all-gather broadcast alongside — capture them,
            # stamped with the STATE's own step counter (not the
            # process-local _host_step, which restarts at 0 on a resumed
            # trainer and would make the freshness guard refuse every
            # repair after a restore) so a later repair's guard compares
            # like with like against state.step
            self.replica_store.capture(
                out[0].opt_state,
                int(np.asarray(jax.device_get(out[0].step))),
            )
        if not self.measure_gns:
            return tuple(out) if isinstance(out, list) else out
        new_state, loss, norms = out
        self._record_gns(batch, norms, active_mask)
        return new_state, loss

    def scan_steps(
        self, state: TrainState, batch: Any, n_steps: int
    ) -> Tuple[TrainState, jnp.ndarray]:
        """``n_steps`` full-world steps on one batch as ONE compiled dispatch
        (``lax.scan`` inside the shard_map).

        On a remote/tunneled backend every ``step()`` call pays a
        host→device dispatch round-trip; a scanned multi-step program pays
        it once, so this is the honest way to measure device-side
        throughput (bench.py) and the fast way to run tight loops whose
        active set cannot change mid-scan.  Static full world only — no
        per-step negotiation, relay banking, or GNS capture.  Returns
        ``(final_state, losses [world, n_steps])``.
        """
        if self._dynamic_mask or not self.bsp or self.measure_gns:
            raise ValueError(
                "scan_steps runs a static full-world program: incompatible "
                "with dynamic_mask, async relay (bsp=False), and measure_gns"
            )
        if self.error_feedback:
            raise ValueError(
                "scan_steps does not thread the error-feedback residual "
                "across scanned steps; use step() with error_feedback=True"
            )
        self._check_state(state)
        key = ("scan", int(n_steps))
        fn = self._scan_cache.get(key)
        if fn is None:
            from jax import lax

            def per_shard(state: TrainState, batch: Any):
                def body(st, _):
                    return self._static_full_step(st, batch)

                st, losses = lax.scan(body, state, None, length=n_steps)
                return st, losses[None]  # [1, n] per rank → stacked [world, n]

            fn = jax.jit(
                jax.shard_map(
                    per_shard,
                    mesh=self.mesh,
                    in_specs=(self._state_spec(), P(self.axis_name)),
                    out_specs=(self._state_spec(), P(self.axis_name)),
                    check_vma=False,
                ),
                donate_argnums=(0,) if self.donate_state else (),
            )
            self._scan_cache[key] = fn
        new_state, losses = fn(state, batch)
        self._host_step += n_steps
        return new_state, losses

    # -- autotuning ------------------------------------------------------------

    def _step_cell(self, grad_bytes: int):
        """The database cell the *current* configuration's step walltimes
        pool under: the hook's effective wire codec crossed with the
        executed overlap schedule (encoded in the key's path slot via
        ``hook_path``).  The cell must stay inside
        ``TuningPolicy.candidates("ddp_step")`` — the (codec × overlap)
        grid narrowed to this trainer's legal modes — or the posterior
        never forms and exploration never ends; the ZeRO-1 ring chunk is a
        separate knob, tuned once at ``Zero1Optimizer.init`` under its own
        "zero1_ring" cells."""
        from adapcc_tpu.tuner.policy import NO_CHUNK, hook_path

        return self.tuner.key_for(
            "ddp_step", grad_bytes, hook_path(self.overlap), NO_CHUNK,
            self.hook.effective_compress(),
        )

    def _tune_observe(self, state: TrainState, seconds: float) -> None:
        """Record one step walltime; periodically let the policy re-choose
        the gradient-sync codec and adopt a winning challenger (recompile).
        Step times of different codecs share the same compute, so their
        medians are mutually comparable — exactly the posterior the policy
        ranks on."""
        if self._grad_bytes is None:
            self._grad_bytes = float(
                sum(
                    leaf.nbytes
                    for leaf in jax.tree_util.tree_leaves(state.params)
                )
            )
        grad_bytes = int(self._grad_bytes)
        self.tuner.observe_dispatch(
            self._step_cell(grad_bytes), ("ddp_step", self._build_gen), seconds
        )
        if not self.tuner.choosing:
            return  # record-only mode: measure, never steer
        if self._host_step % self.tune_every:
            return
        import os as _os

        from adapcc_tpu.ddp.overlap import OVERLAP_ENV
        from adapcc_tpu.quant import WIRE_DTYPE_ENV
        from adapcc_tpu.tuner.policy import hook_overlap_of

        if _os.environ.get(WIRE_DTYPE_ENV, "").strip():
            # ADAPCC_WIRE_DTYPE pins the executed codec (effective_compress
            # resolves it); "adopting" would recompile the step for zero
            # behavioral change, every tune_every boundary, forever — keep
            # measuring the pinned cell and never steer
            return
        # error feedback cannot legally run the 'off' codec (the residual
        # would bank zero at world x params); excluding it from the grid —
        # not just from adoption — keeps the explorer from pinning on a
        # cell that can never accrue samples
        wire_dtypes = (
            tuple(w for w in self.tuner.policy.wire_dtypes if w != "off")
            if self.error_feedback
            else None
        )
        # ADAPCC_OVERLAP pins the executed schedule the same way the wire
        # env pins the codec: collapse the overlap axis to the pinned mode
        # (the codec axis stays free) instead of "adopting" a schedule the
        # env would override at the next construction anyway
        overlap_modes = (
            (self.overlap,)
            if _os.environ.get(OVERLAP_ENV, "").strip()
            else self._overlap_modes
        )
        plan = self.tuner.choose(
            "ddp_step", grad_bytes,
            wire_dtypes=wire_dtypes, overlap_modes=overlap_modes,
        )
        wd = plan.wire_dtype
        ov = hook_overlap_of(plan.key.path)
        if wd == self.hook.effective_compress() and ov == self.overlap:
            return
        self.hook.compress = wd
        if ov != self.overlap:
            # adopting an overlap schedule re-steers EVERY half that
            # executes it: the hook (bucket-rolling dispatch), the trainer
            # (pipelined scan), and an already-constructed Zero1Optimizer
            # (chunked RS/AG) — a stale optimizer would leave the adopted
            # cell's measurements half-applied, corrupting the very A/B
            # the adoption logic ranks on
            self.overlap = ov
            self.hook.overlap = ov
            if self._zero1_opt is not None:
                self._zero1_opt.overlap = self._zero1_overlap()
                self._zero1_opt._compiled = None
        self.hook.reset_plan()
        self._compiled = None  # recompile with the adopted codec/schedule
        self._scan_cache.clear()

    def _record_gns(self, batch: Any, norms: jnp.ndarray, active_mask) -> None:
        if self._gns is None:
            from adapcc_tpu.measure.gns import GNSEstimator

            world = self.mesh.devices.size
            b_big = jax.tree_util.tree_leaves(batch)[0].shape[0]
            self._gns = GNSEstimator(b_small=max(1, b_big // world), b_big=b_big)
        # partial-world steps break the estimator's batch-size accounting
        # (synced averages only the active ranks), so only full-world steps
        # contribute; in async relay mode the first step after a miss is
        # contaminated too (synced folds in the stragglers' banked previous-
        # batch gradients), so it is skipped and the bank marked drained.
        # Norms stay on device until someone reads `gns`, keeping async
        # dispatch intact (see the host-step comment above).
        full = active_mask is None or bool(np.asarray(active_mask).all())
        contaminated = (not self.bsp) and self._bank_dirty
        if not self.bsp:
            self._bank_dirty = not full
        if full and not contaminated:
            self._gns_pending.append(norms)
            # bound retained device buffers on runs that never read `gns`
            if len(self._gns_pending) > 256:
                self._flush_gns()

    def _flush_gns(self) -> None:
        if self._gns is not None and self._gns_pending:
            pending, self._gns_pending = self._gns_pending, []
            for small, big in np.asarray(jax.device_get(pending)):
                self._gns.update(small, big)

    @property
    def gns(self) -> Optional[Any]:
        """The GNS estimator (flushes buffered per-step norms on access)."""
        self._flush_gns()
        return self._gns

    def reset(self) -> None:
        """Zero the host step counter and drop any banked (deferred)
        gradients, keeping compiled programs.  For harnesses that warm up
        the compile cache on throwaway state before a measured run."""
        self._host_step = 0
        self._deferred = None
        self._residual = None
        self._bank_dirty = False

    # -- re-adaptation ---------------------------------------------------------

    def _program_key(self, strategy: Optional[Strategy] = None) -> tuple:
        """Compiled-step cache key: everything the traced program bakes in
        that can change at runtime — the strategy shape, the wire codec
        (tuner adoption rewrites ``hook.compress``), and the overlap
        schedule.  Two configurations sharing a key replay one program;
        anything else retraces."""
        s = strategy if strategy is not None else self.hook.strategy
        return (s.fingerprint(), self.hook.effective_compress(), self.overlap)

    def rebuild(self, strategy: Strategy) -> None:
        """Swap in a freshly synthesized strategy and recompile the step
        (the reconstruct_topology analog for the training loop).  A
        strategy whose program was already compiled under the current
        codec/overlap (a prewarmed standby, or a swap back after
        recovery) is a cache hit — the swap costs one dict lookup."""
        self.hook.strategy = strategy
        self.hook.reset_plan()
        self._compiled = None
        self._scan_cache.clear()  # scanned programs trace the old schedule too

    # -- elastic plan failover (docs/ELASTIC.md) -------------------------------

    @property
    def recompiles(self) -> int:
        """How many step programs were actually traced+compiled — the
        counter the elastic acceptance test pins: a failover onto a
        prewarmed standby strategy must NOT increment it."""
        return self._build_gen

    def prewarm(
        self,
        strategy: Strategy,
        state: "TrainState",
        batch: Any,
        active_mask: Optional[jnp.ndarray] = None,
    ) -> bool:
        """AOT-compile the step program for a standby ``strategy`` on the
        real state/batch shapes, so a later :meth:`adopt_strategy` is a
        dispatch-time switch with no recompile stall on the failover step.

        One throwaway dispatch traces + compiles the program; its outputs
        are discarded, and the prewarmed program is built WITHOUT donation
        (the caller's live state must survive the warmup dispatch — the
        cost is one extra state copy per step on that program, which a
        degraded epoch tolerates).  Returns False when
        the program was already warm.  Banked modes (async relay, error
        feedback) thread per-step buffers the throwaway dispatch would
        corrupt, so they are rejected here — prewarm before training
        starts, or run those modes with the cold-swap path.
        """
        if not self.bsp or self.error_feedback:
            raise ValueError(
                "prewarm() supports the plain BSP step only: banked modes "
                "(async relay / error feedback) carry per-step buffers a "
                "throwaway warmup dispatch would corrupt"
            )
        self._check_state(state)
        saved_strategy = self.hook.strategy
        saved_donate = self.donate_state
        # the key must resolve under the SWAPPED strategy: with
        # compress="strategy" the effective codec is the standby
        # strategy's synthesized wire_dtype, not the incumbent's
        self.hook.strategy = strategy
        self.donate_state = False
        try:
            key = self._program_key()
            if key in self._program_cache:
                return False
            fn = self._build()
            self._build_gen += 1
            args = [state, batch]
            if self._dynamic_mask:
                if active_mask is None:
                    active_mask = jnp.ones(
                        (self.mesh.devices.size,), dtype=jnp.bool_
                    )
                args.append(active_mask)
            jax.block_until_ready(fn(*args))
        finally:
            self.hook.strategy = saved_strategy
            self.donate_state = saved_donate
        self._program_cache[key] = fn
        return True

    def attach_supervisor(self, supervisor) -> "DDPTrainer":
        """Hand membership authority to an out-of-band
        :class:`~adapcc_tpu.supervisor.Supervisor` (docs/SUPERVISOR.md):
        every ``step()`` without an explicit ``active_mask`` consumes the
        daemon's last actuated view, and strategy swaps arrive through
        :meth:`adopt_strategy` driven by the daemon — the trainer only
        observes epoch bumps.  Requires a dynamic-mask step (the mask is
        runtime state, so supervision never recompiles)."""
        if supervisor is not None and not self._dynamic_mask:
            raise ValueError(
                "a supervised trainer needs dynamic_mask=True: the "
                "supervisor's world changes arrive as runtime masks, and "
                "a static full-world step could not shrink without a "
                "retrace"
            )
        self._supervisor = supervisor
        return self

    def adopt_strategy(self, strategy: Strategy) -> bool:
        """Hot-swap the training step onto ``strategy``.

        Returns True when the swap hit a prewarmed program (dispatch-time
        cache-key switch — the no-recompile failover the standby cache
        exists for) and False when it fell back to a cold rebuild (an
        unanticipated world shape; the next step pays the compile).
        """
        self.rebuild(strategy)
        # resolved AFTER the swap so a compress="strategy" hook keys on
        # the adopted strategy's codec (exactly what step() will look up)
        return self._program_key() in self._program_cache
