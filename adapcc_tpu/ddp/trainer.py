"""DDP trainer: jitted data-parallel train step with adaptive gradient sync.

The TPU-shaped equivalent of the reference's training template
(train_ddp.py:30-58): model replicated, batch sharded over the world mesh
axis, gradients synced by the :class:`GradSyncHook` (strategy allreduce with
relay masking), optimizer step applied identically everywhere.  The whole
step — forward, backward, sync, update — is one ``shard_map`` program under
``jit``; the per-step coordinator negotiation stays on the host and feeds in
only a ``[world]`` active mask, so relay decisions never recompile.

``reconstruct_topology`` parity: calling :meth:`rebuild` with a new strategy
recompiles the step against the re-synthesized schedule (the analog of
tearing down and re-creating transmission contexts, adapcc.py:63-67).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, PartitionSpec as P

from adapcc_tpu.comm.mesh import RANKS_AXIS
from adapcc_tpu.ddp.hook import GradSyncHook
from adapcc_tpu.strategy.ir import Strategy


@struct.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jnp.ndarray

    @classmethod
    def create(cls, params: Any, tx: optax.GradientTransformation) -> "TrainState":
        return cls(params=params, opt_state=tx.init(params), step=jnp.zeros((), jnp.int32))


class DDPTrainer:
    """Builds and caches the compiled data-parallel train step.

    ``loss_fn(params, batch) -> scalar`` is evaluated per rank on that rank's
    batch shard; everything else is the trainer's business.
    """

    def __init__(
        self,
        loss_fn: Callable[[Any, Any], jnp.ndarray],
        tx: optax.GradientTransformation,
        mesh: Mesh,
        strategy: Strategy,
        axis_name: str = RANKS_AXIS,
        bucket_cap_mb: float = 100.0,
        use_xla_fastpath: bool = True,
        communicator: Optional[Any] = None,
        # off by default: donation deletes the caller's input state buffers,
        # which surprises library users; training loops that own their state
        # should turn it on for in-place updates
        donate_state: bool = False,
        sync_mode: str = "auto",
    ) -> None:
        self.loss_fn = loss_fn
        self.tx = tx
        self.mesh = mesh
        self.axis_name = axis_name
        self.donate_state = donate_state
        self.hook = GradSyncHook(
            strategy,
            axis_name=axis_name,
            bucket_cap_mb=bucket_cap_mb,
            use_xla_fastpath=use_xla_fastpath,
            communicator=communicator,
            mode=sync_mode,
        )
        self._compiled: Optional[Callable] = None
        self._host_step = 0

    # -- step program ----------------------------------------------------------

    def _build(self) -> Callable:
        # without a coordinator the active set is statically full-world, so
        # the compiled program takes no mask input and the masking folds away
        dynamic_mask = self.hook.communicator is not None

        def per_shard(state: TrainState, batch: Any, *mask: jnp.ndarray):
            loss, grads = jax.value_and_grad(self.loss_fn)(state.params, batch)
            grads = self.hook.sync(grads, mask[0] if mask else None)
            updates, opt_state = self.tx.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            new_state = TrainState(params=params, opt_state=opt_state, step=state.step + 1)
            return new_state, loss[None]  # [1] per rank → stacked [world]

        in_specs = (P(), P(self.axis_name)) + ((P(),) if dynamic_mask else ())
        fn = jax.shard_map(
            per_shard,
            mesh=self.mesh,
            in_specs=in_specs,
            out_specs=(P(), P(self.axis_name)),
            # gradients pass through ppermute chains; jax cannot prove the
            # result replicated, but the allreduce guarantees it
            check_vma=False,
        )
        donate = (0,) if self.donate_state else ()
        return jax.jit(fn, donate_argnums=donate)

    def step(
        self, state: TrainState, batch: Any, step_idx: Optional[int] = None
    ) -> Tuple[TrainState, jnp.ndarray]:
        """One training step.  ``batch`` leading dim is the global batch,
        sharded over the mesh axis.  Returns (new_state, per-rank losses)."""
        if self._compiled is None:
            self._compiled = self._build()
        # host-side counter: reading state.step would force a device sync on
        # every dispatch, serializing the loop
        idx = self._host_step if step_idx is None else step_idx
        self._host_step = idx + 1
        if self.hook.communicator is None:
            return self._compiled(state, batch)
        active_mask = self.hook.negotiate(idx)
        return self._compiled(state, batch, active_mask)

    # -- re-adaptation ---------------------------------------------------------

    def rebuild(self, strategy: Strategy) -> None:
        """Swap in a freshly synthesized strategy and recompile the step
        (the reconstruct_topology analog for the training loop)."""
        self.hook.strategy = strategy
        self.hook.reset_plan()
        self._compiled = None
