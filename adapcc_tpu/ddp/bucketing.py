"""Gradient bucketing: pytree ↔ fixed buckets of ≤ cap bytes.

The reference leans on torch DDP's bucketing (``bucket_cap_mb=100``,
train_ddp.py:35-37) and sizes chunks per bucket (>10 MB buckets get 4 MB
chunks, else size/4 — commu.py:401-403).  Under XLA the bucket plan must be
static: it is computed once from the gradient pytree structure and then the
jitted step flattens leaves into bucket vectors, syncs each bucket, and
scatters back — all shape-static, so the plan is part of the compiled
program rather than a runtime callback.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from adapcc_tpu.primitives import CHUNK_HEURISTIC_THRESHOLD, DEFAULT_CHUNK_BYTES


@dataclass(frozen=True)
class BucketPlan:
    """Static assignment of pytree leaves to buckets.

    ``leaf_bucket[i]`` is the bucket index of leaf ``i`` (flatten order);
    ``bucket_sizes[b]`` is the element count of bucket ``b``;
    ``chunk_bytes[b]`` mirrors the reference per-bucket chunk heuristic.
    """

    treedef: Any
    leaf_shapes: Tuple[Tuple[int, ...], ...]
    leaf_bucket: Tuple[int, ...]
    bucket_sizes: Tuple[int, ...]
    chunk_bytes: Tuple[int, ...]
    #: per-bucket payload bytes (the histogram the observability surface
    #: records); empty tuple only on plans predating the field
    bucket_bytes: Tuple[int, ...] = ()
    #: how many single leaves exceeded the cap on their own (each lands in
    #: a dedicated oversized bucket — torch DDP does the same; the count is
    #: the signal that the cap is mis-sized for the model)
    oversized_leaves: int = 0

    @property
    def num_buckets(self) -> int:
        return len(self.bucket_sizes)

    @property
    def total_bytes(self) -> int:
        return int(sum(self.bucket_bytes))


def _chunk_heuristic(nbytes: int) -> int:
    """Reference chunk sizing (commu.py:401-403)."""
    if nbytes > CHUNK_HEURISTIC_THRESHOLD:
        return DEFAULT_CHUNK_BYTES
    return max(nbytes // 4, 1)


def build_bucket_plan(grads_pytree: Any, bucket_cap_mb: float = 100.0) -> BucketPlan:
    """Greedy fill buckets to the cap in reverse flatten order.

    Reverse order approximates torch DDP's behavior of bucketing gradients in
    roughly backward-pass completion order (last layers first), which is what
    the reference's recorded bucket tables reflect (log/model_bucket_info.txt).
    """
    leaves, treedef = jax.tree_util.tree_flatten(grads_pytree)
    if not leaves:
        # an empty plan would "sync" nothing and read as success; the one
        # caller shape this catches is a loss whose grads pytree lost its
        # leaves (e.g. a frozen-params filter applied twice)
        raise ValueError(
            "build_bucket_plan: gradient pytree has no leaves — nothing to "
            "bucket (did a filter strip every parameter?)"
        )
    cap = int(bucket_cap_mb * 1024 * 1024)

    leaf_bucket = [0] * len(leaves)
    bucket_sizes: List[int] = []
    bucket_bytes: List[int] = []
    oversized = 0
    cur_bucket = -1
    cur_bytes = cap + 1  # force a new bucket on first leaf
    for i in reversed(range(len(leaves))):
        leaf = leaves[i]
        nbytes = leaf.size * leaf.dtype.itemsize
        if nbytes > cap:
            # a single leaf over the cap gets its own bucket (it cannot be
            # split — the plan is leaf-granular); counted for observability
            oversized += 1
        if cur_bytes + nbytes > cap and cur_bytes > 0:
            cur_bucket += 1
            bucket_sizes.append(0)
            bucket_bytes.append(0)
            cur_bytes = 0
        leaf_bucket[i] = cur_bucket
        bucket_sizes[cur_bucket] += leaf.size
        bucket_bytes[cur_bucket] += nbytes
        cur_bytes += nbytes

    return BucketPlan(
        treedef=treedef,
        leaf_shapes=tuple(tuple(l.shape) for l in leaves),
        leaf_bucket=tuple(leaf_bucket),
        bucket_sizes=tuple(bucket_sizes),
        chunk_bytes=tuple(_chunk_heuristic(b) for b in bucket_bytes),
        bucket_bytes=tuple(bucket_bytes),
        oversized_leaves=oversized,
    )


def flatten_to_buckets(plan: BucketPlan, grads_pytree: Any) -> List[jnp.ndarray]:
    """Pack pytree leaves into per-bucket 1-D vectors (static shapes)."""
    leaves = jax.tree_util.tree_leaves(grads_pytree)
    parts: List[List[jnp.ndarray]] = [[] for _ in range(plan.num_buckets)]
    for i, leaf in enumerate(leaves):
        parts[plan.leaf_bucket[i]].append(leaf.reshape(-1))
    return [jnp.concatenate(p) if len(p) > 1 else p[0] for p in parts]


def unflatten_from_buckets(plan: BucketPlan, buckets: Sequence[jnp.ndarray]) -> Any:
    """Scatter bucket vectors back into the original pytree structure."""
    offsets = [0] * plan.num_buckets
    leaves = []
    for i, shape in enumerate(plan.leaf_shapes):
        b = plan.leaf_bucket[i]
        n = int(np.prod(shape)) if shape else 1
        leaves.append(buckets[b][offsets[b] : offsets[b] + n].reshape(shape))
        offsets[b] += n
    return jax.tree_util.tree_unflatten(plan.treedef, leaves)
