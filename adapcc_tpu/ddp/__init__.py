"""Data-parallel training plane: gradient bucketing, sync hook, trainer."""

from adapcc_tpu.ddp.bucketing import BucketPlan, build_bucket_plan
from adapcc_tpu.ddp.hook import GradSyncHook
from adapcc_tpu.ddp.trainer import DDPTrainer, TrainState

__all__ = ["BucketPlan", "build_bucket_plan", "GradSyncHook", "DDPTrainer", "TrainState"]
