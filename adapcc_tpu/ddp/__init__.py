"""Data-parallel training plane: gradient bucketing, sync hook, trainer,
and the overlapped-sync schedules (docs/OVERLAP.md)."""

from adapcc_tpu.ddp.bucketing import BucketPlan, build_bucket_plan
from adapcc_tpu.ddp.hook import GradSyncHook
from adapcc_tpu.ddp.overlap import (
    OVERLAP_ENV,
    OVERLAP_MODES,
    resolve_overlap_mode,
)
from adapcc_tpu.ddp.trainer import DDPTrainer, TrainState

__all__ = [
    "BucketPlan",
    "build_bucket_plan",
    "GradSyncHook",
    "DDPTrainer",
    "TrainState",
    "OVERLAP_ENV",
    "OVERLAP_MODES",
    "resolve_overlap_mode",
]
