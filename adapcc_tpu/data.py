"""Input pipeline: host-side batching + async device prefetch.

The reference feeds its workloads with torch ``DataLoader`` iterators
(models/image-classification/main_elastic.py, models/gpt2/train_gpt2_ddp.py
dataset → padded batches); the host-to-GPU copy rides inside torch.  On TPU
the equivalent overlap must be built explicitly: a background thread moves
the next host batch to device (optionally already laid out in its
``NamedSharding``) while the current step computes, so the device never
waits on PCIe/host for input — the standard double-buffering recipe.

``device_batches`` is the one-call path used by the workloads: shuffled
full batches of a packed array, sharded over the mesh's data axis, with a
bounded prefetch queue.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from adapcc_tpu.comm.mesh import RANKS_AXIS

_END = object()


class _PrefetchError:
    """Private in-band wrapper for a producer failure — unambiguous even
    when the iterator legitimately yields tuples."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


def prefetch_to_device(
    it: Iterator[Any],
    size: int = 2,
    sharding: Optional[Any] = None,
) -> Iterator[Any]:
    """Yield ``device_put`` results of ``it`` with ``size`` batches in flight.

    A daemon producer thread stages host→device transfers into a bounded
    queue: while the consumer computes on batch *n*, batches *n+1..n+size*
    are already copying.  ``sharding`` (a ``NamedSharding`` or pytree of
    them) commits each batch to its device layout at transfer time, so the
    compiled step never reshards its input.  Producer exceptions re-raise at
    the consumer's next pull, preserving the failure's traceback cause.
    """
    if size < 1:
        raise ValueError(f"prefetch size must be >= 1, got {size}")
    q: queue.Queue = queue.Queue(maxsize=size)
    stop = threading.Event()  # consumer gone: unblock + stop the producer

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def produce() -> None:
        try:
            for batch in it:
                if stop.is_set():
                    return
                if sharding is not None:
                    batch = jax.device_put(batch, sharding)
                else:
                    batch = jax.device_put(batch)
                if not _put(batch):
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised at the consumer
            _put(_PrefetchError(e))
            return
        _put(_END)

    t = threading.Thread(target=produce, daemon=True, name="adapcc-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, _PrefetchError):
                raise RuntimeError("prefetch producer failed") from item.exc
            yield item
    finally:
        # an abandoned iterator (break / exception in the consumer) must not
        # leave the producer blocked holding device batches alive
        stop.set()


def batch_indices(
    n: int, batch: int, seed: Optional[int], drop_last: bool = True
) -> Iterator[np.ndarray]:
    """Index blocks for one epoch: shuffled when ``seed`` is given."""
    idx = (
        np.random.default_rng(seed).permutation(n)
        if seed is not None
        else np.arange(n)
    )
    end = n - batch + 1 if drop_last else n
    for i in range(0, end, batch):
        yield idx[i : i + batch]


def device_batches(
    packed: np.ndarray,
    batch: int,
    mesh: Optional[Mesh] = None,
    axis_name: str = RANKS_AXIS,
    seed: Optional[int] = 0,
    prefetch: int = 2,
) -> Iterator[Any]:
    """Shuffled ``[batch, ...]`` device batches of a packed host array.

    With a ``mesh``, each batch is committed sharded over ``axis_name``
    (the DDP layout) while the previous step runs; without one, it lands on
    the default device.  One pass = one epoch; reseed for the next.
    """
    if batch < 1:
        raise ValueError(f"batch must be >= 1, got {batch}")
    if mesh is not None and batch % mesh.shape[axis_name]:
        raise ValueError(
            f"batch {batch} not divisible by mesh axis '{axis_name}' "
            f"({mesh.shape[axis_name]})"
        )
    sharding = (
        NamedSharding(mesh, P(axis_name)) if mesh is not None else None
    )

    def host_batches() -> Iterator[np.ndarray]:
        for idx in batch_indices(len(packed), batch, seed):
            yield packed[idx]

    return prefetch_to_device(host_batches(), size=prefetch, sharding=sharding)
