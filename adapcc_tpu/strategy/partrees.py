"""ParTrees: parallel-spanning-tree strategy synthesis from profiled matrices.

Re-implements the reference heuristic (gurobi/trees.py, described in
SURVEY.md §2.2 P7): per-host "master" ranks (local-rank-0s) are sorted by the
bandwidth–delay product of their outbound inter-host link, an array-heap
binary tree is built over the masters, the master list is rotated once per
parallel transmission for root diversity, and each master's intra-host ranks
hang beneath it as a chain (the reference's "Chain policy",
gurobi/trees.py:85-88).  On TPU "intra-host" means same ICI domain and
"inter-host" means DCN, so the chain rides the fast mesh while the binary
tree spans the slow links.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from adapcc_tpu.primitives import DEFAULT_CHUNK_BYTES
from adapcc_tpu.strategy.ir import Strategy, Tree
from adapcc_tpu.strategy.xml_io import emit_strategy_xml


@dataclass
class _Master:
    rank: int
    ip: str
    group: List[int]  # all ranks on this host, master first
    bandwidth: float
    latency: float

    @property
    def bdp(self) -> float:
        return self.bandwidth * self.latency


def _host_groups(ip_table: Sequence[str], masters: Sequence[int]) -> Dict[int, List[int]]:
    """Consecutive ranks sharing the master's ip form its host group.

    A group also ends at the next master: two masters can share an ip (one
    server exposing two nics in the logical graph), and their groups must not
    overlap.
    """
    master_set = set(masters)
    groups: Dict[int, List[int]] = {}
    for m in masters:
        group = [m]
        r = m + 1
        while r < len(ip_table) and ip_table[r] == ip_table[m] and r not in master_set:
            group.append(r)
            r += 1
        groups[m] = group
    return groups


def _attach_chains(
    children: Dict[int, List[int]], masters: Sequence[int], groups: Dict[int, List[int]]
) -> None:
    """Chain policy: hang each master's intra-host ranks beneath it as a chain
    whose head is the master's *first* child, so the sibling index (staging
    priority) favors the fast local edge (reference gurobi/trees.py:85-88)."""
    for m in masters:
        chain = groups[m][1:]
        if not chain:
            continue
        kids = children.setdefault(m, [])
        kids.insert(0, chain[0])
        for a, b in zip(chain, chain[1:]):
            children.setdefault(a, []).append(b)


def _heap_tree_edges(order: Sequence[int]) -> Dict[int, List[int]]:
    """Array-heap binary tree: element i parents elements 2i+1 and 2i+2."""
    children: Dict[int, List[int]] = {}
    for i, rank in enumerate(order):
        kids = [order[j] for j in (2 * i + 1, 2 * i + 2) if j < len(order)]
        if kids:
            children[rank] = kids
    return children


class ParTrees:
    """Heuristic synthesizer (default policy, reference synthesizer.py:44-52)."""

    def optimize(
        self,
        ip_table: Sequence[str],
        local_rank0_list: Sequence[int],
        prim: int,
        parallel_degree: int,
        transmission_size: int,
        bandwidth_graph: Sequence[Sequence[float]],
        latency_graph: Sequence[Sequence[float]],
        strategy_file: Optional[str] = None,
    ) -> int:
        """Synthesize the strategy, optionally write it as XML, and return the
        chunk size in bytes (same signature shape as the reference so the
        control plane swaps policies freely)."""
        strategy = self.synthesize(
            ip_table,
            local_rank0_list,
            parallel_degree,
            bandwidth_graph,
            latency_graph,
        )
        if strategy_file:
            emit_strategy_xml(strategy, strategy_file)
        return strategy.chunk_bytes

    def synthesize(
        self,
        ip_table: Sequence[str],
        local_rank0_list: Sequence[int],
        parallel_degree: int,
        bandwidth_graph: Sequence[Sequence[float]],
        latency_graph: Sequence[Sequence[float]],
    ) -> Strategy:
        world = len(ip_table)
        groups = _host_groups(ip_table, local_rank0_list)

        masters: List[_Master] = []
        for m in local_rank0_list:
            # probe target: the first rank of the "next" host around the ring,
            # i.e. this master's representative outbound inter-host link
            peer = (m + len(groups[m])) % world
            masters.append(
                _Master(
                    rank=m,
                    ip=ip_table[m],
                    group=groups[m],
                    bandwidth=bandwidth_graph[m][peer],
                    latency=latency_graph[m][peer],
                )
            )
        # best-provisioned master first: it becomes the first tree's root
        masters.sort(key=lambda n: n.bdp, reverse=True)

        degree = min(len(masters), max(1, parallel_degree))
        ips = {r: ip_table[r] for r in range(world)}

        trees: List[Tree] = []
        rotation = list(masters)
        for t in range(degree):
            if t > 0:
                rotation = rotation[1:] + rotation[:1]
            trees.append(self._build_tree(rotation, groups, ips))
        return Strategy(trees, world, DEFAULT_CHUNK_BYTES, synthesis="partrees")

    @staticmethod
    def _build_tree(
        masters: Sequence[_Master],
        groups: Dict[int, List[int]],
        ips: Dict[int, str],
    ) -> Tree:
        order = [m.rank for m in masters]
        children = _heap_tree_edges(order)
        _attach_chains(children, order, groups)
        return Tree(order[0], children, ips)
