"""XML compatibility layer for strategy trees, logical graphs and ip tables.

Keeps the reference's declarative artifact formats (SURVEY.md §5.6) so
hand-written or previously synthesized files keep working:

- strategy XML: ``<trees><root id ip><gpu id ip>…</gpu></root></trees>``
  (reference strategy/*.xml, parsed natively by tinyxml2 at
  csrc/allreduce.cu:52-104)
- logical graph XML: ``<graph version><server id ip><nic id><gpu id/></nic>
  </server></graph>`` (reference topology/logical_graph_*.xml, parsed at
  csrc/profile.cu:56-161)
- ip table: one ip per line, line index = world rank (written by the
  reference launcher, launcher.py:64-79)

Implemented with the stdlib ``xml.etree`` (no vendored tinyxml2 / xmltodict):
the reference fixtures contain attribute pairs with no separating whitespace
(e.g. ``<gpu id='1'ip='…'/>`` in strategy/4.xml), which strict XML rejects, so
parsing goes through a small lenient pre-pass.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional
from xml.etree import ElementTree as ET

from adapcc_tpu.strategy.ir import Strategy, Tree

#: schema version stamped on emitted artifacts (``<trees schema=…>`` and
#: ``<schedule schema=…>``).  Major.minor: a parser accepts any minor of its
#: own major (attributes it does not know are additive) and **loudly
#: rejects** a different major — before this stamp existed, a newer-schema
#: artifact parsed "successfully" with its new semantics silently dropped.
#: Absence of the attribute means a legacy/reference artifact and is
#: accepted: reference strategy/*.xml fixtures never carried one.
SCHEDULE_SCHEMA_VERSION = "1.0"


def _check_schema_version(doc: "ET.Element", element: str) -> None:
    """Reject an artifact stamped with a schema major we do not speak."""
    raw = doc.attrib.get("schema")
    if raw is None:
        return  # legacy / reference artifact: pre-stamp schema, accepted
    ours = SCHEDULE_SCHEMA_VERSION.split(".")[0]
    theirs = raw.split(".")[0]
    if not theirs.isdigit() or theirs != ours:
        raise ValueError(
            f"<{element} schema={raw!r}>: this build speaks schema major "
            f"{ours} (version {SCHEDULE_SCHEMA_VERSION}); refusing to parse "
            "a different major rather than silently dropping its semantics"
        )


# closing quote immediately followed by the next attribute pair (name='…'):
# insert the missing space.  The lookahead requires a quote right after the
# '=' so attribute *values* containing 'word=' (e.g. ip='host=a') are not
# touched.
_MISSING_SPACE = re.compile(r"(['\"])(?=[A-Za-z_][\w.-]*\s*=\s*['\"])")


def _lenient_fromstring(text: str) -> ET.Element:
    try:
        return ET.fromstring(text)
    except ET.ParseError:
        return ET.fromstring(_MISSING_SPACE.sub(r"\1 ", text))


def _valid_wire_dtype(raw: str) -> str:
    """Validate a wire_dtype attribute at parse time: a corrupted artifact
    must fail at the file that carries it, not deep inside a later engine
    dispatch (the chunk_bytes precedent)."""
    from adapcc_tpu.quant.codec import codec_names

    if raw not in codec_names():
        raise ValueError(
            f"<trees wire_dtype={raw!r}>: expected one of "
            f"{'|'.join(codec_names())}"
        )
    return raw


def _positive_chunk(raw: str, element: str) -> int:
    """Validate a chunk_bytes attribute at parse time: a corrupted artifact
    must fail at the file that carries it, not deep inside a later ring
    dispatch."""
    try:
        value = int(raw)
    except ValueError:
        value = -1
    if value <= 0:
        raise ValueError(
            f"<{element} chunk_bytes={raw!r}>: expected a positive byte count"
        )
    return value


# --------------------------------------------------------------------------- #
# strategy trees
# --------------------------------------------------------------------------- #

def parse_strategy_xml(text_or_path: str, chunk_bytes: int = 4 * 1024 * 1024) -> Strategy:
    """Parse a strategy XML document (or file path) into a :class:`Strategy`.

    ``chunk_bytes`` is only a default: a ``chunk_bytes`` attribute persisted
    on ``<trees>`` (and per-tree on ``<root>``, the solver's c_m output —
    reference gurobi/solver.py:211) wins, so a persisted strategy fully
    determines ring execution without out-of-band state.  Reference XMLs
    without the attribute keep the caller's default.
    """
    text = _maybe_read(text_or_path)
    doc = _lenient_fromstring(text)
    if doc.tag != "trees":
        raise ValueError(f"expected <trees> root element, got <{doc.tag}>")
    _check_schema_version(doc, "trees")

    trees: List[Tree] = []
    all_ranks: set = set()
    per_tree_chunks: List[Optional[int]] = []
    for root_el in doc.findall("root"):
        children: Dict[int, List[int]] = {}
        ips: Dict[int, str] = {}

        def walk(el: ET.Element, parent_rank: Optional[int]) -> None:
            rank = int(el.attrib["id"])
            ips[rank] = el.attrib.get("ip", "")
            if parent_rank is not None:
                children.setdefault(parent_rank, []).append(rank)
            for sub in el.findall("gpu"):
                walk(sub, rank)

        walk(root_el, None)
        root_rank = int(root_el.attrib["id"])
        trees.append(Tree(root_rank, children, ips))
        all_ranks |= trees[-1].ranks
        raw = root_el.attrib.get("chunk_bytes")
        per_tree_chunks.append(_positive_chunk(raw, "root") if raw else None)

    world_size = max(all_ranks) + 1 if all_ranks else 0
    doc_chunk = doc.attrib.get("chunk_bytes")
    if doc_chunk:
        chunk_bytes = _positive_chunk(doc_chunk, "trees")
    tree_chunk_bytes: Optional[List[int]] = None
    if any(c is not None for c in per_tree_chunks):
        # a tree without its own attribute pipelines at the document chunk
        tree_chunk_bytes = [
            c if c is not None else chunk_bytes for c in per_tree_chunks
        ]
    raw_wire = doc.attrib.get("wire_dtype")
    strategy = Strategy(
        trees, world_size, chunk_bytes,
        synthesis=doc.attrib.get("synthesis") or None,
        tree_chunk_bytes=tree_chunk_bytes,
        wire_dtype=_valid_wire_dtype(raw_wire) if raw_wire else "off",
    )
    raw_hier = doc.attrib.get("hier")
    if raw_hier:
        # a composed two-level plan's sketch rides the artifact: reattach
        # it so a parsed strategy executes the composed phases, not the
        # projected fixed schedule.  Malformed attributes fail at the file
        # that carries them (the chunk_bytes / wire_dtype precedent).
        from adapcc_tpu.strategy import hierarchy

        m = re.fullmatch(r"([1-9]\d*)x([1-9]\d*)", raw_hier)
        if not m:
            raise ValueError(
                f"<trees hier={raw_hier!r}>: expected '<pods>x<pod_size>'"
            )
        sketch = hierarchy.HierarchySketch(int(m.group(1)), int(m.group(2)))
        hierarchy.plan_from_strategy(
            strategy,
            sketch,
            doc.attrib.get("hier_pod_algo", "rs-ag"),
            doc.attrib.get("hier_leader_algo", "tree"),
        )
    return strategy


def emit_strategy_xml(strategy: Strategy, path: Optional[str] = None) -> str:
    """Serialize a :class:`Strategy` back to the reference XML schema, plus
    the chunk-granularity attributes (`<trees chunk_bytes=…>` and per-tree
    on `<root>`) that make the artifact self-contained for ring execution."""
    doc = ET.Element("trees")
    doc.set("schema", SCHEDULE_SCHEMA_VERSION)
    if strategy.synthesis:
        # provenance: which formulation produced this strategy (a solver
        # fallback in production must be distinguishable from an optimum)
        doc.set("synthesis", strategy.synthesis)
    doc.set("chunk_bytes", str(strategy.chunk_bytes))
    plan = getattr(strategy, "_two_level_plan", None)
    if plan is not None:
        # the composed plan's sketch + per-level schedules are part of the
        # artifact: a re-parsed strategy must execute the same phases
        doc.set(
            "hier", f"{plan.sketch.num_pods}x{plan.sketch.pod_size}"
        )
        doc.set("hier_pod_algo", plan.pod_algo)
        doc.set("hier_leader_algo", plan.leader_algo)
    if strategy.wire_dtype != "off":
        # only a non-default codec is persisted: reference XMLs and pre-quant
        # artifacts stay byte-stable, and absence unambiguously means "off"
        doc.set("wire_dtype", strategy.wire_dtype)
    for i, tree in enumerate(strategy.trees):
        def build(rank: int, parent_el: ET.Element, tag: str) -> None:
            el = ET.SubElement(parent_el, tag)
            el.set("id", str(rank))
            el.set("ip", tree.ips.get(rank, ""))
            if tag == "root" and strategy.tree_chunk_bytes is not None:
                el.set("chunk_bytes", str(strategy.tree_chunk_bytes[i]))
            for c in tree.children.get(rank, ()):
                build(c, el, "gpu")

        build(tree.root, doc, "root")
    text = ET.tostring(doc, encoding="unicode")
    if path:
        with open(path, "w") as f:
            f.write(text)
    return text


# --------------------------------------------------------------------------- #
# schedule programs (the compiler IR's artifact form, docs/COMPILER.md)
# --------------------------------------------------------------------------- #

def emit_program_xml(program, path: Optional[str] = None) -> str:
    """Serialize a ``compiler.ScheduleProgram`` to its XML artifact form.

    ``<schedule schema=… name world chunks collective wire_dtype relays>``
    wrapping one ``<round>`` element per round, one ``<step kind rank chunk
    [peer] [codec]>`` per step **in program order** — step order inside a
    round is semantic (it fixes combine order, hence bitwise results), so
    the artifact preserves it and :func:`parse_program_xml` round-trips to
    an equal fingerprint.
    """
    doc = ET.Element("schedule")
    doc.set("schema", SCHEDULE_SCHEMA_VERSION)
    doc.set("name", program.name)
    doc.set("world", str(program.world))
    doc.set("chunks", str(program.chunks))
    doc.set("collective", program.collective)
    doc.set("wire_dtype", program.wire_dtype)
    if program.relays:
        doc.set("relays", ",".join(str(r) for r in program.relays))
    for round_steps in program.rounds:
        round_el = ET.SubElement(doc, "round")
        for step in round_steps:
            el = ET.SubElement(round_el, "step")
            el.set("kind", step.kind)
            el.set("rank", str(step.rank))
            el.set("chunk", str(step.chunk))
            if step.peer is not None:
                el.set("peer", str(step.peer))
            if step.codec is not None:
                el.set("codec", step.codec)
    text = ET.tostring(doc, encoding="unicode")
    if path:
        with open(path, "w") as f:
            f.write(text)
    return text


def parse_program_xml(text_or_path: str):
    """Parse a schedule-program XML artifact back into a
    ``compiler.ScheduleProgram`` (inverse of :func:`emit_program_xml`).

    Schema-major mismatches reject loudly (:data:`SCHEDULE_SCHEMA_VERSION`);
    the program's own ``__post_init__`` validation then re-checks every
    rank/chunk bound, so a corrupted artifact dies at the file that carries
    it, not inside a later lowering.
    """
    from adapcc_tpu.compiler.ir import ScheduleProgram, Step

    text = _maybe_read(text_or_path)
    doc = _lenient_fromstring(text)
    if doc.tag != "schedule":
        raise ValueError(f"expected <schedule> root element, got <{doc.tag}>")
    _check_schema_version(doc, "schedule")
    try:
        world = int(doc.attrib["world"])
        chunks = int(doc.attrib["chunks"])
    except (KeyError, ValueError) as e:
        raise ValueError(f"<schedule>: bad or missing world/chunks attribute: {e}")
    raw_relays = doc.attrib.get("relays", "")
    relays = tuple(int(r) for r in raw_relays.split(",") if r.strip()) if raw_relays else ()
    rounds = []
    for round_el in doc.findall("round"):
        steps = []
        for el in round_el.findall("step"):
            peer = el.attrib.get("peer")
            steps.append(
                Step(
                    el.attrib["kind"],
                    int(el.attrib["rank"]),
                    int(el.attrib["chunk"]),
                    peer=int(peer) if peer is not None else None,
                    codec=el.attrib.get("codec"),
                )
            )
        rounds.append(tuple(steps))
    return ScheduleProgram(
        name=doc.attrib.get("name", "parsed"),
        world=world,
        chunks=chunks,
        rounds=tuple(rounds),
        collective=doc.attrib.get("collective", "allreduce"),
        wire_dtype=doc.attrib.get("wire_dtype", "off"),
        relays=relays,
    )


# --------------------------------------------------------------------------- #
# logical graph
# --------------------------------------------------------------------------- #

@dataclass
class LogicalGraph:
    """Cluster sketch: which ranks live on which server behind which nic.

    On TPU, "server" maps to a host/process and "nic" to an ICI domain or DCN
    endpoint (SURVEY.md §7's detect.cu mapping).
    """

    servers: List["ServerEntry"] = field(default_factory=list)
    version: str = "adapcc-tpu"

    def rank_to_ip(self) -> Dict[int, str]:
        out: Dict[int, str] = {}
        for s in self.servers:
            for g in s.gpus:
                out[g] = s.ip
        return out

    def local_rank0_list(self) -> List[int]:
        return [min(s.gpus) for s in self.servers if s.gpus]

    @property
    def world_size(self) -> int:
        return sum(len(s.gpus) for s in self.servers)


@dataclass
class ServerEntry:
    server_id: int
    ip: str
    nic_id: int
    gpus: List[int] = field(default_factory=list)


def parse_logical_graph_xml(text_or_path: str) -> LogicalGraph:
    text = _maybe_read(text_or_path)
    doc = _lenient_fromstring(text)
    if doc.tag != "graph":
        raise ValueError(f"expected <graph> root element, got <{doc.tag}>")
    graph = LogicalGraph(version=doc.attrib.get("version", ""))
    for server_el in doc.findall("server"):
        sid = int(server_el.attrib["id"])
        ip = server_el.attrib.get("ip", "")
        for nic_el in server_el.findall("nic"):
            entry = ServerEntry(sid, ip, int(nic_el.attrib.get("id", 0)))
            for gpu_el in nic_el.findall("gpu"):
                entry.gpus.append(int(gpu_el.attrib["id"]))
            graph.servers.append(entry)
    return graph


def emit_logical_graph_xml(graph: LogicalGraph, path: Optional[str] = None) -> str:
    doc = ET.Element("graph")
    doc.set("version", graph.version)
    for s in graph.servers:
        server_el = ET.SubElement(doc, "server")
        server_el.set("id", str(s.server_id))
        server_el.set("ip", s.ip)
        nic_el = ET.SubElement(server_el, "nic")
        nic_el.set("id", str(s.nic_id))
        for g in s.gpus:
            gpu_el = ET.SubElement(nic_el, "gpu")
            gpu_el.set("id", str(g))
    text = ET.tostring(doc, encoding="unicode")
    if path:
        with open(path, "w") as f:
            f.write(text)
    return text


# --------------------------------------------------------------------------- #
# ip table
# --------------------------------------------------------------------------- #

def read_ip_table(path: str) -> List[str]:
    """Rank→ip list; line index is the world rank (reference commu.py:109-114)."""
    with open(path) as f:
        return [line.strip() for line in f if line.strip()]


def write_ip_table(ips: List[str], path: str) -> None:
    with open(path, "w") as f:
        for ip in ips:
            f.write(ip + "\n")


def _maybe_read(text_or_path: str) -> str:
    if text_or_path.lstrip().startswith("<"):
        return text_or_path
    with open(text_or_path) as f:
        return f.read()
