"""Strategy layer: schedule IR, XML compatibility, and synthesizers.

The reference encodes communication strategies as XML trees parsed natively by
tinyxml2 (reference csrc/allreduce.cu:52-104) into per-rank role tables.  Here
the same XML schema lowers to a pure-Python IR of per-round partial
permutations, which the collective engine turns into masked
`jax.lax.ppermute` programs on a `jax.sharding.Mesh` axis.
"""

from adapcc_tpu.strategy.ir import Tree, Strategy, CommRound
from adapcc_tpu.strategy.xml_io import (
    parse_strategy_xml,
    emit_strategy_xml,
    parse_logical_graph_xml,
    emit_logical_graph_xml,
    read_ip_table,
    write_ip_table,
)
from adapcc_tpu.strategy.partrees import ParTrees
from adapcc_tpu.strategy.synthesizer import Synthesizer

__all__ = [
    "Tree",
    "Strategy",
    "CommRound",
    "parse_strategy_xml",
    "emit_strategy_xml",
    "parse_logical_graph_xml",
    "emit_logical_graph_xml",
    "read_ip_table",
    "write_ip_table",
    "ParTrees",
    "Synthesizer",
]
