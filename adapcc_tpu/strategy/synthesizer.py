"""Strategy synthesizer: policy switch over ParTrees / MILP / fixed shapes.

Mirrors the reference's policy dispatch (gurobi/synthesizer.py:44-62): the
default heuristic is ParTrees; an exact MILP formulation is available when a
solver backend exists.  Two TPU-native fixed policies (``ring`` and
``binary``) are added because on an ICI torus the regular schedules are often
optimal and need no profile data.

A fifth policy, ``sim-rank``, synthesizes every cheap candidate (ParTrees,
ring, binary) and commits to whichever the calibrated α-β replay
(:mod:`adapcc_tpu.sim`) predicts fastest — the TACCL-style offline ranking
pass that keeps strategy selection *measured* even when no hardware is
reachable (docs/SIMULATION.md).
"""

from __future__ import annotations

import sys
from typing import List, Optional, Sequence, Tuple

from adapcc_tpu.primitives import DEFAULT_CHUNK_BYTES
from adapcc_tpu.strategy.ir import Strategy
from adapcc_tpu.strategy.partrees import ParTrees
from adapcc_tpu.strategy.xml_io import emit_strategy_xml


class Synthesizer:
    """Generates a communication strategy from profiled lat/bw matrices."""

    def __init__(self, strategy_file: Optional[str], ip_table: Sequence[str], policy: str = "par-trees"):
        self.strategy_file = strategy_file
        self.ip_table = list(ip_table)
        self.policy = policy

    def generate_strategy(
        self,
        prim: int,
        parallel_degree: int,
        transmission_size: int,
        bandwidth_graph: Sequence[Sequence[float]],
        latency_graph: Sequence[Sequence[float]],
        local_rank0_list: Optional[Sequence[int]] = None,
    ) -> int:
        """Synthesize + persist the strategy XML; returns chunk bytes.

        The persisted ``chunk_bytes`` is the ring data plane's staging
        granularity (docs/RING.md §2), clamped to the transmission size it
        pipelines — a chunk larger than the payload is just the payload.
        The XML carries it (plus any per-tree c_m the solver emitted), so
        the artifact alone determines ring execution on every process.
        """
        strategy = self.synthesize(
            prim, parallel_degree, transmission_size, bandwidth_graph, latency_graph, local_rank0_list
        )
        if transmission_size and transmission_size > 0:
            strategy.chunk_bytes = max(
                1, min(strategy.chunk_bytes, int(transmission_size))
            )
        if self.strategy_file:
            emit_strategy_xml(strategy, self.strategy_file)
        return strategy.chunk_bytes

    def synthesize(
        self,
        prim: int,
        parallel_degree: int,
        transmission_size: int,
        bandwidth_graph: Sequence[Sequence[float]],
        latency_graph: Sequence[Sequence[float]],
        local_rank0_list: Optional[Sequence[int]] = None,
    ) -> Strategy:
        world = len(self.ip_table)
        if local_rank0_list is None:
            local_rank0_list = _infer_local_rank0s(self.ip_table)

        if self.policy == "par-trees":
            return ParTrees().synthesize(
                self.ip_table, local_rank0_list, parallel_degree, bandwidth_graph, latency_graph
            )
        if self.policy == "milp":
            from adapcc_tpu.strategy.solver import MilpSolver

            return MilpSolver().synthesize(
                self.ip_table,
                local_rank0_list,
                prim,
                parallel_degree,
                transmission_size,
                bandwidth_graph,
                latency_graph,
            )
        if self.policy == "sim-rank":
            return self._sim_ranked(
                prim, parallel_degree, transmission_size, bandwidth_graph,
                latency_graph, local_rank0_list,
            )
        if self.policy == "hier":
            return self._hierarchical(
                parallel_degree, transmission_size, bandwidth_graph,
                latency_graph,
            )
        ips = {r: ip for r, ip in enumerate(self.ip_table)}
        if self.policy == "ring":
            s = Strategy.ring(world, max(1, parallel_degree), ips)
        elif self.policy == "binary":
            s = Strategy.binary(world, max(1, parallel_degree), ips)
        else:
            raise ValueError(f"unknown synthesis policy {self.policy!r}")
        s.synthesis = self.policy
        return s

    def _hierarchical(
        self,
        parallel_degree: int,
        transmission_size: int,
        bandwidth_graph,
        latency_graph,
    ) -> Strategy:
        """The ``hier`` policy (docs/HIERARCHY.md): derive the DCN×ICI
        sketch from the ip table (``ADAPCC_HIER_SKETCH`` overrides,
        malformed → loud), solve each level against the per-link-class
        α-β costs, and compose the two-level plan.  Per-level work is
        O(pod) + O(num_pods) — never O(world) — which is what lets
        world=4096 synthesis fit the MILP budget the flat solver blows.
        A flat (single-pod) world rejects loudly: there is no hierarchy
        to sketch, and silently synthesizing a flat shape under the
        ``hier`` label would invalidate the scaling curve."""
        from adapcc_tpu.strategy import hierarchy

        world = len(self.ip_table)
        sketch = hierarchy.resolve_sketch(world, self.ip_table)
        if sketch is None:
            raise ValueError(
                f"policy 'hier' needs a multi-pod hierarchy, but the "
                f"{world}-rank ip table resolves to a single pod / flat "
                f"world; use a flat policy, or pin "
                f"{hierarchy.HIER_SKETCH_ENV}"
            )
        usable = (
            bandwidth_graph is not None
            and latency_graph is not None
            and len(bandwidth_graph) == world
        )
        model = hierarchy.model_from_graphs(
            sketch,
            bandwidth_graph if usable else None,
            latency_graph if usable else None,
        )
        nbytes = (
            transmission_size if transmission_size and transmission_size > 0
            else DEFAULT_CHUNK_BYTES
        )
        plan = hierarchy.synthesize_two_level(
            sketch, model, nbytes=nbytes, num_trans=max(1, parallel_degree)
        )
        return plan.strategy

    # -- simulated ranking pass ------------------------------------------------

    def candidates(
        self,
        parallel_degree: int,
        bandwidth_graph: Sequence[Sequence[float]],
        latency_graph: Sequence[Sequence[float]],
        local_rank0_list: Optional[Sequence[int]] = None,
    ) -> List[Tuple[str, Strategy]]:
        """Every cheap candidate shape, ParTrees first so a predicted tie
        keeps the default heuristic (and the compiled-program cache warm)."""
        world = len(self.ip_table)
        if local_rank0_list is None:
            local_rank0_list = _infer_local_rank0s(self.ip_table)
        ips = {r: ip for r, ip in enumerate(self.ip_table)}
        degree = max(1, parallel_degree)
        out: List[Tuple[str, Strategy]] = []
        try:
            out.append((
                "par-trees",
                ParTrees().synthesize(
                    self.ip_table, local_rank0_list, degree,
                    bandwidth_graph, latency_graph,
                ),
            ))
        except Exception as e:  # noqa: BLE001
            # degenerate topology: the fixed shapes still compete — but say
            # so (on stderr: stdout may be a --json row stream), or a real
            # ParTrees regression silently shrinks the field
            print(
                f"[synthesizer] par-trees candidate dropped: "
                f"{type(e).__name__}: {e}",
                file=sys.stderr,
                flush=True,
            )
        out.append(("ring", Strategy.ring(world, degree, ips)))
        out.append(("binary", Strategy.binary(world, degree, ips)))
        return out

    def rank(
        self,
        candidates: Sequence[Tuple[str, Strategy]],
        nbytes: int,
        bandwidth_graph: Optional[Sequence[Sequence[float]]] = None,
        latency_graph: Optional[Sequence[Sequence[float]]] = None,
        collective: str = "allreduce",
        model=None,
        engine: Optional[str] = None,
    ):
        """Order labeled candidates fastest-first on the α-β replay.

        The cost model is ``model`` when given (the online re-adaptation
        path hands in its drift-corrected model, docs/ADAPT.md), else from
        the profiled matrices (the exact inputs ``synthesize`` receives
        from the bootstrap), else from the persisted calibration artifact /
        synthetic defaults.  Returns
        :class:`adapcc_tpu.sim.rank.RankedCandidate` rows, each stamped
        with its certified ``optimality_gap`` against the topology's
        latency+bandwidth lower bound — the ranking reports distance from
        optimal, not just the pool order.  ``engine`` threads through to
        the replay funnel (``auto`` picks the vectorized path at pod
        scale; the lowered columns are cached per strategy fingerprint,
        so repeated re-ranks under drifted models re-price instead of
        re-lowering).
        """
        from adapcc_tpu import sim

        if model is None:
            model = self._cost_model(bandwidth_graph, latency_graph)
        return sim.rank_candidates(
            list(candidates), model, max(1, int(nbytes)), collective,
            engine=engine,
        )

    def resynthesize(
        self,
        model,
        nbytes: int,
        parallel_degree: int = 1,
        incumbent: Optional[Strategy] = None,
        collective: str = "allreduce",
        provenance: str = "adapt-rerank",
        engine: Optional[str] = None,
    ):
        """Online re-rank under a drift-corrected (or transiently
        contended — docs/FABRIC.md) cost model: synthesize the candidate
        pool from the model's own link matrices (so candidate SHAPES —
        ParTrees master routing included — see the corrected network),
        rank on the corrected replay, and re-price the winner's wire
        codec on its corrected bottleneck edge.  Candidates priced under
        a contention model rank exactly as they would execute there, so
        trees that avoid the hot links win the re-rank.

        ``incumbent`` is listed FIRST, so a prediction-identical
        alternative keeps the executing strategy (no compiled-program
        churn for nothing — the rank_candidates tie rule).
        ``provenance`` stamps the winner's synthesis label ("adapt-rerank"
        for the re-calibrate path, "congestion-reroute" for the transient
        triage path — the artifact must say WHY the shape changed).
        Returns the full ranked list; callers gate adoption on their own
        hysteresis.  Pure host work: no probe traffic, no compilation.
        """
        bw, lat = model.to_graphs()
        cands: List[Tuple[str, Strategy]] = []
        if incumbent is not None:
            cands.append(("incumbent", incumbent))
        cands.extend(self.candidates(parallel_degree, bw, lat))
        ranked = self.rank(
            cands, nbytes, collective=collective, model=model, engine=engine
        )
        winner = ranked[0]
        if winner.strategy is not None and winner.strategy is not incumbent:
            winner.strategy.synthesis = f"{winner.label}+{provenance}"
            winner.strategy.wire_dtype = self._choose_wire_dtype(
                winner.strategy, nbytes, bw, lat
            )
        return ranked

    def _cost_model(self, bandwidth_graph, latency_graph):
        import numpy as np

        from adapcc_tpu.sim.calibrate import load_or_default
        from adapcc_tpu.sim.cost_model import LinkCostModel

        world = len(self.ip_table)
        ips = {r: ip for r, ip in enumerate(self.ip_table)}
        if bandwidth_graph is not None and latency_graph is not None:
            bw = np.asarray(bandwidth_graph, dtype=float)
            lat = np.asarray(latency_graph, dtype=float)
            if bw.shape == (world, world) and (bw > 0).any():
                return LinkCostModel.from_matrices(
                    lat, bw, ips, source="profile-graphs"
                )
        model = load_or_default(world=world)
        if model.ips is None:
            # the fallback must still price cross-host edges as DCN: attach
            # the synthesizer's own ip table (battery calibrations and the
            # world-resize path carry none), same as sim_collectives.sweep
            model = model.with_ips(ips)
        return model

    def _sim_ranked(
        self,
        prim: int,
        parallel_degree: int,
        transmission_size: int,
        bandwidth_graph: Sequence[Sequence[float]],
        latency_graph: Sequence[Sequence[float]],
        local_rank0_list: Optional[Sequence[int]],
    ) -> Strategy:
        from adapcc_tpu.primitives import BROADCAST, REDUCE

        # rank on the primitive actually being synthesized; primitives the
        # replay can't lower (scatter/gather family) rank on allreduce, the
        # superset schedule both halves of those collectives ride
        collective = {REDUCE: "reduce", BROADCAST: "broadcast"}.get(
            prim, "allreduce"
        )
        nbytes = transmission_size if transmission_size > 0 else DEFAULT_CHUNK_BYTES
        ranked = self.rank(
            self.candidates(
                parallel_degree, bandwidth_graph, latency_graph, local_rank0_list
            ),
            nbytes,
            bandwidth_graph,
            latency_graph,
            collective=collective,
        )
        winner = ranked[0]
        # provenance: the emitted XML records both the winning shape and
        # that a simulated ranking (not a measurement) chose it
        winner.strategy.synthesis = f"{winner.label}+sim-rank"
        winner.strategy.wire_dtype = self._choose_wire_dtype(
            winner.strategy, nbytes, bandwidth_graph, latency_graph
        )
        return winner.strategy

    def _choose_wire_dtype(
        self,
        strategy: Strategy,
        nbytes: int,
        bandwidth_graph,
        latency_graph,
    ) -> str:
        """Price the wire codecs on the strategy's bottleneck link and keep
        the cheapest — the quant half of the sim-rank pass.  A lockstep
        schedule advances at its slowest edge, so the codec's break-even is
        judged there: fat ICI links keep the fp32 wire (codec passes cost
        more than the saved bytes), a DCN-bottlenecked or degraded fabric
        flips to int8.  The choice rides the strategy XML, so the engine
        and hook execute exactly what was priced."""
        from adapcc_tpu.sim.cost_model import choose_wire_dtype

        model = self._cost_model(bandwidth_graph, latency_graph)
        edges = [
            (parent, child)
            for tree in strategy.trees
            for child, parent in tree.parent.items()
        ]
        if not edges:  # world=1: nothing crosses a wire
            return "off"
        bottleneck = max(
            (model.coeffs(s, d) for s, d in edges),
            key=lambda c: c.time(1 << 20),
        )
        choice, _ = choose_wire_dtype(
            strategy.world_size, max(1, int(nbytes)), bottleneck
        )
        return choice


def _infer_local_rank0s(ip_table: Sequence[str]) -> List[int]:
    """First rank of each run of equal ips is that host's master."""
    masters = []
    for r, ip in enumerate(ip_table):
        if r == 0 or ip_table[r - 1] != ip:
            masters.append(r)
    return masters
