"""Strategy synthesizer: policy switch over ParTrees / MILP / fixed shapes.

Mirrors the reference's policy dispatch (gurobi/synthesizer.py:44-62): the
default heuristic is ParTrees; an exact MILP formulation is available when a
solver backend exists.  Two TPU-native fixed policies (``ring`` and
``binary``) are added because on an ICI torus the regular schedules are often
optimal and need no profile data.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from adapcc_tpu.primitives import DEFAULT_CHUNK_BYTES
from adapcc_tpu.strategy.ir import Strategy
from adapcc_tpu.strategy.partrees import ParTrees
from adapcc_tpu.strategy.xml_io import emit_strategy_xml


class Synthesizer:
    """Generates a communication strategy from profiled lat/bw matrices."""

    def __init__(self, strategy_file: Optional[str], ip_table: Sequence[str], policy: str = "par-trees"):
        self.strategy_file = strategy_file
        self.ip_table = list(ip_table)
        self.policy = policy

    def generate_strategy(
        self,
        prim: int,
        parallel_degree: int,
        transmission_size: int,
        bandwidth_graph: Sequence[Sequence[float]],
        latency_graph: Sequence[Sequence[float]],
        local_rank0_list: Optional[Sequence[int]] = None,
    ) -> int:
        """Synthesize + persist the strategy XML; returns chunk bytes."""
        strategy = self.synthesize(
            prim, parallel_degree, transmission_size, bandwidth_graph, latency_graph, local_rank0_list
        )
        if self.strategy_file:
            emit_strategy_xml(strategy, self.strategy_file)
        return strategy.chunk_bytes

    def synthesize(
        self,
        prim: int,
        parallel_degree: int,
        transmission_size: int,
        bandwidth_graph: Sequence[Sequence[float]],
        latency_graph: Sequence[Sequence[float]],
        local_rank0_list: Optional[Sequence[int]] = None,
    ) -> Strategy:
        world = len(self.ip_table)
        if local_rank0_list is None:
            local_rank0_list = _infer_local_rank0s(self.ip_table)

        if self.policy == "par-trees":
            return ParTrees().synthesize(
                self.ip_table, local_rank0_list, parallel_degree, bandwidth_graph, latency_graph
            )
        if self.policy == "milp":
            from adapcc_tpu.strategy.solver import MilpSolver

            return MilpSolver().synthesize(
                self.ip_table,
                local_rank0_list,
                prim,
                parallel_degree,
                transmission_size,
                bandwidth_graph,
                latency_graph,
            )
        ips = {r: ip for r, ip in enumerate(self.ip_table)}
        if self.policy == "ring":
            s = Strategy.ring(world, max(1, parallel_degree), ips)
        elif self.policy == "binary":
            s = Strategy.binary(world, max(1, parallel_degree), ips)
        else:
            raise ValueError(f"unknown synthesis policy {self.policy!r}")
        s.synthesis = self.policy
        return s


def _infer_local_rank0s(ip_table: Sequence[str]) -> List[int]:
    """First rank of each run of equal ips is that host's master."""
    masters = []
    for r, ip in enumerate(ip_table):
        if r == 0 or ip_table[r - 1] != ip:
            masters.append(r)
    return masters
