"""Multi-round broadcast flow LP over arbitrary connectivity graphs.

The reference carries an exploratory CVXPY study (gurobi/code-gen/
cvxpy-broadcast-multi-round.py:43-60) formulating broadcast as a multi-round
flow problem with forwarding-rule constraints: a node may only forward data
it has already received in earlier rounds.  That study was Python-2-era and
never wired into the runtime; here it is reformulated for
``scipy.optimize.linprog`` (HiGHS) and made loadable into the schedule IR.

Formulation (unit data broadcast from ``source`` over ``R`` rounds):

    variables  f[e, r] ≥ 0   data moved on directed edge e during round r
               T[r]    ≥ 0   duration of round r
    foreach e, r:        f[e, r] ≤ bandwidth[e] · T[r]       (capacity)
    foreach v≠src, r:    Σ_out f[·, r] ≤ Σ_{r'<r} Σ_in f[·, r']   (forwarding)
    foreach v≠src:       Σ_r Σ_in f[·, r] ≥ 1                (delivery)
    minimize   Σ_r T[r]                                      (makespan)

The optimal per-round flows lower to :class:`~adapcc_tpu.strategy.ir`
``CommRound`` edge lists (an edge participates in round r when it carries
non-negligible flow), giving a broadcast schedule for irregular topologies
that tree synthesis cannot express.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple

import numpy as np

Edge = Tuple[int, int]


@dataclass
class FlowSolution:
    """LP output: per-round edge flows + round durations."""

    num_nodes: int
    source: int
    rounds: List[Dict[Edge, float]]  # flow per edge, per round
    durations: List[float]
    makespan: float

    def comm_rounds(self, threshold: float = 1e-6):
        """Lower to schedule-IR rounds (edges carrying > threshold flow).

        A ``CommRound`` executes as one ``ppermute``, which is a partial
        permutation — each rank sends to at most one peer and receives from
        at most one.  An LP round may fan flows out (one node feeding several
        in the same time slot), so it is split greedily into as many
        permutation sub-rounds as its maximum fan degree requires; heavier
        flows are scheduled first so the dominant traffic leads.
        """
        from adapcc_tpu.strategy.ir import CommRound

        out = []
        for flows in self.rounds:
            remaining = sorted(
                ((f, e) for e, f in flows.items() if f > threshold), reverse=True
            )
            while remaining:
                srcs, dsts, batch, deferred = set(), set(), [], []
                for f, (u, v) in remaining:
                    if u in srcs or v in dsts:
                        deferred.append((f, (u, v)))
                    else:
                        srcs.add(u)
                        dsts.add(v)
                        batch.append((u, v))
                out.append(CommRound(edges=tuple(sorted(batch))))
                remaining = deferred
        return out


def solve_broadcast_lp(
    num_nodes: int,
    edges: Sequence[Edge],
    bandwidth: Sequence[float],
    source: int = 0,
    num_rounds: int = 0,
) -> FlowSolution:
    """Solve the multi-round broadcast LP; raises if infeasible.

    ``edges`` are directed; pass both directions for full-duplex links.
    ``num_rounds=0`` picks ⌈log2(n)⌉ + 1 (enough for any connected graph a
    binomial-tree broadcast can cover; more rounds never hurt the optimum).
    """
    from scipy.optimize import linprog

    n, E = num_nodes, len(edges)
    if not 0 <= source < n:
        raise ValueError(f"source {source} outside [0, {n})")
    if len(bandwidth) != E:
        raise ValueError("bandwidth list must match edges")
    if len(set(edges)) != E:
        raise ValueError(
            "duplicate directed edges; merge parallel links into one edge "
            "with summed bandwidth"
        )
    R = num_rounds or (max(1, int(np.ceil(np.log2(max(n, 2))))) + 1)

    # variable layout: [f[e0,r0], f[e1,r0], ..., f[E-1,R-1], T[0..R-1]]
    nf = E * R
    nvar = nf + R

    def fi(e: int, r: int) -> int:
        return r * E + e

    c = np.zeros(nvar)
    c[nf:] = 1.0  # minimize Σ T_r

    A_ub: List[np.ndarray] = []
    b_ub: List[float] = []

    # capacity: f[e,r] − bw[e]·T[r] ≤ 0
    for r in range(R):
        for e in range(E):
            row = np.zeros(nvar)
            row[fi(e, r)] = 1.0
            row[nf + r] = -bandwidth[e]
            A_ub.append(row)
            b_ub.append(0.0)

    in_edges: List[List[int]] = [[] for _ in range(n)]
    out_edges: List[List[int]] = [[] for _ in range(n)]
    for e, (u, v) in enumerate(edges):
        out_edges[u].append(e)
        in_edges[v].append(e)

    # forwarding: what v sends in round r is bounded by what it held before
    for v in range(n):
        if v == source:
            continue
        for r in range(R):
            row = np.zeros(nvar)
            for e in out_edges[v]:
                row[fi(e, r)] = 1.0
            for rp in range(r):
                for e in in_edges[v]:
                    row[fi(e, rp)] -= 1.0
            A_ub.append(row)
            b_ub.append(0.0)

    # delivery: every non-source node receives ≥ 1 in total
    for v in range(n):
        if v == source:
            continue
        row = np.zeros(nvar)
        for r in range(R):
            for e in in_edges[v]:
                row[fi(e, r)] = -1.0
        A_ub.append(row)
        b_ub.append(-1.0)

    res = linprog(
        c, A_ub=np.array(A_ub), b_ub=np.array(b_ub), bounds=[(0, None)] * nvar,
        method="highs",
    )
    if not res.success:
        raise ValueError(f"broadcast LP infeasible: {res.message}")

    x = res.x
    rounds = [
        {edges[e]: float(x[fi(e, r)]) for e in range(E) if x[fi(e, r)] > 1e-9}
        for r in range(R)
    ]
    durations = [float(t) for t in x[nf:]]
    return FlowSolution(
        num_nodes=n,
        source=source,
        rounds=rounds,
        durations=durations,
        makespan=float(sum(durations)),
    )
