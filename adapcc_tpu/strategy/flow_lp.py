"""Multi-round broadcast flow LP over arbitrary connectivity graphs.

The reference carries an exploratory CVXPY study (gurobi/code-gen/
cvxpy-broadcast-multi-round.py:43-60) formulating broadcast as a multi-round
flow problem with forwarding-rule constraints: a node may only forward data
it has already received in earlier rounds.  That study was Python-2-era and
never wired into the runtime; here it is reformulated for
``scipy.optimize.linprog`` (HiGHS) and made loadable into the schedule IR.

Formulation (unit data broadcast from ``source`` over ``R`` rounds), as a
**multicast commodity LP**: one unit commodity per receiver ``d``, all
commodities sharing each edge's transmissions (one physical send serves
every commodity — the multicast property):

    variables  f[d, e, r] ≥ 0   commodity-d data on edge e during round r
               x[e, r]    ≥ 0   physical transmission on e during round r
               T[r]       ≥ 0   duration of round r
    foreach d, e, r:    f[d, e, r] ≤ x[e, r]                     (multicast)
    foreach e, r:       x[e, r] ≤ bandwidth[e] · T[r]            (capacity)
    foreach d, v≠src, r: Σ_{r'≤r} Σ_out f[d, ·, r'] ≤ Σ_{r'<r} Σ_in f[d, ·, r']
                                         (store-and-forward, time-expanded)
    foreach d:          Σ_r (Σ_in − Σ_out) f[d, ·, r] at d ≥ 1   (delivery)
    minimize   Σ_r T[r]                                          (makespan)

Delivery counts *net* inflow at the receiver, so data recirculating around a
cycle cancels out — gross-inflow formulations are unsound on any graph with
a cycle among non-source nodes (data bounced around a fast cycle would
satisfy them without ever crossing the source's slow uplink).

The schedule lowers from the **commodity flows** (per edge and round, the
max over commodities riding it), not the physical ``x``: the LP only bounds
``x`` between the commodity max and the capacity, so alternate optima can
park ``x`` mass on edges that carry no commodity at all — lowering from
``x`` could emit sends of data the sender never received.  The commodity
flows are exactly the traffic the broadcast semantics require, giving a
broadcast schedule for irregular topologies that tree synthesis cannot
express.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple

import numpy as np

Edge = Tuple[int, int]


@dataclass
class FlowSolution:
    """LP output: per-round edge flows + round durations."""

    num_nodes: int
    source: int
    # per round: max commodity flow per edge (the data the broadcast actually
    # needs on that edge — NOT the LP's physical x, which alternate optima
    # can inflate on edges carrying no commodity)
    rounds: List[Dict[Edge, float]]
    durations: List[float]
    makespan: float

    def comm_rounds(self, threshold: float = 1e-6):
        """Lower to schedule-IR rounds (edges carrying > threshold flow).

        A ``CommRound`` executes as one ``ppermute``, which is a partial
        permutation — each rank sends to at most one peer and receives from
        at most one.  An LP round may fan flows out (one node feeding several
        in the same time slot), so it is split greedily into as many
        permutation sub-rounds as its maximum fan degree requires; heavier
        flows are scheduled first so the dominant traffic leads.
        """
        from adapcc_tpu.strategy.ir import CommRound

        out = []
        for flows in self.rounds:
            remaining = sorted(
                ((f, e) for e, f in flows.items() if f > threshold), reverse=True
            )
            while remaining:
                srcs, dsts, batch, deferred = set(), set(), [], []
                for f, (u, v) in remaining:
                    if u in srcs or v in dsts:
                        deferred.append((f, (u, v)))
                    else:
                        srcs.add(u)
                        dsts.add(v)
                        batch.append((u, v))
                out.append(CommRound(edges=tuple(sorted(batch))))
                remaining = deferred
        return out


def _bfs_depths(n: int, out_neighbors: List[List[int]], source: int) -> List[int]:
    depth = [-1] * n
    depth[source] = 0
    frontier = [source]
    while frontier:
        nxt = []
        for u in frontier:
            for v in out_neighbors[u]:
                if depth[v] < 0:
                    depth[v] = depth[u] + 1
                    nxt.append(v)
        frontier = nxt
    return depth


def solve_broadcast_lp(
    num_nodes: int,
    edges: Sequence[Edge],
    bandwidth: Sequence[float],
    source: int = 0,
    num_rounds: int = 0,
) -> FlowSolution:
    """Solve the multi-round multicast broadcast LP; raises if infeasible.

    ``edges`` are directed; pass both directions for full-duplex links.
    ``num_rounds=0`` picks max(graph eccentricity from the source,
    ⌈log2(n)⌉ + 1): a sparse line graph needs its diameter in rounds, a
    dense one benefits from the extra pipelining slots; more rounds never
    hurt the optimum.
    """
    from scipy.optimize import linprog

    n, E = num_nodes, len(edges)
    if not 0 <= source < n:
        raise ValueError(f"source {source} outside [0, {n})")
    if len(bandwidth) != E:
        raise ValueError("bandwidth list must match edges")
    if len(set(edges)) != E:
        raise ValueError(
            "duplicate directed edges; merge parallel links into one edge "
            "with summed bandwidth"
        )
    bad = [e for e in edges if not (0 <= e[0] < n and 0 <= e[1] < n) or e[0] == e[1]]
    if bad:
        raise ValueError(f"edges outside [0, {n}) or self-loops: {bad}")

    in_edges: List[List[int]] = [[] for _ in range(n)]
    out_edges: List[List[int]] = [[] for _ in range(n)]
    out_neighbors: List[List[int]] = [[] for _ in range(n)]
    for e, (u, v) in enumerate(edges):
        out_edges[u].append(e)
        in_edges[v].append(e)
        out_neighbors[u].append(v)

    depths = _bfs_depths(n, out_neighbors, source)
    unreachable = [v for v in range(n) if depths[v] < 0]
    if unreachable:
        raise ValueError(f"broadcast LP infeasible: nodes {unreachable} unreachable from {source}")
    if num_rounds:
        R = num_rounds
    else:
        R = max(max(depths), max(1, int(np.ceil(np.log2(max(n, 2))))) + 1)

    receivers = [v for v in range(n) if v != source]
    D = len(receivers)

    # variable layout:
    #   f[d, e, r]  commodity flows        D·E·R
    #   x[e, r]     physical transmissions E·R
    #   T[r]        round durations        R
    nf = D * E * R
    nx = E * R
    nvar = nf + nx + R

    def fi(d: int, e: int, r: int) -> int:
        return (d * R + r) * E + e

    def xi(e: int, r: int) -> int:
        return nf + r * E + e

    c = np.zeros(nvar)
    c[nf + nx :] = 1.0  # minimize Σ T_r

    A_ub: List[np.ndarray] = []
    b_ub: List[float] = []

    for r in range(R):
        for e in range(E):
            # capacity: x[e,r] − bw[e]·T[r] ≤ 0
            row = np.zeros(nvar)
            row[xi(e, r)] = 1.0
            row[nf + nx + r] = -bandwidth[e]
            A_ub.append(row)
            b_ub.append(0.0)
            # multicast: each commodity rides the shared transmission
            for d in range(D):
                row = np.zeros(nvar)
                row[fi(d, e, r)] = 1.0
                row[xi(e, r)] = -1.0
                A_ub.append(row)
                b_ub.append(0.0)

    # store-and-forward per commodity, as time-expanded flow conservation:
    # everything v sent *through round r* is bounded by everything it
    # received *before round r*.  Bounding only the single round's sends
    # (instead of the cumulative) would let v re-send the same data every
    # round — combined with a cycle that amplifies flow without touching
    # the source.  Never applies to the source, which originates the data.
    for d in range(D):
        for v in range(n):
            if v == source:
                continue
            for r in range(R):
                row = np.zeros(nvar)
                for rp in range(r + 1):
                    for e in out_edges[v]:
                        row[fi(d, e, rp)] = 1.0
                for rp in range(r):
                    for e in in_edges[v]:
                        row[fi(d, e, rp)] -= 1.0
                A_ub.append(row)
                b_ub.append(0.0)

    # delivery: NET inflow of commodity d at its receiver ≥ 1 (gross inflow
    # would be satisfiable by recirculating data around a cycle)
    for d, dest in enumerate(receivers):
        row = np.zeros(nvar)
        for r in range(R):
            for e in in_edges[dest]:
                row[fi(d, e, r)] -= 1.0
            for e in out_edges[dest]:
                row[fi(d, e, r)] += 1.0
        A_ub.append(row)
        b_ub.append(-1.0)

    res = linprog(
        c, A_ub=np.array(A_ub), b_ub=np.array(b_ub), bounds=[(0, None)] * nvar,
        method="highs",
    )
    if not res.success:
        raise ValueError(f"broadcast LP infeasible: {res.message}")

    sol = res.x
    rounds = []
    for r in range(R):
        flows: Dict[Edge, float] = {}
        for e in range(E):
            need = max((sol[fi(d, e, r)] for d in range(D)), default=0.0)
            if need > 1e-9:
                flows[edges[e]] = float(need)
        rounds.append(flows)
    durations = [float(t) for t in sol[nf + nx :]]
    return FlowSolution(
        num_nodes=n,
        source=source,
        rounds=rounds,
        durations=durations,
        makespan=float(sum(durations)),
    )
