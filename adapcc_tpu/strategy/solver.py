"""MILP strategy solver (scipy/HiGHS re-formulation of the reference's Gurobi model).

The reference formulates strategy synthesis as a Gurobi MILP with binary
root-assignment variables, per-tree tensor shares, routing/flow variables and
a pipeline-aware makespan objective (gurobi/solver.py:143-208, SURVEY.md §2.2
P8).  Gurobi is proprietary and not part of this image, so this module keeps
the decision structure that matters — which masters root the parallel trees,
and how the tensor is split across them — and solves it exactly with
``scipy.optimize.milp`` (HiGHS):

    min  T
    s.t. Σ_g x_mg = 1                       each tree m picks one root
         Σ_m x_mg ≤ 1                       root diversity
         Σ_m s_m = 1                        tensor fully covered
         T ≥ lat_g·x_mg + size·k_g·s_m − M·(1−x_mg)   per (m, g)

where, for a candidate root g, ``lat_g`` is the summed per-level latency and
``k_g`` the summed per-level bottleneck inverse bandwidth of the heap tree
rooted at g (levels serialize, edges within a level run in parallel — the
same pipeline-aware completion model as the reference objective
solver.py:190-208).  Tree shapes themselves follow the ParTrees chain+heap
construction; the MILP chooses roots and shares.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from adapcc_tpu.primitives import DEFAULT_CHUNK_BYTES
from adapcc_tpu.strategy.ir import Strategy, Tree
from adapcc_tpu.strategy.partrees import (
    ParTrees,
    _attach_chains,
    _heap_tree_edges,
    _host_groups,
)


def _tree_cost_coeffs(
    order: Sequence[int],
    bw: Sequence[Sequence[float]],
    lat: Sequence[Sequence[float]],
):
    """(summed per-level latency, summed per-level max 1/bw) for the heap tree
    over ``order``."""
    children = _heap_tree_edges(order)
    depth = {order[0]: 0}
    levels: Dict[int, List[tuple]] = {}
    stack = [order[0]]
    while stack:
        p = stack.pop()
        for c in children.get(p, ()):
            depth[c] = depth[p] + 1
            levels.setdefault(depth[c], []).append((p, c))
            stack.append(c)
    lat_sum, inv_bw_sum = 0.0, 0.0
    for lvl in sorted(levels):
        edges = levels[lvl]
        lat_sum += max(lat[p][c] for p, c in edges)
        inv_bw_sum += max(1.0 / max(bw[p][c], 1e-9) for p, c in edges)
    return lat_sum, inv_bw_sum


class MilpSolver:
    def synthesize(
        self,
        ip_table: Sequence[str],
        local_rank0_list: Sequence[int],
        prim: int,
        parallel_degree: int,
        transmission_size: int,
        bandwidth_graph: Sequence[Sequence[float]],
        latency_graph: Sequence[Sequence[float]],
    ) -> Strategy:
        from scipy.optimize import LinearConstraint, milp

        world = len(ip_table)
        masters = list(local_rank0_list)
        n = len(masters)
        m_trees = min(max(1, parallel_degree), n)
        size = float(max(transmission_size, 1))

        # candidate tree per root: ring rotation of masters starting at g
        rotations = {
            g: [masters[(i + k) % n] for k in range(n)] for i, g in enumerate(masters)
        }
        lat_c = np.zeros(n)
        bw_c = np.zeros(n)
        for i, g in enumerate(masters):
            lat_c[i], bw_c[i] = _tree_cost_coeffs(rotations[g], bandwidth_graph, latency_graph)

        # variables: x[m,g] (n*m_trees binaries), s[m] (m_trees), T
        nx = m_trees * n
        nvar = nx + m_trees + 1
        xi = lambda m, g: m * n + g
        si = lambda m: nx + m
        Ti = nvar - 1

        c = np.zeros(nvar)
        c[Ti] = 1.0

        A_rows, lb, ub = [], [], []

        for m in range(m_trees):  # Σ_g x_mg = 1
            row = np.zeros(nvar)
            for g in range(n):
                row[xi(m, g)] = 1.0
            A_rows.append(row); lb.append(1.0); ub.append(1.0)
        for g in range(n):  # Σ_m x_mg ≤ 1
            row = np.zeros(nvar)
            for m in range(m_trees):
                row[xi(m, g)] = 1.0
            A_rows.append(row); lb.append(0.0); ub.append(1.0)
        row = np.zeros(nvar)  # Σ_m s_m = 1
        for m in range(m_trees):
            row[si(m)] = 1.0
        A_rows.append(row); lb.append(1.0); ub.append(1.0)

        big_m = float(lat_c.max() + size * bw_c.max()) + 1.0
        for m in range(m_trees):  # T ≥ lat_g·x + size·k_g·s − M(1−x)
            for g in range(n):
                row = np.zeros(nvar)
                row[Ti] = 1.0
                row[xi(m, g)] = -(lat_c[g] + big_m)
                row[si(m)] = -size * bw_c[g]
                A_rows.append(row); lb.append(-big_m); ub.append(np.inf)

        integrality = np.zeros(nvar)
        integrality[:nx] = 1
        bounds_lb = np.zeros(nvar)
        bounds_ub = np.full(nvar, np.inf)
        bounds_ub[:nx] = 1.0

        from scipy.optimize import Bounds

        res = milp(
            c=c,
            constraints=LinearConstraint(np.array(A_rows), np.array(lb), np.array(ub)),
            integrality=integrality,
            bounds=Bounds(bounds_lb, bounds_ub),
        )
        if not res.success:
            # solver hiccup → fall back to the heuristic
            return ParTrees().synthesize(
                ip_table, local_rank0_list, parallel_degree, bandwidth_graph, latency_graph
            )

        groups = _host_groups(ip_table, masters)
        ips = {r: ip for r, ip in enumerate(ip_table)}
        trees: List[Tree] = []
        shares: List[float] = []
        for m in range(m_trees):
            g = int(np.argmax(res.x[m * n : (m + 1) * n]))
            order = rotations[masters[g]]
            children = _heap_tree_edges(order)
            _attach_chains(children, order, groups)
            trees.append(Tree(order[0], children, ips))
            shares.append(float(res.x[si(m)]))
        return Strategy(trees, world, DEFAULT_CHUNK_BYTES, shares=shares)
