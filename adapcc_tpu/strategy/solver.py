"""MILP strategy solver (scipy/HiGHS re-formulation of the reference's Gurobi model).

The reference formulates strategy synthesis as a Gurobi MILP with binary
root-assignment variables, per-tree tensor shares, routing/flow variables and
a pipeline-aware makespan objective (gurobi/solver.py:143-208, SURVEY.md §2.2
P8).  Gurobi is proprietary and not part of this image, so this module keeps
the decision structure that matters — which masters root the parallel trees,
and how the tensor is split across them — and solves it exactly with
``scipy.optimize.milp`` (HiGHS):

    min  T
    s.t. Σ_g x_mg = 1                       each tree m picks one root
         Σ_m x_mg ≤ 1                       root diversity
         Σ_m s_m = 1                        tensor fully covered
         T ≥ lat_g·x_mg + size·k_g·s_m − M·(1−x_mg)   per (m, g)

where, for a candidate root g, ``lat_g`` is the summed per-level latency and
``k_g`` the summed per-level bottleneck inverse bandwidth of the heap tree
rooted at g (levels serialize, edges within a level run in parallel — the
same pipeline-aware completion model as the reference objective
solver.py:190-208).  Tree shapes themselves follow the ParTrees chain+heap
construction; the MILP chooses roots and shares.
"""

from __future__ import annotations

import math
from typing import Dict, List, Sequence

import numpy as np

from adapcc_tpu.primitives import (
    ALLTOALL,
    BOARDCAST,
    DEFAULT_CHUNK_BYTES,
    REDUCE,
)
from adapcc_tpu.strategy.ir import Strategy, Tree
from adapcc_tpu.strategy.partrees import (
    ParTrees,
    _attach_chains,
    _heap_tree_edges,
    _host_groups,
)


def _edge_lat_invbw(
    prim: int,
    lat: "np.ndarray",
    inv_bw: "np.ndarray",
    i: int,
    j: int,
    load: float = 1.0,
):
    """Effective (latency, 1/bandwidth·load) of tree edge ``i parents j``.

    The reference differentiates per-primitive link loads N_mij
    (gurobi/solver.py:143-176): broadcast traffic rides parent→child once,
    reduce rides child→parent once (aggregation keeps it one payload on a
    tree), allreduce serializes both directions, and alltoall carries one
    distinct flow per destination behind the edge (``load`` = that
    multiplicity; each flow is 1/n of the payload, scaled by the caller).
    """
    if prim == BOARDCAST:
        return lat[i][j], inv_bw[i][j]
    if prim == REDUCE:
        return lat[j][i], inv_bw[j][i]
    if prim == ALLTOALL:
        # per-pair payloads cross in both directions; multiplicity = load
        return lat[i][j] + lat[j][i], (inv_bw[i][j] + inv_bw[j][i]) * load
    # ALLREDUCE (and anything tree-shaped by default): reduce up + broadcast
    # down, each direction carrying the tree's share once
    return lat[i][j] + lat[j][i], inv_bw[i][j] + inv_bw[j][i]


def _subtree_sizes(children: Dict[int, List[int]], root: int) -> Dict[int, int]:
    sizes: Dict[int, int] = {}
    order: List[int] = []
    stack = [root]
    while stack:
        r = stack.pop()
        order.append(r)
        stack.extend(children.get(r, ()))
    for r in reversed(order):
        sizes[r] = 1 + sum(sizes[c] for c in children.get(r, ()))
    return sizes


def _tree_cost_coeffs(
    order: Sequence[int],
    bw: Sequence[Sequence[float]],
    lat: Sequence[Sequence[float]],
    prim: int = -1,
):
    """(summed per-level latency, summed per-level max 1/bw·load) for the
    heap tree over ``order``, with the per-primitive edge model of
    :func:`_edge_lat_invbw`."""
    children = _heap_tree_edges(order)
    n = len(order)
    sizes = _subtree_sizes(children, order[0])
    lat_m = np.asarray(lat, dtype=float)
    bw_m = np.asarray(bw, dtype=float)
    inv_bw = 1.0 / np.maximum(bw_m, 1e-9)
    depth = {order[0]: 0}
    levels: Dict[int, List[tuple]] = {}
    stack = [order[0]]
    while stack:
        p = stack.pop()
        for c in children.get(p, ()):
            depth[c] = depth[p] + 1
            levels.setdefault(depth[c], []).append((p, c))
            stack.append(c)
    lat_sum, inv_bw_sum = 0.0, 0.0
    for lvl in sorted(levels):
        edges = levels[lvl]
        costs = [
            _edge_lat_invbw(
                prim, lat_m, inv_bw, p, c,
                load=sizes[c] / n if prim == ALLTOALL else 1.0,
            )
            for p, c in edges
        ]
        lat_sum += max(l for l, _ in costs)
        inv_bw_sum += max(k for _, k in costs)
    return lat_sum, inv_bw_sum


def modeled_makespan(
    strategy,
    masters: Sequence[int],
    prim: int,
    transmission_size: int,
    bandwidth_graph: Sequence[Sequence[float]],
    latency_graph: Sequence[Sequence[float]],
) -> float:
    """The routing MILP's pipeline-aware bottleneck objective, evaluated on
    *any* synthesized strategy: max over used trees and their inter-master
    edges of ``lat + size·(1/bw·load)·share`` (reference objective
    gurobi/solver.py:190-208).  Puts the heuristic and the solver on one
    scale — the property that justifies the solver's existence is
    ``makespan(milp) ≤ makespan(partrees)`` on the same profile.

    The MAX across trees assumes parallel transmissions run concurrently —
    true in the reference via per-tree pthread pairs (allreduce.cu:735-742)
    and true here via the merged-round executor (engine._run_merged), which
    combines all trees' round-k edges into shared ppermutes.  Under the
    sequential fallback (single tree, skewed shares, or
    ADAPCC_MERGE_ROUNDS=0) tree times ADD instead, and this objective is a
    lower bound rather than an estimate.
    """
    bw = np.asarray(bandwidth_graph, dtype=float)
    lat = np.asarray(latency_graph, dtype=float)
    inv_bw = 1.0 / np.maximum(bw, 1e-9)
    mset = set(masters)
    n = len(masters)
    size = float(max(transmission_size, 1))
    worst = 0.0
    for tree, share in zip(strategy.trees, strategy.tree_shares()):
        if share <= 0.0:
            continue
        # project to the inter-master edges (chains are intra-host, not
        # modeled by the routing MILP) and count masters behind each edge
        # for the ALLTOALL flow multiplicity
        mchildren = {
            p: [c for c in cs if c in mset]
            for p, cs in tree.children.items()
            if p in mset
        }
        sizes = _subtree_sizes(mchildren, tree.root) if tree.root in mset else {}
        for p, cs in mchildren.items():
            for c in cs:
                load = sizes.get(c, 1) / n if prim == ALLTOALL else 1.0
                l, k = _edge_lat_invbw(prim, lat, inv_bw, p, c, load=load)
                worst = max(worst, l + size * k * share)
    return worst


#: above this many masters the routing MILP (O(M·n²) binaries) is skipped in
#: favor of the rotation model, which only chooses roots and shares
ROUTING_MILP_MAX_MASTERS = 12

#: branch-and-bound budget for the routing MILP; on timeout HiGHS reports
#: failure and synthesis falls back to the rotation model, bounding the
#: topology-reconstruction stall a hard instance could cause
ROUTING_MILP_TIME_LIMIT_S = 10.0

#: candidate-space pruning for the routing MILP: only the top-k masters by
#: BDP (bandwidth·delay over their inter-master links — ParTrees' master
#: ranking) may root a tree, and each master considers only its
#: ``ROUTING_MILP_PARENT_CANDIDATES`` cheapest upstream edges (plus the
#: best-BDP master, which stays a universal parent so an arborescence always
#: exists).  Measured on the world=64 synthetic pod this cuts HiGHS
#: branch-and-bound from ~4.3 s to ~0.1 s with the SAME optimal makespan —
#: the candidate graph keeps every edge the optimum actually uses.  An
#: infeasible pruned instance (adversarial profile) retries unpruned within
#: the time limit before falling back to the rotation model.
ROUTING_MILP_ROOT_CANDIDATES = 4
ROUTING_MILP_PARENT_CANDIDATES = 3

#: wall-time budget the pruned synthesis is expected to meet at pod scale
#: (world=64); benchmarks/synthesis_scale.py emits it as a regression row
MILP_SYNTH_BUDGET_S = 1.0


def per_tree_chunk_bytes(
    shares: Sequence[float], transmission_size: int
) -> List[int]:
    """The solver's per-tree chunk output (reference c_m, gurobi/
    solver.py:211): each tree pipelines its segment at the default chunk,
    clamped to the segment's own share of the payload — a tree carrying a
    sliver must not run a single over-sized chunk with no pipeline at all."""
    size = max(1, int(transmission_size))
    return [
        max(1, min(DEFAULT_CHUNK_BYTES, int(math.ceil(size * s))))
        for s in shares
    ]


class MilpSolver:
    def synthesize(
        self,
        ip_table: Sequence[str],
        local_rank0_list: Sequence[int],
        prim: int,
        parallel_degree: int,
        transmission_size: int,
        bandwidth_graph: Sequence[Sequence[float]],
        latency_graph: Sequence[Sequence[float]],
    ) -> Strategy:
        """Routing MILP when the master count permits, else the rotation
        model; both fall back to ParTrees on solver failure.  The routing
        instance is pruned (top-k roots by BDP + k-cheapest parent
        candidates); a *provably infeasible* pruned instance retries
        unpruned inside ``_synthesize_routing`` — a timeout does NOT retry,
        so the reconstruction stall stays bounded by one time limit."""
        if 1 < len(local_rank0_list) <= ROUTING_MILP_MAX_MASTERS:
            strategy = self._synthesize_routing(
                ip_table, local_rank0_list, prim, parallel_degree,
                transmission_size, bandwidth_graph, latency_graph,
            )
            if strategy is not None:
                return strategy
        return self._synthesize_rotation(
            ip_table, local_rank0_list, prim, parallel_degree,
            transmission_size, bandwidth_graph, latency_graph,
        )

    # -- full routing formulation (reference solver.py x_ijf + flow) -----------

    def _synthesize_routing(
        self,
        ip_table: Sequence[str],
        local_rank0_list: Sequence[int],
        prim: int,
        parallel_degree: int,
        transmission_size: int,
        bandwidth_graph: Sequence[Sequence[float]],
        latency_graph: Sequence[Sequence[float]],
        prune: bool = True,
    ) -> "Strategy | None":
        """Choose the actual inter-host tree edges, not just the root.

        Per tree m over the n masters:

            r[m,g]   binary   g roots tree m        (Σ_g r = 1; Σ_m r_mg ≤ 1)
            e[m,i,j] binary   i parents j           (Σ_i e_mij = 1 − r_mj)
            f[m,i,j] ≥ 0      flow, conservation    (in − out = 1 − n·r_mj)
                              f ≤ (n−1)·e           (flow rides chosen edges)
            s[m] ≥ 0          tensor share          (Σ s = 1; a share may be
                              0 — that tree then carries nothing)
            u[m]     binary   tree m is used        (s_m ≤ u_m)
            T ≥ lat·e + size·k·s_m − M_ij(1−e) − M_ij(1−u_m)   per (m,i,j)

        The flow system forces each tree to be a spanning arborescence (the
        reference's flow-conservation big-M constraints, solver.py:143-176);
        the per-edge T bound is the pipeline-aware bottleneck objective
        (chunks pipeline, so completion tracks the slowest active link;
        solver.py:190-208).  The ``u`` gate keeps a zero-share tree's edges
        from bounding T (its latencies would otherwise inflate the optimum).
        ``(lat, k)`` per edge follow the per-primitive link-load model of
        :func:`_edge_lat_invbw` (reference N_mij, solver.py:143-176); for
        ALLTOALL the multiplicity is the flow variable itself (number of
        destinations behind the edge) with shares pinned uniform so the term
        stays linear.  ``M_ij`` is per-edge (the edge's own worst cost) —
        one global M derived from a near-dead profiled link would dwarf
        every real coefficient and let tolerance-sized violations erase the
        objective.  Returns None when HiGHS fails or times out.
        """
        from scipy.optimize import Bounds, LinearConstraint, milp
        from scipy.sparse import csr_matrix

        world = len(ip_table)
        masters = list(local_rank0_list)
        n = len(masters)
        m_trees = min(max(1, parallel_degree), n)
        size = float(max(transmission_size, 1))
        bw = np.asarray(bandwidth_graph, dtype=float)
        lat = np.asarray(latency_graph, dtype=float)

        # variable layout per tree m: r[g] (n), e[i,j] (n²), f[i,j] (n²);
        # then s[m] (m_trees), u[m] (m_trees) and T
        per_tree = n + 2 * n * n
        nvar = m_trees * per_tree + 2 * m_trees + 1
        Ti = nvar - 1

        def ri(m, g):
            return m * per_tree + g

        def ei(m, i, j):
            return m * per_tree + n + i * n + j

        def fi(m, i, j):
            return m * per_tree + n + n * n + i * n + j

        def si(m):
            return m_trees * per_tree + m

        def ui(m):
            return m_trees * per_tree + m_trees + m

        c = np.zeros(nvar)
        c[Ti] = 1.0

        # sparse triplet assembly: dense length-nvar rows would be >99% zeros
        # and cost ~100 MB at the size guard
        rows_i: List[int] = []
        cols: List[int] = []
        vals: List[float] = []
        lb: List[float] = []
        ub: List[float] = []

        def add(entries, lo, hi):
            r = len(lb)
            for col, val in entries:
                rows_i.append(r)
                cols.append(col)
                vals.append(val)
            lb.append(lo)
            ub.append(hi)

        for m in range(m_trees):
            # one root
            add([(ri(m, g), 1.0) for g in range(n)], 1.0, 1.0)
            for j in range(n):
                # parent count: Σ_i e[i,j] + r[j] = 1
                add(
                    [(ri(m, j), 1.0)]
                    + [(ei(m, i, j), 1.0) for i in range(n) if i != j],
                    1.0, 1.0,
                )
                # flow conservation: Σ_i f[i,j] − Σ_k f[j,k] = 1 − n·r[j]
                add(
                    [(ri(m, j), float(n))]
                    + [(fi(m, i, j), 1.0) for i in range(n) if i != j]
                    + [(fi(m, j, k), -1.0) for k in range(n) if k != j],
                    1.0, 1.0,
                )
            for i in range(n):
                for j in range(n):
                    if i != j:
                        # flow rides chosen edges: f ≤ (n−1)·e
                        add(
                            [(fi(m, i, j), 1.0), (ei(m, i, j), -(n - 1.0))],
                            -np.inf, 0.0,
                        )

        # root diversity across trees
        for g in range(n):
            add([(ri(m, g), 1.0) for m in range(m_trees)], 0.0, 1.0)

        # shares cover the tensor; a tree's share only counts when it is used
        add([(si(m), 1.0) for m in range(m_trees)], 1.0, 1.0)
        for m in range(m_trees):
            add([(si(m), 1.0), (ui(m), -1.0)], -np.inf, 0.0)

        # pipeline-aware bottleneck with per-primitive link loads
        # (_edge_lat_invbw; reference N_mij solver.py:143-176):
        #   T ≥ lat·e + size·k·s − M_ij(1−e) − M_ij(1−u)
        # with the big-M per edge (that edge's own worst-case cost).  For
        # ALLTOALL the bandwidth term rides the flow variable (multiplicity =
        # destinations behind the edge, each a 1/n payload) with shares
        # pinned uniform so the product stays linear.
        is_a2a = prim == ALLTOALL
        lat_mx = np.zeros((n, n))
        inv_bw = np.zeros((n, n))
        for a in range(n):
            for b in range(n):
                if a != b:
                    lat_mx[a][b] = lat[masters[a]][masters[b]]
                    inv_bw[a][b] = 1.0 / max(bw[masters[a]][masters[b]], 1e-9)

        # candidate-space pruning (see ROUTING_MILP_ROOT_CANDIDATES): rank
        # masters by BDP over their inter-master links, keep the top-k as
        # root candidates, and give each child only its k cheapest upstream
        # edges plus the best-BDP master.  Variables outside the candidate
        # graph are fixed to 0 through their bounds, which shrinks the
        # branch-and-bound tree without touching the constraint structure.
        roots_ok = set(range(n))
        parent_ok = {j: set(i for i in range(n) if i != j) for j in range(n)}
        if prune and n > 2:
            bdp = sorted(
                (
                    (
                        sum(
                            bw[masters[i]][masters[j]] * lat[masters[i]][masters[j]]
                            for j in range(n)
                            if j != i
                        ),
                        -i,
                    )
                    for i in range(n)
                ),
                reverse=True,
            )
            ranked = [-neg for _, neg in bdp]
            k_roots = max(m_trees, ROUTING_MILP_ROOT_CANDIDATES)
            roots_ok = set(ranked[:k_roots])
            best = ranked[0]
            for j in range(n):
                costs = []
                for i in range(n):
                    if i == j:
                        continue
                    lat_e, k_e = _edge_lat_invbw(prim, lat_mx, inv_bw, i, j)
                    costs.append((lat_e + size * k_e, i))
                costs.sort()
                keep = {i for _, i in costs[:ROUTING_MILP_PARENT_CANDIDATES]}
                keep.add(best)
                keep.discard(j)
                parent_ok[j] = keep
        for m in range(m_trees):
            for i in range(n):
                for j in range(n):
                    if i == j:
                        continue
                    lat_eff, k_eff = _edge_lat_invbw(prim, lat_mx, inv_bw, i, j)
                    if is_a2a:
                        per_flow = size * k_eff / (n * m_trees)
                        m_ij = lat_eff + per_flow * (n - 1.0) + 1.0
                        add(
                            [
                                (Ti, 1.0),
                                (ei(m, i, j), -(lat_eff + m_ij)),
                                (fi(m, i, j), -per_flow),
                                (ui(m), -m_ij),
                            ],
                            -2.0 * m_ij, np.inf,
                        )
                    else:
                        m_ij = lat_eff + size * k_eff + 1.0
                        add(
                            [
                                (Ti, 1.0),
                                (ei(m, i, j), -(lat_eff + m_ij)),
                                (si(m), -size * k_eff),
                                (ui(m), -m_ij),
                            ],
                            -2.0 * m_ij, np.inf,
                        )

        integrality = np.zeros(nvar)
        bounds_lb = np.zeros(nvar)
        bounds_ub = np.full(nvar, np.inf)
        for m in range(m_trees):
            integrality[ui(m)] = 1
            bounds_ub[ui(m)] = 1.0
            if is_a2a:
                # alltoall payloads are per-pair, not a shardable tensor:
                # every tree carries an equal slice of the pairs
                bounds_lb[si(m)] = bounds_ub[si(m)] = 1.0 / m_trees
            for g in range(n):
                integrality[ri(m, g)] = 1
                bounds_ub[ri(m, g)] = 1.0 if g in roots_ok else 0.0
            for i in range(n):
                for j in range(n):
                    integrality[ei(m, i, j)] = 1
                    allowed = i != j and i in parent_ok[j]
                    bounds_ub[ei(m, i, j)] = 1.0 if allowed else 0.0
                    bounds_ub[fi(m, i, j)] = float(n - 1) if allowed else 0.0

        A = csr_matrix(
            (vals, (rows_i, cols)), shape=(len(lb), nvar), dtype=float
        )
        res = milp(
            c=c,
            constraints=LinearConstraint(A, np.array(lb), np.array(ub)),
            integrality=integrality,
            bounds=Bounds(bounds_lb, bounds_ub),
            options={"time_limit": ROUTING_MILP_TIME_LIMIT_S},
        )
        if not res.success or res.x is None:
            # status 2 = proven infeasible: only then can pruning itself be
            # the culprit, so retry once with the full candidate space.  A
            # timeout (status 1) must NOT retry — the unpruned instance is
            # strictly harder, and the reconstruction stall is documented
            # as bounded by one ROUTING_MILP_TIME_LIMIT_S
            if prune and getattr(res, "status", None) == 2:
                return self._synthesize_routing(
                    ip_table, local_rank0_list, prim, parallel_degree,
                    transmission_size, bandwidth_graph, latency_graph,
                    prune=False,
                )
            return None

        groups = _host_groups(ip_table, masters)
        ips = {r: ip for r, ip in enumerate(ip_table)}
        trees: List[Tree] = []
        shares: List[float] = []
        for m in range(m_trees):
            children: Dict[int, List[int]] = {}
            root = masters[int(np.argmax([res.x[ri(m, g)] for g in range(n)]))]
            for i in range(n):
                for j in range(n):
                    if i != j and res.x[ei(m, i, j)] > 0.5:
                        children.setdefault(masters[i], []).append(masters[j])
            _attach_chains(children, masters, groups)
            trees.append(Tree(root, children, ips))
            shares.append(float(res.x[si(m)]))
        return Strategy(
            trees, world, DEFAULT_CHUNK_BYTES, shares=shares,
            synthesis="milp-routing",
            tree_chunk_bytes=per_tree_chunk_bytes(shares, transmission_size),
        )

    # -- rotation formulation (roots + shares over ParTrees shapes) ------------

    def _synthesize_rotation(
        self,
        ip_table: Sequence[str],
        local_rank0_list: Sequence[int],
        prim: int,
        parallel_degree: int,
        transmission_size: int,
        bandwidth_graph: Sequence[Sequence[float]],
        latency_graph: Sequence[Sequence[float]],
    ) -> Strategy:
        from scipy.optimize import LinearConstraint, milp

        world = len(ip_table)
        masters = list(local_rank0_list)
        n = len(masters)
        m_trees = min(max(1, parallel_degree), n)
        size = float(max(transmission_size, 1))

        # candidate tree per root: ring rotation of masters starting at g
        rotations = {
            g: [masters[(i + k) % n] for k in range(n)] for i, g in enumerate(masters)
        }
        lat_c = np.zeros(n)
        bw_c = np.zeros(n)
        for i, g in enumerate(masters):
            lat_c[i], bw_c[i] = _tree_cost_coeffs(
                rotations[g], bandwidth_graph, latency_graph, prim
            )

        # variables: x[m,g] (n*m_trees binaries), s[m], u[m] (m_trees each), T
        nx = m_trees * n
        nvar = nx + 2 * m_trees + 1
        xi = lambda m, g: m * n + g
        si = lambda m: nx + m
        ui = lambda m: nx + m_trees + m
        Ti = nvar - 1

        c = np.zeros(nvar)
        c[Ti] = 1.0

        A_rows, lb, ub = [], [], []

        for m in range(m_trees):  # Σ_g x_mg = 1
            row = np.zeros(nvar)
            for g in range(n):
                row[xi(m, g)] = 1.0
            A_rows.append(row); lb.append(1.0); ub.append(1.0)
        for g in range(n):  # Σ_m x_mg ≤ 1
            row = np.zeros(nvar)
            for m in range(m_trees):
                row[xi(m, g)] = 1.0
            A_rows.append(row); lb.append(0.0); ub.append(1.0)
        row = np.zeros(nvar)  # Σ_m s_m = 1
        for m in range(m_trees):
            row[si(m)] = 1.0
        A_rows.append(row); lb.append(1.0); ub.append(1.0)
        for m in range(m_trees):  # s_m ≤ u_m (share only on used trees)
            row = np.zeros(nvar)
            row[si(m)] = 1.0
            row[ui(m)] = -1.0
            A_rows.append(row); lb.append(-np.inf); ub.append(0.0)

        big_m = float(lat_c.max() + size * bw_c.max()) + 1.0
        # T ≥ lat_g·x + size·k_g·s − M(1−x) − M(1−u): an unused (share-0)
        # tree's rotation latency must not bound T (same gate as the routing
        # formulation)
        for m in range(m_trees):
            for g in range(n):
                row = np.zeros(nvar)
                row[Ti] = 1.0
                row[xi(m, g)] = -(lat_c[g] + big_m)
                row[si(m)] = -size * bw_c[g]
                row[ui(m)] = -big_m
                A_rows.append(row); lb.append(-2.0 * big_m); ub.append(np.inf)

        integrality = np.zeros(nvar)
        integrality[:nx] = 1
        bounds_lb = np.zeros(nvar)
        bounds_ub = np.full(nvar, np.inf)
        bounds_ub[:nx] = 1.0
        for m in range(m_trees):
            integrality[ui(m)] = 1
            bounds_ub[ui(m)] = 1.0
            if prim == ALLTOALL:
                # same invariant as the routing formulation: alltoall
                # payloads are per-pair, not a shardable tensor — shares
                # stay uniform (the per-flow cost model assumes it)
                bounds_lb[si(m)] = bounds_ub[si(m)] = 1.0 / m_trees

        from scipy.optimize import Bounds

        res = milp(
            c=c,
            constraints=LinearConstraint(np.array(A_rows), np.array(lb), np.array(ub)),
            integrality=integrality,
            bounds=Bounds(bounds_lb, bounds_ub),
        )
        if not res.success:
            # solver hiccup → fall back to the heuristic, and say so in the
            # strategy provenance
            fallback = ParTrees().synthesize(
                ip_table, local_rank0_list, parallel_degree, bandwidth_graph, latency_graph
            )
            fallback.synthesis = "partrees-fallback"
            return fallback

        groups = _host_groups(ip_table, masters)
        ips = {r: ip for r, ip in enumerate(ip_table)}
        trees: List[Tree] = []
        shares: List[float] = []
        for m in range(m_trees):
            g = int(np.argmax(res.x[m * n : (m + 1) * n]))
            order = rotations[masters[g]]
            children = _heap_tree_edges(order)
            _attach_chains(children, order, groups)
            trees.append(Tree(order[0], children, ips))
            shares.append(float(res.x[si(m)]))
        return Strategy(
            trees, world, DEFAULT_CHUNK_BYTES, shares=shares,
            synthesis="milp-rotation",
            tree_chunk_bytes=per_tree_chunk_bytes(shares, transmission_size),
        )
