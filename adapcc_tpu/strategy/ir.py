"""Schedule IR: spanning trees lowered to per-round partial permutations.

The reference's native engine walks each strategy tree with `treeDFS` and
builds a per-rank role table `{precedents, subsequents, siblingIdx}`
(reference csrc/allreduce.cu:52-104, csrc/include/trans.h:45-53), then runs a
per-chunk recv→reduce→send pipeline in persistent pthreads.  On TPU the data
plane is XLA: we lower each tree to a static list of **communication rounds**,
where every round is a partial permutation (distinct sources, distinct
destinations) — exactly the contract of `jax.lax.ppermute`.  The reduction up
the tree and the broadcast down the tree become masked ppermute+select rounds
inside one compiled program; pipelining across chunks is XLA's / Pallas'
concern, not a host thread's.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class CommRound:
    """One communication round: a partial permutation of rank→rank sends.

    ``edges`` is a tuple of ``(src, dst)`` pairs with all sources distinct and
    all destinations distinct, so one round maps 1:1 onto one
    ``jax.lax.ppermute``.
    """

    edges: Tuple[Tuple[int, int], ...]

    def __post_init__(self) -> None:
        srcs = [s for s, _ in self.edges]
        dsts = [d for _, d in self.edges]
        if len(set(srcs)) != len(srcs) or len(set(dsts)) != len(dsts):
            raise ValueError(f"round is not a partial permutation: {self.edges}")

    @property
    def sources(self) -> Tuple[int, ...]:
        return tuple(s for s, _ in self.edges)

    @property
    def destinations(self) -> Tuple[int, ...]:
        return tuple(d for _, d in self.edges)


class Tree:
    """One spanning tree of ranks (one parallel transmission).

    Mirrors the information content of a ``<root …><gpu …/></root>`` strategy
    element (reference strategy/*.xml; parse loop csrc/allreduce.cu:52-104)
    without any of the reference's staging-buffer machinery: just parent /
    children / sibling-index maps plus the rank→ip map used to classify
    intra- vs inter-host edges.
    """

    def __init__(
        self,
        root: int,
        children: Dict[int, List[int]],
        ips: Optional[Dict[int, str]] = None,
    ) -> None:
        self.root = root
        self.children: Dict[int, List[int]] = {r: list(c) for r, c in children.items()}
        self.ips: Dict[int, str] = dict(ips or {})
        self.parent: Dict[int, int] = {}
        for p, cs in self.children.items():
            for c in cs:
                if c in self.parent:
                    raise ValueError(f"rank {c} has two parents in tree rooted at {root}")
                self.parent[c] = p
        self._validate()
        self._reduce_rounds_cache: Optional[List[CommRound]] = None
        self._broadcast_rounds_cache: Optional[List[CommRound]] = None

    def _validate(self) -> None:
        seen = set()
        stack = [self.root]
        while stack:
            r = stack.pop()
            if r in seen:
                raise ValueError(f"cycle through rank {r} in tree rooted at {self.root}")
            seen.add(r)
            stack.extend(self.children.get(r, ()))
        dangling = set(self.parent) - seen
        if dangling:
            raise ValueError(f"ranks {sorted(dangling)} unreachable from root {self.root}")
        self._ranks = seen

    # -- structure queries -----------------------------------------------------

    @property
    def ranks(self) -> frozenset:
        return frozenset(self._ranks)

    def precedents(self, rank: int) -> List[int]:
        """Children of ``rank`` — who sends to it during reduce (reference
        trans.h role naming)."""
        return list(self.children.get(rank, ()))

    def subsequent(self, rank: int) -> Optional[int]:
        """Parent of ``rank`` — where it sends during reduce; None at root."""
        return self.parent.get(rank)

    def sibling_index(self, rank: int) -> int:
        """Position among the parent's children (the reference's siblingIdx,
        which indexed the receiver's staging-buffer slot)."""
        p = self.parent.get(rank)
        if p is None:
            return 0
        return self.children[p].index(rank)

    def subtree(self, rank: int) -> frozenset:
        out = set()
        stack = [rank]
        while stack:
            r = stack.pop()
            out.add(r)
            stack.extend(self.children.get(r, ()))
        return frozenset(out)

    def height(self, rank: int) -> int:
        heights: Dict[int, int] = {}
        for r in self._postorder(rank):
            cs = self.children.get(r, ())
            heights[r] = 1 + max((heights[c] for c in cs), default=-1)
        return heights[rank]

    def _postorder(self, start: int) -> List[int]:
        """Iterative post-order (children before parent) from ``start``."""
        order: List[int] = []
        stack: List[Tuple[int, bool]] = [(start, False)]
        while stack:
            r, done = stack.pop()
            if done:
                order.append(r)
                continue
            stack.append((r, True))
            for c in reversed(self.children.get(r, ())):
                stack.append((c, False))
        return order

    def depth(self, rank: int) -> int:
        d = 0
        while rank != self.root:
            rank = self.parent[rank]
            d += 1
        return d

    def is_cross_host(self, src: int, dst: int) -> bool:
        """Whether an edge crosses hosts (reference classifies by ip,
        allreduce.cu:473-522, to choose CUDA-IPC vs MPI; on TPU this picks
        ICI vs DCN cost in the synthesizer)."""
        return self.ips.get(src) != self.ips.get(dst)

    # -- lowering to rounds ----------------------------------------------------

    #: ranks above this count delegate round lowering to the native engine
    #: (libadapcc_rt.so) when it is built — pure-Python lowering of pod-scale
    #: trees is measurable host time during reconstruction
    NATIVE_LOWERING_THRESHOLD = 64

    def reduce_rounds(self) -> List[CommRound]:
        """Rounds of child→parent sends implementing the up-tree reduction.

        Constraint 1 (dataflow): a rank sends to its parent only after all of
        its children have sent to it.
        Constraint 2 (ppermute): within one round, sources are distinct
        (trivially true — each rank has one parent) and destinations are
        distinct — so siblings sending to one parent are staggered across
        rounds, the round-based analog of the reference's per-sibling staging
        slots (allreduce.cu:628-646).
        """
        if self._reduce_rounds_cache is None:
            native = self._native_lowering("reduce")
            if native is not None:
                self._reduce_rounds_cache = native
            else:
                edges = [(r, self.parent[r]) for r in self._topo_leaves_first()]
                self._reduce_rounds_cache = _pack_rounds(edges, after_all_incoming_of_src=True)
        return list(self._reduce_rounds_cache)

    def broadcast_rounds(self) -> List[CommRound]:
        """Rounds of parent→child sends implementing the down-tree broadcast.

        A rank forwards only after it has received from its own parent; one
        source serves its children across consecutive rounds.  Note the
        reference implements broadcast with the *same* XML but inverted edge
        semantics (csrc/boardcast.cu:255-305) — lowering from the tree
        directly makes that symmetry explicit.
        """
        if self._broadcast_rounds_cache is None:
            native = self._native_lowering("broadcast")
            if native is not None:
                self._broadcast_rounds_cache = native
            else:
                edges = [(self.parent[r], r) for r in self._topo_root_first()]
                self._broadcast_rounds_cache = _pack_rounds(edges, after_all_incoming_of_src=False)
        return list(self._broadcast_rounds_cache)

    def _native_lowering(self, kind: str) -> Optional[List[CommRound]]:
        if len(self._ranks) < self.NATIVE_LOWERING_THRESHOLD:
            return None
        try:
            from adapcc_tpu import native
            from adapcc_tpu.strategy import xml_io

            if not native.available():
                return None
            ns = native.NativeStrategy(
                xml_io.emit_strategy_xml(Strategy([self], max(self._ranks) + 1))
            )
            return ns.reduce_rounds(0) if kind == "reduce" else ns.broadcast_rounds(0)
        except Exception:
            return None  # any native hiccup falls back to the Python path

    def _topo_leaves_first(self) -> List[int]:
        return [r for r in self._postorder(self.root) if r != self.root]

    def _topo_root_first(self) -> List[int]:
        from collections import deque

        order: List[int] = []
        queue = deque([self.root])
        while queue:
            r = queue.popleft()
            if r != self.root:
                order.append(r)
            queue.extend(self.children.get(r, ()))
        return order

    # -- serialization helpers -------------------------------------------------

    def to_nested(self) -> dict:
        def rec(r: int) -> dict:
            return {
                "id": r,
                "ip": self.ips.get(r, ""),
                "children": [rec(c) for c in self.children.get(r, ())],
            }

        return rec(self.root)

    def __repr__(self) -> str:
        return f"Tree(root={self.root}, ranks={sorted(self._ranks)})"


def _pack_rounds(
    edges: Sequence[Tuple[int, int]], after_all_incoming_of_src: bool
) -> List[CommRound]:
    """Greedy pack dependency-ordered edges into partial-permutation rounds.

    ``edges`` must already be in a valid dependency order.  For reduce
    (``after_all_incoming_of_src``) an edge ``(s, d)`` may run only strictly
    after every edge ``(*, s)``; for broadcast, only after the single edge
    ``(*, s)`` that delivered the value to ``s``.  Both reduce to the same
    rule: earliest round of (s, d) = 1 + max(round of every packed edge into
    s), then bump past rounds where s or d is already used.
    """
    rounds: List[List[Tuple[int, int]]] = []
    round_srcs: List[set] = []
    round_dsts: List[set] = []
    landed: Dict[int, int] = {}  # dst -> last round in which it received

    for s, d in edges:
        r = landed[s] + 1 if s in landed else 0
        while r < len(rounds) and (s in round_srcs[r] or d in round_dsts[r]):
            r += 1
        while r >= len(rounds):
            rounds.append([])
            round_srcs.append(set())
            round_dsts.append(set())
        rounds[r].append((s, d))
        round_srcs[r].add(s)
        round_dsts[r].add(d)
        landed[d] = max(landed.get(d, -1), r)

    return [CommRound(tuple(es)) for es in rounds]


#: process-wide Strategy → ScheduleProgram memo, keyed by (structural
#: fingerprint, wire_dtype, synthesis, explicit name).  Programs are
#: immutable and small (step tuples, no payload), so the cache is
#: unbounded — the live vocabulary is a handful of strategies per run.
_PROGRAM_CACHE: Dict[tuple, object] = {}


@dataclass
class Strategy:
    """A full communication strategy: ``num_trans`` parallel spanning trees.

    The tensor is sharded 1/num_trans per tree (reference allreduce.cu:310,536)
    and each shard's reduction/broadcast follows its own tree — the reference's
    "parallel transmissions" axis, which on TPU becomes independent ppermute
    chains that XLA can overlap.
    """

    trees: List[Tree]
    world_size: int
    chunk_bytes: int = 4 * 1024 * 1024
    #: fraction of the tensor carried by each tree; None = equal split.  Set
    #: by the MILP solver when it optimizes unequal shares (the reference's
    #: per-tree sizes s_m, gurobi/solver.py objective).
    shares: Optional[List[float]] = None
    #: per-tree chunk granularity in bytes; None = every tree pipelines at
    #: the global ``chunk_bytes``.  Set by the MILP solver (the reference's
    #: per-tree chunk output c_m, gurobi/solver.py:211) so a skewed share
    #: keeps a comparable pipeline depth, and round-tripped through the
    #: strategy XML so a persisted strategy fully determines ring execution.
    tree_chunk_bytes: Optional[List[int]] = None
    #: which formulation produced this strategy ("milp-routing",
    #: "milp-rotation", "partrees", "partrees-fallback", "ring", "binary",
    #: …).  Recorded into the emitted XML so a production fallback is
    #: distinguishable from an optimized result.
    synthesis: Optional[str] = None
    #: wire codec for the data plane ("off" | "bf16" | "int8" — any name in
    #: the quant registry).  Chosen by the synthesizer's sim-rank pricing
    #: pass (sim/cost_model.choose_wire_dtype), round-tripped through the
    #: strategy XML, executed by the engine's ring path, and adopted by a
    #: ``GradSyncHook(compress="strategy")``.  "off" = the payload dtype.
    wire_dtype: str = "off"

    def __post_init__(self) -> None:
        if not self.trees:
            raise ValueError("strategy needs at least one tree")
        for t in self.trees:
            missing = set(range(self.world_size)) - t.ranks
            if missing:
                raise ValueError(
                    f"tree rooted at {t.root} is missing ranks {sorted(missing)}"
                )
        if self.shares is not None:
            if len(self.shares) != len(self.trees):
                raise ValueError("shares must have one entry per tree")
            total = sum(self.shares)
            if total <= 0:
                raise ValueError("shares must sum to a positive value")
            self.shares = [s / total for s in self.shares]
        if self.tree_chunk_bytes is not None:
            if len(self.tree_chunk_bytes) != len(self.trees):
                raise ValueError("tree_chunk_bytes must have one entry per tree")
            bad = [c for c in self.tree_chunk_bytes if c <= 0]
            if bad:
                raise ValueError(f"tree_chunk_bytes must be positive, got {bad}")
        # wire_dtype names must exist in the codec registry at construction
        # time — a strategy carrying a codec no engine can decode must die
        # here, not at the first traced collective.  The default "off" is
        # trivially valid and skips the registry import entirely, so
        # control-plane Strategy construction (solvers, XML parsing of
        # pre-quant artifacts) stays jax-free; any other name pulls the
        # registry, whose caller is about to execute the codec anyway.
        if self.wire_dtype != "off":
            from adapcc_tpu.quant.codec import get_codec

            get_codec(self.wire_dtype)

    def chunk_bytes_for_tree(self, index: int) -> int:
        """The chunk granularity tree ``index``'s segment pipelines at: its
        solver-assigned c_m when present, else the global ``chunk_bytes``."""
        if self.tree_chunk_bytes is not None:
            return self.tree_chunk_bytes[index]
        return self.chunk_bytes

    def tree_shares(self) -> List[float]:
        if self.shares is not None:
            return list(self.shares)
        return [1.0 / len(self.trees)] * len(self.trees)

    @property
    def num_trans(self) -> int:
        return len(self.trees)

    def fingerprint(self) -> str:
        """Stable hash for the compiled-program cache (the analog of the
        reference's per-strategy transmission contexts, SURVEY.md §7).
        Memoized — trees are structurally immutable after construction, and
        hot dispatch paths consult this per collective call."""
        cached = self.__dict__.get("_fingerprint")
        if cached is not None:
            return cached
        h = hashlib.sha256()
        h.update(str(self.world_size).encode())
        for t in self.trees:
            h.update(repr(sorted((p, tuple(c)) for p, c in t.children.items())).encode())
            h.update(str(t.root).encode())
        self.__dict__["_fingerprint"] = fp = h.hexdigest()[:16]
        return fp

    def schedule_program(self, name: Optional[str] = None):
        """This strategy as a chunk-granular ``compiler.ScheduleProgram``.

        The program view of the tree set: chunk ``t`` is tree ``t``'s
        segment, reduce rounds aligned by index across trees, then the
        broadcast rounds — the same merged-round structure the schedule
        plane executes, now in the one IR the verifier certifies and
        ``engine.all_reduce(algo="ir")`` lowers (docs/COMPILER.md).

        Memoized per (structural fingerprint, wire_dtype, name): repeated
        ``algo="ir"`` dispatches reuse one immutable program object instead
        of rebuilding the IR on the hot path.  Whether THIS call hit the
        cache is left on ``_last_program_cache_hit`` for the engine's
        dispatch-trace extras."""
        from adapcc_tpu.compiler.builders import program_from_strategy

        # synthesis rides in the key because the derived program NAME
        # spells it when the caller passes none
        key = (self.fingerprint(), self.wire_dtype, self.synthesis, name)
        program = _PROGRAM_CACHE.get(key)
        self.__dict__["_last_program_cache_hit"] = program is not None
        if program is None:
            program = program_from_strategy(self, name=name)
            _PROGRAM_CACHE[key] = program
        return program

    @staticmethod
    def ring(world_size: int, num_trans: int = 1, ips: Optional[Dict[int, str]] = None) -> "Strategy":
        """Chain ("ring"-schedule) strategy: tree t is the chain rooted at
        rank t, a degenerate tree matching the reference's intra-node Chain
        policy (gurobi/trees.py:85-88) and a good default on an ICI ring."""
        trees = []
        for t in range(num_trans):
            order = [(t + i) % world_size for i in range(world_size)]
            children = {order[i]: [order[i + 1]] for i in range(world_size - 1)}
            trees.append(Tree(order[0], children, ips))
        return Strategy(trees, world_size, synthesis="ring")

    @staticmethod
    def binary(world_size: int, num_trans: int = 1, ips: Optional[Dict[int, str]] = None) -> "Strategy":
        """Array-heap binary trees rotated per transmission for root
        diversity (the shape ParTrees emits for inter-node masters,
        gurobi/trees.py:110-139)."""
        trees = []
        for t in range(num_trans):
            order = [(t + i) % world_size for i in range(world_size)]
            children: Dict[int, List[int]] = {}
            for i in range(world_size):
                kids = [order[j] for j in (2 * i + 1, 2 * i + 2) if j < world_size]
                if kids:
                    children[order[i]] = kids
            trees.append(Tree(order[0], children, ips))
        return Strategy(trees, world_size, synthesis="binary")
