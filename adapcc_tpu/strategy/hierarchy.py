"""Hierarchical (DCN × ICI) strategy synthesis: sketch → per-level solve.

TACCL's central idea (PAPERS.md) is that a communication *sketch* — the
operator's knowledge of the fabric hierarchy — collapses the synthesis
search space from the flat cross-product to a composition of per-level
problems.  SCCL's synthesized-algorithm model supplies the per-level cost
algebra.  This module is that sketch for the pod fabric this repo targets:

- a :class:`HierarchySketch` names the ``pods × pod_size`` layout, derived
  from the ip table / host layout (ragged layouts reject loudly) or pinned
  by the ``ADAPCC_HIER_SKETCH`` env override (malformed → loud);
- each level is solved independently against the calibrated per-link-class
  α-β costs (:mod:`adapcc_tpu.sim.calibrate`): the ICI level picks the
  intra-pod schedule (bandwidth-optimal RS/AG split vs the replicate-first
  fixed schedule ``comm/two_level.py`` shipped with), the DCN level picks
  the cross-pod-leader schedule (binomial tree vs segmented leader ring) —
  per-level work is ``O(pod_size) + O(num_pods)``, never ``O(world)``, so
  world=4096 solves orders of magnitude inside ``MILP_SYNTH_BUDGET_S``
  where the flat MILP blows through it (benchmarks/synthesis_scale.py);
- the solved levels compose into a real :class:`~adapcc_tpu.strategy.ir.
  Strategy` — slice-hierarchical full-world trees (pod members chained
  under their pod leader, leaders wired by the DCN-level trees) that
  ``comm/two_level.py`` executes, ``sim/replay.py`` replays, and the
  strategy XML round-trips (the sketch rides ``<trees hier=…>``).

The composed plan is the double win ROADMAP item 1 names: synthesis-time
(per-level solves) and wire-time (RS-within-pod → AR-across-leaders →
AG-within-pod keeps DCN traffic at ``1/pod_size`` of the payload, where
the flat ring — and the fixed replicate-first schedule — ship the whole
buffer across the slow level).
"""

from __future__ import annotations

import os
import re
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from adapcc_tpu.primitives import DEFAULT_CHUNK_BYTES
from adapcc_tpu.strategy.ir import Strategy, Tree

#: env override pinning the sketch ("<pods>x<pod_size>", e.g. "4x8"); wins
#: over the ip-table-derived layout.  Malformed → loud error (the
#: ADAPCC_RING_CHUNK_BYTES precedent: a typo'd sketch silently falling back
#: to the flat plane would invalidate exactly the A/B it was set for).
HIER_SKETCH_ENV = "ADAPCC_HIER_SKETCH"

#: intra-pod schedule candidates: "rs-ag" (reduce-scatter the payload over
#: ICI so DCN carries 1/pod_size of it, all-gather after the leader level)
#: vs "replicate" (the incumbent fixed schedule: slice-local psum, DCN
#: carries the full payload — cheaper only when α dominates)
POD_ALGOS = ("rs-ag", "replicate")

#: cross-pod leader schedule candidates: "tree" (binomial over leaders —
#: log2(P) rounds of the full chunk, latency-optimal) vs "rs-ag" (segmented
#: leader ring — 2(P−1) rounds of chunk/P, bandwidth-optimal)
LEADER_ALGOS = ("tree", "rs-ag")


@dataclass(frozen=True)
class HierarchySketch:
    """The two-level layout: ``num_pods`` pods of ``pod_size`` ranks each,
    flat rank ``r`` at pod ``r // pod_size``, lane ``r % pod_size``; the
    pod leader is lane 0 (the local-rank-0 master convention)."""

    num_pods: int
    pod_size: int
    #: real per-rank ips when the sketch came from an ip table; synthetic
    #: ``pod-<p>`` labels otherwise
    ip_table: Optional[Tuple[str, ...]] = None

    def __post_init__(self) -> None:
        if self.num_pods < 1:
            raise ValueError(f"num_pods must be >= 1, got {self.num_pods}")
        if self.pod_size < 2:
            raise ValueError(
                f"pod_size must be >= 2, got {self.pod_size}: a pod of one "
                "rank has no ICI level — use the flat plane"
            )
        if self.ip_table is not None and len(self.ip_table) != self.world:
            raise ValueError(
                f"ip table has {len(self.ip_table)} entries for a "
                f"{self.num_pods}x{self.pod_size} sketch (world {self.world})"
            )

    @property
    def world(self) -> int:
        return self.num_pods * self.pod_size

    def leader(self, pod: int) -> int:
        return pod * self.pod_size

    @property
    def leaders(self) -> List[int]:
        return [self.leader(p) for p in range(self.num_pods)]

    def pod_of(self, rank: int) -> int:
        return rank // self.pod_size

    def lane_of(self, rank: int) -> int:
        return rank % self.pod_size

    def ips(self) -> Dict[int, str]:
        if self.ip_table is not None:
            return {r: ip for r, ip in enumerate(self.ip_table)}
        return {r: f"pod-{self.pod_of(r)}" for r in range(self.world)}

    @classmethod
    def from_ip_table(cls, ip_table: Sequence[str]) -> "HierarchySketch":
        """Derive the sketch from a rank→ip table: each run of equal ips is
        one pod.  Loud rejection of layouts the two-level mesh cannot
        carry: ragged pods (unequal run lengths), a host appearing in two
        non-contiguous runs, and pods of one rank (no ICI level)."""
        ips = list(ip_table)
        if not ips:
            raise ValueError("cannot derive a hierarchy sketch from an empty ip table")
        runs: List[Tuple[str, int]] = []
        for ip in ips:
            if runs and runs[-1][0] == ip:
                runs[-1] = (ip, runs[-1][1] + 1)
            else:
                runs.append((ip, 1))
        seen: Dict[str, int] = {}
        for i, (ip, _) in enumerate(runs):
            if ip in seen:
                raise ValueError(
                    f"host {ip!r} appears in two non-contiguous rank runs "
                    f"(runs {seen[ip]} and {i}): the sketch needs contiguous "
                    "pods — fix the ip table's rank order"
                )
            seen[ip] = i
        sizes = {n for _, n in runs}
        if len(sizes) > 1:
            raise ValueError(
                f"ragged host layout {[(ip, n) for ip, n in runs]}: every pod "
                "must have the same rank count for a two-level sketch"
            )
        pod_size = runs[0][1]
        if pod_size < 2:
            raise ValueError(
                "every host holds a single rank: there is no ICI level to "
                "sketch — use the flat plane"
            )
        return cls(len(runs), pod_size, ip_table=tuple(ips))


def sketch_from_env(world: Optional[int] = None) -> Optional[HierarchySketch]:
    """The ``ADAPCC_HIER_SKETCH`` override, validated: None when unset,
    loud on a malformed spelling or a world mismatch."""
    raw = os.environ.get(HIER_SKETCH_ENV)
    if raw is None or not raw.strip():
        return None
    m = re.fullmatch(r"([1-9]\d*)x([1-9]\d*)", raw.strip().lower())
    if not m:
        raise ValueError(
            f"{HIER_SKETCH_ENV}={raw!r}: expected '<pods>x<pod_size>' "
            "(e.g. 4x8)"
        )
    pods, pod_size = int(m.group(1)), int(m.group(2))
    if world is not None and pods * pod_size != world:
        raise ValueError(
            f"{HIER_SKETCH_ENV}={raw!r} describes {pods * pod_size} ranks "
            f"but the world is {world}"
        )
    if pod_size < 2:
        raise ValueError(
            f"{HIER_SKETCH_ENV}={raw!r}: pod_size must be >= 2 (a pod of "
            "one rank has no ICI level)"
        )
    if pods < 2:
        return None  # single pod: the degenerate case IS the flat plane
    return HierarchySketch(pods, pod_size)


def resolve_sketch(
    world: Optional[int] = None, ip_table: Optional[Sequence[str]] = None
) -> Optional[HierarchySketch]:
    """The sketch in force: env override > ip-table-derived > None.

    Returns None exactly when the world is flat (single pod, or nothing to
    derive from) — the callers' cue to fall back to the flat plane.
    Malformed env values and ragged ip tables raise (never a silent flat
    fallback)."""
    env = sketch_from_env(world)
    if env is not None:
        return env
    if os.environ.get(HIER_SKETCH_ENV, "").strip():
        return None  # env said "1xN": explicitly the flat plane
    if ip_table is None:
        return None
    sketch = HierarchySketch.from_ip_table(ip_table)
    return sketch if sketch.num_pods >= 2 else None


def model_from_graphs(
    sketch: HierarchySketch,
    bandwidth_graph: Optional[Sequence[Sequence[float]]] = None,
    latency_graph: Optional[Sequence[Sequence[float]]] = None,
):
    """An O(num_pods) class-coefficient fit from profiled matrices — the
    sketch-aware twin of ``LinkCostModel.from_matrices``, whose full
    per-link fit is O(world²) and would alone blow the synthesis budget at
    pod-cluster scale.  The sketch names which probe pairs matter: one
    intra-pod edge per pod (ICI class) and each leader's ring-successor
    edge (DCN class).  ``None`` matrices fall back to the persisted
    calibration / synthetic defaults."""
    from adapcc_tpu.sim.calibrate import load_or_default
    from adapcc_tpu.sim.cost_model import (
        BANDWIDTH_PROBE_BYTES,
        DCN,
        ICI,
        LATENCY_PROBE_BYTES,
        LinkCostModel,
        fit_alpha_beta,
    )

    if bandwidth_graph is None or latency_graph is None:
        return load_or_default(world=sketch.world).with_ips(sketch.ips())
    if len(bandwidth_graph) != sketch.world or len(latency_graph) != sketch.world:
        raise ValueError(
            f"profile matrices are {len(bandwidth_graph)}-rank but the "
            f"sketch world is {sketch.world}"
        )

    def probe_points(s: int, d: int) -> List[Tuple[float, float]]:
        pts: List[Tuple[float, float]] = []
        lat, bw = float(latency_graph[s][d]), float(bandwidth_graph[s][d])
        if lat > 0:
            pts.append((LATENCY_PROBE_BYTES, lat))
        if bw > 0:
            pts.append(
                (BANDWIDTH_PROBE_BYTES, BANDWIDTH_PROBE_BYTES / (bw * 1e9))
            )
        return pts

    ici_pts: List[Tuple[float, float]] = []
    dcn_pts: List[Tuple[float, float]] = []
    for pod in range(sketch.num_pods):
        lead = sketch.leader(pod)
        ici_pts.extend(probe_points(lead, lead + 1))
        nxt = sketch.leader((pod + 1) % sketch.num_pods)
        if nxt != lead:
            dcn_pts.extend(probe_points(lead, nxt))
    classes = {}
    if ici_pts:
        classes[ICI] = fit_alpha_beta(ici_pts)
    if dcn_pts:
        classes[DCN] = fit_alpha_beta(dcn_pts)
    return LinkCostModel(
        sketch.world, classes=classes, ips=sketch.ips(),
        source="hier-sketch-probes",
    )


# --------------------------------------------------------------------------- #
# per-level solve
# --------------------------------------------------------------------------- #

@dataclass
class LevelSolve:
    """One level's solve: the winning schedule, the priced candidate field,
    and the host walltime the solve cost (the number the synthesis-scale
    curve records)."""

    level: str                      #: "ici" | "dcn"
    algo: str
    predicted_s: float
    candidates: Dict[str, float]
    solve_s: float

    def to_row(self) -> dict:
        return {
            "level": self.level,
            "algo": self.algo,
            "pred_us": round(self.predicted_s * 1e6, 3),
            "candidates_us": {
                k: round(v * 1e6, 3) for k, v in self.candidates.items()
            },
            "solve_ms": round(self.solve_s * 1e3, 4),
        }


def solve_leader_level(
    num_pods: int, dcn, chunk_bytes: float
) -> LevelSolve:
    """DCN level: price the cross-leader allreduce of one ``chunk_bytes``
    payload per candidate (O(num_pods) arithmetic, no world-sized state)
    and keep the cheapest; ties keep "tree" (candidate order)."""
    from adapcc_tpu.sim.cost_model import two_level_leader_time

    t0 = time.perf_counter()
    times = {
        algo: two_level_leader_time(num_pods, chunk_bytes, dcn, algo)
        for algo in LEADER_ALGOS
    }
    algo = min(LEADER_ALGOS, key=lambda a: times[a])
    return LevelSolve("dcn", algo, times[algo], times, time.perf_counter() - t0)


def solve_pod_level(
    sketch: HierarchySketch, ici, dcn, nbytes: float
) -> Tuple[LevelSolve, LevelSolve]:
    """ICI level: choose between the RS/AG split (DCN carries ``nbytes /
    pod_size``) and the replicate-first fixed schedule (DCN carries the
    full payload), each composed with its own best leader-level solve —
    the pod algorithm decides the DCN volume, so the two levels are priced
    jointly but *solved* independently (O(pod) + O(num_pods)).  Returns
    ``(pod_solve, leader_solve_of_the_winner)``."""
    from adapcc_tpu.sim.cost_model import two_level_allreduce_time

    leaders = {
        "rs-ag": solve_leader_level(
            sketch.num_pods, dcn, nbytes / sketch.pod_size
        ),
        "replicate": solve_leader_level(sketch.num_pods, dcn, nbytes),
    }
    t0 = time.perf_counter()
    times = {
        pod_algo: two_level_allreduce_time(
            sketch.num_pods, sketch.pod_size, nbytes, ici, dcn,
            pod_algo=pod_algo, leader_algo=leaders[pod_algo].algo,
        )
        for pod_algo in POD_ALGOS
    }
    algo = min(POD_ALGOS, key=lambda a: times[a])
    pod = LevelSolve("ici", algo, times[algo], times, time.perf_counter() - t0)
    return pod, leaders[algo]


# --------------------------------------------------------------------------- #
# composition: per-level solves → one slice-hierarchical Strategy
# --------------------------------------------------------------------------- #

@dataclass
class TwoLevelPlan:
    """The synthesized two-level plan: the sketch, both level solves, the
    leader-level strategy (trees over pod indices — what the DCN rounds
    execute), and the composed full-world :class:`Strategy`."""

    sketch: HierarchySketch
    pod_algo: str                   #: "rs-ag" | "replicate"
    leader_algo: str                #: "tree" | "rs-ag"
    leader_strategy: Strategy       #: world = num_pods (pod indices)
    strategy: Strategy = field(repr=False)
    predicted_s: float = 0.0
    ici_solve: Optional[LevelSolve] = None
    dcn_solve: Optional[LevelSolve] = None
    #: total synthesis walltime (solves + composition)
    solve_s: float = 0.0
    #: which levels this plan re-solved: "both" at synthesis, "dcn" when a
    #: DCN drift re-solved only the leader level (pod level kept warm)
    resolved_level: str = "both"
    #: the flat lockstep ring's predicted time on the same payload (the
    #: hierarchy-blind comparator) and which arm the pod-count-aware
    #: crossover chose — stamped at synthesis so bench rows are artifacts
    flat_pred_s: float = 0.0
    chosen_vs_flat: str = "two_level"

    def to_row(self) -> dict:
        return {
            "pods": self.sketch.num_pods,
            "pod_size": self.sketch.pod_size,
            "world": self.sketch.world,
            "pod_algo": self.pod_algo,
            "leader_algo": self.leader_algo,
            "pred_us": round(self.predicted_s * 1e6, 3),
            "pred_flat_us": round(self.flat_pred_s * 1e6, 3),
            "chosen": self.chosen_vs_flat,
            "solve_ms": round(self.solve_s * 1e3, 4),
            "resolved_level": self.resolved_level,
            "levels": [
                s.to_row() for s in (self.ici_solve, self.dcn_solve) if s
            ],
        }


def attach_plan(strategy: Strategy, plan: TwoLevelPlan) -> Strategy:
    """Carry the plan on the composed strategy (the engine's dispatch cue:
    a strategy with a plan executes the composed RS→AR→AG phases instead
    of the fixed replicate-first schedule)."""
    strategy._two_level_plan = plan
    return strategy


def plan_of(strategy: Strategy) -> Optional[TwoLevelPlan]:
    return getattr(strategy, "_two_level_plan", None)


def _compose_trees(
    sketch: HierarchySketch, leader_strategy: Strategy, ips: Dict[int, str]
) -> List[Tree]:
    """Lower each leader tree (over pod indices) to a full-world tree: pod
    leaders keep the leader tree's edges, every pod's remaining lanes chain
    under their leader (the ParTrees chain policy — the chain head is the
    leader's FIRST child so the fast local edge gets staging priority).
    Slice-hierarchical by construction: exactly one inbound inter-pod edge
    per non-root pod, so ``comm.two_level.slice_tree`` accepts it."""
    P, I = sketch.num_pods, sketch.pod_size
    trees: List[Tree] = []
    for lt in leader_strategy.trees:
        children: Dict[int, List[int]] = {}
        for pod, kids in lt.children.items():
            children[sketch.leader(pod)] = [sketch.leader(c) for c in kids]
        for pod in range(P):
            head = sketch.leader(pod)
            members = list(range(head + 1, head + I))
            kids = children.setdefault(head, [])
            kids.insert(0, members[0])
            for a, b in zip(members, members[1:]):
                children.setdefault(a, []).append(b)
        trees.append(Tree(sketch.leader(lt.root), children, ips))
    return trees


def leader_projection(strategy: Strategy, sketch: HierarchySketch) -> Strategy:
    """Collapse a composed strategy back to its leader-level trees (pure
    arithmetic — the jax-free twin of ``comm.two_level.slice_tree``, used
    by the XML reattach path and the structural tests).  Rejects trees
    that are not slice-hierarchical, loudly."""
    trees: List[Tree] = []
    for tree in strategy.trees:
        inbound: Dict[int, int] = {}
        children: Dict[int, List[int]] = {}
        for c, p in tree.parent.items():
            pp, pc = sketch.pod_of(p), sketch.pod_of(c)
            if pp == pc:
                continue
            if pc in inbound:
                raise ValueError(
                    f"pod {pc} has two inbound inter-pod edges (from "
                    f"{inbound[pc]} and {pp}); strategy is not "
                    "slice-hierarchical"
                )
            inbound[pc] = pp
            children.setdefault(pp, []).append(pc)
        root = sketch.pod_of(tree.root)
        lt = Tree(root, children)
        missing = set(range(sketch.num_pods)) - lt.ranks
        if missing:
            raise ValueError(
                f"pods {sorted(missing)} unreachable in the leader tree"
            )
        trees.append(lt)
    return Strategy(trees, sketch.num_pods, synthesis="leader-projection")


def synthesize_two_level(
    sketch: HierarchySketch,
    model=None,
    nbytes: int = 16 << 20,
    num_trans: int = 1,
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
) -> TwoLevelPlan:
    """Sketch → per-level solve → composed :class:`Strategy` (module doc).

    ``model`` is a :class:`~adapcc_tpu.sim.cost_model.LinkCostModel`
    (default: the persisted calibration artifact / synthetic defaults) —
    only its ICI/DCN *class* coefficients are read, so synthesis never
    touches world² state.  The composed strategy carries the plan
    (:func:`plan_of`) and the sketch survives the strategy XML.
    """
    from adapcc_tpu.sim.cost_model import DCN, ICI, choose_two_level

    if sketch.num_pods < 2:
        raise ValueError(
            f"two-level synthesis needs >= 2 pods, got {sketch.num_pods}: "
            "a single-pod world is the flat plane"
        )
    t0 = time.perf_counter()
    if model is None:
        from adapcc_tpu.sim.calibrate import load_or_default

        model = load_or_default(world=sketch.world)
    ici, dcn = model.classes[ICI], model.classes[DCN]
    pod_solve, dcn_solve = solve_pod_level(sketch, ici, dcn, float(nbytes))
    chosen_vs_flat, vs_flat = choose_two_level(
        sketch.num_pods, sketch.pod_size, float(nbytes), ici, dcn
    )
    degree = min(max(1, num_trans), sketch.num_pods)
    if dcn_solve.algo == "tree":
        leader_strategy = Strategy.binary(sketch.num_pods, degree)
    else:
        # the segmented leader ring's IR spelling is the rotated chain —
        # the mesh execution runs it as XLA RS/AG over the dcn axis
        leader_strategy = Strategy.ring(sketch.num_pods, degree)
    strategy = Strategy(
        _compose_trees(sketch, leader_strategy, sketch.ips()),
        sketch.world,
        chunk_bytes,
        synthesis="two-level",
    )
    plan = TwoLevelPlan(
        sketch=sketch,
        pod_algo=pod_solve.algo,
        leader_algo=dcn_solve.algo,
        leader_strategy=leader_strategy,
        strategy=strategy,
        predicted_s=pod_solve.predicted_s,
        ici_solve=pod_solve,
        dcn_solve=dcn_solve,
        solve_s=time.perf_counter() - t0,
        flat_pred_s=vs_flat["flat"],
        chosen_vs_flat=chosen_vs_flat,
    )
    attach_plan(strategy, plan)
    return plan


def resolve_leader_level(
    plan: TwoLevelPlan, model, nbytes: Optional[int] = None
) -> TwoLevelPlan:
    """Re-solve ONLY the DCN level under a (drift-corrected) ``model`` —
    the drift-localization half of the closed loop (docs/HIERARCHY.md §5):
    a DCN degradation says nothing about the ICI level, so the pod
    algorithm (and every pod-level compiled program keyed by it) stays
    warm; only the leader schedule is re-priced and re-composed.

    Returns a fresh plan with ``resolved_level="dcn"`` and the pod solve
    carried over verbatim (``ici_solve`` object identity preserved — the
    regression tests pin that no pod-level work re-ran)."""
    from adapcc_tpu.sim.cost_model import DCN, two_level_allreduce_time, ICI

    t0 = time.perf_counter()
    sketch = plan.sketch
    n = float(nbytes) if nbytes is not None else float(16 << 20)
    dcn = model.classes[DCN]
    ici = model.classes[ICI]
    chunk = n / sketch.pod_size if plan.pod_algo == "rs-ag" else n
    dcn_solve = solve_leader_level(sketch.num_pods, dcn, chunk)
    degree = plan.leader_strategy.num_trans
    if dcn_solve.algo == "tree":
        leader_strategy = Strategy.binary(sketch.num_pods, degree)
    else:
        leader_strategy = Strategy.ring(sketch.num_pods, degree)
    strategy = Strategy(
        _compose_trees(sketch, leader_strategy, sketch.ips()),
        sketch.world,
        plan.strategy.chunk_bytes,
        synthesis="two-level",
    )
    strategy.wire_dtype = plan.strategy.wire_dtype
    new = TwoLevelPlan(
        sketch=sketch,
        pod_algo=plan.pod_algo,
        leader_algo=dcn_solve.algo,
        leader_strategy=leader_strategy,
        strategy=strategy,
        predicted_s=two_level_allreduce_time(
            sketch.num_pods, sketch.pod_size, n, ici, dcn,
            pod_algo=plan.pod_algo, leader_algo=dcn_solve.algo,
        ),
        ici_solve=plan.ici_solve,   # NOT re-solved: the pod level is warm
        dcn_solve=dcn_solve,
        solve_s=time.perf_counter() - t0,
        resolved_level="dcn",
    )
    attach_plan(strategy, new)
    return new


def leader_variant(plan: TwoLevelPlan, leader_algo: str) -> TwoLevelPlan:
    """The composed plan with a FORCED leader schedule (no solve) — the
    per-level standby shape: every schedule the DCN level could re-solve
    to is constructible (and AOT-warmable,
    :meth:`~adapcc_tpu.elastic.standby.StandbyPlanCache.
    warm_leader_alternatives`) ahead of the drift that wants it."""
    if leader_algo not in LEADER_ALGOS:
        raise ValueError(
            f"unknown leader algo {leader_algo!r}; expected one of "
            f"{LEADER_ALGOS}"
        )
    if leader_algo == plan.leader_algo:
        return plan
    sketch = plan.sketch
    degree = plan.leader_strategy.num_trans
    leader_strategy = (
        Strategy.binary(sketch.num_pods, degree)
        if leader_algo == "tree"
        else Strategy.ring(sketch.num_pods, degree)
    )
    strategy = Strategy(
        _compose_trees(sketch, leader_strategy, sketch.ips()),
        sketch.world,
        plan.strategy.chunk_bytes,
        synthesis="two-level",
    )
    strategy.wire_dtype = plan.strategy.wire_dtype
    variant = TwoLevelPlan(
        sketch=sketch,
        pod_algo=plan.pod_algo,
        leader_algo=leader_algo,
        leader_strategy=leader_strategy,
        strategy=strategy,
        ici_solve=plan.ici_solve,
        dcn_solve=None,          # forced, not solved
        # honest provenance: this variant was FORCED for standby warming,
        # not drift-resolved — a trace reading "dcn" here would fake a
        # leader re-solve that never happened
        resolved_level="forced",
    )
    attach_plan(strategy, variant)
    return variant


def plan_from_strategy(
    strategy: Strategy,
    sketch: HierarchySketch,
    pod_algo: str,
    leader_algo: str,
) -> TwoLevelPlan:
    """Reconstruct the plan for a composed strategy whose sketch rode an
    artifact (the strategy-XML reattach path): the leader level IS the
    composed trees' pod projection, so nothing beyond the three stamped
    attributes is needed."""
    if pod_algo not in POD_ALGOS:
        raise ValueError(
            f"unknown pod algo {pod_algo!r}; expected one of {POD_ALGOS}"
        )
    if leader_algo not in LEADER_ALGOS:
        raise ValueError(
            f"unknown leader algo {leader_algo!r}; expected one of "
            f"{LEADER_ALGOS}"
        )
    if strategy.world_size != sketch.world:
        raise ValueError(
            f"strategy world {strategy.world_size} != sketch world "
            f"{sketch.world}"
        )
    plan = TwoLevelPlan(
        sketch=sketch,
        pod_algo=pod_algo,
        leader_algo=leader_algo,
        leader_strategy=leader_projection(strategy, sketch),
        strategy=strategy,
    )
    attach_plan(strategy, plan)
    return plan
