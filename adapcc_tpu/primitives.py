"""Primitive identifiers and system-wide constants.

The integer primitive IDs are part of the reference's public contract: the
Python control plane and the native engine share one enum (reference
commu.py:28-35 mirroring csrc/include/trans.h:27-36).  We keep identical
numbering so reference-style launch flags (`--entry_point 6`) keep meaning.
"""

from __future__ import annotations

import enum

# --- primitive ids (reference trans.h:27-36 / commu.py:28-35) -----------------
ALLREDUCE = 0
REDUCE = 1
BOARDCAST = 2  # reference spelling, kept for API compat
BROADCAST = 2  # sane alias
ALLGATHER = 3
ALLTOALL = 4
REDUCESCATTER = 5
DETECT = 6
PROFILE = 7

#: entry_point value meaning "skip the detect/profile bootstrap entirely"
SKIP_BOOTSTRAP = -1

PRIMITIVE_NAMES = {
    ALLREDUCE: "allreduce",
    REDUCE: "reduce",
    BOARDCAST: "broadcast",
    ALLGATHER: "allgather",
    ALLTOALL: "alltoall",
    REDUCESCATTER: "reducescatter",
    DETECT: "detect",
    PROFILE: "profile",
}


class ReduceOp(enum.Enum):
    """Reduction operator for reduce-style collectives.

    The reference ships sum/avg/max CUDA kernels (reference csrc/trans.cu:10-56
    reduceSum/Avg/MaxKernel); here the operator is a property of the compiled
    XLA program instead of a kernel choice.
    """

    SUM = "sum"
    AVG = "avg"
    MAX = "max"


# --- system-wide constants ----------------------------------------------------
# TPU-native analogs of the reference compile-time constants
# (reference csrc/include/init.h:14-25).  MAX_BUF_SIZE there is a 400MB
# CUDA staging buffer per fan-in slot; on TPU the staging memory is XLA's
# problem, so the only constants that survive are schedule-shaping ones.

#: maximum number of parallel transmissions (trees) per strategy
#: (reference init.h MAX_TRANS=8)
MAX_TRANS = 8

#: default chunk size for tree pipelining, bytes
#: (reference gurobi/trees.py:118 default_chunk = 4MB)
DEFAULT_CHUNK_BYTES = 4 * 1024 * 1024

#: DDP bucket-hook chunking heuristic threshold, bytes
#: (reference commu.py:401-403: buckets >10MB use 4MB chunks, else size/4)
CHUNK_HEURISTIC_THRESHOLD = 10 * 1024 * 1024

#: coordinator defaults (reference proto/rpc_server.py:27-46)
RELAY_THRESHOLD_S = 0.1
TIME_SLOT_DURATION_S = 0.005
FAULT_TOLERANT_TIME_S = 10.0
COORDINATOR_PORT = 50051
