"""Expert-parallel MoE workload (reference models/moe/train_moe.py).

The reference wraps fastmoe's ``FMoETransformerMLP`` in DDP and times an
*inference* loop — the all-to-all is fastmoe/NCCL's, not AdapCC's
(SURVEY §2.3: the ALLTOALL context is a stub there).  Here the all-to-all
IS the framework's (parallel/expert.py over the ``experts`` mesh axis), and
on top of the reference's timed inference mode this also *trains*: gradients
flow through the dispatch/combine all-to-alls (expert weights sharded, the
router replicated with its gradient summed by the shard_map transpose), with
the load-balancing auxiliary loss in the objective.

Usage::

    python -m adapcc_tpu.workloads.train_moe --steps 30            # train
    python -m adapcc_tpu.workloads.train_moe --mode inference      # ref loop
"""

from __future__ import annotations

import argparse
import functools
import sys
import time
from typing import Optional, Sequence, Tuple

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--mode", choices=("train", "inference"), default="train")
    p.add_argument("--experts", type=int, default=8)
    p.add_argument("--dmodel", type=int, default=64)
    p.add_argument("--dhidden", type=int, default=128)
    p.add_argument("--top-k", type=int, default=2)
    p.add_argument("--batch", type=int, default=256, help="tokens per step")
    p.add_argument("--classes", type=int, default=8)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--aux-weight", type=float, default=0.01)
    p.add_argument("--world", type=int, default=None)
    p.add_argument(
        "--tune-every", type=int, default=10,
        help="steps between all-to-all tuner probes when a tuner is active "
        "(ADAPCC_TUNER=record|choose): the engine times real all_to_all "
        "dispatches at the MoE exchange geometry into the tuning database "
        "(the in-jit dispatch/combine shuffles cannot be walltimed "
        "individually)",
    )
    return p


def _cluster_data(n: int, d: int, classes: int, seed: int = 0):
    """Gaussian clusters: learnable by an expert MLP, and the clusters give
    the router something real to specialize on."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(classes, d)) * 2.0
    labels = rng.integers(0, classes, size=(n,))
    x = centers[labels] + rng.normal(size=(n, d)) * 0.5
    return x.astype(np.float32), labels.astype(np.int32)


def run(args) -> Tuple[float, float]:
    """Train (or time inference); returns (first_loss, last_loss) — in
    inference mode both are the mean step milliseconds."""
    from adapcc_tpu.launch import maybe_initialize_distributed

    maybe_initialize_distributed()

    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh

    from adapcc_tpu.models.moe import MoEConfig, MoEMLP
    from adapcc_tpu.parallel import expert_parallel_moe

    world = args.world or len(jax.devices())
    if len(jax.devices()) < world:
        raise ValueError(f"need {world} devices, have {len(jax.devices())}")
    if args.batch % world:
        raise ValueError(f"--batch {args.batch} must divide by world {world}")
    mesh = Mesh(np.array(jax.devices()[:world]), ("experts",))

    cfg = MoEConfig(
        num_experts=args.experts, d_model=args.dmodel, d_hidden=args.dhidden,
        top_k=args.top_k, capacity_factor=2.0, dtype=jnp.float32,
        router_z_coef=0.1,
    )
    model = MoEMLP(cfg)
    x_np, y_np = _cluster_data(args.batch, cfg.d_model, args.classes)
    x, y = jnp.asarray(x_np), jnp.asarray(y_np)

    # expert traffic rides the engine when a tuner is active: the MoE
    # dispatch/combine all-to-alls route through engine.expert_a2a (traced
    # per compiled program) and periodic engine.all_to_all probes at the
    # SAME payload geometry feed the tuning database under the
    # `all_to_all` primitive (docs/LATENCY.md §5)
    from adapcc_tpu.tuner import tuner_mode

    engine = None
    a2a_probe = None
    if tuner_mode() != "off":
        from adapcc_tpu.comm.engine import CollectiveEngine
        from adapcc_tpu.strategy.ir import Strategy
        from adapcc_tpu.utils import CollectiveTrace

        from adapcc_tpu.parallel.expert import moe_capacity

        engine = CollectiveEngine(
            mesh, Strategy.ring(world), axis_name="experts",
            trace=CollectiveTrace(),
        )
        e_loc = cfg.num_experts // world
        cap = moe_capacity(cfg, args.batch // world)
        probe = jnp.zeros(
            (world, world, e_loc * cap * cfg.d_model), jnp.float32
        )

        def a2a_probe():
            engine.all_to_all(probe)

    import flax.linen as nn

    class Readout(nn.Module):
        classes: int

        @nn.compact
        def __call__(self, h):
            return nn.Dense(self.classes, name="out")(h)

    readout = Readout(args.classes)
    moe_params = model.init(jax.random.PRNGKey(0), x[None])
    head_params = readout.init(jax.random.PRNGKey(1), x)

    if args.mode == "inference":
        fwd = jax.jit(
            lambda p, x: expert_parallel_moe(p, x, cfg, mesh, engine=engine)[0]
        )
        jax.block_until_ready(fwd(moe_params, x))  # compile
        times = []
        for i in range(args.steps):
            if a2a_probe is not None and i % max(1, args.tune_every) == 0:
                a2a_probe()
            t0 = time.perf_counter()
            jax.block_until_ready(fwd(moe_params, x))
            times.append(time.perf_counter() - t0)
        ms = float(np.mean(times) * 1e3)
        _report_tuner(engine)
        # reference prints per-iteration computation time (train_moe.py)
        print(f"computation time: {ms:.3f} ms/step ({args.batch} tokens, world={world})")
        return ms, ms

    def loss_fn(params, x, y):
        h, aux = expert_parallel_moe(params["moe"], x, cfg, mesh, engine=engine)
        logits = readout.apply(params["head"], h.astype(jnp.float32))
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
        return ce + args.aux_weight * aux, (ce, aux)

    tx = optax.adam(args.lr)
    params = {"moe": moe_params, "head": head_params}
    opt_state = tx.init(params)

    # donate the loop-owned state: in-place updates, and on tunneled
    # runtimes non-donated threading re-uploads it every step (PERF_NOTES
    # round-4 bisection); x/y are static and never donated
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, x, y):
        (loss, (ce, aux)), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, x, y)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss, ce, aux

    first = last = None
    for i in range(args.steps):
        if a2a_probe is not None and i % max(1, args.tune_every) == 0:
            a2a_probe()
        params, opt_state, loss, ce, aux = step(params, opt_state, x, y)
        if i == 0 or i == args.steps - 1 or (i + 1) % 10 == 0:
            loss_v = float(loss)
            print(f"step {i:4d}  loss {loss_v:.4f}  ce {float(ce):.4f}  aux {float(aux):.4f}")
            if first is None:
                first = loss_v
            last = loss_v
    _report_tuner(engine)
    return first, last


def _report_tuner(engine) -> None:
    """One summary line per tuned all_to_all cell — the run's evidence that
    expert traffic landed in the tuning database."""
    if engine is None or engine.tuner is None:
        return
    rows = [
        r for r in engine.tuner.db.snapshot() if r["primitive"] == "all_to_all"
    ]
    for r in rows:
        print(
            f"[tuner] all_to_all bucket={r['size_bucket']}B path={r['path']} "
            f"n={r['count']} median={r['median_s'] * 1e6:.1f}us"
        )


def main(argv: Optional[Sequence[str]] = None) -> int:
    run(build_parser().parse_args(argv))
    return 0


if __name__ == "__main__":
    sys.exit(main())
