"""Latency-SLO GPT-2 serving workload — the decode twin of train_gpt2.

The first non-training workload the adaptive-CC stack serves end to end
(docs/SERVING.md): a tensor-parallel GPT-2 behind the continuous batcher
(:mod:`adapcc_tpu.serve`), driven by a deterministic synthetic arrival
trace — seeded Poisson via ``jax.random``, or a replayed JSON artifact
through ``ADAPCC_SERVE_TRACE`` — with every decode-step allreduce routed
through the traced :class:`~adapcc_tpu.comm.engine.CollectiveEngine`, so
the size-adaptive algorithm selection (at serving payloads: the
small-message plane, docs/LATENCY.md) and the dispatch trace apply to
decode traffic.  The combine runs fp32 on purpose — exactness buys the
bit parity the acceptance drill pins; a quantized decode wire is open
work (ROADMAP item 3).

The run prints one ledger row per request (sojourn / TTFT on the
deterministic step clock, EOS eviction) and a summary with step-time
percentiles, SLO attainment, and the executed decode-collective algorithm
histogram read back from the dispatch trace — the serving analog of the
training workloads' step meters.

Run (virtual pod)::

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \\
    python -m adapcc_tpu.workloads.serve_gpt2 --requests 8 --slots 4
"""

from __future__ import annotations

import argparse
import json
from typing import Optional, Sequence


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--requests", type=int, default=8,
                   help="synthetic requests to serve (ignored when "
                        "ADAPCC_SERVE_TRACE replays an artifact)")
    p.add_argument("--rate", type=float, default=0.25,
                   help="Poisson arrival rate (requests per decode step)")
    p.add_argument("--slots", type=int, default=None,
                   help="decode-slot count (default: ADAPCC_SERVE_SLOTS "
                        "env > 4)")
    p.add_argument("--seed", type=int, default=0,
                   help="arrival-trace seed (per-request RNG seeds derive "
                        "from it)")
    p.add_argument("--slo-ms", type=float, default=None,
                   help="per-request sojourn SLO in milliseconds "
                        "(default: ADAPCC_SERVE_SLO_MS env > none)")
    p.add_argument("--algo", default="auto",
                   help="decode-step collective algorithm "
                        "(auto/ring/rd/tree; ADAPCC_COLL_ALGO outranks)")
    p.add_argument("--temperature", type=float, default=1.0)
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--top-p", type=float, default=0.0)
    p.add_argument("--eos-id", type=int, default=None,
                   help="EOS token: a sampled EOS latches the stream and "
                        "evicts the lane early (slot reuse)")
    p.add_argument("--max-new-tokens", type=int, default=12,
                   help="upper bound of the per-request generation budget")
    p.add_argument("--disagg", action="store_true",
                   help="serve disaggregated: split the mesh into a "
                        "prefill pod and a decode pod (world must be "
                        "even), KV pages migrating over the traced "
                        "kv_transfer DCN stream (ADAPCC_DISAGG outranks)")
    p.add_argument("--kv-wire-dtype", default=None,
                   help="disagg KV-migration wire codec (off/bf16/int8; "
                        "ADAPCC_KV_WIRE_DTYPE outranks; 'off' = fp32, "
                        "bit-exact; lossy codecs are admitted only under "
                        "the ADAPCC_KV_KL_BOUND token-level KL bound)")
    p.add_argument("--ckpt", "--checkpoint", dest="ckpt", default=None,
                   help="serve trained params (TrainCheckpointState file "
                        "from train_gpt2 --checkpoint-file; shape flags "
                        "must match training)")
    p.add_argument("--trace-out", default=None,
                   help="save the (synthesized) arrival trace as a JSON "
                        "artifact replayable via ADAPCC_SERVE_TRACE")
    # model shape: same flags and defaults as train_gpt2 (vocab follows the
    # serving trace's synthetic token range when untrained)
    p.add_argument("--vocab", type=int, default=258)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=None,
                   help="default: one head per rank (n_head must divide "
                        "over the TP world)")
    p.add_argument("--dmodel", type=int, default=128)
    p.add_argument("--world", type=int, default=None)
    p.add_argument("--json", action="store_true",
                   help="one JSON row per request plus a summary row")
    return p


def run(args) -> dict:
    """Serve the trace; returns the summary dict (the printed artifact)."""
    from adapcc_tpu.launch.launcher import apply_platform_env

    apply_platform_env()  # honor JAX_PLATFORMS despite site customizations

    import jax
    import jax.numpy as jnp

    from adapcc_tpu.comm.mesh import build_world_mesh
    from adapcc_tpu.models.gpt2 import GPT2, GPT2Config
    from adapcc_tpu.serve import GPT2Server, resolve_disagg
    from adapcc_tpu.serve.trace import (
        load_serve_trace,
        synthesize_arrival_trace,
    )
    from adapcc_tpu.utils.observability import CollectiveTrace

    mesh = build_world_mesh(args.world)
    world = int(mesh.devices.size)
    disagg = resolve_disagg(getattr(args, "disagg", False))
    heads = args.heads if args.heads is not None else max(1, world)
    if heads % world:
        raise SystemExit(
            f"--heads {heads} must divide over the TP world {world} "
            "(head-sharded decode)"
        )
    if disagg:
        if world < 2 or world % 2:
            raise SystemExit(
                f"--disagg splits the mesh into two equal pods: world "
                f"{world} must be an even count >= 2"
            )
        if heads % (world // 2):
            raise SystemExit(
                f"--heads {heads} must divide over the per-pod TP world "
                f"{world // 2} under --disagg"
            )
    if args.dmodel % heads:
        raise SystemExit(
            f"--dmodel {args.dmodel} must divide over --heads {heads}"
        )
    if args.max_new_tokens < 1 or args.max_new_tokens > args.seq - 2:
        # seq - 2: the KV cache holds prompt + generation together and
        # the shortest synthesized prompt is 2 tokens
        raise SystemExit(
            f"--max-new-tokens {args.max_new_tokens} must be in "
            f"[1, --seq - 2 = {args.seq - 2}]: the KV cache holds the "
            "prompt (>= 2 tokens) and the generation together"
        )
    cfg = GPT2Config(
        vocab_size=args.vocab, max_seq=args.seq, n_layer=args.layers,
        n_head=heads, d_model=args.dmodel, dtype=jnp.float32,
    )
    model = GPT2(cfg)
    params = model.init(
        jax.random.PRNGKey(args.seed), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    if args.ckpt:
        from adapcc_tpu.checkpoint import TrainCheckpointState, load_checkpoint

        state = TrainCheckpointState(params={"params": params})
        if not load_checkpoint(state, args.ckpt):
            raise SystemExit(
                f"checkpoint {args.ckpt!r} not found or incompatible with "
                "the model shape (--vocab/--seq/--layers/--heads/--dmodel "
                "must match training)"
            )
        params = state.params["params"]

    trace = load_serve_trace(world=world)
    if trace is None:
        # prompts must fit the cache next to the generation budget
        max_prompt = max(2, min(12, args.seq - args.max_new_tokens - 1))
        trace = synthesize_arrival_trace(
            world, args.requests, args.rate, seed=args.seed,
            prompt_len=(2, max_prompt),
            max_new_tokens=(max(1, args.max_new_tokens // 2),
                            args.max_new_tokens),
            vocab_size=args.vocab, eos_id=args.eos_id,
        )
    if args.trace_out:
        trace.save(args.trace_out)
        print(f"[serve] arrival trace -> {args.trace_out}")

    dispatch_trace = CollectiveTrace()
    if disagg:
        import numpy as np
        from jax.sharding import Mesh

        from adapcc_tpu.serve import ClusterRouter

        pw = world // 2
        devs = mesh.devices.flatten()
        server = ClusterRouter(
            cfg, params,
            Mesh(np.asarray(devs[:pw]), ("ranks",)),
            Mesh(np.asarray(devs[pw:]), ("ranks",)),
            prefill_slots=args.slots, decode_slots=args.slots,
            temperature=args.temperature, top_k=args.top_k,
            top_p=args.top_p, eos_id=args.eos_id, algo=args.algo,
            trace=dispatch_trace, slo_ms=args.slo_ms,
            kv_wire_dtype=args.kv_wire_dtype,
        )
    else:
        server = GPT2Server(
            cfg, params, mesh, slots=args.slots,
            temperature=args.temperature, top_k=args.top_k,
            top_p=args.top_p, eos_id=args.eos_id, algo=args.algo,
            trace=dispatch_trace, slo_ms=args.slo_ms,
        )
    server.submit_trace(trace)
    results = server.run()

    for r in results:
        row = {
            "req_id": r.req_id,
            "arrival_step": r.arrival_step,
            "admitted_step": r.admitted_step,
            "ttft_steps": r.ttft_steps,
            "sojourn_steps": r.sojourn_steps,
            "eos_evicted": r.eos_evicted,
            "generated": r.generated,
        }
        if args.json:
            print(json.dumps(row))
        else:
            print(
                f"[serve] req={r.req_id:>3} arrive={r.arrival_step:>4} "
                f"admit={r.admitted_step:>4} ttft={r.ttft_steps:>3} "
                f"sojourn={r.sojourn_steps:>4}"
                f"{' EOS' if r.eos_evicted else '    '} "
                f"tokens={r.generated}"
            )
    summary = server.summary()
    # the executed decode collectives, read back from the dispatch trace:
    # which algorithm actually ran (auto → the small-message plane at
    # serving payloads) — the observable the tail claims hang on
    algos: dict = {}
    kv_events = 0
    for e in dispatch_trace.events():
        if e.primitive == "allreduce":
            algos[e.impl] = algos.get(e.impl, 0) + 1
        elif e.primitive == "kv_transfer":
            kv_events += 1
    summary["decode_collectives"] = algos
    if disagg:
        # every KV migration must be visible in the dispatch trace — the
        # acceptance drill cross-checks this count against kv_stream
        summary["kv_transfer_events"] = kv_events
    summary["trace_label"] = trace.label
    if args.json:
        print(json.dumps({"summary": summary}, sort_keys=True))
    else:
        print(f"[serve] summary: {json.dumps(summary, sort_keys=True)}")
    return summary


def main(argv: Optional[Sequence[str]] = None) -> int:
    run(build_parser().parse_args(argv))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
