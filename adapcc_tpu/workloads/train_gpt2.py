"""GPT-2 language-model training pipeline — the reference train_gpt2_ddp flow.

The reference fine-tunes HuggingFace GPT-2 on PersonaChat under torch DDP
with ignite (models/gpt2/train_gpt2_ddp.py): dataset → packed LM batches →
AdamW + linear LR decay + gradient clipping → periodic evaluation (the
convai_evaluation.py metric is perplexity) → interact.py sampling.  This
pipeline keeps that shape end to end on TPU: corpus → packed ``[B, T]``
batches → :class:`DDPTrainer` (adaptive allreduce) with warmup+decay LR and
global-norm clipping → held-out perplexity per epoch → a generation sample
from the trained weights.

The corpus is a seeded Markov chain over the vocabulary (zero-egress stand-in
for PersonaChat): it has real sequential structure, so validation perplexity
falls far below the uniform bound iff the model actually learns.

Run (virtual pod):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python -m adapcc_tpu.workloads.train_gpt2 --epochs 2
"""

from __future__ import annotations

import argparse
from typing import Optional, Sequence, Tuple

import numpy as np


# --- corpus (PersonaChat stand-in) --------------------------------------------


def markov_corpus(
    n_tokens: int, vocab_size: int, branching: int = 4, seed: int = 0
) -> np.ndarray:
    """A token stream from a sparse random Markov chain: each token has
    ``branching`` likely successors.  Entropy ≈ log(branching) ≪
    log(vocab_size), so a language model has real structure to learn."""
    rng = np.random.default_rng(seed)
    successors = rng.integers(0, vocab_size, size=(vocab_size, branching))
    probs = rng.dirichlet(np.ones(branching) * 2.0, size=vocab_size)
    # draw all uniforms up front and step via cumulative inverse transform —
    # per-token rng.choice(p=...) revalidates the distribution every call and
    # costs seconds at default corpus sizes
    cum = probs.cumsum(axis=1)
    uniforms = rng.random(n_tokens)
    out = np.empty(n_tokens, dtype=np.int32)
    tok = int(rng.integers(0, vocab_size))
    for i in range(n_tokens):
        out[i] = tok
        tok = int(successors[tok, np.searchsorted(cum[tok], uniforms[i])])
    return out


def pack_sequences(stream: np.ndarray, seq_len: int) -> np.ndarray:
    """Contiguous ``[N, seq_len]`` packing (drops the ragged tail) — the
    reference's padded-batch builder, minus padding (packing wastes nothing)."""
    n = len(stream) // seq_len
    return stream[: n * seq_len].reshape(n, seq_len)


# --- evaluation (convai_evaluation.py analog: perplexity + hits@1) ------------


#: per-model jitted NLL — a fresh @jax.jit closure per evaluate call would
#: discard the compile cache and recompile the forward pass every epoch
_NLL_CACHE: dict = {}


def evaluate_perplexity(model, params, packed: np.ndarray, batch: int = 16) -> float:
    """exp(mean next-token NLL) over a held-out packed set."""
    import jax
    import jax.numpy as jnp

    from adapcc_tpu.models.gpt2 import lm_loss

    nll = _NLL_CACHE.get(model)
    if nll is None:
        nll = jax.jit(lambda p, b: lm_loss(model.apply(p, b), b))
        _NLL_CACHE[model] = nll

    total, count = 0.0, 0
    for i in range(0, len(packed) - batch + 1, batch):
        b = jnp.asarray(packed[i : i + batch])
        total += float(nll(params, b)) * len(b)
        count += len(b)
    if count == 0:
        raise ValueError(f"held-out set smaller than one batch ({len(packed)} < {batch})")
    return float(np.exp(total / count))


_SCORE_CACHE: dict = {}


def evaluate_hits_at_1(
    model, params, packed: np.ndarray, n_candidates: int = 4, max_rows: int = 64
) -> float:
    """Candidate-ranking accuracy, the reference's ConvAI hits@1 metric
    (models/gpt2/convai_evaluation.py ranks each gold reply against
    distractor candidates; its double-head model uses a trained classifier,
    ours ranks by LM log-likelihood — the zero-extra-parameter variant).

    Each held-out row ``[T]`` splits into context (first half) and
    continuation (second half); the gold continuation competes against
    ``n_candidates - 1`` distractor continuations drawn from other rows.
    Score = sum of next-token log-probs over the continuation positions.
    Chance level is ``1 / n_candidates``.
    """
    import jax
    import jax.numpy as jnp

    rows = np.asarray(packed[:max_rows])
    M, T = rows.shape
    half = T // 2
    if M < n_candidates or half < 2:
        raise ValueError(f"need >= {n_candidates} rows of length >= 4, got {rows.shape}")

    # the closure bakes in `half`, so the cache key must carry it (a
    # hash-equal model with a different seq split must not collide)
    score = _SCORE_CACHE.get((model, half))
    if score is None:

        def _score(p, seqs):
            # logits[:, t] predicts token t+1; sum log p over the continuation
            logits = model.apply(p, seqs).astype(jnp.float32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            nxt = jnp.take_along_axis(
                logp[:, :-1], seqs[:, 1:, None], axis=-1
            )[..., 0]
            return nxt[:, half - 1 :].sum(axis=-1)

        score = jax.jit(_score)
        _SCORE_CACHE[(model, half)] = score

    # candidate c for row i = continuation of row (i + c·stride) mod M; c=0 is
    # the gold one.  A fixed stride keeps the distractor draw deterministic.
    # All M·C sequences score in ONE jitted call — per-dispatch latency is the
    # dominant cost on a remote-tunnel backend (see benchmarks/profile_step).
    seqs = np.stack([
        np.concatenate([rows[i, :half], rows[(i + c * max(1, M // n_candidates)) % M, half:]])
        for i in range(M)
        for c in range(n_candidates)
    ])
    s = np.asarray(score(params, jnp.asarray(seqs))).reshape(M, n_candidates)
    return float(np.mean(np.argmax(s, axis=1) == 0))


# --- training -----------------------------------------------------------------


def _run_pipeline(
    args, mesh, world, model, cfg, params, tx, train_set, val_set
) -> Tuple[float, float]:
    """The --pp-stages branch: block stack split over stages, every hop
    through the traced engine, schedule resolved env > flag > tuner
    (docs/PIPELINE.md)."""
    import jax
    import jax.numpy as jnp
    import optax

    from adapcc_tpu.comm.engine import CollectiveEngine
    from adapcc_tpu.pipe import (
        PipelineExecutor,
        merge_params,
        partition_gpt2,
        split_params,
        sync_tied_embedding,
    )
    from adapcc_tpu.strategy.ir import Strategy
    from adapcc_tpu.utils import AverageMeter

    partition = partition_gpt2(cfg, args.pp_stages)
    engine = CollectiveEngine(mesh, Strategy.ring(world))
    executor = PipelineExecutor(
        cfg,
        partition,
        engine,
        num_microbatches=args.pp_microbatches,
        schedule=args.pp_schedule,
    )
    stage_params = split_params(params["params"], partition)
    opt_state = tx.init(stage_params)
    print(
        f"pipeline: {args.pp_stages} stages x {args.pp_microbatches} "
        f"microbatches, schedule {executor.schedule_kind}, "
        f"params/stage {partition.param_counts}"
    )

    def merged():
        # merge_params already rebuilds the {"params": ...} wrapper
        return merge_params(stage_params, partition)

    initial_ppl = evaluate_perplexity(model, merged(), val_set)
    print(f"val ppl before training: {initial_ppl:.1f} (uniform bound {float(args.vocab):.0f})")

    rng = np.random.default_rng(0)
    steps_per_epoch = max(1, len(train_set) // args.batch)
    ppl = initial_ppl
    for epoch in range(args.epochs):
        losses = AverageMeter("lm_loss", ":.4f")
        order = rng.permutation(len(train_set))
        for i in range(steps_per_epoch):
            b = jnp.asarray(train_set[order[i * args.batch : (i + 1) * args.batch]])
            loss, grads, report = executor.forward_backward(stage_params, b)
            updates, opt_state = tx.update(grads, opt_state, stage_params)
            stage_params = optax.apply_updates(stage_params, updates)
            sync_tied_embedding(stage_params)
            losses.update(float(loss), args.batch)
        ppl = evaluate_perplexity(model, merged(), val_set)
        print(
            f"epoch {epoch:3d}  {losses}  val ppl {ppl:.2f}  "
            f"(bubble {report.bubble_fraction:.2f}, stash peak "
            f"{report.stash_peak})"
        )

    hits = evaluate_hits_at_1(model, merged(), val_set)
    print(f"hits@1 over 4 candidates: {hits:.2f} (chance 0.25)")
    return initial_ppl, ppl


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--warmup-steps", type=int, default=20)
    p.add_argument("--clip-norm", type=float, default=1.0, help="reference max_norm=1.0")
    # 258 = the generate CLI's ByteTokenizer vocab (bytes + BOS/EOS), so a
    # default-trained checkpoint round-trips with a default generate command
    p.add_argument("--vocab", type=int, default=258)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=2)
    p.add_argument("--dmodel", type=int, default=128)
    p.add_argument("--corpus-tokens", type=int, default=200_000)
    p.add_argument("--world", type=int, default=None)
    p.add_argument("--checkpoint-file", type=str, default=None)
    p.add_argument("--sample", action="store_true", help="print a generation sample at the end")
    p.add_argument("--sp", choices=("none", "ring", "ulysses"), default="none",
                   help="sequence parallelism: shard the sequence (not the "
                        "batch) over the world — the long-context regime")
    p.add_argument("--attn", choices=("xla", "flash"), default="xla",
                   help="block attention implementation (flash = Pallas kernel)")
    p.add_argument("--loss", choices=("dense", "chunked"), default="dense",
                   help="LM loss: dense materializes [B,T,vocab] logits; "
                        "chunked fuses the head into an online-softmax scan")
    p.add_argument("--accum", type=int, default=1,
                   help="gradient accumulation: microbatches per step "
                        "(DDP path; per-rank batch must divide by it)")
    p.add_argument("--zero1", action="store_true",
                   help="shard the optimizer state ZeRO-1 style inside the "
                        "adaptive DDP step (fp32 flat master)")
    p.add_argument("--grad-compress", choices=["off", "bf16"], default="off",
                   help="bf16 gradient-sync wire compression (DDP path)")
    p.add_argument("--pp-stages", type=int, default=0,
                   help="pipeline parallelism: split the block stack over "
                        "this many stages (0 = off; docs/PIPELINE.md)")
    p.add_argument("--pp-microbatches", type=int, default=4,
                   help="microbatches per pipelined step (--batch must "
                        "divide by it)")
    p.add_argument("--pp-schedule", choices=("gpipe", "1f1b"), default=None,
                   help="pipeline tick schedule; omitted = "
                        "ADAPCC_PIPE_SCHEDULE > tuner > 1f1b")
    return p


def run(args) -> Tuple[float, float]:
    """Train; returns (initial_val_ppl, final_val_ppl)."""
    if args.sp != "none" and (args.accum != 1 or args.zero1):
        raise ValueError(
            "--accum/--zero1 ride the DDP trainer; they are not wired "
            "into the sequence-parallel step — drop --sp to use them"
        )
    if args.pp_stages:
        incompatible = []
        if args.sp != "none":
            incompatible.append("--sp")
        if args.accum != 1:
            incompatible.append("--accum")
        if args.zero1:
            incompatible.append("--zero1")
        if args.grad_compress != "off":
            incompatible.append("--grad-compress")
        if args.checkpoint_file:
            incompatible.append("--checkpoint-file")
        if incompatible:
            raise ValueError(
                f"{', '.join(incompatible)} ride the DDP trainer; the "
                "pipeline-parallel step (--pp-stages) runs its own "
                "executor — the pipeline already microbatches, syncs no "
                "gradients, and is not checkpoint-wired (docs/PIPELINE.md)"
            )
        if args.pp_stages < 2:
            raise ValueError(
                f"--pp-stages {args.pp_stages}: a pipeline needs at least "
                "2 stages (omit the flag for single-stage training)"
            )
        if args.batch % args.pp_microbatches:
            raise ValueError(
                f"--batch {args.batch} must divide by --pp-microbatches "
                f"{args.pp_microbatches}"
            )
    from adapcc_tpu.launch import maybe_initialize_distributed

    maybe_initialize_distributed()

    import jax
    import jax.numpy as jnp
    import optax

    from adapcc_tpu.comm.mesh import build_world_mesh
    from adapcc_tpu.data import device_batches
    from adapcc_tpu.ddp import DDPTrainer, TrainState
    from adapcc_tpu.models.gpt2 import GPT2, GPT2Config, lm_loss
    from adapcc_tpu.strategy.ir import Strategy
    from adapcc_tpu.utils import AverageMeter

    mesh = build_world_mesh(args.world)
    world = int(mesh.devices.size)

    stream = markov_corpus(args.corpus_tokens, args.vocab, seed=0)
    packed = pack_sequences(stream, args.seq)
    n_val = max(16, len(packed) // 10)
    if len(packed) < n_val + args.batch:
        raise ValueError(
            f"corpus too small: {len(packed)} sequences of len {args.seq} can't "
            f"cover {n_val} validation rows plus one {args.batch}-row training "
            f"batch; raise --corpus-tokens or lower --seq/--batch"
        )
    train_set, val_set = packed[:-n_val], packed[-n_val:]

    cfg = GPT2Config(
        vocab_size=args.vocab, max_seq=args.seq, n_layer=args.layers,
        n_head=args.heads, d_model=args.dmodel, dtype=jnp.float32,
        attention=args.attn,
    )
    model = GPT2(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(train_set[:1]))

    if args.loss == "chunked":
        # fuse the LM head into the online-softmax loss: no [B, T, vocab]
        # logits tensor (ops/chunked_ce.py) — the long-vocab memory saver
        # (the SP branch passes loss= through to its own sharded variant)
        from adapcc_tpu.models.gpt2 import lm_loss_chunked

        def loss_fn(p, b):
            return lm_loss_chunked(model, p, b, block=min(1024, args.vocab))
    else:

        def loss_fn(p, b):
            return lm_loss(model.apply(p, b), b)

    steps_per_epoch = max(1, len(train_set) // args.batch)
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=args.lr,
        warmup_steps=args.warmup_steps,
        decay_steps=max(args.warmup_steps + 1, steps_per_epoch * args.epochs),
    )
    # reference recipe: AdamW + clipping + decaying LR (train_gpt2_ddp.py's
    # PiecewiseLinear decay and max_norm clipping)
    tx = optax.chain(
        optax.clip_by_global_norm(args.clip_norm),
        optax.adamw(schedule, weight_decay=0.01),
    )
    if args.pp_stages:
        return _run_pipeline(
            args, mesh, world, model, cfg, params, tx, train_set, val_set
        )
    if args.sp != "none":
        # sequence parallelism: the batch is replicated and the SEQUENCE is
        # sharded over the world axis — the long-context regime (the DDP
        # axis is the reference's; SP is the new capability, SURVEY §5.7)
        import dataclasses

        from adapcc_tpu.parallel import gpt2_sp_train_step

        if args.seq % world:
            raise ValueError(f"--seq {args.seq} must divide by world {world} under --sp")
        sp_model = GPT2(dataclasses.replace(cfg, sp_axis="ranks", sp_impl=args.sp))
        sp_step = gpt2_sp_train_step(sp_model, tx, mesh, loss=args.loss)
        trainer = None
    else:
        trainer = DDPTrainer(
            loss_fn, tx, mesh, Strategy.ring(world),
            accum_steps=args.accum, zero1=args.zero1,
            grad_compress=args.grad_compress,
            # NO donate_state here, unlike the other workloads: this loop
            # feeds from the async device_batches prefetcher, and a donating
            # step racing the prefetch thread's device_put deadlocks the
            # XLA CPU collective rendezvous (verified on the 8-device pod:
            # only some ranks join, 40 s timeout, SIGABRT).  Donated
            # steady-state throughput is measured by bench.py, which uses a
            # static batch and can donate safely.
        )
    state = (
        trainer.init_state(params) if trainer is not None
        else TrainState.create(params, tx)
    )

    initial_ppl = evaluate_perplexity(model, state.params, val_set)
    uniform = float(args.vocab)
    print(f"val ppl before training: {initial_ppl:.1f} (uniform bound {uniform:.0f})")

    ppl = initial_ppl
    for epoch in range(args.epochs):
        losses = AverageMeter("lm_loss", ":.4f")
        # keep per-step losses on device; one host sync per epoch preserves
        # the trainer's async dispatch (see DDPTrainer's host-step comment)
        epoch_losses = []
        # async input pipeline: the next batch lands on device — already
        # sharded over the data axis on the DDP path — while the current
        # step computes
        batches = device_batches(
            train_set, args.batch,
            mesh=None if trainer is None else mesh, seed=epoch,
        )
        for b in batches:
            if trainer is None:
                params2, opt_state2, loss = sp_step(
                    state.params, state.opt_state, b
                )
                state = TrainState(
                    params=params2, opt_state=opt_state2, step=state.step + 1
                )
            else:
                state, loss = trainer.step(state, b)
            epoch_losses.append(jnp.mean(loss))
        for val in np.asarray(jax.device_get(epoch_losses)):
            losses.update(float(val), args.batch)
        ppl = evaluate_perplexity(model, state.params, val_set)
        print(f"epoch {epoch:3d}  {losses}  val ppl {ppl:.2f}")

        if args.checkpoint_file:
            from adapcc_tpu.checkpoint import TrainCheckpointState, save_checkpoint

            save_checkpoint(
                TrainCheckpointState(
                    params=state.params, opt_state=state.opt_state,
                    epoch=epoch, step=int(state.step),
                    # --zero1 runs stamp the optimizer layout so a resume
                    # with --zero1-ring flipped fails loudly (checkpoint.py's
                    # apply_snapshot guard) instead of loading permuted
                    # master weights
                    extra=(
                        trainer.checkpoint_extra() if trainer is not None
                        else {}
                    ),
                ),
                args.checkpoint_file,
            )

    hits = evaluate_hits_at_1(model, state.params, val_set)
    print(f"hits@1 over 4 candidates: {hits:.2f} (chance 0.25)")

    if args.sample:
        from adapcc_tpu.models.gpt2_generate import generate

        prompt = jnp.asarray(val_set[:1, :8], jnp.int32)
        out = generate(
            model, state.params["params"],  # init() wraps in a "params" collection
            prompt, prompt_len=8, max_new_tokens=24, temperature=0.8, top_k=8,
        )
        print("sample continuation:", np.asarray(out[0])[8:].tolist())

    return initial_ppl, ppl


def main(argv: Optional[Sequence[str]] = None) -> int:
    run(build_parser().parse_args(argv))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
