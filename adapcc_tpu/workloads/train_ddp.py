"""DDP training driver — the reference ``train_ddp.py`` re-shaped for TPU.

The reference flow (train_ddp.py:30-58): init AdapCC with the launcher flag
contract, register the allreduce bucket hook on a torch DDP model, call
``update_relay(step)`` every iteration, and ``reconstruct_topology`` every
``profile_freq`` steps.  This driver keeps that flow — same flags, same
lifecycle — with the jitted :class:`DDPTrainer` as the data plane and
synthetic data (the reference benchmarks run synthetic batches too).

Run (virtual pod):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python -m adapcc_tpu.workloads.train_ddp --model mlp --steps 20
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from adapcc_tpu import ALLREDUCE, AdapCC
from adapcc_tpu.comm.mesh import build_world_mesh
from adapcc_tpu.config import CommArgs
from adapcc_tpu.ddp import DDPTrainer, TrainState
from adapcc_tpu.primitives import DETECT


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    # reference launcher flag contract (launcher.py:19-32)
    p.add_argument("--port", type=int, default=50051)
    p.add_argument("--strategy_file", type=str, default="topology/strategy.xml")
    p.add_argument("--logical_graph", type=str, default="topology/logical_graph.xml")
    p.add_argument("--entry_point", type=int, default=DETECT)
    p.add_argument("--parallel_degree", type=int, default=2)
    p.add_argument("--profile_freq", type=int, default=0)
    # workload knobs
    p.add_argument(
        "--model",
        choices=["mlp", "vgg", "resnet18", "resnet50", "vit", "gpt2"],
        default="mlp",
    )
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--world", type=int, default=None, help="mesh size (default: all devices)")
    p.add_argument("--coordinator", action="store_true", help="enable the relay/fault coordinator")
    p.add_argument(
        "--dp-mode", choices=["ddp", "fsdp", "zero1"], default="ddp",
        help="data-parallel state layout: ddp replicates (adaptive bucket "
        "hook); fsdp shards params+optimizer via GSPMD; zero1 shards the "
        "optimizer on a flat fp32 master (both beyond the reference)",
    )
    p.add_argument(
        "--zero1-ring", action="store_true",
        help="zero1: ride the Pallas ICI ring kernels for the "
        "reduce-scatter/all-gather pair instead of XLA's (the hand-tuned "
        "data plane; shards become VMEM-tile aligned)",
    )
    p.add_argument(
        "--ring-chunk-bytes", type=int, default=0,
        help="zero1-ring staging granularity in bytes (0 = the synthesized "
        "default); payloads above it stream through fixed HBM→VMEM staging. "
        "ADAPCC_RING_CHUNK_BYTES overrides for sweeps",
    )
    p.add_argument(
        "--min-shard-elems", type=int, default=2**14,
        help="fsdp: leaves smaller than this stay replicated",
    )
    p.add_argument(
        "--no-bsp", dest="is_bsp", action="store_false", default=True,
        help="async relay mode: straggler gradients are buffered and folded "
        "into their next active step instead of dropped (reference is_bsp)",
    )
    p.add_argument(
        "--grad-compress", choices=["off", "bf16", "int8"], default="off",
        help="gradient-sync wire codec (quant registry): bf16 halves ICI/DCN "
        "bytes (~bf16-eps error on the synced mean); int8 quantizes "
        "block-wise with per-block fp32 scales (docs/QUANT.md)",
    )
    p.add_argument(
        "--wire-dtype", choices=["off", "bf16", "int8", "strategy"],
        default=None,
        help="wire codec for the data plane, overriding --grad-compress "
        "when given: ddp mode feeds the gradient hook ('strategy' adopts "
        "the synthesized Strategy.wire_dtype); zero1 mode feeds the "
        "reduce-scatter contribution.  ADAPCC_WIRE_DTYPE overrides for "
        "sweeps (malformed value -> loud error)",
    )
    p.add_argument(
        "--error-feedback", action="store_true",
        help="carry the per-rank quantization residual into the next step's "
        "gradient (closes the int8 accuracy gap; requires --dp-mode ddp)",
    )
    p.add_argument(
        "--tune", action="store_true",
        help="measurement-driven autotuning (adapcc_tpu/tuner): record each "
        "step's walltime into the tuning database (ADAPCC_TUNER_DB, default "
        "topology/tuning.jsonl) and adopt the policy's choices — the "
        "gradient-sync wire codec in ddp mode, the ring staging chunk in "
        "zero1 mode.  ADAPCC_TUNER=off disables globally; "
        "ADAPCC_RING_CHUNK_BYTES / ADAPCC_WIRE_DTYPE still override "
        "whatever the tuner picks (docs/TUNER.md)",
    )
    p.add_argument(
        "--overlap", choices=["off", "microbatch", "bucket"], default="off",
        help="overlapped gradient sync (docs/OVERLAP.md): bucket = "
        "per-bucket rolling collectives honoring the plan's chunk_bytes "
        "(bitwise-identical gradients); microbatch = pipeline each "
        "microbatch delta's allreduce behind the next microbatch's "
        "compute (needs --accum >= 2, --dp-mode ddp).  ADAPCC_OVERLAP "
        "overrides for sweeps (malformed value -> loud error)",
    )
    p.add_argument(
        "--accum", type=int, default=1,
        help="gradient accumulation microbatches per step (ddp mode; the "
        "axis the microbatch overlap schedule pipelines over)",
    )
    p.add_argument(
        "--sync-mode", choices=["auto", "psum", "schedule"], default="auto",
        help="gradient-sync data plane: psum = masked XLA collective per "
        "leaf; schedule = bucketed strategy-tree allreduce (multi-tree "
        "strategies run merged rounds); auto picks by topology",
    )
    p.add_argument(
        "--adapt", choices=["off", "detect", "swap"], default="off",
        help="closed-loop online adaptation (docs/ADAPT.md; requires "
        "--dp-mode ddp): feed each step's walltime to the passive drift "
        "detector and run detect -> recalibrate -> re-rank every "
        "--adapt-every steps; 'swap' additionally adopts the re-ranked "
        "strategy through the epoch hot-swap.  ADAPCC_ADAPT overrides "
        "(malformed value -> loud error); ADAPCC_DRIFT_FACTOR / "
        "ADAPCC_DRIFT_WINDOW tune the detector",
    )
    p.add_argument(
        "--adapt-every", type=int, default=8,
        help="steps between adaptation passes (--adapt detect|swap)",
    )
    p.add_argument(
        "--supervisor", action="store_true",
        help="autonomous supervisor daemon (docs/SUPERVISOR.md; requires "
        "--dp-mode ddp): an out-of-band thread owns detect -> decide -> "
        "swap — heartbeat/fault-plan detection, fsync'd decision journal "
        "(topology/supervisor.journal), standby-cache failover, and the "
        "--adapt loop when armed — while the training loop only observes "
        "epoch bumps.  ADAPCC_SUPERVISOR=on|off overrides (malformed -> "
        "loud error)",
    )
    p.add_argument(
        "--supervisor-period", type=float, default=0.25,
        help="supervisor poll cadence in seconds (--supervisor)",
    )
    return p


def make_workload(name: str, batch: int, rng):
    """Returns (loss_fn, params, batch_fn)."""
    if name == "mlp":
        from adapcc_tpu.models import MLP

        model = MLP(features=(64, 64, 10))
        x = jnp.asarray(np.random.default_rng(0).normal(size=(batch, 32)), jnp.float32)
        y = jnp.asarray(np.random.default_rng(1).integers(0, 10, size=(batch,)))
        params = model.init(rng, x[:1])

        def loss_fn(p, b):
            bx, by = b
            logits = model.apply(p, bx)
            return optax.softmax_cross_entropy_with_integer_labels(logits, by).mean()

        return loss_fn, params, lambda: (x, y)

    if name in ("vgg", "resnet18", "resnet50"):
        if name == "vgg":
            from adapcc_tpu.models.vgg import VGG16

            model = VGG16(num_classes=10, classifier_width=512)
        else:
            # stateless GroupNorm variant: drops into the same loss_fn
            # contract as every other workload (SyncBN runs in main_elastic)
            from adapcc_tpu.models.resnet import ResNet18, ResNet50

            ctor = ResNet18 if name == "resnet18" else ResNet50
            model = ctor(num_classes=10, small_inputs=True, dtype=jnp.float32)
        x = jnp.asarray(np.random.default_rng(0).normal(size=(batch, 32, 32, 3)), jnp.float32)
        y = jnp.asarray(np.random.default_rng(1).integers(0, 10, size=(batch,)))
        params = model.init(rng, x[:1])

        def loss_fn(p, b):
            bx, by = b
            logits = model.apply(p, bx)
            return optax.softmax_cross_entropy_with_integer_labels(logits, by).mean()

        return loss_fn, params, lambda: (x, y)

    if name == "vit":
        from adapcc_tpu.models.vit import ViT, ViTConfig

        cfg = ViTConfig(image_size=64, patch_size=8, num_classes=100, d_model=192, n_layer=6, n_head=3)
        model = ViT(cfg)
        x = jnp.asarray(np.random.default_rng(0).normal(size=(batch, 64, 64, 3)), jnp.float32)
        y = jnp.asarray(np.random.default_rng(1).integers(0, 100, size=(batch,)))
        params = model.init(rng, x[:1])

        def loss_fn(p, b):
            bx, by = b
            logits = model.apply(p, bx)
            return optax.softmax_cross_entropy_with_integer_labels(logits, by).mean()

        return loss_fn, params, lambda: (x, y)

    if name == "gpt2":
        from adapcc_tpu.models.gpt2 import GPT2, GPT2Config, lm_loss

        cfg = GPT2Config(vocab_size=8192, max_seq=256, n_layer=4, n_head=4, d_model=256)
        model = GPT2(cfg)
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab_size, size=(batch, cfg.max_seq))
        )
        params = model.init(rng, tokens[:1])

        def loss_fn(p, b):
            return lm_loss(model.apply(p, b), b)

        return loss_fn, params, lambda: tokens

    raise ValueError(name)


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    # the adaptation mode actually in force (ADAPCC_ADAPT wins over the
    # flag; malformed env/flag -> loud error before any engine side effects)
    from adapcc_tpu.adapt import adapt_mode

    adapt = adapt_mode(args.adapt)
    if args.adapt_every < 1:
        raise ValueError(f"--adapt-every must be >= 1, got {args.adapt_every}")
    if adapt != "off" and args.dp_mode != "ddp":
        raise ValueError(
            "--adapt/ADAPCC_ADAPT requires --dp-mode ddp: the closed loop "
            "re-ranks and hot-swaps the DDP gradient hook's strategy "
            "(zero1/fsdp sync via GSPMD and carry no strategy to swap)"
        )
    # the supervisor mode actually in force (ADAPCC_SUPERVISOR wins over
    # the flag; malformed env -> loud error before any engine side effects)
    from adapcc_tpu.supervisor import supervisor_enabled

    supervised = supervisor_enabled(args.supervisor)
    if args.supervisor_period <= 0:
        raise ValueError(
            f"--supervisor-period must be > 0, got {args.supervisor_period}"
        )
    if supervised and args.dp_mode != "ddp":
        raise ValueError(
            "--supervisor/ADAPCC_SUPERVISOR requires --dp-mode ddp: the "
            "daemon actuates the DDP gradient hook's strategy through the "
            "standby cache (zero1/fsdp carry no strategy to swap)"
        )
    if args.dp_mode != "ddp":
        # sharded-state modes sync via GSPMD/psum, not the adaptive hook —
        # the relay/straggler machinery rides the hook, so reject the combo
        # up front (before any server/engine side effects) instead of
        # silently ignoring the flags
        if args.coordinator or not args.is_bsp or args.profile_freq:
            raise ValueError(
                "--coordinator/--no-bsp/--profile_freq require --dp-mode ddp "
                "(relay and re-adaptation ride the DDP gradient hook)"
            )
        import os as _os

        from adapcc_tpu.elastic import FAULT_PLAN_ENV

        if _os.environ.get(FAULT_PLAN_ENV, "").strip():
            # fault injection rides the DDP hook's relay masks; silently
            # running a healthy world under a set plan would be the exact
            # "set-but-broken is quiet" failure the env contract forbids
            raise ValueError(
                f"{FAULT_PLAN_ENV} requires --dp-mode ddp (fault injection "
                "drives the DDP trainer's per-step relay masks; zero1/fsdp "
                "have no relay plane to inject into)"
            )
        from adapcc_tpu.sim.congestion import CONGESTION_PROFILE_ENV as _CONG

        if _os.environ.get(_CONG, "").strip():
            # congestion injection rides the DDP adaptation controller;
            # same set-but-quiet contract as the fault plan above
            raise ValueError(
                f"{_CONG} requires --dp-mode ddp (congestion injection "
                "feeds the adaptation controller's observation funnel, "
                "which rides the DDP gradient hook)"
            )
    if args.zero1_ring and args.dp_mode != "zero1":
        raise ValueError("--zero1-ring requires --dp-mode zero1")
    # one wire-codec knob across modes: --wire-dtype wins over the older
    # --grad-compress spelling when both are given
    wire_dtype = args.wire_dtype if args.wire_dtype is not None else args.grad_compress
    if args.error_feedback and args.dp_mode != "ddp":
        raise ValueError(
            "--error-feedback requires --dp-mode ddp (the residual bank "
            "rides the DDP gradient hook)"
        )
    if wire_dtype == "strategy" and args.dp_mode != "ddp":
        raise ValueError(
            "--wire-dtype strategy requires --dp-mode ddp (only the "
            "gradient hook carries a synthesized strategy to adopt)"
        )
    if args.tune and args.dp_mode == "fsdp":
        raise ValueError(
            "--tune requires --dp-mode ddp or zero1: fsdp syncs via GSPMD "
            "and exposes none of the tuner's knobs (chunk/codec)"
        )
    # the overlap schedule actually in force (ADAPCC_OVERLAP wins over the
    # flag; malformed env -> loud error before any engine side effects)
    from adapcc_tpu.ddp import resolve_overlap_mode

    overlap = resolve_overlap_mode(args.overlap)
    if args.dp_mode == "fsdp" and overlap != "off":
        raise ValueError(
            "--overlap requires --dp-mode ddp or zero1: fsdp's collectives "
            "are GSPMD-inserted and expose no overlap schedule"
        )
    if args.dp_mode == "zero1" and overlap == "microbatch":
        raise ValueError(
            "--overlap microbatch requires --dp-mode ddp: the pipeline "
            "rides the DDP trainer's accumulation scan (zero1 supports "
            "--overlap bucket — chunked reduce-scatter/all-gather)"
        )
    if args.accum < 1:
        raise ValueError(f"--accum must be >= 1, got {args.accum}")
    if args.accum > 1 and args.dp_mode != "ddp":
        raise ValueError(
            "--accum requires --dp-mode ddp (gradient accumulation rides "
            "the DDP trainer's compiled scan)"
        )
    # join the multi-host world if the launcher set the coordinator env
    from adapcc_tpu.launch import maybe_initialize_distributed

    maybe_initialize_distributed()
    mesh = build_world_mesh(args.world)
    world = int(mesh.devices.size)

    comm_args = CommArgs.from_namespace(args)
    if args.dp_mode == "ddp":
        # the adaptive bootstrap + collective engine back the gradient hook;
        # the GSPMD modes never touch them, so they skip the whole lifecycle
        AdapCC.init(comm_args, mesh=mesh)
        AdapCC.setup(ALLREDUCE)
        if args.coordinator:
            AdapCC.communicator.enable_coordinator(
                is_master=True, num_processes=1, port=0
            )

    loss_fn, params, batch_fn = make_workload(args.model, args.batch, jax.random.PRNGKey(0))
    tx = optax.adam(args.lr)

    if args.dp_mode == "fsdp":
        from adapcc_tpu.parallel import fsdp_shardings, fsdp_train_step
        from jax.sharding import PartitionSpec

        sh = fsdp_shardings(params, mesh, min_shard_elems=args.min_shard_elems)
        params = jax.device_put(params, sh)
        n_sharded = sum(
            s.spec != PartitionSpec() for s in jax.tree_util.tree_leaves(sh)
        )
        print(f"fsdp: {n_sharded}/{len(jax.tree_util.tree_leaves(sh))} leaves sharded")
        opt_state = tx.init(params)
        fsdp_step = fsdp_train_step(
            loss_fn, tx, mesh, min_shard_elems=args.min_shard_elems
        )

        def run_step(step):
            nonlocal params, opt_state
            params, opt_state, loss = fsdp_step(params, opt_state, batch_fn())
            return loss

    elif args.dp_mode == "zero1":
        from adapcc_tpu.parallel import Zero1Optimizer, zero1_train_step

        z_tuner = None
        if args.tune:
            from adapcc_tpu.tuner import CollectiveTuner

            z_tuner = CollectiveTuner.for_mesh(mesh, mode="choose")
        z_opt = Zero1Optimizer(
            tx, mesh, ring=args.zero1_ring,
            ring_chunk_bytes=args.ring_chunk_bytes or None,
            wire_dtype=wire_dtype,
            tuner=z_tuner,
            # env-resolved above; the Pallas ring keeps one chunking plane
            overlap="off" if args.zero1_ring else overlap,
        )
        master, z_state = z_opt.init(params)
        # redundant shard placement (docs/RECOVERY.md §1): with
        # ADAPCC_SHARD_REPLICAS > 0 every step's freshly-written optimizer
        # shard rows are captured to their ring-neighbor holders inside
        # the post-step window — the elastic_rejoin battery A/Bs this
        # against k=0 to price the piggyback on real chips
        from adapcc_tpu.elastic.redundancy import (
            ShardReplicaStore,
            shard_replicas,
        )

        z_replicas = shard_replicas(default=0)
        z_store = None
        if z_replicas:
            z_store = ShardReplicaStore(world, replicas=z_replicas)
            print(
                f"redundancy: zero1 shard replicas k={z_replicas} "
                f"(ring-neighbor placement over world={world})"
            )
        if z_opt.tuned_plan is not None:
            tp = z_opt.tuned_plan
            print(
                f"tuner: zero1 ring chunk_bytes={z_opt.ring_chunk_bytes} "
                f"(source={tp.source})"
            )
        z_step = zero1_train_step(loss_fn, z_opt, mesh)

        # step walltimes must land in the SAME cell the next run's
        # init()-time choose("zero1_ring", ...) ranks, or the feedback loop
        # never closes; tuning_key() is that cell (None off the ring path —
        # plain zero1 has no tuner knob, so nothing is recorded)
        z_cell = z_opt.tuning_key() if z_tuner is not None else None

        def run_step(step):
            nonlocal params, master, z_state
            if z_cell is not None and z_tuner.recording:
                import time as _time

                t0 = _time.perf_counter()
                params, master, z_state, losses = z_step(
                    params, master, z_state, batch_fn()
                )
                jax.block_until_ready(losses)
                z_tuner.observe_dispatch(
                    z_cell, ("zero1_step",), _time.perf_counter() - t0
                )
            else:
                params, master, z_state, losses = z_step(
                    params, master, z_state, batch_fn()
                )
            if z_store is not None:
                # the piggyback window: the shard rows this step's update
                # just wrote ride to their holders, stamped for the
                # repair path's freshness guard
                z_store.capture((master, z_state), step)
            return losses

    else:
        trainer = DDPTrainer(
            loss_fn,
            tx,
            mesh,
            AdapCC.communicator.strategy,
            communicator=AdapCC.communicator,
            use_xla_fastpath=comm_args.use_xla_fastpath,
            bsp=comm_args.is_bsp,
            sync_mode=args.sync_mode,
            grad_compress=wire_dtype,
            error_feedback=args.error_feedback,
            tune=args.tune,
            accum_steps=args.accum,
            overlap=overlap,
            # loop-owned state: see train_gpt2 donation note
            donate_state=True,
        )
        state = TrainState.create(params, tx)

        # deterministic fault injection (docs/ELASTIC.md): with
        # ADAPCC_FAULT_PLAN set, each step's relay mask is derived from the
        # plan's fault state — down/slow ranks stop contributing (and
        # recover on schedule) through the SAME compiled dynamic-mask step,
        # so the run exercises a real world shrink + recovery.  This is the
        # data plane the elastic_failover battery entry measures.
        from adapcc_tpu.elastic import load_fault_plan
        from adapcc_tpu.sim.congestion import (
            CONGESTION_PROFILE_ENV,
            load_congestion_profile,
        )

        fault_plan = load_fault_plan(world=world)
        if fault_plan is not None:
            print(f"fault injection: {fault_plan!r}")
        congestion_profile = load_congestion_profile(world=world)
        if congestion_profile is not None and adapt == "off":
            # the profile feeds the adaptation controller's triage; a set
            # profile with the loop disarmed would silently inject nothing
            # — the exact "set-but-broken is quiet" failure the env
            # contract forbids
            raise ValueError(
                f"{CONGESTION_PROFILE_ENV} requires --adapt detect|swap "
                "(congestion injection rides the adaptation controller's "
                "observation funnel; with the loop off nothing consumes it)"
            )

        # closed-loop online adaptation (docs/ADAPT.md): the controller
        # rides the communicator's own seams (engine, synthesizer, tuning
        # database, calibration artifact); step walltimes are its passive
        # measurement feed — zero probe traffic
        adapt_ctl = None
        grad_bytes = 0
        if adapt != "off":
            # prewarm the TRAINER's step program for a winning candidate
            # before adoption, so the swap is a cache hit there too (no
            # recompile on the failover step).  The closure reads the live
            # `state`, so the AOT trace sees the real shapes.  Banked
            # trainer modes (async relay / error feedback) cannot prewarm
            # — adoption falls back to the documented cold rebuild.
            prewarm = None
            if comm_args.is_bsp and not args.error_feedback:
                prewarm = lambda s: trainer.prewarm(s, state, batch_fn())  # noqa: E731
            adapt_ctl = AdapCC.communicator.adaptation_controller(
                trainer=trainer, mode=args.adapt, trainer_prewarm=prewarm,
            )
            grad_bytes = sum(
                leaf.nbytes for leaf in jax.tree_util.tree_leaves(params)
            )
            print(f"online adaptation: mode={adapt} every={args.adapt_every}")
            # deterministic congestion injection (docs/FABRIC.md §4): with
            # ADAPCC_CONGESTION_PROFILE set, each step ticks the profile's
            # windows into the controller's PRICED observation feed (the
            # observation-funnel twin of the fault-plan injection above),
            # so the congestion-vs-degradation triage is exercisable on a
            # live run — re-route inside a window, restore after it
            if congestion_profile is not None:
                adapt_ctl.attach_congestion_profile(congestion_profile)
                print(f"congestion injection: {congestion_profile!r}")

        # autonomous supervisor (docs/SUPERVISOR.md): the daemon — not
        # this loop — folds the fault plan (and any heartbeat silence)
        # into the worldview, journals every decision, and actuates the
        # standby-cache swap + trainer adoption; the loop only consumes
        # the last actuated mask through the attached-trainer seam and
        # retries EpochMismatch as it always did
        supervisor = None
        current_step = [0]
        if supervised:
            import os as _os

            # a FRESH run must not replay the previous run's journal into
            # its healthy world; an elastic restart of the SAME run (the
            # replay case the journal exists for) is marked by the
            # launcher's ADAPCC_RESTART_GEN and keeps it
            journal_path = _os.path.join(
                comm_args.topology_dir, "supervisor.journal"
            )
            if (
                not _os.environ.get("ADAPCC_RESTART_GEN", "").strip()
                and _os.path.exists(journal_path)
            ):
                _os.remove(journal_path)
            supervisor = AdapCC.communicator.supervisor(
                journal_path=journal_path,
                trainer=trainer,
                fault_plan=fault_plan,
                step_source=(
                    (lambda: current_step[0])
                    if fault_plan is not None else None
                ),
                adapt=adapt_ctl,
                # polls, not steps: the daemon's clock is its own
                adapt_every=args.adapt_every if adapt_ctl is not None else 0,
            )
            trainer.attach_supervisor(supervisor)
            if comm_args.is_bsp and not args.error_feedback and args.accum == 1:
                # AOT-prewarm the step for the top standby plans so the
                # daemon's adoption is a cache hit on the trainer plane too
                for splan in supervisor.cache.ranked()[: supervisor.cache.top_k]:
                    trainer.prewarm(splan.strategy, state, batch_fn())
            supervisor.start(period_s=args.supervisor_period)
            print(
                f"supervisor: period={args.supervisor_period}s "
                f"journal={supervisor.journal.path}"
            )

        def run_step(step):
            nonlocal state
            current_step[0] = step
            if supervisor is not None and fault_plan is not None:
                # the injected feed is STEP-indexed, so its natural clock
                # is the step counter: one deterministic tick per step
                # (the decisions stay the daemon's; wall-clock heartbeat
                # detection keeps riding the background thread)
                supervisor.poll()
            # periodic re-adaptation (reference train_ddp.py:45-46)
            if args.profile_freq and step > 0 and step % args.profile_freq == 0:
                AdapCC.reconstruct_topology(comm_args, ALLREDUCE)
                trainer.rebuild(AdapCC.communicator.strategy)
            mask = None
            if fault_plan is not None and supervisor is None:
                mask = jnp.asarray(fault_plan.mask_at(step))
            t0 = time.perf_counter() if adapt_ctl is not None else 0.0
            state, loss = trainer.step(
                state, batch_fn(), step_idx=step, active_mask=mask
            )
            if adapt_ctl is not None:
                # the block serializes the loop by design: the sample is
                # the step's dispatch-to-completion walltime (the tuner's
                # record-mode contract)
                jax.block_until_ready(loss)
                adapt_ctl.observe_step(time.perf_counter() - t0, grad_bytes)
                # the congestion profile's step tick (no-op when no
                # profile is attached): window steps feed contended priced
                # samples, healthy steps feed reversal evidence
                adapt_ctl.tick(step)
                if supervisor is not None:
                    pass  # the daemon runs maybe_adapt on its own cadence
                elif step > 0 and step % args.adapt_every == 0:
                    rep = adapt_ctl.maybe_adapt()
                    if rep.swapped:
                        print(
                            f"adapt: step {step} swapped to "
                            f"{rep.winner_label} ({rep.winner_fingerprint}) "
                            f"stall={rep.stall_s:.6f}s "
                            f"trainer_hit={rep.trainer_adopt_hit}"
                        )
                    elif rep.outcome == "uninvertible":
                        # step walltimes alone carry no link algebra, so a
                        # pure-DDP loop can DETECT drift but not attribute
                        # it to links — say so instead of silently idling
                        print(
                            f"adapt: step {step} drift detected but "
                            "uninvertible (step-walltime evidence only; "
                            "link-attributable samples — tuner-recorded "
                            "engine dispatches — are needed to "
                            "re-calibrate and swap)"
                        )
                    elif rep.outcome not in ("no-drift", "off"):
                        print(f"adapt: step {step} {rep.outcome}")
            return loss

    t_last = time.perf_counter()
    for step in range(args.steps):
        loss = run_step(step)
        if step % 5 == 0 or step == args.steps - 1:
            now = time.perf_counter()
            print(
                f"step {step:4d}  loss {float(jnp.mean(loss)):.4f}  "
                f"({(now - t_last):.3f}s since last log)  world={world} "
                f"mode={args.dp_mode}"
            )
            t_last = now

    if args.dp_mode == "ddp":
        if supervisor is not None:
            supervisor.stop()
            wv = supervisor.worldview()
            print(
                f"supervisor: {supervisor.decisions} decisions, "
                f"wv_epoch={wv.epoch} alive={sorted(wv.alive)} "
                f"relays={sorted(wv.relays)} "
                f"journal={supervisor.journal.path}"
            )
        AdapCC.clear(ALLREDUCE)


if __name__ == "__main__":
    main()
