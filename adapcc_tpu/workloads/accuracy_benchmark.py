"""Accuracy benchmark — the reference's image-classification accuracy study.

Reference behavior (models/image-classification/accuracy_benchmark.py):
epoch-based classifier training with AverageMeter/ProgressMeter progress
lines, top-1/top-5 accuracy, a validation pass per epoch, optional
gradient-noise-scale hooks (commented there at accuracy_benchmark.py:369-374
— first-class here via ``DDPTrainer(measure_gns=True)``), and accuracy
traces dumped to .txt for the committed plots.

The dataset is synthetic-but-learnable (Gaussian class blobs): accuracy
starts at chance and climbs, so the benchmark validates end-to-end learning
through the adaptive DDP stack, not just step mechanics.

Run (virtual pod):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python -m adapcc_tpu.workloads.accuracy_benchmark --epochs 3
"""

from __future__ import annotations

import argparse
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

from adapcc_tpu.utils import AverageMeter, ProgressMeter


def topk_accuracy(logits, labels, ks: Sequence[int] = (1, 5)):
    """Top-k accuracies (%) for ``logits [B, C]`` vs ``labels [B]`` —
    the reference's ``accuracy(output, target, topk=(1, 5))``."""
    import jax.numpy as jnp

    ks = tuple(min(k, logits.shape[-1]) for k in ks)
    ranked = jnp.argsort(logits, axis=-1)[:, ::-1]
    out = []
    for k in ks:
        hit = (ranked[:, :k] == labels[:, None]).any(axis=-1)
        out.append(100.0 * jnp.mean(hit.astype(jnp.float32)))
    return out


def make_blob_dataset(
    n: int, num_classes: int, image_size: int = 8, channels: int = 3,
    noise: float = 1.0, seed: int = 0, means_seed: int = 1234,
) -> Tuple[np.ndarray, np.ndarray]:
    """Learnable synthetic classification data: one Gaussian blob per class
    in pixel space, noise-corrupted.  Linear separability makes accuracy an
    honest end-to-end training signal without any dataset download.

    ``means_seed`` fixes the class centers independently of ``seed`` (the
    sample draw), so train and validation splits share one distribution.
    """
    means = np.random.default_rng(means_seed).normal(
        size=(num_classes, image_size, image_size, channels)
    )
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, size=(n,))
    images = means[labels] + noise * rng.normal(size=(n, image_size, image_size, channels))
    return images.astype(np.float32), labels.astype(np.int32)


def batches(
    images: np.ndarray, labels: np.ndarray, batch: int, seed: int
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Shuffled full batches (drops the ragged tail, like the reference's
    DataLoader with drop_last)."""
    idx = np.random.default_rng(seed).permutation(len(images))
    for i in range(0, len(idx) - batch + 1, batch):
        sel = idx[i : i + batch]
        yield images[sel], labels[sel]


def validate(apply_fn, params, images, labels, batch: int = 64) -> Tuple[float, float]:
    """Full-dataset top-1/top-5 (%), batched to bound memory.
    ``apply_fn(params, images) -> logits``; pass an already-jitted function
    (as :func:`run` does) — wrapping in a fresh ``jax.jit`` here would start
    every call with an empty compilation cache."""
    import jax.numpy as jnp

    hits1, hits5, seen = 0.0, 0.0, 0
    for i in range(0, len(images), batch):
        x = jnp.asarray(images[i : i + batch])
        y = jnp.asarray(labels[i : i + batch])
        a1, a5 = topk_accuracy(apply_fn(params, x), y)
        hits1 += float(a1) * len(x)
        hits5 += float(a5) * len(x)
        seen += len(x)
    return hits1 / max(seen, 1), hits5 / max(seen, 1)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--num-classes", type=int, default=10)
    p.add_argument("--train-size", type=int, default=512)
    p.add_argument("--val-size", type=int, default=128)
    # VGG11's five 2x pooling stages need ≥32px inputs
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--noise", type=float, default=1.0,
                   help="blob corruption; lower = easier problem")
    p.add_argument("--model", choices=["vgg", "resnet18", "mlp"], default="vgg",
                   help="resnet18 = the reference study's default --arch "
                        "(stateless GroupNorm variant here; the SyncBN path "
                        "runs in main_elastic); vgg = fast conv benchmark; "
                        "mlp = fast smoke")
    p.add_argument("--world", type=int, default=None)
    p.add_argument("--measure-gns", action="store_true")
    p.add_argument("--accuracy-trace", type=str, default=None,
                   help="append 'epoch top1 top5' lines (reference .txt traces)")
    p.add_argument("--print-freq", type=int, default=5)
    return p


def run(args) -> Tuple[float, float]:
    """Train + validate; returns the final (top1, top5)."""
    from adapcc_tpu.launch import maybe_initialize_distributed

    # re-pins jax_platforms from the env (site customizations override the
    # env var at startup) and joins a multi-host world when launched as one
    maybe_initialize_distributed()

    import jax
    import jax.numpy as jnp
    import optax

    from adapcc_tpu.comm.mesh import build_world_mesh
    from adapcc_tpu.ddp import DDPTrainer, TrainState
    from adapcc_tpu.models.vgg import VGG11
    from adapcc_tpu.strategy.ir import Strategy

    mesh = build_world_mesh(args.world)
    world = int(mesh.devices.size)

    train_x, train_y = make_blob_dataset(
        args.train_size, args.num_classes, args.image_size, noise=args.noise, seed=0
    )
    val_x, val_y = make_blob_dataset(
        args.val_size, args.num_classes, args.image_size, noise=args.noise, seed=1
    )

    if args.model == "vgg":
        net = VGG11(num_classes=args.num_classes, classifier_width=64, dtype=jnp.float32)
        apply_fn = net.apply
        params = net.init(jax.random.PRNGKey(0), jnp.asarray(train_x[:1]))
    elif args.model == "resnet18":
        from adapcc_tpu.models.resnet import ResNet18

        net = ResNet18(
            num_classes=args.num_classes, small_inputs=True, dtype=jnp.float32
        )
        apply_fn = net.apply
        params = net.init(jax.random.PRNGKey(0), jnp.asarray(train_x[:1]))
    else:
        from adapcc_tpu.models.mlp import MLP

        net = MLP(features=(128, 64, args.num_classes))

        def apply_fn(p, x):
            return net.apply(p, x.reshape(x.shape[0], -1))

        params = net.init(
            jax.random.PRNGKey(0), jnp.asarray(train_x[:1]).reshape(1, -1)
        )

    def loss_fn(p, batch):
        x, y = batch
        logits = apply_fn(p, x)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    tx = optax.adam(args.lr)
    trainer = DDPTrainer(
        loss_fn, tx, mesh, Strategy.ring(world),
        measure_gns=args.measure_gns and world > 1,
        # loop-owned state: see train_gpt2 donation note
        donate_state=True,
    )
    state = TrainState.create(params, tx)
    eval_forward = jax.jit(apply_fn)  # one cache for all validation epochs

    top1 = top5 = 0.0
    for epoch in range(args.epochs):
        losses = AverageMeter("loss", ":.4f")
        steps = max(1, args.train_size // args.batch)
        progress = ProgressMeter(steps, [losses], prefix=f"epoch {epoch} ")
        for i, (x, y) in enumerate(batches(train_x, train_y, args.batch, seed=epoch)):
            state, loss = trainer.step(state, (jnp.asarray(x), jnp.asarray(y)))
            losses.update(float(jnp.mean(loss)), len(x))
            if i % args.print_freq == 0:
                progress.display(i)
        top1, top5 = validate(eval_forward, state.params, val_x, val_y)
        gns = trainer.gns.gns if trainer.gns is not None else None
        gns_txt = f"  gns {gns:.1f}" if gns is not None else ""
        print(f"epoch {epoch:3d}  val top1 {top1:.2f}%  top5 {top5:.2f}%{gns_txt}")
        if args.accuracy_trace:
            with open(args.accuracy_trace, "a") as f:
                f.write(f"{epoch} {top1:.4f} {top5:.4f}\n")
    return top1, top5


def main(argv: Optional[Sequence[str]] = None) -> int:
    run(build_parser().parse_args(argv))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
