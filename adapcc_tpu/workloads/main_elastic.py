"""Elastic image-classification training — the reference main_elastic.py flow.

Reference behavior (models/image-classification/main_elastic.py): torchrun
elastic workers restore the newest checkpoint at rendezvous, train epochs
with DDP, atomically checkpoint each epoch, and survive ``--max_restarts``
crashes.  Here the worker trains a VGG classifier under the AdapCC DDP
trainer, checkpoints through :mod:`adapcc_tpu.checkpoint`, and the
``--supervise`` mode wraps the worker in the elastic restart loop.

Run (virtual pod):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python -m adapcc_tpu.workloads.main_elastic --epochs 3 --steps-per-epoch 5
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np
import optax

from adapcc_tpu.checkpoint import (
    AsyncCheckpointManager,
    TrainCheckpointState,
    async_checkpointing_enabled,
    load_checkpoint,
    restore_newest_across_processes,
    run_elastic,
    save_checkpoint,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--steps-per-epoch", type=int, default=5)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--checkpoint-file", type=str, default="/tmp/adapcc_elastic/checkpoint.ckpt")
    p.add_argument("--world", type=int, default=None)
    p.add_argument("--model", choices=("resnet18", "resnet50", "vgg11", "mlp"),
                   default="vgg11",
                   help="resnet18 is the reference's default --arch "
                        "(main_elastic.py:75); vgg11 compiles much faster on "
                        "the virtual pod; mlp compiles in seconds for "
                        "restart-path tests")
    p.add_argument("--norm", choices=("group", "batch"), default="batch",
                   help="resnet norm layer: batch = SyncBN running stats "
                        "carried in the checkpoint (reference torchvision "
                        "behavior, cross-replica synced); group = stateless")
    p.add_argument("--crash-at-epoch", type=int, default=None,
                   help="fault injection: die after checkpointing this epoch")
    p.add_argument("--supervise", action="store_true",
                   help="run as the elastic supervisor wrapping a worker")
    p.add_argument("--max-restarts", type=int, default=3)
    return p


def worker(args) -> int:
    from adapcc_tpu.launch import maybe_initialize_distributed

    maybe_initialize_distributed()

    import jax
    import jax.numpy as jnp

    from adapcc_tpu.comm.mesh import build_world_mesh
    from adapcc_tpu.ddp import DDPTrainer, TrainState
    from adapcc_tpu.strategy.ir import Strategy

    mesh = build_world_mesh(args.world)
    world = int(mesh.devices.size)

    stateful = False
    if args.model in ("resnet18", "resnet50"):
        from adapcc_tpu.models.resnet import ResNet18, ResNet50

        from adapcc_tpu.comm.mesh import RANKS_AXIS

        ctor = ResNet18 if args.model == "resnet18" else ResNet50
        # small_inputs: the 32x32 synthetic data below is CIFAR-shaped
        model = ctor(
            num_classes=10, small_inputs=True, dtype=jnp.float32,
            norm=args.norm,
            axis_name=RANKS_AXIS if args.norm == "batch" else None,
        )
        stateful = args.norm == "batch"
    elif args.model == "vgg11":
        from adapcc_tpu.models.vgg import VGG11

        model = VGG11(num_classes=10, classifier_width=128, dtype=jnp.float32)
    else:
        from adapcc_tpu.models.mlp import MLP

        class _Flat(MLP):
            def __call__(self, x):
                return super().__call__(x.reshape(x.shape[0], -1))

        model = _Flat(features=(16, 10))
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.normal(size=(args.batch, 32, 32, 3)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 10, size=(args.batch,)))

    if stateful:
        # SyncBN: running statistics ride in TrainState.model_state and the
        # checkpoint's extra dict (the reference's State carries the whole
        # torchvision module incl. BN buffers)
        variables = model.init(jax.random.PRNGKey(0), images[:1], train=True)
        params, model_state = variables["params"], variables["batch_stats"]

        def loss_fn(p, ms, batch):
            x, y = batch
            logits, upd = model.apply(
                {"params": p, "batch_stats": ms}, x, train=True,
                mutable=["batch_stats"],
            )
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
            return ce.mean(), upd["batch_stats"]
    else:
        params = model.init(jax.random.PRNGKey(0), images[:1])
        model_state = ()

        def loss_fn(p, batch):
            x, y = batch
            logits = model.apply(p, x)
            return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    tx = optax.sgd(args.lr, momentum=0.9)
    trainer = DDPTrainer(
        loss_fn, tx, mesh, Strategy.ring(world), stateful_loss=stateful,
        # loop-owned state: see train_gpt2 donation note
        donate_state=True,
    )
    train_state = trainer.init_state(params, model_state=model_state)

    # rendezvous restore: newest checkpoint wins across the (new) world
    ckpt = TrainCheckpointState(
        params=train_state.params,
        opt_state=train_state.opt_state,
        extra={"model_state": model_state} if stateful else {},
    )
    # async crash-consistent checkpointing (ADAPCC_ASYNC_CKPT,
    # docs/RECOVERY.md §2): epoch saves run on the manager's background
    # pipeline (snapshot → serialize → checksum → atomic publish) and the
    # local restore reads the newest VERIFIED step — a mid-save crash
    # leaves only ignorable .tmp debris, never a torn live checkpoint
    amgr = None
    if async_checkpointing_enabled():
        steps_dir = args.checkpoint_file + ".steps"
        if jax.process_count() > 1:
            # every process owns its own step directory: two publishers
            # racing one shared step-<n>/ rename is exactly the
            # cross-process collision the manager's loud
            # already-published guard rejects (the legacy single-file
            # path tolerates the race only because last-rename-wins)
            steps_dir += f".p{jax.process_index()}"
        amgr = AsyncCheckpointManager(steps_dir)
    try:
        restored_step = None
        if amgr is not None:
            restored_step = amgr.latest_good_step()
            if restored_step is not None:
                amgr.restore(ckpt, restored_step)
        if restored_step is not None:
            # the legacy single-file checkpoint may still be FRESHER
            # (async was off in an earlier run); adopt it only then —
            # loading it unconditionally would rewind the verified step
            # restore under a stale leftover file
            legacy = TrainCheckpointState(
                params=ckpt.params,
                opt_state=ckpt.opt_state,
                extra=dict(ckpt.extra),
            )
            try:
                fresher = (
                    load_checkpoint(legacy, args.checkpoint_file)
                    and legacy.epoch > ckpt.epoch
                )
            except (KeyError, ValueError, TypeError):
                # an unreadable/incompatible stale file simply LOSES the
                # freshness comparison — it must not abort a worker that
                # already holds a good verified restore
                fresher = False
            if fresher:
                ckpt = legacy
            ckpt = restore_newest_across_processes(
                ckpt, args.checkpoint_file, load_local=False
            )
        else:
            ckpt = restore_newest_across_processes(ckpt, args.checkpoint_file)
    except (KeyError, ValueError, TypeError) as e:
        # flax from_bytes raises a raw dict-key/shape mismatch when the file
        # was written under a different --norm mode (e.g. a pre-SyncBN ckpt
        # without extra["model_state"]); surface the actual cause instead
        print(
            f"=> checkpoint {args.checkpoint_file!r} is incompatible with "
            f"--norm {args.norm!r} (was it written under a different norm "
            f"mode?): {e}",
            file=sys.stderr,
        )
        return 2
    start_epoch = ckpt.epoch + 1
    if start_epoch > 0:
        print(f"=> resuming from epoch {start_epoch}")
        train_state = TrainState(
            params=ckpt.params, opt_state=ckpt.opt_state, step=ckpt.step,
            model_state=ckpt.extra.get("model_state", ()) if stateful else (),
        )

    for epoch in range(start_epoch, args.epochs):
        for _ in range(args.steps_per_epoch):
            train_state, loss = trainer.step(train_state, (images, labels))
        print(f"epoch {epoch:3d}  loss {float(jnp.mean(loss)):.4f}  world={world}")

        ckpt.params = train_state.params
        ckpt.opt_state = train_state.opt_state
        ckpt.epoch = epoch
        ckpt.step = int(train_state.step)
        if stateful:
            ckpt.extra["model_state"] = train_state.model_state
        if amgr is not None:
            amgr.save_async(epoch, ckpt)
        else:
            save_checkpoint(ckpt, args.checkpoint_file)

        # fault injection fires only in the first generation, so the
        # supervisor's restart actually makes progress past the crash point
        gen = int(os.environ.get("ADAPCC_RESTART_GEN", "0"))
        if args.crash_at_epoch is not None and epoch == args.crash_at_epoch and gen == 0:
            if amgr is not None:
                # the INJECTED crash is deterministic by contract — the
                # genuinely-mid-save kill is the chaos drill's job
                amgr.wait()
            print(f"=> injected fault at epoch {epoch}", flush=True)
            return 17  # nonzero: the supervisor restarts us
    if amgr is not None:
        amgr.wait()
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.supervise:
        worker_argv = [
            sys.executable, "-m", "adapcc_tpu.workloads.main_elastic",
            "--epochs", str(args.epochs),
            "--steps-per-epoch", str(args.steps_per_epoch),
            "--batch", str(args.batch),
            "--lr", str(args.lr),
            "--checkpoint-file", args.checkpoint_file,
            "--model", args.model,
            "--norm", args.norm,
        ]
        if args.world:
            worker_argv += ["--world", str(args.world)]
        if args.crash_at_epoch is not None:
            worker_argv += ["--crash-at-epoch", str(args.crash_at_epoch)]
        return run_elastic(worker_argv, max_restarts=args.max_restarts)
    return worker(args)


if __name__ == "__main__":
    raise SystemExit(main())
