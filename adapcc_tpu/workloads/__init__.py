"""Training workloads mirroring the reference's model benchmarks
(train_ddp.py, models/{vit,gpt2,moe,image-classification})."""
