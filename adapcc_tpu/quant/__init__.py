"""Wire-codec subsystem: block-wise quantized collectives + error feedback.

See docs/QUANT.md for the codec math, the wire format, the error-feedback
loop, and when the sim-rank policy picks int8.
"""

from adapcc_tpu.quant.codec import (
    DEFAULT_BLOCK_SIZE,
    WIRE_DTYPE_ENV,
    WireCodec,
    codec_names,
    dequantize_int8,
    error_feedback_step,
    get_codec,
    int8_error_bound,
    int8_roundtrip,
    quantize_int8,
    register_codec,
    resolve_wire_dtype,
    timed_roundtrip,
)
from adapcc_tpu.quant.ring import ring_error_bound, wire_ring_allreduce_shard

__all__ = [
    "DEFAULT_BLOCK_SIZE",
    "WIRE_DTYPE_ENV",
    "WireCodec",
    "codec_names",
    "dequantize_int8",
    "error_feedback_step",
    "get_codec",
    "int8_error_bound",
    "int8_roundtrip",
    "quantize_int8",
    "register_codec",
    "resolve_wire_dtype",
    "ring_error_bound",
    "timed_roundtrip",
    "wire_ring_allreduce_shard",
]
