"""Wire-codec ring allreduce: quantized chunks over a ppermute ring.

The EQuARX dual-quantization shape (PAPERS.md) mapped onto the engine's
collective contract:

- **reduce-scatter phase** (``world - 1`` hops): each rank keeps its payload
  as fp32 chunks and, per hop, *encodes* the chunk in flight, ships the wire
  arrays (int8 codes + fp32 block scales, or a bf16 cast) one ring step, and
  the receiver *decodes and accumulates* into its fp32 partial — which is
  re-encoded when it moves on the next hop.  Accumulation error therefore
  grows with ring depth, never compounds inside a chunk (fp32 carries the
  running sum; only the wire is narrow).
- **all-gather phase** (``world - 1`` hops): the fully reduced chunk is
  encoded ONCE by its owner and the encoded blocks are forwarded verbatim;
  every rank — owner included — decodes the same bits, so the result is
  bit-identical across ranks.

This is the ppermute realization (any mesh, any backend, subset of no one's
VMEM) — the strategy plane selects it via ``Strategy.wire_dtype`` and the
engine records the executed codec in the dispatch trace.  The uncompressed
(``"off"``) ring stays on the hand-tuned Pallas kernels
(:mod:`adapcc_tpu.comm.pallas_ring`); this module exists for the wire
dtypes those kernels do not speak.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from adapcc_tpu.comm.mesh import RANKS_AXIS
from adapcc_tpu.quant.codec import DEFAULT_BLOCK_SIZE, get_codec


def wire_ring_allreduce_shard(
    x: jnp.ndarray,
    world: int,
    axis_name: str = RANKS_AXIS,
    wire_dtype: str = "int8",
    block_size: int = DEFAULT_BLOCK_SIZE,
) -> jnp.ndarray:
    """SUM-allreduce ``x`` over ``axis_name`` with the wire codec applied
    per hop; call inside shard_map.  Any input shape; result in the input's
    shape and dtype on every rank.

    ``world == 1`` degenerates to the identity (no wire, no codec error).
    """
    codec = get_codec(wire_dtype)
    if world == 1:
        return x
    orig_dtype = x.dtype
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    # chunk layout: world chunks, each padded to whole codec blocks so one
    # chunk's scales never straddle another's elements
    chunk = -(-n // world)
    chunk = -(-chunk // block_size) * block_size
    acc = jnp.pad(flat, (0, world * chunk - n)).reshape(world, chunk)
    me = lax.axis_index(axis_name)
    ring = [(i, (i + 1) % world) for i in range(world)]

    def ship(chunk_val):
        """Encode -> one ring hop -> the received wire arrays."""
        wire = codec.encode(chunk_val, block_size)
        return tuple(lax.ppermute(w, axis_name, ring) for w in wire)

    # -- reduce-scatter: dequant-accumulate-requant at every hop ------------
    for s in range(world - 1):
        send_idx = (me - s) % world
        recvd = ship(lax.dynamic_index_in_dim(acc, send_idx, keepdims=False))
        recv_idx = (me - s - 1) % world
        cur = lax.dynamic_index_in_dim(acc, recv_idx, keepdims=False)
        acc = lax.dynamic_update_index_in_dim(
            acc, cur + codec.decode(recvd, chunk, block_size), recv_idx, 0
        )

    # -- all-gather: encode once, forward the encoded blocks verbatim ------
    own_idx = (me + 1) % world  # the chunk this rank finished reducing
    own_wire = codec.encode(
        lax.dynamic_index_in_dim(acc, own_idx, keepdims=False), block_size
    )
    # the owner adopts its own DECODED chunk: every rank must see the same
    # post-codec value, owner included
    out = lax.dynamic_update_index_in_dim(
        jnp.zeros_like(acc), codec.decode(own_wire, chunk, block_size),
        own_idx, 0,
    )
    wire = own_wire
    for s in range(world - 1):
        wire = tuple(lax.ppermute(w, axis_name, ring) for w in wire)
        # the block arriving at hop s originated at rank (me - 1 - s) and
        # carries that rank's owned chunk, index (me - s) % world
        idx = (me - s) % world
        out = lax.dynamic_update_index_in_dim(
            out, codec.decode(wire, chunk, block_size), idx, 0
        )
    return out.reshape(-1)[:n].reshape(x.shape).astype(orig_dtype)


def ring_error_bound(
    xs, world: Optional[int] = None, block_size: int = DEFAULT_BLOCK_SIZE
):
    """Elementwise |quantized ring - fp32 sum| bound for the int8 ring.

    Each element's running partial is re-quantized at most ``world`` times
    (``world - 1`` reduce-scatter hops + the single all-gather encode), each
    costing at most half a step of the *largest* partial sum its block ever
    holds, which is bounded by the block max of ``sum_r |x_r|``.  Loose but
    shape-correct: tight enough to catch a broken codec, robust to the
    ring's hop order.
    """
    import numpy as np

    xs = np.asarray(xs, dtype=np.float32)  # [world, n]
    if world is None:
        world = xs.shape[0]
    n = xs[0].reshape(-1).shape[0]
    mass = np.abs(xs).reshape(world, -1).sum(axis=0)
    chunk = -(-n // world)
    chunk = -(-chunk // block_size) * block_size
    padded = np.pad(mass, (0, world * chunk - n)).reshape(-1, block_size)
    per_block = np.max(padded, axis=1) / 127.0
    bound = 0.5 * world * np.repeat(per_block, block_size)[:n]
    return bound + 1e-6  # absolute slack for fp32 accumulation noise
